"""OSDMapMapping delta remap: the table after ``update`` must equal a
from-scratch sweep of the same map — for every kind of incremental in
a randomized stream — and the cheap delta paths must actually be the
ones taken (a delta that silently full-sweeps would pass equality and
defeat the point)."""

import numpy as np
import pytest

from ceph_tpu.bench import osdmaptool
from ceph_tpu.crush import builder
from ceph_tpu.crush.types import WEIGHT_ONE
from ceph_tpu.osd.osdmap import Incremental, OSDMap
from ceph_tpu.osd.osdmap_mapping import OSDMapMapping
from ceph_tpu.osd.types import PGPool, pg_t


def _assert_matches_scratch(mm: OSDMapMapping, m: OSDMap):
    assert mm.epoch == m.epoch
    for pid, pool in m.pools.items():
        seeds = np.arange(pool.pg_num, dtype=np.uint32)
        craw, pps = m.pg_to_crush_osds(pid, seeds)
        up, upp, acting, actp = m._pipeline_from_crush(
            pool, seeds, craw, pps)
        t = mm._pools[pid]
        assert np.array_equal(t.craw, craw), f"pool {pid} raw"
        assert np.array_equal(t.up, up), f"pool {pid} up"
        assert np.array_equal(t.up_primary, upp)
        assert np.array_equal(t.acting, acting)
        assert np.array_equal(t.acting_primary, actp)


def _mk(n_osds=8, pg_num=16, size=3):
    # small on purpose: every distinct map shape pays an XLA rule
    # compile on the tier-1 CPU run, and reweights/crush edits in
    # these tests force recompiles — size only inflates that cost
    m = osdmaptool.create_simple(n_osds, pg_num, size, erasure=False)
    return m, OSDMapMapping(m)


class TestDeltaRemap:
    def test_state_flip_is_delta(self):
        """up/down flips keep the raw table and sweep nothing."""
        m, mm = _mk()
        inc = Incremental(epoch=m.epoch + 1, new_down=[3])
        m.apply_incremental(inc)
        mm.update(m)
        assert mm.last_full_sweep_pools == 0
        assert mm.last_remap_pgs > 0       # osd.3 held some PGs
        _assert_matches_scratch(mm, m)
        inc = Incremental(epoch=m.epoch + 1, new_up=[3])
        m.apply_incremental(inc)
        mm.update(m)
        assert mm.last_full_sweep_pools == 0
        _assert_matches_scratch(mm, m)

    def test_weight_decrease_is_delta(self):
        """mark_out / reweight-down: affected set = PGs holding the
        OSD in the old raw table; no full sweep. One incremental
        carries both shapes (partial decrease + full out) — each
        distinct weight vector pays an XLA recompile in tier-1."""
        m, mm = _mk()
        inc = Incremental(epoch=m.epoch + 1,
                          new_weight={5: WEIGHT_ONE // 2, 3: 0})
        m.apply_incremental(inc)
        mm.update(m)
        assert mm.last_full_sweep_pools == 0
        _assert_matches_scratch(mm, m)

    def test_weight_increase_full_sweeps_reachable_pools(self):
        """mark_in: newly-accepting PGs are invisible to the old
        table, so the pool full-sweeps (dirty-bucket gated)."""
        m, mm = _mk()
        m.apply_incremental(Incremental(epoch=m.epoch + 1,
                                        new_weight={5: 0}))
        mm.update(m)
        m.apply_incremental(Incremental(epoch=m.epoch + 1,
                                        new_weight={5: WEIGHT_ONE}))
        mm.update(m)
        assert mm.last_full_sweep_pools == 1
        _assert_matches_scratch(mm, m)

    def test_overrides_are_delta(self):
        m, mm = _mk()
        pg = pg_t(1, 4)
        inc = Incremental(epoch=m.epoch + 1)
        inc.new_pg_temp[pg] = [1, 2, 3]
        inc.new_primary_temp[pg_t(1, 7)] = 2
        inc.new_pg_upmap_items[pg_t(1, 9)] = [(0, 8)]
        m.apply_incremental(inc)
        mm.update(m)
        assert mm.last_full_sweep_pools == 0
        assert mm.last_remap_pgs == 3      # exactly the named PGs
        _assert_matches_scratch(mm, m)
        # removal is a delta too
        inc = Incremental(epoch=m.epoch + 1)
        inc.new_pg_temp[pg] = []
        inc.old_pg_upmap_items.append(pg_t(1, 9))
        m.apply_incremental(inc)
        mm.update(m)
        assert mm.last_full_sweep_pools == 0
        _assert_matches_scratch(mm, m)

    def test_primary_affinity_is_delta(self):
        m, mm = _mk()
        m.set_primary_affinity(2, 0)
        mm.update(m)
        assert mm.last_full_sweep_pools == 0
        _assert_matches_scratch(mm, m)

    @pytest.mark.slow
    def test_crush_topology_change_full_sweeps(self):
        # tier-1 coverage of the fallback lives in the randomized
        # stream (its crush-edit steps assert the full-sweep counter)
        m, mm = _mk()
        host0 = [b.id for b in m.crush.buckets.values()
                 if b.type == builder.TYPE_HOST][0]
        new_osd = m.max_osd           # first id past the existing ones
        m.insert_crush_item(new_osd, WEIGHT_ONE, host0)
        mm.update(m)
        assert mm.last_full_sweep_pools >= 1
        _assert_matches_scratch(mm, m)
        m.remove_crush_item(new_osd)
        mm.update(m)
        assert mm.last_full_sweep_pools >= 1
        _assert_matches_scratch(mm, m)

    def test_pool_lifecycle(self):
        m, mm = _mk()
        m.add_pool(PGPool(id=2, pg_num=16, size=2, crush_rule=0,
                          name="two"))
        mm.update(m)
        assert 2 in mm._pools
        _assert_matches_scratch(mm, m)
        m.apply_incremental(Incremental(epoch=m.epoch + 1,
                                        old_pools=[2]))
        mm.update(m)
        assert 2 not in mm._pools
        _assert_matches_scratch(mm, m)

    def test_fresh_decode_delta_via_digest(self):
        """The mon decodes a NEW OSDMap object per epoch: object
        identity breaks but the crush digest proves the tree unchanged
        — state flips must still take the delta path."""
        from ceph_tpu.encoding import decode_osdmap, encode_osdmap
        m, mm = _mk()
        m2 = decode_osdmap(encode_osdmap(m))
        m2.apply_incremental(Incremental(epoch=m2.epoch + 1,
                                         new_down=[1]))
        mm.update(m2)
        assert mm.last_full_sweep_pools == 0
        _assert_matches_scratch(mm, m2)

    def test_randomized_incremental_stream(self):
        """The satellite ask: a random stream of weights / up-down /
        upmap / pg_temp / affinity / crush edits — delta-remapped
        table == from-scratch remap at EVERY epoch. 10 steps in
        tier-1 (crush-edit steps force mapper recompiles, ~4 s each
        on CPU); the 24-step deep stream runs under slow."""
        self._run_stream(10)

    @pytest.mark.slow
    def test_randomized_incremental_stream_deep(self):
        self._run_stream(24)

    def _run_stream(self, n_steps: int):
        rng = np.random.default_rng(321)
        m, mm = _mk(n_osds=12, pg_num=32, size=3)
        m.add_pool(PGPool(id=2, pg_num=16, size=2, crush_rule=0,
                          name="two"))
        mm.update(m)
        for step in range(n_steps):
            kind = rng.integers(0, 8)
            inc = Incremental(epoch=m.epoch + 1)
            o = int(rng.integers(0, 12))
            if kind == 0:
                inc.new_down.append(o)
            elif kind == 1:
                inc.new_up.append(o)
            elif kind == 2:
                inc.new_weight[o] = int(rng.choice(
                    [0, WEIGHT_ONE // 3, WEIGHT_ONE // 2,
                     WEIGHT_ONE]))
            elif kind == 3:
                pid = int(rng.choice([1, 2]))
                npg = m.pools[pid].pg_num
                pg = pg_t(pid, int(rng.integers(0, npg)))
                if rng.integers(0, 2):
                    inc.new_pg_temp[pg] = [int(x) for x in
                                           rng.choice(12, size=3,
                                                      replace=False)]
                else:
                    inc.new_pg_temp[pg] = []
            elif kind == 4:
                pid = int(rng.choice([1, 2]))
                npg = m.pools[pid].pg_num
                pg = pg_t(pid, int(rng.integers(0, npg)))
                if rng.integers(0, 2):
                    inc.new_pg_upmap_items[pg] = [(o, (o + 1) % 12)]
                else:
                    inc.old_pg_upmap_items.append(pg)
            elif kind == 5:
                inc.new_primary_affinity[o] = int(rng.choice(
                    [0, 0x8000, 0x10000]))
            elif kind == 6:
                pg = pg_t(1, int(rng.integers(0, 32)))
                inc.new_primary_temp[pg] = int(rng.integers(-1, 12))
            else:
                # crush edit: reweight an item inside its bucket
                # (topology-level change -> full-sweep fallback)
                from ceph_tpu.crush import builder as cb
                host = [b for b in m.crush.buckets.values()
                        if b.type == cb.TYPE_HOST][
                    int(rng.integers(0, 3))]
                slot = int(rng.integers(0, host.size))
                w = int(rng.choice([WEIGHT_ONE, 2 * WEIGHT_ONE]))
                if host.weights[slot] == w:
                    # the edit must really change the tree (the
                    # stream's full-sweep-counter assert relies on it)
                    w = (2 * WEIGHT_ONE if w == WEIGHT_ONE
                         else WEIGHT_ONE)
                host.weights[slot] = w
                m._dirty(crush_changed=True)
                m.epoch -= 1               # inc below counts it
            m.apply_incremental(inc)
            mm.update(m)
            if kind == 7:
                assert mm.last_full_sweep_pools >= 1, \
                    "crush edit must take the full-sweep fallback"
            _assert_matches_scratch(mm, m)


class TestEpochCache:
    def test_scalar_memo_hits_and_epoch_invalidation(self):
        m, _ = _mk()
        m.mapping_cache_hits = m.mapping_cache_misses = 0
        a1 = m.pg_to_up_acting_osds(1, [5])
        assert m.mapping_cache_misses == 1
        a2 = m.pg_to_up_acting_osds(1, [5])
        assert m.mapping_cache_hits == 1
        for x, y in zip(a1, a2):
            assert np.array_equal(x, y)
        # any epoch bump drops the memo
        m.mark_down(3)
        m.pg_to_up_acting_osds(1, [5])
        assert m.mapping_cache_misses == 2

    def test_memo_never_serves_across_incremental(self):
        m, _ = _mk()
        up_a, _, _, _ = m.pg_to_up_acting_osds(1, [5])
        osd = int(up_a[0][0])
        m.apply_incremental(Incremental(epoch=m.epoch + 1,
                                        new_down=[osd]))
        up_b, _, _, _ = m.pg_to_up_acting_osds(1, [5])
        assert osd not in list(up_b[0])

    def test_attached_mapping_serves_bulk(self):
        m, mm = _mk()
        m.attach_mapping(mm)
        m.mapping_cache_hits = 0
        npg = m.pools[1].pg_num
        up, upp, acting, actp = m.map_pool(1)
        assert m.mapping_cache_hits == npg      # every seed from table
        seeds = np.arange(npg, dtype=np.uint32)
        craw, pps = m.pg_to_crush_osds(1, seeds)
        u2, up2, a2, ap2 = m._pipeline_from_crush(
            m.pools[1], seeds, craw, pps)
        assert np.array_equal(up, u2)
        assert np.array_equal(actp, ap2)
        # stale table (epoch moved, no update yet) must NOT serve
        m.mark_down(1)
        m.mapping_cache_hits = 0
        m.map_pool(1)
        assert m.mapping_cache_hits == 0
        # after update it serves again
        mm.update(m)
        m.mapping_cache_hits = 0
        m.map_pool(1)
        assert m.mapping_cache_hits == npg

    def test_lookup_returns_copies(self):
        m, mm = _mk()
        m.attach_mapping(mm)
        up, _, _, _ = m.map_pool(1)
        up[:] = -7
        up2, _, _, _ = m.map_pool(1)
        assert not np.array_equal(up, up2)


class TestSteadyStateServing:
    def test_objecter_ops_hit_epoch_cache(self):
        """The acceptance bar: steady-state client op targeting is
        served from the epoch-keyed cache — repeated ops against a
        stable map must register cache HITS (no mapper re-entry per
        op) — each OSD's tracked mapping table follows the map epoch
        (advance-map reads come from the table), and the mgr's
        prometheus render carries the mapping counters. One cluster
        boot for all three asserts (tier-1 budget)."""
        import asyncio

        from ceph_tpu.cluster.vstart import Cluster
        from ceph_tpu.mgr.modules import PrometheusModule

        async def go():
            c = await Cluster(n_mons=1, n_osds=3,
                              mgr_modules=[PrometheusModule]).start()
            try:
                await c.client.pool_create("m", pg_num=8, size=3)
                await c.wait_for_clean(timeout=90)
                io = await c.client.open_ioctx("m")
                await io.write_full("warm", b"x")   # misses fill memo
                om = c.client.objecter.monc.osdmap
                om.mapping_cache_hits = 0
                for i in range(8):
                    await io.write_full("warm", bytes([i]))
                    assert await io.read("warm") == bytes([i])
                assert om.mapping_cache_hits > 0
                assert om is c.client.objecter.monc.osdmap, \
                    "map changed mid-test; steady-state assert is void"
                # every OSD's delta-maintained table is at map epoch
                for o in c.osds:
                    mt = o.monc.mapping_table
                    assert mt is not None
                    assert mt.epoch == o.osdmap.epoch
                    # the asok "status" verb's mapping block
                    ms = o._mapping_status()
                    assert ms.get("table_epoch") == mt.epoch
                    assert "osdmap" in ms      # perf counter family
                # prometheus: dedicated mapping-engine metric rows
                prom = next(m for m in c.mgr.modules
                            if isinstance(m, PrometheusModule))
                text = await prom.render()
                assert "ceph_osdmap_mapping_cache_hits" in text
                assert "ceph_osdmap_mapping_cache_misses" in text
                assert "ceph_osdmap_remap_pgs" in text
                assert "ceph_osdmap_remap_full_sweeps" in text
            finally:
                await c.stop()

        asyncio.run(go())


class TestBalancerOnTable:
    def test_calc_pg_upmaps_matches_and_applies(self):
        """The balancer's candidate probes replay the pipeline over
        the cached raw table — results must still pass the full
        validation (no dup osds, no holes) and actually flatten."""
        m = osdmaptool.create_simple(16, 256, 3, erasure=False)
        before = m.pool_utilization(1)
        changes = m.calc_pg_upmaps(max_deviation=1,
                                   max_iterations=50)
        assert changes > 0
        after = m.pool_utilization(1)
        live = np.asarray(m.osd_weight)[:16] > 0
        assert after[live].max() - after[live].min() <= \
            before[live].max() - before[live].min()
        # and the recorded upmaps survive a from-scratch remap
        up, _, _, _ = m._pg_to_up_acting_uncached(
            m.pools[1], np.arange(256, dtype=np.uint32))
        for pg, pairs in m.pg_upmap_items.items():
            row = up[pg.seed]
            for frm, to in pairs:
                assert frm not in row


class TestMeshProvenance:
    """Round 15 (ROADMAP #1d first slice): the registered
    ``osd_crush_mesh`` knob decides where an OSD's device mesh comes
    from — ``auto`` attaches the local default mesh at boot when more
    than one device is visible, so sharded full-pool sweeps stop
    requiring hand-wiring."""

    def test_boot_crush_mesh_knob(self):
        from ceph_tpu.osd.daemon import _boot_crush_mesh
        assert _boot_crush_mesh({}) is None                  # default
        assert _boot_crush_mesh({"osd_crush_mesh": "off"}) is None
        # auto on a single-device host: the sharded sweep needs >1
        # device, so no mesh attaches (the plain path stands)
        import jax
        if len(jax.devices()) == 1:
            assert _boot_crush_mesh(
                {"osd_crush_mesh": "auto"}) is None
        else:                                # pragma: no cover (TPU)
            mesh = _boot_crush_mesh({"osd_crush_mesh": "auto"})
            assert mesh is not None and mesh.devices.size > 1

    def test_auto_builds_mesh_over_visible_devices(self, monkeypatch):
        """>1 visible device: auto returns make_mesh(devices) — the
        device probe is faked (CPU CI has one device), the mesh
        constructor is observed."""
        from ceph_tpu.osd import daemon as osd_daemon
        fake_devices = [object(), object()]
        built = {}
        monkeypatch.setattr(
            "jax.devices", lambda *a, **k: fake_devices)

        def fake_make_mesh(devices):
            built["devices"] = devices
            return "mesh-sentinel"

        import ceph_tpu.parallel
        monkeypatch.setattr(ceph_tpu.parallel, "make_mesh",
                            fake_make_mesh)
        got = osd_daemon._boot_crush_mesh({"osd_crush_mesh": "auto"})
        assert got == "mesh-sentinel"
        assert built["devices"] is fake_devices

    def test_osd_boot_wires_mesh_into_tracked_table(self, monkeypatch):
        """OSD.__init__ hands the knob's mesh to the MonClient, which
        constructs the tracked OSDMapMapping with it — the table then
        re-attaches the mesh to every map it updates against."""
        from ceph_tpu.mon import MonMap
        from ceph_tpu.osd import daemon as osd_daemon
        sentinel = object()
        monkeypatch.setattr(osd_daemon, "_boot_crush_mesh",
                            lambda cfg: sentinel
                            if cfg.get("osd_crush_mesh") == "auto"
                            else None)
        monmap = MonMap()
        monmap.add("a", 0, "127.0.0.1", 6789)
        osd = osd_daemon.OSD(0, monmap,
                             config={"osd_crush_mesh": "auto"})
        assert osd.monc.mapping_mesh is sentinel
        osd2 = osd_daemon.OSD(1, monmap, config={})
        assert osd2.monc.mapping_mesh is None
