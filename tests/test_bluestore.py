"""BlueStoreLite: extent allocation, COW/deferred writes, crash
boundaries, fsck (ref test model: src/test/objectstore/store_test.cc
+ the BlueStore fsck cases)."""

import pytest

from ceph_tpu.os_.allocator import AllocatorError, BitmapAllocator
from ceph_tpu.os_.bluestore import BlueStore
from ceph_tpu.os_.objectstore import ChecksumError, StoreError, Transaction


def mk(tmp_path, size=4 << 20):
    return BlueStore(str(tmp_path / "bs"), size=size)


def T():
    return Transaction()


class TestAllocator:
    def test_alloc_free_cycle(self):
        a = BitmapAllocator(64)
        x = a.allocate(10)
        assert sum(n for _, n in x) == 10
        assert a.free_aus == 54
        a.release(x)
        assert a.free_aus == 64

    def test_fragmented_allocation(self):
        a = BitmapAllocator(8)
        first = a.allocate(8)
        a.release([(1, 1), (3, 1), (5, 1)])     # free holes
        got = a.allocate(3)
        assert sorted(got) == [(1, 1), (3, 1), (5, 1)]
        assert a.free_aus == 0
        a.release(first[0:0])                    # no-op

    def test_enospc(self):
        a = BitmapAllocator(4)
        a.allocate(4)
        with pytest.raises(AllocatorError):
            a.allocate(1)

    def test_double_claim_detected(self):
        a = BitmapAllocator(8)
        a.mark_used([(0, 4)])
        with pytest.raises(AllocatorError):
            a.mark_used([(3, 2)])


class TestBlueStore:
    def test_basic_lifecycle(self, tmp_path):
        s = mk(tmp_path)
        s.queue_transaction(T().create_collection("c"))
        s.queue_transaction(
            T().write("c", "o", 0, b"hello world")
               .setattrs("c", "o", {"k": b"v"})
               .omap_setkeys("c", "o", {"m": b"n"}))
        assert s.read("c", "o") == b"hello world"
        assert s.read("c", "o", 6, 5) == b"world"
        assert s.stat("c", "o") == 11
        assert s.getattrs("c", "o") == {"k": b"v"}
        assert s.omap_get("c", "o") == {"m": b"n"}
        assert s.list_objects("c") == ["o"]
        assert s.fsck() == []
        before = s.statfs()["allocated"]
        assert before >= s.AU
        s.queue_transaction(T().remove("c", "o"))
        assert s.statfs()["allocated"] == 0
        assert not s.exists("c", "o")
        s.umount()

    def test_persistence_across_remount(self, tmp_path):
        s = mk(tmp_path)
        s.queue_transaction(T().create_collection("c"))
        payload = bytes(range(256)) * 64          # 16 KiB
        s.queue_transaction(T().write("c", "o", 0, payload))
        s.queue_transaction(T().write("c", "o", 5000, b"patch"))
        s.umount()
        s2 = mk(tmp_path)
        want = bytearray(payload)
        want[5000:5005] = b"patch"
        assert s2.read("c", "o") == bytes(want)
        assert s2.fsck() == []
        s2.umount()

    def test_sparse_objects_allocate_lazily(self, tmp_path):
        s = mk(tmp_path)
        s.queue_transaction(T().create_collection("c"))
        s.queue_transaction(T().write("c", "o", 1 << 20, b"tail"))
        assert s.stat("c", "o") == (1 << 20) + 4
        # only the tail AU is allocated; the 1 MiB hole reads zeros
        assert s.statfs()["allocated"] == s.AU
        assert s.read("c", "o", 0, 16) == b"\x00" * 16
        assert s.read("c", "o", 1 << 20, 4) == b"tail"
        s.umount()

    def test_deferred_small_overwrite(self, tmp_path):
        """A small overwrite inside an allocated AU takes the deferred
        path: same extents (no COW), correct content, durable across
        remount."""
        s = mk(tmp_path)
        s.queue_transaction(T().create_collection("c"))
        s.queue_transaction(T().write("c", "o", 0, b"A" * 8192))
        ext_before = [tuple(x[:3]) for x in
                      s.onodes[("c", "o")].extents]
        s.queue_transaction(T().write("c", "o", 100, b"B" * 50))
        ext_after = [tuple(x[:3]) for x in
                     s.onodes[("c", "o")].extents]
        assert ext_before == ext_after, "deferred path must not COW"
        want = b"A" * 100 + b"B" * 50 + b"A" * (8192 - 150)
        assert s.read("c", "o") == want
        s.umount()
        s2 = mk(tmp_path)
        assert s2.read("c", "o") == want
        assert s2.fsck() == []
        s2.umount()

    def test_deferred_crash_replays_on_mount(self, tmp_path):
        """Crash after the kv commit but before the in-place block
        write: the deferred record replays on mount and the content is
        the POST-overwrite bytes (the metadata's crc already points at
        them)."""
        s = mk(tmp_path)
        s.queue_transaction(T().create_collection("c"))
        s.queue_transaction(T().write("c", "o", 0, b"A" * 4096))
        s._fail_point = "after_kv_commit"
        with pytest.raises(StoreError):
            s.queue_transaction(T().write("c", "o", 10, b"CRASH"))
        s.db.close()
        s._f.close()
        s2 = mk(tmp_path)
        want = b"A" * 10 + b"CRASH" + b"A" * (4096 - 15)
        assert s2.read("c", "o") == want
        assert s2.fsck() == []
        s2.umount()

    def test_cow_crash_before_commit_keeps_old_data(self, tmp_path):
        """Crash after the COW block write but before the kv commit:
        the metadata still points at the OLD extents, so the old data
        survives and fsck is clean (no leaked allocations persist —
        the allocator rebuilds from the committed extent maps)."""
        s = mk(tmp_path)
        s.queue_transaction(T().create_collection("c"))
        s.queue_transaction(T().write("c", "o", 0, b"OLD!" * 1024))
        s._fail_point = "before_kv_commit"
        with pytest.raises(StoreError):
            s.queue_transaction(
                T().write("c", "o", 0, b"NEW!" * 32768))  # COW path
        s.db.close()
        s._f.close()
        s2 = mk(tmp_path)
        assert s2.read("c", "o") == b"OLD!" * 1024
        assert s2.fsck() == []
        s2.umount()

    def test_truncate_frees_and_zeroes(self, tmp_path):
        s = mk(tmp_path)
        s.queue_transaction(T().create_collection("c"))
        s.queue_transaction(T().write("c", "o", 0, b"X" * 65536))
        alloc_full = s.statfs()["allocated"]
        s.queue_transaction(T().truncate("c", "o", 6000))
        assert s.statfs()["allocated"] < alloc_full
        assert s.stat("c", "o") == 6000
        # re-extend: the dropped tail reads zeros, not stale bytes
        s.queue_transaction(T().truncate("c", "o", 8192))
        assert s.read("c", "o", 6000, 2192) == b"\x00" * 2192
        assert s.read("c", "o", 0, 6000) == b"X" * 6000
        assert s.fsck() == []
        s.umount()

    def test_clone_and_zero(self, tmp_path):
        s = mk(tmp_path)
        s.queue_transaction(T().create_collection("c"))
        s.queue_transaction(
            T().write("c", "o", 0, b"12345678" * 1024)
               .setattrs("c", "o", {"a": b"1"})
               .omap_setkeys("c", "o", {"b": b"2"}))
        s.queue_transaction(T().clone("c", "o", "o2"))
        assert s.read("c", "o2") == b"12345678" * 1024
        assert s.getattrs("c", "o2") == {"a": b"1"}
        # clone is COW through fresh extents: mutating o leaves o2
        s.queue_transaction(T().write("c", "o", 0, b"mutated!"))
        assert s.read("c", "o2", 0, 8) == b"12345678"
        s.queue_transaction(T().zero("c", "o2", 8, 16))
        assert s.read("c", "o2", 8, 16) == b"\x00" * 16
        assert s.fsck() == []
        s.umount()

    def test_fsck_detects_block_corruption(self, tmp_path):
        s = mk(tmp_path)
        s.queue_transaction(T().create_collection("c"))
        s.queue_transaction(T().write("c", "o", 0, b"D" * 4096))
        au = s.onodes[("c", "o")].extents[0][1]
        s._f.seek(au * s.AU + 17)
        s._f.write(b"\xff")
        s._f.flush()
        errs = s.fsck()
        assert errs and "crc mismatch" in errs[0]
        with pytest.raises(ChecksumError):
            s.read("c", "o")
        s.umount()

    def test_enospc_rolls_back(self, tmp_path):
        s = mk(tmp_path, size=128 << 10)         # 32 AUs
        s.queue_transaction(T().create_collection("c"))
        s.queue_transaction(T().write("c", "keep", 0, b"K" * 4096))
        with pytest.raises((StoreError, AllocatorError)):
            s.queue_transaction(
                T().write("c", "big", 0, b"B" * (256 << 10)))
        # the failed transaction left no trace: object absent, space
        # returned, committed data intact
        assert not s.exists("c", "big")
        assert s.read("c", "keep") == b"K" * 4096
        assert s.statfs()["allocated"] == s.AU
        assert s.fsck() == []
        s.umount()

    def test_osd_runs_on_bluestore(self, tmp_path):
        """The OSD daemon's store contract (the PG meta/log/object
        persistence WALStore serves) holds on BlueStore too."""
        import asyncio

        from ceph_tpu.cluster.vstart import Cluster

        async def go():
            stores = [mk(tmp_path / f"osd{i}") for i in range(3)]
            c = await Cluster(n_mons=1, n_osds=3,
                              stores=stores).start()
            try:
                await c.client.pool_create("p", pg_num=8, size=3)
                await c.wait_for_clean(timeout=240)
                io = await c.client.open_ioctx("p")
                for i in range(10):
                    await io.write_full(f"obj{i}", f"v{i}".encode()
                                        * 100)
                for i in range(10):
                    assert await io.read(f"obj{i}") == \
                        f"v{i}".encode() * 100
                for st in stores:
                    assert st.fsck() == []
            finally:
                await c.stop()
        asyncio.run(go())


class TestReviewRegressions:
    def test_two_deferred_writes_one_transaction(self, tmp_path):
        """Both small overwrites in ONE transaction must survive: the
        second op's buffer rebuild has to see the first op's pending
        deferred bytes (pre-fix, the first write silently vanished
        with a clean crc)."""
        s = mk(tmp_path)
        s.queue_transaction(T().create_collection("c"))
        s.queue_transaction(T().write("c", "o", 0, b"A" * 4096))
        s.queue_transaction(
            T().write("c", "o", 0, b"X")
               .write("c", "o", 100, b"Y"))
        got = s.read("c", "o")
        assert got[0:1] == b"X" and got[100:101] == b"Y"
        assert s.fsck() == []
        # and a deferred write followed by a clone in one transaction
        s.queue_transaction(
            T().write("c", "o", 200, b"Z").clone("c", "o", "o2"))
        assert s.read("c", "o2", 200, 1) == b"Z"
        s.umount()
        s2 = mk(tmp_path)               # replay path sees it all too
        got = s2.read("c", "o")
        assert got[0:1] == b"X" and got[100:101] == b"Y" \
            and got[200:201] == b"Z"
        s2.umount()

    def test_full_overwrite_repairs_corrupt_extent(self, tmp_path):
        """A fully-covering AU-aligned rewrite must not read (and so
        not crc-reject) the old bytes: it is the repair path for a
        corrupted extent."""
        s = mk(tmp_path)
        s.queue_transaction(T().create_collection("c"))
        s.queue_transaction(T().write("c", "o", 0, b"D" * 4096))
        au = s.onodes[("c", "o")].extents[0][1]
        s._f.seek(au * s.AU)
        s._f.write(b"\xee" * 64)
        s._f.flush()
        assert s.fsck()                  # corruption detected...
        s.queue_transaction(
            T().write("c", "o", 0, b"R" * 4096))   # ...repaired
        assert s.read("c", "o") == b"R" * 4096
        assert s.fsck() == []
        s.umount()


def test_objectstore_tool_on_bluestore(tmp_path):
    """ceph-objectstore-tool offline surgery works against a BlueStore
    data path (auto-sniffed via the block file)."""
    import json
    import os
    import subprocess
    import sys

    d = str(tmp_path / "osd0")
    s = BlueStore(d)
    s.queue_transaction(
        T().create_collection("1.0")
           .write("1.0", "obj", 0, b"hello")
           .setattrs("1.0", "obj", {"a": b"\x01"}))
    s.umount()
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, "-m", "ceph_tpu.bench.objectstore_tool",
         "--data-path", d, "--op", "info", "--pgid", "1.0",
         "--object", "obj"], capture_output=True, env=env)
    assert out.returncode == 0, out.stderr
    info = json.loads(out.stdout)
    assert info["size"] == 5 and info["attrs"] == {"a": "01"}
    out = subprocess.run(
        [sys.executable, "-m", "ceph_tpu.bench.objectstore_tool",
         "--data-path", d, "--op", "fsck"], capture_output=True,
        env=env)
    assert out.returncode == 0


@pytest.mark.slow
def test_osd_crash_remount_on_bluestore(tmp_path):
    """Kill an OSD, REMOUNT its BlueStore from disk (fresh instance —
    the real restart path incl. deferred replay), revive, and verify
    acked data survives and serves degraded + recovered reads."""
    import asyncio

    from ceph_tpu.cluster.vstart import Cluster

    async def go():
        stores = [mk(tmp_path / f"osd{i}") for i in range(3)]
        c = await Cluster(n_mons=1, n_osds=3, stores=stores).start()
        try:
            await c.client.pool_create("p", pg_num=8, size=3)
            await c.wait_for_clean(timeout=240)
            io = await c.client.open_ioctx("p")
            for i in range(12):
                await io.write_full(f"obj{i}", f"v{i}".encode() * 200)
            # hard-stop osd.2 and unmount its store entirely
            await c.kill_osd(2)
            stores[2].umount()
            await c.wait_for_osd_down(2, timeout=30)
            # degraded writes land on the survivors
            await io.write_full("during", b"degraded-write")
            # remount from disk: fresh BlueStore instance, mount replay
            remounted = mk(tmp_path / "osd2")
            assert remounted.fsck() == []
            await c.revive_osd(2, store=remounted)
            await c.wait_for_clean(timeout=240)
            for i in range(12):
                assert await io.read(f"obj{i}") == \
                    f"v{i}".encode() * 200
            assert await io.read("during") == b"degraded-write"
        finally:
            await c.stop()
    asyncio.run(go())


class TestReviewRegressions2:
    def test_partial_overwrite_of_corrupt_extent_refuses(self, tmp_path):
        """A partial overwrite of a corrupt extent must refuse rather
        than re-stamp a fresh crc over rotten bytes (laundering), on
        BOTH paths — the deferred in-place patch and the COW split —
        while the full-cover overwrite remains the repair path."""
        s = mk(tmp_path)
        s.queue_transaction(T().create_collection("c"))
        s.queue_transaction(T().write("c", "o", 0, b"G" * (128 << 10)))
        au = s.onodes[("c", "o")].extents[0][1]
        s._f.seek(au * s.AU + 3)
        s._f.write(b"\x99")
        s._f.flush()
        with pytest.raises(ChecksumError):
            # 8 KiB fits DEFERRED_MAX: the deferred patch verifies
            s.queue_transaction(
                T().write("c", "o", 4096, b"W" * 8192))
        with pytest.raises(ChecksumError):
            # 80 KiB > DEFERRED_MAX: the COW _replace_extents split's
            # pre-slice covers the corrupt AU 0 and must also refuse
            s.queue_transaction(
                T().write("c", "o", 4096, b"W" * (80 << 10)))
        # full-cover rewrite still repairs
        s2 = mk(tmp_path)  # reopen: the failed txns forced reloads
        s2.queue_transaction(
            T().write("c", "o", 0, b"R" * (128 << 10)))
        assert s2.read("c", "o") == b"R" * (128 << 10)
        assert s2.fsck() == []
        s2.umount()
        s.db.close()
        s._f.close()

    def test_zero_punches_holes_not_allocates(self, tmp_path):
        """Zeroing a huge allocated range FREES space (hole punch)
        instead of materializing zero bytes — and cannot ENOSPC."""
        s = mk(tmp_path, size=1 << 20)           # 256 AUs
        s.queue_transaction(T().create_collection("c"))
        payload = b"Q" * (600 << 10)             # 150 AUs
        s.queue_transaction(T().write("c", "o", 0, payload))
        used = s.statfs()["allocated"]
        assert used == 600 << 10
        # near-full store: zeroing most of the object must succeed
        s.queue_transaction(T().zero("c", "o", 100, (590 << 10)))
        assert s.statfs()["allocated"] < used // 2
        got = s.read("c", "o")
        assert got[:100] == b"Q" * 100
        assert got[100:100 + (590 << 10)] == b"\x00" * (590 << 10)
        assert got[100 + (590 << 10):] == payload[100 + (590 << 10):]
        assert s.fsck() == []
        s.umount()

    def test_benign_failure_is_cheap_and_clean(self, tmp_path):
        """Missing-object errors raise from the precondition pass
        (no reload, no mutation) and leave the store fully usable."""
        s = mk(tmp_path)
        s.queue_transaction(T().create_collection("c"))
        s.queue_transaction(T().write("c", "o", 0, b"keep"))
        with pytest.raises(StoreError):
            s.queue_transaction(
                T().rmattr("c", "ghost", "a"))
        with pytest.raises(StoreError):
            s.queue_transaction(T().write("nocoll", "o", 0, b"x"))
        assert s.read("c", "o") == b"keep"
        assert s.fsck() == []
        s.umount()


def test_unaligned_zero_on_full_store(tmp_path):
    """Zeroing with unaligned edges on a COMPLETELY full store must
    succeed: interior AUs punch into the free list and the sub-AU
    edges take the deferred (allocation-free) path."""
    s = mk(tmp_path, size=128 << 10)            # 32 AUs
    s.queue_transaction(T().create_collection("c"))
    s.queue_transaction(T().write("c", "o", 0, b"F" * (128 << 10)))
    assert s.statfs()["free"] == 0
    s.queue_transaction(T().zero("c", "o", 100, (120 << 10)))
    got = s.read("c", "o")
    assert got[:100] == b"F" * 100
    assert got[100:100 + (120 << 10)] == b"\x00" * (120 << 10)
    assert got[100 + (120 << 10):] == b"F" * ((8 << 10) - 100)
    assert s.statfs()["free"] > 0
    assert s.fsck() == []
    s.umount()


@pytest.mark.slow
def test_thrash_on_bluestore_with_remounts(tmp_path):
    """Small kill/revive thrash where every revive REMOUNTS the
    victim's BlueStore from disk (fresh instance — deferred replay,
    allocator rebuild): acked writes must survive recovery onto a
    store that went through a real restart, and every store fscks
    clean at the end (ref: the Thrasher discipline over the
    store_test crash matrix)."""
    import asyncio
    import random

    from ceph_tpu.cluster.vstart import Cluster

    async def go():
        rng = random.Random(5)
        stores = [mk(tmp_path / f"osd{i}") for i in range(4)]
        c = await Cluster(
            n_mons=1, n_osds=4, stores=stores,
            config={"mon_osd_down_out_interval": 600.0}).start()
        try:
            await c.client.pool_create("t", pg_num=8, size=3,
                                       min_size=2)
            await c.wait_for_clean(timeout=240)
            io = await c.client.open_ioctx("t")
            acked: dict[str, bytes] = {}
            seq = 0

            async def write_some(n: int) -> None:
                nonlocal seq
                for _ in range(n):
                    oid = f"obj{seq % 20}"
                    data = bytes([seq % 256]) * rng.randint(1, 4096)
                    await io.write_full(oid, data)
                    acked[oid] = data
                    seq += 1

            await write_some(10)
            for _ in range(2):
                victim = rng.randrange(4)
                await c.kill_osd(victim)
                stores[victim].umount()
                await c.wait_for_osd_down(victim, timeout=60)
                for oid, data in list(acked.items())[:4]:
                    assert await io.read(oid) == data
                await write_some(6)
                remounted = mk(tmp_path / f"osd{victim}")
                stores[victim] = remounted
                await c.revive_osd(victim, store=remounted)
                await c.wait_for_clean(timeout=240)
                await write_some(4)
            for oid, data in acked.items():
                assert await io.read(oid) == data, oid
            for st in stores:
                assert st.fsck() == [], "store fsck after thrash"
        finally:
            await c.stop()
    asyncio.run(go())


class TestSharedBlobClone:
    """Round-20 shared-blob COW clone: clone is O(metadata) (zero data
    extents duplicated), overwrites COW away from shared extents, AUs
    free only at refcount 0, the refcount table persists across
    remount, and fsck cross-checks stored refcounts against extent-map
    references (ref: BlueStore::SharedBlob + bluestore_shared_blob_t)."""

    def test_clone_moves_zero_bytes(self, tmp_path):
        s = mk(tmp_path)
        s.queue_transaction(T().create_collection("c"))
        s.queue_transaction(T().write("c", "o", 0, b"S" * 65536))
        alloc = s.statfs()["allocated"]
        src_aus = [list(x)[1:3] for x in s.onodes[("c", "o")].extents]
        s.queue_transaction(T().clone("c", "o", "o2"))
        # zero new space, identical AU references — the extent-map
        # assert from the acceptance criteria
        assert s.statfs()["allocated"] == alloc
        assert [list(x)[1:3] for x in
                s.onodes[("c", "o2")].extents] == src_aus
        assert s.statfs()["shared_blobs"] >= 1
        assert s.read("c", "o2") == b"S" * 65536
        assert s.fsck() == []
        s.umount()

    def test_overwrite_cows_off_shared_extent(self, tmp_path):
        s = mk(tmp_path)
        s.queue_transaction(T().create_collection("c"))
        s.queue_transaction(T().write("c", "o", 0, b"1" * 16384))
        s.queue_transaction(T().clone("c", "o", "snap"))
        # small overwrite would take the deferred in-place path on an
        # unshared extent; shared forces COW so the snap is untouched
        s.queue_transaction(T().write("c", "o", 100, b"XX"))
        assert s.read("c", "snap") == b"1" * 16384
        got = s.read("c", "o")
        assert got[100:102] == b"XX" and got[:100] == b"1" * 100
        assert s.fsck() == []
        s.umount()

    def test_refcount_pins_extents_until_last_ref(self, tmp_path):
        s = mk(tmp_path)
        s.queue_transaction(T().create_collection("c"))
        s.queue_transaction(T().write("c", "o", 0, b"P" * 32768))
        s.queue_transaction(T().clone("c", "o", "a"))
        s.queue_transaction(T().clone("c", "o", "b"))
        used = s.statfs()["allocated"]
        # removing two of three referencers frees nothing
        s.queue_transaction(T().remove("c", "o"))
        s.queue_transaction(T().remove("c", "a"))
        assert s.statfs()["allocated"] == used
        assert s.read("c", "b") == b"P" * 32768
        assert s.fsck() == []
        # the last referencer drops the AUs and the shared records
        s.queue_transaction(T().remove("c", "b"))
        assert s.statfs()["allocated"] == 0
        assert s.statfs()["shared_blobs"] == 0
        assert s.fsck() == []
        s.umount()

    def test_shared_refs_survive_remount(self, tmp_path):
        s = mk(tmp_path)
        s.queue_transaction(T().create_collection("c"))
        s.queue_transaction(T().write("c", "o", 0, b"R" * 20480))
        s.queue_transaction(T().clone("c", "o", "o2"))
        s.umount()
        s2 = mk(tmp_path)
        assert s2.statfs()["shared_blobs"] >= 1
        assert s2.fsck() == []
        # COW + release discipline still hold on the reloaded table
        s2.queue_transaction(T().write("c", "o", 0, b"W" * 20480))
        assert s2.read("c", "o2") == b"R" * 20480
        s2.queue_transaction(T().remove("c", "o2"))
        assert s2.statfs()["shared_blobs"] == 0
        assert s2.fsck() == []
        s2.umount()

    def test_truncate_partial_release_of_shared(self, tmp_path):
        s = mk(tmp_path)
        s.queue_transaction(T().create_collection("c"))
        s.queue_transaction(T().write("c", "o", 0, b"T" * 32768))
        s.queue_transaction(T().clone("c", "o", "o2"))
        used = s.statfs()["allocated"]
        # truncating one referencer drops its refs but frees nothing
        s.queue_transaction(T().truncate("c", "o2", 4096))
        assert s.statfs()["allocated"] == used
        assert s.read("c", "o") == b"T" * 32768
        assert s.read("c", "o2") == b"T" * 4096
        assert s.fsck() == []
        s.umount()

    def test_fsck_catches_refcount_drift(self, tmp_path):
        s = mk(tmp_path)
        s.queue_transaction(T().create_collection("c"))
        s.queue_transaction(T().write("c", "o", 0, b"F" * 8192))
        s.queue_transaction(T().clone("c", "o", "o2"))
        sb = next(iter(s.shared))
        au = next(iter(s.shared[sb]))
        s.shared[sb][au] += 1              # simulated leak
        errs = s.fsck()
        assert errs and any("refcount" in e for e in errs)
        s.shared[sb][au] -= 1
        assert s.fsck() == []
        s.umount()

    def test_knob_off_restores_byte_copy(self, tmp_path):
        s = BlueStore(str(tmp_path / "bs"),
                      config={"bluestore_sharedblob_enabled": False})
        s.queue_transaction(T().create_collection("c"))
        s.queue_transaction(T().write("c", "o", 0, b"K" * 8192))
        alloc = s.statfs()["allocated"]
        s.queue_transaction(T().clone("c", "o", "o2"))
        assert s.statfs()["allocated"] == 2 * alloc
        assert s.statfs()["shared_blobs"] == 0
        assert s.read("c", "o2") == b"K" * 8192
        assert s.fsck() == []
        s.umount()


def test_after_kv_commit_failpoint_leaves_reusable_store(tmp_path):
    """ADVICE low #5: the after_kv_commit fail point fires after the
    kv batch committed but before the deferred block writes and
    allocator release ran. The same cleanup as the other failure
    paths must run, so a REUSED instance (no remount) serves the
    committed content, has a consistent allocator, and fscks clean."""
    s = mk(tmp_path)
    s.queue_transaction(T().create_collection("c"))
    s.queue_transaction(T().write("c", "o", 0, b"A" * 4096))
    alloc_before = s.statfs()["allocated"]
    s._fail_point = "after_kv_commit"
    with pytest.raises(StoreError):
        s.queue_transaction(T().write("c", "o", 10, b"CRASH"))
    s._fail_point = None
    # the kv committed: the deferred overwrite is durable and must be
    # visible on the SAME instance (pre-fix the overlay was stale and
    # the allocator still held any replaced AUs)
    want = b"A" * 10 + b"CRASH" + b"A" * (4096 - 15)
    assert s.read("c", "o") == want
    assert s.statfs()["allocated"] == alloc_before
    assert s.fsck() == []
    # and the instance keeps working: COW rewrite + new object
    s.queue_transaction(T().write("c", "o", 0, b"B" * 65536))
    s.queue_transaction(T().write("c", "o2", 0, b"fresh"))
    assert s.read("c", "o") == b"B" * 65536
    assert s.read("c", "o2") == b"fresh"
    assert s.fsck() == []
    s.umount()
    # remount agrees (nothing replayed twice, nothing leaked)
    s2 = mk(tmp_path)
    assert s2.read("c", "o") == b"B" * 65536
    assert s2.fsck() == []
    s2.umount()
