"""GF(2^8) field and kernel tests.

Pattern mirrors the reference's EC unit tests: known-answer + algebraic
property checks (ref: src/test/erasure-code/TestErasureCode.cc style).
"""

import numpy as np
import pytest

from ceph_tpu.gf import (
    coeff_bitmatrix, expand_bitmatrix, gf_div, gf_inv, gf_matinv_np,
    gf_matmul_np, gf_matmul_bitplanes, gf_matmul_bytes, gf_matmul_lut,
    gf_mul, gf_mul_np, gf_pow, nibble_tables, pack_bits, unpack_bits,
)
from ceph_tpu.gf.tables import mul_table


class TestField:
    def test_known_products(self):
        # Hand-checked products under poly 0x11d.
        assert gf_mul(0, 5) == 0
        assert gf_mul(1, 5) == 5
        assert gf_mul(2, 128) == 0x11D ^ 0x100  # alpha * alpha^7 overflows
        assert gf_mul(3, 7) == 9  # (x+1)(x^2+x+1) = x^3+1
        # Commutativity + associativity on a sample.
        for a in (3, 77, 200, 255):
            for b in (9, 101, 254):
                assert gf_mul(a, b) == gf_mul(b, a)
                assert gf_mul(a, gf_mul(b, 13)) == gf_mul(gf_mul(a, b), 13)

    def test_distributive(self):
        for a in (5, 130, 251):
            for b in (17, 68):
                for c in (33, 240):
                    assert gf_mul(a, b ^ c) == gf_mul(a, b) ^ gf_mul(a, c)

    def test_inverse(self):
        for a in range(1, 256):
            assert gf_mul(a, gf_inv(a)) == 1
            assert gf_div(a, a) == 1

    def test_pow(self):
        assert gf_pow(2, 0) == 1
        x = 1
        for n in range(1, 20):
            x = gf_mul(x, 2)
            assert gf_pow(2, n) == x

    def test_mul_table_symmetric(self):
        t = mul_table()
        assert np.array_equal(t, t.T)
        assert np.array_equal(t[1], np.arange(256, dtype=np.uint8))


class TestBitmatrix:
    def test_coeff_bitmatrix_is_multiplication(self, rng):
        for c in (0, 1, 2, 3, 0x1D, 137, 255):
            M = coeff_bitmatrix(c)
            for x in rng.integers(0, 256, size=16):
                bits = (int(x) >> np.arange(8)) & 1
                ybits = M @ bits % 2
                y = int((ybits << np.arange(8)).sum())
                assert y == gf_mul(c, int(x)), (c, x)

    def test_expand_matches_blocks(self):
        m = np.array([[3, 7], [1, 255]], dtype=np.uint8)
        B = expand_bitmatrix(m)
        assert B.shape == (16, 16)
        assert np.array_equal(B[0:8, 8:16], coeff_bitmatrix(7))
        assert np.array_equal(B[8:16, 0:8], coeff_bitmatrix(1))


class TestMatinv:
    def test_roundtrip(self, rng):
        for n in (1, 2, 4, 8):
            while True:
                m = rng.integers(0, 256, size=(n, n)).astype(np.uint8)
                try:
                    inv = gf_matinv_np(m)
                    break
                except ValueError:
                    continue
            eye = gf_matmul_np(m, inv)
            assert np.array_equal(eye, np.eye(n, dtype=np.uint8))

    def test_singular_raises(self):
        with pytest.raises(ValueError):
            gf_matinv_np(np.zeros((3, 3), dtype=np.uint8))


class TestKernels:
    @pytest.fixture
    def case(self, rng):
        m = rng.integers(0, 256, size=(3, 8)).astype(np.uint8)
        data = rng.integers(0, 256, size=(8, 512)).astype(np.uint8)
        expect = gf_matmul_np(m, data)
        return m, data, expect

    def test_unpack_pack_roundtrip(self, rng):
        data = rng.integers(0, 256, size=(4, 64)).astype(np.uint8)
        assert np.array_equal(np.asarray(pack_bits(unpack_bits(data))), data)

    def test_bitplanes_matches_oracle(self, case):
        m, data, expect = case
        B = expand_bitmatrix(m).astype(np.int8)
        got = np.asarray(gf_matmul_bitplanes(B, data))
        assert np.array_equal(got, expect)

    def test_lut_matches_oracle(self, case):
        m, data, expect = case
        lo, hi = nibble_tables(m)
        got = np.asarray(gf_matmul_lut(lo, hi, data))
        assert np.array_equal(got, expect)

    def test_bytes_matches_oracle(self, case):
        m, data, expect = case
        got = np.asarray(gf_matmul_bytes(m, data))
        assert np.array_equal(got, expect)


class TestPallasKernel:
    """The fused pallas encode must be byte-exact vs the independent
    numpy GF oracle and the XLA bitmatmul path. Runs in interpret mode
    on CPU; the same code path runs compiled on TPU (benchmarked by
    bench.py, measured ~1.5x the XLA kernel on v5e)."""

    def _check(self, rng, k, m, B, C):
        from ceph_tpu.gf import pallas_kernels as pk

        mat = rng.integers(0, 256, size=(m, k)).astype(np.uint8)
        data = rng.integers(0, 256, size=(B, k, C)).astype(np.uint8)
        bm = expand_bitmatrix(mat)
        got = np.asarray(pk.encode_batch_planned(
            pk.make_plan(bm), np.asarray(data), interpret=True))
        expect = np.stack([gf_matmul_np(mat, d) for d in data])
        assert np.array_equal(got, expect), (k, m, B, C)

    def test_k8m3_tile_aligned(self, rng):
        from ceph_tpu.gf import pallas_kernels as pk
        self._check(rng, 8, 3, 2, pk.TILE_L)

    def test_multi_tile_and_geometries(self, rng):
        from ceph_tpu.gf import pallas_kernels as pk
        self._check(rng, 4, 2, 1, 2 * pk.TILE_L)
        self._check(rng, 10, 4, 2, pk.TILE_L)

    def test_plan_permutation(self, rng):
        from ceph_tpu.gf import pallas_kernels as pk

        mat = rng.integers(0, 256, size=(3, 8)).astype(np.uint8)
        bm = expand_bitmatrix(mat)
        plan = pk.make_plan(bm)
        bmm = np.asarray(plan.bm_bitmajor)
        k = 8
        for b in range(8):
            for i in range(k):
                assert np.array_equal(bmm[:, b * k + i], bm[:, 8 * i + b])

    def test_pallas_ok_gating(self):
        from ceph_tpu.gf import pallas_kernels as pk

        assert pk.pallas_ok(pk.TILE_L)
        assert pk.pallas_ok(4 * pk.TILE_L)
        assert not pk.pallas_ok(pk.TILE_L + 1)
        assert not pk.pallas_ok(0)


class TestPallasPlugin:
    """backend=pallas through the ErasureCodeJax plugin surface."""

    def test_encode_batch_matches_bitmatmul(self, rng):
        from ceph_tpu.ec.jax_plugin import ErasureCodeJax
        from ceph_tpu.gf import pallas_kernels as pk

        prof = "plugin=jax technique=reed_sol_van k=8 m=3"
        pall = ErasureCodeJax(prof + " backend=pallas")
        base = ErasureCodeJax(prof + " backend=bitmatmul")
        data = rng.integers(0, 256, size=(2, 8, pk.TILE_L)).astype(np.uint8)
        got = np.asarray(pall.encode_batch(np.asarray(data)))
        expect = np.asarray(base.encode_batch(np.asarray(data)))
        assert np.array_equal(got, expect)

    def test_unaligned_falls_back(self, rng):
        from ceph_tpu.ec.jax_plugin import ErasureCodeJax

        pall = ErasureCodeJax(
            "plugin=jax technique=reed_sol_van k=4 m=2 backend=pallas")
        base = ErasureCodeJax(
            "plugin=jax technique=reed_sol_van k=4 m=2 backend=bitmatmul")
        data = rng.integers(0, 256, size=(3, 4, 4096)).astype(np.uint8)
        got = np.asarray(pall.encode_batch(np.asarray(data)))
        expect = np.asarray(base.encode_batch(np.asarray(data)))
        assert np.array_equal(got, expect)

    def test_decode_roundtrip_pallas(self, rng):
        from ceph_tpu.ec.jax_plugin import ErasureCodeJax
        from ceph_tpu.gf import pallas_kernels as pk

        ec = ErasureCodeJax(
            "plugin=jax technique=reed_sol_van k=4 m=2 backend=pallas")
        data = rng.integers(0, 256, size=(4, pk.TILE_L)).astype(np.uint8)
        parity = np.asarray(ec.encode_chunks(data))
        chunks = {i: data[i] for i in range(4)} | {
            4 + j: parity[j] for j in range(2)}
        del chunks[0], chunks[5]
        out = ec.decode_chunks([0], chunks)
        assert np.array_equal(out[0], data[0])
