"""Standalone end-to-end scenario, qa-standalone style.

Mirrors qa/standalone/erasure-code/test-erasure-code.sh +
test-erasure-eio.sh: build a map from crushmap TEXT, create pools, write
objects through placement, kill OSDs, recover via decode, scrub, and
assert the cluster converges clean — all through the public APIs
(compiler, OSDMap, ECBackendLite, ChurnSim), no test-only backdoors.
"""

import numpy as np

from ceph_tpu.bench import osdmaptool
from ceph_tpu.crush.compiler import compile_crushmap
from ceph_tpu.crush.types import ITEM_NONE
from ceph_tpu.ec import factory
from ceph_tpu.osd import OSDMap, PGPool, POOL_TYPE_ERASURE
from ceph_tpu.osd.ec_backend import ECBackendLite
from ceph_tpu.osd.types import ObjectLocator
from ceph_tpu.sim import ChurnEvent, ChurnSim

CRUSHMAP_TEXT = """
# begin crush map
tunable chooseleaf_stable 1
{devices}
type 0 osd
type 1 host
type 10 root
{hosts}
root default {{
\tid -9
\talg straw2
\thash 0
{rootitems}
}}
rule replicated_rule {{
\tid 0
\ttype replicated
\tstep take default
\tstep chooseleaf firstn 0 type host
\tstep emit
}}
rule ecpool {{
\tid 1
\ttype erasure
\tstep take default
\tstep chooseleaf indep 0 type host
\tstep emit
}}
# end crush map
"""


def build_cluster(n_hosts=8, per_host=2):
    devices = "\n".join(f"device {i} osd.{i}"
                        for i in range(n_hosts * per_host))
    hosts = []
    for h in range(n_hosts):
        items = "\n".join(
            f"\titem osd.{h * per_host + j} weight 1.000"
            for j in range(per_host))
        hosts.append(f"host host{h} {{\n\tid -{h + 1}\n\talg straw2\n"
                     f"\thash 0\n{items}\n}}")
    root = "\n".join(f"\titem host{h} weight {per_host:.3f}"
                     for h in range(n_hosts))
    text = CRUSHMAP_TEXT.format(devices=devices,
                                hosts="\n".join(hosts), rootitems=root)
    crush = compile_crushmap(text)
    m = OSDMap(crush)
    m.add_pool(PGPool(id=1, pg_num=32, size=3, type=1, crush_rule=0))
    m.add_pool(PGPool(id=2, pg_num=32, size=5, type=POOL_TYPE_ERASURE,
                      crush_rule=1))
    return m


class Cluster:
    """A tiny client view: object name -> PG -> OSDs -> shard store.

    Object data lives in per-PG ECBackendLite instances (the EC pool's
    data path); placement comes from the OSDMap pipeline exactly as the
    Objecter computes it (ref: src/osdc/Objecter.cc _calc_target)."""

    def __init__(self, osdmap: OSDMap, k=3, m=2):
        self.map = osdmap
        self.k, self.m = k, m
        self.backends: dict[int, ECBackendLite] = {}
        self.placements: dict[str, tuple[int, np.ndarray]] = {}

    def _backend(self, seed: int) -> ECBackendLite:
        if seed not in self.backends:
            self.backends[seed] = ECBackendLite(
                factory(f"plugin=jax k={self.k} m={self.m}"),
                chunk_size=128, name=f"pg2_{seed}")
        return self.backends[seed]

    def write(self, name: str, data: bytes) -> None:
        pg = self.map.object_locator_to_pg(name, ObjectLocator(pool=2))
        seed = self.map.pools[2].raw_pg_to_pg(
            np.asarray([pg.seed], dtype=np.uint32))[0]
        up, _, _, _ = self.map.pg_to_up_acting_osds(2, [int(seed)])
        self._backend(int(seed)).write(name, 0, data)
        self.placements[name] = (int(seed), up[0].copy())

    def read(self, name: str, length: int) -> bytes:
        seed, _ = self.placements[name]
        return self._backend(seed).read(name, 0, length)

    def osd_died(self, osd: int) -> None:
        """Drop every shard the dead OSD held (by placement slot)."""
        for name, (seed, up) in self.placements.items():
            for slot in range(len(up)):
                if up[slot] == osd:
                    self._backend(seed).lose_shard(slot, name)

    def recover_all(self) -> int:
        n = 0
        for seed, be in self.backends.items():
            n += sum(len(v) for v in be.recover_all().values())
        return n

    def scrub_all(self) -> dict:
        bad = {}
        for seed, be in self.backends.items():
            for name in list(be.sizes):
                errs = be.scrub(name)
                if errs:
                    bad[name] = errs
        return bad


class TestStandaloneScenario:
    def test_full_lifecycle(self):
        rng = np.random.default_rng(29)
        m = build_cluster()
        # 1. healthy placement: full distinct-host sets in both pools
        up_r, _, _, _ = m.map_pool(1)
        up_e, _, _, _ = m.map_pool(2)
        assert not (up_r == ITEM_NONE).any()
        assert not (up_e == ITEM_NONE).any()
        for row in up_e:
            assert len({int(o) // 2 for o in row}) == 5  # distinct hosts

        # 2. write objects through placement
        cluster = Cluster(m)
        payloads = {}
        for i in range(24):
            name = f"obj{i}"
            payloads[name] = rng.integers(
                0, 256, int(rng.integers(100, 4000)),
                dtype=np.uint8).tobytes()
            cluster.write(name, payloads[name])
        for name, data in payloads.items():
            assert cluster.read(name, len(data)) == data
        assert cluster.scrub_all() == {}

        # 3. kill an OSD: placement remaps, shards are lost
        victim = int(up_e[0, 0])
        sim = ChurnSim(m, 2)
        rep = sim.apply(ChurnEvent("down", victim))
        assert rep.degraded_pgs > 0          # indep holes until out
        cluster.osd_died(victim)
        assert any(cluster._backend(s).missing_shards(n)
                   for n, (s, _) in cluster.placements.items()
                   if victim in cluster.placements[n][1])

        # 4. recover via decode (the EC recovery path), data survives
        recovered = cluster.recover_all()
        assert recovered > 0
        for name, data in payloads.items():
            assert cluster.read(name, len(data)) == data
        assert cluster.scrub_all() == {}

        # 5. mark out: backfill targets found, placement complete again
        rep = sim.apply(ChurnEvent("out", victim))
        assert rep.degraded_pgs == 0
        up_e2, _, _, _ = m.map_pool(2)
        assert not (up_e2 == ITEM_NONE).any()
        assert not (up_e2 == victim).any()

        # 6. balancer keeps the survivors even
        m.calc_pg_upmaps(max_deviation=3, max_iterations=200)
        util = m.pool_utilization(1) + m.pool_utilization(2)
        alive = util[np.asarray(m.osd_weight) > 0]
        tgt = alive.mean()
        assert np.abs(alive - tgt).max() <= 2 * 3 + 1

        # 7. revive: pure-function placement returns to the original
        sim.apply(ChurnEvent("in", victim))
        sim.apply(ChurnEvent("up", victim))
        m.pg_upmap_items.clear()
        m._dirty()
        up_e3, _, _, _ = m.map_pool(2)
        assert (up_e3 == up_e).all()
