"""Durable lossless-replay peer for test_replay_restart.py.

A tiny stand-in for a daemon's apply path, run as a REAL OS process:

    python tests/_replay_child.py PORT NAME PEER KEY_SELF KEY_PEER LOG

Binds a lossless messenger on the FIXED port and appends every MRec it
dispatches to LOG with flush+fsync before returning — i.e. before the
transport acks — so the log after a SIGKILL holds exactly the ops whose
acks the sender may have seen.  The parent kills this process and
respawns it with identical argv: same entity name, same port, fresh
memory.  Not a pytest module (underscore prefix keeps it uncollected).
"""

import asyncio
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ceph_tpu.msg import (                                    # noqa: E402
    Dispatcher, Keyring, Message, Messenger, Policy, register,
)


@register
class MRec(Message):
    TYPE = 902            # test-only; golden corpus filters non-ceph_tpu
    FIELDS = [("op", "u64"), ("payload", "blob")]


class _Applier(Dispatcher):
    def __init__(self, path: str):
        self.path = path

    async def ms_dispatch(self, msg):
        if not isinstance(msg, MRec):
            return False
        with open(self.path, "a") as f:
            f.write(f"{msg.op}:{msg.payload.hex()}\n")
            f.flush()
            os.fsync(f.fileno())
        return True


async def _main(port: int, name: str, peer: str,
                key_self: bytes, key_peer: bytes, path: str) -> None:
    kr = Keyring({name: key_self, peer: key_peer})
    msgr = Messenger(name, keyring=kr)
    msgr.set_policy(peer.split(".", 1)[0], Policy.lossless_peer())
    msgr.add_dispatcher(_Applier(path))
    await msgr.bind("127.0.0.1", port)
    print("READY", flush=True)
    await asyncio.Event().wait()      # run until SIGKILLed


if __name__ == "__main__":
    _port, _name, _peer, _ks, _kp, _path = sys.argv[1:7]
    asyncio.run(_main(int(_port), _name, _peer,
                      bytes.fromhex(_ks), bytes.fromhex(_kp), _path))
