"""Cluster telemetry plane (round 12).

Acceptance surface:

- in a multi-daemon cluster with the singleton fallback DISABLED,
  `/metrics` is built solely from shipped MMgrOpen/MMgrReport state
  and agrees with each daemon's local ``perf dump``;
- a monotonic-counter rate query returns the correct derivative
  across report periods (exact in the unit test, live in-cluster);
- a backfill storm's progress event goes 0 -> 1 and clears on settle
  (`ceph progress ls` empty, the completed ring keeps the history);
- mgr failover: kill the active mgr, the mon's beacon-grace tick
  promotes a standby, daemons re-open their sessions (schema
  re-sent), the fresh DaemonStateIndex repopulates, and `/metrics` +
  `progress ls` recover with no stale daemons pinned;
- `ceph osd perf` serves per-OSD commit/apply latency from the
  reported objectstore time-avgs, and `daemon-stats` serves live
  rates from the retained time series over the mgr's admin socket.

Budget discipline: ONE vstart cluster carries every telemetry assert
(metrics agreement, rates, osd perf, daemon-stats, backfill
progress); the failover test uses a second, smaller cluster; the
mid-storm failover variant is ``slow``.
"""

import asyncio
import json
import re
import time

import pytest

from ceph_tpu.cluster.vstart import Cluster
from ceph_tpu.mgr.daemon_state import ALLOWED_TYPES, DaemonStateIndex
from ceph_tpu.mgr.client import MgrReporter, schema_entries
from ceph_tpu.mgr.modules import ProgressModule, PrometheusModule
from ceph_tpu.mon.mgr_monitor import MgrMap
from ceph_tpu.os_.objectstore import MemStore
from ceph_tpu.utils.perf_counters import PerfCountersBuilder


def run(coro):
    asyncio.run(coro)


# -- units: the DaemonStateIndex store + query surface ----------------------

def _schema(*entries):
    return [{"logger": lg, "counter": ct, "type": ty,
             "monotonic": mono, "doc": ""}
            for lg, ct, ty, mono in entries]


def test_rate_query_exact_derivative():
    """The acceptance-pinned contract: a monotonic counter reported at
    known (t, v) pairs yields exactly (v1-v0)/(t1-t0) over the ring,
    and the windowed variant uses the oldest sample INSIDE the
    window."""
    idx = DaemonStateIndex(retention=8)
    sch = _schema(("osd.0", "ops", "u64", True))
    idx.report("osd.0", 1, sch, 10.0, {"osd.0": {"ops": 100}})
    idx.report("osd.0", 1, None, 12.0, {"osd.0": {"ops": 150}})
    idx.report("osd.0", 1, None, 14.0, {"osd.0": {"ops": 260}})
    # whole ring: (260 - 100) / (14 - 10)
    assert idx.rate("osd.0", "osd.0", "ops") == pytest.approx(40.0)
    # window covering only the last span: (260 - 150) / (14 - 12)
    assert idx.rate("osd.0", "osd.0", "ops",
                    window_s=2.0) == pytest.approx(55.0)
    # unchanged counter still samples: rate decays toward 0
    idx.report("osd.0", 1, None, 18.0, {})
    assert idx.rate("osd.0", "osd.0", "ops",
                    window_s=4.0) == pytest.approx(0.0)
    # ring is bounded by retention
    st = idx.daemons["osd.0"]
    for i in range(20):
        idx.report("osd.0", 1, None, 20.0 + i,
                   {"osd.0": {"ops": 300 + i}})
    assert len(st.series[("osd.0", "ops")]) == 8
    # non-monotonic / unknown counters have no series
    assert idx.rate("osd.0", "osd.0", "nope") is None


def test_session_seq_discipline_and_schema_first():
    """A newer session_seq RESETS state (failover re-open / fresh
    incarnation); an older one is a zombie and is dropped; a
    schema-less report for an unknown daemon is dropped (the sender
    re-opens with schema next period); a schema-carrying report is
    self-sufficient."""
    idx = DaemonStateIndex()
    sch = _schema(("osd.1", "ops", "u64", True))
    # schema-less report for an unknown daemon: dropped
    assert not idx.report("osd.1", 1, None, 1.0,
                          {"osd.1": {"ops": 5}})
    assert "osd.1" not in idx.daemons
    # schema-carrying report is self-sufficient (lost/raced open)
    assert idx.report("osd.1", 1, sch, 1.0, {"osd.1": {"ops": 5}})
    assert idx.daemons["osd.1"].latest[("osd.1", "ops")] == 5
    # zombie incarnation (older seq): dropped, state intact
    assert not idx.report("osd.1", 0, sch, 2.0,
                          {"osd.1": {"ops": 999}})
    assert idx.daemons["osd.1"].latest[("osd.1", "ops")] == 5
    # newer seq resets: old counters must not survive the reset
    idx.daemons["osd.1"].latest[("osd.1", "retired")] = 42
    assert idx.report("osd.1", 2, sch, 3.0, {"osd.1": {"ops": 7}})
    st = idx.daemons["osd.1"]
    assert ("osd.1", "retired") not in st.latest
    assert st.latest[("osd.1", "ops")] == 7
    # values without a schema entry are dropped (typeless guessing
    # is exactly what the schema-first discipline forbids)
    idx.report("osd.1", 2, None, 4.0, {"osd.1": {"mystery": 1}})
    assert ("osd.1", "mystery") not in st.latest
    # schema entries naming unregistered types are dropped
    n = st.apply_schema(_schema(("osd.1", "bad", "florp", True)))
    assert n == 0 and ("osd.1", "bad") not in st.schema


def test_histogram_percentile_and_avg_reads():
    idx = DaemonStateIndex()
    sch = _schema(("osd.2", "lat_hist", "hist", False),
                  ("osd.2", "commit_latency", "avg", False))
    buckets = [0] * 64
    # 90 values in bucket 3 (<=8), 10 in bucket 10 (<=1024)
    buckets[3], buckets[10] = 90, 10
    idx.report("osd.2", 1, sch, 1.0, {"osd.2": {
        "lat_hist": {"count": 100, "sum": 5000.0,
                     "log2_buckets": buckets},
        "commit_latency": {"avgcount": 4, "sum": 2.0}}})
    st = idx.daemons["osd.2"]
    assert st.percentile("osd.2", "lat_hist", 0.5) == 8.0
    assert st.percentile("osd.2", "lat_hist", 0.99) == 1024.0
    assert st.avg_value("osd.2", "commit_latency") == \
        pytest.approx(0.5)
    assert st.percentile("osd.2", "commit_latency", 0.5) is None


def test_cull_ttl_drops_silent_daemons():
    idx = DaemonStateIndex()
    sch = _schema(("osd.3", "ops", "u64", True))
    idx.report("osd.3", 1, sch, 1.0, {})
    idx.daemons["osd.3"].last_report -= 100.0      # long silent
    idx.report("osd.4", 1, _schema(("osd.4", "ops", "u64", True)),
               1.0, {})
    assert idx.cull(stale_s=10.0) == ["osd.3"]
    assert sorted(idx.daemons) == ["osd.4"]


def test_mgrmap_roundtrip_and_summary():
    m = MgrMap()
    m.epoch = 7
    m.active_gid = 3
    m.active_name = "x"
    m.active_addr = ("127.0.0.1", 4242)
    m.standbys = {5: ("y", "127.0.0.1", 4243)}
    again = MgrMap.decode(m.encode())
    assert (again.epoch, again.active_gid, again.active_name,
            again.active_addr) == (7, 3, "x", ("127.0.0.1", 4242))
    assert again.standbys == m.standbys
    assert again.available()
    assert MgrMap.decode(b"").epoch == 0
    assert not MgrMap.decode(b"").available()
    assert again.summary()["standbys"] == ["y"]


class _FakeMessenger:
    """Records (message, addr, peer) sends for the reporter unit."""

    def __init__(self):
        self.sent = []
        self.fail_next = False

    async def send_message(self, msg, addr, peer):
        if self.fail_next:
            self.fail_next = False
            raise ConnectionError("injected")
        self.sent.append(msg)


def test_reporter_schema_once_then_deltas_and_failover_resend():
    """The wire discipline: schema ships on session open (with FULL
    values — it re-seeds the receiver), later reports carry only
    changed counters, and a new active gid (failover) or a send
    failure re-opens with schema again."""
    async def go():
        pc = (PerfCountersBuilder("unit.0")
              .add_u64_counter("ops", "unit fixture")
              .add_u64("gauge", "unit fixture")
              .create_perf_counters(register=False))
        mm = MgrMap()
        mm.active_gid, mm.active_name = 1, "x"
        mm.active_addr = ("127.0.0.1", 9999)
        msgr = _FakeMessenger()
        rep = MgrReporter("unit.0", msgr, lambda: mm, lambda: [pc],
                          {"mgr_stats_schema_refresh": 1000})
        pc.inc("ops", 3)
        assert await rep.report_once()
        open_msg, first = msgr.sent[0], msgr.sent[1]
        assert open_msg.daemon == "unit.0"
        sch = json.loads(first.schema)
        assert {e["counter"] for e in sch} == {"ops", "gauge"}
        assert all(e["type"] in ALLOWED_TYPES for e in sch)
        vals = json.loads(first.values)["counters"]["unit.0"]
        assert vals == {"ops": 3, "gauge": 0}     # full on schema
        # steady state: only the changed counter travels, no schema
        pc.inc("ops")
        assert await rep.report_once()
        second = msgr.sent[-1]
        assert second.schema == b""
        assert json.loads(second.values)["counters"] == \
            {"unit.0": {"ops": 4}}
        # all-unchanged period still reports (TTL refresh, rate 0)
        assert await rep.report_once()
        assert json.loads(msgr.sent[-1].values)["counters"] == {}
        # send failure resets the session: next report re-opens
        msgr.fail_next = True
        with pytest.raises(ConnectionError):
            await rep.report_once()
        n = len(msgr.sent)
        assert await rep.report_once()
        reopen, full = msgr.sent[n], msgr.sent[n + 1]
        assert type(reopen).__name__ == "MMgrOpen"
        assert reopen.session_seq > open_msg.session_seq
        assert json.loads(full.schema)            # schema re-sent
        # failover (new active gid): same re-open discipline
        mm.active_gid = 2
        assert await rep.report_once()
        assert type(msgr.sent[-2]).__name__ == "MMgrOpen"
        assert json.loads(msgr.sent[-1].schema)
        assert rep.sessions_opened == 3
    run(go())


# -- the shared-cluster acceptance run --------------------------------------

TELEMETRY_CFG = {
    "mgr_stats_singleton_fallback": False,   # reported state ONLY
    "mgr_stats_period": 0.2,
    "mgr_stats_retention": 600,
    "mon_osd_down_out_interval": 600.0,
    # tiny retained log so the backfill phase crosses the trim
    # horizon, throttled pushes so the progress event is observable
    # in flight (50 x 256B at ~4KB/s spans multiple progress ticks)
    "osd_min_pg_log_entries": 5,
    "osd_recovery_max_bytes": 4000,
}

_PERF_ROW = re.compile(
    r'^ceph_perf\{ceph_daemon="([^"]+)",counter="([^"]+)"\} (\S+)$')


async def _reported_counter(mgr, daemon, counter):
    st = mgr.daemon_state.daemons.get(daemon)
    if st is None:
        return None
    return st.latest.get((daemon, counter))


async def _wait_reported(mgr, daemons, timeout=20.0):
    deadline = asyncio.get_event_loop().time() + timeout
    while set(daemons) - set(mgr.daemon_state.daemons):
        assert asyncio.get_event_loop().time() < deadline, (
            f"daemons never reported: expected {sorted(daemons)}, "
            f"have {sorted(mgr.daemon_state.daemons)}")
        await asyncio.sleep(0.05)


def test_telemetry_plane(tmp_path):
    """The tentpole acceptance run on ONE cluster: report sessions
    populate the index; `/metrics` renders solely from reported state
    and agrees with each daemon's local perf dump; rate queries are
    live; `ceph osd perf` + `daemon-stats` serve; a backfill's
    progress event goes 0 -> 1 and clears on settle."""
    async def go():
        c = await Cluster(
            n_mons=1, n_osds=3, n_mgrs=1,
            config=dict(TELEMETRY_CFG,
                        admin_socket_dir=str(tmp_path)),
            mgr_modules=[PrometheusModule, ProgressModule]).start()
        try:
            await c.client.pool_create("t", pg_num=4, size=3,
                                       min_size=2)
            await c.wait_for_clean(timeout=120)
            io = await c.client.open_ioctx("t")
            mgr = c.active_mgr()
            assert mgr is not None

            # -- sessions: every daemon type reports (OSDs + mon) -----
            await _wait_reported(
                mgr, ["osd.0", "osd.1", "osd.2", "mon.a"])
            for name in ("osd.0", "osd.1", "osd.2", "mon.a"):
                assert mgr.daemon_state.daemons[name].schema, name

            # -- write burst; reported state must converge on the ----
            # -- daemons' own perf dumps once quiesced ----------------
            t0 = time.monotonic()
            for i in range(40):
                await io.write_full(f"obj-{i % 8}", b"x" * 512)
            burst_span = time.monotonic() - t0
            local = {f"osd.{o.whoami}": o.perf.dump()["ops"]
                     for o in c.osds}
            assert sum(local.values()) >= 40
            deadline = asyncio.get_event_loop().time() + 20
            while True:
                reported = {
                    n: (await _reported_counter(mgr, n, "ops"))
                    for n in local}
                if reported == local:
                    break
                assert asyncio.get_event_loop().time() < deadline, (
                    f"reported state never converged: {reported} "
                    f"vs local {local}")
                await asyncio.sleep(0.1)

            # -- live rate: the burst's derivative is visible ---------
            window = max(burst_span, 1.0) + 2.0
            rates = [mgr.daemon_state.rate(n, n, "ops", window)
                     for n in local]
            assert any(r and r > 0 for r in rates), rates
            # sum of per-OSD op rates over the burst window is the
            # cluster write rate, bounded by the offered load
            total = sum(r or 0.0 for r in rates)
            assert 0 < total <= (40 / burst_span) * 3 + 50, (
                total, burst_span)

            # -- /metrics is built from reported state ONLY -----------
            pm = next(m for m in mgr.modules
                      if m.NAME == "prometheus")
            text = await pm.render()
            rows = {}
            for line in text.splitlines():
                m2 = _PERF_ROW.match(line)
                if m2:
                    rows[(m2.group(1), m2.group(2))] = m2.group(3)
            for n, v in local.items():
                assert float(rows[(n, "ops")]) == v, (n, rows)
            assert ("mon.a", "paxos_commits") in rows
            # the singleton render's label key never appears
            assert 'ceph_perf{daemon=' not in text
            # reported histograms render as le-bucketed series
            assert 'ceph_perf_hist_bucket{ceph_daemon="' in text

            # -- `ceph osd perf` + prometheus latency rows ------------
            # (poll: the mon serves the ACTIVE MGR'S LAST DIGEST,
            # which can predate the write burst by one progress tick)
            deadline = asyncio.get_event_loop().time() + 15
            while True:
                ret, _, out = await c.client.mon_command(
                    {"prefix": "osd perf"})
                assert ret == 0
                perf = json.loads(out)["osd_perf"]
                if sorted(perf) == ["0", "1", "2"]:
                    break
                assert asyncio.get_event_loop().time() < deadline, (
                    f"osd perf digest never populated: {perf}")
                await asyncio.sleep(0.1)
            for row in perf.values():
                assert row["commit_latency_ms"] >= 0.0
                assert row["apply_latency_ms"] >= 0.0
            assert "ceph_osd_commit_latency_ms{" in text
            assert "ceph_osd_apply_latency_ms{" in text

            # -- daemon-stats over the mgr admin socket ---------------
            from ceph_tpu.utils.admin_socket import daemon_command
            stats = await daemon_command(
                f"{tmp_path}/mgr.{mgr.name}.asok",
                {"prefix": "daemon-stats", "name": "osd.0"})
            assert stats["daemon"] == "osd.0"
            assert stats["series_depth"] >= 2
            assert "ops" in stats["rates_per_s"].get("osd.0", {})
            missing = await daemon_command(
                f"{tmp_path}/mgr.{mgr.name}.asok",
                {"prefix": "daemon-stats", "name": "osd.99"})
            assert "error" in missing

            # -- backfill progress: 0 -> 1, clears on settle ----------
            data = {}
            for i in range(50):
                oid = f"bf-{i:04d}"
                await io.write_full(oid, bytes([i % 256]) * 256)
                data[oid] = bytes([i % 256]) * 256
                if i == 9:
                    await c.kill_osd(2)
                    await c.wait_for_osd_down(2, timeout=60)
            await c.revive_osd(2, store=MemStore())   # fresh join
            saw_inflight = None
            deadline = asyncio.get_event_loop().time() + 90
            while True:
                ret, _, out = await c.client.mon_command(
                    {"prefix": "progress ls"})
                assert ret == 0
                evs = {e["id"]: e for e in
                       json.loads(out)["events"]}
                bf = evs.get("backfill")
                if bf is not None and 0.0 <= bf["fraction"] < 1.0:
                    saw_inflight = bf
                try:
                    await c.wait_for_clean(timeout=0.5)
                    break
                except (TimeoutError, AssertionError):
                    pass
                assert asyncio.get_event_loop().time() < deadline, \
                    f"backfill never settled (events: {evs})"
            assert saw_inflight is not None, \
                "backfill progress event never observed in flight"
            assert "Backfilling" in saw_inflight["message"]
            # settle: `progress ls` clears, the completed ring keeps
            # the event at fraction 1.0
            deadline = asyncio.get_event_loop().time() + 30
            while True:
                ret, _, out = await c.client.mon_command(
                    {"prefix": "progress json"})
                assert ret == 0
                pj = json.loads(out)
                live = {e["id"] for e in pj["events"]}
                done = {e["id"]: e for e in pj["completed"]}
                if "backfill" not in live and "backfill" in done:
                    assert done["backfill"]["fraction"] == 1.0
                    break
                assert asyncio.get_event_loop().time() < deadline, (
                    f"backfill event never completed: live={live} "
                    f"done={sorted(done)}")
                await asyncio.sleep(0.2)
            # the storm's data really backfilled (not just reported)
            for oid, payload in data.items():
                assert await io.read(oid) == payload

            # status carries the progress block + mgrmap
            ret, _, out = await c.client.mon_command(
                {"prefix": "status"})
            status = json.loads(out)
            assert "progress" in status
            assert status["mgrmap"]["available"]
        finally:
            await c.stop()
    run(go())


# -- mgr failover: the self-healing discipline ------------------------------

FAILOVER_CFG = {
    "mgr_stats_singleton_fallback": False,
    "mgr_stats_period": 0.2,
    "mgr_beacon_grace": 1.5,
    "mgr_stats_stale_s": 3.0,
}


async def _failover_once(c, io, write_concurrently=False):
    """Kill the active mgr, wait for the standby's promotion, and
    assert the new index repopulates from re-opened sessions."""
    old = c.active_mgr()
    assert old is not None
    await _wait_reported(old, ["osd.0", "osd.1"])
    writer_errors = []
    stop_writing = asyncio.Event()

    async def writer():
        i = 0
        while not stop_writing.is_set():
            try:
                await io.write_full(f"st-{i % 16}", b"w" * 512)
            except Exception as e:           # zero-errors contract
                writer_errors.append(e)
            i += 1
            await asyncio.sleep(0.01)

    wtask = asyncio.ensure_future(writer()) if write_concurrently \
        else None
    try:
        await c.kill_mgr(old)
        new = await c.wait_for_mgr_active(not_gid=old.gid,
                                          timeout=30.0)
        assert new.gid != old.gid and new.active
        # daemons re-open against the promoted standby: its EMPTY
        # index repopulates, schema re-sent because the session seq
        # changed (poll — one report period after promotion)
        await _wait_reported(new, ["osd.0", "osd.1", "mon.a"],
                             timeout=30.0)
        for name in ("osd.0", "osd.1", "mon.a"):
            st = new.daemon_state.daemons[name]
            assert st.schema, f"{name}: schema not re-sent"
        # reporter-side: a fresh session was opened per daemon
        for osd in c.osds:
            assert osd._mgr_reporter.sessions_opened >= 2
    finally:
        if wtask is not None:
            stop_writing.set()
            await wtask
    assert not writer_errors, writer_errors[:3]
    return old, new


def test_mgr_failover_repopulates_index(tmp_path):
    """Kill the active mgr; the standby promotes through the mon's
    beacon-grace tick; daemons re-open sessions; `/metrics` and
    `progress ls` recover with no stale daemons pinned."""
    async def go():
        c = await Cluster(
            n_mons=1, n_osds=2, n_mgrs=2,
            config=dict(FAILOVER_CFG,
                        admin_socket_dir=str(tmp_path)),
            mgr_modules=[PrometheusModule, ProgressModule]).start()
        try:
            await c.client.pool_create("t", pg_num=4, size=2)
            await c.wait_for_clean(timeout=120)
            io = await c.client.open_ioctx("t")
            for i in range(10):
                await io.write_full(f"o-{i}", b"x" * 256)
            old, new = await _failover_once(c, io)
            # /metrics from the NEW active renders reported state
            pm = next(m for m in new.modules
                      if m.NAME == "prometheus")
            deadline = asyncio.get_event_loop().time() + 20
            while True:
                text = await pm.render()
                if 'ceph_perf{ceph_daemon="osd.0"' in text and \
                        'ceph_perf{ceph_daemon="osd.1"' in text:
                    break
                assert asyncio.get_event_loop().time() < deadline, \
                    "new active's /metrics never recovered"
                await asyncio.sleep(0.1)
            # no stale daemons pinned: the culled view holds exactly
            # the live reporters (old mgr's own state never leaks in)
            new.daemon_state.cull(3.0)
            assert set(new.daemon_state.daemons) <= \
                {"osd.0", "osd.1", "mon.a"}
            # progress serves from the new gid's digests
            deadline = asyncio.get_event_loop().time() + 20
            while True:
                ret, _, out = await c.client.mon_command(
                    {"prefix": "progress json"})
                assert ret == 0
                if json.loads(out).get("from_mgr_gid") == new.gid:
                    break
                assert asyncio.get_event_loop().time() < deadline, \
                    "mon never saw the new active's digest"
                await asyncio.sleep(0.1)
            # the map agrees end to end
            ret, _, out = await c.client.mon_command(
                {"prefix": "mgr stat"})
            assert ret == 0
            stat = json.loads(out)
            assert stat["active_gid"] == new.gid
            assert stat["available"]
        finally:
            await c.stop()
    run(go())


@pytest.mark.slow
def test_mgr_failover_mid_storm_deep(tmp_path):
    """Deep variant: failover UNDER a concurrent write storm (zero
    writer errors — the data path never depends on the mgr), twice in
    a row (the second failover exercises a previously-promoted
    active's replacement), with rate queries live on the final
    active."""
    async def go():
        c = await Cluster(
            n_mons=1, n_osds=2, n_mgrs=3,
            config=FAILOVER_CFG,
            mgr_modules=[PrometheusModule, ProgressModule]).start()
        try:
            await c.client.pool_create("t", pg_num=4, size=2)
            await c.wait_for_clean(timeout=120)
            io = await c.client.open_ioctx("t")
            _, second = await _failover_once(
                c, io, write_concurrently=True)
            _, third = await _failover_once(
                c, io, write_concurrently=True)
            assert third.gid != second.gid
            # the final active's time series answers rate queries
            deadline = asyncio.get_event_loop().time() + 20
            while True:
                r = third.daemon_state.rate("osd.0", "osd.0", "ops")
                if r is not None:
                    break
                assert asyncio.get_event_loop().time() < deadline
                await asyncio.sleep(0.1)
        finally:
            await c.stop()
    run(go())


# -- the CLI surface --------------------------------------------------------

def test_ceph_cli_telemetry_verbs_parse():
    """`ceph osd perf` / `progress ls|json` / `mgr dump|stat|fail`
    parse to their mon command prefixes (read-only cap class pinned
    in mon/auth_monitor.py's READONLY_COMMANDS)."""
    from ceph_tpu.bench.ceph_cli import _parse_command
    from ceph_tpu.mon.auth_monitor import READONLY_COMMANDS
    for words, prefix in [
            (["osd", "perf"], "osd perf"),
            (["progress", "ls"], "progress ls"),
            (["progress", "json"], "progress json"),
            (["mgr", "dump"], "mgr dump"),
            (["mgr", "stat"], "mgr stat")]:
        cmd, _ = _parse_command(words)
        assert cmd["prefix"] == prefix
        assert prefix in READONLY_COMMANDS, (
            f"{prefix} must be readable with read-only caps")
    cmd, _ = _parse_command(["mgr", "fail"])
    assert cmd["prefix"] == "mgr fail"
    assert "mgr fail" not in READONLY_COMMANDS   # it mutates the map


def test_schema_entries_match_perf_counters_types():
    """Every schema entry shipped for a full-typed PerfCounters names
    a type the DaemonStateIndex accepts (the live half of the
    test_meta AST guard)."""
    pc = (PerfCountersBuilder("guard.0")
          .add_u64_counter("mono", "guard")
          .add_u64("gauge", "guard")
          .add_time("elapsed", "guard")
          .add_time_avg("avg", "guard")
          .add_histogram("hist", "guard")
          .create_perf_counters(register=False))
    entries = schema_entries([pc])
    assert len(entries) == 5
    assert all(e["type"] in ALLOWED_TYPES for e in entries)
    st = DaemonStateIndex().open("guard.0", 1)
    assert st.apply_schema(entries) == 5
