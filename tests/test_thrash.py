"""Live thrashing: randomized OSD kill/revive under client workload.

ref test model: qa/tasks/ceph_manager.py Thrasher + the
rados/thrash-erasure-code suites — while a client keeps writing,
OSDs are killed and revived in rounds; after the storm the cluster
must return to clean with every acknowledged write readable.

The deep tier (round 4, VERDICT r3 weak #5) runs the storm at the
qa-suite's shape: 8 OSDs, replicated AND EC pools thrashed together,
kill-during-recovery (a second victim dies before the first one's
recovery finishes), a mon killed mid-storm (3-mon quorum survives),
concurrent map churn (pg_num growth — live PG splitting — while
degraded), and a seed matrix.
"""

import asyncio
import random

import pytest

from ceph_tpu.cluster.vstart import Cluster


def run(coro):
    asyncio.run(coro)


def test_thrash_replicated_pool():
    async def go():
        rng = random.Random(42)
        c = await Cluster(
            n_mons=1, n_osds=4,
            config={"mon_osd_down_out_interval": 600.0}).start()
        try:
            await c.client.pool_create("t", pg_num=8, size=3,
                                       min_size=2)
            await c.wait_for_clean(timeout=120)
            io = await c.client.open_ioctx("t")
            acked: dict[str, bytes] = {}
            seq = 0

            async def write_some(n: int) -> None:
                nonlocal seq
                for _ in range(n):
                    oid = f"obj{seq % 30}"
                    data = bytes([seq % 256]) * rng.randint(1, 2048)
                    await io.write_full(oid, data)
                    acked[oid] = data          # acked => must survive
                    seq += 1

            await write_some(10)
            for round_no in range(2):
                victim = rng.randrange(4)
                await c.kill_osd(victim)
                await c.wait_for_osd_down(victim, timeout=25)
                # acked writes stay readable; new writes land degraded
                for oid, data in list(acked.items())[:5]:
                    assert await io.read(oid) == data
                await write_some(8)
                await c.revive_osd(victim)
                await c.wait_for_clean(timeout=120)
                await write_some(5)
            # final verification: every acknowledged write intact
            for oid, data in acked.items():
                assert await io.read(oid) == data, oid
            status = await c.client.status()
            assert status["osdmap"]["num_up_osds"] == 4
        finally:
            await c.stop()
    run(go())


@pytest.mark.parametrize("seed", [7, 21])
@pytest.mark.slow
def test_thrash_deep_mixed_pools(seed):
    """8 OSDs / 3 mons / replicated + EC pools / 4 rounds with
    kill-during-recovery, a mon kill, and pg_num growth mid-storm."""
    async def go():
        rng = random.Random(seed)
        # 8 OSDs + 3 mons + recovery storms oversubscribe this host's
        # single core; the default sub-second mon lease would expire
        # spuriously under load and loop elections, stalling the
        # up_thru grants peering needs. Production-shaped timing here.
        c = await Cluster(
            n_mons=3, n_osds=8,
            config={"mon_osd_down_out_interval": 600.0,
                    "mon_lease": 4.0, "mon_lease_interval": 0.5,
                    "mon_election_timeout": 1.0,
                    "mon_paxos_timeout": 8.0}).start()
        try:
            ret, rs, _ = await c.client.mon_command(
                {"prefix": "osd erasure-code-profile set",
                 "name": "tprof", "profile": ["k=2", "m=2"]})
            assert ret == 0, rs
            await c.client.pool_create("rep", pg_num=16, size=3,
                                       min_size=2)
            await c.client.pool_create(
                "ec", pg_num=8, pool_type="erasure",
                erasure_code_profile="tprof")
            await c.wait_for_clean(timeout=180)
            rep = await c.client.open_ioctx("rep")
            ecio = await c.client.open_ioctx("ec")
            acked: dict[tuple, bytes] = {}     # (pool, oid) -> data
            seq = 0

            async def write_some(n: int) -> None:
                nonlocal seq
                for _ in range(n):
                    io, pool = (rep, "rep") if seq % 2 else (ecio, "ec")
                    oid = f"obj{seq % 40}"
                    data = bytes([seq % 256]) * rng.randint(1, 4096)
                    await io.write_full(oid, data)
                    acked[(pool, oid)] = data
                    seq += 1

            async def spot_check(k: int) -> None:
                items = list(acked.items())
                rng.shuffle(items)
                for (pool, oid), data in items[:k]:
                    io = rep if pool == "rep" else ecio
                    assert await io.read(oid) == data, (pool, oid)

            await write_some(16)
            for round_no in range(4):
                v1 = rng.randrange(8)
                await c.kill_osd(v1)
                await c.wait_for_osd_down(v1, timeout=30)
                await write_some(8)            # degraded writes land
                await spot_check(5)
                await c.revive_osd(v1)
                # kill-during-recovery: the next victim dies while v1's
                # recovery is still running (no wait_for_clean between)
                v2 = (v1 + 1 + rng.randrange(7)) % 8
                await c.kill_osd(v2)
                await c.wait_for_osd_down(v2, timeout=30)
                await write_some(8)
                await spot_check(5)
                await c.revive_osd(v2)
                if round_no == 1:
                    # mon thrash: the LEADER dies; 2-of-3 quorum keeps
                    # serving the rest of the storm
                    leader = c.leader()
                    if leader is not None:
                        await leader.stop()
                        c.mons.remove(leader)
                if round_no == 2:
                    # concurrent map churn: grow the replicated pool's
                    # pg_num (live PG splitting) before recovery settles
                    ret, rs, _ = await c.client.mon_command(
                        {"prefix": "osd pool set", "pool": "rep",
                         "var": "pg_num", "val": "32"})
                    assert ret == 0, rs
                    ret, rs, _ = await c.client.mon_command(
                        {"prefix": "osd pool set", "pool": "rep",
                         "var": "pgp_num", "val": "32"})
                    assert ret == 0, rs
                await c.wait_for_clean(timeout=240)
                await write_some(6)
            # the storm is over: every acknowledged write must be intact
            for (pool, oid), data in acked.items():
                io = rep if pool == "rep" else ecio
                assert await io.read(oid) == data, (pool, oid)
            status = await c.client.status()
            assert status["osdmap"]["num_up_osds"] == 8
        finally:
            await c.stop()
    run(go())
