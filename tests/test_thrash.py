"""Live thrashing: randomized OSD kill/revive under client workload.

ref test model: qa/tasks/ceph_manager.py Thrasher + the
rados/thrash-erasure-code suites — while a client keeps writing,
OSDs are killed and revived in rounds; after the storm the cluster
must return to clean with every acknowledged write readable.
"""

import asyncio
import random

from ceph_tpu.cluster.vstart import Cluster


def run(coro):
    asyncio.run(coro)


def test_thrash_replicated_pool():
    async def go():
        rng = random.Random(42)
        c = await Cluster(
            n_mons=1, n_osds=4,
            config={"mon_osd_down_out_interval": 600.0}).start()
        try:
            await c.client.pool_create("t", pg_num=8, size=3,
                                       min_size=2)
            await c.wait_for_clean(timeout=120)
            io = await c.client.open_ioctx("t")
            acked: dict[str, bytes] = {}
            seq = 0

            async def write_some(n: int) -> None:
                nonlocal seq
                for _ in range(n):
                    oid = f"obj{seq % 30}"
                    data = bytes([seq % 256]) * rng.randint(1, 2048)
                    await io.write_full(oid, data)
                    acked[oid] = data          # acked => must survive
                    seq += 1

            await write_some(10)
            for round_no in range(2):
                victim = rng.randrange(4)
                await c.kill_osd(victim)
                await c.wait_for_osd_down(victim, timeout=25)
                # acked writes stay readable; new writes land degraded
                for oid, data in list(acked.items())[:5]:
                    assert await io.read(oid) == data
                await write_some(8)
                await c.revive_osd(victim)
                await c.wait_for_clean(timeout=120)
                await write_some(5)
            # final verification: every acknowledged write intact
            for oid, data in acked.items():
                assert await io.read(oid) == data, oid
            status = await c.client.status()
            assert status["osdmap"]["num_up_osds"] == 4
        finally:
            await c.stop()
    run(go())
