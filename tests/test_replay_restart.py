"""Lossless-replay dedup across a REAL process restart.

The in-process messenger tests exercise replay across a killed
*connection*; here the peer dies by SIGKILL mid-session and comes back
as a fresh OS process with the same entity name on the same port.  The
client's at-least-once machinery prunes ops once acked, so ops acked
before the crash must appear in the survivor's durable log exactly
once — never re-sent to the respawned process — while ops sent after
the restart flow over the renegotiated session and apply once too.

ref: src/test/msgr/test_msgr.cc (MessengerTest reconnect cases), but
with an actual process boundary instead of a simulated reset.
"""

import asyncio
import importlib.util
import os
import socket
import subprocess
import sys

from ceph_tpu.msg import Keyring, Messenger, Policy
from ceph_tpu.msg.messenger import EntityAddr

_HERE = os.path.dirname(os.path.abspath(__file__))
_spec = importlib.util.spec_from_file_location(
    "_replay_child", os.path.join(_HERE, "_replay_child.py"))
_child_mod = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_child_mod)
MRec = _child_mod.MRec


async def _wait(pred, timeout=30.0):
    t0 = asyncio.get_event_loop().time()
    while not pred():
        if asyncio.get_event_loop().time() - t0 > timeout:
            raise TimeoutError
        await asyncio.sleep(0.02)


def _free_port() -> int:
    s = socket.socket()
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn(port: int, log_path: str, key_srv: str, key_cli: str):
    proc = subprocess.Popen(
        [sys.executable, os.path.join(_HERE, "_replay_child.py"),
         str(port), "osd.9", "client.r", key_srv, key_cli, log_path],
        stdout=subprocess.PIPE, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    line = proc.stdout.readline()
    assert "READY" in line, f"child failed to start: {line!r}"
    return proc


def _ops(log_path: str) -> list[int]:
    if not os.path.exists(log_path):
        return []
    with open(log_path) as f:
        return [int(line.split(":", 1)[0])
                for line in f.read().splitlines() if line]


def test_acked_ops_apply_once_across_process_restart(tmp_path):
    async def go():
        kr = Keyring()
        key_cli = kr.add("client.r")
        key_srv = kr.add("osd.9")
        port = _free_port()
        log_path = str(tmp_path / "applied.log")
        procs = [_spawn(port, log_path, key_srv.hex(), key_cli.hex())]
        client = None
        try:
            client = Messenger("client.r", keyring=kr)
            client.set_policy("osd", Policy.lossless_peer())
            addr = EntityAddr("127.0.0.1", port)
            for i in range(1, 6):
                await client.send_message(
                    MRec(op=i, payload=bytes([i])), addr, "osd.9")
            conn = client.conns[addr]

            def drained():
                sess = conn.session
                pend = sess.unacked if sess is not None else conn.unacked
                return not pend

            # every op applied (fsync'd) AND acked back to us
            await _wait(lambda: len(_ops(log_path)) >= 5)
            await _wait(drained)
            # crash honesty: SIGKILL — no handler, no graceful goodbye
            procs[0].kill()
            procs[0].wait()
            # same name, same port, fresh memory, same durable log
            procs.append(
                _spawn(port, log_path, key_srv.hex(), key_cli.hex()))
            for i in range(6, 11):
                await client.send_message(
                    MRec(op=i, payload=bytes([i])), addr, "osd.9")
            await _wait(lambda: len(_ops(log_path)) >= 10)
            ops = _ops(log_path)
            assert sorted(ops) == list(range(1, 11)), (
                f"acked ops must apply exactly once across the "
                f"restart, got {sorted(ops)}")
        finally:
            if client is not None:
                await client.shutdown()
            for p in procs:
                if p.poll() is None:
                    p.kill()
                    p.wait()
    asyncio.run(go())
