"""MDS daemon tests: sessions, journaled metadata, capability
revoke/ack between clients (ref test model: src/test/libcephfs +
qa mds journal replay)."""

import asyncio

import pytest

from ceph_tpu.cephfs import FSError
from ceph_tpu.cephfs.client import CephFSClient
from ceph_tpu.cephfs.mds import (
    CAP_FR, CAP_FW, JOURNAL_OID, MDSDaemon,
)
from ceph_tpu.cluster.vstart import Cluster
from ceph_tpu.rados import ObjectOperationError


def run(coro):
    asyncio.run(coro)


async def _pool(c, name="fs"):
    await c.client.pool_create(name, pg_num=8, size=3)
    await c.wait_for_clean(timeout=90)
    io = await c.client.open_ioctx(name)
    for _ in range(30):
        try:
            await io.write_full("_warm", b"x")
            break
        except ObjectOperationError:
            await asyncio.sleep(1)
    return io


def test_mds_namespace_and_session():
    async def go():
        c = await Cluster(n_mons=1, n_osds=3).start()
        try:
            io = await _pool(c)
            mds = MDSDaemon(io)
            await mds.fs.mount()
            addr = await mds.start()
            cl_io = await c.client.open_ioctx("fs")
            cl = await CephFSClient(cl_io, addr).mount()
            # metadata ops go through the MDS
            await cl.mkdir("/a")
            await cl.mkdir("/a/b")
            await cl.write_file("/a/b/f.txt", b"via mds")
            assert await cl.ls("/a") == ["b"]
            assert await cl.read_file("/a/b/f.txt") == b"via mds"
            st = await cl.stat("/a/b/f.txt")
            assert st["type"] == "file" and st["size"] == 7
            await cl.rename("/a/b/f.txt", "/top.txt")
            assert await cl.read_file("/top.txt") == b"via mds"
            with pytest.raises(FSError):
                await cl.mkdir("/a")                  # EEXIST
            with pytest.raises(FSError):
                await cl.rmdir("/a")                  # ENOTEMPTY
            # no session: a raw second client that never mounted
            cl2 = CephFSClient(cl_io, addr)
            with pytest.raises(FSError):
                await cl2._request("mkdir", "/nope")
            await cl2.msgr.shutdown()
            await cl.unmount()
            await mds.stop()
        finally:
            await c.stop()
    run(go())


def test_mds_journal_replay():
    """A mutation journaled but not applied (crash between append and
    apply) lands after MDS restart — the EUpdate replay guarantee.
    Applied-but-resident events (lazy batch trim) must NOT re-apply:
    replaying an applied create+rename of an atomic-replace pattern
    against the latest namespace would overwrite the acked target
    with an empty file — the persisted applied watermark confines
    replay to the genuine crash window."""
    async def go():
        c = await Cluster(n_mons=1, n_osds=3).start()
        try:
            io = await _pool(c)
            mds = MDSDaemon(io)
            await mds.fs.mount()
            addr = await mds.start()
            cl = await CephFSClient(
                await c.client.open_ioctx("fs"), addr).mount()
            await cl.mkdir("/kept")
            # atomic-replace pattern; all four events stay resident
            # in the journal (journal_max=64 — nothing trims them)
            await cl.write_file("/target", b"old")
            await cl.write_file("/tmp.x", b"precious")
            await cl.rename("/tmp.x", "/target")
            assert await cl.read_file("/target") == b"precious"
            await cl.unmount()
            # simulate a crash mid-mutation: journal a mkdir the MDS
            # never applied, then restart
            import json
            await io.set_omap(JOURNAL_OID, f"{99:016d}",
                              json.dumps({"op": "mkdir",
                                          "path": "/lost"}).encode())
            await mds.stop()
            mds2 = MDSDaemon(io)
            addr2 = await mds2.start()                # replays journal
            cl2 = await CephFSClient(
                await c.client.open_ioctx("fs"), addr2).mount()
            names = await cl2.ls("/")
            assert "lost" in names and "kept" in names
            # the resident (already-applied) create+rename events did
            # NOT re-apply: the acked target survives the restart
            assert await cl2.read_file("/target") == b"precious"
            assert "tmp.x" not in names
            # the journal is trimmed after replay
            entries = await io.get_omap_vals(JOURNAL_OID)
            assert not entries
            # replaying an ALREADY-applied event is harmless: restart
            # again with a duplicate of the mkdir
            await cl2.unmount()
            await io.set_omap(JOURNAL_OID, f"{100:016d}",
                              json.dumps({"op": "mkdir",
                                          "path": "/lost"}).encode())
            await mds2.stop()
            mds3 = MDSDaemon(io)
            addr3 = await mds3.start()
            cl3 = await CephFSClient(
                await c.client.open_ioctx("fs"), addr3).mount()
            assert "lost" in await cl3.ls("/")
            await cl3.unmount()
            await mds3.stop()
        finally:
            await c.stop()
    run(go())


def test_mds_cap_revoke_between_clients():
    """Two clients, one file: the second writer's open blocks until the
    first holder's cap is revoked and acked; readers coexist."""
    async def go():
        c = await Cluster(n_mons=1, n_osds=3).start()
        try:
            io = await _pool(c)
            mds = MDSDaemon(io)
            await mds.fs.mount()
            addr = await mds.start()
            io1 = await c.client.open_ioctx("fs")
            io2 = await c.client.open_ioctx("fs")
            a = await CephFSClient(io1, addr).mount()
            b = await CephFSClient(io2, addr).mount()

            # writer a holds FW
            ha = await a.open_file("/shared.txt", "w")
            await ha.write(b"from a")
            assert mds.caps["/shared.txt"][a.msgr.name][0] == CAP_FW
            # b's reader open triggers revoke of a's FW; a acks
            # (write-through, nothing dirty) and the grant proceeds
            hb = await b.open_file("/shared.txt", "r")
            assert await hb.read() == b"from a"
            assert not ha.valid                    # a's handle revoked
            assert mds.caps["/shared.txt"][b.msgr.name][0] == CAP_FR
            assert a.msgr.name not in mds.caps["/shared.txt"]

            # two readers coexist (no revoke of a shared cap)
            ha2 = await a.open_file("/shared.txt", "r")
            assert hb.valid and ha2.valid
            assert set(mds.caps["/shared.txt"]) == {a.msgr.name,
                                                    b.msgr.name}

            # a writer revokes BOTH readers
            hw = await b.open_file("/shared.txt", "w")
            await hw.write(b"from b")
            assert not ha2.valid
            assert set(mds.caps["/shared.txt"]) == {b.msgr.name}
            assert mds.caps["/shared.txt"][b.msgr.name][0] == CAP_FW

            # a's revoked handle transparently reacquires on next read
            assert await ha2.read() == b"from b"

            # same-client second open must not erode exclusivity:
            # opening and closing a READER on a path where the client
            # holds FW leaves the FW intact (mode absorbs, refcount
            # drains one)
            haw = await a.open_file("/dual.txt", "w")
            await haw.write(b"x")
            har = await a.open_file("/dual.txt", "r")
            await har.close()
            for _ in range(50):
                if mds.caps.get("/dual.txt", {}).get(
                        a.msgr.name, [0, 0])[1] == 1:
                    break
                await asyncio.sleep(0.1)
            assert mds.caps["/dual.txt"][a.msgr.name][0] == CAP_FW
            hbw = await b.open_file("/dual.txt", "w")   # revokes a
            assert not haw.valid
            assert set(mds.caps["/dual.txt"]) == {b.msgr.name}
            await hbw.close()
            await haw.close()

            # release on close frees the cap table entry (releases are
            # one-way messages — poll briefly for the table to drain)
            await hw.close()
            await ha2.close()
            await hb.close()
            for _ in range(50):
                if "/shared.txt" not in mds.caps:
                    break
                await asyncio.sleep(0.1)
            assert "/shared.txt" not in mds.caps
            await a.unmount()
            await b.unmount()
            await mds.stop()
        finally:
            await c.stop()
    run(go())


def test_mds_cross_open_no_deadlock():
    """Two clients each hold FW on one file and concurrently open the
    OTHER's file: each open revokes a cap whose ack arrives on the
    holder's connection. If the MDS dispatched requests inline in
    ms_dispatch (pre round-5), each ack sat head-of-line blocked behind
    that client's own pending open and both opens stalled to the 30 s
    revoke timeout — requests must run in their own tasks."""
    async def go():
        c = await Cluster(n_mons=1, n_osds=3).start()
        try:
            io = await _pool(c)
            mds = MDSDaemon(io)
            await mds.fs.mount()
            addr = await mds.start()
            a = await CephFSClient(
                await c.client.open_ioctx("fs"), addr).mount()
            b = await CephFSClient(
                await c.client.open_ioctx("fs"), addr).mount()
            ha = await a.open_file("/f1", "w")
            await ha.write(b"1")
            hb = await b.open_file("/f2", "w")
            await hb.write(b"2")
            assert mds.caps["/f1"][a.msgr.name][0] == CAP_FW
            assert mds.caps["/f2"][b.msgr.name][0] == CAP_FW
            # cross opens, concurrently; well under the 30 s revoke
            # timeout both must succeed
            h2, h1 = await asyncio.wait_for(asyncio.gather(
                a.open_file("/f2", "w"), b.open_file("/f1", "w")),
                timeout=20)
            assert h2.valid and h1.valid
            assert mds.caps["/f2"][a.msgr.name][0] == CAP_FW
            assert mds.caps["/f1"][b.msgr.name][0] == CAP_FW
            for h in (h1, h2):
                await h.close()
            await a.unmount()
            await b.unmount()
            await mds.stop()
        finally:
            await c.stop()
    run(go())


def test_mds_create_on_open_race_preserves_write():
    """Two racing open-w's on a new path: the loser's create (a
    write_full truncate) must not land after the winner was granted FW
    and wrote data. The create gate below stalls each create-write at
    exactly the advisor's window — after the journal apply's stat-guard,
    before the truncating write — so the pre-fix interleaving (B's
    create truncating A's acknowledged write) is forced
    deterministically; the fix puts stat+create inside the per-path
    open lock, so B never reaches a second create at all."""
    async def go():
        c = await Cluster(n_mons=1, n_osds=3).start()
        try:
            io = await _pool(c)
            mds = MDSDaemon(io)
            await mds.fs.mount()
            addr = await mds.start()
            a = await CephFSClient(
                await c.client.open_ioctx("fs"), addr).mount()
            b = await CephFSClient(
                await c.client.open_ioctx("fs"), addr).mount()
            orig = mds.fs.write_file
            gates = [asyncio.Event(), asyncio.Event()]
            seen = 0

            async def gated(path, data):
                nonlocal seen
                if path == "/race.txt" and data == b"":
                    i = min(seen, 1)
                    seen += 1
                    await gates[i].wait()
                return await orig(path, data)

            mds.fs.write_file = gated
            ta = asyncio.create_task(a.open_file("/race.txt", "w"))
            await asyncio.sleep(0.3)       # a reaches its gated create
            tb = asyncio.create_task(b.open_file("/race.txt", "w"))
            await asyncio.sleep(0.3)       # pre-fix: b statted ENOENT
            gates[0].set()                 # a's create lands; a granted
            ha = await asyncio.wait_for(ta, 20)
            await ha.write(b"precious")    # acknowledged client write
            gates[1].set()                 # pre-fix: b's create NOW
            hb = await asyncio.wait_for(tb, 20)   # truncates it
            assert hb.valid
            data = await b.read_file("/race.txt")
            assert data == b"precious", data
            await hb.close()
            await ha.close()
            await a.unmount()
            await b.unmount()
            await mds.stop()
        finally:
            await c.stop()
    run(go())


def test_mds_cap_lease_eviction_blocklists():
    """A hung client (no renewals, never acks revokes) must not hold
    exclusivity hostage: when its lease lapses during a revoke wait
    the MDS evicts it AND blocklists it at the OSDs before the
    competing open proceeds — so even if the zombie resumes with its
    stale FW handle, its data writes bounce with EBLOCKLISTED instead
    of corrupting the new holder's file (ref: Session lease renewal +
    Locker stale-session eviction + the paired osdmap blocklist)."""
    async def go():
        c = await Cluster(n_mons=1, n_osds=3).start()
        try:
            io = await _pool(c)
            mds = MDSDaemon(io, lease_timeout=1.0, revoke_timeout=12.0)
            await mds.fs.mount()
            addr = await mds.start()
            monmap = c.client.monc.monmap
            a = await CephFSClient.create(monmap, addr, "fs",
                                          keyring=c.keyring)
            b = await CephFSClient.create(monmap, addr, "fs",
                                          keyring=c.keyring)
            ha = await a.open_file("/hostage.txt", "w")
            await ha.write(b"held")
            # hang client a: no more renewals, revokes go unanswered
            a._renew_task.cancel()

            async def never_acks(msg):
                pass

            a._handle_revoke = never_acks
            t0 = asyncio.get_event_loop().time()
            hb = await asyncio.wait_for(b.open_file("/hostage.txt", "w"),
                                        timeout=10)
            took = asyncio.get_event_loop().time() - t0
            assert hb.valid
            # a was evicted: session and caps gone, and the open did
            # not ride to the revoke timeout
            assert a.msgr.name not in mds.sessions
            assert a.msgr.name not in mds.caps.get("/hostage.txt", {})
            assert took < 8, took
            # the zombie resumes and writes DIRECTLY to the data
            # object under its stale handle. The fence rides the map
            # push to the OSDs, so probe until the refusal lands; from
            # then on the zombie can never mutate data again.
            from ceph_tpu.cephfs import _fileobj
            fenced = False
            for _ in range(50):
                try:
                    await a.ioctx.write_full(
                        _fileobj("/hostage.txt"), b"zombie")
                except ObjectOperationError as e:
                    assert e.errno == -108, e
                    fenced = True
                    break
                await asyncio.sleep(0.2)
            assert fenced, "zombie writes were never refused"
            await hb.write(b"taken")
            with pytest.raises(ObjectOperationError):
                await a.ioctx.write_full(_fileobj("/hostage.txt"),
                                         b"zombie")
            # ...and the new holder's data survived
            assert await b.read_file("/hostage.txt") == b"taken"
            # blocklist is visible and removable via the mon command
            ret, _, out = await c.client.mon_command(
                {"prefix": "osd blocklist", "blocklistop": "ls"})
            assert ret == 0 and a.msgr.name in out.decode()
            ret, _, _ = await c.client.mon_command(
                {"prefix": "osd blocklist", "blocklistop": "rm",
                 "addr": a.msgr.name})
            assert ret == 0
            await hb.close()
            await b.unmount()
            await a.msgr.shutdown()
            if a._own_rados is not None:
                await a._own_rados.shutdown()
            await mds.stop()
        finally:
            await c.stop()
    run(go())
