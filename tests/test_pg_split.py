"""PG splitting: pg_num growth on POPULATED pools.

ref test model: qa/standalone + PG::split_into semantics — raising
pg_num re-folds object names onto child PGs; while pgp_num is unchanged
a child places exactly like its parent (ceph_stable_mod), so every OSD
splits its local collections deterministically; raising pgp_num then
migrates whole child PGs through normal peering. Round-2/3 verdicts
flagged this as the one OSDMap/PG mechanism with no analog (VERDICT r3
Missing #3) — the autoscaler was a no-op on any populated pool.
"""

import asyncio

import pytest

from ceph_tpu.cluster.vstart import Cluster


def run(coro):
    asyncio.run(coro)


PAYLOAD = {f"obj-{i:03d}": bytes([i % 251]) * (64 + i) for i in range(48)}


async def _write_all(io):
    for oid, data in PAYLOAD.items():
        await io.write_full(oid, data)


async def _assert_all_readable(io):
    for oid, data in PAYLOAD.items():
        assert await io.read(oid) == data, oid


def test_split_populated_pool_and_pgp_migration():
    async def go():
        c = await Cluster(n_mons=1, n_osds=3).start()
        try:
            await c.client.pool_create("data", pg_num=4, size=3,
                                       min_size=2)
            await c.wait_for_clean(timeout=240)
            io = await c.client.open_ioctx("data")
            await _write_all(io)
            # phase 1: split in place (pgp_num stays at 4)
            ret, rs, _ = await c.client.mon_command(
                {"prefix": "osd pool set", "pool": "data",
                 "var": "pg_num", "val": "8"})
            assert ret == 0, rs
            await c.wait_for_clean(timeout=240)
            await _assert_all_readable(io)
            status = await c.client.status()
            assert status["pgmap"]["num_pgs"] >= 8
            # objects actually moved: child collections are populated
            child_objs = 0
            prefix = f"{io.pool_id}."
            for o in c.osds:
                for cid in o.store.list_collections():
                    if cid.startswith(prefix) and \
                            int(cid.split(".")[1]) >= 4:
                        child_objs += sum(
                            1 for x in o.store.list_objects(cid)
                            if x.startswith("obj-"))
            assert child_objs > 0, "no objects moved to child PGs"
            # phase 2: migrate children (pgp_num -> 8)
            ret, rs, _ = await c.client.mon_command(
                {"prefix": "osd pool set", "pool": "data",
                 "var": "pgp_num", "val": "8"})
            assert ret == 0, rs
            await c.wait_for_clean(timeout=240)
            await _assert_all_readable(io)
            # writes keep working post-split
            await io.write_full("post-split", b"fresh")
            assert await io.read("post-split") == b"fresh"
        finally:
            await c.stop()
    run(go())


def test_pg_num_decrease_gated_by_knob():
    """Round 6: pg_num decreases are MERGES now (tests/test_pg_merge
    .py) — but `mon_allow_pg_merge=false` reproduces the old
    grow-only behavior, and pgp_num still can't exceed pg_num."""
    async def go():
        c = await Cluster(n_mons=1, n_osds=3,
                          config={"mon_allow_pg_merge": False}).start()
        try:
            await c.client.pool_create("data", pg_num=8, size=2)
            ret, rs, _ = await c.client.mon_command(
                {"prefix": "osd pool set", "pool": "data",
                 "var": "pg_num", "val": "4"})
            assert ret == -22 and "merge" in rs
            ret, rs, _ = await c.client.mon_command(
                {"prefix": "osd pool set", "pool": "data",
                 "var": "pgp_num", "val": "16"})
            assert ret == -22
        finally:
            await c.stop()
    run(go())


def test_autoscaler_grows_populated_pool():
    """The autoscaler must now grow a pool that HOLDS DATA (round-2/3
    verdicts: it skipped populated pools), then ramp pgp_num."""
    async def go():
        from ceph_tpu.mgr.modules import PGAutoscalerModule
        c = await Cluster(
            n_mons=1, n_osds=3,
            config={"mon_target_pg_per_osd": 8},
            mgr_modules=[PGAutoscalerModule]).start()
        try:
            await c.client.pool_create("data", pg_num=4, size=3,
                                       min_size=2)
            await c.wait_for_clean(timeout=240)
            io = await c.client.open_ioctx("data")
            await _write_all(io)

            async def pool_nums():
                _, _, out = await c.client.mon_command(
                    {"prefix": "osd dump"})
                import json
                pools = json.loads(out)["pools"]
                p = next(x for x in pools if x["name"] == "data")
                return p["pg_num"], p.get("pgp_num", p["pg_num"])

            deadline = asyncio.get_event_loop().time() + 90
            while True:
                pg_num, pgp_num = await pool_nums()
                if pg_num == 8 and pgp_num == 8:
                    break
                assert asyncio.get_event_loop().time() < deadline, \
                    f"autoscaler stalled at pg_num={pg_num} " \
                    f"pgp_num={pgp_num}"
                await asyncio.sleep(1.0)
            await c.wait_for_clean(timeout=240)
            await _assert_all_readable(io)
        finally:
            await c.stop()
    run(go())
