"""Osdmap epoch barrier: eviction under a stale-map OSD.

The advisor-flagged race (ADVICE r5 medium): MDS eviction blocklists a
zombie client at the MON, but OSDs enforce ``is_blocklisted()``
against their OWN osdmap — an OSD that has not yet received the
blocklist epoch will happily accept the zombie's writes after the new
holder was granted FW. The fix is the epoch barrier
(``Objecter.wait_for_map_on_osds``): eviction drops caps only after
the OSDs have observably caught up.

These tests force the race window deterministically with the fault
layer: a one-way ``mon.* -> osd.*`` blackhole freezes the OSD's map
at a pre-blocklist epoch. The regression test shows the corruption
with the barrier disabled (the pre-fix behavior); the fix test shows
the barrier holding eviction until the map lands, after which the
zombie's very FIRST resumed write bounces — no probe window.
"""

import asyncio
import json

import pytest

from ceph_tpu.cephfs import _fileobj
from ceph_tpu.cephfs.client import CephFSClient
from ceph_tpu.cephfs.mds import MDSDaemon
from ceph_tpu.cluster.vstart import Cluster
from ceph_tpu.rados import ObjectOperationError
from ceph_tpu.sim import faults as F


def run(coro):
    asyncio.run(coro)


async def _setup(c):
    """size-1 pool on the single OSD: every object's primary is
    osd.0, so 'the OSD with the stale map' is deterministic."""
    await c.client.pool_create("fs", pg_num=4, size=1, min_size=1)
    await c.wait_for_clean(timeout=120)
    io = await c.client.open_ioctx("fs")
    for _ in range(30):
        try:
            await io.write_full("_warm", b"x")
            break
        except ObjectOperationError:
            await asyncio.sleep(1)
    return io


def _hang(client):
    """Make a client a zombie: no renewals, revokes unanswered."""
    client._renew_task.cancel()

    async def never_acks(msg):
        pass
    client._handle_revoke = never_acks


async def _teardown(c, mds, clients):
    for cl in clients:
        try:
            await cl.msgr.shutdown()
            if cl._own_rados is not None:
                await cl._own_rados.shutdown()
        except Exception:
            pass
    await mds.stop()
    await c.stop()


def test_eviction_waits_for_osd_to_observe_blocklist_epoch():
    """WITH the barrier: while osd.0's map is frozen pre-blocklist,
    the competing open must NOT be granted; once the map flows again
    the open completes and the zombie's first write is already
    fenced (-EBLOCKLISTED, no probe loop)."""
    async def go():
        c = await Cluster(n_mons=1, n_osds=1).start()
        inj = F.FaultInjector(seed=4)
        c.install_faults(inj)
        io = await _setup(c)
        mds = MDSDaemon(io, lease_timeout=1.0, revoke_timeout=25.0)
        await mds.fs.mount()
        addr = await mds.start()
        monmap = c.client.monc.monmap
        a = await CephFSClient.create(monmap, addr, "fs",
                                      keyring=c.keyring)
        b = await CephFSClient.create(monmap, addr, "fs",
                                      keyring=c.keyring)
        try:
            ha = await a.open_file("/fence.txt", "w")
            await ha.write(b"held")
            _hang(a)
            # freeze osd.0's osdmap: map publishes are mon -> osd
            inj.install("stale-map", [F.drop("mon.*", "osd.*")])
            topen = asyncio.ensure_future(
                b.open_file("/fence.txt", "w"))
            # the lease lapses at ~1s and the MDS blocklists a — but
            # the barrier must hold the grant while osd.0 is stale
            await asyncio.sleep(3.0)
            assert not topen.done(), \
                "open granted while osd.0 had a pre-blocklist map"
            # map flows again: barrier passes, eviction completes
            inj.clear("stale-map")
            hb = await asyncio.wait_for(topen, timeout=30)
            assert hb.valid
            await hb.write(b"taken")
            # the zombie resumes: its FIRST write must already bounce
            # (the barrier proved osd.0 enforces the fence before any
            # cap moved) — the pre-barrier code needed a probe loop
            with pytest.raises(ObjectOperationError) as ei:
                await a.ioctx.write_full(_fileobj("/fence.txt"),
                                         b"zombie")
            assert ei.value.errno == -108
            assert await b.read_file("/fence.txt") == b"taken"
            await hb.close()
            await b.unmount()
        finally:
            inj.clear_all()
            await _teardown(c, mds, [a, b])
    run(go())


def test_eviction_without_barrier_lets_zombie_corrupt():
    """WITHOUT the barrier (pre-fix behavior, barrier stubbed out):
    the same scenario lets the zombie's write land on the stale OSD
    AFTER the new holder wrote — the corruption the barrier exists to
    prevent. This is the regression proof: if the barrier stops being
    wired into eviction, the previous test fails; this one documents
    exactly what goes wrong."""
    async def go():
        c = await Cluster(n_mons=1, n_osds=1).start()
        inj = F.FaultInjector(seed=4)
        c.install_faults(inj)
        io = await _setup(c)
        mds = MDSDaemon(io, lease_timeout=1.0, revoke_timeout=25.0)

        async def no_barrier(holder, outbl):
            return True                      # pre-fix: mon commit only
        mds._blocklist_barrier = no_barrier
        await mds.fs.mount()
        addr = await mds.start()
        monmap = c.client.monc.monmap
        a = await CephFSClient.create(monmap, addr, "fs",
                                      keyring=c.keyring)
        b = await CephFSClient.create(monmap, addr, "fs",
                                      keyring=c.keyring)
        try:
            ha = await a.open_file("/fence.txt", "w")
            await ha.write(b"held")
            _hang(a)
            inj.install("stale-map", [F.drop("mon.*", "osd.*")])
            # without the barrier the open is granted while osd.0 is
            # still on the pre-blocklist map
            hb = await asyncio.wait_for(
                b.open_file("/fence.txt", "w"), timeout=20)
            assert hb.valid
            await hb.write(b"taken!")
            # the zombie's write is ACCEPTED by the stale osd.0 and
            # clobbers the new holder's acknowledged data
            # equal-length payloads: read_file is MDS-size-bounded,
            # and the zombie never told the MDS about its write
            await a.ioctx.write_full(_fileobj("/fence.txt"), b"zombie")
            got = await b.read_file("/fence.txt")
            assert got == b"zombie", \
                "stale-map corruption no longer reproduces; the " \
                "no-barrier stub may be dead code now"
            await hb.close()
            await b.unmount()
        finally:
            inj.clear_all()
            await _teardown(c, mds, [a, b])
    run(go())


def test_blocklist_add_reports_commit_epoch():
    """`osd blocklist add` returns the epoch the fence commits at —
    the value the barrier needs."""
    async def go():
        c = await Cluster(n_mons=1, n_osds=1).start()
        try:
            before = c.client.monc.osdmap.epoch
            ret, _, out = await c.client.mon_command(
                {"prefix": "osd blocklist", "blocklistop": "add",
                 "addr": "client.zombie", "expire": 60.0})
            assert ret == 0
            epoch = json.loads(out)["epoch"]
            assert epoch > before
            # and the barrier proves the (sole) OSD observed it
            await c.client.objecter.wait_for_map_on_osds(
                epoch, timeout=15.0)
        finally:
            await c.stop()
    run(go())
