"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip hardware is unavailable in CI; sharding tests run on XLA's
host-platform device virtualization (the same mechanism the driver's
dryrun_multichip uses).

This environment registers a remote-TPU ('axon') PJRT backend from
sitecustomize and forces ``jax_platforms=axon,cpu`` via jax.config — env vars
alone cannot override it, and initializing the axon backend dials a remote
claim that can block for minutes. Tests are CPU-only, so we reset the config
knob before any backend initializes.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(1234)
