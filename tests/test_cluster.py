"""qa-standalone tier: a live cluster on localhost sockets.

ref test model: qa/standalone/ (ceph-helpers.sh run_mon/run_osd/
wait_for_clean + test-erasure-eio style kill/recover scenarios) —
boot to clean, run client I/O, kill an OSD, watch failure detection
remap and recovery restore full health.
"""

import asyncio

import pytest

from ceph_tpu.cluster.vstart import Cluster
from ceph_tpu.rados import ObjectOperationError


def run(coro):
    asyncio.run(coro)


def test_cluster_lifecycle_and_io(tmp_path):
    async def go():
        c = await Cluster(n_mons=1, n_osds=3).start()
        try:
            await c.client.pool_create("rbd", pg_num=8, size=3)
            await c.wait_for_clean(timeout=90)
            io = await c.client.open_ioctx("rbd")
            # basic object lifecycle
            await io.write_full("obj1", b"hello world")
            assert await io.read("obj1") == b"hello world"
            await io.write("obj1", b"ceph!", offset=6)
            assert await io.read("obj1") == b"hello ceph!"
            assert await io.stat("obj1") == 11
            await io.truncate("obj1", 5)
            assert await io.read("obj1") == b"hello"
            await io.setxattr("obj1", "user.tag", b"gold")
            assert await io.getxattr("obj1", "user.tag") == b"gold"
            await io.set_omap("obj1", "k1", b"v1")
            assert await io.get_omap_vals("obj1") == {"k1": b"v1"}
            for i in range(10):
                await io.write_full(f"many{i}", bytes([i]) * 100)
            names = await io.list_objects()
            assert set(names) >= {f"many{i}" for i in range(10)} | {"obj1"}
            await io.remove("many0")
            with pytest.raises(ObjectOperationError):
                await io.read("many0")
            # replicas actually hold the data (all 3 stores)
            stored = 0
            for o in c.osds:
                for cid in o.store.list_collections():
                    if "obj1" in o.store.list_objects(cid):
                        stored += 1
                        assert o.store.read(cid, "obj1") == b"hello"
            assert stored == 3
            # status/health surface
            status = await c.client.status()
            assert status["osdmap"]["num_up_osds"] == 3
            await asyncio.sleep(1.0)        # let pg stats flow
            status = await c.client.status()
            assert status["pgmap"]["num_pgs"] == 8
            assert status["health"]["status"] == "HEALTH_OK"
        finally:
            await c.stop()
    run(go())


def test_osd_failure_remap_and_recovery():
    async def go():
        cfg = {"mon_osd_down_out_interval": 2.0}
        c = await Cluster(n_mons=1, n_osds=3, config=cfg).start()
        try:
            await c.client.pool_create("data", pg_num=8, size=3,
                                       min_size=1)
            await c.wait_for_clean(timeout=90)
            io = await c.client.open_ioctx("data")
            payload = {f"o{i}": bytes([i]) * 512 for i in range(8)}
            for oid, data in payload.items():
                await io.write_full(oid, data)
            # hard-kill osd.2: heartbeats must report it, the mon marks
            # it down, PGs re-peer undersized but stay writeable
            await c.kill_osd(2)
            await c.wait_for_osd_down(2, timeout=20)
            await io.write_full("during-outage", b"still-writable")
            assert await io.read("during-outage") == b"still-writable"
            for oid, data in payload.items():
                assert await io.read(oid) == data
            status = await c.client.status()
            assert status["osdmap"]["num_up_osds"] == 2
            # revive with its old (stale) store: peering computes the
            # missing set from pg logs and recovery pushes the delta
            await c.revive_osd(2)
            await c.wait_for_clean(timeout=90)
            st2 = c.osds[2].store
            found = {}
            for cid in st2.list_collections():
                for oid in st2.list_objects(cid):
                    if oid != "_pgmeta_":
                        found[oid] = st2.read(cid, oid)
            assert found.get("during-outage") == b"still-writable"
            for oid, data in payload.items():
                if oid in found:                  # only its PGs' share
                    assert found[oid] == data
            status = await c.client.status()
            assert status["osdmap"]["num_up_osds"] == 3
            # health clears once primaries re-report pg stats
            deadline = asyncio.get_event_loop().time() + 15
            while True:
                status = await c.client.status()
                if status["health"]["status"] == "HEALTH_OK":
                    break
                assert asyncio.get_event_loop().time() < deadline, \
                    status["health"]
                await asyncio.sleep(0.3)
        finally:
            await c.stop()
    run(go())


def test_multi_mon_cluster_survives_mon_failure():
    async def go():
        c = await Cluster(n_mons=3, n_osds=2).start()
        try:
            await c.client.pool_create("p", pg_num=4, size=2)
            await c.wait_for_clean(timeout=90)
            io = await c.client.open_ioctx("p")
            await io.write_full("x", b"1")
            # kill the lead mon: quorum shrinks, i/o keeps working
            leader = c.leader()
            await leader.stop()
            await asyncio.sleep(1.0)
            await io.write_full("y", b"2")
            assert await io.read("x") == b"1"
            assert await io.read("y") == b"2"
            ret, _, _ = await c.client.mon_command({"prefix": "status"},
                                                   timeout=30)
            assert ret == 0
        finally:
            await c.stop()
    run(go())


def test_durable_osd_store_survives_restart(tmp_path):
    async def go():
        c = await Cluster(n_mons=1, n_osds=2,
                          data_dir=str(tmp_path)).start()
        try:
            await c.client.pool_create("wal", pg_num=4, size=2)
            await c.wait_for_clean(timeout=90)
            io = await c.client.open_ioctx("wal")
            await io.write_full("persisted", b"on-disk")
            # restart osd.1 from its on-disk WALStore
            await c.kill_osd(1)
            from ceph_tpu.os_.objectstore import WALStore
            c.osds[1].store.umount()
            fresh_store = WALStore(f"{tmp_path}/osd1")
            from ceph_tpu.osd.daemon import OSD
            c.osds[1] = OSD(1, c.monmap, store=fresh_store,
                            keyring=c.keyring, config=c.cfg)
            await c.osds[1].boot()
            await c.wait_for_clean(timeout=90)
            assert await io.read("persisted") == b"on-disk"
            # the reloaded store serves its pg data
            names = []
            for cid in fresh_store.list_collections():
                names += [o for o in fresh_store.list_objects(cid)
                          if o != "_pgmeta_"]
            assert "persisted" in names
        finally:
            await c.stop()
    run(go())
