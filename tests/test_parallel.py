"""Sharded pipeline tests on the 8-device virtual CPU mesh."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from ceph_tpu.ec import matrix as rs
from ceph_tpu.gf import tables, gf_matmul_np
from ceph_tpu.parallel import local_mesh, make_mesh, sharded_encode, sharded_decode


@pytest.fixture(scope="module")
def mesh():
    return local_mesh()


def test_mesh_has_8_devices(mesh):
    assert mesh.devices.size == 8


def test_make_mesh_shape_mismatch():
    with pytest.raises(ValueError):
        make_mesh(jax.devices(), axes=("a", "b"), shape=(3, 2))


def test_sharded_encode_matches_oracle(mesh, rng):
    k, m = 4, 2
    coding = rs.coding_matrix("reed_sol_van", k, m)
    bitmatrix = jnp.asarray(tables.expand_bitmatrix(coding), jnp.int8)
    lo, hi = map(jnp.asarray, tables.nibble_tables(coding))
    data = rng.integers(0, 256, size=(16, k, 128), dtype=np.uint8)
    out = np.asarray(sharded_encode(mesh, bitmatrix, lo, hi,
                                    jnp.asarray(data)))
    for b in range(16):
        assert np.array_equal(out[b], gf_matmul_np(coding, data[b]))


def test_sharded_roundtrip(mesh, rng):
    k, m = 8, 3
    coding = rs.coding_matrix("reed_sol_van", k, m)
    bitmatrix = jnp.asarray(tables.expand_bitmatrix(coding), jnp.int8)
    lo, hi = map(jnp.asarray, tables.nibble_tables(coding))
    data = jnp.asarray(rng.integers(0, 256, size=(8, k, 128), dtype=np.uint8))
    parity = sharded_encode(mesh, bitmatrix, lo, hi, data)
    full = jnp.concatenate([data, parity], axis=1)
    erased = (1, 8, 10)
    avail = tuple(i for i in range(k + m) if i not in erased)[:k]
    dmat = rs.decode_matrix("reed_sol_van", k, m, avail, erased)
    dbit = jnp.asarray(tables.expand_bitmatrix(dmat), jnp.int8)
    dlo, dhi = map(jnp.asarray, tables.nibble_tables(dmat))
    rec = sharded_decode(mesh, dbit, dlo, dhi, full[:, jnp.asarray(avail), :])
    assert np.array_equal(np.asarray(rec),
                          np.asarray(full[:, jnp.asarray(erased), :]))


def test_graft_entry_single():
    import __graft_entry__ as g
    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    assert out.shape[1] == 3


def test_graft_entry_multichip():
    import __graft_entry__ as g
    g.dryrun_multichip(8)


class TestShardedCrush:
    def test_sharded_sweep_matches_single_device(self):
        """The multichip CRUSH sweep (shard_map + psum) must agree
        exactly with Mapper.sweep (VERDICT #7)."""
        import numpy as np

        from ceph_tpu.bench.crush_sweep import canonical_map
        from ceph_tpu.crush.mapper import Mapper
        from ceph_tpu.parallel import local_mesh, sharded_crush_sweep

        mp = Mapper(canonical_map(256), block=1 << 11)
        mesh = local_mesh(8)
        c, b = sharded_crush_sweep(mesh, mp, 0, 0, 8192, 3)
        c1, b1 = mp.sweep(0, 0, 8192, 3)
        assert (np.asarray(c) == np.asarray(c1)).all()
        assert int(b) == int(b1)
        assert int(np.asarray(c).sum()) == 3 * 8192

    def test_uneven_n_rejected(self):
        import pytest

        from ceph_tpu.bench.crush_sweep import canonical_map
        from ceph_tpu.crush.mapper import Mapper
        from ceph_tpu.parallel import local_mesh, sharded_crush_sweep

        mp = Mapper(canonical_map(64), block=1 << 10)
        with pytest.raises(ValueError):
            sharded_crush_sweep(local_mesh(8), mp, 0, 0, 1001, 3)
