"""Multi-process CLI tier: vstart --serve in a subprocess, ceph/rados
CLIs against it from this process.

ref test model: qa/workunits (rados CLI loops against a live vstart
cluster). This is the only tier where client and daemons are in
DIFFERENT processes, exercising the full wire path with no shared
event loop.
"""

import json
import os
import pathlib
import signal
import subprocess
import sys
import time

import pytest

from ceph_tpu.bench import ceph_cli, rados_cli


REPO = pathlib.Path(__file__).resolve().parent.parent


@pytest.fixture(scope="module")
def cluster_conf(tmp_path_factory):
    conf = str(tmp_path_factory.mktemp("cli") / "ceph_tpu.conf")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=str(REPO))
    logf = open(conf + ".log", "wb")      # a pipe would deadlock once
    proc = subprocess.Popen(               # the buffer fills
        [sys.executable, "-m", "ceph_tpu.cluster.vstart", "--serve",
         "--mon-num", "1", "--osd-num", "3", "--pool", "rbd",
         "--pg-num", "8", "--conf", conf],
        cwd=str(REPO), env=env, stdout=logf,
        stderr=subprocess.STDOUT)
    deadline = time.time() + 180
    while not os.path.exists(conf):
        if proc.poll() is not None:
            out = pathlib.Path(conf + ".log").read_bytes().decode(
                errors="replace")
            raise RuntimeError(f"vstart died:\n{out[-2000:]}")
        if time.time() > deadline:
            proc.kill()
            raise TimeoutError("vstart never published its conf")
        time.sleep(0.5)
    yield conf
    proc.send_signal(signal.SIGTERM)
    try:
        proc.wait(timeout=10)
    except subprocess.TimeoutExpired:
        proc.kill()
    logf.close()


def test_ceph_status_and_pool_admin(cluster_conf, capsys):
    assert ceph_cli.main(["-c", cluster_conf, "status"]) == 0
    status = json.loads(capsys.readouterr().out)
    assert status["osdmap"]["num_up_osds"] == 3
    assert ceph_cli.main(["-c", cluster_conf, "osd", "tree"]) == 0
    out = capsys.readouterr().out
    assert "host0" in out
    assert ceph_cli.main(["-c", cluster_conf, "osd", "pool", "ls"]) == 0
    pools = json.loads(capsys.readouterr().out)
    assert any(p["name"] == "rbd" for p in pools)
    assert ceph_cli.main(["-c", cluster_conf, "osd", "map", "rbd",
                          "someobj"]) == 0
    mapping = json.loads(capsys.readouterr().out)
    assert len(mapping["acting"]) == 3
    assert ceph_cli.main(["-c", cluster_conf, "config", "set",
                          "global", "debug_osd", "5"]) == 0
    capsys.readouterr()
    assert ceph_cli.main(["-c", cluster_conf, "config", "get",
                          "global", "debug_osd"]) == 0
    assert capsys.readouterr().out.strip() == "5"


def test_rados_put_get_ls_bench(cluster_conf, tmp_path, capsys):
    src = tmp_path / "in.bin"
    src.write_bytes(os.urandom(4096))
    dst = tmp_path / "out.bin"
    assert rados_cli.main(["-c", cluster_conf, "-p", "rbd", "put",
                           "cliobj", str(src)]) == 0
    assert rados_cli.main(["-c", cluster_conf, "-p", "rbd", "get",
                           "cliobj", str(dst)]) == 0
    assert dst.read_bytes() == src.read_bytes()
    capsys.readouterr()
    assert rados_cli.main(["-c", cluster_conf, "-p", "rbd",
                           "stat", "cliobj"]) == 0
    assert "size 4096" in capsys.readouterr().out
    assert rados_cli.main(["-c", cluster_conf, "-p", "rbd", "ls"]) == 0
    assert "cliobj" in capsys.readouterr().out.split()
    # a short bench: the reference's `rados bench 3 write`
    assert rados_cli.main(["-c", cluster_conf, "-p", "rbd", "bench",
                           "3", "write", "-b", "65536", "-t", "8"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["ops"] > 0 and rep["mb_per_sec"] > 0
    assert rados_cli.main(["-c", cluster_conf, "-p", "rbd", "rm",
                           "cliobj"]) == 0
    assert rados_cli.main(["-c", cluster_conf, "lspools"]) == 0
    assert "rbd" in capsys.readouterr().out.split()


def test_rbd_cli_lifecycle_and_diff(cluster_conf, tmp_path, capsys):
    """rbd CLI against the served cluster: create/ls/info/snap,
    export/import, and the export-diff/import-diff replication chain
    (ref: src/tools/rbd action set)."""
    from ceph_tpu.bench import rbd_cli

    c = ["-c", cluster_conf, "-p", "rbd"]
    assert rbd_cli.main(c + ["create", "img", "--size", "131072",
                             "--order", "16"]) == 0
    assert rbd_cli.main(c + ["ls"]) == 0
    assert "img" in capsys.readouterr().out
    assert rbd_cli.main(c + ["info", "img"]) == 0
    assert json.loads(capsys.readouterr().out)["size"] == 131072

    # seed data by importing a file, snapshot, mutate, diff-replicate
    src = tmp_path / "payload.bin"
    src.write_bytes(b"AB" * 8192)                  # 16 KiB
    assert rbd_cli.main(c + ["import", str(src), "img2",
                             "--order", "16"]) == 0
    assert rbd_cli.main(c + ["snap", "create", "img2@s1"]) == 0
    full = tmp_path / "full.diff"
    assert rbd_cli.main(c + ["export-diff", "img2@s1",
                             str(full)]) == 0
    capsys.readouterr()

    # replicate onto a fresh image via import-diff
    assert rbd_cli.main(c + ["create", "copy", "--size", "16384",
                             "--order", "16"]) == 0
    assert rbd_cli.main(c + ["import-diff", str(full), "copy"]) == 0
    out = tmp_path / "copy.bin"
    assert rbd_cli.main(c + ["export", "copy", str(out)]) == 0
    assert out.read_bytes() == b"AB" * 8192
    assert rbd_cli.main(c + ["snap", "ls", "copy"]) == 0
    assert "s1" in capsys.readouterr().out

    assert rbd_cli.main(c + ["rm", "img"]) == 0
    capsys.readouterr()


def test_ceph_osd_blocklist_cli(cluster_conf, capsys):
    """ceph osd blocklist add/ls/rm through the CLI (the fence behind
    MDS eviction, operator-driven)."""
    assert ceph_cli.main(["-c", cluster_conf, "osd", "blocklist",
                          "add", "client.evil", "600"]) == 0
    capsys.readouterr()
    assert ceph_cli.main(["-c", cluster_conf, "osd", "blocklist",
                          "ls"]) == 0
    assert "client.evil" in capsys.readouterr().out
    assert ceph_cli.main(["-c", cluster_conf, "osd", "blocklist",
                          "rm", "client.evil"]) == 0
    capsys.readouterr()
    assert ceph_cli.main(["-c", cluster_conf, "osd", "blocklist",
                          "ls"]) == 0
    assert "client.evil" not in capsys.readouterr().out
