"""Encoding-stability tests (the ceph-dencoder corpus tier).

ref: src/test/encoding + ceph-dencoder readable.sh — every versioned
struct round-trips, and its canonical instances' encoded bytes match a
committed corpus so the format cannot drift silently.
"""

import json
import pathlib

import numpy as np
import pytest

from ceph_tpu.bench import dencoder
from ceph_tpu.crush import builder
from ceph_tpu.crush.types import ChooseArg, Tunables
from ceph_tpu.encoding import (
    BufferList, Decoder, Encoder, EncodingError,
    decode_crush_map, decode_incremental, decode_osdmap,
    encode_crush_map, encode_incremental, encode_osdmap,
)
from ceph_tpu.osd.osdmap import Incremental, OSDMap
from ceph_tpu.osd.types import PGPool, pg_t

GOLDEN = pathlib.Path(__file__).parent / "golden" / "encoding.json"


# -- primitives -----------------------------------------------------------

def test_scalar_roundtrip():
    e = Encoder()
    e.u8(7).u16(65535).u32(0xDEADBEEF).u64(2**63).s32(-5).s64(-2**40)
    e.bool(True).string("héllo").blob(b"\x00\x01").f64(2.5)
    d = Decoder(e.tobytes())
    assert [d.u8(), d.u16(), d.u32(), d.u64(), d.s32(), d.s64()] == \
        [7, 65535, 0xDEADBEEF, 2**63, -5, -2**40]
    assert d.bool() is True
    assert d.string() == "héllo"
    assert d.blob() == b"\x00\x01"
    assert d.f64() == 2.5
    assert d.remaining() == 0


def test_containers_and_optional():
    e = Encoder()
    e.list([1, 2, 3], lambda e, v: e.s32(v))
    e.map({"a": 1, "b": 2}, lambda e, k: e.string(k),
          lambda e, v: e.u32(v))
    e.optional(None, lambda e, v: e.u64(v))
    e.optional(9, lambda e, v: e.u64(v))
    d = Decoder(e.tobytes())
    assert d.list(lambda d: d.s32()) == [1, 2, 3]
    assert d.map(lambda d: d.string(), lambda d: d.u32()) == \
        {"a": 1, "b": 2}
    assert d.optional(lambda d: d.u64()) is None
    assert d.optional(lambda d: d.u64()) == 9


def test_versioned_section_forward_compat():
    # a "newer" encoder appends a field; old decoder must skip it
    e = Encoder()
    with e.start(2):
        e.u32(42)
        e.string("new-field-old-decoder-never-saw")
    e.u32(7)  # data after the section
    d = Decoder(e.tobytes())
    with d.start(2) as v:
        assert v == 2
        assert d.u32() == 42
        # stop reading early: exit skips the rest
    assert d.u32() == 7


def test_versioned_section_incompat_raises():
    e = Encoder()
    with e.start(3, compat=3):
        e.u32(1)
    d = Decoder(e.tobytes())
    with pytest.raises(EncodingError):
        with d.start(2):
            pass


def test_decode_past_end_raises():
    with pytest.raises(EncodingError):
        Decoder(b"\x01").u32()


def test_bufferlist():
    bl = BufferList(b"abc")
    bl.append(b"def")
    bl2 = BufferList()
    bl2.append(bl)
    bl2.append(memoryview(b"gh"))
    assert len(bl2) == 8
    assert bl2.tobytes() == b"abcdefgh"
    assert bl2.substr(2, 3) == b"cde"
    import zlib
    assert bl2.crc32() == zlib.crc32(b"abcdefgh")


# -- struct roundtrips ----------------------------------------------------

def _rich_crush_map():
    m, root = builder.build_hierarchy(n_hosts=4, osds_per_host=2,
                                      n_racks=2)
    builder.add_simple_rule(m, root, 1, name="replicated_rule")
    m.device_classes = {0: "ssd", 3: "hdd"}
    m.choose_args = {-1: {root: ChooseArg(
        weight_set=[[0x10000] * len(m.buckets[root].items)],
        ids=None)}}
    return m


def test_crush_map_roundtrip():
    m = _rich_crush_map()
    m2 = decode_crush_map(encode_crush_map(m))
    assert m2.buckets.keys() == m.buckets.keys()
    for bid in m.buckets:
        a, b = m.buckets[bid], m2.buckets[bid]
        assert (a.id, a.type, a.alg, a.items, a.weights) == \
            (b.id, b.type, b.alg, b.items, b.weights)
    assert m2.rules.keys() == m.rules.keys()
    assert m2.rules[0].steps == m.rules[0].steps
    assert m2.tunables == m.tunables
    assert m2.type_names == m.type_names
    assert m2.bucket_names == m.bucket_names
    assert m2.device_classes == m.device_classes
    assert m2.choose_args.keys() == m.choose_args.keys()
    # decoded map still places PGs identically
    from ceph_tpu.crush.mapper import Mapper
    x = np.arange(64, dtype=np.uint32)
    a = Mapper(m).map_pgs(0, x, 3)
    b = Mapper(m2).map_pgs(0, x, 3)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_crush_map_bad_magic():
    with pytest.raises(EncodingError):
        decode_crush_map(b"\x00" * 16)


def test_osdmap_roundtrip():
    om = dencoder._test_osdmap()
    om2 = decode_osdmap(encode_osdmap(om))
    assert om2.epoch == om.epoch
    assert om2.max_osd == om.max_osd
    np.testing.assert_array_equal(om2.osd_state, om.osd_state)
    np.testing.assert_array_equal(om2.osd_weight, om.osd_weight)
    assert set(om2.pools) == set(om.pools)
    assert om2.pools[1].name == om.pools[1].name
    assert om2.pg_upmap_items == om.pg_upmap_items
    assert om2.pg_temp == om.pg_temp
    # identical placement after roundtrip
    for pid in om.pools:
        up_a, _, act_a, _ = om.map_pool(pid)
        up_b, _, act_b, _ = om2.map_pool(pid)
        np.testing.assert_array_equal(up_a, up_b)
        np.testing.assert_array_equal(act_a, act_b)


def test_incremental_roundtrip_and_apply():
    om = dencoder._test_osdmap()
    om2 = decode_osdmap(encode_osdmap(om))
    inc = Incremental(epoch=om.epoch + 1)
    inc.new_down = [1]
    inc.new_weight = {1: 0}
    inc.new_pg_temp[pg_t(1, 5)] = [4, 2]
    inc2 = decode_incremental(encode_incremental(inc))
    assert inc2.epoch == inc.epoch
    assert inc2.new_down == [1]
    assert inc2.new_pg_temp == {pg_t(1, 5): [4, 2]}
    om.apply_incremental(inc)
    om2.apply_incremental(inc2)
    for pid in om.pools:
        up_a, _, act_a, _ = om.map_pool(pid)
        up_b, _, act_b, _ = om2.map_pool(pid)
        np.testing.assert_array_equal(act_a, act_b)


# -- golden corpus --------------------------------------------------------

def test_golden_corpus():
    """Every dencoder test instance's bytes match the committed corpus.

    Regenerate intentionally with:
        python -m tests.test_encoding regen
    """
    corpus = json.loads(GOLDEN.read_text())
    current = _corpus()
    assert current.keys() == corpus.keys()
    for name, entries in current.items():
        assert entries == corpus[name], \
            f"encoding of {name} changed — bump struct version instead"


def test_dencoder_cli(tmp_path, capsys):
    assert dencoder.main(["list_types"]) == 0
    assert dencoder.main([
        "type", "pg_pool_t", "select_test", "1", "encode", "decode",
        "dump_json"]) == 0
    out = capsys.readouterr().out
    assert "ecpool" in out
    f = tmp_path / "m.bin"
    assert dencoder.main([
        "type", "crush_map", "select_test", "0", "encode", "export",
        str(f)]) == 0
    assert dencoder.main([
        "type", "crush_map", "import", str(f), "decode",
        "dump_json"]) == 0


def _corpus() -> dict:
    out = {}
    for name, t in dencoder.TYPES.items():
        out[name] = [t["encode"](mk()).hex() for mk in t["tests"]]
    return out


if __name__ == "__main__":
    import sys
    if len(sys.argv) > 1 and sys.argv[1] == "regen":
        GOLDEN.write_text(json.dumps(_corpus(), indent=1))
        print(f"wrote {GOLDEN}")


@pytest.mark.slow
def test_crushtool_binary_roundtrip(tmp_path, capsys):
    from ceph_tpu.bench import crushtool
    bin_f = tmp_path / "map.bin"
    txt_f = tmp_path / "map.txt"
    # build -> binary (ref: crushtool --build -o map.bin)
    crushtool.main(["--build", "--num-osds", "8", "--hosts", "4",
                    "-o", str(bin_f)])
    capsys.readouterr()
    # binary -> text (ref: crushtool -d map.bin -o map.txt)
    crushtool.main(["-d", str(bin_f), "-o", str(txt_f)])
    text = txt_f.read_text()
    assert "host0" in text and "root" in text
    # text -> binary -> test produces identical mappings to --build
    bin2 = tmp_path / "map2.bin"
    crushtool.main(["-c", str(txt_f), "-o", str(bin2)])
    r1 = crushtool.main(["-i", str(bin_f), "--test", "--num-rep", "2",
                         "--max-x", "255"])
    r2 = crushtool.main(["-i", str(bin2), "--test", "--num-rep", "2",
                         "--max-x", "255"])
    assert r1["utilization"] == r2["utilization"]
    assert r1["bad_mappings"] == r2["bad_mappings"]


def test_osdmaptool_export_import(tmp_path, capsys):
    from ceph_tpu.bench import osdmaptool
    f = tmp_path / "osdmap.bin"
    cf = tmp_path / "crush.bin"
    osdmaptool.main(["--createsimple", "12", "--pg-num", "64",
                     "--mark-out", "3",
                     "--export", str(f), "--export-crush", str(cf)])
    capsys.readouterr()
    assert f.exists() and cf.exists()
    osdmaptool.main(["--mapfn", str(f), "--test-map-pgs",
                     "--format", "json"])
    out = json.loads(capsys.readouterr().out)
    assert out["map_pgs"]["avg"] > 0
    # import-crush replaces the blob on a fresh map
    osdmaptool.main(["--createsimple", "12", "--pg-num", "64",
                     "--import-crush", str(cf), "--format", "json"])
    json.loads(capsys.readouterr().out)
