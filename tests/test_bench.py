"""Benchmark CLI tests (small sizes; validates flags + accounting)."""

import json

from ceph_tpu.bench.ec_benchmark import ErasureCodeBench, parse_args


def _run(argv):
    return ErasureCodeBench(parse_args(argv)).run()


def test_encode_flags_and_accounting():
    res = _run(["--plugin", "jax", "--workload", "encode",
                "--size", "16384", "--iterations", "4",
                "--parameter", "k=4", "--parameter", "m=2"])
    assert res["k"] == 4 and res["m"] == 2
    assert res["chunk_size"] == 4096
    assert res["total_bytes"] == res["batch"] * 4 * 4096
    assert res["GiB/s"] > 0
    assert res["timing"]["method"].startswith("chained_fori_loop")


def test_decode_workload_with_erasures():
    res = _run(["--plugin", "jerasure", "--workload", "decode",
                "--size", "16384", "--iterations", "2",
                "--parameter", "k=4", "--parameter", "m=2",
                "--erasures", "2"])
    assert res["workload"] == "decode"
    assert res["erased"] == [0, 1]


def test_explicit_erased_chunks():
    res = _run(["--workload", "decode", "--size", "8192",
                "--iterations", "1", "--parameter", "k=2",
                "--parameter", "m=2", "--erased", "1", "--erased", "2"])
    assert res["erased"] == [1, 2]


def test_json_output_parses(capsys):
    from ceph_tpu.bench import ec_benchmark
    ec_benchmark.main(["--size", "8192", "--iterations", "1",
                       "--parameter", "k=2", "--parameter", "m=1",
                       "--json"])
    lines = capsys.readouterr().out.strip().splitlines()
    assert len(lines) == 2
    secs, mbs = lines[0].split("\t")
    float(secs), float(mbs)
    detail = json.loads(lines[1])
    assert detail["plugin"] == "jax"


def test_sweep_rate_records_path_regression():
    """A run whose built plan promised the kernel but executed another
    engine must record path_expected_vs_actual (the PR 4 choose_args
    regression hid behind exactly this silence)."""
    from ceph_tpu.bench.crush_sweep import (canonical_map,
                                            path_regressions,
                                            sweep_rate)
    from ceph_tpu.crush.mapper import Mapper

    mp = Mapper(canonical_map(64), block=1 << 10)
    real = mp.mapping_path
    state = {"first": True}

    def fake(rule, width):
        # the pre-run prediction says pallas; every later read (and
        # the run itself, on CPU) is the xla path — the mid-run
        # degrade shape
        if state["first"]:
            state["first"] = False
            return "pallas"
        return real(rule, width)

    mp.mapping_path = fake
    r = sweep_rate(n_osds=64, n_pgs=1 << 12, num_rep=3, mapper=mp)
    assert r["path"] == "xla"
    assert r["path_expected_vs_actual"] == "pallas->xla"
    assert path_regressions({"v": r}) == ["v: pallas->xla"]
    # a degraded row must not dress its fallback numbers in the
    # batched kernel's geometry (round 15)
    assert "candidate_batched" not in r
    assert "fetches_per_sweep" not in r


def test_sweep_rate_reports_candidate_batched_kernel(monkeypatch):
    """Round 15 schema pin: a kernel-path sweep_rate row carries the
    candidate-batching facts (fetches_per_sweep, candidate_batched)
    and stays JSON-clean; an XLA-path row omits them. The timed sweep
    is stubbed — the keys come from the PLAN, and an interpret-mode
    kernel sweep would cost tier-1 a full compile for nothing."""
    from ceph_tpu.bench import crush_sweep as cs
    from ceph_tpu.crush import pallas_mapper as pm
    from ceph_tpu.crush.mapper import Mapper

    monkeypatch.setenv("CEPH_TPU_CRUSH_KERNEL", "interpret")
    mp = Mapper(cs.canonical_map(64), block=1 << 10)
    info = mp.kernel_plan_info(0, 3)
    assert info is not None and info["candidate_batched"] is True
    plan = mp._kernel_plan(0)
    _, fold, groups = pm.kernel_geometry(plan, 3 + pm.SPEC_EXTRA)
    assert info["fetches_per_sweep"] == \
        groups * (plan.l_main + plan.l_leaf)
    monkeypatch.setattr(cs, "_timed_sweep", lambda *a: 0.01)
    r = cs.sweep_rate(n_osds=64, n_pgs=1 << 12, num_rep=3, mapper=mp)
    assert r["candidate_batched"] is True
    assert r["fetches_per_sweep"] == info["fetches_per_sweep"]
    assert r["candidate_fold"] == info["candidate_fold"]
    assert json.loads(json.dumps(r)) == r       # JSON-clean
    # XLA path (kernel off): the keys are absent, not null
    monkeypatch.setenv("CEPH_TPU_CRUSH_KERNEL", "0")
    mx = Mapper(cs.canonical_map(64), block=1 << 10)
    rx = cs.sweep_rate(n_osds=64, n_pgs=1 << 12, num_rep=3, mapper=mx)
    assert "fetches_per_sweep" not in rx
    assert "candidate_batched" not in rx
