"""Unit pins for the round-18 proc-backend support layers: incarnation
key derivation (msg/auth.py), the mon-config apply/restore algebra
(utils/config.py), and the conf document roundtrip (cluster/conf.py).
All pure/in-memory — the cluster-level behavior rides
test_proc_cluster.py.
"""

import pytest

from ceph_tpu.cluster.conf import (
    conf_keyring,
    conf_monmap,
    read_conf_doc,
    write_conf,
)
from ceph_tpu.mon.monitor import MonMap
from ceph_tpu.msg.auth import AuthError, Keyring
from ceph_tpu.utils.config import apply_mon_config


# -- incarnation key derivation --------------------------------------------

def test_incarnation_key_derives_from_base():
    """Two keyrings provisioned with the same base secret derive the
    SAME per-incarnation key — a separate-process daemon and the mon
    agree without sharing a dict."""
    master = Keyring()
    master.add("mds.a")
    child = master.copy_for("mds.a")
    assert master.get("mds.a.12345") == child.get("mds.a.12345")
    # different incarnations get different keys
    assert master.get("mds.a.12345") != master.get("mds.a.12346")
    # and none equals the base
    assert master.get("mds.a.12345") != master.get("mds.a")


def test_incarnation_key_requires_base():
    kr = Keyring()
    with pytest.raises(AuthError):
        kr.get("mds.a.12345")
    # a non-numeric suffix is NOT an incarnation pattern
    kr.add("mds.a")
    with pytest.raises(AuthError):
        kr.get("mds.a.standby")


def test_incarnation_key_follows_base_rotation():
    kr = Keyring()
    kr.add("mds.a")
    before = kr.get("mds.a.7")
    kr.set_key("mds.a", kr.generate_key())
    assert kr.get("mds.a.7") != before


def test_explicit_ident_key_shadows_derivation():
    """An explicitly added incarnation key wins over derivation (the
    standalone-harness path where no base entity exists is the same
    add)."""
    kr = Keyring()
    kr.add("mds.a")
    explicit = kr.add("mds.a.7")
    assert kr.get("mds.a.7") == explicit


# -- apply_mon_config algebra ----------------------------------------------

def test_apply_mon_config_precedence():
    """Per-entity beats per-type beats global; typed coercion for
    registered options."""
    live: dict = {}
    state: dict = {}
    cfgmap = {"global": {"osd_max_backfills": "2"},
              "osd": {"osd_max_backfills": "3"},
              "osd.0": {"osd_max_backfills": "7"}}
    changed = apply_mon_config("osd.0", cfgmap, live, state)
    assert live["osd_max_backfills"] == 7 and changed
    live2: dict = {}
    apply_mon_config("osd.1", cfgmap, live2, {})
    assert live2["osd_max_backfills"] == 3
    live3: dict = {}
    apply_mon_config("mon.a", cfgmap, live3, {})
    assert live3["osd_max_backfills"] == 2


def test_apply_mon_config_restores_baseline_on_rm():
    live = {"osd_max_backfills": 4}
    state: dict = {}
    apply_mon_config("osd.0", {"osd": {"osd_max_backfills": "9"}},
                     live, state)
    assert live["osd_max_backfills"] == 9
    apply_mon_config("osd.0", {}, live, state)
    assert live["osd_max_backfills"] == 4
    # a key the daemon never had is REMOVED, not left as an override
    live2: dict = {}
    state2: dict = {}
    apply_mon_config("osd.0", {"osd": {"osd_max_backfills": "9"}},
                     live2, state2)
    apply_mon_config("osd.0", {}, live2, state2)
    assert "osd_max_backfills" not in live2


def test_apply_mon_config_shared_dict_not_poisoned():
    """The in-process backend shares ONE live dict across daemons: a
    later applier must not snapshot the already-applied value as its
    'baseline' (config rm would then restore the override)."""
    live = {"osd_max_backfills": 1}
    s0: dict = {}
    s1: dict = {}
    cfgmap = {"osd": {"osd_max_backfills": "9"}}
    apply_mon_config("osd.0", cfgmap, live, s0)
    apply_mon_config("osd.1", cfgmap, live, s1)   # sees 9 already
    apply_mon_config("osd.0", {}, live, s0)
    apply_mon_config("osd.1", {}, live, s1)
    assert live["osd_max_backfills"] == 1


def test_apply_mon_config_invalid_value_skipped():
    """A malformed central value must not kill (or change) a daemon."""
    live = {"osd_max_backfills": 1}
    changed = apply_mon_config(
        "osd.0", {"osd": {"osd_max_backfills": "not-an-int"}},
        live, {})
    assert live["osd_max_backfills"] == 1 and changed == []


# -- conf document roundtrip -----------------------------------------------

def test_conf_document_roundtrip(tmp_path):
    mm = MonMap(fsid="unit-fsid")
    mm.add("a", 0, "127.0.0.1", 6789)
    mm.add("b", 1, "127.0.0.1", 6790)
    kr = Keyring()
    kr.add("mon.a")
    kr.add("client.admin")
    path = str(tmp_path / "cluster.conf")
    write_conf(path, mm, kr, config={"osd_heartbeat_grace": 10.0},
               extra={"data_dir": "/nonexistent/x"})
    doc = read_conf_doc(path)
    mm2 = conf_monmap(doc)
    assert mm2.fsid == "unit-fsid"
    assert {(n, r[2]) for n, r in mm2.mons.items()} == \
        {("a", 6789), ("b", 6790)}
    kr2 = conf_keyring(doc)
    assert kr2.get("client.admin") == kr.get("client.admin")
    assert doc["config"]["osd_heartbeat_grace"] == 10.0
    assert doc["data_dir"] == "/nonexistent/x"
