"""CephFS snaprealm acceptance: .snap namespace, point-in-time reads
through the OSD COW-clone machinery, read-only walls, and realm
survival across MDS failover and subtree migration (ref test model:
qa/tasks/cephfs/test_snapshots.py)."""

import asyncio
import json

import pytest

from ceph_tpu.cephfs import FSError
from ceph_tpu.cephfs.client import CephFSClient
from ceph_tpu.cephfs.fsmap import FSMap
from ceph_tpu.cephfs.mds import MDSDaemon, snap_split
from ceph_tpu.cluster.vstart import Cluster
from ceph_tpu.rados import ObjectOperationError

FAST_CFG = {
    "mds_beacon_interval": 0.2,
    "mds_beacon_grace": 2.0,
    "mds_reconnect_timeout": 1.0,
    "mds_replay_interval": 0.1,
    "mds_bal_interval": 0.0,
}


def run(coro):
    asyncio.run(coro)


async def _pool(c, name="fs"):
    await c.client.pool_create(name, pg_num=8, size=3)
    await c.wait_for_clean(timeout=120)
    io = await c.client.open_ioctx(name)
    for _ in range(30):
        try:
            await io.write_full("_warm", b"x")
            break
        except ObjectOperationError:
            await asyncio.sleep(1)
    return io


def test_snap_split_and_fsmap_v3():
    """Unit pins: the .snap path parser and the v3 FSMap snap
    registry (round-trip + realm-coverage query)."""
    assert snap_split("/d/.snap/s1/a/b") == ("/d", "s1", "a/b")
    assert snap_split("/d/.snap/s1") == ("/d", "s1", "")
    assert snap_split("/d/.snap") == ("/d", "", "")
    assert snap_split("/.snap/s1") == ("/", "s1", "")
    assert snap_split("/d/sub/f") is None
    m = FSMap()
    m.snaps = {1: {"name": "s1", "path": "/d", "pool": "fs"},
               2: {"name": "s2", "path": "/", "pool": "fs"}}
    d = FSMap.decode(m.encode())
    assert d.snaps == m.snaps
    # coverage: /d/f is governed by both realms, /x only by "/"
    assert set(d.snaps_under("/d/f")) == {1, 2}
    assert set(d.snaps_under("/x")) == {2}
    assert set(d.snaps_under("/d")) == {1, 2}
    # a default map has no snaps and decodes clean
    assert FSMap.decode(FSMap().encode()).snaps == {}


def test_snaprealm_point_in_time_and_erofs():
    """THE core pin: mkdir .snap/<name> freezes the subtree —
    later head writes COW at the OSD, snap reads stay byte-identical,
    every mutation through .snap is -EROFS, and rmsnap removes the
    snapshot without disturbing its sibling or the heads."""
    async def go():
        c = await Cluster(n_mons=1, n_osds=3).start()
        try:
            io = await _pool(c)
            mds = MDSDaemon(io)
            await mds.fs.mount()
            addr = await mds.start()
            cl_io = await c.client.open_ioctx("fs")
            cl = await CephFSClient(cl_io, addr).mount()
            await cl.mkdir("/d")
            await cl.mkdir("/d/sub")
            await cl.write_file("/d/f1", b"one" * 100)
            await cl.write_file("/d/sub/f2", b"two" * 200)
            await cl.mkdir("/d/.snap/s1")
            # namespace through .snap
            assert await cl.ls("/d/.snap") == ["s1"]
            assert sorted(await cl.ls("/d/.snap/s1")) == ["f1", "sub"]
            st = await cl.stat("/d/.snap/s1/f1")
            assert st["type"] == "file" and st["size"] == 300
            # overwrite heads; snapshot stays point-in-time
            await cl.write_file("/d/f1", b"ONE!" * 150)
            await cl.write_file("/d/sub/f2", b"TWO!" * 10)
            assert await cl.read_file("/d/.snap/s1/f1") == b"one" * 100
            assert await cl.read_file("/d/.snap/s1/sub/f2") == \
                b"two" * 200
            assert await cl.read_file("/d/f1") == b"ONE!" * 150
            # read-only walls: write/create/unlink/rename in or across
            for coro in (cl.write_file("/d/.snap/s1/f1", b"x"),
                         cl.mkdir("/d/.snap/s1/new"),
                         cl.unlink("/d/.snap/s1/f1"),
                         cl.rename("/d/f1", "/d/.snap/s1/f1"),
                         cl.rename("/d/.snap/s1/f1", "/d/out")):
                with pytest.raises(FSError) as ei:
                    await coro
                assert ei.value.errno == -30          # -EROFS
            # second snapshot sees the new content, first is unmoved
            await cl.mkdir("/d/.snap/s2")
            assert await cl.read_file("/d/.snap/s2/f1") == b"ONE!" * 150
            assert await cl.read_file("/d/.snap/s1/f1") == b"one" * 100
            with pytest.raises(FSError) as ei:
                await cl.mkdir("/d/.snap/s1")         # dup
            assert ei.value.errno == -17
            # the mon is the registry of record
            ret, _, out = await c.client.mon_command(
                {"prefix": "fs snap ls"})
            assert ret == 0 and len(json.loads(out)["snaps"]) == 2
            # rmsnap: s1 gone (reads -ENOENT), s2 + heads intact
            await cl.rmdir("/d/.snap/s1")
            assert await cl.ls("/d/.snap") == ["s2"]
            with pytest.raises(FSError) as ei:
                await cl.read_file("/d/.snap/s1/f1")
            assert ei.value.errno == -2
            assert await cl.read_file("/d/.snap/s2/f1") == b"ONE!" * 150
            assert await cl.read_file("/d/f1") == b"ONE!" * 150
            await cl.unmount()
            await mds.stop()
        finally:
            await c.stop()
    run(go())


def test_snaprealm_knob_and_limit():
    """mds_snap_enabled=false refuses mksnap -EPERM;
    mds_snap_max_per_realm caps a realm at -EMLINK."""
    async def go():
        c = await Cluster(n_mons=1, n_osds=3).start()
        try:
            io = await _pool(c)
            mds = MDSDaemon(io, config={"mds_snap_max_per_realm": 2})
            await mds.fs.mount()
            addr = await mds.start()
            cl_io = await c.client.open_ioctx("fs")
            cl = await CephFSClient(cl_io, addr).mount()
            await cl.mkdir("/d")
            await cl.mkdir("/d/.snap/a")
            await cl.mkdir("/d/.snap/b")
            with pytest.raises(FSError) as ei:
                await cl.mkdir("/d/.snap/c")
            assert ei.value.errno == -31              # -EMLINK
            # knob off: NEW snapshots refuse -EPERM, existing ones
            # still serve and can still be removed
            mds.snap_enabled = False
            with pytest.raises(FSError) as ei:
                await cl.mkdir("/d/.snap/z")
            assert ei.value.errno == -1               # -EPERM
            await cl.mkdir("/other")      # namespace mkdir unaffected
            assert await cl.ls("/d/.snap") == ["a", "b"]
            await cl.rmdir("/d/.snap/a")
            assert await cl.ls("/d/.snap") == ["b"]
            await cl.unmount()
            await mds.stop()
        finally:
            await c.stop()
    run(go())


def test_snaprealm_survives_failover():
    """kill -9 the active MDS after mksnap: the promoted standby
    reloads the realm (persisted table + journaled mksnap replay) and
    keeps serving byte-identical point-in-time reads."""
    async def go():
        c = await Cluster(n_mons=1, n_osds=3, config=FAST_CFG).start()
        try:
            await c.start_fs(n_mds=2)
            monmap = c.client.monc.monmap
            cl = await CephFSClient.create(monmap, None, "cephfs",
                                           keyring=c.keyring)
            await cl.mkdir("/d")
            await cl.write_file("/d/f", b"pre-snap" * 64)
            await cl.mkdir("/d/.snap/s1")
            await cl.write_file("/d/f", b"post-snap" * 32)
            victim = await c.wait_for_mds_active()
            await c.kill_mds(victim)
            await c.wait_for_mds_active(not_name=victim, timeout=30)
            assert await cl.ls("/d/.snap") == ["s1"]
            assert await cl.read_file("/d/.snap/s1/f") == \
                b"pre-snap" * 64
            assert await cl.read_file("/d/f") == b"post-snap" * 32
            # the realm is live on the successor: new snaps still work
            await cl.mkdir("/d/.snap/s2")
            assert await cl.read_file("/d/.snap/s2/f") == \
                b"post-snap" * 32
            await cl.unmount()
        finally:
            await c.stop()
    run(go())


def test_snaprealm_rides_subtree_migration():
    """A realm rooted in a migrated subtree moves with it: after the
    two-phase handoff the IMPORTING rank serves .snap lookups and the
    snapshot stays point-in-time."""
    async def go():
        c = await Cluster(n_mons=1, n_osds=3, config=FAST_CFG).start()
        try:
            await c.start_fs(n_mds=2, max_mds=2)
            monmap = c.client.monc.monmap
            cl = await CephFSClient.create(monmap, None, "cephfs",
                                           keyring=c.keyring)
            await cl.mkdir("/d")
            await cl.write_file("/d/f", b"before" * 50)
            await cl.mkdir("/d/.snap/s1")
            await c.subtree_pin("/d", 1)
            await cl.write_file("/d/f", b"after" * 99)
            assert await cl.ls("/d/.snap") == ["s1"]
            assert await cl.read_file("/d/.snap/s1/f") == b"before" * 50
            assert await cl.read_file("/d/f") == b"after" * 99
            # the importer's own realm table serves it (not a stale
            # copy on the exporter)
            importer = next(m for m in c.mdss
                            if m.rank == 1 and not m._stopping)
            assert any(r["path"] == "/d" and r["name"] == "s1"
                       for r in importer.realms.values())
            await cl.unmount()
        finally:
            await c.stop()
    run(go())
