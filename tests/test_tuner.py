"""The round-17 self-driving tuner, tested in layers:

- **guardrail algebra** — Guardrails under virtual ticks (no clock,
  no cluster): hysteresis streaks, flap protection, the per-tick
  change budget that DEFERS instead of dropping;
- **mon-side ledger** — TuneState ownership/audit lifecycle and the
  pure single-writer lease filter the dampening sweep consults;
- **policy convergence** — TunerModule against a scripted world (a
  stub mon that applies actuator commands the way the real one does,
  backed by a REAL TuneState): observe commits nothing, drive
  act/revert cycles are level-based (a fresh module instance — the
  promoted-standby shape — resumes without double-committing), the
  operator always wins;
- **one storm acceptance** — the only cluster spin here (tier-1 is
  near its wall-clock cap): a steady balanced workload in drive mode
  commits ZERO, a hot-pool burst trips a guardrailed client-profile
  commit whose audit entry carries the sensors, a mid-storm mgr
  failover does not double-commit, and the heal reverts.
"""

import asyncio
import json

import pytest

from ceph_tpu.mgr.tuner import Guardrails, Proposal, TunerModule
from ceph_tpu.mon.tune import TuneState, tuner_lease_filter


def run(coro):
    asyncio.run(coro)


def _p(policy="pol", key="affinity:1", kind="act"):
    return Proposal(policy, key, kind,
                    {"prefix": "osd primary-affinity", "id": 1,
                     "weight": 0.0},
                    {"osd": 1}, f"{kind} {key}")


# -- guardrail algebra (virtual ticks) -------------------------------------

def test_guardrails_hysteresis_and_flap():
    """act needs N CONSECUTIVE breaching ticks; a flapping sensor
    (breach every other tick) never accumulates a streak and commits
    nothing."""
    g = Guardrails({"mgr_tuner_act_ticks": 3})
    assert g.filter([_p()]) == ([], [])            # tick 1
    assert g.filter([_p()]) == ([], [])            # tick 2
    granted, deferred = g.filter([_p()])           # tick 3
    assert len(granted) == 1 and not deferred
    # flap: present on odd ticks only -> streak resets each gap
    g2 = Guardrails({"mgr_tuner_act_ticks": 2})
    for _ in range(6):
        assert g2.filter([_p()]) == ([], [])
        assert g2.filter([]) == ([], [])           # clean tick resets
    assert g2.streaks == {}


def test_guardrails_revert_threshold_is_separate():
    """reverts wait out their own (longer) clean-streak threshold."""
    g = Guardrails({"mgr_tuner_act_ticks": 1, "mgr_tuner_revert_ticks": 3})
    r = _p(kind="revert")
    assert g.filter([r]) == ([], [])
    assert g.filter([r]) == ([], [])
    granted, _ = g.filter([r])
    assert [p.kind for p in granted] == ["revert"]


def test_guardrails_budget_defers_not_drops():
    """Three eligible changes against a budget of 2: two granted, one
    DEFERRED — and the deferred one keeps its streak, so it is granted
    on the very next tick (not dropped, not restarted)."""
    g = Guardrails({"mgr_tuner_act_ticks": 1,
                    "mgr_tuner_max_changes_per_tick": 2})
    props = [_p(key=f"affinity:{i}") for i in range(3)]
    granted, deferred = g.filter(props)
    assert [p.key for p in granted] == ["affinity:0", "affinity:1"]
    assert [p.key for p in deferred] == ["affinity:2"]
    assert g.deferred_total == 1
    granted2, deferred2 = g.filter([props[2]])
    assert [p.key for p in granted2] == ["affinity:2"] and not deferred2


def test_guardrails_settle_restarts_streak():
    """settle() after an apply restarts the ident's streak — in
    observe mode this is the audit-ring anti-spam (one record per
    hysteresis window, not one per tick)."""
    g = Guardrails({"mgr_tuner_act_ticks": 2})
    g.filter([_p()])
    granted, _ = g.filter([_p()])
    assert granted
    g.settle(granted[0])
    assert g.filter([_p()]) == ([], [])            # streak restarted


# -- the single-writer lease filter ----------------------------------------

def test_lease_filter_defers_both_directions():
    """An OSD under an active tuner affinity lease is the TUNER's to
    dampen AND to heal — the mon sweep's candidates are filtered in
    both directions; expired leases and profile keys don't count."""
    owned = {"affinity:2": {"since": 100.0},
             "affinity:5": {"since": 0.0},          # expired
             "profile:client.x": {"since": 100.0}}
    damp, heal, deferred = tuner_lease_filter(
        [1, 2], [2, 5], owned, now=110.0, lease_s=60.0)
    assert damp == [1]
    assert heal == [5]                              # lease expired
    assert deferred == [2]
    # no leases -> pass-through
    assert tuner_lease_filter([1], [2], {}, 0.0, 60.0) == \
        ([1], [2], [])


# -- TuneState: ownership + bounded audit ----------------------------------

def test_tune_state_ownership_lifecycle():
    ts = TuneState({})
    prov = {"policy": "gray_osd_responder", "mode": "drive",
            "action": "act", "sensors": {"osd": 2}}
    ts.record_commit({"prefix": "osd primary-affinity", "id": 2,
                      "weight": 0.0}, prov)
    assert "affinity:2" in ts.owned
    assert ts.committed == 1
    # the revert half releases
    ts.record_commit({"prefix": "osd primary-affinity", "id": 2,
                      "weight": 1.0},
                     {**prov, "action": "revert"})
    assert "affinity:2" not in ts.owned and ts.reverted == 1
    # profile set acquires, operator rm releases (the operator wins)
    ts.record_commit({"prefix": "osd client-profile", "op": "set",
                      "entity": "client.h", "reservation": 0.0,
                      "weight": 0.5, "limit": 40.0},
                     {"policy": "hot_pool_protector",
                      "action": "act"})
    assert "profile:client.h" in ts.owned
    ts.record_operator({"prefix": "osd client-profile", "op": "rm",
                        "entity": "client.h"})
    assert ts.owned == {}
    # config set carries no per-target ownership
    assert TuneState.target_key({"prefix": "config set",
                                 "name": "osd_recovery_max_active"}) \
        is None
    # observations never touch ownership
    ts.record_observation({"policy": "p", "action": "act",
                           "sensors": {}, "cmd": {}})
    assert ts.owned == {} and ts.observed == 1
    assert ts.log()[-1]["committed"] is False


def test_tune_state_audit_bounded_and_status_shape():
    ts = TuneState({"mon_tune_audit_max": 8})
    for i in range(30):
        ts.record_observation({"policy": "p", "action": "act",
                               "sensors": {"i": i}, "cmd": {}})
    assert len(ts.audit) == 8
    assert ts.log(3)[-1]["sensors"] == {"i": 29}    # newest last
    assert len(ts.log(3)) == 3
    st = ts.status("observe")
    assert st["mode"] == "observe" and st["audit_max"] == 8
    assert st["audit_entries"] == 8 and st["observed"] == 30
    ts.record_commit({"prefix": "osd primary-affinity", "id": 1,
                      "weight": 0.0}, {"policy": "x", "action": "act"})
    st = ts.status("drive")
    assert "affinity:1" in st["owned"]
    assert "cmd" not in st["owned"]["affinity:1"]   # status stays small
    assert json.loads(json.dumps(st)) == st          # JSON-clean


# -- read-only cap class + CLI spellings -----------------------------------

def test_tune_command_cap_class_and_cli_parse():
    """`tune status`/`tune log` are mon-r reads; `tune record` mutates
    the audit ring and must stay behind mon w. The CLI spells all
    three views."""
    from ceph_tpu.bench.ceph_cli import parse_command
    from ceph_tpu.mon.auth_monitor import READONLY_COMMANDS
    assert "tune status" in READONLY_COMMANDS
    assert "tune log" in READONLY_COMMANDS
    assert "tune record" not in READONLY_COMMANDS
    assert parse_command(["tune", "status"])[0] == \
        {"prefix": "tune status"}
    assert parse_command(["tune", "log"])[0] == {"prefix": "tune log"}
    assert parse_command(["tune", "log", "5"])[0] == \
        {"prefix": "tune log", "num": 5}


# -- policy convergence against a scripted world ---------------------------

class _World:
    """The tuner-relevant slice of a mon: canned status/pg_dump/
    osd_dump, and a command endpoint that applies actuator commands
    to that state the way the real routing does — backed by a REAL
    TuneState, so ownership/audit semantics are the shipped ones."""

    def __init__(self, **cfg):
        self.config = {
            "mgr_tuner_mode": "drive",
            "mgr_tuner_act_ticks": 2,
            "mgr_tuner_revert_ticks": 2,
            "mgr_tuner_hot_pool_min_ops": 1.0,
            "mgr_tuner_hot_pool_ratio": 2.0,
            **cfg}
        self.tune = TuneState(self.config)
        self.commands: list[dict] = []
        self.status = {"osdmap": {"slow_osds": {}},
                       "pgmap": {"backfilling_pgs": 0,
                                 "degraded_pgs": 0}}
        self.pg_dump = {"pg_stats": {}}
        self.osd_dump = {
            "osds": [{"osd": i, "primary_affinity": 1.0}
                     for i in range(3)],
            "client_profiles": {}}
        self.degraded: dict = {}

    async def command(self, cmd: dict, inbl: bytes = b""):
        self.commands.append(dict(cmd))
        prefix = cmd.get("prefix")
        if prefix == "tune status":
            mode = str(self.config.get("mgr_tuner_mode", "observe"))
            return 0, "", json.dumps(
                self.tune.status(mode)).encode()
        if prefix == "tune record":
            self.tune.record_observation(cmd["entry"])
            return 0, "", b""
        if prefix == "device-runtime status":
            return 0, "", json.dumps(
                {"daemons": {}, "degraded": self.degraded}).encode()
        if prefix == "osd primary-affinity":
            for o in self.osd_dump["osds"]:
                if o["osd"] == int(cmd["id"]):
                    o["primary_affinity"] = float(cmd["weight"])
        elif prefix == "osd client-profile":
            profs = self.osd_dump["client_profiles"]
            if cmd["op"] == "set":
                profs[cmd["entity"]] = [cmd["reservation"],
                                        cmd["weight"], cmd["limit"]]
            elif cmd["op"] == "rm":
                profs.pop(cmd["entity"], None)
        elif prefix == "config set":
            self.config[cmd["name"]] = cmd["value"]
        else:
            return -22, f"unknown {prefix}", b""
        prov = cmd.get("provenance")
        if prov is not None:
            self.tune.record_commit(cmd, prov)
        else:
            self.tune.record_operator(cmd)
        return 0, "", b""

    def actuations(self, prefix: str) -> list[dict]:
        return [c for c in self.commands
                if c.get("prefix") == prefix]


class _StubMgr:
    def __init__(self, world):
        self.config = world.config
        self.monc = world                    # .command()
        self.modules: list = []
        self.daemon_state = None
        self._world = world

    async def get(self, what: str):
        return {"status": self._world.status,
                "pg_dump": self._world.pg_dump,
                "osd_dump": self._world.osd_dump}[what]


def _tuner(world) -> TunerModule:
    mgr = _StubMgr(world)
    t = TunerModule(mgr)
    mgr.modules = [t]
    return t


async def _ticks(t: TunerModule, n: int) -> None:
    for _ in range(n):
        await t.tick()
        await asyncio.sleep(0.002)     # real dt for the rate sensor


def test_observe_mode_commits_nothing():
    """A sustained breach in observe mode issues ONLY reads and
    `tune record` — no actuator command, no map change — and the
    settle discipline keeps it to one record per hysteresis window,
    not one per tick."""
    async def go():
        w = _World(mgr_tuner_mode="observe")
        w.status["osdmap"]["slow_osds"] = {"2": 4.0}
        await _ticks(_tuner(w), 5)
        assert not w.actuations("osd primary-affinity")
        assert not w.actuations("config set")
        assert w.osd_dump["osds"][2]["primary_affinity"] == 1.0
        assert w.tune.owned == {}
        # act_ticks=2 over 5 ticks -> records at ticks 2 and 4 only
        assert w.tune.observed == 2
        entry = w.tune.log()[-1]
        assert entry["committed"] is False
        assert entry["policy"] == "gray_osd_responder"
        assert entry["sensors"]["osd"] == 2
    run(go())


def test_gray_osd_drive_act_then_level_holds_then_revert():
    """Drive mode: a confirmed-slow OSD is dampened after act_ticks,
    further ticks propose NOTHING (desired == actual — the level-based
    no-double-commit property), and the heal reverts after
    revert_ticks with both halves in the audit."""
    async def go():
        w = _World()
        w.status["osdmap"]["slow_osds"] = {"2": 4.0}
        t = _tuner(w)
        await _ticks(t, 2)
        assert w.osd_dump["osds"][2]["primary_affinity"] == 0.0
        assert w.tune.committed == 1 and t.actions_committed == 1
        assert "affinity:2" in w.tune.owned
        assert w.tune.owned["affinity:2"]["policy"] == \
            "gray_osd_responder"
        await _ticks(t, 3)                 # held: no re-commit
        assert len(w.actuations("osd primary-affinity")) == 1
        w.status["osdmap"]["slow_osds"] = {}
        await _ticks(t, 2)
        assert w.osd_dump["osds"][2]["primary_affinity"] == 1.0
        assert w.tune.reverted == 1 and t.actions_reverted == 1
        assert w.tune.owned == {}
        acts = [(e["action"], e["committed"]) for e in w.tune.log()]
        assert acts == [("act", True), ("revert", True)]
    run(go())


def test_promoted_standby_resumes_without_double_commit():
    """The failover shape without a cluster: a FRESH TunerModule (the
    promoted standby — empty streaks, no rate baseline) against the
    same mon state sees desired == actual for the in-flight action and
    commits nothing; when the OSD heals, the new instance owns the
    revert because ownership lives mon-side."""
    async def go():
        w = _World()
        w.status["osdmap"]["slow_osds"] = {"1": 5.0}
        await _ticks(_tuner(w), 2)         # incarnation A commits
        assert w.tune.committed == 1
        t_b = _tuner(w)                    # incarnation B, clean RAM
        await _ticks(t_b, 4)
        assert w.tune.committed == 1       # no double-commit
        assert len(w.actuations("osd primary-affinity")) == 1
        w.status["osdmap"]["slow_osds"] = {}
        await _ticks(t_b, 2)
        assert w.tune.reverted == 1 and w.tune.owned == {}
        assert w.osd_dump["osds"][1]["primary_affinity"] == 1.0
    run(go())


def test_operator_wins_and_tuner_stands_down():
    """An operator (provenance-less) command on a tuner-held target
    releases the lease; the tuner then has nothing to revert and
    issues no further actuator commands."""
    async def go():
        w = _World()
        w.status["osdmap"]["slow_osds"] = {"2": 4.0}
        t = _tuner(w)
        await _ticks(t, 2)
        assert "affinity:2" in w.tune.owned
        # the operator undoes it by hand (no provenance)
        await w.command({"prefix": "osd primary-affinity", "id": 2,
                         "weight": 1.0})
        assert w.tune.owned == {}
        w.status["osdmap"]["slow_osds"] = {}
        n_before = len(w.actuations("osd primary-affinity"))
        await _ticks(t, 4)
        assert len(w.actuations("osd primary-affinity")) == n_before
    run(go())


def test_hot_pool_protector_trip_and_heal():
    """Per-pool op rates from pg-stats client_ops deltas: a pool
    starving another gets its aggressor entity a tightened profile
    (reservation 0, bounded limit); when the burst ends the owned
    profile is removed."""
    async def go():
        w = _World()
        hot, cold = [100], [10]
        w.pg_dump["pg_stats"] = {
            "1.0": {"client_ops": {"client.hot": hot[0]}},
            "2.0": {"client_ops": {"client.cold": cold[0]}}}

        def bump():
            hot[0] += 200
            cold[0] += 2
            w.pg_dump["pg_stats"]["1.0"]["client_ops"] = \
                {"client.hot": hot[0]}
            w.pg_dump["pg_stats"]["2.0"]["client_ops"] = \
                {"client.cold": cold[0]}
        t = _tuner(w)
        await _ticks(t, 1)                 # baseline tick: no rates
        assert not w.actuations("osd client-profile")
        for _ in range(3):
            bump()
            await _ticks(t, 1)
        profs = w.osd_dump["client_profiles"]
        assert "client.hot" in profs
        res, weight, limit = profs["client.hot"]
        assert res == 0.0 and limit > 0.0
        assert "profile:client.hot" in w.tune.owned
        entry = next(e for e in w.tune.log()
                     if e["policy"] == "hot_pool_protector")
        assert entry["sensors"]["entity"] == "client.hot"
        assert entry["sensors"]["hot_pool"] == 1
        assert entry["sensors"]["hot_pool_rate"] > 0
        # heal: counters stop moving -> rates decay to zero
        await _ticks(t, 3)
        assert w.osd_dump["client_profiles"] == {}
        assert w.tune.owned == {} and w.tune.reverted == 1
    run(go())


def test_kernel_watchdog_acts_on_permanent_only():
    """Only a PERMANENTLY degraded kernel path (quarantine gave up)
    loses primary eligibility; transient backoff phases never
    actuate. The heal reverts through the same affinity path."""
    async def go():
        w = _World()
        w.degraded = {"1": {"ratio": 0.8, "engine": "pallas",
                            "phase": "backoff", "since": 0.0}}
        t = _tuner(w)
        await _ticks(t, 3)
        assert not w.actuations("osd primary-affinity")
        w.degraded["1"]["phase"] = "permanent"
        await _ticks(t, 2)
        assert w.osd_dump["osds"][1]["primary_affinity"] == 0.0
        assert w.tune.owned["affinity:1"]["policy"] == \
            "kernel_path_watchdog"
        entry = w.tune.log()[-1]
        assert entry["sensors"]["mismatch_ratio"] == 0.8
        assert entry["sensors"]["engine"] == "pallas"
        w.degraded = {}
        await _ticks(t, 2)
        assert w.osd_dump["osds"][1]["primary_affinity"] == 1.0
        assert w.tune.owned == {}
    run(go())


def test_shared_affinity_key_single_writer_per_tick():
    """An OSD both confirmed-slow AND permanently degraded: the two
    policies share the affinity actuator, and the per-tick dedupe
    keeps ONE writer (the responder) — one commit, one owner."""
    async def go():
        w = _World()
        w.status["osdmap"]["slow_osds"] = {"1": 6.0}
        w.degraded = {"1": {"ratio": 0.9, "engine": "pallas",
                            "phase": "permanent", "since": 0.0}}
        t = _tuner(w)
        await _ticks(t, 3)
        cmds = w.actuations("osd primary-affinity")
        assert len(cmds) == 1
        assert w.tune.owned["affinity:1"]["policy"] == \
            "gray_osd_responder"
        # heal BOTH sensors -> a single revert
        w.status["osdmap"]["slow_osds"] = {}
        w.degraded = {}
        await _ticks(t, 2)
        assert len(w.actuations("osd primary-affinity")) == 2
        assert w.tune.owned == {}
    run(go())


def test_change_budget_spreads_commits_across_ticks():
    """Three OSDs go slow at once against a budget of 2: the third
    commit lands one tick later (deferred, not dropped)."""
    async def go():
        w = _World(mgr_tuner_max_changes_per_tick=2)
        w.status["osdmap"]["slow_osds"] = {"0": 4.0, "1": 4.0,
                                           "2": 4.0}
        t = _tuner(w)
        await _ticks(t, 2)
        assert w.tune.committed == 2
        assert t.guardrails.deferred_total >= 1
        await _ticks(t, 1)
        assert w.tune.committed == 3
        affinity = {o["osd"]: o["primary_affinity"]
                    for o in w.osd_dump["osds"]}
        assert affinity == {0: 0.0, 1: 0.0, 2: 0.0}
    run(go())


def test_recovery_governor_levels():
    """The governor's level table, policy-direct (no tick loop):
    QoS-floor breach halves, backfill-with-headroom doubles toward
    the cap, drained backfill reverts to the registered default, and
    the steady state proposes nothing."""
    from ceph_tpu.utils.config import OPTIONS
    base = OPTIONS["osd_recovery_max_active"].default
    w = _World()
    t = _tuner(w)

    def gov(p99, bf, cur):
        w.config["osd_recovery_max_active"] = cur
        return t._recovery_governor(
            {"p99_ms": p99, "backfilling_pgs": bf})
    # breach: shed NOW, even below base
    props = gov(5000.0, 3, base)
    assert props[0].kind == "act"
    assert props[0].cmd["value"] == str(base // 2)
    # headroom + pending backfill: double
    props = gov(10.0, 2, base)
    assert props[0].cmd["value"] == str(base * 2)
    # capped
    w.config["mgr_tuner_recovery_max_active_cap"] = base * 2
    assert gov(10.0, 2, base * 2) == []
    # drained: revert to the registered default
    props = gov(None, 0, base * 4)
    assert props[0].kind == "revert"
    assert props[0].cmd["value"] == str(base)
    # steady: nothing
    assert gov(None, 0, base) == []
    # floor breach at 1 can't go lower
    assert gov(5000.0, 1, 1) == []


def test_tuner_progress_events_pair():
    """A drive-mode act renders a held ``tuner:<key>`` event in the
    ProgressModule's table; the revert completes it into the
    `progress json` ring."""
    async def go():
        from ceph_tpu.mgr.modules import ProgressModule
        w = _World()
        w.status["osdmap"]["slow_osds"] = {"2": 4.0}
        t = _tuner(w)
        prog = ProgressModule(t.mgr)
        t.mgr.modules.append(prog)
        await _ticks(t, 2)
        ev = prog.events.get("tuner:affinity:2")
        assert ev is not None and ev["fraction"] == 0.5
        assert "[gray_osd_responder]" in ev["message"]
        # the progress module's own derivation must not sweep the
        # foreign tuner event
        prog._derive(w.status, w.pg_dump, 1.0)
        assert "tuner:affinity:2" in prog.events
        w.status["osdmap"]["slow_osds"] = {}
        await _ticks(t, 2)
        assert "tuner:affinity:2" not in prog.events
        assert any(e["id"] == "tuner:affinity:2"
                   for e in prog.completed)
    run(go())


# -- the storm acceptance (the ONE cluster spin in this module) ------------

def test_tuner_closed_loop_storm():
    """Closed loop on a live cluster, drive mode, one spin:

    - a steady balanced two-pool workload commits ZERO actions;
    - a hot-pool burst trips the protector — a guardrailed
      client-profile commit whose audit entry carries the sensor
      readings that justified it, visible in `ceph progress ls`;
    - the heal removes the owned profile (act/revert pair);
    - a mgr failover mid-storm promotes a standby whose tuner resumes
      WITHOUT double-committing, and the revert after the storm is
      the promoted incarnation's.
    """
    async def go():
        from ceph_tpu.cluster.vstart import Cluster
        from ceph_tpu.mgr.modules import ProgressModule
        from ceph_tpu.msg import Keyring
        from ceph_tpu.rados import Rados
        from ceph_tpu.sim.thrasher import Thrasher
        c = await Cluster(
            n_mons=1, n_osds=3, n_mgrs=2,
            mgr_modules=[ProgressModule, TunerModule],
            config={
                "osd_client_message_cap": 4,
                "osd_op_queue": "mclock",
                # fresh counts in every rate window (the tick must
                # never see two identical pg dumps mid-burst, or the
                # consecutive-breach streak resets)
                "osd_stats_interval": 0.1,
                # off during boot/teardown; flipped live below
                "mgr_tuner_mode": "off",
                "mgr_tuner_interval": 0.25,
                "mgr_tuner_act_ticks": 3,
                "mgr_tuner_revert_ticks": 3,
                "mgr_tuner_hot_pool_min_ops": 30.0,
                "mgr_tuner_hot_pool_ratio": 4.0,
                # keep the recovery governor out of the frame: no
                # backfill here, and no latency under a 30 s op
                # timeout can breach this floor
                "mgr_tuner_qos_floor_ms": 60000.0,
            }).start()
        try:
            await c.client.pool_create("cold", pg_num=4)
            await c.client.pool_create("hot", pg_num=4)
            await c.wait_for_clean(timeout=120)
            ret, rs, out = await c.client.mon_command(
                {"prefix": "auth get-or-create",
                 "entity": "client.hot"})
            assert ret == 0, rs
            key = bytes.fromhex(json.loads(out)["key"])
            hot = Rados(c.monmap, name="client.hot",
                        keyring=Keyring({"client.hot": key}),
                        config=c.cfg)
            await hot.connect()
            io_hot = await hot.open_ioctx("hot")
            io_cold = await c.client.open_ioctx("cold")
            for i in range(4):           # warm both write paths
                await io_cold.write_full(f"w-{i}", b"w" * 256,
                                         timeout=30.0)
                await io_hot.write_full(f"w-{i}", b"w" * 256,
                                        timeout=30.0)

            async def tune_status():
                ret, _, out = await c.client.mon_command(
                    {"prefix": "tune status"})
                assert ret == 0
                return json.loads(out)

            # -- steady: balanced trickle, drive mode, ZERO commits --
            c.cfg["mgr_tuner_mode"] = "drive"
            for i in range(12):
                await io_cold.write_full(f"s-{i}", b"s" * 256,
                                         timeout=30.0)
                await io_hot.write_full(f"s-{i}", b"s" * 256,
                                        timeout=30.0)
                await asyncio.sleep(0.04)
            await asyncio.sleep(1.2)     # several tuner ticks
            st = await tune_status()
            assert st["mode"] == "drive"
            assert st["committed"] == 0 and st["reverted"] == 0, st

            # -- storm 1: the protector trips and heals --------------
            th = Thrasher(c, seed=17)
            storm = await th.tuner_storm(io_cold, io_hot, writes=24,
                                         hot_parallel=4,
                                         hot_burst=16, ramp_s=1.0)
            assert storm["cold_errors"] == 0
            assert storm["tuner"]["committed"] >= 1, storm
            log_entries = (await c.client.mon_command(
                {"prefix": "tune log"}))[2]
            entries = json.loads(log_entries)["entries"]
            act = next(e for e in entries
                       if e["policy"] == "hot_pool_protector" and
                       e["action"] == "act")
            assert act["committed"] is True
            assert act["sensors"]["entity"] == "client.hot"
            assert act["sensors"]["hot_pool_rate"] > 0
            assert act["cmd"]["prefix"] == "osd client-profile"
            # heal: the owned profile comes off within the revert
            # window once the burst stops
            deadline = asyncio.get_event_loop().time() + 20.0
            while True:
                st = await tune_status()
                if not st["owned"]:
                    break
                assert asyncio.get_event_loop().time() < deadline, \
                    f"tuner never reverted: {st}"
                await asyncio.sleep(0.2)
            assert st["reverted"] >= 1

            # -- storm 2: mgr failover mid-storm, no double-commit ---
            base = await tune_status()
            storm_task = asyncio.ensure_future(
                th.tuner_storm(io_cold, io_hot, writes=24,
                               hot_parallel=4, hot_burst=16,
                               ramp_s=1.0, cold_think_s=0.05))
            deadline = asyncio.get_event_loop().time() + 25.0
            while True:                  # wait for the commit to land
                st = await tune_status()
                if st["committed"] > base["committed"]:
                    break
                assert asyncio.get_event_loop().time() < deadline, \
                    "storm 2 never tripped the protector"
                await asyncio.sleep(0.2)
            committed_mid = st["committed"]
            assert "profile:client.hot" in st["owned"]
            # the in-flight act renders as a held tuner event in
            # `ceph progress ls` (the mgr digests it monward)
            deadline = asyncio.get_event_loop().time() + 5.0
            while True:
                ret, _, pout = await c.client.mon_command(
                    {"prefix": "progress ls"})
                evs = json.loads(pout)["events"]
                if any(e.get("id") == "tuner:profile:client.hot"
                       for e in evs):
                    break
                assert asyncio.get_event_loop().time() < deadline, \
                    f"no tuner progress event: {evs}"
                await asyncio.sleep(0.2)
            old = await c.kill_mgr()
            new = await c.wait_for_mgr_active(not_gid=old.gid,
                                              timeout=30)
            assert new is not None and new.gid != old.gid
            storm2 = await storm_task
            assert storm2["cold_errors"] == 0
            # the promoted tuner saw desired == actual: same commit
            # count, and the heal (its revert) still lands
            st = await tune_status()
            assert st["committed"] == committed_mid, st
            deadline = asyncio.get_event_loop().time() + 20.0
            while True:
                st = await tune_status()
                if not st["owned"]:
                    break
                assert asyncio.get_event_loop().time() < deadline, \
                    f"promoted tuner never reverted: {st}"
                await asyncio.sleep(0.2)
            assert st["committed"] == committed_mid
            c.cfg["mgr_tuner_mode"] = "off"
            await hot.shutdown()
        finally:
            await c.stop()
    run(go())
