"""EC pools through the live cluster (the ECBackend path).

ref test model: qa/standalone/erasure-code/test-erasure-code.sh +
test-erasure-eio.sh — EC pool I/O over the wire, degraded reads with a
shard OSD down, and shard reconstruction on revive.
"""

import asyncio

import numpy as np
import pytest

from ceph_tpu.cluster.vstart import Cluster
from ceph_tpu.rados import ObjectOperationError


def run(coro):
    asyncio.run(coro)


async def _ec_cluster(n_osds=4, k=2, m=1, config=None):
    c = await Cluster(n_mons=1, n_osds=n_osds,
                      config=dict({"mon_osd_down_out_interval": 2.0},
                                  **(config or {}))).start()
    ret, rs, _ = await c.client.mon_command(
        {"prefix": "osd erasure-code-profile set", "name": "kprof",
         "profile": [f"k={k}", f"m={m}", "crush-failure-domain=osd",
                     "stripe_unit=1024"]})
    assert ret == 0, rs
    ret, rs, _ = await c.client.mon_command(
        {"prefix": "osd pool create", "pool": "ecpool", "pg_num": 4,
         "pool_type": "erasure", "erasure_code_profile": "kprof"})
    assert ret == 0, rs
    # 240: this wait flakes under whole-suite CPU contention on the
    # 1-core CI host (observed at 120 with peering's up_thru round trip)
    await c.wait_for_clean(timeout=240)
    io = await c.client.open_ioctx("ecpool")
    return c, io


def test_ec_pool_io_roundtrip():
    async def go():
        c, io = await _ec_cluster()
        try:
            rng = np.random.default_rng(7)
            # full-stripe, sub-stripe, multi-stripe and unaligned writes
            cases = {
                "full": rng.integers(0, 256, 2048, dtype=np.uint8)
                .tobytes(),
                "small": b"tiny",
                "big": rng.integers(0, 256, 10000, dtype=np.uint8)
                .tobytes(),
            }
            for oid, data in cases.items():
                await io.write_full(oid, data)
                assert await io.read(oid) == data, oid
                assert await io.stat(oid) == len(data)
            # partial overwrite at an unaligned offset (the RMW path)
            await io.write("big", b"@" * 777, offset=1500)
            want = bytearray(cases["big"])
            want[1500:1500 + 777] = b"@" * 777
            assert await io.read("big") == bytes(want)
            # append past EOF
            await io.write("small", b"MORE", offset=4096)
            got = await io.read("small")
            assert got[:4] == b"tiny" and got[4096:] == b"MORE"
            assert got[4:4096] == b"\x00" * 4092
            # ranged read
            assert await io.read("big", length=100, offset=1500) == \
                b"@" * 100
            # xattr/omap ride the sub-ops
            await io.setxattr("big", "user.x", b"1")
            assert await io.getxattr("big", "user.x") == b"1"
            await io.set_omap("big", "mk", b"mv")
            assert await io.get_omap_vals("big") == {"mk": b"mv"}
            # shards are really spread: no single osd holds the object
            holders = [o.whoami for o in c.osds
                       for cid in o.store.list_collections()
                       if "big" in o.store.list_objects(cid)]
            assert len(holders) == 3      # k+m distinct shard osds
            # each shard holds ~size/k bytes, not the whole object
            for o in c.osds:
                for cid in o.store.list_collections():
                    if "big" in o.store.list_objects(cid):
                        shard = o.store.read(cid, "big")
                        assert len(shard) < 10000
            await io.remove("small")
            names = await io.list_objects()
            assert "small" not in names and "big" in names
        finally:
            await c.stop()
    run(go())


def test_ec_degraded_read_and_write():
    """One shard OSD down: reads decode around the hole, writes land on
    the survivors (k=2 m=1, min_size=k)."""
    async def go():
        c, io = await _ec_cluster(n_osds=3)
        try:
            rng = np.random.default_rng(3)
            data = rng.integers(0, 256, 6000, dtype=np.uint8).tobytes()
            await io.write_full("victim", data)
            # find an osd holding a shard and kill it
            holder = next(o.whoami for o in c.osds
                          for cid in o.store.list_collections()
                          if "victim" in o.store.list_objects(cid))
            await c.kill_osd(holder)
            await c.wait_for_osd_down(holder, timeout=60)
            # degraded read must decode via parity
            assert await io.read("victim") == data
            # degraded write (2 of 3 shards live = min_size)
            await io.write_full("during", b"degraded-write" * 10)
            assert await io.read("during") == b"degraded-write" * 10
        finally:
            await c.stop()
    run(go())


def test_ec_shard_reconstruction_on_revive():
    async def go():
        c, io = await _ec_cluster(n_osds=3)
        try:
            rng = np.random.default_rng(11)
            objs = {f"e{i}": rng.integers(0, 256, 3000,
                                          dtype=np.uint8).tobytes()
                    for i in range(4)}
            for oid, data in objs.items():
                await io.write_full(oid, data)
            await c.kill_osd(2)
            await c.wait_for_osd_down(2, timeout=60)
            # mutate while the shard osd is gone -> osd.2 goes stale
            objs["e0"] = b"replaced!" * 100
            await io.write_full("e0", objs["e0"])
            await io.write_full("new-while-down", b"N" * 2000)
            objs["new-while-down"] = b"N" * 2000
            await c.revive_osd(2)
            await c.wait_for_clean(timeout=120)
            # all data still reads back
            for oid, data in objs.items():
                assert await io.read(oid) == data, oid
            # osd.2's shards were reconstructed: every object whose PG
            # includes osd.2 has a local shard with the right version
            st = c.osds[2].store
            shard_objs = [o for cid in st.list_collections()
                          for o in st.list_objects(cid)
                          if o != "_pgmeta_"]
            assert shard_objs, "osd.2 recovered no shards"
        finally:
            await c.stop()
    run(go())


def test_ec_write_survives_position_shuffle():
    """A write landing in the TRANSIENT interval after an auto-out
    remap (a surviving OSD shifted to a different acting position)
    must stay readable — and regain full redundancy — once the
    revived OSD shifts the positions back.

    Without position-stamped shards (`_pos` attr, pos-keyed gather)
    the shifted survivor's bytes were later misread as the shard of
    its OLD position and the revived OSD's rebuild decoded zeros —
    silent corruption of the tail of every affected object."""
    async def go():
        c, io = await _ec_cluster(n_osds=3)
        try:
            await io.write_full("pre", b"P" * 2000)
            await c.kill_osd(2)
            # deterministic down-wait: heartbeat-failure detection is
            # timing-dependent and flaked under whole-suite load
            # ("osd.2 still up" — reporter tasks starved past the
            # 60 s wait). The daemon is already hard-stopped, so mark
            # it down by mon command and wait only for the map commit
            # — what the test needs is the DOWN map, not the
            # detection latency (covered by the heartbeat tests).
            ret, rs, _ = await c.client.mon_command(
                {"prefix": "osd down", "id": 2})
            assert ret == 0, rs
            await c.wait_for_osd_down(2, timeout=60)
            # wait past mon_osd_down_out_interval (2.0s in _ec_cluster)
            # so the OUT remap lands: acting positions shuffle among
            # the two survivors
            deadline = asyncio.get_event_loop().time() + 30.0
            lead = c.leader()
            while lead.osdmon.osdmap.osd_weight[2] > 0:
                assert asyncio.get_event_loop().time() < deadline, \
                    "osd.2 never auto-outed"
                await asyncio.sleep(0.1)
            await asyncio.sleep(0.5)        # let re-peering settle
            # writes INSIDE the shuffled interval
            await io.write_full("shuffled", b"S" * 2000,
                                timeout=60.0)
            await io.write_full("pre", b"Q" * 2000, timeout=60.0)
            await c.revive_osd(2)           # positions shuffle back
            await c.wait_for_clean(timeout=120)
            assert await io.read("shuffled") == b"S" * 2000
            assert await io.read("pre") == b"Q" * 2000
            # redundancy restored: within a grace window every live
            # holder's shard is stamped for its CURRENT position
            deadline = asyncio.get_event_loop().time() + 30.0
            while True:
                stale = []
                for o in c.osds:
                    if o._stopped:
                        continue
                    for pgid_s, pg in o.pgs.items():
                        if not hasattr(pg, "_stored_pos"):
                            continue
                        my = pg.my_shard()
                        if my < 0:
                            continue
                        for oid in o.store.list_objects(pg.cid):
                            if oid == "_pgmeta_":
                                continue
                            sp = pg._stored_pos(oid)
                            if 0 <= sp != my:
                                stale.append((o.whoami, pgid_s, oid,
                                              sp, my))
                if not stale:
                    break
                assert asyncio.get_event_loop().time() < deadline, \
                    f"position-stale shards never healed: {stale}"
                await asyncio.sleep(0.5)
        finally:
            await c.stop()
    run(go())


# -- round 13: the cross-op encode aggregator at cluster scope -------------

def _shard_map(c, oid):
    """{position: (stored bytes, _hcrc attr, _size attr)} across every
    live OSD holding a shard of ``oid``."""
    out = {}
    for o in c.osds:
        if o._stopped:
            continue
        for cid in o.store.list_collections():
            if oid not in o.store.list_objects(cid):
                continue
            attrs = o.store.getattrs(cid, oid)
            pos = int.from_bytes(attrs["_pos"], "little", signed=True)
            out[pos] = (o.store.read(cid, oid),
                        attrs.get("_hcrc", b""), attrs["_size"])
    return out


def test_ec_agg_concurrent_writes_acceptance(tmp_path):
    """Round 13 acceptance, one cluster spin: under concurrent
    multi-op EC writes through the aggregator (a) acked data reads
    back bit-identical and deep scrub verifies parity clean, (b) the
    fused ``_hcrc`` stamps equal host zlib.crc32 of every STORED
    shard, (c) p99 op latency never regresses past the configured
    batching window vs the live-flipped ``osd_ec_agg=off`` baseline,
    and (d) a randomized edit stream produces byte-identical shards
    (data, parity, attrs) through the aggregated and per-op paths."""
    async def go():
        import zlib

        from ceph_tpu.utils.admin_socket import daemon_command
        window_s = 0.02
        c, io = await _ec_cluster(n_osds=4, config={
            "osd_ec_agg_window_us": window_s * 1e6,
            "admin_socket_dir": str(tmp_path)})
        try:
            rng = np.random.default_rng(1313)

            async def burst(tag, n=12):
                """n concurrent whole-object writes; returns
                ({oid: payload}, sorted per-op latencies)."""
                payloads = {
                    f"{tag}-{i}": rng.integers(
                        0, 256, int(rng.integers(1500, 6000)),
                        dtype=np.uint8).tobytes()
                    for i in range(n)}
                lats = []

                async def one(oid, data):
                    t0 = asyncio.get_event_loop().time()
                    await io.write_full(oid, data, timeout=60.0)
                    lats.append(
                        asyncio.get_event_loop().time() - t0)
                await asyncio.gather(*[one(o, d)
                                       for o, d in payloads.items()])
                return payloads, sorted(lats)

            # warm both paths' kernels outside any timed burst
            await burst("warm", n=4)
            c.cfg["osd_ec_agg"] = False
            await burst("warmoff", n=4)
            c.cfg["osd_ec_agg"] = True

            # (a) aggregated concurrent burst: bit-identical readback
            on_payloads, on_lats = await burst("agg")
            for oid, data in on_payloads.items():
                assert await io.read(oid) == data, oid
            agg_totals = {}
            for o in c.osds:
                for k_, v in o.ec_agg.dump().items():
                    if isinstance(v, (int, float)):
                        agg_totals[k_] = agg_totals.get(k_, 0) + v
            assert agg_totals["batches"] >= 1
            assert agg_totals["ops"] >= len(on_payloads)
            # the asok status surfaces the block (canned guard rides
            # test_meta's render checks; this pins the live daemon)
            live = next(o for o in c.osds if not o._stopped)
            st = await daemon_command(
                f"{tmp_path}/osd.{live.whoami}.asok", "status")
            assert st["ec_agg"]["enabled"] is True
            assert st["ec_agg"]["window_us"] == window_s * 1e6

            # (b) fused _hcrc stamps == host zlib of the STORED bytes
            checked = 0
            for oid in on_payloads:
                for pos, (data, hcrc, _sz) in \
                        _shard_map(c, oid).items():
                    assert hcrc, (oid, pos)
                    assert hcrc == zlib.crc32(data).to_bytes(
                        4, "little"), (oid, pos)
                    checked += 1
            assert checked >= 3 * len(on_payloads)

            # ...and deep scrub agrees the parity is sound
            scrubbed = set()
            for o in c.osds:
                for pg in o.pgs.values():
                    if not pg.is_primary() or pg.cid in scrubbed:
                        continue
                    if not (set(on_payloads) &
                            set(o.store.list_objects(pg.cid))):
                        continue
                    scrubbed.add(pg.cid)
                    await pg.scrubber.scrub(deep=True)
                    assert pg.scrub_errors == 0, pg.cid
            assert scrubbed

            # (c) per-op baseline burst (osd_ec_agg=off, read LIVE):
            # p99 with the aggregator must not regress past the
            # batching window (+ CI scheduling slack on this 1-core
            # host — the bound still catches an op pinned to a
            # multi-window wait)
            c.cfg["osd_ec_agg"] = False
            off_payloads, off_lats = await burst("off")
            for oid, data in off_payloads.items():
                assert await io.read(oid) == data, oid
            p99_on = on_lats[int(0.99 * (len(on_lats) - 1))]
            p99_off = off_lats[int(0.99 * (len(off_lats) - 1))]
            assert p99_on <= p99_off + window_s + 0.75, \
                (p99_on, p99_off)

            # (d) randomized edit stream: per-op vs aggregated paths
            # produce IDENTICAL shards — data, parity, _hcrc, _size
            async def edit_stream(oid, seed):
                r = np.random.default_rng(seed)
                size = 4096
                await io.write_full(oid, r.integers(
                    0, 256, size, dtype=np.uint8).tobytes(),
                    timeout=60.0)
                for _ in range(6):
                    off = int(r.integers(0, size))
                    ln = int(r.integers(1, 1500))
                    await io.write(oid, r.integers(
                        0, 256, ln, dtype=np.uint8).tobytes(),
                        offset=off, timeout=60.0)
                final = r.integers(0, 256, 5000,
                                   dtype=np.uint8).tobytes()
                await io.write_full(oid, final, timeout=60.0)
                return final

            want_off = await edit_stream("edit-off", 77)   # agg off
            c.cfg["osd_ec_agg"] = True
            want_on = await edit_stream("edit-on", 77)     # agg on
            assert want_on == want_off
            assert await io.read("edit-on") == want_on
            assert await io.read("edit-off") == want_off
            s_on = _shard_map(c, "edit-on")
            s_off = _shard_map(c, "edit-off")
            assert set(s_on) == set(s_off) and len(s_on) == 3
            for pos in s_on:
                assert s_on[pos] == s_off[pos], pos
        finally:
            await c.stop()
    run(go())


# -- round 19: the read-side data plane at cluster scope -------------------

def test_ec_read_agg_cluster_acceptance():
    """Round 19 acceptance, one cluster spin: (a) deep scrub runs as
    ONE device CRC job per scrub-map/parity-check batch — O(batches),
    not O(objects) — with zero host-CRC fallbacks and zero scrub
    errors; (b) a degraded-read storm decodes through the read
    aggregator bit-identically; (c) repeat reads of unchanged objects
    hit the device-resident shard cache; (d) the live
    ``osd_ec_read_agg=off`` flip serves the same bytes through the
    unbatched bypass; (e) the revive-rebuild's repair decode charges a
    recovery-class QoS grant inside the aggregator, and a cold-tenant
    fleet riding through the repair churn sees zero errors with p99
    held near its pre-failure baseline (repair competes under the
    scheduler, not around it)."""
    async def go():
        from ceph_tpu.osd.scrub import SCRUB_PERF
        from ceph_tpu.sim.loadgen import LoadGen

        # down_out high: the dead OSD must stay IN so the storm keeps
        # decoding (an auto-out remap with k+m == n_osds would let
        # rebuild-to-survivor erase the degradedness mid-test)
        c, io = await _ec_cluster(n_osds=3, config={
            "mon_osd_down_out_interval": 600.0,
            "osd_ec_resident_bytes": 8 << 20})
        try:
            rng = np.random.default_rng(1919)
            objs = {f"d-{i}": rng.integers(
                0, 256, int(rng.integers(2000, 6000)),
                dtype=np.uint8).tobytes() for i in range(10)}
            for oid, data in objs.items():
                await io.write_full(oid, data, timeout=60.0)

            def ragg_totals():
                out = {}
                for o in c.osds:
                    if o._stopped:
                        continue
                    for k_, v in o.ec_read_agg.perf.dump().items():
                        if isinstance(v, (int, float)):
                            out[k_] = out.get(k_, 0) + v
                return out

            # (a) deep scrub: every per-object digest rides batched
            # device CRC jobs — bounded by scrub maps (k+m holders)
            # + one parity re-check per PG, independent of how many
            # objects each PG carries
            s0 = SCRUB_PERF.dump()
            scrubbed = set()
            for o in c.osds:
                for pg in o.pgs.values():
                    if not pg.is_primary() or pg.cid in scrubbed:
                        continue
                    if not (set(objs) &
                            set(o.store.list_objects(pg.cid))):
                        continue
                    scrubbed.add(pg.cid)
                    await pg.scrubber.scrub(deep=True)
                    assert pg.scrub_errors == 0, pg.cid
            assert scrubbed
            s1 = SCRUB_PERF.dump()
            dj = s1["device_crc_jobs"] - s0["device_crc_jobs"]
            assert 1 <= dj <= 4 * len(scrubbed), (dj, len(scrubbed))
            assert s1["device_crc_rows"] > s0["device_crc_rows"]
            assert s1["host_crc_objects"] == s0["host_crc_objects"], \
                "scrub fell back to per-object host CRCs"

            # cold-tenant baseline on the healthy cluster — the p99
            # yardstick for the repair-churn leg in (e)
            base = await LoadGen(
                c, "ecpool", sessions=20, clients=2,
                ops_per_session=3, write_bytes=512,
                concurrency=8, op_timeout=60.0, seed=19).run()
            assert base["errors"] == 0, base["error_samples"]

            # (b) kill a DATA-shard holder of d-0 (killing the parity
            # holder would leave reads decode-free) and storm reads.
            # NON-primary: peering re-adopts a revived primary's stale
            # log as authoritative and rolls back the phase-(d)
            # overwrite committed while it was down (pre-existing
            # weakness, noted in ROADMAP follow-ups) — with k data
            # shards on distinct OSDs a non-primary data holder
            # always exists
            holder = next(
                o.whoami for o in c.osds
                for cid in o.store.list_collections()
                if "d-0" in o.store.list_objects(cid)
                and int.from_bytes(
                    o.store.getattrs(cid, "d-0")["_pos"],
                    "little", signed=True) < 2
                and not (o.pgs.get(str(cid)) is not None
                         and o.pgs[str(cid)].is_primary()))
            await c.kill_osd(holder)
            ret, rs, _ = await c.client.mon_command(
                {"prefix": "osd down", "id": holder})
            assert ret == 0, rs
            await c.wait_for_osd_down(holder, timeout=60)
            r0 = ragg_totals()
            got = await asyncio.gather(*[io.read(oid)
                                         for oid in objs])
            assert dict(zip(objs, got)) == objs
            r1 = ragg_totals()
            assert r1["ops"] - r0.get("ops", 0) >= 1
            assert r1["batches"] - r0.get("batches", 0) >= 1

            # (c) unchanged objects re-read from the resident cache
            got = await asyncio.gather(*[io.read(oid)
                                         for oid in objs])
            assert dict(zip(objs, got)) == objs
            hits = sum(o.ec_resident.perf.dump()["hits"]
                       for o in c.osds if not o._stopped)
            assert hits >= 1

            # (d) live off-flip: a fresh version (cache-unreachable)
            # decodes through the unbatched bypass, same bytes
            c.cfg["osd_ec_read_agg"] = False
            objs["d-0"] = b"flipped!" * 300
            await io.write_full("d-0", objs["d-0"], timeout=60.0)
            assert await io.read("d-0") == objs["d-0"]
            r2 = ragg_totals()
            assert r2["bypass"] - r1.get("bypass", 0) >= 1
            c.cfg["osd_ec_read_agg"] = True

            # (e) revive: rebuilding the stale shard decodes with
            # repair=True — the recovery-class QoS grant lands in the
            # aggregator's counter — while a cold-tenant fleet rides
            # through the repair churn error-free, p99 bounded. Slack
            # is generous (post-revive peering legitimately parks ops
            # for a few seconds on 1-core CI); repair running AROUND
            # the scheduler would park at op_timeout scale
            await c.revive_osd(holder)
            cold, _ = await asyncio.gather(
                LoadGen(c, "ecpool", sessions=20, clients=2,
                        ops_per_session=3, write_bytes=512,
                        concurrency=8, op_timeout=60.0,
                        seed=20).run(),
                c.wait_for_clean(timeout=240))
            assert cold["errors"] == 0, cold["error_samples"]
            assert cold["p99_ms"] <= base["p99_ms"] + 10_000.0, \
                (cold["p99_ms"], base["p99_ms"])
            for oid, data in objs.items():
                assert await io.read(oid) == data, oid
            r3 = ragg_totals()
            assert r3["qos_grants"] - r0.get("qos_grants", 0) >= 1
        finally:
            await c.stop()
    run(go())


def test_ec_killed_primary_overwrites_survive_revive():
    """Killed-primary acceptance: kill -9 the PG primary, overwrite
    the object several generations while it is down, revive it. The
    revived primary's stale log must NOT win peering back — every
    while-down overwrite stays committed and the log heads of all
    live holders converge."""
    async def go():
        c, io = await _ec_cluster(
            n_osds=3, config={"mon_osd_down_out_interval": 600.0})
        try:
            rng = np.random.default_rng(1919)
            objs = {f"d-{i}": rng.integers(
                0, 256, int(rng.integers(2000, 6000)),
                dtype=np.uint8).tobytes() for i in range(6)}
            for oid, data in objs.items():
                await io.write_full(oid, data, timeout=60.0)
            prim = cid0 = None
            for o in c.osds:
                for cid in o.store.list_collections():
                    if "d-0" in o.store.list_objects(cid):
                        pg = o.pgs.get(str(cid))
                        if pg is not None and pg.is_primary():
                            prim, cid0 = o.whoami, cid
            assert prim is not None
            await c.kill_osd(prim)
            await c.client.mon_command(
                {"prefix": "osd down", "id": prim})
            await c.wait_for_osd_down(prim, timeout=60)
            # several overwrite generations while the primary is down
            for gen in range(3):
                objs["d-0"] = bytes([65 + gen]) * (2000 + gen * 500)
                await io.write_full("d-0", objs["d-0"], timeout=60.0)
            objs["while-down"] = b"W" * 3000
            await io.write_full("while-down", objs["while-down"],
                                timeout=60.0)
            await c.revive_osd(prim)
            await c.wait_for_clean(timeout=240)
            for oid, data in objs.items():
                assert await io.read(oid, timeout=60.0) == data, oid
            heads = {o.whoami: tuple(o.pgs[str(cid0)].pg_log.head)
                     for o in c.osds
                     if not o._stopped and str(cid0) in o.pgs}
            assert len(set(heads.values())) == 1, heads
        finally:
            await c.stop()
    run(go())


def test_ec_revived_primary_divergent_entry_rolls_back():
    """THE stale-primary-log pin (find_best_info by (les, head)): a
    write that logs on the primary but commits on fewer than k shards
    (both replica sub-writes dropped) leaves a DIVERGENT log entry
    whose version outranks everything the surviving interval has —
    the survivors take NO writes, so their head stays at the last
    committed version and a head-only election would hand authority
    back to the revived primary, resurrecting a write whose client
    was told it FAILED. The survivors' activation (recorded as
    last_epoch_started) must out-rank the divergent head, the entry
    must roll back, and reads must serve the committed bytes."""
    async def go():
        c, io = await _ec_cluster(
            n_osds=3, config={"mon_osd_down_out_interval": 600.0})
        try:
            committed = b"committed" * 500
            await io.write_full("obj", committed, timeout=60.0)
            prim = cid0 = None
            for o in c.osds:
                for cid in o.store.list_collections():
                    if "obj" in o.store.list_objects(cid):
                        pg = o.pgs.get(str(cid))
                        if pg is not None and pg.is_primary():
                            prim, cid0 = o.whoami, cid
            assert prim is not None
            # drop BOTH replicas' sub-writes: the next write appends
            # to the primary's log but can never reach k durable
            # shards — the client is told -EIO, yet the entry (and the
            # primary's own shard bytes) linger in its store
            patched = []
            for o in c.osds:
                if o.whoami == prim or o._stopped:
                    continue
                pg = o.pgs.get(str(cid0))
                if pg is not None:
                    patched.append((pg, pg.handle_ec_sub_write))
                    pg.handle_ec_sub_write = lambda m: None
            with pytest.raises(ObjectOperationError):
                await io.write_full("obj", b"never-committed" * 400,
                                    timeout=60.0)
            for pg, orig in patched:
                pg.handle_ec_sub_write = orig
            old_primary_pg = c.osds[prim].pgs[str(cid0)]
            divergent_head = old_primary_pg.pg_log.head
            await c.kill_osd(prim)
            await c.client.mon_command(
                {"prefix": "osd down", "id": prim})
            await c.wait_for_osd_down(prim, timeout=60)
            # survivors peer and ACTIVATE a new interval — crucially
            # with NO writes: their head stays at the committed
            # version, strictly BELOW the divergent entry. A head-only
            # election would elect the revived primary's log here.
            # Degraded reads prove the survivors activated and serve
            # the committed bytes.
            assert await io.read("obj", timeout=60.0) == committed
            # revive: the old primary re-wins primariness (same crush
            # position) with the higher-versioned divergent log
            await c.revive_osd(prim)
            await c.wait_for_clean(timeout=240)
            # the never-committed write stays dead
            got = await io.read("obj", timeout=60.0)
            assert got == committed, (len(got), got[:20])
            # the revived holder's log adopted the survivors' head and
            # dropped the divergent entry
            heads = {o.whoami: tuple(o.pgs[str(cid0)].pg_log.head)
                     for o in c.osds
                     if not o._stopped and str(cid0) in o.pgs}
            assert len(set(heads.values())) == 1, heads
            assert heads[prim] != tuple(divergent_head), heads
            # and a deep scrub over the PG finds nothing to repair
            revived = c.osds[prim].pgs[str(cid0)]
            if revived.is_primary():
                res = await revived.scrubber.scrub(deep=True)
                assert res["errors"] == [], res
        finally:
            await c.stop()
    run(go())
