"""EC pools through the live cluster (the ECBackend path).

ref test model: qa/standalone/erasure-code/test-erasure-code.sh +
test-erasure-eio.sh — EC pool I/O over the wire, degraded reads with a
shard OSD down, and shard reconstruction on revive.
"""

import asyncio

import numpy as np
import pytest

from ceph_tpu.cluster.vstart import Cluster


def run(coro):
    asyncio.run(coro)


async def _ec_cluster(n_osds=4, k=2, m=1):
    c = await Cluster(n_mons=1, n_osds=n_osds,
                      config={"mon_osd_down_out_interval": 2.0}).start()
    ret, rs, _ = await c.client.mon_command(
        {"prefix": "osd erasure-code-profile set", "name": "kprof",
         "profile": [f"k={k}", f"m={m}", "crush-failure-domain=osd",
                     "stripe_unit=1024"]})
    assert ret == 0, rs
    ret, rs, _ = await c.client.mon_command(
        {"prefix": "osd pool create", "pool": "ecpool", "pg_num": 4,
         "pool_type": "erasure", "erasure_code_profile": "kprof"})
    assert ret == 0, rs
    # 240: this wait flakes under whole-suite CPU contention on the
    # 1-core CI host (observed at 120 with peering's up_thru round trip)
    await c.wait_for_clean(timeout=240)
    io = await c.client.open_ioctx("ecpool")
    return c, io


def test_ec_pool_io_roundtrip():
    async def go():
        c, io = await _ec_cluster()
        try:
            rng = np.random.default_rng(7)
            # full-stripe, sub-stripe, multi-stripe and unaligned writes
            cases = {
                "full": rng.integers(0, 256, 2048, dtype=np.uint8)
                .tobytes(),
                "small": b"tiny",
                "big": rng.integers(0, 256, 10000, dtype=np.uint8)
                .tobytes(),
            }
            for oid, data in cases.items():
                await io.write_full(oid, data)
                assert await io.read(oid) == data, oid
                assert await io.stat(oid) == len(data)
            # partial overwrite at an unaligned offset (the RMW path)
            await io.write("big", b"@" * 777, offset=1500)
            want = bytearray(cases["big"])
            want[1500:1500 + 777] = b"@" * 777
            assert await io.read("big") == bytes(want)
            # append past EOF
            await io.write("small", b"MORE", offset=4096)
            got = await io.read("small")
            assert got[:4] == b"tiny" and got[4096:] == b"MORE"
            assert got[4:4096] == b"\x00" * 4092
            # ranged read
            assert await io.read("big", length=100, offset=1500) == \
                b"@" * 100
            # xattr/omap ride the sub-ops
            await io.setxattr("big", "user.x", b"1")
            assert await io.getxattr("big", "user.x") == b"1"
            await io.set_omap("big", "mk", b"mv")
            assert await io.get_omap_vals("big") == {"mk": b"mv"}
            # shards are really spread: no single osd holds the object
            holders = [o.whoami for o in c.osds
                       for cid in o.store.list_collections()
                       if "big" in o.store.list_objects(cid)]
            assert len(holders) == 3      # k+m distinct shard osds
            # each shard holds ~size/k bytes, not the whole object
            for o in c.osds:
                for cid in o.store.list_collections():
                    if "big" in o.store.list_objects(cid):
                        shard = o.store.read(cid, "big")
                        assert len(shard) < 10000
            await io.remove("small")
            names = await io.list_objects()
            assert "small" not in names and "big" in names
        finally:
            await c.stop()
    run(go())


def test_ec_degraded_read_and_write():
    """One shard OSD down: reads decode around the hole, writes land on
    the survivors (k=2 m=1, min_size=k)."""
    async def go():
        c, io = await _ec_cluster(n_osds=3)
        try:
            rng = np.random.default_rng(3)
            data = rng.integers(0, 256, 6000, dtype=np.uint8).tobytes()
            await io.write_full("victim", data)
            # find an osd holding a shard and kill it
            holder = next(o.whoami for o in c.osds
                          for cid in o.store.list_collections()
                          if "victim" in o.store.list_objects(cid))
            await c.kill_osd(holder)
            await c.wait_for_osd_down(holder, timeout=60)
            # degraded read must decode via parity
            assert await io.read("victim") == data
            # degraded write (2 of 3 shards live = min_size)
            await io.write_full("during", b"degraded-write" * 10)
            assert await io.read("during") == b"degraded-write" * 10
        finally:
            await c.stop()
    run(go())


def test_ec_shard_reconstruction_on_revive():
    async def go():
        c, io = await _ec_cluster(n_osds=3)
        try:
            rng = np.random.default_rng(11)
            objs = {f"e{i}": rng.integers(0, 256, 3000,
                                          dtype=np.uint8).tobytes()
                    for i in range(4)}
            for oid, data in objs.items():
                await io.write_full(oid, data)
            await c.kill_osd(2)
            await c.wait_for_osd_down(2, timeout=60)
            # mutate while the shard osd is gone -> osd.2 goes stale
            objs["e0"] = b"replaced!" * 100
            await io.write_full("e0", objs["e0"])
            await io.write_full("new-while-down", b"N" * 2000)
            objs["new-while-down"] = b"N" * 2000
            await c.revive_osd(2)
            await c.wait_for_clean(timeout=120)
            # all data still reads back
            for oid, data in objs.items():
                assert await io.read(oid) == data, oid
            # osd.2's shards were reconstructed: every object whose PG
            # includes osd.2 has a local shard with the right version
            st = c.osds[2].store
            shard_objs = [o for cid in st.list_collections()
                          for o in st.list_objects(cid)
                          if o != "_pgmeta_"]
            assert shard_objs, "osd.2 recovered no shards"
        finally:
            await c.stop()
    run(go())


def test_ec_write_survives_position_shuffle():
    """A write landing in the TRANSIENT interval after an auto-out
    remap (a surviving OSD shifted to a different acting position)
    must stay readable — and regain full redundancy — once the
    revived OSD shifts the positions back.

    Without position-stamped shards (`_pos` attr, pos-keyed gather)
    the shifted survivor's bytes were later misread as the shard of
    its OLD position and the revived OSD's rebuild decoded zeros —
    silent corruption of the tail of every affected object."""
    async def go():
        c, io = await _ec_cluster(n_osds=3)
        try:
            await io.write_full("pre", b"P" * 2000)
            await c.kill_osd(2)
            await c.wait_for_osd_down(2, timeout=60)
            # wait past mon_osd_down_out_interval (2.0s in _ec_cluster)
            # so the OUT remap lands: acting positions shuffle among
            # the two survivors
            deadline = asyncio.get_event_loop().time() + 30.0
            lead = c.leader()
            while lead.osdmon.osdmap.osd_weight[2] > 0:
                assert asyncio.get_event_loop().time() < deadline, \
                    "osd.2 never auto-outed"
                await asyncio.sleep(0.1)
            await asyncio.sleep(0.5)        # let re-peering settle
            # writes INSIDE the shuffled interval
            await io.write_full("shuffled", b"S" * 2000,
                                timeout=60.0)
            await io.write_full("pre", b"Q" * 2000, timeout=60.0)
            await c.revive_osd(2)           # positions shuffle back
            await c.wait_for_clean(timeout=120)
            assert await io.read("shuffled") == b"S" * 2000
            assert await io.read("pre") == b"Q" * 2000
            # redundancy restored: within a grace window every live
            # holder's shard is stamped for its CURRENT position
            deadline = asyncio.get_event_loop().time() + 30.0
            while True:
                stale = []
                for o in c.osds:
                    if o._stopped:
                        continue
                    for pgid_s, pg in o.pgs.items():
                        if not hasattr(pg, "_stored_pos"):
                            continue
                        my = pg.my_shard()
                        if my < 0:
                            continue
                        for oid in o.store.list_objects(pg.cid):
                            if oid == "_pgmeta_":
                                continue
                            sp = pg._stored_pos(oid)
                            if 0 <= sp != my:
                                stale.append((o.whoami, pgid_s, oid,
                                              sp, my))
                if not stale:
                    break
                assert asyncio.get_event_loop().time() < deadline, \
                    f"position-stale shards never healed: {stale}"
                await asyncio.sleep(0.5)
        finally:
            await c.stop()
    run(go())
