"""PastIntervals: peering must consult PRIOR acting sets, not just the
current one.

ref test model: the reference's PastIntervals/build_prior machinery
(osd_types PastIntervals, PeeringState::build_prior) is what proves no
acknowledged write is lost across overlapping acting-set changes — the
canonical failure being acting A -> B -> A, where B acknowledged writes
while A's members were absent. Without it, A's members peer among
themselves, elect a stale log, and silently discard B's writes. These
tests steer acting sets deterministically with pg-upmap-items (the
balancer's own mechanism) so the A->B->A flip is exact, not thrashed.
"""

import asyncio

import pytest

from ceph_tpu.cluster.vstart import Cluster
from ceph_tpu.rados import ObjectOperationError


def run(coro):
    asyncio.run(coro)


async def _cluster():
    c = await Cluster(n_mons=1, n_osds=4,
                      config={"mon_osd_down_out_interval": 2.0}).start()
    # one PG so the acting set is a single steerable pair; min_size=1
    # so interval B can acknowledge writes on its own
    await c.client.pool_create("p", pg_num=1, size=2, min_size=1)
    await c.wait_for_clean(timeout=120)
    io = await c.client.open_ioctx("p")
    return c, io


def _acting(c, pool_id):
    for o in c.osds:
        if o._stopped:
            continue
        pg = o.pgs.get(f"{pool_id}.0")
        if pg is not None and pg.is_primary():
            return list(pg.acting)
    return []


async def _upmap_to(c, pool_id, pairs):
    maps = [str(x) for pair in pairs for x in pair]
    ret, rs, _ = await c.client.mon_command(
        {"prefix": "osd pg-upmap-items", "pgid": f"{pool_id}.0",
         "mappings": maps})
    assert ret == 0, rs


async def _rm_upmap(c, pool_id):
    ret, rs, _ = await c.client.mon_command(
        {"prefix": "osd rm-pg-upmap-items", "pgid": f"{pool_id}.0"})
    assert ret == 0, rs


async def _wait_acting(c, pool_id, want, timeout=60.0):
    """The upmap change must PROPAGATE before wait_for_clean means
    anything — the PG is still 'clean' under the old acting set."""
    deadline = asyncio.get_event_loop().time() + timeout
    while set(_acting(c, pool_id)) != set(want):
        assert asyncio.get_event_loop().time() < deadline, \
            (_acting(c, pool_id), want)
        await asyncio.sleep(0.1)


def test_acting_flip_does_not_lose_acked_writes():
    """A -> B -> A via upmap: a write acknowledged in interval B must
    survive the flip back to A. Fails on the single-interval model:
    A's members peer among themselves, elect the stale pre-B log, and
    serve the old data."""
    async def go():
        c, io = await _cluster()
        try:
            await io.write_full("obj", b"v1-interval-A")
            a = _acting(c, io.pool_id)
            assert len(a) == 2, a
            b = [o.whoami for o in c.osds if o.whoami not in a][:2]
            # interval B: remap both acting members
            await _upmap_to(c, io.pool_id, list(zip(a, b)))
            await _wait_acting(c, io.pool_id, b)
            await c.wait_for_clean(timeout=120)
            await io.write_full("obj", b"v2-interval-B")
            # back to A (the raw CRUSH mapping)
            await _rm_upmap(c, io.pool_id)
            await _wait_acting(c, io.pool_id, a)
            await c.wait_for_clean(timeout=120)
            assert await io.read("obj") == b"v2-interval-B", \
                "write acknowledged in interval B was lost on A->B->A"
        finally:
            await c.stop()
    run(go())


def test_down_past_interval_blocks_activation():
    """If EVERY member of a past interval is down, the PG must block
    peering (upstream 'down'/'incomplete') instead of activating with a
    possibly-stale log — and must activate with the newer data once one
    of them returns."""
    async def go():
        c, io = await _cluster()
        try:
            await io.write_full("obj", b"v1-interval-A")
            a = _acting(c, io.pool_id)
            b = [o.whoami for o in c.osds if o.whoami not in a][:2]
            await _upmap_to(c, io.pool_id, list(zip(a, b)))
            await _wait_acting(c, io.pool_id, b)
            await c.wait_for_clean(timeout=120)
            await io.write_full("obj", b"v2-interval-B")
            # kill BOTH of interval B's members; acting falls back to A
            for osd_id in b:
                await c.kill_osd(osd_id)
            for osd_id in b:
                await c.wait_for_osd_down(osd_id, timeout=30)
            await _rm_upmap(c, io.pool_id)
            # A must NOT activate: its only logs predate interval B
            await asyncio.sleep(2.0)
            pg_states = [o.pgs[f"{io.pool_id}.0"].state
                         for o in c.osds
                         if not o._stopped and
                         f"{io.pool_id}.0" in o.pgs and
                         o.pgs[f"{io.pool_id}.0"].is_primary()]
            assert all(s == "peering" for s in pg_states), pg_states
            with pytest.raises(ObjectOperationError):
                await io.read("obj", timeout=2.0)
            # the LAST-alive prior member returns (it covers both the
            # [b0,b1] interval and any transient singleton interval of
            # its own): peering completes with B's log
            await c.revive_osd(b[1])
            await c.wait_for_clean(timeout=120)
            assert await io.read("obj") == b"v2-interval-B"
        finally:
            await c.stop()
    run(go())
