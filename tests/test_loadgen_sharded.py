"""Worker-process loadgen sharding (round 18).

``run_sharded`` forks N worker processes, each running its own LoadGen
fleet over real client handles built from the cluster conf document,
and merges the reports with percentiles computed over the CONCATENATED
latency population (averaging per-worker p99s would hide a slow
shard). The tier-1 smoke runs the session-scale bar (10k) through ONE
forked worker — the whole path (conf hand-off, fork, stdin params,
merge) at one interpreter-startup of cost; the 8-worker 100k run is
``slow``.
"""

import asyncio
import time

import pytest

from ceph_tpu.cluster.vstart import Cluster
from ceph_tpu.sim.loadgen import run_sharded


def run(coro):
    asyncio.run(coro)


def test_loadgen_sharded_10k_one_worker():
    """10k sessions through one forked worker: zero errors, all ops
    acked, merged percentiles present and ordered."""
    async def go():
        c = await Cluster(n_mons=1, n_osds=3, config={
            "osd_client_message_cap": 1024}).start()
        try:
            await c.client.pool_create("load", pg_num=16)
            await c.wait_for_clean(timeout=240)
            t0 = time.perf_counter()
            # ops_per_session=1: the bar this smoke holds is SESSION
            # scale (10k logical sessions multiplexed over real
            # handles inside a forked worker), not op volume — one op
            # per session halves the tier-1 wall (the suite runs
            # against the 870 s cap; ROADMAP "budget new tests")
            report = await run_sharded(
                c, "load", sessions=10000, workers=1, clients=16,
                ops_per_session=1, write_bytes=128,
                concurrency=512, op_timeout=120.0)
            assert report["errors"] == 0, report["error_samples"]
            assert report["sessions"] == 10_000
            assert report["ops"] == 10_000
            assert report["workers"] == 1
            assert len(report["per_worker"]) == 1
            # merged tail stats come from the concatenated population
            assert report["p50_ms"] <= report["p99_ms"] <= \
                report["max_ms"]
            assert report["ops_per_s"] > 0
            print(f"sharded 10k/1w: {report['ops_per_s']} ops/s, "
                  f"p50 {report['p50_ms']} ms, "
                  f"p99 {report['p99_ms']} ms "
                  f"({time.perf_counter() - t0:.1f}s wall)")
        finally:
            await c.stop()
    run(go())


@pytest.mark.slow
def test_loadgen_sharded_100k_eight_workers():
    """The full-scale sharded harness: 100k sessions across 8 forked
    workers complete with zero errors and a coherent merged tail."""
    async def go():
        c = await Cluster(n_mons=1, n_osds=3, config={
            "osd_client_message_cap": 2048}).start()
        try:
            await c.client.pool_create("load", pg_num=32)
            await c.wait_for_clean(timeout=240)
            t0 = time.perf_counter()
            report = await run_sharded(
                c, "load", sessions=100_000, workers=8, clients=16,
                ops_per_session=2, write_bytes=128,
                concurrency=256, op_timeout=240.0)
            assert report["errors"] == 0, report["error_samples"]
            assert report["sessions"] == 100_000
            assert report["ops"] == 200_000
            assert report["workers"] == 8
            assert len(report["per_worker"]) == 8
            assert report["p50_ms"] <= report["p99_ms"] <= \
                report["max_ms"]
            # every shard contributed (the split is near-even)
            per = [r["ops"] for r in report["per_worker"]]
            assert min(per) > 0 and max(per) - min(per) <= \
                2 * 2  # sessions round by at most 1 -> ops by 2
            print(f"sharded 100k/8w: {report['ops_per_s']} ops/s, "
                  f"p99 {report['p99_ms']} ms "
                  f"({time.perf_counter() - t0:.1f}s wall)")
        finally:
            await c.stop()
    run(go())
