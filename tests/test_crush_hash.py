"""rjenkins hash + crush_ln tests (self-consistency, vector==scalar,
statistical quality of straw2 draws)."""

import numpy as np
import jax.numpy as jnp
import pytest

from ceph_tpu.crush import hash as h
from ceph_tpu.crush.ln_table import crush_ln, ll_table, rh_lh_tables


class TestHash:
    def test_deterministic_and_spread(self):
        vals = {int(h.hash32_3(x, 7, 0)) for x in range(1000)}
        assert len(vals) == 1000  # no collisions in a small sample
        assert int(h.hash32_3(3, 7, 0)) == int(h.hash32_3(3, 7, 0))

    def test_arity_variants_differ(self):
        assert int(h.hash32_2(1, 2)) != int(h.hash32_3(1, 2, 0))
        assert int(h.hash32_3(1, 2, 3)) != int(h.hash32_4(1, 2, 3, 0))

    def test_vectorized_matches_scalar(self, rng):
        a = rng.integers(0, 2 ** 32, size=256, dtype=np.uint32)
        b = rng.integers(0, 2 ** 32, size=256, dtype=np.uint32)
        c = rng.integers(0, 2 ** 32, size=256, dtype=np.uint32)
        np_res = h.hash32_3(a, b, c)
        jnp_res = np.asarray(h.hash32_3(jnp.asarray(a), jnp.asarray(b),
                                        jnp.asarray(c), xp=jnp))
        assert np.array_equal(np_res, jnp_res)
        np2 = h.hash32_2(a, b)
        jnp2 = np.asarray(h.hash32_2(jnp.asarray(a), jnp.asarray(b), xp=jnp))
        assert np.array_equal(np2, jnp2)

    def test_uniformity(self):
        """Low bit bias check over a large sample (chi^2-ish)."""
        x = np.arange(20000, dtype=np.uint32)
        vals = h.hash32_3(x, np.uint32(42), np.uint32(0))
        frac_msb = np.mean((vals >> 31) & 1)
        assert 0.48 < frac_msb < 0.52
        frac_lsb = np.mean(vals & 1)
        assert 0.48 < frac_lsb < 0.52


class TestCrushLn:
    def test_tables_shapes(self):
        rh, lh = rh_lh_tables()
        assert rh.shape == (129,) and lh.shape == (129,)
        assert ll_table().shape == (256,)

    def test_endpoints(self):
        # crush_ln(0) = 2^44*log2(1) = 0; crush_ln(0xffff) = 2^44*16 = 2^48.
        assert int(crush_ln(np.array(0))) == 0
        assert int(crush_ln(np.array(0xFFFF))) == 1 << 48

    def test_monotone(self):
        xs = np.arange(0x10000)
        v = crush_ln(xs)
        assert np.all(np.diff(v) >= 0)

    def test_accuracy(self):
        xs = np.arange(1, 0x10000)
        got = crush_ln(xs).astype(np.float64)
        want = 2.0 ** 44 * np.log2(xs + 1.0)
        rel = np.abs(got - want) / np.maximum(want, 1)
        assert rel.max() < 2e-4

    def test_jnp_matches_np(self):
        xs = np.arange(0, 0x10000, 17)
        a = crush_ln(xs)
        b = np.asarray(crush_ln(jnp.asarray(xs), xp=jnp))
        assert np.array_equal(a, b)


class TestStraw2Statistics:
    def test_weight_proportional_selection(self):
        """straw2's contract: selection probability proportional to weight
        (the straw2 design goal; ref: mapper.c bucket_straw2_choose)."""
        from ceph_tpu.crush import builder, mapper_ref
        from ceph_tpu.crush.types import WEIGHT_ONE

        weights = [WEIGHT_ONE, 2 * WEIGHT_ONE, 3 * WEIGHT_ONE,
                   2 * WEIGHT_ONE]
        m, root = builder.build_flat(4, weights=weights)
        rid = builder.add_simple_rule(m, root, builder.TYPE_OSD)
        n = 8000
        counts = np.zeros(4)
        for x in range(n):
            counts[mapper_ref.do_rule(m, rid, x, 1)[0]] += 1
        expect = np.array([1, 2, 3, 2], dtype=float) / 8 * n
        # within 5 sigma of binomial noise
        sigma = np.sqrt(expect * (1 - expect / n))
        assert np.all(np.abs(counts - expect) < 5 * sigma), (counts, expect)
