"""calc_pg_upmaps balancer tests (VERDICT round-1 item #5;
ref: src/osd/OSDMap.cc OSDMap::calc_pg_upmaps, mgr balancer upmap mode)."""

import numpy as np
import pytest

from ceph_tpu.bench import osdmaptool
from ceph_tpu.crush.types import ITEM_NONE


def deviation_stats(m, pool_id=1):
    util = m.pool_utilization(pool_id).astype(np.float64)
    inmask = np.asarray(m.osd_weight) > 0
    tgt = util[inmask].sum() / max(inmask.sum(), 1)
    return util, np.abs(util[inmask] - tgt).max()


def fd_of(m, osd, fd_type):
    parents = m._crush_parents()
    return m._failure_domain_of(parents, osd, fd_type)


class TestBalancer:
    @pytest.mark.slow
    def test_flattens_skewed_distribution(self):
        """Natural CRUSH skew on a smallish map must drop to within the
        default upmap_max_deviation=5 (the reference balancer's done
        criterion)."""
        m = osdmaptool.create_simple(48, 1024, 3, erasure=False)
        _, before = deviation_stats(m)
        assert before > 5        # CRUSH alone is skewed at this pg/osd ratio
        changes = m.calc_pg_upmaps(max_deviation=5, max_iterations=400)
        assert changes > 0
        _, after = deviation_stats(m)
        assert after <= 5, f"deviation {after} still above 5"

    def test_upmaps_respect_failure_domain_and_validity(self):
        m = osdmaptool.create_simple(48, 512, 3, erasure=False)
        m.calc_pg_upmaps(max_deviation=3, max_iterations=300)
        assert len(m.pg_upmap_items) > 0
        up, _, _, _ = m.map_pool(1)
        # no duplicate osds, full sets, distinct hosts per PG
        for row in up:
            vals = row[row != ITEM_NONE]
            assert len(vals) == 3
            assert len(set(vals.tolist())) == 3
            hosts = {fd_of(m, int(o), osdmaptool.builder.TYPE_HOST)
                     for o in vals}
            assert len(hosts) == 3

    @pytest.mark.slow
    def test_ec_pool_balances_positionally(self):
        m = osdmaptool.create_simple(40, 512, 5, erasure=True)
        _, before = deviation_stats(m)
        m.calc_pg_upmaps(max_deviation=4, max_iterations=300)
        _, after = deviation_stats(m)
        assert after <= max(4, before)  # improved or already tight
        up, _, _, _ = m.map_pool(1)
        assert not (up == ITEM_NONE).any()   # no holes introduced

    @pytest.mark.slow
    def test_reverts_existing_upmap_feeding_overfull(self):
        from ceph_tpu.osd.types import pg_t
        m = osdmaptool.create_simple(16, 256, 3, erasure=False)
        # artificially pile PGs onto osd 0 with hand-made upmaps
        up, _, _, _ = m.map_pool(1)
        forced = 0
        for seed in range(256):
            row = up[seed]
            if 0 in row or forced >= 30:
                continue
            frm = int(row[0])
            if fd_of(m, 0, osdmaptool.builder.TYPE_HOST) in {
                    fd_of(m, int(o), osdmaptool.builder.TYPE_HOST)
                    for o in row if int(o) != frm}:
                continue
            m.pg_upmap_items[pg_t(1, seed)] = [(frm, 0)]
            forced += 1
        m._dirty()
        assert forced > 10
        _, before = deviation_stats(m)
        assert before > 5
        m.calc_pg_upmaps(max_deviation=5, max_iterations=200)
        _, after = deviation_stats(m)
        assert after <= 5
        # balancer reverted (some of) the artificial entries
        assert len(m.pg_upmap_items) < forced

    def test_heterogeneous_weights_respected(self):
        """2x-weight OSDs legitimately hold ~2x PGs; the balancer's
        target must account for that instead of stripping them."""
        from ceph_tpu.crush import builder
        from ceph_tpu.crush.types import WEIGHT_ONE, CrushMap
        from ceph_tpu.osd import OSDMap, PGPool

        crush = CrushMap(type_names=dict(builder.DEFAULT_TYPE_NAMES))
        n = 24
        crush.max_devices = n
        hosts = []
        for hi, lo in enumerate(range(0, n, 4)):
            osds = list(range(lo, lo + 4))
            w = [2 * WEIGHT_ONE if hi < 3 else WEIGHT_ONE] * 4
            hosts.append(builder.make_bucket(
                crush, builder.TYPE_HOST, osds, w, name=f"host{hi}"))
        root = builder.make_bucket(crush, builder.TYPE_ROOT, hosts,
                                   name="root")
        rule = builder.add_simple_rule(crush, root, builder.TYPE_HOST)
        m = OSDMap(crush)
        m.add_pool(PGPool(id=1, pg_num=1024, size=3, type=1,
                          crush_rule=rule))
        changes = m.calc_pg_upmaps(max_deviation=5, max_iterations=300)
        util = m.pool_utilization(1).astype(np.float64)
        heavy = util[:12].mean()
        light = util[12:].mean()
        # 2x-weight OSDs must retain roughly 2x load after balancing
        assert heavy / light > 1.5, (heavy, light, changes)

    def test_incremental_records_changes(self):
        from ceph_tpu.osd.osdmap import Incremental
        m = osdmaptool.create_simple(32, 512, 3, erasure=False)
        inc = Incremental(epoch=m.epoch + 1)
        changes = m.calc_pg_upmaps(max_deviation=3, max_iterations=100,
                                   inc=inc)
        assert changes > 0
        # a PG touched twice collapses into one entry; the recorded state
        # must equal the map's final upmap state for every touched PG
        assert changes >= len(inc.new_pg_upmap_items) + \
            len(inc.old_pg_upmap_items)
        for pg, pairs in m.pg_upmap_items.items():
            assert inc.new_pg_upmap_items.get(pg) == pairs
        for pg in inc.old_pg_upmap_items:
            assert pg not in m.pg_upmap_items

    def test_osdmaptool_upmap_flag(self, capsys):
        osdmaptool.main(["--createsimple", "32", "--pg-num", "256",
                        "--upmap", "--format", "json"])
        import json
        out = json.loads(capsys.readouterr().out)
        assert "upmap" in out
        assert out["upmap"]["after"]["max_deviation"] <= \
            out["upmap"]["before"]["max_deviation"]


class TestChooseArgsDiscipline:
    """choose_args weight-set quantization (VERDICT weak #3): the
    fused mapping kernel carries <= 4 distinct weights per bucket, so
    balancer-emitted weight-sets must be quantized — and a continuous
    map that slipped in anyway must surface as a health warning, not
    silently run 35x slower."""

    def _continuous_map(self, n=16):
        from ceph_tpu.crush import builder
        from ceph_tpu.crush.types import WEIGHT_ONE, ChooseArg
        # 8 osds per host: a continuous set gives 8 distinct weights
        # per bucket vector, well past the kernel's 4-class budget
        m = osdmaptool.create_simple(n, 64, 3, erasure=False,
                                     osds_per_host=8)
        crush = m.crush
        args = {}
        for bid, b in crush.buckets.items():
            if not any(0 <= it < n for it in b.items):
                continue
            # every item its own weight: the continuous shape an
            # unconstrained balancer emits
            args[bid] = ChooseArg(weight_set=[[
                WEIGHT_ONE + 137 * i for i in range(len(b.items))]])
        crush.choose_args[-1] = args
        return m

    def test_quantize_reduces_classes_and_preserves_zero(self):
        from ceph_tpu.crush import builder
        from ceph_tpu.crush.types import ChooseArg, CrushMap
        m = CrushMap()
        ws = [100, 200, 300, 400, 500, 600, 700, 800, 0, -1]
        m.choose_args[-1] = {-2: ChooseArg(weight_set=[list(ws)])}
        assert builder.choose_args_weight_classes(m) == 8
        worst = builder.quantize_choose_args(m, max_classes=4)
        assert worst <= 4
        got = m.choose_args[-1][-2].weight_set[0]
        assert got[8] == 0 and got[9] == -1   # drained items stay out
        assert len({w for w in got if w > 0}) <= 4
        # quantization is weight-preserving in aggregate (means)
        assert abs(sum(got[:8]) - sum(ws[:8])) < 8 * 50

    def test_quantize_noop_when_already_quantized(self):
        from ceph_tpu.crush import builder
        from ceph_tpu.crush.types import ChooseArg, CrushMap
        m = CrushMap()
        ws = [100, 100, 200, 200]
        m.choose_args[0] = {-2: ChooseArg(weight_set=[list(ws)])}
        assert builder.quantize_choose_args(m) == 2
        assert m.choose_args[0][-2].weight_set[0] == ws

    def test_health_warns_on_continuous_choose_args(self):
        from types import SimpleNamespace
        from ceph_tpu.crush import builder
        from ceph_tpu.mon.service import HealthMonitor
        m = self._continuous_map()
        fake_osdmon = SimpleNamespace(
            osdmap=m, pg_summary=lambda: {}, osd_slow_ops={})
        fake_mon = SimpleNamespace(
            quorum=[0], monmap=SimpleNamespace(ranks=lambda: [0]),
            osdmon=fake_osdmon, store=None)
        checks = HealthMonitor(fake_mon).checks()
        assert "CRUSH_CHOOSE_ARGS_CONTINUOUS" in checks["checks"]
        # quantized: the warning clears
        builder.quantize_choose_args(m.crush)
        checks = HealthMonitor(fake_mon).checks()
        assert "CRUSH_CHOOSE_ARGS_CONTINUOUS" not in checks["checks"]

    def test_balancer_crush_compat_emits_quantized(self):
        """The mgr balancer's crush-compat mode must emit weight-sets
        already inside the kernel's class budget — the quantization
        discipline enforced at the source."""
        import asyncio
        from types import SimpleNamespace
        from ceph_tpu.crush import builder
        from ceph_tpu.encoding import decode_crush_map
        from ceph_tpu.mgr.modules import BalancerModule

        m = osdmaptool.create_simple(24, 512, 3, erasure=False)
        pushed = {}

        class FakeBalancer(BalancerModule):
            def __init__(self):
                self.mgr = None
                self.mode = "crush-compat"

            async def get(self, what):
                assert what == "osd_map"
                return m

            async def mon_command(self, cmd, inbl=b""):
                pushed["cmd"] = cmd
                pushed["crush"] = decode_crush_map(inbl)
                return 0, "", b""

        changes = asyncio.run(FakeBalancer().optimize_weight_set())
        assert changes > 0
        assert pushed["cmd"]["prefix"] == "osd setcrushmap"
        crush = pushed["crush"]
        assert -1 in crush.choose_args       # the compat weight-set
        assert builder.choose_args_weight_classes(crush) <= \
            builder.KERNEL_WEIGHT_CLASSES
