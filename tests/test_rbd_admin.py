"""librbd-lite images + admin socket + op tracking.

ref test models: src/test/librbd (image I/O semantics) and the
`ceph daemon` admin-socket workunits.
"""

import asyncio
import os

import pytest

from ceph_tpu.cluster.vstart import Cluster
from ceph_tpu.rados import ObjectOperationError
from ceph_tpu.rbd import RBD
from ceph_tpu.utils.admin_socket import daemon_command
from ceph_tpu.utils.op_tracker import OpTracker


def run(coro):
    asyncio.run(coro)


def test_rbd_image_lifecycle():
    async def go():
        c = await Cluster(n_mons=1, n_osds=3).start()
        try:
            await c.client.pool_create("rbd", pg_num=8, size=3)
            await c.wait_for_clean(timeout=90)
            io = await c.client.open_ioctx("rbd")
            rbd = RBD(io)
            # 256 KiB image with 64 KiB objects -> 4 data objects
            await rbd.create("disk0", 256 << 10, order=16)
            assert await rbd.list() == ["disk0"]
            with pytest.raises(ObjectOperationError):
                await rbd.create("disk0", 1 << 20)
            img = await rbd.open("disk0")
            info = await img.stat()
            assert info["obj_size"] == 64 << 10
            assert info["num_objs"] == 4
            # write spanning two data objects
            span = os.urandom(100_000)
            await img.write(30_000, span)
            assert await img.read(30_000, len(span)) == span
            # sparse read: untouched region is zeros
            assert await img.read(200_000, 100) == b"\x00" * 100
            # the data objects exist with the striper's names
            names = await io.list_objects()
            assert "rbd_data.disk0.0000000000000000" in names
            assert "rbd_data.disk0.0000000000000001" in names
            # writes past the image size are rejected
            with pytest.raises(ObjectOperationError):
                await img.write(260_000, b"x" * 10_000)
            # shrink: trailing objects go away
            await img.resize(64 << 10)
            img2 = await rbd.open("disk0")
            assert await img2.size() == 64 << 10
            names = await io.list_objects()
            assert "rbd_data.disk0.0000000000000001" not in names
            # data inside the surviving object is intact
            assert await img2.read(30_000, 1000) == span[:1000]
            await rbd.remove("disk0")
            assert await rbd.list() == []
            assert not [n for n in await io.list_objects()
                        if n.startswith("rbd_data.disk0")]
        finally:
            await c.stop()
    run(go())


def test_admin_socket_and_op_tracking(tmp_path):
    async def go():
        c = await Cluster(
            n_mons=1, n_osds=2,
            config={"admin_socket_dir": str(tmp_path)}).start()
        try:
            await c.client.pool_create("p", pg_num=4, size=2)
            await c.wait_for_clean(timeout=90)
            io = await c.client.open_ioctx("p")
            for i in range(5):
                await io.write_full(f"o{i}", b"x" * 128)
            sock = str(tmp_path / "osd.0.asok")
            # ceph daemon osd.0 status
            st = await daemon_command(sock, "status")
            assert st["whoami"] == 0 and st["up"] is True
            assert st["num_pgs"] > 0
            # perf dump returns the process-wide counters
            perf = await daemon_command(sock, "perf dump")
            assert isinstance(perf, dict)
            # historic ops recorded the writes this osd served
            hist = await daemon_command(sock, "dump_historic_ops")
            total_hist = hist["num_ops"]
            other = await daemon_command(
                str(tmp_path / "osd.1.asok"), "dump_historic_ops")
            assert total_hist + other["num_ops"] >= 5
            if hist["ops"]:
                op = hist["ops"][0]
                assert "osd_op(" in op["description"]
                assert any(e["event"] == "done" for e in op["events"])
            # unknown command errors cleanly
            bad = await daemon_command(sock, "no-such-cmd")
            assert "error" in bad
            helpmap = await daemon_command(sock, "help")
            assert "dump_ops_in_flight" in helpmap
        finally:
            await c.stop()
    run(go())


def test_op_tracker_unit():
    t = OpTracker(history_size=2, slow_op_warn_s=0.0)
    a = t.create("op-a")
    a.mark_event("started")
    assert t.dump_ops_in_flight()["num_ops"] == 1
    assert t.slow_ops() == [a]           # warn threshold 0
    a.finish()
    assert t.dump_ops_in_flight()["num_ops"] == 0
    assert t.dump_historic_ops()["num_ops"] == 1
    b, c_ = t.create("b"), t.create("c")
    b.finish()
    c_.finish()
    hist = t.dump_historic_ops()
    assert hist["num_ops"] == 2          # bounded history
    assert [o["description"] for o in hist["ops"]] == ["b", "c"]


def test_rbd_export_import_diff():
    """Incremental replication: export-diff chains (full-at-snap, then
    snap-to-snap, then snap-to-head) rebuild an identical image —
    data, sizes, and snapshots — and zeroed extents travel as 'z'
    records, not data (ref: rbd export-diff/import-diff over the
    doc/dev/rbd-diff.rst v1 stream)."""
    async def go():
        c = await Cluster(n_mons=1, n_osds=3).start()
        try:
            await c.client.pool_create("rbd", pg_num=8, size=3)
            await c.wait_for_clean(timeout=90)
            io = await c.client.open_ioctx("rbd")
            rbd = RBD(io)
            await rbd.create("src", 256 << 10, order=16)
            src = await rbd.open("src")
            await src.write(0, b"AAAA" * 1024)          # 4K at 0
            await src.write(128 << 10, b"BBBB" * 1024)  # 4K at 128K
            await src.snap_create("s1")
            await src.write(64 << 10, b"CCCC" * 1024)
            await src.write(0, b"\0" * 4096)            # zeroed extent
            await src.snap_create("s2")
            await src.write(192 << 10, b"DDDD" * 1024)  # head-only

            # chain: full @s1 -> diff s1..s2 -> diff s2..head
            at_s1 = await rbd.open("src", snapshot="s1")
            full = await at_s1.export_diff()
            at_s2 = await rbd.open("src", snapshot="s2")
            d12 = await at_s2.export_diff(from_snap="s1")
            head = await rbd.open("src")
            d2h = await head.export_diff(from_snap="s2")
            # the zeroed extent must travel as a 'z' record, not as
            # data: walk the stream's tagged records
            def record_tags(stream):
                import struct as _s
                from ceph_tpu.rbd import Image
                assert stream.startswith(Image.DIFF_MAGIC)
                pos = len(Image.DIFF_MAGIC)
                tags = []
                while pos < len(stream):
                    t = stream[pos:pos + 1]
                    pos += 1
                    tags.append(t)
                    if t in (b"f", b"t"):
                        (n,) = _s.unpack_from("<I", stream, pos)
                        pos += 4 + n
                    elif t == b"s":
                        pos += 8
                    elif t == b"w":
                        _, n = _s.unpack_from("<QQ", stream, pos)
                        pos += 16 + n
                    elif t == b"z":
                        pos += 16
                    elif t == b"e":
                        break
                    else:
                        raise AssertionError(f"bad tag {t!r}")
                return tags
            tags = record_tags(d12)
            assert b"z" in tags and tags[-1] == b"e", tags
            assert b"AAAA" not in d12    # unchanged data not shipped

            await rbd.create("dst", 4096, order=16)  # wrong size: 's'
            dst = await rbd.open("dst")              # record fixes it
            await dst.import_diff(full)
            assert "s1" in dst.snaps
            # applying the s1..s2 diff without s1 present must refuse
            await rbd.create("fresh", 256 << 10, order=16)
            fresh = await rbd.open("fresh")
            with pytest.raises(ObjectOperationError):
                await fresh.import_diff(d12)
            await dst.import_diff(d12)
            await dst.import_diff(d2h)

            # identical head content
            s_head = await (await rbd.open("src")).read(0, 256 << 10)
            d_head = await (await rbd.open("dst")).read(0, 256 << 10)
            assert s_head == d_head
            # identical snap views
            for snap in ("s1", "s2"):
                a = await (await rbd.open("src", snapshot=snap)).read(
                    0, 256 << 10)
                b = await (await rbd.open("dst", snapshot=snap)).read(
                    0, 256 << 10)
                assert a == b, snap
            # and the zeroed extent is actually zero at s2
            z = await (await rbd.open("dst", snapshot="s2")).read(
                0, 4096)
            assert z == b"\0" * 4096

            # tail-grain regression: an image whose size is NOT a
            # multiple of the 4 KiB diff grain must still export its
            # final (partial) run — the pre-fix loop dropped it
            await rbd.create("odd", 6000, order=16)
            odd = await rbd.open("odd")
            await odd.write(0, b"E" * 6000)
            stream = await odd.export_diff()
            await rbd.create("odd2", 6000, order=16)
            odd2 = await rbd.open("odd2")
            await odd2.import_diff(stream)
            assert await odd2.read(0, 6000) == b"E" * 6000
        finally:
            await c.stop()
    run(go())


def test_import_diff_truncated_stream_raises_cleanly():
    """ADVICE low #4: a diff stream truncated mid-record must raise
    ObjectOperationError(-22, 'truncated diff stream') on every record
    type — never a raw struct.error leaking to rbd_cli — and must not
    partially corrupt the image before the malformed record."""
    import struct

    from ceph_tpu.rbd import Image

    async def go():
        c = await Cluster(n_mons=1, n_osds=3).start()
        try:
            await c.client.pool_create("rbd", pg_num=8, size=3)
            await c.wait_for_clean(timeout=120)
            io = await c.client.open_ioctx("rbd")
            rbd = RBD(io)
            await rbd.create("dst", 128 << 10, order=16)
            dst = await rbd.open("dst")
            magic = Image.DIFF_MAGIC
            cases = [
                magic + b"s" + b"\x01\x02",            # size cut short
                magic + b"w" + struct.pack("<Q", 0),   # header cut
                magic + b"w" + struct.pack("<QQ", 0, 4096) + b"xy",
                magic + b"z" + struct.pack("<Q", 0)[:4],
                magic + b"t" + struct.pack("<I", 10) + b"abc",
                magic + b"f" + b"\xff",
            ]
            for bad in cases:
                with pytest.raises(ObjectOperationError) as ei:
                    await dst.import_diff(bad)
                assert ei.value.errno == -22, bad
                assert "truncated" in str(ei.value) or \
                    "not present" in str(ei.value), bad
            # missing end record still reports truncation
            with pytest.raises(ObjectOperationError) as ei:
                await dst.import_diff(
                    magic + b"w" + struct.pack("<QQ", 0, 4) + b"good")
            assert ei.value.errno == -22
            # a well-formed stream still applies after the failures
            await dst.import_diff(
                magic + b"w" + struct.pack("<QQ", 0, 4) + b"good" +
                b"e")
            assert await dst.read(0, 4) == b"good"
        finally:
            await c.stop()
    run(go())


def test_rbd_snap_refusal_matrix_and_clone_teardown():
    """The snap rm/unprotect/protect errno matrix (ref: librbd
    Operations::snap_* return codes), the open-child race — a clone
    minted through ANOTHER handle after this one opened must still
    block unprotect — and the shared-blob teardown: once the last
    child detaches, unprotect + snap rm drain every OSD-side COW
    clone object the snapshot pinned."""
    async def go():
        c = await Cluster(n_mons=1, n_osds=3).start()
        try:
            await c.client.pool_create("rbd", pg_num=8, size=3)
            await c.wait_for_clean(timeout=90)
            io = await c.client.open_ioctx("rbd")
            rbd = RBD(io)
            await rbd.create("parent", 128 << 10, order=16)
            img = await rbd.open("parent")
            await img.write(0, b"v1" * 8192)
            # -- protect matrix
            with pytest.raises(ObjectOperationError) as ei:
                await img.snap_protect("nosnap")
            assert ei.value.errno == -2
            await img.snap_create("s1")
            await img.snap_protect("s1")
            with pytest.raises(ObjectOperationError) as ei:
                await img.snap_protect("s1")          # already
            assert ei.value.errno == -16
            # -- unprotect matrix
            with pytest.raises(ObjectOperationError) as ei:
                await img.snap_unprotect("nosnap")
            assert ei.value.errno == -2
            await img.snap_create("bare")
            with pytest.raises(ObjectOperationError) as ei:
                await img.snap_unprotect("bare")      # never protected
            assert ei.value.errno == -22
            # -- the open-child race: `img` was opened BEFORE the
            # clone exists; its in-memory children list is stale, but
            # unprotect must re-read the header and refuse
            await rbd.clone("parent", "s1", "child")
            with pytest.raises(ObjectOperationError) as ei:
                await img.snap_unprotect("s1")
            assert ei.value.errno == -16
            # snap rm of a protected snap refuses too
            with pytest.raises(ObjectOperationError) as ei:
                await img.snap_remove("s1")
            assert ei.value.errno == -16
            # clone prerequisites: unprotected parent snap refuses,
            # duplicate child name refuses
            with pytest.raises(ObjectOperationError) as ei:
                await rbd.clone("parent", "bare", "child2")
            assert ei.value.errno == -22
            with pytest.raises(ObjectOperationError) as ei:
                await rbd.clone("parent", "s1", "child")
            assert ei.value.errno == -17
            # image remove with snapshots refuses
            with pytest.raises(ObjectOperationError) as ei:
                await rbd.remove("parent")
            assert ei.value.errno == -39
            # the child serves the parent snapshot's bytes through
            # layering while the head diverges (COW clones at the OSD
            # keep s1's data: shared-blob references, not copies)
            await img.write(0, b"v2" * 8192)
            child = await rbd.open("child")
            assert await child.read(0, 4) == b"v1v1"
            assert await img.read(0, 4) == b"v2v2"
            clones = [n for o in c.osds
                      for cid in o.store.list_collections()
                      for n in o.store.list_objects(cid)
                      if n.startswith("_snapclone.")]
            assert clones, "overwrite under a snap minted no COW clone"
            # -- teardown in dependency order: child, unprotect, rm
            await rbd.remove("child")
            await img.snap_unprotect("s1")
            await img.snap_remove("s1")
            await img.snap_remove("bare")
            assert await img.snap_list() == []
            # the snapshot's COW clones drain from every OSD store
            deadline = asyncio.get_event_loop().time() + 60.0
            while True:
                left = [n for o in c.osds
                        for cid in o.store.list_collections()
                        for n in o.store.list_objects(cid)
                        if n.startswith("_snapclone.")]
                if not left:
                    break
                assert asyncio.get_event_loop().time() < deadline, \
                    f"snap trim left clone objects: {left[:4]}"
                await asyncio.sleep(0.5)
            # head data untouched by the trims
            assert await img.read(0, 4) == b"v2v2"
            await rbd.remove("parent")
        finally:
            await c.stop()
    run(go())
