"""OSDMap-lite tests — mirrors src/test/osd/TestOSDMap.cc patterns:
synthetic maps in-process, assert placement pipeline behavior, overrides,
and stability. The scalar oracle re-implements the reference pipeline
independently (mapper_ref + pure-python post-processing)."""

import numpy as np
import pytest

from ceph_tpu.crush import builder, mapper_ref
from ceph_tpu.crush.types import ITEM_NONE, WEIGHT_ONE
from ceph_tpu.osd import (
    OSDMap, ObjectLocator, PGPool, pg_t,
    POOL_TYPE_ERASURE, ceph_stable_mod,
)
from ceph_tpu.osd.osdmap import DEFAULT_PRIMARY_AFFINITY, Incremental
from ceph_tpu.osd.str_hash import (
    CEPH_STR_HASH_LINUX, CEPH_STR_HASH_RJENKINS, pack_names,
    str_hash, str_hash_batch, str_hash_linux, str_hash_rjenkins,
)
from ceph_tpu.osd.types import FLAG_HASHPSPOOL, calc_mask


# ---------------------------------------------------------------------------
# Independent scalar oracle for the rjenkins string hash
# ---------------------------------------------------------------------------

def _mix_py(a, b, c):
    M = 0xFFFFFFFF
    a = (a - b - c) & M; a ^= c >> 13
    b = (b - c - a) & M; b ^= (a << 8) & M
    c = (c - a - b) & M; c ^= b >> 13
    a = (a - b - c) & M; a ^= c >> 12
    b = (b - c - a) & M; b ^= (a << 16) & M
    c = (c - a - b) & M; c ^= b >> 5
    a = (a - b - c) & M; a ^= c >> 3
    b = (b - c - a) & M; b ^= (a << 10) & M
    c = (c - a - b) & M; c ^= b >> 15
    return a, b, c


def _rjenkins_oracle(data: bytes) -> int:
    k, length = data, len(data)
    a = b = 0x9E3779B9
    c = 0
    i = 0
    ln = length
    while ln >= 12:
        a = (a + int.from_bytes(k[i:i + 4], "little")) & 0xFFFFFFFF
        b = (b + int.from_bytes(k[i + 4:i + 8], "little")) & 0xFFFFFFFF
        c = (c + int.from_bytes(k[i + 8:i + 12], "little")) & 0xFFFFFFFF
        a, b, c = _mix_py(a, b, c)
        i += 12
        ln -= 12
    c = (c + length) & 0xFFFFFFFF
    t = k[i:]
    if ln >= 11: c = (c + (t[10] << 24)) & 0xFFFFFFFF
    if ln >= 10: c = (c + (t[9] << 16)) & 0xFFFFFFFF
    if ln >= 9: c = (c + (t[8] << 8)) & 0xFFFFFFFF
    if ln >= 8: b = (b + (t[7] << 24)) & 0xFFFFFFFF
    if ln >= 7: b = (b + (t[6] << 16)) & 0xFFFFFFFF
    if ln >= 6: b = (b + (t[5] << 8)) & 0xFFFFFFFF
    if ln >= 5: b = (b + t[4]) & 0xFFFFFFFF
    if ln >= 4: a = (a + (t[3] << 24)) & 0xFFFFFFFF
    if ln >= 3: a = (a + (t[2] << 16)) & 0xFFFFFFFF
    if ln >= 2: a = (a + (t[1] << 8)) & 0xFFFFFFFF
    if ln >= 1: a = (a + t[0]) & 0xFFFFFFFF
    a, b, c = _mix_py(a, b, c)
    return c


class TestStrHash:
    def test_rjenkins_matches_oracle(self):
        names = [b"", b"a", b"foo", b"rbd_data.1234", b"x" * 11, b"y" * 12,
                 b"z" * 13, b"benchmark_data_host_12345_object67",
                 bytes(range(256))]
        for n in names:
            assert str_hash_rjenkins(n) == _rjenkins_oracle(n), n

    def test_batch_matches_scalar(self, rng):
        names = [bytes(rng.integers(1, 255, size=int(L), dtype=np.uint8))
                 for L in rng.integers(0, 40, size=64)]
        padded, lens = pack_names(names)
        out = str_hash_batch(CEPH_STR_HASH_RJENKINS, padded, lens)
        for i, n in enumerate(names):
            assert int(out[i]) == str_hash_rjenkins(n)

    def test_linux_hash(self):
        # hand-computed: h=0; h=(h + (c<<4)+(c>>4))*11 per byte
        assert str_hash_linux(b"") == 0
        c = ord("a")
        assert str_hash_linux(b"a") == (((c << 4) + (c >> 4)) * 11) \
            & 0xFFFFFFFF
        padded, lens = pack_names([b"abc", b"hello"])
        out = str_hash_batch(CEPH_STR_HASH_LINUX, padded, lens)
        assert int(out[0]) == str_hash_linux(b"abc")
        assert int(out[1]) == str_hash_linux(b"hello")

    def test_dispatch(self):
        assert str_hash(CEPH_STR_HASH_RJENKINS, b"foo") == \
            str_hash_rjenkins(b"foo")
        with pytest.raises(ValueError):
            str_hash(99, b"foo")


class TestStableMod:
    def test_matches_definition(self):
        for pg_num in (1, 3, 12, 16, 100):
            bmask = calc_mask(pg_num)
            for x in range(200):
                want = x & bmask if (x & bmask) < pg_num else \
                    x & (bmask >> 1)
                assert int(ceph_stable_mod(x, pg_num, bmask)) == want

    def test_mask(self):
        assert calc_mask(1) == 0
        assert calc_mask(16) == 15
        assert calc_mask(17) == 31
        assert calc_mask(12) == 15


# ---------------------------------------------------------------------------
# OSDMap pipeline
# ---------------------------------------------------------------------------

def make_map(n_hosts=8, per_host=2, pool_size=3, pg_num=64,
             erasure=False, ec_size=5):
    crush, root = builder.build_hierarchy(n_hosts, per_host)
    rule = builder.add_simple_rule(crush, root, builder.TYPE_HOST,
                                   indep=erasure)
    m = OSDMap(crush)
    m.add_pool(PGPool(id=1, pg_num=pg_num, size=ec_size if erasure
                      else pool_size,
                      type=POOL_TYPE_ERASURE if erasure else 1,
                      crush_rule=rule))
    return m


def scalar_pipeline(m: OSDMap, pool: PGPool, seed: int):
    """Independent re-derivation of pg_to_up_acting for one seed."""
    pps = pool.raw_pg_to_pps(seed, xp=None)
    weight = [0] * m.crush.max_devices
    for o in range(m.max_osd):
        weight[o] = int(m.osd_weight[o])
    raw = mapper_ref.do_rule(m.crush, pool.crush_rule, pps, pool.size,
                             weight)
    raw = raw + [ITEM_NONE] * (pool.size - len(raw))
    # nonexistent + down filter
    def alive(o):
        return (0 <= o < m.max_osd and
                bool(m.osd_state[o] & 1) and bool(m.osd_state[o] & 2))
    if pool.can_shift_osds():
        up = [o for o in raw if o != ITEM_NONE and alive(o)]
        up += [ITEM_NONE] * (pool.size - len(up))
    else:
        up = [o if o != ITEM_NONE and alive(o) else ITEM_NONE for o in raw]
    primary = next((o for o in up if o != ITEM_NONE), -1)
    return up, primary


class TestOSDMapBasic:
    def test_matches_scalar_pipeline(self):
        m = make_map()
        pool = m.pools[1]
        seeds = np.arange(64, dtype=np.uint32)
        up, upp, acting, actp = m.pg_to_up_acting_osds(1, seeds)
        assert (up == acting).all() and (upp == actp).all()
        for s in range(0, 64, 7):
            want_up, want_p = scalar_pipeline(m, pool, s)
            assert list(up[s]) == want_up, f"seed {s}"
            assert upp[s] == want_p

    def test_full_and_distinct_hosts(self):
        m = make_map()
        up, upp, _, _ = m.map_pool(1)
        assert (up != ITEM_NONE).all()
        assert (upp == up[:, 0]).all()
        hosts = up // 2  # per_host=2, contiguous ids
        for row in hosts:
            assert len(set(row.tolist())) == 3

    def test_ec_positional(self):
        m = make_map(erasure=True)
        up, _, _, _ = m.map_pool(1)
        assert up.shape[1] == 5
        assert (up != ITEM_NONE).all()  # plenty of hosts

    def test_mark_down_removes_from_up(self):
        m = make_map()
        victim = 3
        m.mark_down(victim)
        up, _, _, _ = m.map_pool(1)
        assert not (up == victim).any()
        # replicated: compaction leaves NONE only at the tail
        for s in range(64):
            want_up, _ = scalar_pipeline(m, m.pools[1], s)
            assert list(up[s]) == want_up

    def test_mark_out_rereplicates(self):
        m = make_map()
        victim = 3
        before = m.map_pool(1)[0]
        m.mark_out(victim)
        up, _, _, _ = m.map_pool(1)
        assert not (up == victim).any()
        # out (weight=0) triggers CRUSH retry: sets stay full
        assert (up != ITEM_NONE).all()
        # only PGs that touched the victim move
        moved = (before != up).any(axis=1)
        touched = (before == victim).any(axis=1)
        assert (moved == touched).all()

    def test_ec_down_leaves_hole(self):
        m = make_map(erasure=True)
        victim = int(m.map_pool(1)[0][0, 2])
        m.mark_down(victim)
        up, _, _, _ = m.map_pool(1)
        assert (up[0] == ITEM_NONE).sum() >= 1
        assert up[0, 2] == ITEM_NONE

    def test_epoch_bumps(self):
        m = make_map()
        e = m.epoch
        m.mark_down(0)
        assert m.epoch == e + 1


class TestOverrides:
    def test_pg_upmap(self):
        m = make_map()
        up0 = m.map_pool(1)[0]
        target = (10, 12, 14)
        m.pg_upmap[pg_t(1, 5)] = target
        up, upp, _, _ = m.map_pool(1)
        assert tuple(up[5]) == target
        assert upp[5] == 10
        assert (up[4] == up0[4]).all()

    def test_pg_upmap_rejected_when_target_out(self):
        m = make_map()
        up0 = m.map_pool(1)[0]
        m.mark_out(10)
        m.pg_upmap[pg_t(1, 5)] = (10, 12, 14)
        up, _, _, _ = m.map_pool(1)
        assert not (up[5] == 10).any()
        del m.pg_upmap[pg_t(1, 5)]

    def test_pg_upmap_items(self):
        m = make_map()
        up0 = m.map_pool(1)[0]
        frm = int(up0[7, 1])
        to = next(o for o in range(m.max_osd)
                  if o not in up0[7].tolist())
        m.pg_upmap_items[pg_t(1, 7)] = [(frm, to)]
        up, _, _, _ = m.map_pool(1)
        assert up[7, 1] == to
        assert not (up[7] == frm).any()

    def test_pg_temp(self):
        m = make_map()
        m.pg_temp[pg_t(1, 9)] = [1, 5, 9]
        up, upp, acting, actp = m.map_pool(1)
        assert list(acting[9]) == [1, 5, 9]
        assert actp[9] == 1
        assert not (up[9] == acting[9]).all() or True
        assert (acting[8] == up[8]).all()

    def test_primary_temp(self):
        m = make_map()
        up0, upp0, _, _ = m.map_pool(1)
        other = int(up0[3, 1])
        m.primary_temp[pg_t(1, 3)] = other
        _, _, _, actp = m.map_pool(1)
        assert actp[3] == other

    def test_primary_affinity_zero_never_primary(self):
        m = make_map()
        victim = int(m.map_pool(1)[1][0])
        m.set_primary_affinity(victim, 0)
        up, upp, _, _ = m.map_pool(1)
        present = (up == victim).any(axis=1)
        assert present.any()
        assert not (upp == victim).any()

    def test_primary_affinity_partial_shifts_some(self):
        m = make_map()
        upp0 = m.map_pool(1)[1]
        victim = int(upp0[0])
        n_before = (upp0 == victim).sum()
        m.set_primary_affinity(victim, DEFAULT_PRIMARY_AFFINITY // 2)
        upp = m.map_pool(1)[1]
        n_after = (upp == victim).sum()
        assert 0 < n_after < n_before


class TestObjectMapping:
    def test_object_locator_to_pg(self):
        m = make_map()
        pool = m.pools[1]
        raw = m.object_locator_to_pg("rbd_data.abc", ObjectLocator(pool=1))
        assert raw.pool == 1
        assert raw.seed == pool.hash_key("rbd_data.abc")
        folded = pool.raw_pg_to_pg(raw.seed, xp=None)
        assert 0 <= folded < pool.pg_num

    def test_locator_key_overrides_name(self):
        m = make_map()
        a = m.object_locator_to_pg("x", ObjectLocator(pool=1, key="lock"))
        b = m.object_locator_to_pg("y", ObjectLocator(pool=1, key="lock"))
        assert a == b

    def test_hashpspool_separates_pools(self):
        m = make_map()
        m.add_pool(PGPool(id=2, pg_num=64, size=3, crush_rule=0))
        seeds = np.arange(64, dtype=np.uint32)
        p1 = m.pools[1].raw_pg_to_pps(seeds)
        p2 = m.pools[2].raw_pg_to_pps(seeds)
        assert (np.asarray(p1) != np.asarray(p2)).any()

    def test_batch_hash_keys(self):
        m = make_map()
        pool = m.pools[1]
        names = [f"obj{i}".encode() for i in range(32)]
        padded, lens = pack_names(names)
        out = pool.hash_keys(padded, lens)
        for i, n in enumerate(names):
            assert int(out[i]) == pool.hash_key(n)


class TestIncremental:
    def test_apply(self):
        m = make_map()
        direct = make_map()
        inc = Incremental(epoch=m.epoch + 1, new_down=[2],
                          new_weight={5: 0},
                          new_pg_temp={pg_t(1, 4): [1, 7, 9]})
        m.apply_incremental(inc)
        direct.mark_down(2)
        direct.set_weight(5, 0)
        direct.pg_temp[pg_t(1, 4)] = [1, 7, 9]
        a = m.map_pool(1)
        b = direct.map_pool(1)
        for x, y in zip(a, b):
            assert (x == y).all()

    def test_bad_epoch_rejected(self):
        m = make_map()
        with pytest.raises(ValueError):
            m.apply_incremental(Incremental(epoch=m.epoch + 5))

    def test_remove_pg_temp(self):
        m = make_map()
        m.pg_temp[pg_t(1, 4)] = [1, 7, 9]
        m.apply_incremental(Incremental(epoch=m.epoch + 1,
                                        new_pg_temp={pg_t(1, 4): []}))
        assert pg_t(1, 4) not in m.pg_temp


class TestUtilization:
    def test_counts(self):
        m = make_map()
        util = m.pool_utilization(1)
        assert util.sum() == 64 * 3
        assert (util > 0).all()  # 16 osds, 192 slots


class TestChooseArgsSelection:
    def test_compat_weight_set_changes_placement(self):
        """A -1 (compat) weight-set is picked up by the mapping pipeline
        (ref: CrushWrapper::choose_args_get_with_fallback)."""
        import numpy as np
        from ceph_tpu.bench import osdmaptool
        from ceph_tpu.crush.types import ChooseArg, WEIGHT_ONE

        m = osdmaptool.create_simple(16, 128, 3, erasure=False)
        up_before, _, _, _ = m.map_pool(1)
        root = next(b.id for b in m.crush.buckets.values()
                    if b.type == osdmaptool.builder.TYPE_ROOT)
        hosts = m.crush.buckets[root].items
        m.crush.choose_args[-1] = {
            root: ChooseArg(weight_set=[[3 * WEIGHT_ONE] +
                                        [WEIGHT_ONE] * (len(hosts) - 1)])}
        m._dirty(crush_changed=True)
        up_after, _, _, _ = m.map_pool(1)
        assert not np.array_equal(up_before, up_after)
        # the overweighted first host appears in nearly every PG's set
        # (baseline: 3 distinct hosts of 4 => 75% of PGs; 3x weight
        # pushes it toward the 100% cap)
        h0 = m.crush.buckets[hosts[0]].items
        util = m.pool_utilization(1)
        assert util[h0].sum() > 0.9 * 128
