"""`ceph pg repair`: the scrub repair path (VERDICT missing #6).

ref test model: qa/standalone/scrub/osd-scrub-repair.sh — corrupt a
copy behind the cluster's back, `ceph pg repair`, and the digest-
mismatched replica is rewritten from the authoritative copy (majority
vote across whole-object digests; the reference picks by object-info
digest). EC: a bad shard is regenerated from the survivors through
the decode path.
"""

import asyncio
import os

from ceph_tpu.cluster.vstart import Cluster
from ceph_tpu.os_.objectstore import Transaction


def run(coro):
    asyncio.run(coro)


def _pg_holding(c, oid, primary: bool):
    for o in c.osds:
        for pg in o.pgs.values():
            if pg.is_primary() == primary and \
                    oid in o.store.list_objects(pg.cid):
                return o, pg
    return None, None


def test_pg_repair_replicated():
    """Replica corruption repairs from the primary; PRIMARY corruption
    repairs from the replica majority (the vote must out-rank the
    primary's own bad copy); the `pg repair` mon command drives the
    same path end-to-end."""
    async def go():
        c = await Cluster(n_mons=1, n_osds=3).start()
        try:
            await c.client.pool_create("s", pg_num=2, size=3)
            await c.wait_for_clean(timeout=120)
            io = await c.client.open_ioctx("s")
            good = b"\xabGOOD" * 64
            await io.write_full("r1", good)

            # 1: corrupt a REPLICA copy
            osd, pg = _pg_holding(c, "r1", primary=False)
            assert pg is not None
            osd.store.queue_transaction(
                Transaction().write(pg.cid, "r1", 0, b"CORRUPT"))
            posd = next(x for x in c.osds if x.whoami == pg.primary)
            ppg = posd.pgs[pg.cid]
            rep = await ppg.scrubber.repair()
            assert rep["errors_before"], rep
            assert rep["repaired"] >= 1, rep
            assert rep["errors_after"] == [], rep
            assert osd.store.read(pg.cid, "r1") == good
            assert ppg.scrub_errors == 0

            # 2: corrupt the PRIMARY's copy — majority wins
            posd.store.queue_transaction(
                Transaction().write(ppg.cid, "r1", 0, b"BADPRIM"))
            rep = await ppg.scrubber.repair()
            assert rep["errors_after"] == [], rep
            assert posd.store.read(ppg.cid, "r1") == good

            # 3: the CLI/mon path (`ceph pg repair <pgid>`)
            osd.store.queue_transaction(
                Transaction().write(pg.cid, "r1", 0, b"AGAIN"))
            ret, rs, _ = await c.client.mon_command(
                {"prefix": "pg repair", "pgid": pg.cid})
            assert ret == 0, rs
            deadline = asyncio.get_event_loop().time() + 15
            while osd.store.read(pg.cid, "r1") != good:
                assert asyncio.get_event_loop().time() < deadline, \
                    "mon-driven repair never landed"
                await asyncio.sleep(0.1)

            # unknown pg errors cleanly
            ret, rs, _ = await c.client.mon_command(
                {"prefix": "pg repair", "pgid": "9.0"})
            assert ret == -2, rs
        finally:
            await c.stop()
    run(go())


def test_pg_repair_ec_shard():
    """A corrupted parity shard is detected by deep scrub and
    regenerated from the data shards via the existing decode/encode
    path; the inconsistent flag clears."""
    async def go():
        c = await Cluster(n_mons=1, n_osds=3).start()
        try:
            ret, rs, _ = await c.client.mon_command(
                {"prefix": "osd erasure-code-profile set",
                 "name": "p21",
                 "profile": ["k=2", "m=1",
                             "crush-failure-domain=osd",
                             "stripe_unit=512"]})
            assert ret == 0, rs
            await c.client.pool_create("e", pg_num=2,
                                       pool_type="erasure",
                                       erasure_code_profile="p21")
            await c.wait_for_clean(timeout=120)
            io = await c.client.open_ioctx("e")
            payload = os.urandom(3000)
            await io.write_full("obj", payload)
            prim_pg = next(pg for o in c.osds
                           for pg in o.pgs.values()
                           if pg.is_primary() and
                           "obj" in o.store.list_objects(pg.cid))
            parity_osd = next(o for o in c.osds
                              if o.whoami == prim_pg.acting[2])
            parity_osd.store.queue_transaction(
                Transaction().write(prim_pg.cid, "obj", 10, b"XXXX"))
            rep = await prim_pg.scrubber.repair()
            assert rep["errors_before"], rep
            assert rep["errors_after"] == [], rep
            assert prim_pg.scrub_errors == 0
            assert await io.read("obj") == payload
            # a fresh deep scrub agrees the shard is sound again
            rep = await prim_pg.scrubber.scrub(deep=True)
            assert rep["errors"] == [], rep
        finally:
            await c.stop()
    run(go())


def test_pg_repair_ec_data_shard():
    """The adversarial case: corrupting a DATA shard also makes the
    regenerated parity disagree with the stored (good) parity — a
    naive repair would 'fix' the good parity from the bad data and
    canonicalize the corruption. Leave-one-out identification must
    pin the actual culprit and rebuild IT from the survivors."""
    async def go():
        c = await Cluster(n_mons=1, n_osds=3).start()
        try:
            ret, rs, _ = await c.client.mon_command(
                {"prefix": "osd erasure-code-profile set",
                 "name": "p21",
                 "profile": ["k=2", "m=1",
                             "crush-failure-domain=osd",
                             "stripe_unit=512"]})
            assert ret == 0, rs
            await c.client.pool_create("e", pg_num=2,
                                       pool_type="erasure",
                                       erasure_code_profile="p21")
            await c.wait_for_clean(timeout=120)
            io = await c.client.open_ioctx("e")
            payload = os.urandom(3000)
            await io.write_full("obj", payload)
            prim_pg = next(pg for o in c.osds
                           for pg in o.pgs.values()
                           if pg.is_primary() and
                           "obj" in o.store.list_objects(pg.cid))
            # corrupt DATA shard position 0
            data_osd = next(o for o in c.osds
                            if o.whoami == prim_pg.acting[0])
            parity_osd = next(o for o in c.osds
                              if o.whoami == prim_pg.acting[2])
            good_parity = parity_osd.store.read(prim_pg.cid, "obj")
            good_data0 = data_osd.store.read(prim_pg.cid, "obj")
            data_osd.store.queue_transaction(
                Transaction().write(prim_pg.cid, "obj", 7, b"ROT"))
            rep = await prim_pg.scrubber.repair()
            assert rep["errors_before"], rep
            assert any("shard 0 identified corrupt" in f
                       for f in rep["errors_before"]), rep
            assert rep["errors_after"] == [], rep
            # the DATA shard was restored; the parity NEVER rewritten
            # from corrupt data
            assert data_osd.store.read(prim_pg.cid, "obj") == \
                good_data0
            assert parity_osd.store.read(prim_pg.cid, "obj") == \
                good_parity
            assert await io.read("obj") == payload
        finally:
            await c.stop()
    run(go())
