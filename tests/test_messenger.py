"""Messenger tier: handshake, dispatch, auth, loss, injection.

ref test model: src/test/msgr/test_msgr.cc (MessengerTest) — client/
server pairs exercising delivery, policies, reconnect and fault
injection on localhost sockets.
"""

import asyncio

import pytest

from ceph_tpu.msg import (
    MODE_SECURE, AuthError, Dispatcher, Keyring, Message, Messenger,
    Policy, register,
)
from ceph_tpu.msg.messenger import ConnectionError_


@register
class MPing(Message):
    TYPE = 900
    FIELDS = [("x", "u64"), ("note", "str")]


@register
class MData(Message):
    TYPE = 901
    FIELDS = [("oid", "str"), ("data", "blob"), ("osds", "list:s32")]


class Collector(Dispatcher):
    def __init__(self):
        self.got = []
        self.resets = 0
        self.event = asyncio.Event()

    async def ms_dispatch(self, msg):
        self.got.append(msg)
        self.event.set()
        return True

    async def ms_handle_reset(self, conn):
        self.resets += 1


async def _wait(pred, timeout=5.0):
    t0 = asyncio.get_event_loop().time()
    while not pred():
        if asyncio.get_event_loop().time() - t0 > timeout:
            raise TimeoutError
        await asyncio.sleep(0.01)


def run(coro):
    return asyncio.run(coro)


def _keyring(*names):
    kr = Keyring()
    for n in names:
        kr.add(n)
    return kr


def test_basic_roundtrip_with_auth():
    async def go():
        kr = _keyring("osd.1", "client.a")
        server = Messenger("osd.1", keyring=kr)
        sink = Collector()
        server.add_dispatcher(sink)
        addr = await server.bind()
        client = Messenger("client.a", keyring=kr)
        await client.send_message(
            MData(oid="obj1", data=b"\x01\x02", osds=[3, -1]), addr,
            "osd.1")
        await _wait(lambda: sink.got)
        m = sink.got[0]
        assert isinstance(m, MData)
        assert (m.oid, m.data, m.osds) == ("obj1", b"\x01\x02", [3, -1])
        assert m.src == "client.a"
        # reply over the incoming connection
        reply_sink = Collector()
        client.add_dispatcher(reply_sink)
        await m.conn.send_message(MPing(x=7, note="reply"))
        await _wait(lambda: reply_sink.got)
        assert reply_sink.got[0].x == 7
        await client.shutdown()
        await server.shutdown()
    run(go())


def test_auth_rejects_wrong_key():
    async def go():
        server = Messenger("mon.a", keyring=_keyring("mon.a", "client.x"))
        await server.bind()
        bad = Messenger("client.x", keyring=_keyring("mon.a", "client.x"))
        # tamper: different secret than the server's for client.x
        bad.keyring.add("client.x")
        with pytest.raises((AuthError, ConnectionError_, OSError,
                            asyncio.IncompleteReadError)):
            await bad.send_message(MPing(x=1, note=""), server.addr,
                                   "mon.a")
        await bad.shutdown()
        await server.shutdown()
    run(go())


def test_unknown_entity_rejected():
    async def go():
        server = Messenger("mon.a", keyring=_keyring("mon.a"))
        await server.bind()
        kr = _keyring("mon.a")
        kr.add("client.ghost")
        ghost = Messenger("client.ghost", keyring=kr)
        with pytest.raises((AuthError, ConnectionError_, OSError,
                            asyncio.IncompleteReadError)):
            await ghost.send_message(MPing(x=1, note=""), server.addr,
                                     "mon.a")
        await ghost.shutdown()
        await server.shutdown()
    run(go())


def test_secure_mode_frames():
    async def go():
        kr = _keyring("osd.0", "osd.1")
        server = Messenger("osd.1", keyring=kr, mode=MODE_SECURE)
        sink = Collector()
        server.add_dispatcher(sink)
        addr = await server.bind()
        client = Messenger("osd.0", keyring=kr, mode=MODE_SECURE)
        for i in range(5):
            await client.send_message(MPing(x=i, note="s"), addr, "osd.1")
        await _wait(lambda: len(sink.got) == 5)
        assert [m.x for m in sink.got] == list(range(5))
        await client.shutdown()
        await server.shutdown()
    run(go())


def test_secure_mode_no_plaintext_on_wire():
    """Secure mode is ENCRYPTION, not just integrity (VERDICT r3
    Missing #7): a distinctive payload must never appear in the bytes
    written to either socket; in crc mode it must (sanity check that
    the tap works)."""
    def tap(msgr, captured):
        orig_handshake = msgr._client_handshake_inner

        async def wrapped(reader, writer, addr, peer_name):
            orig_write = writer.write

            def spy(data):
                captured.append(bytes(data))
                return orig_write(data)
            writer.write = spy
            return await orig_handshake(reader, writer, addr, peer_name)
        msgr._client_handshake_inner = wrapped

    async def go(mode):
        kr = _keyring("osd.0", "osd.1")
        server = Messenger("osd.1", keyring=kr, mode=mode)
        sink = Collector()
        server.add_dispatcher(sink)
        addr = await server.bind()
        client = Messenger("osd.0", keyring=kr, mode=mode)
        captured: list[bytes] = []
        tap(client, captured)
        marker = b"TOP-SECRET-PAYLOAD-0123456789"
        await client.send_message(
            MData(oid="o", data=marker, osds=[1]), addr, "osd.1")
        await _wait(lambda: sink.got)
        assert sink.got[0].data == marker
        wire = b"".join(captured)
        await client.shutdown()
        await server.shutdown()
        return marker in wire

    assert run(go(MODE_SECURE)) is False, "plaintext leaked in secure mode"
    from ceph_tpu.msg.messenger import MODE_CRC
    assert run(go(MODE_CRC)) is True, "wire tap failed to observe frames"


def test_secure_mode_survives_rekey():
    """Sessions must keep flowing across in-band key rotations (the
    cephx ticket-rotation analog)."""
    async def go():
        kr = _keyring("osd.0", "osd.1")
        server = Messenger("osd.1", keyring=kr, mode=MODE_SECURE,
                           rekey_frames=3)
        sink = Collector()
        server.add_dispatcher(sink)
        addr = await server.bind()
        client = Messenger("osd.0", keyring=kr, mode=MODE_SECURE,
                           rekey_frames=3)
        for i in range(20):
            await client.send_message(MPing(x=i, note="r"), addr, "osd.1")
        await _wait(lambda: len(sink.got) == 20)
        assert [m.x for m in sink.got] == list(range(20))
        conn = next(iter(client.conns.values()))
        assert conn._tx_epoch >= 5, "rekey never happened"
        await client.shutdown()
        await server.shutdown()
    run(go())


def test_secure_mode_rejects_tampered_frames():
    """Flipping one ciphertext bit must kill the frame (AEAD tag)."""
    async def go():
        kr = _keyring("osd.0", "osd.1")
        server = Messenger("osd.1", keyring=kr, mode=MODE_SECURE)
        sink = Collector()
        server.add_dispatcher(sink)
        addr = await server.bind()
        client = Messenger("osd.0", keyring=kr, mode=MODE_SECURE)
        await client.send_message(MPing(x=1, note="a"), addr, "osd.1")
        await _wait(lambda: len(sink.got) == 1)
        conn = next(iter(client.conns.values()))
        orig_write = conn.writer.write

        def corrupt(data):
            b = bytearray(data)
            if len(b) > 20:
                b[-1] ^= 0x40          # flip a ciphertext/tag bit
            return orig_write(bytes(b))
        conn.writer.write = corrupt
        try:
            await conn.send_message(MPing(x=2, note="b"))
        except ConnectionError_:
            pass
        await asyncio.sleep(0.3)
        assert len(sink.got) == 1, "tampered frame was dispatched"
        await client.shutdown()
        await server.shutdown()
    run(go())


def test_lossless_replay_exactly_once_under_injection():
    """Injected socket failures on a lossless peer link: every message
    still arrives, in order, exactly once (the qa thrash invariant)."""
    async def go():
        kr = _keyring("osd.0", "osd.1")
        server = Messenger("osd.1", keyring=kr)
        server.set_policy("osd", Policy.lossless_peer())
        sink = Collector()
        server.add_dispatcher(sink)
        addr = await server.bind()
        client = Messenger("osd.0", keyring=kr,
                           inject_socket_failures=12, seed=7)
        client.set_policy("osd", Policy.lossless_peer())
        n = 40
        for i in range(n):
            # injected failures surface as reconnect+replay inside
            await client.send_message(MPing(x=i, note="inj"), addr,
                                      "osd.1")
        client.inject_socket_failures = 0
        await _wait(lambda: len(sink.got) >= n, timeout=15)
        xs = [m.x for m in sink.got]
        assert xs == sorted(set(xs)), "duplicates or reordering"
        assert xs == list(range(n))
        await client.shutdown()
        await server.shutdown()
    run(go())


def test_lossy_connection_raises_on_failure():
    async def go():
        kr = _keyring("client.a", "osd.1")
        server = Messenger("osd.1", keyring=kr)
        server.add_dispatcher(Collector())
        addr = await server.bind()
        client = Messenger("client.a", keyring=kr,
                           inject_socket_failures=1, seed=3)
        with pytest.raises(ConnectionError_):
            for _ in range(50):
                await client.send_message(MPing(x=0, note=""), addr,
                                          "osd.1")
        await client.shutdown()
        await server.shutdown()
    run(go())


def test_throttled_dispatch_delivers_all():
    async def go():
        kr = _keyring("client.a", "osd.1")
        server = Messenger("osd.1", keyring=kr,
                           default_policy=Policy(lossy=True,
                                                 throttler_bytes=256))
        sink = Collector()
        server.add_dispatcher(sink)
        addr = await server.bind()
        client = Messenger("client.a", keyring=kr)
        for i in range(20):
            await client.send_message(
                MData(oid=f"o{i}", data=b"x" * 100, osds=[]), addr,
                "osd.1")
        await _wait(lambda: len(sink.got) == 20)
        await client.shutdown()
        await server.shutdown()
    run(go())


def test_no_auth_mode():
    async def go():
        server = Messenger("mon.a")       # no keyring: auth disabled
        sink = Collector()
        server.add_dispatcher(sink)
        addr = await server.bind()
        client = Messenger("client.a")
        await client.send_message(MPing(x=3, note="open"), addr, "mon.a")
        await _wait(lambda: sink.got)
        assert sink.got[0].x == 3
        await client.shutdown()
        await server.shutdown()
    run(go())


def test_message_registry_duplicate_type_rejected():
    with pytest.raises(ValueError):
        @register
        class Clash(Message):
            TYPE = 900
            FIELDS = []


def test_auth_mode_mismatch_fails_fast():
    async def go():
        server = Messenger("mon.a")               # no auth
        await server.bind()
        kr = _keyring("mon.a", "client.a")
        client = Messenger("client.a", keyring=kr)  # auth required
        with pytest.raises((AuthError, ConnectionError_, OSError,
                            asyncio.IncompleteReadError)):
            await client.send_message(MPing(x=1, note=""), server.addr,
                                      "mon.a")
        await client.shutdown()
        await server.shutdown()
    run(go())


def test_secure_mode_requires_keyring():
    with pytest.raises(ValueError):
        Messenger("osd.0", mode=MODE_SECURE)


def test_lossless_resumes_after_reader_side_abort():
    """A conn killed from the reader path must not silently lose later
    messages (the fresh handshake must inherit seq + unacked)."""
    async def go():
        kr = _keyring("osd.0", "osd.1")
        server = Messenger("osd.1", keyring=kr)
        server.set_policy("osd", Policy.lossless_peer())
        sink = Collector()
        server.add_dispatcher(sink)
        addr = await server.bind()
        client = Messenger("osd.0", keyring=kr)
        client.set_policy("osd", Policy.lossless_peer())
        await client.send_message(MPing(x=1, note=""), addr, "osd.1")
        await _wait(lambda: len(sink.got) == 1)
        # simulate a reader-side failure: abort the live connection
        conn = client.conns[addr]
        conn._abort()
        await client.send_message(MPing(x=2, note=""), addr, "osd.1")
        await _wait(lambda: len(sink.got) == 2)
        assert [m.x for m in sink.got] == [1, 2]
        await client.shutdown()
        await server.shutdown()
    run(go())


def test_concurrent_first_sends_single_connection():
    """Racing first sends must share one connection + session."""
    async def go():
        kr = _keyring("osd.0", "osd.1")
        server = Messenger("osd.1", keyring=kr)
        server.set_policy("osd", Policy.lossless_peer())
        sink = Collector()
        server.add_dispatcher(sink)
        addr = await server.bind()
        client = Messenger("osd.0", keyring=kr)
        client.set_policy("osd", Policy.lossless_peer())
        await asyncio.gather(*[
            client.send_message(MPing(x=i, note="race"), addr, "osd.1")
            for i in range(10)])
        await _wait(lambda: len(sink.got) == 10)
        assert sorted(m.x for m in sink.got) == list(range(10))
        assert len(client.conns) == 1
        await client.shutdown()
        await server.shutdown()
    run(go())


def test_crc_vs_secure_mode_mismatch_fails_fast():
    async def go():
        kr = _keyring("osd.0", "osd.1")
        server = Messenger("osd.1", keyring=kr, mode=MODE_SECURE)
        await server.bind()
        client = Messenger("osd.0", keyring=kr)   # MODE_CRC
        with pytest.raises((AuthError, ConnectionError_, OSError,
                            asyncio.IncompleteReadError)):
            await client.send_message(MPing(x=1, note=""), server.addr,
                                      "osd.1")
        await client.shutdown()
        await server.shutdown()
    run(go())


def test_key_rotation_reauths_live_secure_session():
    """AuthMonitor key rotation (round 18): both ends hold the new
    secret, so the in-band REKEY session-ticket verifies and traffic
    continues on the live session — no reconnect, no reset."""
    async def go():
        master = _keyring("osd.0", "osd.1")
        kr_srv = master.copy_for("osd.0", "osd.1")
        kr_cli = master.copy_for("osd.0", "osd.1")
        server = Messenger("osd.1", keyring=kr_srv, mode=MODE_SECURE)
        server.set_policy("osd", Policy.lossless_peer())
        sink = Collector()
        server.add_dispatcher(sink)
        addr = await server.bind()
        client = Messenger("osd.0", keyring=kr_cli, mode=MODE_SECURE)
        client.set_policy("osd", Policy.lossless_peer())
        reply = Collector()
        client.add_dispatcher(reply)
        await client.send_message(MPing(x=1, note=""), addr, "osd.1")
        await _wait(lambda: len(sink.got) == 1)
        conn = client.conns[addr]
        epoch0 = conn._tx_epoch
        # paxos commits the rotation: every keyring copy gets the new
        # secret, each messenger re-keys the entity's live sessions
        newkey = master.generate_key()
        kr_srv.set_key("osd.0", newkey)
        kr_cli.set_key("osd.0", newkey)
        await _wait(lambda: conn._tx_epoch > epoch0)
        for i in range(2, 6):
            await client.send_message(MPing(x=i, note=""), addr,
                                      "osd.1")
        await _wait(lambda: len(sink.got) == 5)
        assert [m.x for m in sink.got] == [1, 2, 3, 4, 5]
        assert sink.resets == 0 and reply.resets == 0
        assert not conn.closed
        await client.shutdown()
        await server.shutdown()
    run(go())


def test_key_rotation_skew_fences_session():
    """Only ONE side saw the rotation: the REKEY ticket no longer
    proves possession of the peer's notion of the secret, so the peer
    fences the session instead of silently relabeling epochs. The
    reconnect then fails full mutual auth (keys genuinely differ)."""
    async def go():
        master = _keyring("osd.0", "osd.1")
        kr_srv = master.copy_for("osd.0", "osd.1")
        kr_cli = master.copy_for("osd.0", "osd.1")
        server = Messenger("osd.1", keyring=kr_srv, mode=MODE_SECURE)
        sink = Collector()
        server.add_dispatcher(sink)
        addr = await server.bind()
        client = Messenger("osd.0", keyring=kr_cli, mode=MODE_SECURE)
        await client.send_message(MPing(x=1, note=""), addr, "osd.1")
        await _wait(lambda: len(sink.got) == 1)
        conn = client.conns[addr]
        # rotation skew: the client rotates, the server never hears
        kr_cli.set_key("osd.0", master.generate_key())
        await _wait(lambda: sink.resets >= 1)
        await _wait(lambda: conn.closed)
        with pytest.raises((AuthError, ConnectionError_, OSError,
                            asyncio.IncompleteReadError)):
            await client.send_message(MPing(x=2, note=""), addr,
                                      "osd.1")
            # at-least-once may mask the dead socket on the first
            # write; a second send forces the failed re-handshake
            await client.send_message(MPing(x=3, note=""), addr,
                                      "osd.1")
        assert len(sink.got) == 1
        await client.shutdown()
        await server.shutdown()
    run(go())
