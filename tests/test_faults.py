"""Fault-injection layer: messenger-level fault sets + live-cluster
partition/heal + slow-op surfacing.

ref test model: the msgr fault-injection cases of
src/test/msgr/test_msgr.cc plus the qa thrash suites' partition
helpers — here driven through ceph_tpu.sim.faults installed on live
messengers and a vstart cluster.
"""

import asyncio

import pytest

from ceph_tpu.cluster.vstart import Cluster
from ceph_tpu.msg import Dispatcher, Message, Messenger, register
from ceph_tpu.rados import ObjectOperationError
from ceph_tpu.sim import faults as F


@register
class MFault(Message):
    TYPE = 910
    FIELDS = [("x", "u64")]


class Collector(Dispatcher):
    def __init__(self):
        self.got = []

    async def ms_dispatch(self, msg):
        if isinstance(msg, MFault):
            self.got.append(msg.x)
            return True
        return False


async def _wait(pred, timeout=10.0):
    t0 = asyncio.get_event_loop().time()
    while not pred():
        if asyncio.get_event_loop().time() - t0 > timeout:
            raise TimeoutError
        await asyncio.sleep(0.01)


def run(coro):
    return asyncio.run(coro)


async def _pair(inj):
    """client/server messenger pair with the injector installed on
    both ends."""
    server = Messenger("osd.9")
    sink = Collector()
    server.add_dispatcher(sink)
    addr = await server.bind()
    client = Messenger("client.f")
    client.faults = inj
    server.faults = inj
    return server, sink, addr, client


def test_delay_fault_delays_messages():
    async def go():
        inj = F.FaultInjector(seed=1)
        server, sink, addr, client = await _pair(inj)
        inj.install("lag", [F.delay("client.*", "osd.*", 0.3)])
        t0 = asyncio.get_event_loop().time()
        await client.send_message(MFault(x=1), addr, "osd.9")
        await _wait(lambda: sink.got)
        took = asyncio.get_event_loop().time() - t0
        assert took >= 0.3, took
        # healing removes the delay
        inj.clear("lag")
        t0 = asyncio.get_event_loop().time()
        await client.send_message(MFault(x=2), addr, "osd.9")
        await _wait(lambda: len(sink.got) == 2)
        assert asyncio.get_event_loop().time() - t0 < 0.25
        await client.shutdown()
        await server.shutdown()
    run(go())


def test_duplicate_fault_sends_twice_with_distinct_seqs():
    """Message-level duplication delivers the payload twice under
    distinct seqs — proving end-to-end dedup (PG reqid tables) is
    what must make ops exactly-once, not the transport."""
    async def go():
        inj = F.FaultInjector(seed=1)
        server, sink, addr, client = await _pair(inj)
        inj.install("dup", [F.duplicate("client.*", "osd.*",
                                        prob=1.0)])
        await client.send_message(MFault(x=7), addr, "osd.9")
        await _wait(lambda: len(sink.got) == 2)
        assert sink.got == [7, 7]
        await client.shutdown()
        await server.shutdown()
    run(go())


def test_reorder_fault_overtakes_next_message():
    async def go():
        inj = F.FaultInjector(seed=1)
        server, sink, addr, client = await _pair(inj)
        conn = await client.connect(addr, "osd.9")
        inj.install("swap", [F.reorder("client.*", "osd.*", prob=1.0,
                                       hold_s=2.0)])
        # concurrent sends: the first is held until the second passes
        await asyncio.gather(conn.send_message(MFault(x=1)),
                             conn.send_message(MFault(x=2)))
        await _wait(lambda: len(sink.got) == 2)
        assert sink.got == [2, 1], sink.got
        await client.shutdown()
        await server.shutdown()
    run(go())


def test_reorder_hold_bound_never_loses_a_lone_message():
    async def go():
        inj = F.FaultInjector(seed=1)
        server, sink, addr, client = await _pair(inj)
        inj.install("swap", [F.reorder("client.*", "osd.*", prob=1.0,
                                       hold_s=0.2)])
        await client.send_message(MFault(x=5), addr, "osd.9")
        await _wait(lambda: sink.got)      # released by the bound
        assert sink.got == [5]
        await client.shutdown()
        await server.shutdown()
    run(go())


def test_one_way_drop_is_a_silent_blackhole():
    async def go():
        inj = F.FaultInjector(seed=1)
        server, sink, addr, client = await _pair(inj)
        conn = await client.connect(addr, "osd.9")
        inj.install("hole", [F.drop("client.*", "osd.9")])
        await conn.send_message(MFault(x=1))   # swallowed, no error
        await asyncio.sleep(0.2)
        assert sink.got == []
        inj.clear("hole")
        await conn.send_message(MFault(x=2))
        await _wait(lambda: sink.got)
        assert sink.got == [2]
        await client.shutdown()
        await server.shutdown()
    run(go())


def test_partition_cuts_both_connects_and_established_conns():
    async def go():
        from ceph_tpu.msg.messenger import ConnectionError_
        inj = F.FaultInjector(seed=1)
        server, sink, addr, client = await _pair(inj)
        conn = await client.connect(addr, "osd.9")
        inj.install("split", [F.partition("client.f", "osd.9")])
        with pytest.raises(ConnectionError_):
            await conn.send_message(MFault(x=1))
        with pytest.raises(ConnectionError_):
            await client.connect(addr, "osd.9")
        inj.clear("split")                 # heal: traffic resumes
        await client.send_message(MFault(x=2), addr, "osd.9")
        await _wait(lambda: sink.got)
        assert sink.got == [2]
        await client.shutdown()
        await server.shutdown()
    run(go())


def test_cluster_partition_heal_converges_with_data_intact():
    """Two OSDs partitioned from each other mid-writes: the cluster
    keeps serving (min_size=2 of 3 replicas reachable), and after the
    heal it converges to clean with every acked write readable."""
    async def go():
        c = await Cluster(
            n_mons=1, n_osds=4,
            config={"mon_osd_down_out_interval": 600.0,
                    "mon_osd_min_down_reporters": 2}).start()
        try:
            inj = F.FaultInjector(seed=2)
            c.install_faults(inj)
            await c.client.pool_create("p", pg_num=8, size=3,
                                       min_size=2)
            await c.wait_for_clean(timeout=120)
            io = await c.client.open_ioctx("p")
            acked = {}
            for i in range(8):
                data = bytes([i]) * 512
                await io.write_full(f"pre{i}", data)
                acked[f"pre{i}"] = data
            inj.install("split01", [F.partition("osd.0", "osd.1")])
            # degraded-but-serving: writes must still complete (the
            # objecter retries around any primary whose replica set
            # straddles the cut; generous timeout for the storm)
            for i in range(6):
                data = bytes([100 + i]) * 512
                await io.write_full(f"mid{i}", data, timeout=60.0)
                acked[f"mid{i}"] = data
            inj.clear("split01")
            await c.wait_for_clean(timeout=240)
            for oid, data in acked.items():
                assert await io.read(oid) == data, oid
        finally:
            await c.stop()
    run(go())


def test_partitioned_target_fails_cleanly_and_feeds_slow_ops():
    """A client partitioned from every OSD: ops fail with -ETIMEDOUT
    (bounded retry, no hang) and the stuck server-side op surfaces as
    a SLOW_OPS health warning sourced from the OSD's OpTracker."""
    async def go():
        c = await Cluster(
            n_mons=1, n_osds=3,
            config={"mon_osd_down_out_interval": 600.0,
                    "mon_osd_min_down_reporters": 2,
                    "osd_op_complaint_time": 0.3}).start()
        try:
            inj = F.FaultInjector(seed=3)
            c.install_faults(inj)
            await c.client.pool_create("p", pg_num=4, size=3,
                                       min_size=2)
            await c.wait_for_clean(timeout=120)
            io = await c.client.open_ioctx("p")
            await io.write_full("ok", b"fine")
            # cut the client off from every osd: a write must fail
            # cleanly inside its timeout instead of hanging
            inj.install("isolate",
                        [F.partition("client.admin", "osd.*")])
            t0 = asyncio.get_event_loop().time()
            with pytest.raises(ObjectOperationError) as ei:
                await io.write_full("stuck", b"x" * 64, timeout=2.0)
            took = asyncio.get_event_loop().time() - t0
            assert ei.value.errno == -110
            assert took < 10, took
            inj.clear("isolate")
            # server-side: blackhole every osd -> osd.0 frame (rep ops
            # into osd.0, or acks back when osd.0 is primary) without
            # tripping the 2-reporter failure threshold, so any write
            # wedges at its primary, ages past the complaint time, and
            # surfaces in the health report
            inj.install("ack-hole", [F.drop("osd.*", "osd.0")])
            write = asyncio.ensure_future(
                io.write_full("slow", b"y" * 64, timeout=60.0))
            try:
                await _wait(lambda: any(
                    len(o.op_tracker.slow_ops()) > 0
                    for o in c.osds), timeout=20.0)
                status = None
                for _ in range(60):
                    status = await c.client.status()
                    if "SLOW_OPS" in status["health"]["checks"]:
                        break
                    await asyncio.sleep(0.2)
                assert "SLOW_OPS" in status["health"]["checks"], \
                    status["health"]
            finally:
                inj.clear_all()
                write.cancel()
                try:
                    await write
                except (asyncio.CancelledError, ObjectOperationError):
                    pass
        finally:
            await c.stop()
    run(go())


def test_objecter_dump_ops_records_attempts():
    """Client-side op tracking: a thrashed op's TrackedOp timeline
    records the resend attempts (the dump_historic_ops view)."""
    async def go():
        c = await Cluster(n_mons=1, n_osds=3).start()
        try:
            await c.client.pool_create("p", pg_num=4, size=3)
            await c.wait_for_clean(timeout=120)
            io = await c.client.open_ioctx("p")
            await io.write_full("a", b"1")
            hist = c.client.objecter.op_tracker.dump_historic_ops()
            assert hist["num_ops"] >= 1
            events = [e["event"] for e in hist["ops"][-1]["events"]]
            assert any(e.startswith("sent to osd.") for e in events)
            assert "reply received" in events
        finally:
            await c.stop()
    run(go())
