"""Round 19: the READ-side EC data path — the OSD decode/repair
aggregator, the bit-exact host reference decoder, the device-resident
hot-shard cache, and the one-job device scrub CRC.

ref test model: the per-op vs batched equivalence discipline of
tests/test_ec_agg.py, applied to decode. Units only (the live-cluster
acceptance rides tests/test_ec_cluster.py):

- **reference decoder** — ``decode_batch_reference`` (pure numpy, no
  jit) equals the device kernel bit for bit on BOTH kernel planes
  (GF(2^8) matmul and packet-plane bitmatrix XOR), and reconstructs
  real codewords;
- **aggregator** — concurrent decodes coalesce into fewer launches
  with lane-for-lane identical results, every flush trigger fires,
  the ``osd_ec_read_agg=off`` baseline bypasses UNPADDED, padding is
  pow2-bounded, and drain cancels cleanly;
- **degrade ladder** — a failed batched flush disaggregates and
  rejects ONLY its own poisoned waiter, per-op device retries are
  bounded, the reference decoder serves bit-exactly as the last rung,
  and repeated failures quarantine the device decode on exponential
  backoff;
- **QoS honesty** — a repair decode (charge_bytes > 0) pays a
  recovery-class size-scaled grant BEFORE queueing; client degraded
  reads (charge_bytes=0) pay nothing here (already cost-tagged at
  admission);
- **residency** — DeviceShardCache LRU/budget/invalidation semantics,
  copy-on-insert immutability, and the ECBackendLite generation
  discipline (a mutator's bump makes stale entries unreachable);
- **device scrub CRC** — ``crc.device_row_crcs`` folds to
  ``zlib.crc32`` per shard, and one sweep's digests cost ONE device
  job (the O(batches)-not-O(objects) counter pin, unit leg).

One module-scoped plugin instance: every test shares its jit cache
(tier-1 runs near the wall-clock cap — compiles are the budget).
"""

import asyncio
import time
import zlib

import numpy as np
import pytest

from ceph_tpu.ec import crc as ec_crc
from ceph_tpu.ec.jax_plugin import DeviceShardCache, ErasureCodeJax
from ceph_tpu.osd.ec_read_aggregator import ECReadAggregator

K, M, C = 3, 2, 64
N = K + M
WANT = (0,)             # data chunk 0 lost
AVAIL = (1, 2, 3)       # survivors: data 1..2 + parity 0


@pytest.fixture(scope="module")
def ec():
    return ErasureCodeJax(
        f"plugin=jax k={K} m={M} technique=reed_sol_van")


def _rng(seed=19):
    return np.random.default_rng(seed)


def run(coro):
    return asyncio.run(coro)


def _codeword(ec, rng, b):
    """(b, N, C) real codeword batch + its data half."""
    data = rng.integers(0, 256, (b, K, C), dtype=np.uint8)
    parity = np.asarray(ec.encode_batch(data))
    return np.concatenate([data, parity], axis=1), data


def _survivors(word):
    return np.stack([word[:, i, :] for i in AVAIL], axis=1)


# -- the reference decoder -------------------------------------------------

def test_reference_decoder_bit_exact_both_planes(ec):
    """``decode_batch_reference`` equals the device decode bit for bit
    on both kernel planes, and reconstructs real codewords."""
    rng = _rng(1)
    word, data = _codeword(ec, rng, 4)
    chunks = _survivors(word)
    ref = np.asarray(ec.decode_batch_reference(WANT, AVAIL, chunks))
    dev = np.asarray(ec.decode_batch(WANT, AVAIL, chunks))
    assert (ref == dev).all()
    assert (ref[:, 0, :] == data[:, 0, :]).all()   # actual recovery
    # packet-plane bitmatrix (liberation, w=7): same contract
    lib = ErasureCodeJax("plugin=jax k=4 m=2 technique=liberation w=7")
    dl = rng.integers(0, 256, (2, 4, 56), dtype=np.uint8)   # C = 8w
    pl = np.asarray(lib.encode_batch(dl))
    wl = np.concatenate([dl, pl], axis=1)
    av = (1, 2, 3, 4)
    ch = np.stack([wl[:, i, :] for i in av], axis=1)
    assert (np.asarray(lib.decode_batch_reference((0,), av, ch)) ==
            np.asarray(lib.decode_batch((0,), av, ch))).all()


# -- the aggregator --------------------------------------------------------

def test_read_aggregator_coalesces_bit_exact(ec):
    """Concurrent decodes (non-pow2 sizes) coalesce into FEWER
    launches than ops, and every op's slice equals its own per-op
    decode lane for lane."""
    rng = _rng(2)
    ops = [_survivors(_codeword(ec, rng, b)[0])
           for b in (1, 3, 2, 5, 1, 3, 2)]

    async def go():
        agg = ECReadAggregator({"osd_ec_read_agg": True,
                                "osd_ec_read_agg_window_us": 2000.0})
        outs = await asyncio.gather(*[
            agg.decode(ec, WANT, AVAIL, d) for d in ops])
        d = agg.dump()
        assert 1 <= d["batches"] < len(ops)
        assert d["ops"] == len(ops)
        assert d["stripes"] == sum(o.shape[0] for o in ops)
        for i, (chunks, out) in enumerate(zip(ops, outs)):
            assert (np.asarray(out) == np.asarray(
                ec.decode_batch(WANT, AVAIL, chunks))).all(), i
    run(go())


def test_read_aggregator_groups_by_erasure_pattern(ec):
    """Ops with DIFFERENT (avail, want) never share a launch — the
    group key is the decode-kernel cache key."""
    rng = _rng(3)
    word, _ = _codeword(ec, rng, 2)
    a = _survivors(word)
    b = np.stack([word[:, i, :] for i in (0, 2, 4)], axis=1)

    async def go():
        agg = ECReadAggregator({"osd_ec_read_agg": True,
                                "osd_ec_read_agg_window_us": 2000.0})
        oa, ob = await asyncio.gather(
            agg.decode(ec, WANT, AVAIL, a),
            agg.decode(ec, (1,), (0, 2, 4), b))
        assert agg.dump()["batches"] == 2    # distinct groups
        assert (np.asarray(oa) == np.asarray(
            ec.decode_batch(WANT, AVAIL, a))).all()
        assert (np.asarray(ob) == np.asarray(
            ec.decode_batch((1,), (0, 2, 4), b))).all()
    run(go())


def test_read_aggregator_full_trigger(ec):
    """``osd_ec_read_agg_max_stripes`` forces an immediate flush."""
    rng = _rng(4)

    async def go():
        agg = ECReadAggregator({"osd_ec_read_agg": True,
                                "osd_ec_read_agg_window_us": 1e6,
                                "osd_ec_read_agg_max_stripes": 4})
        ops = [_survivors(_codeword(ec, rng, 2)[0]) for _ in range(4)]
        t0 = asyncio.get_event_loop().time()
        await asyncio.gather(*[agg.decode(ec, WANT, AVAIL, d)
                               for d in ops])
        took = asyncio.get_event_loop().time() - t0
        assert agg.dump()["flushes"]["full"] >= 1
        assert took < 1.0      # nobody waited for the 1s window
    run(go())


def test_read_aggregator_lone_op_never_held_past_window(ec):
    """A lone degraded read flushes EARLY on queue idleness."""
    rng = _rng(5)

    async def go():
        agg = ECReadAggregator({"osd_ec_read_agg": True,
                                "osd_ec_read_agg_window_us": 10e6})
        d = _survivors(_codeword(ec, rng, 1)[0])
        t0 = asyncio.get_event_loop().time()
        out = await agg.decode(ec, WANT, AVAIL, d)
        took = asyncio.get_event_loop().time() - t0
        assert (np.asarray(out) == np.asarray(
            ec.decode_batch(WANT, AVAIL, d))).all()
        assert took < 9.0, "lone op pinned to the window"
        assert agg.dump()["flushes"]["idle"] == 1
    run(go())


def test_read_aggregator_off_is_per_op_baseline(ec):
    """``osd_ec_read_agg=off`` (read LIVE) serves every decode per-op
    and UNPADDED: no batches, a bypass count, identical results — the
    measured baseline the bench compares against."""
    rng = _rng(6)
    ops = [_survivors(_codeword(ec, rng, 3)[0]) for _ in range(3)]
    launched = []

    class _Spy:
        profile = "spy"

        def decode_batch(self, want, avail, chunks):
            launched.append(chunks.shape[0])
            return ec.decode_batch(want, avail, chunks)

    async def go():
        cfg = {"osd_ec_read_agg": False}
        agg = ECReadAggregator(cfg)
        for d in ops:
            out = await agg.decode(_Spy(), WANT, AVAIL, d)
            assert (np.asarray(out) == np.asarray(
                ec.decode_batch(WANT, AVAIL, d))).all()
        dmp = agg.dump()
        assert dmp["batches"] == 0 and dmp["bypass"] == len(ops)
        assert dmp["enabled"] is False
        assert launched == [3, 3, 3]     # UNPADDED per-op launches
        # live flip back on: the same instance coalesces again
        cfg["osd_ec_read_agg"] = True
        await asyncio.gather(*[agg.decode(ec, WANT, AVAIL, d)
                               for d in ops])
        assert agg.dump()["batches"] >= 1
    run(go())


def test_read_aggregator_pads_to_pow2(ec):
    """Padded flush launches bound the jit cache to O(log max_batch)
    shapes, and the pad rows never leak into results."""
    for b, want in ((1, 1), (2, 2), (3, 4), (5, 8), (9, 16),
                    (4096, 4096)):
        assert ECReadAggregator._pad(b) == want, b
    rng = _rng(7)
    d = _survivors(_codeword(ec, rng, 5)[0])    # pads to 8
    launched = []

    class _Spy:
        profile = "spy"

        def decode_batch(self, want, avail, chunks):
            launched.append(chunks.shape[0])
            return ec.decode_batch(want, avail, chunks)

    agg = ECReadAggregator({})
    out = agg._run(_Spy(), WANT, AVAIL, d)
    assert launched == [8]              # flush path pads 5 -> 8
    assert out.shape == (5, len(WANT), C)
    assert (out == np.asarray(ec.decode_batch(WANT, AVAIL, d))).all()
    out2 = agg._run(_Spy(), WANT, AVAIL, d, pad=False)
    assert launched == [8, 5]           # the bypass baseline: unpadded
    assert (out2 == out).all()


def test_read_aggregator_drain_cancels_waiters(ec):
    """Daemon stop: pending waiters are CANCELLED, timers die, and the
    stopped aggregator serves later stragglers per-op."""
    rng = _rng(8)

    async def go():
        agg = ECReadAggregator({"osd_ec_read_agg": True,
                                "osd_ec_read_agg_window_us": 10e6,
                                "osd_ec_read_agg_max_stripes": 1 << 20})
        d = _survivors(_codeword(ec, rng, 1)[0])
        waiter = asyncio.ensure_future(agg.decode(ec, WANT, AVAIL, d))
        await asyncio.sleep(0)          # entry lands, timer armed
        assert agg.drain() == 1
        with pytest.raises(asyncio.CancelledError):
            await waiter
        assert agg.dump()["pending_ops"] == 0
        out = await agg.decode(ec, WANT, AVAIL, d)   # straggler
        assert (np.asarray(out) == np.asarray(
            ec.decode_batch(WANT, AVAIL, d))).all()
    run(go())


# -- the degrade ladder ----------------------------------------------------

class _FlakyDecodeEC:
    """Delegates to the module plugin but fails on command: device
    decodes raise while a ``poison`` chunk batch rides along (or
    always, with ``fail_all``), and the reference decoder refuses the
    poison batch itself — the worst case the ladder must isolate."""

    profile = "flaky"

    def __init__(self, ec, poison=None, fail_all=False):
        self._ec = ec
        self._poison = poison
        self.fail_all = fail_all
        self.device_calls = 0

    def _poisoned(self, chunks):
        return self._poison is not None and \
            bool((chunks == self._poison).all(axis=(1, 2)).any())

    def decode_batch(self, want, avail, chunks):
        self.device_calls += 1
        if self.fail_all or self._poisoned(np.asarray(chunks)):
            raise RuntimeError("injected device failure")
        return self._ec.decode_batch(want, avail, chunks)

    def decode_batch_reference(self, want, avail, chunks):
        if self._poisoned(np.asarray(chunks)):
            raise RuntimeError("reference refuses the poison batch")
        return self._ec.decode_batch_reference(want, avail, chunks)


def test_read_flush_failure_rejects_only_the_poisoned_op(ec):
    """A failed batched flush DISAGGREGATES: each batchmate retries
    per-op and is served lane-for-lane exactly; only the op whose
    chunks fail even under the reference decoder sees the exception."""
    rng = _rng(9)
    good = [_survivors(_codeword(ec, rng, 2)[0]) for _ in range(2)]
    poison = np.full((1, len(AVAIL), C), 0xAB, dtype=np.uint8)
    flaky = _FlakyDecodeEC(ec, poison=0xAB)

    async def go():
        agg = ECReadAggregator({"osd_ec_read_agg": True,
                                "osd_ec_read_agg_window_us": 2000.0,
                                "osd_ec_fallback_retries": 1})
        outs = await asyncio.gather(
            agg.decode(flaky, WANT, AVAIL, good[0]),
            agg.decode(flaky, WANT, AVAIL, poison),
            agg.decode(flaky, WANT, AVAIL, good[1]),
            return_exceptions=True)
        for i, chunks in ((0, good[0]), (2, good[1])):
            assert (np.asarray(outs[i]) == np.asarray(
                ec.decode_batch(WANT, AVAIL, chunks))).all(), i
        assert isinstance(outs[1], RuntimeError)
        d = agg.perf.dump()
        assert d.get("flush_failures", 0) == 1
        assert d.get("per_op_retries", 0) >= 1
        assert agg.dump()["pending_ops"] == 0
        # the aggregator stays LIVE after a failed flush
        out = await agg.decode(flaky, WANT, AVAIL, good[0])
        assert (np.asarray(out) == np.asarray(
            ec.decode_batch(WANT, AVAIL, good[0]))).all()
    run(go())


def test_read_degrade_ladder_reference_and_quarantine(ec):
    """Device decode hard-down: the op is served by the bit-exact
    reference decoder after bounded retries; repeated failures
    quarantine the device (later ops go straight to the reference,
    zero device calls), and the quarantine expires on backoff."""
    rng = _rng(10)
    d = _survivors(_codeword(ec, rng, 3)[0])
    flaky = _FlakyDecodeEC(ec, fail_all=True)

    async def go():
        agg = ECReadAggregator({
            "osd_ec_read_agg": False,    # bypass: per-op ladder
            "osd_ec_fallback_retries": 1,
            "osd_ec_fallback_quarantine_base": 0.05,
            "osd_ec_fallback_quarantine_max": 0.2})
        out = await agg.decode(flaky, WANT, AVAIL, d)
        assert (np.asarray(out) == np.asarray(
            ec.decode_batch(WANT, AVAIL, d))).all()
        dmp = agg.perf.dump()
        assert dmp.get("per_op_retries", 0) == 1
        assert dmp.get("fallback_ops", 0) == 1
        calls = flaky.device_calls           # initial try + 1 retry
        assert calls == 2
        # quarantined: the next op never touches the device
        out = await agg.decode(flaky, WANT, AVAIL, d)
        assert (np.asarray(out) == np.asarray(
            ec.decode_batch(WANT, AVAIL, d))).all()
        assert flaky.device_calls == calls
        assert agg.perf.dump().get("quarantined_ops", 0) == 1
        # past the backoff deadline the device is probed again
        time.sleep(0.06)
        await agg.decode(flaky, WANT, AVAIL, d)
        assert flaky.device_calls > calls
        assert agg._dev_failures == 2        # backoff doubled
    run(go())


# -- QoS honesty -----------------------------------------------------------

class _StubScheduler:
    def __init__(self):
        self.grants = []

    async def grant(self, op_class, key=None, cost=1.0):
        self.grants.append((op_class, float(cost)))


def test_repair_decode_charges_recovery_grant(ec):
    """charge_bytes > 0 (a rebuild/backfill decode) pays a
    recovery-class grant at the bytes/osd_qos_cost_per_io_bytes
    divisor BEFORE queueing; charge_bytes=0 (a client degraded read,
    already cost-tagged at admission) pays nothing here."""
    rng = _rng(11)
    d = _survivors(_codeword(ec, rng, 2)[0])
    sched = _StubScheduler()

    async def go():
        agg = ECReadAggregator(
            {"osd_ec_read_agg": False,
             "osd_qos_cost_per_io_bytes": 4096},
            scheduler=sched)
        await agg.decode(ec, WANT, AVAIL, d,
                         charge_bytes=int(d.nbytes))
        assert len(sched.grants) == 1
        op_class, cost = sched.grants[0]
        assert op_class == "recovery"
        assert cost == pytest.approx(max(1.0, d.nbytes / 4096))
        assert agg.perf.dump().get("qos_grants", 0) == 1
        # client degraded read: no double charge
        await agg.decode(ec, WANT, AVAIL, d, charge_bytes=0)
        assert len(sched.grants) == 1
    run(go())


# -- hot-shard residency ---------------------------------------------------

def test_device_shard_cache_lru_budget_invalidate():
    """LRU order, byte budget, oversized reject, prefix invalidation,
    budget-0 disable, and copy-on-insert immutability."""
    ent = np.zeros((2, 3, C), dtype=np.uint8)     # 384 bytes each
    cfg = {"osd_ec_resident_bytes": 3 * ent.nbytes}
    cache = DeviceShardCache(cfg)
    for i in range(3):
        cache.put(("pg1", f"o{i}", 0), np.full_like(ent, i))
    assert cache.get(("pg1", "o0", 0)) is not None   # o0 -> MRU
    cache.put(("pg1", "o3", 0), np.full_like(ent, 3))
    assert cache.get(("pg1", "o1", 0)) is None       # LRU evicted
    assert cache.get(("pg1", "o0", 0)) is not None
    d = cache.perf.dump()
    assert d.get("evictions", 0) == 1
    # oversized single entry: rejected, cache unchanged
    cache.put(("pg1", "big", 0),
              np.zeros(4 * ent.nbytes, dtype=np.uint8))
    assert cache.perf.dump().get("rejected", 0) == 1
    # prefix invalidation drops only the matching object's entries
    cache.put(("pg2", "oX", 0), ent)
    n = cache.invalidate("pg1")
    assert n >= 2 and cache.get(("pg2", "oX", 0)) is not None
    assert cache.get(("pg1", "o0", 0)) is None
    # copy-on-insert: mutating the source after put can't corrupt
    src = np.full_like(ent, 7)
    cache.put(("pg2", "oY", 0), src)
    src[:] = 0
    assert (np.asarray(cache.get(("pg2", "oY", 0))) == 7).all()
    # budget 0 disables lookups AND inserts
    off = DeviceShardCache({"osd_ec_resident_bytes": 0})
    off.put(("k",), ent)
    assert off.get(("k",)) is None and not off.enabled()


def test_ec_backend_residency_generation_discipline(ec):
    """ECBackendLite with residency on: repeated reads hit the cache;
    every mutator (write/lose_shard/recover) bumps the generation so
    RMW merges never see stale device bytes — readback stays exact."""
    from ceph_tpu.osd.ec_backend import ECBackendLite
    be = ECBackendLite(ec, chunk_size=C,
                       config={"osd_ec_resident_bytes": 1 << 20})
    assert be.resident is not None
    rng = _rng(12)
    payload = rng.integers(0, 256, 2 * K * C, dtype=np.uint8).tobytes()
    be.write("obj", 0, payload)
    assert be.read("obj", 0, len(payload)) == payload    # miss + pin
    h0 = be.resident.perf.dump().get("hits", 0)
    assert be.read("obj", 0, len(payload)) == payload    # device hit
    assert be.resident.perf.dump().get("hits", 0) > h0
    # a mutator bumps the generation: the stale pin is unreachable
    # and the RMW merge never sees old device bytes
    be.write("obj", 10, b"\xDD" * 40)
    want = bytearray(payload)
    want[10:50] = b"\xDD" * 40
    assert be.read("obj", 0, len(payload)) == bytes(want)
    assert be.read("obj", 0, len(payload)) == bytes(want)  # fresh hit
    # recovery after shard loss still reads back exactly (gen bumped)
    be.lose_shard(0, "obj")
    assert be.recover("obj") == {0}
    assert be.read("obj", 0, len(payload)) == bytes(want)


# -- one-job device scrub CRC ----------------------------------------------

def test_device_row_crcs_fold_to_zlib():
    """(R, C) device row CRCs fold per shard to zlib.crc32 exactly —
    the byte-equality the one-job scrub stands on."""
    rng = _rng(13)
    rows = rng.integers(0, 256, (12, C), dtype=np.uint8)
    rcs = ec_crc.device_row_crcs(rows)
    assert rcs.shape == (12,) and rcs.dtype == np.uint32
    assert int(ec_crc.shard_crc32(rcs, C)) == zlib.crc32(rows.tobytes())
    # multi-shard fold (the _deep_ec_check layout: (count, m).T)
    per = rcs.reshape(4, 3).transpose()           # 3 shards x 4 rows
    got = [int(x) for x in ec_crc.shard_crc32(per, C)]
    want = [zlib.crc32(rows.reshape(4, 3, C)[:, s, :].tobytes())
            for s in range(3)]
    assert got == want


def test_scrub_sweep_digests_are_one_device_job():
    """The build_scrub_map sweep digests every C-divisible object in
    ONE device CRC launch (counter-pinned); ragged/empty payloads fall
    back to host zlib, byte-identically."""
    from ceph_tpu.osd.scrub import SCRUB_PERF, _device_digests

    class _Pool:
        def is_erasure(self):
            return True

    class _Sinfo:
        chunk_size = C

    class _PG:
        pool = _Pool()
        sinfo = _Sinfo()
        pgid = "9.0"

    rng = _rng(14)
    loaded = [(f"o{i}", rng.integers(0, 256, (i + 1) * C,
                                     dtype=np.uint8).tobytes(),
               {}, {}) for i in range(6)]
    loaded.append(("ragged", b"\x01" * (C + 3), {}, {}))
    loaded.append(("empty", b"", {}, {}))
    before = SCRUB_PERF.dump()
    digests = _device_digests(_PG(), loaded)
    after = SCRUB_PERF.dump()
    assert after.get("device_crc_jobs", 0) - \
        before.get("device_crc_jobs", 0) == 1      # ONE job, 6 objects
    assert after.get("device_crc_rows", 0) - \
        before.get("device_crc_rows", 0) == sum(range(1, 7))
    assert set(digests) == {f"o{i}" for i in range(6)}
    for oid, data, _a, _o in loaded[:6]:
        assert digests[oid] == zlib.crc32(data), oid
    # replicated PGs never touch the device path

    class _RepPool:
        def is_erasure(self):
            return False

    class _RepPG:
        pool = _RepPool()
        sinfo = None
        pgid = "9.1"

    assert _device_digests(_RepPG(), loaded) == {}
