"""Monitor tier: elections, paxos, OSDMonitor, MonClient.

ref test model: src/test/mon/ + qa/standalone/mon — quorum formation,
replicated commits, leader failover with state preservation, and the
command surface, all over real localhost sockets.
"""

import asyncio
import json

import pytest

from ceph_tpu.mon import MonClient, Monitor, MonMap
from ceph_tpu.msg import Keyring

CFG = {"mon_election_timeout": 0.15, "mon_lease_interval": 0.1,
       "mon_lease": 0.6, "mon_paxos_timeout": 1.0,
       "mon_tick_interval": 0.05, "mon_osd_min_down_reporters": 1,
       "mon_osd_down_out_interval": 0.5}


async def start_mons(n: int, cfg=None):
    """Bind messengers first so the monmap has real ports, then start."""
    cfg = dict(CFG, **(cfg or {}))
    names = "abcde"[:n]
    monmap = MonMap()
    mons = []
    for rank, name in enumerate(names):
        monmap.add(name, rank, "127.0.0.1", 0)
    # two-phase: create + bind, patch monmap ports, then elect
    for rank, name in enumerate(names):
        mon = Monitor(name, monmap, config=cfg)
        addr = await mon.msgr.bind()
        monmap.mons[name] = (rank, addr.host, addr.port)
        mons.append(mon)
    for mon in mons:
        mon._tick_task = asyncio.ensure_future(mon._tick_loop())
    for mon in mons:
        await mon.elector.start()
    return mons, monmap


async def wait_for(pred, timeout=8.0, msg="condition"):
    t0 = asyncio.get_event_loop().time()
    while not pred():
        if asyncio.get_event_loop().time() - t0 > timeout:
            raise TimeoutError(f"timeout waiting for {msg}")
        await asyncio.sleep(0.02)


async def wait_quorum(mons, expect=None):
    live = [m for m in mons if not m._stopped]
    expect = expect if expect is not None else len(live)
    await wait_for(
        lambda: any(m.is_leader() and len(m.quorum) >= expect and
                    m.paxos.active for m in live),
        msg="quorum")
    return next(m for m in live if m.is_leader() and
                len(m.quorum) >= expect)


async def stop_all(mons, clients=()):
    for c in clients:
        await c.shutdown()
    for m in mons:
        if not m._stopped:
            await m.stop()


def run(coro):
    asyncio.run(coro)


def test_single_mon_bootstrap():
    async def go():
        mons, monmap = await start_mons(1)
        leader = await wait_quorum(mons)
        assert leader.quorum == [0]
        await wait_for(lambda: leader.osdmon.osdmap is not None,
                       msg="initial osdmap")
        assert leader.osdmon.osdmap.epoch >= 1
        await stop_all(mons)
    run(go())


def test_osd_down_command_rejects_bad_ids():
    """`osd down` guards its id like the failure/mark-me-down paths:
    an out-of-range id must not commit (apply would index past
    osd_state), and a NEGATIVE id must not silently mark — and, with
    the round-15 down_at stamp, later auto-out — the LAST osd via
    numpy negative indexing."""
    async def go():
        mons, monmap = await start_mons(1)
        leader = await wait_quorum(mons)
        await wait_for(lambda: leader.osdmon.osdmap is not None,
                       msg="initial osdmap")
        max_osd = leader.osdmon.osdmap.max_osd
        for bad in (-1, max_osd, max_osd + 7):
            ret, rs, _ = await leader.handle_command(
                {"prefix": "osd down", "id": bad})
            assert ret == -22, (bad, ret, rs)
        assert not leader.osdmon.down_at
        # already-down id (a created-but-never-booted OSD): succeed
        # WITHOUT proposing (no epoch bump, no down_at re-stamp the
        # tick could never clear)
        ret, _, _ = await leader.handle_command(
            {"prefix": "osd new", "id": 0})
        assert ret == 0
        epoch = leader.osdmon.osdmap.epoch
        ret, rs, _ = await leader.handle_command(
            {"prefix": "osd down", "id": 0})
        assert ret == 0 and "already down" in rs, (ret, rs)
        assert leader.osdmon.osdmap.epoch == epoch
        assert not leader.osdmon.down_at
        await stop_all(mons)
    run(go())


def test_three_mon_quorum_and_replication():
    async def go():
        mons, monmap = await start_mons(3)
        leader = await wait_quorum(mons)
        assert leader.rank == 0          # lowest rank wins
        assert sorted(leader.quorum) == [0, 1, 2]
        # commit a config value through paxos; all mons converge
        ret, rs, _ = await leader.handle_command(
            {"prefix": "config set", "who": "global",
             "name": "debug_osd", "value": "10"})
        assert ret == 0
        await wait_for(lambda: all(
            m.store.get("config", "global/debug_osd") == b"10"
            for m in mons), msg="config replication")
        # every mon's paxos log agrees
        await wait_for(lambda: len({m.paxos.last_committed
                                    for m in mons}) == 1,
                       msg="paxos convergence")
        await stop_all(mons)
    run(go())


def test_leader_failover_preserves_state():
    async def go():
        mons, monmap = await start_mons(3)
        leader = await wait_quorum(mons)
        ret, _, _ = await leader.handle_command(
            {"prefix": "config set", "who": "global",
             "name": "key1", "value": "v1"})
        assert ret == 0
        # kill the leader; a new one must take over with the state
        await leader.stop()
        survivors = [m for m in mons if m is not leader]
        new_leader = await wait_quorum(mons, expect=2)
        assert new_leader in survivors
        assert sorted(new_leader.quorum) == sorted(
            m.rank for m in survivors)
        # committed state survived
        assert new_leader.store.get("config", "global/key1") == b"v1"
        # and new commits still work with the reduced quorum
        ret, _, _ = await new_leader.handle_command(
            {"prefix": "config set", "who": "global",
             "name": "key2", "value": "v2"})
        assert ret == 0
        await wait_for(lambda: all(
            m.store.get("config", "global/key2") == b"v2"
            for m in survivors), msg="post-failover replication")
        await stop_all(mons)
    run(go())


def test_monclient_commands_and_redirect():
    async def go():
        mons, monmap = await start_mons(3)
        leader = await wait_quorum(mons)
        mc = MonClient("client.admin", monmap)
        # force the client to start at a peon: it must follow redirects
        mc._cur_rank = 2
        ret, rs, outbl = await mc.command({"prefix": "status"})
        assert ret == 0
        status = json.loads(outbl)
        assert status["quorum"] == [0, 1, 2]
        ret, rs, _ = await mc.command(
            {"prefix": "config set", "who": "global", "name": "x",
             "value": "1"})
        assert ret == 0
        ret, _, outbl = await mc.command(
            {"prefix": "config get", "who": "global", "name": "x"})
        assert ret == 0 and outbl == b"1"
        ret, _, _ = await mc.command({"prefix": "bogus nonsense"})
        assert ret == -22
        await stop_all(mons, [mc])
    run(go())


def test_osdmonitor_lifecycle_via_commands():
    async def go():
        mons, monmap = await start_mons(1)
        leader = await wait_quorum(mons)
        await wait_for(lambda: leader.osdmon.osdmap is not None,
                       msg="osdmap")
        mc = MonClient("client.admin", monmap)
        # osd new x3 + crush add
        for i in range(3):
            ret, _, out = await mc.command({"prefix": "osd new"})
            assert ret == 0
            assert json.loads(out)["osdid"] == i
            ret, rs, _ = await mc.command(
                {"prefix": "osd crush add", "id": i, "weight": 1.0,
                 "host": f"host{i}"})
            assert ret == 0, rs
        # pool create + map an object
        ret, rs, _ = await mc.command(
            {"prefix": "osd pool create", "pool": "rbd", "pg_num": 8,
             "size": 3})
        assert ret == 0, rs
        ret, _, out = await mc.command({"prefix": "osd dump"})
        dump = json.loads(out)
        assert len(dump["osds"]) == 3
        assert dump["pools"][0]["name"] == "rbd"
        ret, _, out = await mc.command(
            {"prefix": "osd map", "pool": "rbd", "object": "obj1"})
        assert ret == 0
        mapping = json.loads(out)
        assert mapping["acting_primary"] in (-1, 0, 1, 2)
        # EC profile + EC pool
        ret, rs, _ = await mc.command(
            {"prefix": "osd erasure-code-profile set", "name": "p21",
             "profile": ["k=2", "m=1", "crush-failure-domain=osd"]})
        assert ret == 0, rs
        ret, rs, _ = await mc.command(
            {"prefix": "osd pool create", "pool": "ecpool",
             "pg_num": 8, "pool_type": "erasure",
             "erasure_code_profile": "p21"})
        assert ret == 0, rs
        ret, _, out = await mc.command({"prefix": "osd pool ls"})
        pools = json.loads(out)
        assert {p["name"] for p in pools} == {"rbd", "ecpool"}
        ec = next(p for p in pools if p["name"] == "ecpool")
        assert ec["size"] == 3 and ec["type"] == "erasure"
        await stop_all(mons, [mc])
    run(go())


def test_osd_down_and_auto_out():
    async def go():
        mons, monmap = await start_mons(1)
        leader = await wait_quorum(mons)
        await wait_for(lambda: leader.osdmon.osdmap is not None,
                       msg="osdmap")
        mc = MonClient("client.admin", monmap)
        for i in range(2):
            await mc.command({"prefix": "osd new"})
            await mc.command({"prefix": "osd crush add", "id": i,
                              "weight": 1.0, "host": f"h{i}"})
        # boot them (state up) via direct handler
        from ceph_tpu.mon.messages import MOSDBoot, MOSDFailure
        for i in range(2):
            await leader.osdmon.handle(MOSDBoot(
                osd=i, addr_host="127.0.0.1", addr_port=1000 + i,
                hb_port=2000 + i, boot_epoch=0))
        om = leader.osdmon.osdmap
        assert bool(om.is_up(0)) and bool(om.is_up(1))
        assert om.osd_addrs[1][1] == 1001
        # failure report (min reporters = 1) -> down, then auto-out
        fail = MOSDFailure(target=1, failed_for=5, epoch=om.epoch,
                           reporter="osd.0")
        await leader.osdmon.handle(fail)
        await wait_for(
            lambda: not bool(leader.osdmon.osdmap.is_up(1)),
            msg="osd.1 down")
        await wait_for(
            lambda: leader.osdmon.osdmap.osd_weight[1] == 0,
            timeout=5.0, msg="osd.1 auto-out")
        # health reflects the down osd
        status = leader.get_status()
        assert status["health"]["status"] == "HEALTH_WARN"
        assert "OSD_DOWN" in status["health"]["checks"]
        await stop_all(mons, [mc])
    run(go())


def test_monclient_survives_mon_death():
    async def go():
        mons, monmap = await start_mons(3)
        leader = await wait_quorum(mons)
        mc = MonClient("client.admin", monmap)
        ret, _, _ = await mc.command({"prefix": "status"})
        assert ret == 0
        await leader.stop()
        await wait_quorum(mons, expect=2)
        # client hunts to a live mon and retries
        ret, _, out = await mc.command({"prefix": "quorum_status"},
                                       timeout=15.0)
        assert ret == 0
        q = json.loads(out)
        assert len(q["quorum"]) == 2
        await stop_all(mons, [mc])
    run(go())


def test_blocklist_expired_entries_trimmed():
    """ADVICE low #3: expired blocklist entries must disappear — from
    `osd blocklist ls` immediately, and from the MAP itself via the
    leader's periodic trim (upstream OSDMonitor trims on tick), so
    the map/encoding stops growing without bound."""
    async def go():
        mons, monmap = await start_mons(1)
        lead = await wait_quorum(mons)
        mc = MonClient("client.admin", monmap)
        try:
            ret, rs, out = await mc.command(
                {"prefix": "osd blocklist", "blocklistop": "add",
                 "addr": "client.ghost", "expire": 0.5})
            assert ret == 0, rs
            ret, _, out = await mc.command(
                {"prefix": "osd blocklist", "blocklistop": "ls"})
            assert ret == 0
            assert "client.ghost" in json.loads(out)["blocklist"]
            assert "client.ghost" in lead.osdmon.osdmap.blocklist
            # after expiry: ls filters it instantly...
            await asyncio.sleep(0.6)
            ret, _, out = await mc.command(
                {"prefix": "osd blocklist", "blocklistop": "ls"})
            assert ret == 0
            assert json.loads(out)["blocklist"] == {}
            # ...and the tick folds the removal into an incremental,
            # shrinking the authoritative map
            await wait_for(
                lambda: "client.ghost" not in
                lead.osdmon.osdmap.blocklist,
                timeout=10.0, msg="blocklist trim")
        finally:
            await stop_all(mons, [mc])
    run(go())
