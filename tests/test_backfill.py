"""Backfill: last_backfill machinery, log-continuity peering, QoS.

ref test model: qa/suites/rados/thrash with backfill_toofull /
osd-backfill-* in qa/standalone/osd — the second recovery mode.
The horizon-crossing pair is the acceptance shape from VERDICT weak
#1: write PAST the pg-log trim horizon, lose a replica, join a fresh
OSD. Without backfill the seed silently under-replicates while
reporting clean (reproduced here with ``osd_backfill: False``); with
it the PG converges with zero missing objects and full data
integrity, resumably across target restarts, under per-OSD
reservation caps.
"""

import asyncio

import pytest

from ceph_tpu.cluster.vstart import Cluster
from ceph_tpu.os_.objectstore import MemStore
from ceph_tpu.osd.pg_log import LogEntry, PGLog, eversion
from ceph_tpu.osd.recovery import AsyncReserver, RecoveryThrottle
from ceph_tpu.osd.types import MAX_OID
from ceph_tpu.sim.thrasher import Thrasher


def run(coro):
    asyncio.run(coro)


HORIZON_CFG = {
    # tiny retained log so a ~50-object working set crosses the trim
    # horizon inside the tier-1 budget (osd_min_pg_log_entries default
    # is 1000 — same machinery, production scale)
    "osd_min_pg_log_entries": 5,
    "mon_osd_down_out_interval": 600.0,
}


# -- units -----------------------------------------------------------------

def test_log_continuity():
    """continuous_with is the backfill decision: an untrimmed log can
    delta-recover anyone; a trimmed one only peers whose head is at or
    past its tail."""
    log = PGLog()
    for i in range(1, 8):
        log.append(LogEntry(eversion(1, i), f"o{i}", 1))
    assert log.continuous_with(eversion())       # never trimmed
    log.trim(keep=3)
    assert log.tail == eversion(1, 5)
    assert not log.continuous_with(eversion())   # empty-log join
    assert not log.continuous_with(eversion(1, 4))
    assert log.continuous_with(eversion(1, 5))
    assert log.continuous_with(eversion(1, 7))


def test_async_reserver_cap_and_peak():
    async def go():
        r = AsyncReserver(2)
        await r.request("a")
        await r.request("b")
        assert not r.try_request("c")
        waited = asyncio.ensure_future(r.request("c"))
        await asyncio.sleep(0)
        assert not waited.done()
        r.release("a")
        await waited
        assert r.granted == {"b", "c"}
        assert r.peak == 2                 # never exceeded the cap
        assert r.try_request("b")          # re-request is idempotent
        r.cancel("b")
        r.cancel("c")
        assert not r.granted
    run(go())


def test_recovery_throttle_rate_limits():
    async def go():
        th = RecoveryThrottle(max_active=2, bytes_per_s=100_000)
        loop = asyncio.get_event_loop()
        t0 = loop.time()
        for _ in range(4):                 # 4 x 50KB at 100KB/s
            (await th.acquire(50_000))()
        elapsed = loop.time() - t0
        # first second of burst is free; the rest must have waited
        assert elapsed >= 0.8, elapsed
        assert th.throttled_ops >= 1
    run(go())


# -- the horizon-crossing pair (VERDICT weak #1) ---------------------------

async def _write_past_horizon(c, io, n_before=10, n_after=40,
                              victim=2):
    """Write, lose `victim`, write PAST the trim horizon. Returns the
    acked data set."""
    data = {}
    for i in range(n_before + n_after):
        oid = f"o{i:04d}"
        payload = bytes([i % 256]) * 256
        await io.write_full(oid, payload)
        data[oid] = payload
        if i == n_before - 1:
            await c.kill_osd(victim)
            await c.wait_for_osd_down(victim, timeout=60)
    return data


def _replica_count(c, oid):
    return sum(1 for o in c.osds if not o._stopped
               for cid in o.store.list_collections()
               if o.store.exists(cid, oid))


def test_horizon_silent_loss_without_backfill():
    """The seed reproduction: with backfill disabled, a fresh OSD
    joining past the horizon receives only the retained log delta —
    the PG reports clean while most objects are under-replicated
    (lose the survivors next and acked data is gone)."""
    async def go():
        cfg = dict(HORIZON_CFG, osd_backfill=False)
        c = await Cluster(n_mons=1, n_osds=3, config=cfg).start()
        try:
            await c.client.pool_create("t", pg_num=2, size=3,
                                       min_size=2)
            await c.wait_for_clean(timeout=120)
            io = await c.client.open_ioctx("t")
            data = await _write_past_horizon(c, io)
            await c.revive_osd(2, store=MemStore())   # fresh join
            await c.wait_for_clean(timeout=120)       # ...it LIES
            lost = [oid for oid in data
                    if _replica_count(c, oid) < 3]
            # only the last osd_min_pg_log_entries per PG were pushed
            assert len(lost) > len(data) // 2, (
                f"expected silent under-replication, lost={len(lost)}")
        finally:
            await c.stop()
    run(go())


def test_horizon_backfill_converges():
    """The same scenario with backfill on (default): the discontinuous
    join becomes a backfill target, the scan copies all of history,
    the PG converges with ZERO missing objects on all acting OSDs, and
    per-OSD concurrent backfills never exceeded osd_max_backfills=1
    (asserted via the reservers' high-water marks)."""
    async def go():
        c = await Cluster(n_mons=1, n_osds=3,
                          config=dict(HORIZON_CFG)).start()
        try:
            await c.client.pool_create("t", pg_num=2, size=3,
                                       min_size=2)
            await c.wait_for_clean(timeout=120)
            io = await c.client.open_ioctx("t")
            data = await _write_past_horizon(c, io)
            await c.revive_osd(2, store=MemStore())
            # client writes stay serviceable DURING backfill
            await asyncio.wait_for(
                io.write_full("during-backfill", b"x" * 64),
                timeout=10.0)
            data["during-backfill"] = b"x" * 64
            await c.wait_for_clean(timeout=120)
            lost = [oid for oid in data
                    if _replica_count(c, oid) < 3]
            assert lost == [], f"under-replicated after backfill: " \
                               f"{lost[:5]} (+{len(lost)} total)"
            for oid, payload in data.items():
                assert await io.read(oid) == payload, oid
            pushed = sum(pg.backfill_stats["pushed"]
                         for o in c.osds for pg in o.pgs.values())
            assert pushed > 0, "backfill never pushed anything"
            for o in c.osds:
                assert o.local_reserver.peak <= 1, \
                    f"osd.{o.whoami} exceeded osd_max_backfills"
                assert o.remote_reserver.peak <= 1
            # every watermark retired to MAX
            for o in c.osds:
                for pg in o.pgs.values():
                    assert pg.last_backfill == MAX_OID
                    assert not pg.backfill_targets
        finally:
            await c.stop()
    run(go())


def test_backfill_resumable_across_target_restart():
    """Restart the target mid-backfill: the persisted last_backfill
    watermark survives the remount and the next backfill resumes from
    it instead of rescanning from MIN (acceptance criterion #4)."""
    async def go():
        from ceph_tpu.os_.bluestore import BlueStore
        import json as _json
        import tempfile
        tmp = tempfile.mkdtemp(prefix="bfres")
        cfg = dict(HORIZON_CFG,
                   osd_backfill_scan_max=4,
                   osd_recovery_max_bytes=60_000)   # ~30 obj/s at 2KB
        c = await Cluster(n_mons=1, n_osds=3, config=cfg).start()
        try:
            await c.client.pool_create("t", pg_num=1, size=3,
                                       min_size=2)
            await c.wait_for_clean(timeout=120)
            io = await c.client.open_ioctx("t")
            data = {}
            for i in range(60):
                oid = f"o{i:04d}"
                payload = bytes([i % 256]) * 2048
                await io.write_full(oid, payload)
                data[oid] = payload
                if i == 9:
                    await c.kill_osd(2)
                    await c.wait_for_osd_down(2, timeout=60)
            # rejoin on a persistent (BlueStore) disk so the watermark
            # survives the mid-backfill restart below
            store = BlueStore(f"{tmp}/osd2")
            await c.revive_osd(2, store=store)

            def persisted_watermark():
                st = c.osds[2].store
                for cid in st.list_collections():
                    try:
                        blob = st.omap_get(cid, "_pgmeta_").get(
                            "peering")
                    except Exception:
                        continue
                    if blob:
                        lb = _json.loads(blob).get("last_backfill",
                                                   MAX_OID)
                        if lb != MAX_OID:
                            return lb
                return None

            # wait until at least one PROGRESS persisted (lb advanced
            # past MIN but not complete), then hard-restart the target
            deadline = asyncio.get_event_loop().time() + 30
            wm = None
            while True:
                wm = persisted_watermark()
                if wm:                      # non-empty, non-MAX
                    break
                if asyncio.get_event_loop().time() > deadline:
                    raise AssertionError(
                        "no backfill progress persisted in time")
                await asyncio.sleep(0.02)
            await c.kill_osd(2)
            store.umount()
            await c.wait_for_osd_down(2, timeout=60)
            remounted = BlueStore(f"{tmp}/osd2")
            await c.revive_osd(2, store=remounted)
            await c.wait_for_clean(timeout=180)
            # the new run RESUMED: some primary recorded picking up a
            # mid-scan watermark (not MIN, not MAX)
            resumed = [pg.backfill_stats["resumed_from"]
                       for o in c.osds for pg in o.pgs.values()
                       if pg.backfill_stats["resumed_from"]]
            assert resumed, "backfill restarted from scratch"
            # the resume point is AT or PAST the watermark we saw
            # persisted before the restart — never back at MIN
            assert any(r >= wm for r in resumed), (resumed, wm)
            lost = [oid for oid in data
                    if _replica_count(c, oid) < 3]
            assert lost == [], f"under-replicated: {lost[:5]}"
            for oid, payload in data.items():
                assert await io.read(oid) == payload, oid
            errs = remounted.fsck() if hasattr(remounted, "fsck") \
                else []
            assert errs == [], errs
        finally:
            await c.stop()
    run(go())


@pytest.mark.slow
def test_horizon_backfill_ec_pool():
    """EC variant: a fresh shard-holder joining past the horizon gets
    its POSITION's shards rebuilt by the backfill scan (decode + re-
    encode), and the degraded gate keeps reads correct throughout."""
    async def go():
        c = await Cluster(n_mons=1, n_osds=4,
                          config=dict(HORIZON_CFG)).start()
        try:
            ret, rs, _ = await c.client.mon_command(
                {"prefix": "osd erasure-code-profile set",
                 "name": "p21", "profile": ["k=2", "m=1",
                                            "crush-failure-domain=osd"]})
            assert ret == 0, rs
            await c.client.pool_create("e", pg_num=2,
                                       pool_type="erasure",
                                       erasure_code_profile="p21")
            await c.wait_for_clean(timeout=120)
            io = await c.client.open_ioctx("e")
            data = {}
            for i in range(40):
                oid = f"e{i:04d}"
                payload = bytes([i % 256]) * 1024
                await io.write_full(oid, payload)
                data[oid] = payload
                if i == 7:
                    await c.kill_osd(3)
                    await c.wait_for_osd_down(3, timeout=60)
            await c.revive_osd(3, store=MemStore())
            await c.wait_for_clean(timeout=180)
            for oid, payload in data.items():
                assert await io.read(oid) == payload, oid
            # every acting shard OSD holds every object's shard
            lost = [oid for oid in data
                    if _replica_count(c, oid) < 3]
            assert lost == [], lost
        finally:
            await c.stop()
    run(go())


# -- thrasher backfill storm (satellite: sim/thrasher wiring) --------------

def test_thrasher_backfill_storm_smoke():
    """Thrasher.backfill_storm: kill, write past the horizon, revive
    with a FRESH store (the replace-an-OSD case), settle-and-verify —
    acked-data survival across the horizon proves the backfill path
    moved the history."""
    async def go():
        c = await Cluster(n_mons=1, n_osds=3,
                          config=dict(HORIZON_CFG)).start()
        try:
            await c.client.pool_create("t", pg_num=2, size=3,
                                       min_size=2)
            await c.wait_for_clean(timeout=120)
            io = await c.client.open_ioctx("t")
            th = Thrasher(c, seed=77, min_live_osds=2)
            res = await th.backfill_storm(io, writes=40,
                                          fresh_store=True)
            assert res["acked_writes"] > 30
            summary = await th.settle_and_verify(io, timeout=180)
            assert summary["acked_writes"] == res["acked_writes"]
            lost = [oid for oid in th.acked
                    if _replica_count(c, oid) < 3]
            assert lost == [], lost
        finally:
            await c.stop()
    run(go())


@pytest.mark.slow
def test_thrasher_backfill_storm_deep(tmp_path):
    """The acceptance storm on BlueStore: horizon-crossing writes
    under a concurrent partition, revive-with-remount, then a full
    settle-and-verify (clean + acked-data survival + store fsck)."""
    async def go():
        from ceph_tpu.os_.bluestore import BlueStore

        def mk(i):
            return BlueStore(str(tmp_path / f"osd{i}" / "bs"))

        stores = [mk(i) for i in range(4)]
        cfg = dict(HORIZON_CFG,
                   mon_osd_min_down_reporters=2,
                   mon_lease=4.0, mon_lease_interval=0.5,
                   mon_election_timeout=1.0, mon_paxos_timeout=8.0)
        c = await Cluster(n_mons=3, n_osds=4, stores=stores,
                          config=cfg).start()
        try:
            await c.client.pool_create("t", pg_num=8, size=3,
                                       min_size=2)
            await c.wait_for_clean(timeout=240)
            io = await c.client.open_ioctx("t")
            th = Thrasher(c, seed=4242, store_factory=mk,
                          min_live_osds=3)
            res = await th.backfill_storm(io, writes=120,
                                          partitions=1,
                                          fresh_store=True)
            assert res["acked_writes"] > 60
            summary = await th.settle_and_verify(io, timeout=600)
            # the victim was REPLACED with a fresh MemStore (no fsck);
            # the three surviving BlueStores must all fsck clean
            assert summary["fscked_stores"] == 3
            lost = [oid for oid in th.acked
                    if _replica_count(c, oid) < 3]
            assert lost == [], lost[:10]
        finally:
            await c.stop()
    run(go())


def test_resume_repairs_sub_watermark_changes_past_horizon():
    """The resume-safety criterion: while the target is down
    mid-backfill, an ALREADY-BACKFILLED object (below its watermark)
    is modified and the update then falls off the retained log. A
    naive resume would skip the sub-watermark region and leave the
    stale copy forever; the persisted ``backfill_at`` point makes
    peering either re-derive the delta (log still continuous with it)
    or restart the scan from MIN — the object must be current on the
    target after convergence either way."""
    async def go():
        cfg = dict(HORIZON_CFG,
                   osd_backfill_scan_max=4,
                   osd_recovery_max_bytes=60_000)
        c = await Cluster(n_mons=1, n_osds=3, config=cfg).start()
        try:
            await c.client.pool_create("t", pg_num=1, size=3,
                                       min_size=2)
            await c.wait_for_clean(timeout=120)
            io = await c.client.open_ioctx("t")
            for i in range(40):
                await io.write_full(f"o{i:04d}",
                                    bytes([i % 256]) * 2048)
                if i == 9:
                    await c.kill_osd(2)
                    await c.wait_for_osd_down(2, timeout=60)
            await c.revive_osd(2, store=MemStore())
            # wait for the scan to advance past o0000, then kill the
            # target mid-backfill
            pg2 = None
            deadline = asyncio.get_event_loop().time() + 30
            while True:
                pg2 = next(iter(c.osds[2].pgs.values()), None)
                if pg2 is not None and \
                        "" < pg2.last_backfill < MAX_OID:
                    break
                assert asyncio.get_event_loop().time() < deadline
                await asyncio.sleep(0.02)
            wm = pg2.last_backfill
            await c.kill_osd(2)
            await c.wait_for_osd_down(2, timeout=60)
            # modify a SUB-watermark object, then push its entry past
            # the retained log horizon (keep=5)
            changed = bytes(b"NEW!") * 512
            await io.write_full("o0000", changed)
            assert "o0000" < wm
            for i in range(10):
                await io.write_full(f"zfill{i}", b"z" * 64)
            old_store = c.osds[2].store      # keeps its pre-kill state
            await c.revive_osd(2, store=old_store)
            await c.wait_for_clean(timeout=180)
            # the stale sub-watermark copy must have been repaired
            pg2 = next(iter(c.osds[2].pgs.values()))
            assert old_store.read(pg2.cid, "o0000") == changed
            assert await io.read("o0000") == changed
        finally:
            await c.stop()
    run(go())
