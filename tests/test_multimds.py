"""Multi-active metadata plane: subtree partitioning, rank-aware
routing, two-phase migration, and the load rebalancer.

The pinned invariants (ISSUE 8):

- with N >= 2 actives serving DISJOINT subtrees under concurrent
  multi-client I/O, kill -9 one active: surviving ranks keep serving
  (writers on them ack DURING the takeover window), the failed rank's
  standby takes over fenced (zombie journal write bounces), and acked
  data is bit-identical afterwards;
- a request aimed at the wrong rank is redirected (-ESTALE naming the
  owner) and succeeds on the resend;
- the rebalancer migrates a hot subtree between LIVE ranks under
  client load with the exactly-once guarantee holding across the
  handoff (rename double-apply would surface as ENOENT).

ref test model: qa/tasks/cephfs/test_exports.py (export pins) +
mds_thrash multimds.
"""

import asyncio
import json

import pytest

from ceph_tpu.cephfs.client import CephFSClient
from ceph_tpu.cephfs.fsmap import FSMap, MDSInfo
from ceph_tpu.cephfs.mds import MDS_PERF
from ceph_tpu.cluster.vstart import Cluster
from ceph_tpu.sim.thrasher import Thrasher

# fast failover pacing (the test_mds_failover settings) + a disabled
# rebalancer so subtree placement is exactly what the test pinned
FAST_CFG = {
    "mds_beacon_interval": 0.2,
    "mds_beacon_grace": 2.0,
    "mds_reconnect_timeout": 1.0,
    "mds_replay_interval": 0.1,
    "mds_bal_interval": 0.0,
}


def run(coro):
    asyncio.run(coro)


async def _subtree_map(c) -> dict:
    ret, _, out = await c.client.mon_command(
        {"prefix": "fs subtree ls"})
    assert ret == 0
    return json.loads(out)


def test_fsmap_v2_roundtrip_and_subtree_resolution():
    """Unit pins for the v2 FSMap: encode/decode round-trip of the
    multi-active fields, default-construction compat, and the
    longest-prefix ownership rule routing relies on."""
    m = FSMap()
    m.epoch = 7
    m.max_mds = 3
    m.infos[11] = MDSInfo(gid=11, name="a", ident="mds.a.11",
                          host="h", port=9, state="active", rank=0)
    m.infos[12] = MDSInfo(gid=12, name="b", ident="mds.b.12",
                          host="h", port=10, state="active", rank=2)
    m.subtrees = {"/": 0, "/a": 1, "/a/b": 2}
    m.migrations = [{"path": "/c", "from": 0, "to": 1}]
    m.failed = [1]
    m.last_failure_osd_epoch = 5
    d = FSMap.decode(m.encode())
    assert d.max_mds == 3
    assert d.subtrees == {"/": 0, "/a": 1, "/a/b": 2}
    assert d.migrations == [{"path": "/c", "from": 0, "to": 1}]
    assert d.actives() == {0: d.infos[11], 2: d.infos[12]}
    # longest-prefix resolution: deeper pins beat ancestors, siblings
    # fall through, "/" catches the rest
    assert d.subtree_owner("/a/b/c.txt") == (2, "/a/b")
    assert d.subtree_owner("/a/bb") == (1, "/a")       # not /a/b!
    assert d.subtree_owner("/a") == (1, "/a")
    assert d.subtree_owner("/x/y") == (0, "/")
    # a default map (v1-era behavior) owns everything at rank 0
    fresh = FSMap.decode(FSMap().encode())
    assert fresh.max_mds == 1 and fresh.subtrees == {"/": 0}
    assert fresh.subtree_owner("/anything") == (0, "/")


def test_multi_active_disjoint_subtrees_kill_one_active():
    """THE acceptance storm: two actives on disjoint subtrees, two
    clients hammering them, kill -9 the rank-1 active. The rank-0
    writer must keep acking DURING the takeover (survivor assertion
    inside mds_storm), no writer may error, acked data stays
    bit-identical, and the zombie's journal write is fenced."""
    async def go():
        c = await Cluster(n_mons=1, n_osds=3, config=FAST_CFG).start()
        try:
            await c.start_fs(n_mds=3, max_mds=2)
            monmap = c.client.monc.monmap
            cl0 = await CephFSClient.create(monmap, None, "cephfs",
                                            keyring=c.keyring)
            cl1 = await CephFSClient.create(monmap, None, "cephfs",
                                            keyring=c.keyring)
            await cl0.mkdir("/w0")
            await cl0.mkdir("/w1")
            # /w1 moves to rank 1 through the two-phase migration
            # (both endpoints live); /w0 stays on rank 0 via "/"
            await c.subtree_pin("/w1", 1)
            sub = await _subtree_map(c)
            assert sub["subtrees"]["/w1"] == 1 and \
                not sub["migrations"]
            th = Thrasher(c, seed=31)
            res = await th.mds_storm(
                [cl0, cl1], writes=12, files_before_kill=4,
                kill_rank=1, writer_dirs=["/w0", "/w1"],
                survivor_writers=[0])
            assert res["errors"] == 0
            assert res["acked_writes"] == 2 * 12
            # the failed rank's successor is active; rank 0's holder
            # never moved
            st = json.loads((await c.client.mon_command(
                {"prefix": "fs dump"}))[2])
            ranks = {r["rank"]: r for r in st["ranks"]}
            assert ranks[0]["state"] == "active"
            assert ranks[1]["state"] == "active"
            assert st["subtrees"]["/w1"] == 1
            assert st["last_failure_osd_epoch"] > 0
            # cross-check through a different client than the writers
            probe = await CephFSClient.create(monmap, None, "cephfs",
                                              keyring=c.keyring)
            assert set(await probe.ls("/w1")) >= {
                f"mds-storm-31-1-{i:04d}" for i in range(12)}
            await cl0.unmount()
            await cl1.unmount()
            await probe.unmount()
        finally:
            await c.stop()
    run(go())


def test_stale_client_is_redirected_to_owner_rank():
    """-ESTALE routing: a client whose fsmap is frozen (it keeps
    routing a migrated subtree to the old rank) gets a redirect
    naming the owner, records the hint, resends, and succeeds —
    plus the cross-rank rename -EXDEV guard."""
    async def go():
        c = await Cluster(n_mons=1, n_osds=3, config=FAST_CFG).start()
        try:
            await c.start_fs(n_mds=2, max_mds=2)
            monmap = c.client.monc.monmap
            cl = await CephFSClient.create(monmap, None, "cephfs",
                                           keyring=c.keyring)
            await cl.mkdir("/a")
            await cl.write_file("/a/before.txt", b"pre-pin")
            # freeze this client's map: it will keep routing /a to
            # rank 0 after the migration commits
            cl._on_fsmap = lambda fm: None
            await c.subtree_pin("/a", 1)
            r0 = MDS_PERF.dump().get("redirects_sent", 0)
            await cl.write_file("/a/after.txt", b"redirected")
            assert MDS_PERF.dump().get("redirects_sent", 0) > r0, \
                "stale-routed request was never redirected"
            # the hint sticks: subsequent ops go straight to rank 1
            assert await cl.read_file("/a/after.txt") == b"redirected"
            assert await cl.read_file("/a/before.txt") == b"pre-pin"
            # the rank-1 daemon actually served ops for /a
            rank1 = next(m for m in c.mdss
                         if m.rank == 1 and not m._stopping)
            assert rank1._subtree_op_counts.get("/a", 0) > 0
            # cross-rank rename refused with a clear -EXDEV
            import pytest as _pytest
            with _pytest.raises(Exception) as ei:
                await cl.rename("/a/after.txt", "/elsewhere.txt")
            assert getattr(ei.value, "errno", None) == -18, ei.value
            # same-rank rename still works
            await cl.rename("/a/after.txt", "/a/renamed.txt")
            assert await cl.read_file("/a/renamed.txt") == \
                b"redirected"
            await cl.unmount()
        finally:
            await c.stop()
    run(go())


def test_rebalancer_migrates_hot_subtree_exactly_once():
    """THE rebalancer acceptance: all load lands on /hot (rank 0 via
    "/"); with rank 1 idle the mon's load rebalancer must migrate
    /hot to rank 1 UNDER the load, with zero writer errors and the
    exactly-once guarantee intact — every writer does a
    create-then-rename pair, so a double-applied rename (a resent
    mutation re-executed instead of answered from the transferred
    completed-table) would surface as -ENOENT."""
    async def go():
        cfg = dict(FAST_CFG, mds_bal_interval=0.4,
                   mds_bal_min_ops=5.0, mds_bal_ratio=1.2)
        c = await Cluster(n_mons=1, n_osds=3, config=cfg).start()
        try:
            await c.start_fs(n_mds=2, max_mds=2)
            monmap = c.client.monc.monmap
            clients = [await CephFSClient.create(
                monmap, None, "cephfs", keyring=c.keyring)
                for _ in range(2)]
            await clients[0].mkdir("/hot")
            errors: list = []
            acked: dict[str, bytes] = {}
            stop = asyncio.Event()

            async def writer(w: int, cl) -> int:
                i = 0
                while not stop.is_set() and i < 200:
                    src = f"/hot/w{w}-{i:04d}.tmp"
                    dst = f"/hot/w{w}-{i:04d}"
                    data = bytes([(w * 7 + i) % 256]) * 128
                    try:
                        await asyncio.wait_for(
                            cl.write_file(src, data), timeout=45.0)
                        await asyncio.wait_for(
                            cl.rename(src, dst), timeout=45.0)
                        acked[dst] = data
                    except asyncio.CancelledError:
                        raise
                    except Exception as e:
                        errors.append((src, repr(e)))
                    i += 1
                    await asyncio.sleep(0)
                return i
            tasks = [asyncio.ensure_future(writer(w, cl))
                     for w, cl in enumerate(clients)]
            # the rebalancer must move /hot to the idle rank 1 while
            # the writers race the freeze/handoff/flip
            deadline = asyncio.get_event_loop().time() + 60.0
            while True:
                sub = await _subtree_map(c)
                if sub["subtrees"].get("/hot") == 1 and \
                        not sub["migrations"]:
                    break
                assert asyncio.get_event_loop().time() < deadline, \
                    f"rebalancer never migrated /hot: {sub}"
                await asyncio.sleep(0.2)
            # keep writing a beat on the new owner, then stop
            await asyncio.sleep(1.0)
            stop.set()
            await asyncio.wait(tasks, timeout=90.0)
            assert not errors, \
                (f"mutations lost/double-applied across the "
                 f"migration: {errors[:4]}")
            # every acked rename exactly once: dst readable
            # bit-identical, src GONE
            reader = clients[0]
            listing = set(await reader.ls("/hot"))
            for dst, data in acked.items():
                name = dst.rsplit("/", 1)[1]
                assert name in listing, f"lost {dst}"
                assert f"{name}.tmp" not in listing, \
                    f"rename of {dst} half-applied"
                assert await reader.read_file(dst) == data, dst
            assert len(acked) > 0
            # rank 1 is now the one accumulating /hot ops
            rank1 = next(m for m in c.mdss
                         if m.rank == 1 and not m._stopping)
            assert rank1._subtree_op_counts.get("/hot", 0) > 0
            for cl in clients:
                await cl.unmount()
        finally:
            await c.stop()
    run(go())


def test_fs_cli_and_command_validation():
    """Cheap surface pins: CLI spellings parse, fs set max_mds
    validates, subtree pin validates, and fs dump carries the
    multi-active blocks."""
    from ceph_tpu.bench.ceph_cli import parse_command
    assert parse_command(["fs", "set", "max_mds", "2"])[0] == \
        {"prefix": "fs set", "var": "max_mds", "val": "2"}
    assert parse_command(["fs", "subtree", "pin", "/a", "1"])[0] == \
        {"prefix": "fs subtree pin", "path": "/a", "rank": 1}
    assert parse_command(["fs", "subtree", "ls"])[0] == \
        {"prefix": "fs subtree ls"}

    async def go():
        c = await Cluster(n_mons=1, n_osds=3, config=FAST_CFG).start()
        try:
            await c.start_fs(n_mds=3, max_mds=2)
            for bad in ("0", "17", "x"):
                ret, rs, _ = await c.client.mon_command(
                    {"prefix": "fs set", "var": "max_mds",
                     "val": bad})
                assert ret == -22, (bad, rs)
            ret, rs, _ = await c.client.mon_command(
                {"prefix": "fs set", "var": "nope", "val": "1"})
            assert ret == -22
            # pin to an out-of-range rank refused with the range named
            ret, rs, _ = await c.client.mon_command(
                {"prefix": "fs subtree pin", "path": "/p",
                 "rank": 9})
            assert ret == -22 and "max_mds" in rs
            # fs dump carries subtrees/migrations/max_mds + rank list
            ret, _, out = await c.client.mon_command(
                {"prefix": "fs dump"})
            dump = json.loads(out)
            assert dump["max_mds"] == 2
            assert dump["subtrees"]["/"] == 0
            assert dump["migrations"] == []
            assert len(dump["ranks"]) == 2
            # status fsmap block exposes the multi-active summary
            st = await c.client.status()
            assert st["fsmap"]["max_mds"] == 2
            assert set(st["fsmap"]["actives"]) == {0, 1} or \
                set(st["fsmap"]["actives"]) == {"0", "1"}
            # LOWERING max_mds: pin a subtree to rank 1 first, then
            # retire it — the subtree reassigns to rank 0 in the same
            # commit, the displaced holder is fenced WITHOUT entering
            # fm.failed (no permanent FS_DEGRADED) and WITHOUT
            # consuming the standby into the retired rank
            await c.subtree_pin("/p2", 1)
            ret, rs, _ = await c.client.mon_command(
                {"prefix": "fs set", "var": "max_mds", "val": "1"})
            assert ret == 0, rs
            deadline = asyncio.get_event_loop().time() + 20.0
            while True:
                lead = c.leader()
                fm = lead.mdsmon.fsmap
                holders = fm.rank_holders()
                if set(holders) == {0} and not fm.failed and \
                        fm.standbys():
                    break
                assert asyncio.get_event_loop().time() < deadline, \
                    (sorted(holders), fm.failed,
                     [i.dump() for i in fm.infos.values()])
                await asyncio.sleep(0.1)
            assert fm.subtrees["/p2"] == 0
            assert fm.max_mds == 1
            # the standby survived for a REAL rank-0 failure, and no
            # daemon holds the retired rank
            assert all(i.rank != 1 for i in fm.infos.values())
        finally:
            await c.stop()
    run(go())


@pytest.mark.slow
def test_multimds_deep_double_kill_with_migration():
    """Deep variant: 3 actives + 1 standby, pins on two subtrees,
    kill the rank-1 AND rank-2 actives back to back under sustained
    I/O, then migrate a subtree between the survivors."""
    async def go():
        c = await Cluster(n_mons=1, n_osds=3, config=FAST_CFG).start()
        try:
            await c.start_fs(n_mds=4, max_mds=3)
            monmap = c.client.monc.monmap
            clients = [await CephFSClient.create(
                monmap, None, "cephfs", keyring=c.keyring)
                for _ in range(3)]
            for d, r in (("/d0", 0), ("/d1", 1), ("/d2", 2)):
                await clients[0].mkdir(d)
                if r:
                    await c.subtree_pin(d, r)
            victim1 = c.mds_active_name(1)
            th = Thrasher(c, seed=47)
            res = await th.mds_storm(
                clients, writes=30, files_before_kill=5, kills=1,
                kill_rank=1, writer_dirs=["/d0", "/d1", "/d2"],
                survivor_writers=[0, 2])
            assert res["errors"] == 0
            # the first kill consumed the standby pool: revive the
            # victim as a FRESH incarnation so rank 2's failover has a
            # successor
            await c.revive_mds(victim1)
            # second kill, rank 2, fresh dirs for writers 0/1 on their
            # existing ranks
            await clients[0].mkdir("/d0b")
            await clients[0].mkdir("/d1b")
            await c.subtree_pin("/d1b", 1)
            th2 = Thrasher(c, seed=48)
            res2 = await th2.mds_storm(
                clients, writes=30, files_before_kill=5, kills=1,
                kill_rank=2, writer_dirs=["/d0b", "/d1b", "/d2"],
                survivor_writers=[0, 1])
            assert res2["errors"] == 0
            # migrate /d2 between the live survivors (2 -> 0)
            await c.subtree_pin("/d2", 0)
            assert (await _subtree_map(c))["subtrees"]["/d2"] == 0
            await clients[2].write_file("/d2/post.txt", b"migrated")
            assert await clients[0].read_file("/d2/post.txt") == \
                b"migrated"
            for cl in clients:
                await cl.unmount()
        finally:
            await c.stop()
    run(go())
