"""RGW-lite S3 gateway + libcephfs-lite over a live cluster.

ref test models: s3-tests subset (bucket/object lifecycle over raw
HTTP) and src/test/libcephfs (namespace semantics).
"""

import asyncio

import pytest

from ceph_tpu.cephfs import CephFSLite, FSError
from ceph_tpu.cluster.vstart import Cluster
from ceph_tpu.rados import ObjectOperationError
from ceph_tpu.rgw import RGWGateway


async def _warm(io) -> None:
    """One write before timing-sensitive asserts: the first op pays the
    CRUSH-mapper jit compile on a loaded 1-core host."""
    for _ in range(30):
        try:
            await io.write_full("_warm", b"x")
            return
        except ObjectOperationError:
            await asyncio.sleep(1)


def run(coro):
    asyncio.run(coro)


async def _http(port: int, method: str, path: str,
                body: bytes = b"",
                headers: dict | None = None,
                want_headers: bool = False):
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    try:
        extra = "".join(f"{k}: {v}\r\n"
                        for k, v in (headers or {}).items())
        writer.write(
            f"{method} {path} HTTP/1.1\r\nHost: x\r\n{extra}"
            f"Content-Length: {len(body)}\r\n\r\n".encode() + body)
        await writer.drain()
        # generous: the first op in a fresh process may sit behind a
        # CRUSH-mapper jit compile on a loaded 1-core host
        status_line = await asyncio.wait_for(reader.readline(),
                                             timeout=60)
        status = int(status_line.split()[1])
        clen = 0
        resp_headers = {}
        while True:
            line = await reader.readline()
            if line in (b"\r\n", b"\n", b""):
                break
            k, _, v = line.decode().partition(":")
            resp_headers[k.strip().lower()] = v.strip()
            if k.strip().lower() == "content-length":
                clen = int(v)
        # HEAD: Content-Length describes the would-be body; none is sent
        payload = b"" if method == "HEAD" or not clen \
            else await reader.readexactly(clen)
        if want_headers:
            return status, payload, resp_headers
        return status, payload
    finally:
        writer.close()


def test_rgw_s3_lifecycle():
    async def go():
        c = await Cluster(n_mons=1, n_osds=3).start()
        try:
            await c.client.pool_create("rgw", pg_num=8, size=3)
            await c.wait_for_clean(timeout=90)
            io = await c.client.open_ioctx("rgw")
            await _warm(io)
            gw = RGWGateway(io)
            port = await gw.start()
            # bucket lifecycle
            st, _ = await _http(port, "PUT", "/photos")
            assert st == 200
            st, xml = await _http(port, "GET", "/")
            assert st == 200 and b"<Name>photos</Name>" in xml
            # object lifecycle
            st, _ = await _http(port, "PUT", "/photos/cat.jpg",
                                b"\xff\xd8meow")
            assert st == 200
            st, data = await _http(port, "GET", "/photos/cat.jpg")
            assert st == 200 and data == b"\xff\xd8meow"
            st, _ = await _http(port, "HEAD", "/photos/cat.jpg")
            assert st == 200
            st, xml = await _http(port, "GET", "/photos")
            assert b"<Key>cat.jpg</Key>" in xml
            assert b"<Size>6</Size>" in xml
            # nested keys
            st, _ = await _http(port, "PUT", "/photos/a/b.txt", b"hi")
            assert st == 200
            st, data = await _http(port, "GET", "/photos/a/b.txt")
            assert data == b"hi"
            # errors: missing key / bucket, non-empty delete
            st, _ = await _http(port, "GET", "/photos/nope")
            assert st == 404
            st, _ = await _http(port, "PUT", "/nobucket/x", b"1")
            assert st == 404
            st, _ = await _http(port, "DELETE", "/photos")
            assert st == 409                      # BucketNotEmpty
            st, _ = await _http(port, "DELETE", "/photos/cat.jpg")
            assert st == 204
            st, _ = await _http(port, "DELETE", "/photos/a/b.txt")
            assert st == 204
            st, _ = await _http(port, "DELETE", "/photos")
            assert st == 204
            st, xml = await _http(port, "GET", "/")
            assert b"photos" not in xml
            await gw.stop()
        finally:
            await c.stop()
    run(go())


def test_rgw_multipart():
    """Initiate -> parts -> list -> complete -> GET assembles in order;
    abort frees everything (ref test model: s3-tests multipart)."""
    async def go():
        import hashlib
        c = await Cluster(n_mons=1, n_osds=3).start()
        try:
            await c.client.pool_create("rgw", pg_num=8, size=3)
            await c.wait_for_clean(timeout=90)
            io = await c.client.open_ioctx("rgw")
            await _warm(io)
            gw = RGWGateway(io)
            port = await gw.start()
            await _http(port, "PUT", "/vids")
            st, xml = await _http(port, "POST", "/vids/movie.bin?uploads")
            assert st == 200
            upload_id = xml.split(b"<UploadId>")[1].split(
                b"</UploadId>")[0].decode()
            parts = [b"AA" * 700, b"BB" * 900, b"CC" * 500]
            etags = []
            for i, p in enumerate(parts, start=1):
                st, _, hdrs = await _http(
                    port, "PUT",
                    f"/vids/movie.bin?partNumber={i}&uploadId={upload_id}",
                    p, want_headers=True)
                assert st == 200
                etags.append(hdrs["etag"].strip('"'))
                assert etags[-1] == hashlib.md5(p).hexdigest()
            # upload listing + part listing
            st, xml = await _http(port, "GET", "/vids?uploads")
            assert st == 200 and upload_id.encode() in xml
            st, xml = await _http(
                port, "GET", f"/vids/movie.bin?uploadId={upload_id}")
            assert st == 200
            assert xml.count(b"<PartNumber>") == 3
            assert f"<Size>{len(parts[1])}</Size>".encode() in xml
            # complete (explicit part list, all three)
            body = ("<CompleteMultipartUpload>" + "".join(
                f"<Part><PartNumber>{i}</PartNumber>"
                f'<ETag>"{e}"</ETag></Part>'
                for i, e in enumerate(etags, start=1)) +
                "</CompleteMultipartUpload>").encode()
            st, xml = await _http(
                port, "POST", f"/vids/movie.bin?uploadId={upload_id}",
                body)
            assert st == 200
            md5s = b"".join(bytes.fromhex(e) for e in etags)
            want_etag = f"{hashlib.md5(md5s).hexdigest()}-3"
            assert f'"{want_etag}"'.encode() in xml
            # GET assembles the parts in order; ETag rides the header
            st, data, hdrs = await _http(port, "GET", "/vids/movie.bin",
                                         want_headers=True)
            assert st == 200 and data == b"".join(parts)
            assert hdrs["etag"].strip('"') == want_etag
            # size in the bucket listing = total of the parts
            st, xml = await _http(port, "GET", "/vids")
            assert f"<Size>{len(data)}</Size>".encode() in xml
            # upload bookkeeping is gone
            st, _ = await _http(
                port, "GET", f"/vids/movie.bin?uploadId={upload_id}")
            assert st == 404
            # abort path: second upload disappears without a trace
            st, xml = await _http(port, "POST", "/vids/tmp?uploads")
            up2 = xml.split(b"<UploadId>")[1].split(
                b"</UploadId>")[0].decode()
            await _http(port, "PUT",
                        f"/vids/tmp?partNumber=1&uploadId={up2}", b"zz")
            st, _ = await _http(port, "DELETE",
                                f"/vids/tmp?uploadId={up2}")
            assert st == 204
            st, _ = await _http(port, "GET",
                                f"/vids/tmp?uploadId={up2}")
            assert st == 404
            # HEAD of the multipart object advertises the real size
            st, _, hdrs = await _http(port, "HEAD", "/vids/movie.bin",
                                      want_headers=True)
            assert st == 200
            assert int(hdrs["content-length"]) == sum(map(len, parts))
            # completing with a part that was never uploaded: InvalidPart
            st, xml = await _http(port, "POST", "/vids/x?uploads")
            up3 = xml.split(b"<UploadId>")[1].split(
                b"</UploadId>")[0].decode()
            st, xml = await _http(
                port, "POST", f"/vids/x?uploadId={up3}",
                b"<CompleteMultipartUpload><Part><PartNumber>7"
                b"</PartNumber></Part></CompleteMultipartUpload>")
            assert st == 400 and b"InvalidPart" in xml
            # out-of-order / duplicated part list: InvalidPartOrder
            await _http(port, "PUT",
                        f"/vids/x?partNumber=1&uploadId={up3}", b"p1")
            await _http(port, "PUT",
                        f"/vids/x?partNumber=2&uploadId={up3}", b"p2")
            st, xml = await _http(
                port, "POST", f"/vids/x?uploadId={up3}",
                b"<CompleteMultipartUpload>"
                b"<Part><PartNumber>2</PartNumber></Part>"
                b"<Part><PartNumber>1</PartNumber></Part>"
                b"</CompleteMultipartUpload>")
            assert st == 400 and b"InvalidPartOrder" in xml
            # stale client ETag for a part: InvalidPart
            st, xml = await _http(
                port, "POST", f"/vids/x?uploadId={up3}",
                b"<CompleteMultipartUpload><Part><PartNumber>1"
                b'</PartNumber><ETag>"deadbeefdeadbeefdeadbeef'
                b'deadbeef"</ETag></Part></CompleteMultipartUpload>')
            assert st == 400 and b"InvalidPart" in xml
            # malformed partNumber: 400, not a dropped connection
            st, xml = await _http(
                port, "PUT", f"/vids/x?partNumber=abc&uploadId={up3}",
                b"zz")
            assert st == 400 and b"InvalidPartNumber" in xml
            # abort under the WRONG key must not destroy the upload
            st, _ = await _http(port, "DELETE",
                                f"/vids/OTHER?uploadId={up3}")
            assert st == 404
            st, _ = await _http(port, "GET",
                                f"/vids/x?uploadId={up3}")
            assert st == 200
            # DELETE of the multipart object frees part objects too
            st, _ = await _http(port, "DELETE", "/vids/movie.bin")
            assert st == 204
            st, _ = await _http(port, "GET", "/vids/movie.bin")
            assert st == 404
            await gw.stop()
        finally:
            await c.stop()
    run(go())


def _sigv4_oracle(method, path, query, amzdate, payload, access, secret,
                  region="us-east-1"):
    """Independent in-test SigV4 implementation (spelled out linearly
    from the published algorithm, no shared code with rgw/auth.py)."""
    import hashlib
    import hmac as hm
    phash = hashlib.sha256(payload).hexdigest()
    headers = {"host": "x", "x-amz-date": amzdate,
               "x-amz-content-sha256": phash}
    names = sorted(headers)
    canon = (method + "\n" + path + "\n" + query + "\n"
             + "".join(f"{n}:{headers[n]}\n" for n in names) + "\n"
             + ";".join(names) + "\n" + phash)
    date = amzdate[:8]
    scope = f"{date}/{region}/s3/aws4_request"
    sts = ("AWS4-HMAC-SHA256\n" + amzdate + "\n" + scope + "\n"
           + hashlib.sha256(canon.encode()).hexdigest())
    key = ("AWS4" + secret).encode()
    for piece in (date, region, "s3", "aws4_request"):
        key = hm.new(key, piece.encode(), hashlib.sha256).digest()
    sig = hm.new(key, sts.encode(), hashlib.sha256).hexdigest()
    auth = (f"AWS4-HMAC-SHA256 Credential={access}/{scope}, "
            f"SignedHeaders={';'.join(names)}, Signature={sig}")
    return {"x-amz-date": amzdate, "x-amz-content-sha256": phash,
            "authorization": auth}


def test_sigv4_signer_matches_independent_oracle():
    """The client signer and the hand-rolled spec implementation must
    produce identical signatures (simple path, no query)."""
    from ceph_tpu.rgw import auth as sigv4
    amzdate = "20260731T120000Z"
    ours = sigv4.sign("GET", "/b/k", "", {"host": "x"}, b"payload",
                      "AK", "SK", amzdate=amzdate)
    oracle = _sigv4_oracle("GET", "/b/k", "", amzdate, b"payload",
                           "AK", "SK")
    assert ours["authorization"] == oracle["authorization"]


def test_rgw_sigv4_auth():
    """Gateway with users= requires a valid V4 signature: anonymous and
    tampered requests bounce with AccessDenied; signed ones work."""
    async def go():
        from ceph_tpu.rgw import auth as sigv4
        c = await Cluster(n_mons=1, n_osds=3).start()
        try:
            await c.client.pool_create("rgw", pg_num=8, size=3)
            await c.wait_for_clean(timeout=90)
            io = await c.client.open_ioctx("rgw")
            await _warm(io)
            gw = RGWGateway(io, users={"AKIDEXAMPLE": "secretkey"})
            port = await gw.start()

            def signed(method, target, body=b"", secret="secretkey"):
                path, _, query = target.partition("?")
                return sigv4.sign(method, path, query, {"host": "x"},
                                  body, "AKIDEXAMPLE", secret)

            # anonymous: denied
            st, xml = await _http(port, "PUT", "/secure")
            assert st == 403 and b"AccessDenied" in xml
            # signed bucket + object lifecycle
            st, _ = await _http(port, "PUT", "/secure",
                                headers=signed("PUT", "/secure"))
            assert st == 200
            st, _ = await _http(port, "PUT", "/secure/doc", b"data!",
                                headers=signed("PUT", "/secure/doc",
                                               b"data!"))
            assert st == 200
            st, data = await _http(port, "GET", "/secure/doc",
                                   headers=signed("GET", "/secure/doc"))
            assert st == 200 and data == b"data!"
            # signature computed with the wrong secret: denied
            st, _ = await _http(port, "GET", "/secure/doc",
                                headers=signed("GET", "/secure/doc",
                                               secret="wrong"))
            assert st == 403
            # body swapped after signing (payload hash mismatch): denied
            h = signed("PUT", "/secure/doc", b"data!")
            st, _ = await _http(port, "PUT", "/secure/doc", b"EVIL!",
                                headers=h)
            assert st == 403
            # signed multipart initiate (query string in scope)
            st, xml = await _http(
                port, "POST", "/secure/big?uploads",
                headers=signed("POST", "/secure/big?uploads"))
            assert st == 200 and b"<UploadId>" in xml
            # replayed/stale signature (old x-amz-date): denied
            stale = sigv4.sign("GET", "/secure/doc", "", {"host": "x"},
                               b"", "AKIDEXAMPLE", "secretkey",
                               amzdate="20200101T000000Z")
            st, _ = await _http(port, "GET", "/secure/doc",
                                headers=stale)
            assert st == 403
            await gw.stop()
        finally:
            await c.stop()
    run(go())


def test_cephfs_namespace():
    async def go():
        c = await Cluster(n_mons=1, n_osds=3).start()
        try:
            await c.client.pool_create("fs", pg_num=8, size=3)
            await c.wait_for_clean(timeout=90)
            io = await c.client.open_ioctx("fs")
            fs = await CephFSLite(io).mount()
            await fs.mkdir("/home")
            await fs.mkdir("/home/user")
            await fs.write_file("/home/user/notes.txt", b"hello fs")
            await fs.write_file("/readme", b"root file")
            assert await fs.ls("/") == ["home", "readme"]
            assert await fs.ls("/home") == ["user"]
            assert await fs.ls("/home/user") == ["notes.txt"]
            assert await fs.read_file("/home/user/notes.txt") == \
                b"hello fs"
            st = await fs.stat("/home/user/notes.txt")
            assert st == {"path": "/home/user/notes.txt",
                          "type": "file", "size": 8}
            assert (await fs.stat("/home"))["type"] == "dir"
            # offset write grows the file
            await fs.write_file("/home/user/notes.txt", b"!", offset=8)
            assert (await fs.stat("/home/user/notes.txt"))["size"] == 9
            # rename across directories
            await fs.rename("/home/user/notes.txt", "/notes-moved")
            assert "notes-moved" in await fs.ls("/")
            assert await fs.ls("/home/user") == []
            assert await fs.read_file("/notes-moved") == b"hello fs!"
            # error semantics
            with pytest.raises(FSError):
                await fs.mkdir("/home")               # EEXIST
            with pytest.raises(FSError):
                await fs.rmdir("/home")               # ENOTEMPTY
            with pytest.raises(FSError):
                await fs.read_file("/home")           # EISDIR
            with pytest.raises(FSError):
                await fs.ls("/ghost")                 # ENOENT
            with pytest.raises(FSError):
                await fs.unlink("/home")              # EISDIR
            # cleanup path: rmdir after emptying
            await fs.rmdir("/home/user")
            await fs.rmdir("/home")
            await fs.unlink("/readme")
            await fs.unlink("/notes-moved")
            assert await fs.ls("/") == []
        finally:
            await c.stop()
    run(go())


def test_rgw_presigned_and_acls():
    """Round 5: canned ACLs (owner-only writes, public-read reads,
    ?acl sub-resource) and presigned query-auth URLs incl. expiry and
    tamper rejection (ref: RGWAccessControlPolicy + the SigV4 query
    flow of rgw_auth_s3)."""
    async def go():
        from ceph_tpu.rgw import auth as sigv4
        c = await Cluster(n_mons=1, n_osds=3).start()
        try:
            await c.client.pool_create("rgw", pg_num=8, size=3)
            await c.wait_for_clean(timeout=90)
            io = await c.client.open_ioctx("rgw")
            await _warm(io)
            gw = RGWGateway(io, users={"OWNER": "sk1", "OTHER": "sk2"})
            port = await gw.start()

            def signed(method, target, body=b"", access="OWNER",
                       secret="sk1", amzacl=None):
                path, _, query = target.partition("?")
                h = {"host": "x"}
                if amzacl:
                    h["x-amz-acl"] = amzacl
                out = sigv4.sign(method, path, query, h, body,
                                 access, secret)
                if amzacl:
                    out["x-amz-acl"] = amzacl
                return out

            # OWNER creates a private bucket and an object
            st, _ = await _http(port, "PUT", "/priv",
                                headers=signed("PUT", "/priv"))
            assert st == 200
            st, _ = await _http(port, "PUT", "/priv/doc", b"secret",
                                headers=signed("PUT", "/priv/doc",
                                               b"secret"))
            assert st == 200
            # anonymous read: denied; OTHER read: denied (private);
            # OTHER write: denied (owner-only)
            st, _ = await _http(port, "GET", "/priv/doc")
            assert st == 403
            st, _ = await _http(port, "GET", "/priv/doc",
                                headers=signed("GET", "/priv/doc",
                                               access="OTHER",
                                               secret="sk2"))
            assert st == 403
            st, _ = await _http(port, "PUT", "/priv/doc2", b"x",
                                headers=signed("PUT", "/priv/doc2",
                                               b"x", access="OTHER",
                                               secret="sk2"))
            assert st == 403
            # object-level public-read via ?acl: anonymous GET passes,
            # bucket listing stays private
            st, _ = await _http(port, "PUT", "/priv/doc?acl",
                                headers=signed("PUT", "/priv/doc?acl",
                                               amzacl="public-read"))
            assert st == 200
            st, data = await _http(port, "GET", "/priv/doc")
            assert st == 200 and data == b"secret"
            st, _ = await _http(port, "GET", "/priv")
            assert st == 403
            # GET ?acl reflects the grant
            st, xml = await _http(port, "GET", "/priv/doc?acl",
                                  headers=signed("GET",
                                                 "/priv/doc?acl"))
            assert st == 200 and b"AllUsers" in xml
            # bucket-level public-read opens listing to anonymous
            st, _ = await _http(port, "PUT", "/priv?acl",
                                headers=signed("PUT", "/priv?acl",
                                               amzacl="public-read"))
            assert st == 200
            st, xml = await _http(port, "GET", "/priv")
            assert st == 200 and b"doc" in xml
            # overwriting the object clears its stale public acl
            st, _ = await _http(port, "PUT", "/priv?acl",
                                headers=signed("PUT", "/priv?acl",
                                               amzacl="private"))
            assert st == 200
            st, _ = await _http(port, "PUT", "/priv/doc", b"v2",
                                headers=signed("PUT", "/priv/doc",
                                               b"v2"))
            assert st == 200
            st, _ = await _http(port, "GET", "/priv/doc")
            assert st == 403

            # presigned URL: anonymous GET through the signed query
            qs = sigv4.presign("GET", "/priv/doc", "x", "OWNER", "sk1",
                               expires=120)
            st, data = await _http(port, "GET", f"/priv/doc?{qs}")
            assert st == 200 and data == b"v2"
            # tampered query: denied
            st, _ = await _http(port, "GET",
                                f"/priv/doc?{qs}&evil=1")
            assert st == 403
            # expired: denied
            old = sigv4.presign(
                "GET", "/priv/doc", "x", "OWNER", "sk1", expires=60,
                amzdate="20200101T000000Z")
            st, _ = await _http(port, "GET", f"/priv/doc?{old}")
            assert st == 403
            # presigned with an unknown key: denied
            bad = sigv4.presign("GET", "/priv/doc", "x", "NOBODY",
                                "sk1", expires=120)
            st, _ = await _http(port, "GET", f"/priv/doc?{bad}")
            assert st == 403
            await gw.stop()
        finally:
            await c.stop()
    run(go())


def test_presigned_expiry_clamp_and_host_binding():
    """ADVICE low #2: X-Amz-Expires must be clamped to (0, 604800]
    and SignedHeaders must include host — otherwise a key holder can
    mint effectively never-expiring or host-unbound URLs."""
    from ceph_tpu.rgw import auth as sigv4

    secrets = {"AK": "sk"}

    def verify(expires=None, signed_headers=None):
        qs = sigv4.presign("GET", "/b/o", "host1", "AK", "sk",
                           expires=120 if expires is None else expires)
        if signed_headers is not None:
            qs = qs.replace("X-Amz-SignedHeaders=host",
                            f"X-Amz-SignedHeaders={signed_headers}")
        return sigv4.verify_presigned("GET", "/b/o", qs,
                                      {"host": "host1"}, secrets)

    ok, who = verify()
    assert ok and who == "AK"
    # zero / negative / over-7-day expiry: rejected with a clear
    # reason (not a signature mismatch)
    for bad in (0, -5, 604801, 10**9):
        ok, why = verify(expires=bad)
        assert not ok and "X-Amz-Expires" in why, (bad, why)
    # exactly 7 days is the legal maximum
    ok, _ = verify(expires=604800)
    assert ok
    # host missing from SignedHeaders: rejected before any signature
    # work (a sig over host-free headers could be replayed elsewhere)
    ok, why = verify(signed_headers="x-amz-date")
    assert not ok and "host" in why
