"""RGW-lite S3 gateway + libcephfs-lite over a live cluster.

ref test models: s3-tests subset (bucket/object lifecycle over raw
HTTP) and src/test/libcephfs (namespace semantics).
"""

import asyncio

import pytest

from ceph_tpu.cephfs import CephFSLite, FSError
from ceph_tpu.cluster.vstart import Cluster
from ceph_tpu.rados import ObjectOperationError
from ceph_tpu.rgw import RGWGateway


async def _warm(io) -> None:
    """One write before timing-sensitive asserts: the first op pays the
    CRUSH-mapper jit compile on a loaded 1-core host."""
    for _ in range(30):
        try:
            await io.write_full("_warm", b"x")
            return
        except ObjectOperationError:
            await asyncio.sleep(1)


def run(coro):
    asyncio.run(coro)


async def _http(port: int, method: str, path: str,
                body: bytes = b"") -> tuple[int, bytes]:
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    try:
        writer.write(
            f"{method} {path} HTTP/1.1\r\nHost: x\r\n"
            f"Content-Length: {len(body)}\r\n\r\n".encode() + body)
        await writer.drain()
        # generous: the first op in a fresh process may sit behind a
        # CRUSH-mapper jit compile on a loaded 1-core host
        status_line = await asyncio.wait_for(reader.readline(),
                                             timeout=60)
        status = int(status_line.split()[1])
        clen = 0
        while True:
            line = await reader.readline()
            if line in (b"\r\n", b"\n", b""):
                break
            if line.lower().startswith(b"content-length"):
                clen = int(line.split(b":")[1])
        payload = await reader.readexactly(clen) if clen else b""
        return status, payload
    finally:
        writer.close()


def test_rgw_s3_lifecycle():
    async def go():
        c = await Cluster(n_mons=1, n_osds=3).start()
        try:
            await c.client.pool_create("rgw", pg_num=8, size=3)
            await c.wait_for_clean(timeout=90)
            io = await c.client.open_ioctx("rgw")
            await _warm(io)
            gw = RGWGateway(io)
            port = await gw.start()
            # bucket lifecycle
            st, _ = await _http(port, "PUT", "/photos")
            assert st == 200
            st, xml = await _http(port, "GET", "/")
            assert st == 200 and b"<Name>photos</Name>" in xml
            # object lifecycle
            st, _ = await _http(port, "PUT", "/photos/cat.jpg",
                                b"\xff\xd8meow")
            assert st == 200
            st, data = await _http(port, "GET", "/photos/cat.jpg")
            assert st == 200 and data == b"\xff\xd8meow"
            st, _ = await _http(port, "HEAD", "/photos/cat.jpg")
            assert st == 200
            st, xml = await _http(port, "GET", "/photos")
            assert b"<Key>cat.jpg</Key>" in xml
            assert b"<Size>6</Size>" in xml
            # nested keys
            st, _ = await _http(port, "PUT", "/photos/a/b.txt", b"hi")
            assert st == 200
            st, data = await _http(port, "GET", "/photos/a/b.txt")
            assert data == b"hi"
            # errors: missing key / bucket, non-empty delete
            st, _ = await _http(port, "GET", "/photos/nope")
            assert st == 404
            st, _ = await _http(port, "PUT", "/nobucket/x", b"1")
            assert st == 404
            st, _ = await _http(port, "DELETE", "/photos")
            assert st == 409                      # BucketNotEmpty
            st, _ = await _http(port, "DELETE", "/photos/cat.jpg")
            assert st == 204
            st, _ = await _http(port, "DELETE", "/photos/a/b.txt")
            assert st == 204
            st, _ = await _http(port, "DELETE", "/photos")
            assert st == 204
            st, xml = await _http(port, "GET", "/")
            assert b"photos" not in xml
            await gw.stop()
        finally:
            await c.stop()
    run(go())


def test_cephfs_namespace():
    async def go():
        c = await Cluster(n_mons=1, n_osds=3).start()
        try:
            await c.client.pool_create("fs", pg_num=8, size=3)
            await c.wait_for_clean(timeout=90)
            io = await c.client.open_ioctx("fs")
            fs = await CephFSLite(io).mount()
            await fs.mkdir("/home")
            await fs.mkdir("/home/user")
            await fs.write_file("/home/user/notes.txt", b"hello fs")
            await fs.write_file("/readme", b"root file")
            assert await fs.ls("/") == ["home", "readme"]
            assert await fs.ls("/home") == ["user"]
            assert await fs.ls("/home/user") == ["notes.txt"]
            assert await fs.read_file("/home/user/notes.txt") == \
                b"hello fs"
            st = await fs.stat("/home/user/notes.txt")
            assert st == {"path": "/home/user/notes.txt",
                          "type": "file", "size": 8}
            assert (await fs.stat("/home"))["type"] == "dir"
            # offset write grows the file
            await fs.write_file("/home/user/notes.txt", b"!", offset=8)
            assert (await fs.stat("/home/user/notes.txt"))["size"] == 9
            # rename across directories
            await fs.rename("/home/user/notes.txt", "/notes-moved")
            assert "notes-moved" in await fs.ls("/")
            assert await fs.ls("/home/user") == []
            assert await fs.read_file("/notes-moved") == b"hello fs!"
            # error semantics
            with pytest.raises(FSError):
                await fs.mkdir("/home")               # EEXIST
            with pytest.raises(FSError):
                await fs.rmdir("/home")               # ENOTEMPTY
            with pytest.raises(FSError):
                await fs.read_file("/home")           # EISDIR
            with pytest.raises(FSError):
                await fs.ls("/ghost")                 # ENOENT
            with pytest.raises(FSError):
                await fs.unlink("/home")              # EISDIR
            # cleanup path: rmdir after emptying
            await fs.rmdir("/home/user")
            await fs.rmdir("/home")
            await fs.unlink("/readme")
            await fs.unlink("/notes-moved")
            assert await fs.ls("/") == []
        finally:
            await c.stop()
    run(go())
