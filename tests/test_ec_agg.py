"""Round 13: the EC data path at production traffic — the OSD-side
cross-op encode aggregator, the fused checksum+encode program, and the
double-buffered streaming pipeline.

ref test model: the per-op vs batched equivalence discipline of
PR 10's sharded-sweep tests + src/test/osd EC determinism pins. Units
only (the live-cluster acceptance rides tests/test_ec_cluster.py):

- **CRC algebra** — the GF(2) decomposition ec/crc.py stands on:
  ``raw`` linearity, the length-only affine split, the per-row bit
  matrix vs zlib, the row->shard combine, and the two ``hcrc_attr``
  producers (fused row CRCs vs host zlib) byte-for-byte equal;
- **fused encode+CRC** — one device program returns the SAME parity as
  the plain kernel plus per-row CRCs that fold to ``zlib.crc32`` of
  every shard (data AND parity positions);
- **aggregator** — concurrent ops coalesce into fewer launches with
  lane-for-lane identical results, every flush trigger fires
  (full/window/idle, a lone op never held past the window), the
  ``osd_ec_agg=off`` baseline bypasses, padding is pow2-bounded, and
  drain cancels cleanly;
- **pipeline** — StreamingEncodePipeline's outputs equal per-batch
  encodes, in order;
- **degrade ladder (round 16)** — a failed batched flush
  disaggregates and rejects ONLY its own poisoned waiter, per-op
  device retries are bounded, the host reference encoder serves
  bit-exactly as the last rung, the fused checksum+encode jit
  quarantines on backoff after failures, and the streaming pipeline
  falls back to the unpipelined path without losing a batch.

One module-scoped plugin instance: every test shares its jit cache
(tier-1 runs near the wall-clock cap — compiles are the budget).
"""

import asyncio
import time
import zlib

import numpy as np
import pytest

from ceph_tpu.ec import crc as ec_crc
from ceph_tpu.ec.interface import ErasureCodeInterface
from ceph_tpu.ec.jax_plugin import ErasureCodeJax, StreamingEncodePipeline
from ceph_tpu.osd.ec_aggregator import ECAggregator

K, M, C = 3, 2, 64
N = K + M


@pytest.fixture(scope="module")
def ec():
    return ErasureCodeJax(
        f"plugin=jax k={K} m={M} technique=reed_sol_van")


def _rng(seed=13):
    return np.random.default_rng(seed)


def run(coro):
    return asyncio.run(coro)


# -- CRC algebra (host-side; the facts the fused pass stands on) -----------

def test_raw_crc_linearity_and_affine_split():
    """``raw`` is GF(2)-linear in the message bits; zlib.crc32 is raw
    plus a length-only constant; raw composes through its own state."""
    rng = _rng(1)
    for ln in (1, 7, 64, 513):
        a = rng.integers(0, 256, ln, dtype=np.uint8).tobytes()
        b = rng.integers(0, 256, ln, dtype=np.uint8).tobytes()
        x = bytes(p ^ q for p, q in zip(a, b))
        assert ec_crc.raw_crc(x) == \
            ec_crc.raw_crc(a) ^ ec_crc.raw_crc(b)
        assert zlib.crc32(a) == \
            ec_crc.raw_crc(a) ^ zlib.crc32(b"\x00" * ln)
        assert ec_crc.raw_crc(a + b) == \
            ec_crc.raw_crc(b, ec_crc.raw_crc(a))
    # the affine constant comes from O(log n) operator squaring, not
    # a length-sized zero buffer — pin it against zlib across scales
    for ln in (0, 1, 513, 65537, 1 << 20):
        assert ec_crc._zero_crc(ln) == zlib.crc32(b"\x00" * ln), ln


def test_row_crc_matrix_matches_zlib():
    """The (8C, 32) GF(2) matrix applied to a row's bits (LSB-first
    per byte) IS the row's raw CRC — the device leg of the fusion."""
    rng = _rng(2)
    G = ec_crc.row_crc_matrix(C)
    assert G.shape == (8 * C, 32)
    for _ in range(4):
        row = rng.integers(0, 256, C, dtype=np.uint8)
        bits = ((row[:, None] >> np.arange(8)) & 1).reshape(-1)
        acc = (bits.astype(np.int64) @ G.astype(np.int64)) & 1
        val = int((acc.astype(np.uint64) <<
                   np.arange(32, dtype=np.uint64)).sum())
        assert val == ec_crc.raw_crc(row.tobytes())


def test_hcrc_attr_producers_agree_byte_for_byte():
    """The unified ``_hcrc`` helper's two producers — device row CRCs
    folded through the combine vs host ``zlib.crc32`` — agree on the
    full attribute bytes for multi-row shards of several lengths."""
    rng = _rng(3)
    for count in (1, 2, 5, 16):
        rows = rng.integers(0, 256, (count, C), dtype=np.uint8)
        shard = rows.tobytes()
        row_crcs = np.array(
            [ec_crc.raw_crc(r.tobytes()) for r in rows],
            dtype=np.uint32)
        assert int(ec_crc.shard_crc32(row_crcs, C)) == \
            zlib.crc32(shard), count
        assert ec_crc.hcrc_attr(shard, row_crcs=row_crcs,
                                chunk_size=C) == \
            ec_crc.hcrc_attr(shard) == \
            zlib.crc32(shard).to_bytes(4, "little")


# -- fused checksum+encode -------------------------------------------------

def test_fused_encode_crc_bit_exact(ec):
    """One device program: parity identical to the plain kernel, and
    the per-row CRCs fold to zlib.crc32 of EVERY shard position's
    bytes (data and parity) — the acceptance pin for the fused
    ``_hcrc`` stamps."""
    rng = _rng(4)
    data = rng.integers(0, 256, (5, K, C), dtype=np.uint8)
    parity, crcs = ec.encode_batch_with_crc(data)
    parity, crcs = np.asarray(parity), np.asarray(crcs)
    assert (parity == np.asarray(ec.encode_batch(data))).all()
    assert crcs.shape == (5, N) and crcs.dtype == np.uint32
    word = np.concatenate([data, parity], axis=1)
    for pos in range(N):
        shard = word[:, pos, :].tobytes()     # the ec_pg shard layout
        assert ec_crc.hcrc_attr(shard, row_crcs=crcs[:, pos],
                                chunk_size=C) == \
            zlib.crc32(shard).to_bytes(4, "little"), pos


def test_base_interface_fused_is_optional():
    """A plugin without a fused path returns ``(parity, None)`` from
    the base ``encode_batch_with_crc`` — callers fall back to host
    zlib via hcrc_attr (the aggregator then hands back None CRCs)."""
    from ceph_tpu.ec.lrc import ErasureCodeLrc
    lrc = ErasureCodeLrc("plugin=lrc k=4 m=2 l=3")
    assert lrc.encode_batch_with_crc.__func__ is \
        ErasureCodeInterface.encode_batch_with_crc
    rng = _rng(5)
    data = rng.integers(0, 256, (2, 4, 32), dtype=np.uint8)
    parity, crcs = lrc.encode_batch_with_crc(data)
    assert crcs is None
    assert (np.asarray(parity) ==
            np.asarray(lrc.encode_batch(data))).all()

    async def go():
        agg = ECAggregator({"osd_ec_agg": True})
        p, c = await agg.encode(lrc, data, with_crc=True)
        assert c is None
        assert (p == np.asarray(lrc.encode_batch(data))).all()
    run(go())


# -- the aggregator --------------------------------------------------------

def test_aggregator_coalesces_bit_exact(ec):
    """Concurrent ops (non-pow2 sizes, mixed with_crc) coalesce into
    FEWER launches than ops, and every op's slice equals its own
    per-op encode lane for lane — the bit-exactness contract."""
    rng = _rng(6)
    ops = [rng.integers(0, 256, (b, K, C), dtype=np.uint8)
           for b in (1, 3, 2, 5, 1, 3, 2)]

    async def go():
        agg = ECAggregator({"osd_ec_agg": True,
                            "osd_ec_agg_window_us": 2000.0})
        outs = await asyncio.gather(*[
            agg.encode(ec, d, with_crc=(i % 2 == 0))
            for i, d in enumerate(ops)])
        d = agg.dump()
        assert 1 <= d["batches"] < len(ops)
        assert d["ops"] == len(ops)
        assert d["stripes"] == sum(o.shape[0] for o in ops)
        for i, (dat, (p, c)) in enumerate(zip(ops, outs)):
            assert (np.asarray(p) ==
                    np.asarray(ec.encode_batch(dat))).all(), i
            if i % 2 == 0:
                word = np.concatenate(
                    [dat, np.asarray(p)], axis=1)
                for pos in range(N):
                    assert ec_crc.hcrc_attr(
                        word[:, pos, :].tobytes(),
                        row_crcs=c[:, pos], chunk_size=C) == \
                        ec_crc.hcrc_attr(word[:, pos, :].tobytes())
            else:
                assert c is None, i
    run(go())


def test_aggregator_full_trigger(ec):
    """``osd_ec_agg_max_stripes`` forces an immediate flush — the
    batch-size ceiling fires before any window elapses."""
    rng = _rng(7)

    async def go():
        agg = ECAggregator({"osd_ec_agg": True,
                            "osd_ec_agg_window_us": 1e6,
                            "osd_ec_agg_max_stripes": 4})
        ops = [rng.integers(0, 256, (2, K, C), dtype=np.uint8)
               for _ in range(4)]
        t0 = asyncio.get_event_loop().time()
        await asyncio.gather(*[agg.encode(ec, d) for d in ops])
        took = asyncio.get_event_loop().time() - t0
        d = agg.dump()
        assert d["flushes"]["full"] >= 1
        assert took < 1.0      # nobody waited for the 1s window
    run(go())


def test_aggregator_lone_op_never_held_past_window(ec):
    """A lone op flushes EARLY on queue idleness — and in any case
    inside the window (here 10s, so a window-bound wait would hang
    the assertion far past the observed bound)."""
    rng = _rng(8)

    async def go():
        agg = ECAggregator({"osd_ec_agg": True,
                            "osd_ec_agg_window_us": 10e6})
        d = rng.integers(0, 256, (1, K, C), dtype=np.uint8)
        t0 = asyncio.get_event_loop().time()
        p, _ = await agg.encode(ec, d)
        took = asyncio.get_event_loop().time() - t0
        assert (p == np.asarray(ec.encode_batch(d))).all()
        assert took < 9.0, "lone op pinned to the window"
        assert agg.dump()["flushes"]["idle"] == 1
    run(go())


def test_aggregator_window_trigger(ec):
    """An expired window flushes whatever accumulated (window ~0:
    the first flusher wake is already past the deadline)."""
    rng = _rng(9)

    async def go():
        agg = ECAggregator({"osd_ec_agg": True,
                            "osd_ec_agg_window_us": 0.0})
        ops = [rng.integers(0, 256, (1, K, C), dtype=np.uint8)
               for _ in range(2)]
        await asyncio.gather(*[agg.encode(ec, d) for d in ops])
        assert agg.dump()["flushes"]["window"] >= 1
    run(go())


def test_aggregator_off_is_per_op_baseline(ec):
    """``osd_ec_agg=off`` (read LIVE) serves every encode per-op:
    no batches, a bypass count, identical results — the measured
    baseline the bench compares against."""
    rng = _rng(10)
    ops = [rng.integers(0, 256, (2, K, C), dtype=np.uint8)
           for _ in range(3)]

    async def go():
        cfg = {"osd_ec_agg": False}
        agg = ECAggregator(cfg)
        for d in ops:
            p, c = await agg.encode(ec, d, with_crc=True)
            assert (p == np.asarray(ec.encode_batch(d))).all()
            assert c is not None      # fusion is orthogonal to agg
        dmp = agg.dump()
        assert dmp["batches"] == 0 and dmp["bypass"] == len(ops)
        assert dmp["enabled"] is False
        # live flip back on: the same instance coalesces again
        cfg["osd_ec_agg"] = True
        await asyncio.gather(*[agg.encode(ec, d) for d in ops])
        assert agg.dump()["batches"] >= 1
    run(go())


def test_aggregator_pads_to_pow2(ec):
    """Padded launch sizes bound the jit cache to O(log max_batch)
    shapes, and the pad rows never leak into results."""
    for b, want in ((1, 1), (2, 2), (3, 4), (5, 8), (9, 16),
                    (4096, 4096)):
        assert ECAggregator._pad(b) == want, b
    rng = _rng(11)
    agg = ECAggregator({})
    d = rng.integers(0, 256, (5, K, C), dtype=np.uint8)  # pads to 8
    launched = []

    class _Spy:
        profile = "spy"

        def encode_batch(self, data):
            launched.append(data.shape[0])
            return ec.encode_batch(data)

        def encode_batch_with_crc(self, data):
            launched.append(data.shape[0])
            return ec.encode_batch_with_crc(data)

    p, crcs = agg._run(_Spy(), d, True)
    assert launched == [8]              # flush path pads 5 -> 8
    assert p.shape == (5, M, C)
    assert crcs.shape == (5, N)
    assert (p == np.asarray(ec.encode_batch(d))).all()
    # the osd_ec_agg=off bypass is the UNPADDED historical per-op
    # launch — the measured baseline must not pay pad compute the
    # pre-aggregator path never paid
    p2, _ = agg._run(_Spy(), d, False, pad=False)
    assert launched == [8, 5]
    assert (p2 == p).all()


def test_aggregator_drain_cancels_waiters(ec):
    """Daemon stop: pending waiters are CANCELLED (their PG op workers
    are going down too), timers die, and the stopped aggregator serves
    later stragglers per-op instead of queueing them forever."""
    rng = _rng(12)

    async def go():
        agg = ECAggregator({"osd_ec_agg": True,
                            "osd_ec_agg_window_us": 10e6,
                            "osd_ec_agg_max_stripes": 1 << 20})
        d = rng.integers(0, 256, (1, K, C), dtype=np.uint8)
        waiter = asyncio.ensure_future(agg.encode(ec, d))
        await asyncio.sleep(0)          # entry lands, timer armed
        assert agg.drain() == 1
        with pytest.raises(asyncio.CancelledError):
            await waiter
        assert agg.dump()["pending_ops"] == 0
        p, _ = await agg.encode(ec, d)  # straggler: served, per-op
        assert (p == np.asarray(ec.encode_batch(d))).all()
    run(go())


# -- the double-buffered streaming pipeline --------------------------------

def test_streaming_pipeline_matches_per_batch(ec):
    """Pipelined outputs equal per-batch encodes, in submission
    order; zero- and one-batch streams behave."""
    rng = _rng(14)
    batches = [rng.integers(0, 256, (2, K, C), dtype=np.uint8)
               for _ in range(5)]
    pipe = StreamingEncodePipeline(ec)
    outs = pipe.encode_all([b.copy() for b in batches])
    assert len(outs) == len(batches)
    for i, (b, o) in enumerate(zip(batches, outs)):
        assert (np.asarray(o) ==
                np.asarray(ec.encode_batch(b))).all(), i
    assert pipe.encode_all([]) == []
    one = pipe.encode_all([batches[0].copy()])
    assert len(one) == 1 and (
        np.asarray(one[0]) ==
        np.asarray(ec.encode_batch(batches[0]))).all()


# -- the degrade ladder (round 16) -----------------------------------------

class _FlakyEC:
    """Delegates to the module plugin but fails on command: device
    launches raise while a ``poison`` stripe rides in the batch (or
    always, with ``fail_all``), and the reference encoder refuses the
    poison stripe itself — the worst case the ladder must isolate."""

    profile = "flaky"

    def __init__(self, ec, poison=None, fail_all=False):
        self._ec = ec
        self._poison = poison
        self.fail_all = fail_all
        self.device_calls = 0

    def _poisoned(self, data):
        return self._poison is not None and \
            bool((data == self._poison).all(axis=(1, 2)).any())

    def _maybe_fail(self, data):
        self.device_calls += 1
        if self.fail_all or self._poisoned(data):
            raise RuntimeError("injected device failure")

    def encode_batch(self, data):
        self._maybe_fail(data)
        return self._ec.encode_batch(data)

    def encode_batch_with_crc(self, data):
        self._maybe_fail(data)
        return self._ec.encode_batch_with_crc(data)

    def encode_batch_reference(self, data):
        if self._poisoned(data):
            raise RuntimeError("reference refuses the poison stripe")
        return self._ec.encode_batch_reference(data)


def test_flush_failure_rejects_only_the_poisoned_op(ec):
    """A failed batched flush DISAGGREGATES: each batchmate retries
    per-op and is served lane-for-lane exactly; only the op whose
    stripe fails even under the reference encoder sees the exception.
    One poisoned stripe must not fail its batchmates."""
    rng = _rng(16)
    good = [rng.integers(0, 256, (2, K, C), dtype=np.uint8)
            for _ in range(2)]
    poison = np.full((1, K, C), 0xAB, dtype=np.uint8)
    flaky = _FlakyEC(ec, poison=0xAB)

    async def go():
        agg = ECAggregator({"osd_ec_agg": True,
                            "osd_ec_agg_window_us": 2000.0,
                            "osd_ec_fallback_retries": 1})
        outs = await asyncio.gather(
            agg.encode(flaky, good[0]),
            agg.encode(flaky, poison),
            agg.encode(flaky, good[1]),
            return_exceptions=True)
        for i, dat in ((0, good[0]), (2, good[1])):
            p, c = outs[i]
            assert c is None
            assert (np.asarray(p) ==
                    np.asarray(ec.encode_batch(dat))).all(), i
        assert isinstance(outs[1], RuntimeError)
        d = agg.perf.dump()
        assert d.get("flush_failures", 0) == 1
        assert d.get("per_op_retries", 0) == 1   # the poison op only
        assert d.get("fallback_ops", 0) == 0     # nothing NEEDED ref
        assert agg.dump()["pending_ops"] == 0
        # the aggregator stays LIVE after a failed flush: the next
        # batch coalesces and serves normally
        p, _ = await agg.encode(flaky, good[0])
        assert (np.asarray(p) ==
                np.asarray(ec.encode_batch(good[0]))).all()
        assert agg.perf.dump().get("batches", 0) == 1
    run(go())


def test_degrade_ladder_reference_serves_after_retries(ec):
    """Device encode hard-down: the op is served by the bit-exact
    host reference encoder after exactly ``osd_ec_fallback_retries``
    more device attempts — a client write never errors because the
    accelerator did; CRCs fall back to None (the caller's zlib
    path)."""
    rng = _rng(17)
    d = rng.integers(0, 256, (3, K, C), dtype=np.uint8)
    flaky = _FlakyEC(ec, fail_all=True)

    async def go():
        agg = ECAggregator({"osd_ec_agg": True,
                            "osd_ec_agg_window_us": 100.0,
                            "osd_ec_fallback_retries": 2})
        p, c = await agg.encode(flaky, d, with_crc=True)
        assert c is None
        assert (np.asarray(p) ==
                np.asarray(ec.encode_batch(d))).all()
        dmp = agg.perf.dump()
        assert dmp.get("flush_failures", 0) == 1
        assert dmp.get("per_op_retries", 0) == 2
        assert dmp.get("fallback_ops", 0) == 1
    run(go())


def test_reference_encoder_bit_exact_both_planes(ec):
    """``encode_batch_reference`` (pure numpy, no jit) equals the
    device kernel bit for bit on BOTH kernel planes: the GF(2^8)
    matmul (reed_sol_van, the module plugin) and the packet-plane
    bitmatrix XOR (liberation)."""
    rng = _rng(18)
    d = rng.integers(0, 256, (4, K, C), dtype=np.uint8)
    assert (np.asarray(ec.encode_batch_reference(d)) ==
            np.asarray(ec.encode_batch(d))).all()
    lib = ErasureCodeJax("plugin=jax k=4 m=2 technique=liberation w=7")
    dl = rng.integers(0, 256, (2, 4, 56), dtype=np.uint8)  # C = 8w
    assert (np.asarray(lib.encode_batch_reference(dl)) ==
            np.asarray(lib.encode_batch(dl))).all()


def test_fused_crc_quarantine_backoff(ec):
    """After the fused checksum+encode jit raises, flushes serve plain
    encode + host crc until an exponential-backoff deadline passes;
    the next crc flush past the deadline IS the probe, and a success
    resets the failure streak."""
    rng = _rng(19)
    d = rng.integers(0, 256, (2, K, C), dtype=np.uint8)

    class _CrcDown:
        profile = "crcdown"

        def __init__(self):
            self.fused_calls = 0
            self.ok = False

        def encode_batch(self, data):
            return ec.encode_batch(data)

        def encode_batch_with_crc(self, data):
            self.fused_calls += 1
            if not self.ok:
                raise RuntimeError("fused jit down")
            return ec.encode_batch_with_crc(data)

    plug = _CrcDown()
    agg = ECAggregator({"osd_ec_fallback_quarantine_base": 0.05,
                        "osd_ec_fallback_quarantine_max": 0.2})
    p, c = agg._run(plug, d, True)       # fused fails -> plain serves
    assert c is None and plug.fused_calls == 1
    assert (p == np.asarray(ec.encode_batch(d))).all()
    p, c = agg._run(plug, d, True)       # inside the rest window
    assert c is None and plug.fused_calls == 1    # fused NOT retried
    assert agg.perf.dump().get("crc_fallbacks", 0) == 1
    time.sleep(0.06)
    p, c = agg._run(plug, d, True)       # probe past deadline: fails
    assert plug.fused_calls == 2 and c is None
    assert agg._crc_failures == 2        # backoff doubled (0.1s)
    assert agg.perf.dump().get("crc_fallbacks", 0) == 2
    plug.ok = True
    time.sleep(0.11)
    p, c = agg._run(plug, d, True)       # probe succeeds: fused back
    assert plug.fused_calls == 3 and c is not None
    assert agg._crc_failures == 0
    assert (p == np.asarray(ec.encode_batch(d))).all()


def test_streaming_pipeline_falls_back_on_device_fault(ec):
    """An injected mid-stream jit failure loses NO batches: the
    pipeline re-encodes in-flight host copies on the non-donated
    unpipelined path and drains the rest, outputs in submission
    order — and devmon counts the fallback and the injected fault."""
    from ceph_tpu.sim import faults as F
    from ceph_tpu.utils import devmon as devmon_mod
    rng = _rng(20)
    batches = [rng.integers(0, 256, (2, K, C), dtype=np.uint8)
               for _ in range(4)]
    dm = devmon_mod.devmon()
    before = dm.perf.dump()
    inj = F.FaultInjector(seed=16)
    inj.install("stream", [F.jit_fail("ec_stream_encode", count=1)])
    devmon_mod.set_fault_injector(inj)
    try:
        pipe = StreamingEncodePipeline(ec)
        outs = pipe.encode_all([b.copy() for b in batches])
    finally:
        devmon_mod.set_fault_injector(None)
    after = dm.perf.dump()
    assert after.get("stream_fallbacks", 0) - \
        before.get("stream_fallbacks", 0) == 1
    assert after.get("faults_injected", 0) - \
        before.get("faults_injected", 0) == 1
    assert len(outs) == len(batches)
    for i, (b, o) in enumerate(zip(batches, outs)):
        assert (np.asarray(o) ==
                np.asarray(ec.encode_batch(b))).all(), i
