"""Mesh-sharded CRUSH sweep (crush/sharded_sweep.py): bit-exactness
vs the single-device engine on the 8-device virtual CPU mesh.

The pod-scale claim rests on the sharded sweep being the SAME
computation as the single-chip path, only split over the mesh axis —
every test here pins lane-for-lane equality against ``Mapper.map_pgs``
/ ``Mapper.sweep`` (and through them ``mapper_ref``), across shard
boundaries, non-divisible batch padding, zero-weight slots,
choose_args weight-sets, and the kernel's ambiguity-flagged fallback
lanes. Multichip behavior is guarded by n_devices detection: CI runs
XLA's 8-virtual-device CPU mesh (conftest forces it), the same
shardings the driver's dryrun and the TPU bench use.

Budget note: the per-test cost here is XLA CPU compiles of 8-shard
programs, so tests share one module-scope map/mapper and matched
(block, local_n) shapes wherever exactness allows — the shard_map
executables then reuse across tests instead of recompiling.
"""

import numpy as np
import pytest

from ceph_tpu.crush import builder, mapper_ref
from ceph_tpu.crush.mapper import Mapper
from ceph_tpu.crush.sharded_sweep import sharded_map_pgs, sharded_sweep
from ceph_tpu.crush.types import ITEM_NONE, WEIGHT_ONE
from ceph_tpu.parallel import local_mesh

N = 8 * 97          # shard-boundary-rich, non-divisible by block


@pytest.fixture(scope="module")
def mesh():
    m = local_mesh()
    # the tier-1 fallback contract: XLA_FLAGS virtualizes 8 CPU
    # devices (conftest); real multichip runs detect their own count
    assert m.devices.size == 8
    return m


def _hier(n_hosts, per_host, weights=None):
    m, root = builder.build_hierarchy(
        n_hosts, per_host, n_racks=max(1, n_hosts // 4),
        osd_weights=weights)
    rid = builder.add_simple_rule(m, root, builder.TYPE_HOST)
    return m, rid


@pytest.fixture(scope="module")
def hier(mesh):
    """One shared (map, rule, mapper, reference table) for every test
    that doesn't need special weights — the compiled shard programs
    and the single-device reference amortize across the module."""
    m, rid = _hier(8, 4)
    mp = Mapper(m, block=1 << 10)
    xs = np.arange(N, dtype=np.uint32)
    want = np.asarray(mp.map_pgs(rid, xs, 3))
    return m, rid, mp, want


def _assert_rows_match_ref(m, rid, got, xs, numrep, weights=None,
                           choose_args=None):
    wl = list(weights) if weights is not None else None
    for i, x in enumerate(xs):
        ref = mapper_ref.do_rule(m, rid, int(x), numrep, weight=wl,
                                 choose_args=choose_args)
        ref = ref + [ITEM_NONE] * (numrep - len(ref))
        assert list(got[i]) == ref, (int(x), list(got[i]), ref)


class TestBitExact:
    def test_map_pgs_matches_single_device_and_ref_at_boundaries(
            self, mesh, hier):
        """Shard-boundary PG ids must not smear: the lanes at every
        shard edge are checked against the scalar spec directly, and
        the whole table against the single-device engine."""
        m, rid, mp, want = hier
        xs = np.arange(N, dtype=np.uint32)
        got = np.asarray(sharded_map_pgs(mesh, mp, rid, xs, 3))
        assert (got == want).all()
        local_n = N // 8
        edges = sorted({0, N - 1} | {
            b for s in range(1, 8) for b in
            (s * local_n - 1, s * local_n)})
        _assert_rows_match_ref(m, rid, got[edges], xs[edges], 3)

    def test_non_divisible_batch_padding(self, mesh, hier):
        """n % n_devices != 0 pads (map) / tail-masks (sweep) — both
        entry points stay exact at an awkward size."""
        m, rid, mp, want = hier
        n = 757                           # prime: 757 % 8 == 5
        xs = np.arange(n, dtype=np.uint32)
        got = np.asarray(sharded_map_pgs(mesh, mp, rid, xs, 3))
        assert (got == want[:n]).all()
        c, b = sharded_sweep(mesh, mp, rid, 0, n, 3)
        c1, b1 = mp.sweep(rid, 0, n, 3)
        assert (np.asarray(c) == np.asarray(c1)).all()
        assert int(b) == int(b1)

    def test_randomized_sweep(self, mesh, hier, rng):
        """Randomized PG ids (not a contiguous range) through the
        sharded full-mapping path vs the single-device engine."""
        m, rid, mp, _ = hier
        xs = rng.integers(0, 1 << 31, size=N).astype(np.uint32)
        got = np.asarray(sharded_map_pgs(mesh, mp, rid, xs, 3))
        want = np.asarray(mp.map_pgs(rid, xs, 3))
        assert (got == want).all()

    def test_zero_weight_slots(self, mesh):
        """Zero-weight OSDs (dead slots in their host buckets) must
        never be chosen, sharded or not."""
        weights = [0 if i % 5 == 0 else WEIGHT_ONE for i in range(16)]
        m, rid = _hier(4, 4, weights=weights)
        mp = Mapper(m, block=1 << 10)
        xs = np.arange(203, dtype=np.uint32)
        got = np.asarray(sharded_map_pgs(mesh, mp, rid, xs, 3))
        want = np.asarray(mp.map_pgs(rid, xs, 3))
        assert (got == want).all()
        dead = [i for i in range(16) if weights[i] == 0]
        assert not (np.isin(got, dead)).any()
        _assert_rows_match_ref(m, rid, got[:16], xs[:16], 3)

    def test_choose_args_weight_sets(self, mesh):
        """A balancer-style single-position choose_args weight-set
        rides the sharded path bit-exactly (the XLA engine here; the
        kernel variant is TestKernelPath)."""
        from ceph_tpu.crush.types import ChooseArg
        m, rid = _hier(4, 5)
        rng = np.random.default_rng(7)
        args = {}
        for bid, b in m.buckets.items():
            scale = rng.uniform(0.9, 1.1, size=b.size)
            args[bid] = ChooseArg(weight_set=[[
                max(1, int(w * s))
                for w, s in zip(b.weights, scale)]])
        m.choose_args[0] = args
        mp = Mapper(m, block=1 << 10, choose_args=0)
        xs = np.arange(203, dtype=np.uint32)
        got = np.asarray(sharded_map_pgs(mesh, mp, rid, xs, 3))
        want = np.asarray(mp.map_pgs(rid, xs, 3))
        assert (got == want).all()
        _assert_rows_match_ref(m, rid, got[:16], xs[:16], 3,
                               choose_args=args)

    def test_legacy_tunables_rejected(self, mesh):
        from ceph_tpu.crush.types import Tunables
        m, rid = _hier(4, 2)
        m.tunables = Tunables(chooseleaf_stable=0)
        mp = Mapper(m)
        with pytest.raises(ValueError):
            sharded_map_pgs(mesh, mp, rid,
                            np.arange(64, dtype=np.uint32), 3)
        with pytest.raises(ValueError):
            sharded_sweep(mesh, mp, rid, 0, 64, 3)


class TestKernelPath:
    """The fused kernel (interpret mode) through the sharded path —
    including lanes the kernel flags to its bit-exact XLA fallback."""

    @pytest.fixture(autouse=True)
    def _interpret_mode(self, monkeypatch):
        monkeypatch.setenv("CEPH_TPU_CRUSH_KERNEL", "interpret")

    def test_ambiguity_flagged_lanes_bit_exact(self, mesh,
                                               monkeypatch):
        """Blown-up margin: EVERY lane flags to the kernel's XLA
        fallback inside every shard — the sharded result must still
        equal the scalar spec (the acceptance criterion's
        ambiguity-lane clause). Continuous weights, so the flagging
        runs the round-10 two-phase choose."""
        from ceph_tpu.crush import pallas_mapper as pm
        monkeypatch.setattr(pm, "MARGIN_ABS", 1e30)
        m, root = builder.build_flat(
            8, weights=[WEIGHT_ONE + 991 * i for i in range(8)])
        rid = builder.add_simple_rule(m, root, builder.TYPE_OSD)
        mp = Mapper(m, block=1 << 8)
        assert mp._kernel_body(rid, 3) is not None
        assert 0 in mp._kernel_plan(rid).kmax    # continuous level
        xs = np.arange(130, dtype=np.uint32)
        got = np.asarray(sharded_map_pgs(mesh, mp, rid, xs, 3))
        _assert_rows_match_ref(m, rid, got, xs, 3)

    @pytest.mark.slow
    def test_kernel_sharded_bit_exact(self, mesh):
        """Unflagged kernel lanes through the sharded path vs the
        single-device kernel engine (deep variant; tier-1 covers the
        kernel+sharded combination via the ambiguity test above)."""
        m, rid = _hier(4, 4)
        mp = Mapper(m, block=1 << 9)
        assert mp._kernel_body(rid, 3) is not None
        xs = np.arange(257, dtype=np.uint32)
        got = np.asarray(sharded_map_pgs(mesh, mp, rid, xs, 3))
        mx = Mapper(m, block=1 << 9)
        want = np.asarray(mx.map_pgs(rid, xs, 3))
        assert (got == want).all()
        _assert_rows_match_ref(m, rid, got[:16], xs[:16], 3)


class TestWiring:
    def test_mapper_mesh_option(self, mesh, hier):
        """Mapper(mesh=...) routes big batches through the sharded
        path (recorded in last_map_path), small ones stay local."""
        m, rid, mx, want = hier
        mp = Mapper(m, block=1 << 10, mesh=mesh, mesh_min_batch=128)
        xs = np.arange(N, dtype=np.uint32)
        got = np.asarray(mp.map_pgs(rid, xs, 3))
        assert mp.last_map_path == "xla+sharded"
        assert (got == want).all()
        small = np.asarray(mp.map_pgs(rid, xs[:16], 3))
        assert mp.last_map_path == "xla"
        assert (small == want[:16]).all()
        c, b = mp.sweep(rid, 0, 757, 3)
        assert mp.last_map_path == "xla+sharded"
        c1, b1 = mx.sweep(rid, 0, 757, 3)
        assert (np.asarray(c) == np.asarray(c1)).all()
        assert int(b) == int(b1)

    def test_osdmap_mapping_sharded_full_sweep(self, mesh):
        """The round-10 satellite: a crush-topology change forces the
        full-sweep fallback; with a mesh attached it runs sharded and
        bumps remap_sharded_sweeps (the prometheus counter's source).
        The resulting table must equal a mesh-less rebuild."""
        from ceph_tpu.bench import osdmaptool
        from ceph_tpu.osd.osdmap import PERF
        from ceph_tpu.osd.osdmap_mapping import OSDMapMapping

        m = osdmaptool.create_simple(32, 256, 3, erasure=False)
        before = PERF.dump()["remap_sharded_sweeps"]
        mm = OSDMapMapping(m, mesh=mesh, mesh_min_batch=1)
        assert mm.last_sharded_sweeps > 0
        assert mm.last_full_sweep_pools > 0
        # crush topology edit -> full-sweep fallback, sharded again
        from ceph_tpu.osd.osdmap import Incremental
        m.crush.buckets[-1].weights[0] += 7        # in-place edit
        m.crush_version += 1
        m.apply_incremental(Incremental(epoch=m.epoch + 1))
        mm.update(m)
        assert mm.last_sharded_sweeps > 0
        assert PERF.dump()["remap_sharded_sweeps"] > before
        # bit-identical vs a from-scratch mesh-less table
        plain = OSDMapMapping(m)
        for pid in m.pools:
            assert (mm._pools[pid].up == plain._pools[pid].up).all()
            assert (mm._pools[pid].acting
                    == plain._pools[pid].acting).all()

    def test_crush_sweep_span(self, mesh):
        """Tracing satellite: bulk full sweeps emit a crush_sweep span
        tagged n_pgs/path/n_devices through the attached Tracer."""
        from ceph_tpu.bench import osdmaptool
        from ceph_tpu.osd.osdmap_mapping import OSDMapMapping
        from ceph_tpu.utils.tracing import Tracer

        tracer = Tracer("osd.test",
                        {"trace_sampling_rate": 1.0,
                         "trace_slow_keep_s": 30.0})
        m = osdmaptool.create_simple(16, 64, 3, erasure=False)
        OSDMapMapping(m, mesh=mesh, mesh_min_batch=1, tracer=tracer)
        spans = [s for s in tracer.dump()["spans"]
                 if s["name"] == "crush_sweep"]
        assert spans, "no crush_sweep span recorded"
        tags = spans[-1]["tags"]
        assert tags["n_pgs"] == 64
        assert tags["n_devices"] == 8
        assert tags["path"].endswith("+sharded")
