"""CrushTester + crushtool CLI tests."""

import numpy as np
import pytest

from ceph_tpu.bench.crushtool import main, parse_args
from ceph_tpu.crush import builder
from ceph_tpu.crush.tester import CrushTester
from ceph_tpu.crush.types import WEIGHT_ONE


@pytest.mark.slow
class TestCrushTester:
    def test_counts_and_badmaps(self):
        m, root = builder.build_hierarchy(5, 2)
        builder.add_simple_rule(m, root, builder.TYPE_HOST)
        t = CrushTester(m)
        res = t.test(0, 3, 0, 511)
        assert res.total_x == 512
        assert res.device_counts.sum() == 512 * 3
        assert res.bad_mappings == 0
        s = res.utilization_summary()
        assert s["active_devices"] == 10
        assert s["placements"] == 512 * 3

    def test_bad_mappings_counted(self):
        # 3 hosts, ask 5 replicas by host -> every x underfills.
        m, root = builder.build_hierarchy(3, 2)
        builder.add_simple_rule(m, root, builder.TYPE_HOST)
        res = CrushTester(m).test(0, 5, 0, 63)
        assert res.bad_mappings == 64

    def test_batching_equivalence(self):
        m, root = builder.build_flat(8)
        builder.add_simple_rule(m, root, builder.TYPE_OSD)
        a = CrushTester(m, batch=64).test(0, 2, 0, 255)
        b = CrushTester(m, batch=1 << 20).test(0, 2, 0, 255)
        assert np.array_equal(a.device_counts, b.device_counts)

    def test_weight_override(self):
        m, root = builder.build_flat(4)
        builder.add_simple_rule(m, root, builder.TYPE_OSD)
        w = np.full(4, WEIGHT_ONE, dtype=np.int64)
        w[2] = 0
        res = CrushTester(m, w).test(0, 2, 0, 255)
        assert res.device_counts[2] == 0


class TestCrushtoolCLI:
    @pytest.mark.slow
    def test_build_test_json(self, capsys):
        out = main(["--build", "--num-osds", "8", "--hosts", "4", "--test",
                    "--num-rep", "2", "--max-x", "127", "--json"])
        assert out["total_x"] == 128
        assert out["bad_mappings"] == 0
        assert out["utilization"]["placements"] == 256

    @pytest.mark.slow
    def test_weight_flag(self):
        out = main(["--build", "--num-osds", "4", "--test", "--num-rep",
                    "2", "--max-x", "127", "--weight", "1", "0.0"])
        # device 1 reweighted to 0 -> no placements
        assert out["utilization"]["active_devices"] == 3

    def test_requires_build(self):
        with pytest.raises(SystemExit):
            main(["--test"])

    def test_uneven_hosts_rejected(self):
        with pytest.raises(SystemExit):
            main(["--build", "--num-osds", "10", "--hosts", "4"])
