"""LRC / SHEC / CLAY plugin tests — the reference's per-plugin gtest
pattern (ref: src/test/erasure-code/TestErasureCodeLrc.cc,
TestErasureCodeShec*.cc, TestErasureCodeClay.cc): encode a known buffer,
erase chunks, check minimum_to_decode, decode, byte-compare. Plus the
plugins' headline properties: LRC local repair reads l not k; CLAY single
repair reads alpha/q sub-chunks from d helpers."""

import numpy as np
import pytest

from ceph_tpu.ec.clay import ErasureCodeClay
from ceph_tpu.ec.lrc import ErasureCodeLrc, generate_kml
from ceph_tpu.ec.registry import factory
from ceph_tpu.ec.shec import ErasureCodeShec, shec_matrix


def roundtrip(ec, payload: bytes, erase: list[int]) -> None:
    n = ec.get_chunk_count()
    enc = ec.encode(range(n), payload)
    chunks = {i: c for i, c in enc.items() if i not in erase}
    dec = ec.decode(list(range(n)), chunks)
    for i in range(n):
        assert dec[i] == enc[i], f"chunk {i} mismatch after erasing {erase}"
    out = ec.decode_concat({i: c for i, c in enc.items()
                            if i not in erase})
    assert out[:len(payload)] == payload


class TestKmlGeneration:
    def test_doc_example(self):
        # doc/rados/operations/erasure-code-lrc.rst k=4 m=2 l=3
        mapping, layers = generate_kml(4, 2, 3)
        assert mapping == "__DD__DD"
        assert layers[0][0] == "_cDD_cDD"
        assert layers[1][0] == "cDDD____"
        assert layers[2][0] == "____cDDD"

    def test_invalid(self):
        with pytest.raises(ValueError):
            generate_kml(4, 2, 4)  # (k+m) % l != 0


class TestLrc:
    def setup_method(self):
        self.ec = ErasureCodeLrc("plugin=lrc k=4 m=2 l=3")
        self.payload = bytes(range(256)) * 13

    def test_geometry(self):
        assert self.ec.get_chunk_count() == 8
        assert self.ec.get_data_chunk_count() == 4

    def test_roundtrip_single(self):
        for erase in range(8):
            roundtrip(self.ec, self.payload, [erase])

    def test_roundtrip_double(self):
        roundtrip(self.ec, self.payload, [0, 5])
        roundtrip(self.ec, self.payload, [1, 2])

    def test_local_repair_reads_l_not_k(self):
        """The whole point of LRC: one lost chunk needs only its local
        group (l=3 reads), not k=4."""
        n = 8
        avail = set(range(n)) - {0}
        need = self.ec.minimum_to_decode([0], avail)
        assert len(need) == 3
        # all reads within chunk 0's local group
        mapping = self.ec.get_chunk_mapping()
        pos = {mapping[i] for i in need} | {mapping[0]}
        group = set(range(0, 4))  # first (l+1)-position group
        assert pos <= group

    def test_comma_separated_profile_with_layers(self):
        from ceph_tpu.ec.interface import ErasureCodeProfile
        prof = ErasureCodeProfile.parse(
            'plugin=lrc,mapping=__DD__DD,'
            'layers=[["_cDD_cDD",""],["cDDD____",""],["____cDDD",""]]')
        assert prof["mapping"] == "__DD__DD"
        assert prof["plugin"] == "lrc"
        ec = ErasureCodeLrc(prof)
        assert ec.get_chunk_count() == 8

    def test_explicit_profile(self):
        ec = ErasureCodeLrc(
            'plugin=lrc mapping=__DD__DD '
            'layers=[["_cDD_cDD",""],["cDDD____",""],["____cDDD",""]]')
        roundtrip(ec, self.payload, [2])

    def test_registry(self):
        ec = factory("plugin=lrc k=4 m=2 l=3")
        assert isinstance(ec, ErasureCodeLrc)

    def test_undecodable_raises(self):
        # losing a whole local group of 4 exceeds any layer's power
        enc = self.ec.encode(range(8), self.payload)
        chunks = {i: c for i, c in enc.items() if i >= 4}
        with pytest.raises(ValueError):
            self.ec.decode(list(range(4)), chunks)


class TestShec:
    def setup_method(self):
        self.ec = ErasureCodeShec("plugin=shec k=4 m=3 c=2")
        self.payload = b"shec" * 999

    def test_matrix_windows(self):
        mat = shec_matrix(4, 3, 2)
        # w = ceil(4*2/3) = 3 consecutive data chunks per parity
        for i in range(3):
            cov = np.flatnonzero(mat[i])
            assert len(cov) <= 3
            assert (np.diff(cov) == 1).all()
        # average coverage ~ c
        assert (mat != 0).sum() >= 4 * 2

    def test_roundtrip_single(self):
        for erase in range(7):
            roundtrip(self.ec, self.payload, [erase])

    def test_roundtrip_double(self):
        roundtrip(self.ec, self.payload, [0, 3])
        roundtrip(self.ec, self.payload, [1, 5])

    def test_local_repair_cheaper_than_k(self):
        avail = set(range(7)) - {0}
        need = self.ec.minimum_to_decode([0], avail)
        # window repair: parity 0 covers [0,1,2] -> read {1,2,parity}
        assert len(need) <= 3

    def test_registry(self):
        ec = factory("plugin=shec k=4 m=3 c=2")
        assert isinstance(ec, ErasureCodeShec)


class TestClay:
    def setup_method(self):
        self.ec = ErasureCodeClay("plugin=clay k=4 m=2")
        self.payload = bytes(range(256)) * 9

    def test_geometry(self):
        # q=2, n=6 -> t=3, alpha=8
        assert self.ec.q == 2 and self.ec.t == 3
        assert self.ec.sub_chunk_count() == 8
        assert self.ec.get_repair_sub_chunk_count() == 4
        assert self.ec.get_chunk_size(100) % 8 == 0

    def test_roundtrip_single_each(self):
        for erase in range(6):
            roundtrip(self.ec, self.payload, [erase])

    def test_roundtrip_double_all_patterns(self):
        for a in range(6):
            for b in range(a + 1, 6):
                roundtrip(self.ec, self.payload, [a, b])

    def test_repair_matches_full_decode(self):
        """Bandwidth-optimal repair and layered decode agree bit-exact."""
        enc = self.ec.encode(range(6), self.payload)
        for failed in range(6):
            chunks = {i: c for i, c in enc.items() if i != failed}
            got = self.ec.decode([failed], chunks)[failed]
            assert got == enc[failed], f"repair of {failed} diverged"

    def test_repair_reads_subchunk_fraction(self):
        """Single repair consumes exactly alpha/q sub-chunks per helper."""
        enc = self.ec.encode(range(6), self.payload)
        failed = 2
        C = len(enc[0])
        alpha = self.ec.sub_chunk_count()
        S = C // alpha
        R = self.ec.repair_plane_indices(failed)
        assert len(R) == alpha // self.ec.q
        arrs = {i: np.frombuffer(c, dtype=np.uint8).reshape(alpha, S)
                for i, c in enc.items() if i != failed}
        subs = {p: {zi: a[zi] for zi in R} for p, a in arrs.items()}
        got = self.ec.repair_chunk(failed, subs, C)
        assert got.tobytes() == enc[failed]

    def test_minimum_single_failure_is_all_helpers(self):
        need = self.ec.minimum_to_decode([1], set(range(6)) - {1})
        assert need == set(range(6)) - {1}

    def test_k8_m4_geometry(self):
        ec = ErasureCodeClay("plugin=clay k=8 m=4")
        # q=4, n=12 -> t=3, alpha=64
        assert ec.sub_chunk_count() == 64
        payload = b"clay-8-4" * 512
        enc = ec.encode(range(12), payload)
        chunks = {i: c for i, c in enc.items() if i not in (0, 5, 9, 11)}
        dec = ec.decode(list(range(12)), chunks)
        for i in range(12):
            assert dec[i] == enc[i]

    def test_virtual_padding_geometry(self):
        # k=5 m=2: n=7, q=2, t=4 (pad 1 virtual), alpha=16
        ec = ErasureCodeClay("plugin=clay k=5 m=2")
        assert ec.nu == 1
        payload = b"pad" * 1000
        for erase in ([0], [6], [1, 4]):
            roundtrip(ec, payload, erase)

    def test_registry(self):
        ec = factory("plugin=clay k=4 m=2")
        assert isinstance(ec, ErasureCodeClay)

    def test_unsupported_d(self):
        with pytest.raises(NotImplementedError):
            ErasureCodeClay("plugin=clay k=4 m=2 d=4")


class TestJerasureTechniqueBreadth:
    """VERDICT round-1 item #8: reed_sol_r6_op + the bitmatrix family
    (ref: ErasureCodeJerasure subclasses)."""

    def test_r6_matrix_structure(self):
        from ceph_tpu.ec.matrix import reed_sol_r6_op
        from ceph_tpu.gf import tables
        m = reed_sol_r6_op(6, 2)
        assert (m[0] == 1).all()                 # P row = XOR
        acc = 1
        for i in range(6):
            assert int(m[1, i]) == acc           # Q row = powers of 2
            acc = tables.gf_mul(acc, 2)

    @pytest.mark.parametrize("technique,k,params", [
        ("reed_sol_r6_op", 4, ""),
        ("reed_sol_r6_op", 6, ""),
        ("liberation", 4, " w=7"),
        ("liberation", 5, " w=5"),
        ("blaum_roth", 4, " w=4"),
        ("blaum_roth", 5, " w=6"),
        ("liber8tion", 4, ""),
        ("liber8tion", 6, ""),
    ])
    def test_roundtrip_all_erasure_patterns(self, technique, k, params):
        from itertools import combinations

        from ceph_tpu.ec import factory
        ec = factory(f"plugin=jerasure technique={technique} k={k} m=2"
                     + params)
        rng = np.random.default_rng(17)
        size = 4096
        payload = rng.integers(0, 256, size, dtype=np.uint8).tobytes()
        enc = ec.encode(range(k + 2), payload)
        # every 1- and 2-erasure pattern must decode byte-exactly (MDS)
        for r in (1, 2):
            for erased in combinations(range(k + 2), r):
                have = {i: c for i, c in enc.items() if i not in erased}
                got = ec.decode(list(erased), have)
                for e in erased:
                    assert got[e] == enc[e], (technique, erased, e)
        assert ec.decode_concat({i: c for i, c in enc.items()
                                 if i >= 2})[:size] == payload

    def test_bitmatrix_mds_verified_at_build(self):
        from ceph_tpu.ec.bitmatrix import (blaum_roth_bitmatrix, is_mds,
                                           liber8tion_bitmatrix,
                                           liberation_bitmatrix)
        assert is_mds(liberation_bitmatrix(5, 7), 5, 2, 7)
        assert is_mds(blaum_roth_bitmatrix(6, 6), 6, 2, 6)
        assert is_mds(liber8tion_bitmatrix(5), 5, 2, 8)

    def test_geometry_guards(self):
        from ceph_tpu.ec import factory
        with pytest.raises(Exception):
            factory("plugin=jerasure technique=reed_sol_r6_op k=4 m=3")
        with pytest.raises(Exception):
            factory("plugin=jerasure technique=liberation k=4 m=2 w=6")
        with pytest.raises(Exception):
            factory("plugin=jerasure technique=blaum_roth k=4 m=2 w=7")

    def test_bitmatrix_batched_device_path(self):
        from ceph_tpu.ec import factory
        ec = factory("plugin=jax technique=liber8tion k=4 m=2")
        rng = np.random.default_rng(23)
        data = rng.integers(0, 256, (5, 4, 1024), dtype=np.uint8)
        parity = np.asarray(ec.encode_batch(data))
        assert parity.shape == (5, 2, 1024)
        # P drive is the XOR of data packets in every array code here
        assert (parity[:, 0] == np.bitwise_xor.reduce(data, axis=1)).all()
        full = np.concatenate([data, parity], axis=1)
        out = np.asarray(ec.decode_batch([1, 4], [0, 2, 3, 5],
                                         full[:, [0, 2, 3, 5]]))
        assert (out[:, 0] == data[:, 1]).all()
        assert (out[:, 1] == parity[:, 0]).all()
