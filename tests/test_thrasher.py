"""Seeded Thrasher: deterministic schedules + live smoke storms.

ref test model: qa/tasks/ceph_manager.py Thrasher as consumed by the
rados/thrash suites — a seeded random storm of kills, revives,
partitions and degraded links under continuing client writes, after
which the cluster must converge clean with every acked write intact
and every store fscking clean.
"""

import asyncio
import random

import pytest

from ceph_tpu.cluster.vstart import Cluster
from ceph_tpu.os_.bluestore import BlueStore
from ceph_tpu.sim.thrasher import Thrasher


def run(coro):
    asyncio.run(coro)


def test_plan_is_pure_function_of_seed():
    """Reproducibility is the whole point of a seeded thrasher: the
    schedule must be identical for one seed and differ across seeds."""
    a = Thrasher.plan(7, 40)
    b = Thrasher.plan(7, 40)
    c = Thrasher.plan(8, 40)
    assert a == b
    assert a != c
    assert len(a) == 40
    kinds = {x["op"] for x in a}
    assert "kill_osd" in kinds and "partition" in kinds


def _mk_store(tmp_path, i):
    return BlueStore(str(tmp_path / f"osd{i}" / "bs"))


def _thrash_cluster_config():
    return {
        "mon_osd_down_out_interval": 600.0,
        "mon_osd_min_down_reporters": 2,
        # oversubscribed single-core host: production-shaped mon
        # timing so elections don't loop under recovery load (the
        # deep-thrash lesson from tests/test_thrash.py)
        "mon_lease": 4.0, "mon_lease_interval": 0.5,
        "mon_election_timeout": 1.0, "mon_paxos_timeout": 8.0,
    }


def test_thrasher_smoke_seeded(tmp_path):
    """Short seeded storm on BlueStore with revive-via-remount and a
    mon-leader kill in the mix: the four Thrasher invariants hold and
    the executed log matches the seeded schedule's feasible actions."""
    async def go():
        stores = [_mk_store(tmp_path, i) for i in range(4)]
        c = await Cluster(n_mons=3, n_osds=4, stores=stores,
                          config=_thrash_cluster_config()).start()
        try:
            await c.client.pool_create("t", pg_num=8, size=3,
                                       min_size=2)
            await c.wait_for_clean(timeout=240)
            io = await c.client.open_ioctx("t")

            def remount(i):
                return _mk_store(tmp_path, i)

            th = Thrasher(c, seed=1234, store_factory=remount,
                          min_live_osds=3)
            log = await th.thrash(io, steps=14)
            assert log, "thrasher executed nothing"
            summary = await th.settle_and_verify(io, timeout=300)
            assert summary["acked_writes"] > 0
            assert summary["fscked_stores"] == 4
        finally:
            await c.stop()
    run(go())


@pytest.mark.slow
def test_thrasher_storm_deep(tmp_path):
    """The acceptance storm: longer seeded run with partitions, OSD
    kill/revive-with-remount and mon leader kills under continuing
    writes; converges clean, all acked data readable, all stores
    fsck clean."""
    async def go():
        stores = [_mk_store(tmp_path, i) for i in range(5)]
        c = await Cluster(n_mons=3, n_osds=5, stores=stores,
                          config=_thrash_cluster_config()).start()
        try:
            await c.client.pool_create("t", pg_num=16, size=3,
                                       min_size=2)
            await c.wait_for_clean(timeout=240)
            io = await c.client.open_ioctx("t")

            def remount(i):
                return _mk_store(tmp_path, i)

            th = Thrasher(c, seed=99, store_factory=remount,
                          min_live_osds=3)
            await th.thrash(io, steps=70)
            summary = await th.settle_and_verify(io, timeout=600)
            assert summary["acked_writes"] > 10
            assert summary["fscked_stores"] == 5
        finally:
            await c.stop()
    run(go())


def test_thrasher_snap_storm_smoke(tmp_path):
    """Snapshot-under-load honesty (MemStore tier-1 smoke): cut
    snapshots mid-write-storm, kill an OSD after the first one, keep
    writing, revive — every snapshot's full readback must stay
    byte-identical to its creation-time capture and the head must
    hold every acked write."""
    async def go():
        c = await Cluster(n_mons=3, n_osds=3,
                          config=_thrash_cluster_config()).start()
        try:
            await c.client.pool_create("rbd", pg_num=8, size=3,
                                       min_size=2)
            await c.wait_for_clean(timeout=240)
            io = await c.client.open_ioctx("rbd")
            th = Thrasher(c, seed=4242, min_live_osds=2)
            report = await th.snap_storm(io, writes=18, snaps=3,
                                         image_kb=16)
            assert report["snaps_verified"] == 3
            assert report["victim"] is not None, \
                "storm never exercised the OSD-kill path"
            assert report["acked_writes"] > 0
            summary = await th.settle_and_verify(io, timeout=240)
            assert summary["killed_mons"] == 0
        finally:
            await c.stop()
    run(go())


@pytest.mark.slow
def test_thrasher_snap_storm_deep(tmp_path):
    """The snapshot acceptance storm on BlueStore: bigger image, more
    snapshots, revive-via-remount (deferred replay + allocator
    rebuild), then the full fsck — including the shared-blob refcount
    census that cross-checks every COW clone's extent references
    against the stored per-blob counts."""
    async def go():
        stores = [_mk_store(tmp_path, i) for i in range(4)]
        c = await Cluster(n_mons=3, n_osds=4, stores=stores,
                          config=_thrash_cluster_config()).start()
        try:
            await c.client.pool_create("rbd", pg_num=8, size=3,
                                       min_size=2)
            await c.wait_for_clean(timeout=240)
            io = await c.client.open_ioctx("rbd")

            def remount(i):
                return _mk_store(tmp_path, i)

            th = Thrasher(c, seed=777, store_factory=remount,
                          min_live_osds=3)
            report = await th.snap_storm(io, writes=48, snaps=5,
                                         image_kb=64,
                                         settle_timeout=600.0)
            assert report["snaps_verified"] == 5
            assert report["victim"] is not None
            summary = await th.settle_and_verify(io, timeout=600)
            assert summary["fscked_stores"] == 4
        finally:
            await c.stop()
    run(go())
