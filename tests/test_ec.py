"""Erasure-code tests.

Mirrors the reference's plugin test pattern: build profile -> factory() ->
encode known buffer -> erase chunks -> minimum_to_decode -> decode ->
byte-compare (ref: src/test/erasure-code/TestErasureCodeJerasure.cc,
TestErasureCodePlugin.cc).
"""

import itertools

import numpy as np
import pytest

from ceph_tpu.ec import ErasureCodeProfile, factory, matrix as rs
from ceph_tpu.gf import gf_matmul_np, gf_matinv_np


class TestMatrices:
    @pytest.mark.parametrize("technique", ["reed_sol_van", "cauchy_orig",
                                           "cauchy_good"])
    @pytest.mark.parametrize("k,m", [(2, 2), (4, 2), (8, 3), (10, 4)])
    def test_mds_property(self, technique, k, m):
        """Every k-subset of generator rows must be invertible (MDS)."""
        g = rs.generator_matrix(technique, k, m)
        rows = list(range(k + m))
        subsets = list(itertools.combinations(rows, k))
        if len(subsets) > 300:
            rng = np.random.default_rng(7)
            subsets = [tuple(sorted(rng.choice(rows, size=k, replace=False)))
                       for _ in range(300)]
        for sub in subsets:
            gf_matinv_np(g[list(sub)])  # raises if singular

    def test_vandermonde_systematic_and_ones(self):
        m = rs.reed_sol_van(8, 3)
        # Construction invariants of the published jerasure algorithm:
        # parity row 0 is all ones, and column 0 of every parity row is one.
        assert np.all(m[0] == 1)
        assert np.all(m[:, 0] == 1)

    def test_cauchy_good_first_row_ones(self):
        assert np.all(rs.cauchy_good(6, 3)[0] == 1)

    def test_decode_matrix_identity_when_available(self):
        d = rs.decode_matrix("reed_sol_van", 4, 2, (0, 1, 2, 3), (1, 3))
        expect = np.zeros((2, 4), dtype=np.uint8)
        expect[0, 1] = 1
        expect[1, 3] = 1
        assert np.array_equal(d, expect)


@pytest.mark.parametrize("technique", ["reed_sol_van", "cauchy_good"])
@pytest.mark.parametrize("k,m", [(2, 2), (4, 2), (8, 3)])
class TestRoundtrip:
    def _plugin(self, technique, k, m):
        return factory(f"plugin=jax technique={technique} k={k} m={m}")

    def test_encode_decode_all_erasure_patterns(self, rng, technique, k, m):
        ec = self._plugin(technique, k, m)
        data = rng.integers(0, 256, size=(k, 256)).astype(np.uint8)
        parity = ec.encode_chunks(data)
        assert parity.shape == (m, 256)
        full = {i: data[i] for i in range(k)}
        full.update({k + i: parity[i] for i in range(m)})
        # Erase every possible <= m subset; decode must reconstruct exactly.
        ids = list(range(k + m))
        patterns = [p for r in range(1, m + 1)
                    for p in itertools.combinations(ids, r)]
        if len(patterns) > 60:
            rng2 = np.random.default_rng(3)
            patterns = [tuple(sorted(rng2.choice(ids, size=m, replace=False)))
                        for _ in range(60)]
        for erased in patterns:
            avail = {i: c for i, c in full.items() if i not in erased}
            got = ec.decode_chunks(list(erased), avail)
            for i in erased:
                assert np.array_equal(got[i], full[i]), (erased, i)

    def test_byte_api(self, rng, technique, k, m):
        ec = self._plugin(technique, k, m)
        payload = rng.integers(0, 256, size=1000).astype(np.uint8).tobytes()
        encoded = ec.encode(range(k + m), payload)
        assert len(encoded) == k + m
        # Drop m chunks, decode_concat must return the payload (plus padding).
        kept = {i: encoded[i] for i in list(encoded)[m:]}
        out = ec.decode_concat(kept)
        assert out[:len(payload)] == payload

    def test_backends_agree(self, rng, technique, k, m):
        lut = factory(f"plugin=jax technique={technique} k={k} m={m} "
                      f"backend=lut")
        mxu = factory(f"plugin=jax technique={technique} k={k} m={m} "
                      f"backend=bitmatmul")
        data = rng.integers(0, 256, size=(k, 128)).astype(np.uint8)
        assert np.array_equal(lut.encode_chunks(data),
                              mxu.encode_chunks(data))

    def test_matches_numpy_oracle(self, rng, technique, k, m):
        ec = self._plugin(technique, k, m)
        data = rng.integers(0, 256, size=(k, 64)).astype(np.uint8)
        expect = gf_matmul_np(rs.coding_matrix(technique, k, m), data)
        assert np.array_equal(ec.encode_chunks(data), expect)


class TestInterface:
    def test_profile_parse(self):
        p = ErasureCodeProfile.parse("plugin=jax technique=reed_sol_van k=8 m=3")
        assert p["plugin"] == "jax"
        assert p.get_int("k", 0) == 8

    def test_chunk_size_alignment(self):
        ec = factory("plugin=jax k=4 m=2")
        cs = ec.get_chunk_size(4 * 1024 * 1024)
        assert cs == 1024 * 1024
        assert ec.get_chunk_size(1) % ec.get_alignment() == 0

    def test_minimum_to_decode(self):
        ec = factory("plugin=jax k=4 m=2")
        # All wanted available -> want itself.
        assert ec.minimum_to_decode([0, 1], [0, 1, 2, 3, 4]) == {0, 1}
        # Missing wanted -> any k available.
        got = ec.minimum_to_decode([0], [1, 2, 3, 4, 5])
        assert len(got) == 4 and got <= {1, 2, 3, 4, 5}
        with pytest.raises(ValueError):
            ec.minimum_to_decode([0], [1, 2])

    def test_minimum_to_decode_with_cost(self):
        ec = factory("plugin=jax k=2 m=2")
        got = ec.minimum_to_decode_with_cost([0], {1: 10, 2: 1, 3: 5})
        assert got == {2, 3}

    def test_registry_aliases(self):
        for name in ("jax", "jerasure", "isa"):
            ec = factory(f"plugin={name} k=4 m=2")
            assert ec.get_chunk_count() == 6

    def test_isa_jerasure_cross_check(self, rng):
        """SURVEY §4's jerasure<->isa oracle: two INDEPENDENT
        implementations — the JAX bit-plane MXU formulation vs the
        native C++ table-based RS backend plugin=isa resolves to —
        must agree byte-for-byte on parity and reconstruction."""
        isa = factory("plugin=isa k=8 m=3 technique=reed_sol_van")
        if not getattr(isa, "independent", False):
            pytest.skip("native toolchain unavailable; isa fell back")
        jer = factory("plugin=jerasure k=8 m=3 technique=reed_sol_van")
        assert type(isa) is not type(jer)       # really two backends
        data = rng.integers(0, 256, size=(8, 2048)).astype(np.uint8)
        pi = np.asarray(isa.encode_chunks(data))
        pj = np.asarray(jer.encode_chunks(data))
        assert np.array_equal(pi, pj)
        full = {i: data[i] for i in range(8)}
        full.update({8 + j: pi[j] for j in range(3)})
        surv = {i: c for i, c in full.items() if i not in (1, 9)}
        di = isa.decode_chunks([1], surv)
        dj = jer.decode_chunks([1], surv)
        assert np.array_equal(di[1], data[1])
        assert np.array_equal(dj[1], di[1])
        # the upstream isa "cauchy" technique name maps onto the
        # cauchy_good construction
        isac = factory("plugin=isa k=4 m=2 technique=cauchy")
        jaxc = factory("plugin=jax k=4 m=2 technique=cauchy_good")
        d2 = rng.integers(0, 256, size=(4, 512)).astype(np.uint8)
        assert np.array_equal(np.asarray(isac.encode_chunks(d2)),
                              np.asarray(jaxc.encode_chunks(d2)))

    def test_unknown_plugin(self):
        with pytest.raises(KeyError):
            factory("plugin=nope k=2 m=1")

    def test_unknown_technique(self):
        with pytest.raises(ValueError):
            factory("plugin=jax technique=liberation8 k=2 m=2")

    def test_batched_encode(self, rng):
        ec = factory("plugin=jax k=4 m=2")
        data = rng.integers(0, 256, size=(8, 4, 128)).astype(np.uint8)
        out = np.asarray(ec.encode_batch(data))
        for b in range(8):
            assert np.array_equal(out[b], ec.encode_chunks(data[b]))
