"""End-to-end distributed op tracing (round 9).

Acceptance surface:

- a single replicated-pool client write at ``trace_sampling_rate=1.0``
  yields ONE mgr-reassembled trace containing client, primary,
  >=2 replica, and objectstore-commit spans with correct parent links
  and non-overlapping phase durations summing ~= the client-observed
  latency;
- an artificially delayed op BELOW the sampling rate is still
  retained via the slow-op tail path (``trace_slow_keep_s``);
- ``PrometheusModule.render`` emits the per-op-class latency
  histograms as valid exposition-format ``le``-bucketed series with
  monotone cumulative buckets (pinned in tests/test_meta.py's parser
  guard; exercised against a LIVE cluster here);
- a storm smoke proves tracing survives kill/revive.
"""

import asyncio
import json
import time

import pytest

from ceph_tpu.cluster.vstart import Cluster
from ceph_tpu.mgr.modules import TracingModule
from ceph_tpu.sim import faults as F
from ceph_tpu.utils.tracing import Tracer, TraceIndex


def run(coro):
    asyncio.run(coro)


# -- unit: sampling + tail retention semantics -----------------------------

def test_tracer_head_sampling_and_propagation():
    t = Tracer("client", {"trace_sampling_rate": 1.0})
    root = t.start_root("client_op", tags={"oid": "o"})
    assert root is not None and root.trace_id != 0
    child = root.child("queue")
    assert child.trace_id == root.trace_id
    assert child.parent_span_id == root.span_id
    child.finish()
    root.finish()
    assert t.ship_pending() == 2
    # context rides a message; the receiver's span links to the sender
    from ceph_tpu.osd.messages import MOSDOp
    m = MOSDOp(tid=1, oid="o")
    m.set_trace(root)
    rx = Tracer("osd.0", {})
    span = rx.from_msg("osd_op", m)
    assert span is not None and span.trace_id == root.trace_id
    assert span.parent_span_id == root.span_id


def test_tracer_tail_retention_and_off_path():
    # unsampled but slow: retained with a post-hoc trace id
    t = Tracer("client", {"trace_sampling_rate": 0.0,
                          "trace_slow_keep_s": 0.01})
    slow = t.start_root("client_op")
    assert slow is not None and slow.trace_id == 0   # local-only
    time.sleep(0.02)
    slow.finish()
    d = t.dump()
    assert slow.trace_id != 0
    assert d["slow_spans"] and \
        d["slow_spans"][0]["tags"]["tail_sampled"]
    # unsampled and fast: dropped
    fast = t.start_root("client_op")
    fast.finish()
    assert len(t.dump()["spans"]) == 1
    # fully off (slow_keep <= 0): no span objects at all
    off = Tracer("client", {"trace_sampling_rate": 0.0,
                            "trace_slow_keep_s": 0.0})
    assert off.start_root("client_op") is None
    # unsampled context never propagates
    from ceph_tpu.osd.messages import MOSDOp
    m = MOSDOp(tid=1, oid="o")
    m.set_trace(t.start_root("client_op"))
    assert m.trace_id == 0


def test_trace_index_survives_malformed_spans():
    """Span blobs arrive from arbitrary clients (MTraceReport is an
    uncapped report): a mistyped field must drop at add(), never
    poison ls()/show() for every later caller."""
    idx = TraceIndex()
    idx.add({"trace_id": 1, "span_id": 2, "start": "not-a-float"})
    idx.add({"trace_id": 5, "span_id": 7, "parent_span_id": 9})
    idx.add({"trace_id": "x", "span_id": 1})
    idx.add({"trace_id": 3, "span_id": 4, "parent_span_id": 0,
             "name": "ok", "service": "client", "start": 1.0,
             "duration": 0.5, "tags": "not-a-dict"})
    rows = idx.ls()          # must not raise
    assert [r["trace_id"] for r in rows if r["root"] == "ok"]
    missing_fields = idx.show(5)
    if missing_fields is not None:       # kept with defaults is fine
        assert missing_fields["duration"] >= 0.0
    ok = idx.show(3)
    assert ok["tree"][0]["tags"] == {}


def test_trace_index_per_trace_span_cap_and_deep_chain():
    """One hostile trace_id cannot grow the index without bound, and
    a parent chain deeper than the serve cap must not drive show()'s
    recursion toward the interpreter limit."""
    idx = TraceIndex()
    for i in range(TraceIndex.MAX_SPANS_PER_TRACE + 50):
        idx.add({"trace_id": 1, "span_id": i + 1,
                 "parent_span_id": i, "name": "chain",
                 "service": "evil", "start": float(i),
                 "duration": 0.0, "tags": {}})
    ent = idx.traces[1]
    assert len(ent["spans"]) == TraceIndex.MAX_SPANS_PER_TRACE
    show = idx.show(1)          # must not raise RecursionError
    depth = 0
    node = show["tree"][0]
    while node["children"]:
        node = node["children"][0]
        depth += 1
    assert depth <= TraceIndex.MAX_TREE_DEPTH + 1


def test_trace_index_bounds_and_slowest_first():
    idx = TraceIndex(max_traces=4)
    for i in range(8):
        idx.add({"trace_id": i + 1, "span_id": 100 + i,
                 "parent_span_id": 0, "name": "client_op",
                 "service": "client", "start": float(i),
                 "duration": float(i) / 100.0, "tags": {}})
    assert len(idx.traces) == 4                  # oldest evicted
    rows = idx.ls()
    durs = [r["duration"] for r in rows]
    assert durs == sorted(durs, reverse=True)    # slowest first


# -- the acceptance trace: one replicated write, fully decomposed ----------

def _flatten(node, out):
    out.append(node)
    for c in node["children"]:
        _flatten(c, out)


def _find(nodes, name):
    return [n for n in nodes if n["name"] == name]


def test_replicated_write_trace_reassembly(tmp_path):
    async def go():
        c = await Cluster(
            n_mons=1, n_osds=3,
            config={"trace_sampling_rate": 1.0,
                    "mgr_tracing_interval": 0.25,
                    "admin_socket_dir": str(tmp_path)},
            mgr_modules=[TracingModule]).start()
        try:
            await c.client.pool_create("t", pg_num=8, size=3,
                                       min_size=2)
            await c.wait_for_clean(timeout=120)
            io = await c.client.open_ioctx("t")
            # warm the connection path: the FIRST write pays messenger
            # connect + auth handshakes, which are client-side time no
            # OSD phase can account for
            await io.write_full("warm-obj", b"w" * 4096)
            t0 = time.monotonic()
            await io.write_full("traced-obj", b"x" * 4096)
            observed = time.monotonic() - t0
            mod = c.mgr.modules[0]
            trace = None
            deadline = asyncio.get_event_loop().time() + 20
            while trace is None:
                for row in mod.trace_ls(limit=10):
                    cand = mod.trace_show(row["trace_id"])
                    if row["root"] == "client_op" and \
                            row["num_spans"] >= 6 and \
                            cand["tree"][0]["tags"].get("oid") == \
                            "traced-obj":
                        trace = cand
                        break
                if trace is None:
                    assert asyncio.get_event_loop().time() < \
                        deadline, (
                        "mgr never reassembled the write trace: "
                        f"{mod.trace_ls(limit=10)}")
                    await asyncio.sleep(0.1)

            spans: list[dict] = []
            assert len(trace["tree"]) == 1, trace
            _flatten(trace["tree"][0], spans)
            root = trace["tree"][0]
            assert root["name"] == "client_op" and \
                root["service"] == "client"
            # primary: one osd_op child with queue + execute phases
            (osd_op,) = _find(root["children"], "osd_op")
            primary_svc = osd_op["service"]
            assert primary_svc.startswith("osd.")
            (queue,) = _find(osd_op["children"], "queue")
            (execute,) = _find(osd_op["children"], "execute")
            # execute decomposes into local store commit + repop wait
            (local_commit,) = _find(execute["children"],
                                    "objectstore_commit")
            assert local_commit["service"] == primary_svc
            (repop_wait,) = _find(execute["children"], "repop_wait")
            # >= 2 replica apply spans from DISTINCT non-primary osds,
            # each with its own objectstore commit
            applies = _find(repop_wait["children"], "repop_apply")
            svcs = {a["service"] for a in applies}
            assert len(applies) >= 2 and len(svcs) >= 2, applies
            assert primary_svc not in svcs
            for a in applies:
                assert _find(a["children"], "objectstore_commit"), a
            commits = _find(spans, "objectstore_commit")
            assert len(commits) >= 3        # primary + both replicas
            # phase durations: non-overlapping children sum to ~= the
            # parent, and the primary's phases fit inside the
            # client-observed latency
            assert queue["duration"] + execute["duration"] <= \
                osd_op["duration"] + 0.010
            assert osd_op["duration"] <= root["duration"] + 0.005
            assert root["duration"] <= observed + 0.005
            phase_sum = queue["duration"] + execute["duration"]
            assert observed - phase_sum < 1.0, (
                "client latency unaccounted for: "
                f"{observed} vs phases {phase_sum}")
            for a in applies:
                assert a["duration"] <= repop_wait["duration"] + 0.010
            assert trace["phases"]["objectstore_commit"] >= 0.0

            # -- `ceph trace ls/show` (the mon-side CLI view) ---------
            ret, _, out = await c.client.mon_command(
                {"prefix": "trace ls"})
            assert ret == 0
            rows = json.loads(out)["traces"]
            durs = [r["duration"] for r in rows]
            assert durs == sorted(durs, reverse=True)
            tid = rows[0]["trace_id"]
            ret, _, out = await c.client.mon_command(
                {"prefix": "trace show", "trace_id": tid})
            assert ret == 0 and json.loads(out)["trace_id"] == tid
            ret, rs, _ = await c.client.mon_command(
                {"prefix": "trace show", "trace_id": 424242})
            assert ret == -2, rs

            # -- asok surfaces: dump_tracing + perf histogram dump ----
            from ceph_tpu.utils.admin_socket import daemon_command
            dump = await daemon_command(
                f"{tmp_path}/osd.{c.osds[0].whoami}.asok",
                "dump_tracing")
            assert dump["sampling_rate"] == 1.0
            assert dump["buffered"] >= 1 or dump["pending_ship"] >= 0
            hist = await daemon_command(
                f"{tmp_path}/osd.{c.osds[0].whoami}.asok",
                "perf histogram dump")
            assert any(
                counters.get("op_w_latency_hist", {}).get("count", 0)
                > 0 and counters["op_w_latency_hist"]["buckets"]
                for name, counters in hist.items()
                if name.startswith("osd.")), hist

            # -- live prometheus render carries the histogram series --
            from ceph_tpu.mgr.modules import PrometheusModule
            prom = PrometheusModule(c.mgr)
            text = await prom.render()
            assert "ceph_perf_hist_bucket{" in text
            assert 'counter="op_w_latency_hist"' in text
        finally:
            await c.stop()
    run(go())


# -- tail path: a delayed op below the sampling rate is still kept ---------

def test_slow_op_retained_below_sampling_rate():
    async def go():
        c = await Cluster(
            n_mons=1, n_osds=3,
            config={"trace_sampling_rate": 0.0,
                    "trace_slow_keep_s": 0.2}).start()
        try:
            await c.client.pool_create("t", pg_num=8, size=3,
                                       min_size=2)
            await c.wait_for_clean(timeout=120)
            io = await c.client.open_ioctx("t")
            await io.write_full("fast-obj", b"y")     # under threshold
            inj = F.FaultInjector(seed=5)
            c.install_faults(inj)
            inj.install("lag",
                        [F.delay("client.*", "osd.*", 0.35)])
            t0 = time.monotonic()
            await io.write_full("slow-obj", b"z" * 128)
            assert time.monotonic() - t0 >= 0.2
            inj.clear("lag")
            lead = c.leader()
            deadline = asyncio.get_event_loop().time() + 10
            tail = []
            while not tail:
                tail = [s for _, s in lead.trace_spans
                        if s.get("tags", {}).get("tail_sampled")]
                if not tail:
                    assert asyncio.get_event_loop().time() < \
                        deadline, list(lead.trace_spans)
                    await asyncio.sleep(0.1)
            assert tail[0]["name"] == "client_op"
            assert tail[0]["duration"] >= 0.2
            assert tail[0]["tags"].get("slow")
        finally:
            await c.stop()
    run(go())


# -- metadata path: client -> MDS spans reassemble -------------------------

def test_metadata_op_trace_reassembly():
    async def go():
        c = await Cluster(
            n_mons=1, n_osds=3,
            config={"trace_sampling_rate": 1.0}).start()
        try:
            await c.start_fs(pool="cephfs", n_mds=1, timeout=120)
            from ceph_tpu.cephfs.client import CephFSClient
            # config threads through to the owned objecter's tracer —
            # without it the cluster's sampling knob never reaches
            # this client and no metadata root is ever created
            cl = await CephFSClient.create(
                c.client.monc.monmap, None, "cephfs",
                keyring=c.keyring, config=c.cfg)
            await cl.mkdir("/traced")
            await cl.unmount()
            lead = c.leader()
            deadline = asyncio.get_event_loop().time() + 15
            found = None
            while found is None:
                for row in lead.trace_index.ls(limit=20):
                    if row["root"] == "mds_req" and any(
                            s.startswith("mds.")
                            for s in row["services"]):
                        found = lead.trace_index.show(
                            row["trace_id"])
                        break
                if found is None:
                    assert asyncio.get_event_loop().time() < \
                        deadline, lead.trace_index.ls(limit=20)
                    await asyncio.sleep(0.1)
            root = found["tree"][0]
            assert root["name"] == "mds_req" and \
                root["service"] == "client"
            (mds_op,) = [n for n in root["children"]
                         if n["name"] == "mds_op"]
            assert mds_op["service"].startswith("mds.")
            assert mds_op["tags"]["op"] in ("mkdir",)
            assert mds_op["duration"] <= root["duration"] + 0.010
        finally:
            await c.stop()
    run(go())


# -- storm smoke: tracing survives kill/revive -----------------------------

def test_tracing_survives_thrash_smoke():
    from ceph_tpu.sim.thrasher import Thrasher

    async def go():
        c = await Cluster(
            n_mons=1, n_osds=4,
            config={"trace_sampling_rate": 1.0,
                    "mon_osd_down_out_interval": 600.0}).start()
        try:
            await c.client.pool_create("t", pg_num=8, size=3,
                                       min_size=2)
            await c.wait_for_clean(timeout=120)
            io = await c.client.open_ioctx("t")
            th = Thrasher(c, seed=77, min_live_osds=3)
            await th.thrash(io, steps=12)
            summary = await th.settle_and_verify(io, timeout=300)
            assert summary["acked_writes"] > 0
            # spans flowed through the storm and the pool survived the
            # kill/revive churn: slowest-first listing still serves
            lead = c.leader()
            assert lead is not None and len(lead.trace_spans) > 0
            ret, _, out = await c.client.mon_command(
                {"prefix": "trace ls", "limit": 5})
            assert ret == 0
            rows = json.loads(out)["traces"]
            assert rows, "no reassembled traces after the storm"
            durs = [r["duration"] for r in rows]
            assert durs == sorted(durs, reverse=True)
        finally:
            await c.stop()
    run(go())


# -- OpTracker monotonic satellite ----------------------------------------

def test_op_tracker_monotonic_and_config_knobs():
    from ceph_tpu.utils.config import Config
    from ceph_tpu.utils.op_tracker import OpTracker

    cfg = Config()
    assert cfg.get("osd_op_history_size") == 20
    assert cfg.get("osd_op_complaint_time") == 30.0
    t = OpTracker()
    assert t.history.maxlen == 20 and t.slow_op_warn_s == 30.0
    op = t.create("probe")
    # the age base is monotonic, not wall: a wall-clock jump cannot
    # corrupt it (initiated_at stays wall for display)
    assert abs(op.initiated_at - time.time()) < 5.0
    assert op.start <= time.monotonic()
    op.mark_event("phase")
    op.finish()
    d = op.dump()
    assert d["age"] >= 0 and d["events"][0]["time"] == 0.0
    assert all(e["time"] >= 0 for e in d["events"])
    t2 = OpTracker(history_size=3, slow_op_warn_s=0.0)
    for i in range(5):
        t2.create(f"op{i}").finish()
    assert len(t2.history) == 3
