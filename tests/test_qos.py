"""The million-client front end: op QoS scheduler, load harness,
per-op caps, and the admission-path plumbing around them.

ref test model: the dmClock simulator's tag-algebra properties +
qa/standalone QoS checks. Layout:

- **units** — the scheduler's dmClock algebra under a virtual clock
  (weight split, reservation floor, limit ceiling, fifo fallback,
  per-tenant backlog), wire-compat pins (pool v3 blob, pre-append
  MPGStats/MAuthUpdate blobs), objectstore phase recording;
- **cluster** — the two-tenant acceptance (hot tenant at ~10x offered
  load: FIFO demonstrably buries the cold tenant, the scheduler holds
  its p99 near solo and its throughput at reservation), recovery
  non-starvation under client load, the per-op cap matrix (-EPERM at
  admission), the stop-time throttle-leak regression, the mon paxos
  span family, and the load-harness smoke (<= 200 sessions tier-1;
  the 10k run is `slow`).
"""

import asyncio
import time

import pytest

from ceph_tpu.cluster.vstart import Cluster
from ceph_tpu.osd.scheduler import OpScheduler, QoSProfile
from ceph_tpu.rados import ObjectOperationError
from ceph_tpu.sim import faults as F
from ceph_tpu.sim.loadgen import LoadGen
from ceph_tpu.sim.thrasher import Thrasher


def run(coro):
    asyncio.run(coro)


# -- scheduler units (virtual clock — fully deterministic) -----------------

def _vclock_sched(**cfg):
    clock = [0.0]
    sched = OpScheduler(dict({"osd_op_queue": "mclock"}, **cfg),
                        now_fn=lambda: clock[0])
    return clock, sched


def test_scheduler_weight_split():
    """Weights split surplus capacity proportionally: 3:1 over a
    backlog dequeues exactly 3:1."""
    clock, s = _vclock_sched()
    for i in range(40):
        s.submit(("hot", i), key=("client", "hot", 1),
                 profile=QoSProfile(weight=3.0))
        s.submit(("cold", i), key=("client", "cold", 1),
                 profile=QoSProfile(weight=1.0))
    clock[0] = 100.0
    got = {"hot": 0, "cold": 0}
    for _ in range(40):
        item, _cls = s.try_dequeue()
        got[item[0]] += 1
    assert got == {"hot": 30, "cold": 10}


def test_scheduler_cost_scaled_weight_split():
    """Round 13 (ROADMAP #3a): ops charge size-scaled cost, so equal
    WEIGHTS split BYTES, not op counts — a 4 MiB writer (cost 64 at
    the 64 KiB divisor) gets one grant per 64 of a 4 KiB writer's,
    and both move the same payload through the window."""
    clock, s = _vclock_sched()
    for i in range(4):
        s.submit(("big", i), key=("client", "big", 1),
                 profile=QoSProfile(weight=1.0), cost=64.0)
    for i in range(256):
        s.submit(("small", i), key=("client", "small", 1),
                 profile=QoSProfile(weight=1.0), cost=1.0)
    clock[0] = 1000.0
    got = {"big": 0, "small": 0}
    for _ in range(130):
        item, _cls = s.try_dequeue()
        got[item[0]] += 1
    # p-tags: big at 64,128,... / small at 1,2,3,... -> 130 grants
    # serve small through p=128 and big through p=128: 64x the ops,
    # equal bytes (2 * 4 MiB == 128 * 64 KiB)
    assert got == {"big": 2, "small": 128}


def test_osd_op_cost_is_size_scaled():
    """The admission path's cost stamp: max(1, bytes/divisor) over
    the op bundle, divisor read LIVE from osd_qos_cost_per_io_bytes.
    Writes charge their payload blobs; reads charge their requested
    op_lens (empty blobs) — a 4 MiB reader must not ride at the
    flat minimum."""
    from types import SimpleNamespace

    from ceph_tpu.osd.daemon import OSD

    def m(datas, lens=None):
        return SimpleNamespace(
            op_datas=datas,
            op_lens=lens if lens is not None
            else [len(d) for d in datas])
    cost = OSD._op_cost
    host = SimpleNamespace(config={})
    assert cost(host, m([])) == 1.0
    assert cost(host, m([b"x" * 100])) == 1.0
    assert cost(host, m([b"x" * (4 << 20)])) == 64.0
    assert cost(host, m([b"x" * (1 << 16), b"y" * (1 << 16)])) == 2.0
    # a read: empty data blob, size in op_lens
    assert cost(host, m([b""], lens=[4 << 20])) == 64.0
    # whole-object read (length 0): size unknowable at admission
    assert cost(host, m([b""], lens=[0])) == 1.0
    host.config = {"osd_qos_cost_per_io_bytes": 1 << 20}
    assert cost(host, m([b"x" * (4 << 20)])) == 4.0


def test_scheduler_reservation_floor_under_flood():
    """A reserved tenant gets >= its reservation IOPS even when a
    floodier tenant has thousands queued — the hard floor the
    two-tenant acceptance depends on."""
    clock, s = _vclock_sched()
    for i in range(2000):
        s.submit(("hot", i), key=("client", "hot", 1),
                 profile=QoSProfile(weight=1.0))
    for i in range(20):
        s.submit(("cold", i), key=("client", "cold", 1),
                 profile=QoSProfile(reservation=10.0, weight=1.0))
    got = {"hot": 0, "cold": 0}
    # serve 50 grants spread over one simulated second
    for g in range(50):
        clock[0] = g / 50.0
        item, _cls = s.try_dequeue()
        got[item[0]] += 1
    assert got["cold"] >= 10        # the reservation floor held


def test_scheduler_limit_is_hard_ceiling():
    """limit IOPS caps a queue even with the cluster otherwise idle:
    nothing else queued, yet only ~limit grants land per second."""
    clock, s = _vclock_sched()
    for i in range(100):
        s.submit(("l", i), key=("client", "l", 1),
                 profile=QoSProfile(weight=1.0, limit=10.0))
    served = 0
    t = 0.0
    while t <= 1.0:
        clock[0] = t
        item, wake = s.try_dequeue()
        if item is not None:
            served += 1
            continue
        assert wake is not None     # limit-deferred, not empty
        t = wake
    assert served <= 11


def test_scheduler_limit_caps_reservation_too():
    """limit is a hard ceiling over BOTH phases: a (mis)configured
    profile with reservation > limit is served at the LIMIT rate —
    the reservation phase honors max(R, L) eligibility."""
    clock, s = _vclock_sched()
    for i in range(50):
        s.submit(("x", i), key=("client", "x", 1),
                 profile=QoSProfile(reservation=20.0, weight=1.0,
                                    limit=2.0))
    served = 0
    t = 0.0
    while t <= 1.0:
        clock[0] = t
        item, wake = s.try_dequeue()
        if item is not None:
            served += 1
            continue
        assert wake is not None
        t = wake
    assert served <= 3, f"limit 2/s ceiling broken: {served} served"


def test_scheduler_fifo_mode_and_live_flip():
    """osd_op_queue=fifo is strict arrival order; a LIVE flip to fifo
    drains already-stamped queues without losing ops."""
    cfg = {"osd_op_queue": "fifo"}
    clock = [0.0]
    s = OpScheduler(cfg, now_fn=lambda: clock[0])
    for i in range(6):
        s.submit(i, key=("client", f"c{i % 2}", 1))
    assert [s.try_dequeue()[0] for _ in range(6)] == list(range(6))
    # flip to mclock, stamp, flip back mid-backlog
    cfg["osd_op_queue"] = "mclock"
    for i in range(4):
        s.submit(("m", i), key=("client", "x", 1),
                 profile=QoSProfile(weight=1.0))
    cfg["osd_op_queue"] = "fifo"
    drained = [s.try_dequeue()[0] for _ in range(2)]
    # flip BACK to mclock mid-backlog: the two remaining tagged ops
    # must stay reachable (fifo-mode drain keeps heap entries fresh)
    cfg["osd_op_queue"] = "mclock"
    clock[0] = 100.0
    drained += [s.try_dequeue()[0] for _ in range(2)]
    assert sorted(drained) == [("m", i) for i in range(4)]
    # and ops stamped IN fifo mode are served first after a flip to
    # mclock (the un-tagged backlog must not strand)
    cfg["osd_op_queue"] = "fifo"
    s.submit("fifo-stamped")
    cfg["osd_op_queue"] = "mclock"
    s.submit(("m", 9), key=("client", "x", 1),
             profile=QoSProfile(weight=1.0))
    assert s.try_dequeue()[0] == "fifo-stamped"
    assert s.try_dequeue()[0] == ("m", 9)
    assert s.try_dequeue() == (None, None)
    assert s.queued == 0


def test_scheduler_backlog_per_tenant():
    """backlog() is per-queue in mclock mode (a hot tenant's pile-up
    must not back off the cold tenant) and global in fifo mode."""
    cfg = {"osd_op_queue": "mclock"}
    clock = [0.0]
    s = OpScheduler(cfg, now_fn=lambda: clock[0])
    for i in range(7):
        s.submit(("hot", i), key=("client", "hot", 1))
    s.submit(("cold", 0), key=("client", "cold", 1))
    assert s.backlog(("client", "hot", 1)) == 7
    assert s.backlog(("client", "cold", 1)) == 1
    assert s.backlog(("client", "absent", 1)) == 0
    cfg["osd_op_queue"] = "fifo"
    s.submit("f1")
    assert s.backlog(("client", "hot", 1)) == 1   # global fifo depth


def test_scheduler_grant_cancelled_on_drain():
    """drain() cancels pending recovery/scrub grant futures and
    reports the dropped count (the stop path must not wedge a
    recovery task on a dead scheduler)."""
    async def go():
        s = OpScheduler({"osd_op_queue": "mclock"})
        task = asyncio.ensure_future(s.grant("recovery"))
        await asyncio.sleep(0.01)
        assert s.queued == 1
        assert s.drain() == 1
        with pytest.raises(asyncio.CancelledError):
            await task
    run(go())


# -- wire-compat pins ------------------------------------------------------

def test_pool_v3_blob_decodes_with_default_qos():
    """A pool struct encoded at v3 (pre-QoS) decodes with qos_* at
    their defaults — the zero-fill append discipline for the v4
    fields."""
    from ceph_tpu.encoding.denc import Decoder, Encoder
    from ceph_tpu.encoding.maps import _dec_pool, _enc_pool
    from ceph_tpu.osd.str_hash import CEPH_STR_HASH_RJENKINS
    from ceph_tpu.osd.types import PGPool
    e = Encoder()
    with e.start(3):                     # the exact v3 layout
        e.s64(5).u32(8).u32(8).u8(1)
        e.u32(3).u32(2).s32(0).u64(4)
        e.u8(CEPH_STR_HASH_RJENKINS).string("").string("p")
        e.bool(False)
        e.string("")
        e.u64(7).u64(9)                  # v2 quotas
        e.u32(4)                         # v3 pg_num_pending
    p = _dec_pool(Decoder(e.tobytes()))
    assert (p.id, p.pg_num, p.name) == (5, 8, "p")
    assert (p.quota_bytes, p.quota_objects, p.pg_num_pending) == \
        (7, 9, 4)
    assert (p.qos_reservation, p.qos_weight, p.qos_limit) == \
        (0.0, 0.0, 0.0)
    # and a v4 round-trip carries the qos fields
    p.qos_reservation, p.qos_weight, p.qos_limit = 20.0, 4.0, 100.0
    e2 = Encoder()
    _enc_pool(e2, p)
    p2 = _dec_pool(Decoder(e2.tobytes()))
    assert isinstance(p2, PGPool)
    assert (p2.qos_reservation, p2.qos_weight, p2.qos_limit) == \
        (20.0, 4.0, 100.0)


def test_pre_append_blobs_decode_with_empty_fields():
    """MPGStats (peer_latency) and MAuthUpdate (caps) blobs encoded
    BEFORE the round-11 append — reconstructed by stripping the empty
    appended container in front of the trace context — decode with
    the new field empty."""
    from ceph_tpu.mon.messages import MAuthUpdate, MPGStats
    from ceph_tpu.msg.message import Message
    m = MPGStats(osd=1, epoch=2, stats={"1.0": b"x"}, slow_ops=3,
                 used_bytes=4, capacity_bytes=5, trace_spans=[b"s"],
                 peer_latency={})
    blob = m.encode()
    assert blob[-16:] == b"\x00" * 16
    old = blob[:-20] + blob[-16:]        # drop the empty-map u32
    m2 = Message.decode(old)
    assert m2.peer_latency == {} and m2.slow_ops == 3
    assert m2.stats == {"1.0": b"x"}
    a = MAuthUpdate(version=9, keys={"client.x": b"k"}, caps={})
    old_a = a.encode()[:-20] + a.encode()[-16:]
    a2 = Message.decode(old_a)
    assert a2.caps == {} and a2.keys == {"client.x": b"k"}
    # and the new fields round-trip when populated
    m.peer_latency = {"3": 1200}
    assert Message.decode(m.encode()).peer_latency == {"3": 1200}


def test_osdmap_client_profiles_roundtrip():
    """client_profiles ride the full map and the incremental; a v5
    (pre-profile) blob decodes with an empty table via the version
    gate."""
    from ceph_tpu.bench import osdmaptool
    from ceph_tpu.encoding import (decode_incremental, decode_osdmap,
                                   encode_incremental, encode_osdmap)
    from ceph_tpu.osd.osdmap import Incremental
    m = osdmaptool.create_simple(4, 8, 2, erasure=False)
    inc = Incremental(epoch=m.epoch + 1)
    inc.new_client_profiles["client.cold"] = (20.0, 4.0, 0.0)
    inc2 = decode_incremental(encode_incremental(inc))
    assert inc2.new_client_profiles == \
        {"client.cold": (20.0, 4.0, 0.0)}
    m.apply_incremental(inc2)
    m2 = decode_osdmap(encode_osdmap(m))
    assert m2.client_profiles == {"client.cold": (20.0, 4.0, 0.0)}
    inc3 = Incremental(epoch=m.epoch + 1)
    inc3.old_client_profiles.append("client.cold")
    m.apply_incremental(decode_incremental(encode_incremental(inc3)))
    assert m.client_profiles == {}


def test_walstore_records_txn_phases(tmp_path):
    """WALStore reports the apply/wal-kv phase walls of the LAST
    transaction, and Span.annotate turns them into finished children
    — the objectstore kv/WAL sub-span split."""
    from ceph_tpu.os_.objectstore import Transaction, WALStore
    from ceph_tpu.utils.tracing import Span, Tracer
    st = WALStore(str(tmp_path / "w"))
    t = Transaction()
    t.create_collection("1.0")
    t.write("1.0", "o", 0, b"x" * 128)
    st.queue_transaction(t)
    phases = st.last_txn_phases
    assert set(phases) == {"apply", "wal_kv_commit"}
    assert all(dt >= 0 for dt in phases.values())
    tracer = Tracer("osd.0", {"trace_sampling_rate": 1.0})
    root = tracer.start_root("objectstore_commit")
    for ph, dt in phases.items():
        root.annotate(ph, dt)
    root.finish()
    names = {s["name"] for s in tracer.dump()["spans"]}
    assert {"apply", "wal_kv_commit",
            "objectstore_commit"} <= names


# -- cluster: the two-tenant acceptance + recovery non-starvation ----------

def test_two_tenant_qos_and_recovery_floor():
    """The round-11 acceptance: with the hot tenant at ~10x offered
    load behind a small dispatch cap,

    - FIFO admission demonstrably violates the cold tenant (p99 blown
      past 2x its solo baseline);
    - the scheduler holds the cold tenant's p99 within 2x of solo
      (generous absolute floor for CI noise) and its throughput at or
      above its reservation;
    - recovery under the same client load still converges (its
      reservation means the hot tenant cannot starve it): kill an
      OSD, write past its outage, revive — the cluster goes clean
      while the flood continues.
    """
    async def go():
        import json as _json

        from ceph_tpu.msg import Keyring as _Keyring
        from ceph_tpu.rados import Rados as _Rados
        c = await Cluster(n_mons=1, n_osds=3, config={
            "osd_client_message_cap": 4,
            "osd_op_queue": "mclock",
            "mon_osd_down_out_interval": 600.0}).start()
        try:
            await c.client.pool_create("qos", pg_num=8)
            await c.wait_for_clean(timeout=120)
            ret, rs, out = await c.client.mon_command(
                {"prefix": "auth get-or-create",
                 "entity": "client.cold"})
            assert ret == 0, rs
            key = bytes.fromhex(_json.loads(out)["key"])
            cold = _Rados(c.monmap, name="client.cold",
                          keyring=_Keyring({"client.cold": key}),
                          config=c.cfg)
            await cold.connect()
            io_cold = await cold.open_ioctx("qos")
            io_hot = await c.client.open_ioctx("qos")
            # cold gets a reservation + weight through the committed
            # client-profile table
            ret, rs, _ = await c.client.mon_command(
                {"prefix": "osd client-profile", "op": "set",
                 "entity": "client.cold", "reservation": 20.0,
                 "weight": 4.0, "limit": 0.0})
            assert ret == 0, rs
            ret, _, out = await c.client.mon_command(
                {"prefix": "osd client-profile", "op": "ls"})
            assert ret == 0
            assert "client.cold" in _json.loads(out)["profiles"]
            # settle + warm the write path: the profile commit bumps
            # the osdmap epoch (brief re-advance) and the first ops
            # pay connection setup — neither belongs in the baseline
            await c.wait_for_clean(timeout=60)
            for i in range(6):
                await io_cold.write_full(f"warm-c-{i}", b"w" * 256,
                                         timeout=30.0)
                await io_hot.write_full(f"warm-h-{i}", b"w" * 256,
                                        timeout=30.0)
            th = Thrasher(c, seed=11)
            solo = await th.qos_storm(io_cold, io_hot, writes=24,
                                      hot_parallel=0)
            assert solo["cold_errors"] == 0
            c.cfg["osd_op_queue"] = "fifo"
            fifo = await th.qos_storm(io_cold, io_hot, writes=24,
                                      hot_parallel=4, hot_burst=16)
            c.cfg["osd_op_queue"] = "mclock"
            mclock = await th.qos_storm(io_cold, io_hot, writes=24,
                                        hot_parallel=4, hot_burst=16)
            # assertions compare p95: at 24 samples p99 IS the max,
            # which a single GC/event-loop blip owns (observed ~100 ms
            # outliers in BOTH directions) — structural queueing delay
            # is what FIFO-vs-scheduler changes, and it shows at p95
            # (measured: FIFO median ~80 ms under this flood, mclock
            # median ~25 ms)
            floor = max(2.0 * solo["cold_p99_s"], 0.08)
            assert fifo["cold_p95_s"] > floor, (
                f"FIFO baseline failed to violate: fifo p95 "
                f"{fifo['cold_p95_s']:.3f}s vs solo "
                f"{solo['cold_p99_s']:.3f}s")
            assert mclock["cold_p95_s"] <= floor, (
                f"scheduler failed to protect: mclock p95 "
                f"{mclock['cold_p95_s']:.3f}s vs solo "
                f"{solo['cold_p99_s']:.3f}s (floor {floor:.3f}s)")
            assert mclock["cold_errors"] == 0
            # throughput at/above reservation (20 IOPS reserved, cold
            # offers ~1/think_s=50; CI margin 0.6)
            assert mclock["cold_ops_per_s"] >= 20.0 * 0.6, mclock
            # -- recovery floor under the same flood ------------------
            stop = asyncio.Event()

            async def flood(w):
                i = 0
                while not stop.is_set():
                    try:
                        await io_hot.write_full(
                            f"rf-{w}-{i % 32}", b"h" * 512,
                            timeout=30.0)
                    except Exception:
                        pass
                    i += 1
            flood_tasks = [asyncio.ensure_future(flood(w))
                           for w in range(3)]
            try:
                await c.kill_osd(0)
                await c.wait_for_osd_down(0, timeout=60)
                for i in range(12):
                    await io_cold.write_full(f"rec-{i}", b"c" * 256,
                                             timeout=30.0)
                await c.revive_osd(0)
                # recovery must converge WHILE the flood continues:
                # its scheduler reservation keeps pushes flowing
                await c.wait_for_clean(timeout=120)
            finally:
                stop.set()
                for t in flood_tasks:
                    t.cancel()
                await asyncio.gather(*flood_tasks,
                                     return_exceptions=True)
            for i in range(12):
                assert await io_cold.read(f"rec-{i}") == b"c" * 256
            await cold.shutdown()
        finally:
            await c.stop()
    run(go())


def test_per_op_cap_matrix_paxos_spans_and_stop_leak():
    """One cluster, three pins: per-op OSD cap enforcement at
    admission (-EPERM matrix), the mon's own paxos span family
    (propose -> accept-wait/commit) reassembling in the leader's
    trace index, and — last, because it stops the OSDs — the
    throttle-leak-on-stop regression (tier-1 is near its wall-clock
    cap; these share one cluster spin by design)."""
    async def go():
        import json as _json

        from ceph_tpu.msg import Keyring as _Keyring
        from ceph_tpu.rados import Rados as _Rados
        c = await Cluster(n_mons=1, n_osds=3, config={
            "trace_sampling_rate": 1.0,
            "osd_client_message_cap": 2}).start()
        try:
            await c.client.pool_create("caps", pg_num=8)
            await c.wait_for_clean(timeout=120)

            async def provision(entity, caps):
                ret, rs, out = await c.client.mon_command(
                    {"prefix": "auth get-or-create",
                     "entity": entity, "caps": caps})
                assert ret == 0, rs
                key = bytes.fromhex(_json.loads(out)["key"])
                r = _Rados(c.monmap, name=entity,
                           keyring=_Keyring({entity: key}),
                           config=c.cfg)
                await r.connect()
                return r, await r.open_ioctx("caps")
            ro, io_ro = await provision(
                "client.ro", {"osd": "allow r"})
            rw, io_rw = await provision(
                "client.rw", {"osd": "allow rw"})
            io_admin = await c.client.open_ioctx("caps")
            # seed an object via the capless admin (unrestricted)
            await io_admin.write_full("obj", b"seed")
            # matrix: (io, can_read, can_write)
            with pytest.raises(ObjectOperationError) as ei:
                await io_ro.write_full("obj", b"denied", timeout=8.0)
            assert ei.value.errno == -1          # -EPERM at admission
            assert await io_ro.read("obj") == b"seed"
            await io_rw.write_full("obj", b"rw-ok", timeout=8.0)
            assert await io_rw.read("obj") == b"rw-ok"
            await io_admin.write_full("obj", b"capless-ok")
            assert await io_admin.read("obj") == b"capless-ok"
            # -- pool-level qos rides the pool struct (v4) ------------
            ret, rs, _ = await c.client.mon_command(
                {"prefix": "osd pool set", "pool": "caps",
                 "var": "qos_reservation", "val": "15"})
            assert ret == 0, rs
            ret, _, out = await c.client.mon_command(
                {"prefix": "osd dump"})
            pool = next(p for p in _json.loads(out)["pools"]
                        if p["name"] == "caps")
            assert pool["qos_reservation"] == 15.0
            # the OSD's profile resolution sees it (no per-entity
            # profile for client.rw -> pool override wins)
            deadline = asyncio.get_event_loop().time() + 10.0
            while True:
                osd = next(o for o in c.osds if not o._stopped)
                pool_obj = osd.osdmap.pools[pool["pool"]]
                if pool_obj.qos_reservation == 15.0:
                    break
                assert asyncio.get_event_loop().time() < deadline
                await asyncio.sleep(0.05)
            prof = osd._client_profile("client.rw", pool_obj)
            assert prof.reservation == 15.0
            # -- paxos span family ------------------------------------
            lead = c.leader()
            deadline = asyncio.get_event_loop().time() + 10.0
            found = None
            while found is None:
                for tid, ent in lead.trace_index.traces.items():
                    names = {s["name"]
                             for s in ent["spans"].values()}
                    if "paxos_propose" in names:
                        found = (tid, names)
                        break
                if found or \
                        asyncio.get_event_loop().time() > deadline:
                    break
                await asyncio.sleep(0.1)
            assert found, "no paxos_propose trace reached the pool"
            tid, names = found
            assert "paxos_commit" in names, names
            show = lead.trace_index.show(tid)
            assert show["phases"].get("paxos_propose", 0) > 0
            await ro.shutdown()
            await rw.shutdown()
            # -- throttle-leak-on-stop regression (the Thrasher-
            # exposed leak: killing an OSD mid-admission must release
            # every queued op's MessageThrottle tokens — queued costs
            # were only drained on primaryship loss, never on stop).
            # Runs LAST: it stops the cluster's OSDs.
            writers = [asyncio.ensure_future(
                io_admin.write_full(f"o-{i}", b"x" * 2048,
                                    timeout=3.0))
                for i in range(12)]
            await asyncio.sleep(0.25)      # ops queued mid-admission
            for osd in list(c.osds):
                await osd.stop()
                assert osd.client_throttle.ops == 0, \
                    f"osd.{osd.whoami} leaked throttle ops"
                assert osd.client_throttle.bytes == 0, \
                    f"osd.{osd.whoami} leaked throttle bytes"
                assert osd.scheduler.queued == 0
            for w in writers:
                w.cancel()
            await asyncio.gather(*writers, return_exceptions=True)
        finally:
            await c.stop()
    run(go())


def test_mds_per_op_cap_matrix():
    """Round 13 (ROADMAP #3b): the MDS leg of per-op cap enforcement.
    An ``mds r``-only entity's mutation is refused -EPERM at the MDS
    request gate (before the dedup table or the journal see it);
    reads still serve; an ``mds rw`` entity and a capless legacy
    entity stay unrestricted — the same admission matrix the OSD
    pins above."""
    async def go():
        from ceph_tpu.cephfs import FSError
        from ceph_tpu.cephfs.client import CephFSClient
        from ceph_tpu.cephfs.mds import MDSDaemon
        c = await Cluster(n_mons=1, n_osds=3).start()
        mounts = []
        mds = None
        try:
            await c.client.pool_create("fs", pg_num=8)
            await c.wait_for_clean(timeout=120)
            io = await c.client.open_ioctx("fs")
            for _ in range(30):
                try:
                    await io.write_full("_warm", b"x")
                    break
                except ObjectOperationError:
                    await asyncio.sleep(1)
            for entity, mdscap in (("client.fsro", "allow r"),
                                   ("client.fsrw", "allow rw")):
                ret, rs, _ = await c.client.mon_command(
                    {"prefix": "auth get-or-create",
                     "entity": entity,
                     "caps": {"mds": mdscap, "osd": "allow rw",
                              "mon": "allow r"}})
                assert ret == 0, rs
            # committed caps reach every shared-keyring holder via
            # the MAuthUpdate push; the MDS reads the same table
            deadline = asyncio.get_event_loop().time() + 10.0
            while c.keyring.caps_of("client.fsro").get("mds") != \
                    "allow r":
                assert asyncio.get_event_loop().time() < deadline
                await asyncio.sleep(0.05)
            mds = MDSDaemon(io, keyring=c.keyring)
            await mds.fs.mount()
            addr = await mds.start()
            monmap = c.client.monc.monmap
            ro = await CephFSClient.create(
                monmap, addr, "fs", keyring=c.keyring,
                name="client.fsro", config=c.cfg)
            rw = await CephFSClient.create(
                monmap, addr, "fs", keyring=c.keyring,
                name="client.fsrw", config=c.cfg)
            legacy = await CephFSClient.create(
                monmap, addr, "fs", keyring=c.keyring,
                config=c.cfg)       # fresh capless entity
            mounts += [ro, rw, legacy]
            # matrix: (entity, mutation allowed)
            with pytest.raises(FSError) as ei:
                await ro.mkdir("/denied")
            assert ei.value.errno == -1       # -EPERM at the gate
            # ...and the refusal never reached the journal or the
            # dedup table (a replay must re-refuse, not re-execute)
            assert not mds._completed.get("client.fsro")
            await rw.mkdir("/ok")
            await legacy.mkdir("/legacy-ok")
            # reads stay open to the r-only entity
            names = set(await ro.ls("/"))
            assert {"ok", "legacy-ok"} <= names
            # the write CLASS is what's gated, not the entity: rw's
            # unlink passes the same gate
            await rw.rmdir("/ok")
            # replay-after-narrowing: a mutation that ALREADY applied
            # keeps answering its recorded result even if the
            # entity's caps narrow afterwards — the dedup table
            # outranks the cap gate (at-most-once is about what
            # happened, not what would be admitted today)
            done = dict(mds._completed.get("client.fsrw") or {})
            assert done
            tid, recorded = next(iter(done.items()))
            c.keyring.set_caps("client.fsrw", {"mds": "allow r"})
            from ceph_tpu.cephfs.mds import MClientRequest
            replies = []

            class _Conn:
                async def send_message(self, msg):
                    replies.append(msg)
            req = MClientRequest(tid=tid, op="mkdir", path="/ok",
                                 path2="", flags=0)
            req.src = "client.fsrw"
            req.conn = _Conn()
            await mds._serve_request(req)
            assert replies and replies[0].result == recorded
            # ...while a NEW mutation from the narrowed entity is
            # refused at the gate
            with pytest.raises(FSError) as ei2:
                await rw.mkdir("/now-denied")
            assert ei2.value.errno == -1
        finally:
            for m in mounts:
                try:
                    await m.unmount()    # shuts msgr + own rados too
                except Exception:
                    pass
            if mds is not None:
                await mds.stop()
            await c.stop()
    run(go())


# -- gray failure: slow-OSD detection --------------------------------------

def test_slow_osd_detection_heals_and_dampens():
    """An injected-latency (delayed, NOT killed) OSD trips OSD_SLOW —
    visible in health, `ceph osd slow ls` and the status slow-score
    block — and clears after the fault heals; a clean settle first
    shows NO false positive (while the tier-1 loadgen smoke runs —
    200 closed-loop sessions, zero errors: real load must not read as
    gray failure, and the harness shares this cluster spin to stay
    inside the tier-1 budget). With primary dampening enabled, the
    slow OSD's primary affinity drops while slow and is restored on
    heal."""
    async def go():
        import json as _json
        c = await Cluster(n_mons=1, n_osds=4, config={
            "mon_osd_slow_min_ms": 20.0,
            "mon_osd_slow_ratio": 3.0,
            "mon_osd_slow_confirm": 2,
            "mon_osd_slow_primary_dampening": True,
            "mon_osd_down_out_interval": 600.0}).start()
        try:
            await c.client.pool_create("gray", pg_num=8)
            await c.wait_for_clean(timeout=120)
            # clean settle UNDER LOAD: the tier-1 loadgen smoke —
            # 200 sessions over 4 shared clients, zero errors — while
            # rtts flow; afterwards assert NO false positive
            report = await LoadGen(
                c, "gray", sessions=200, clients=4,
                ops_per_session=3, write_bytes=256,
                concurrency=64, op_timeout=60.0).run()
            assert report["errors"] == 0, report["error_samples"]
            assert report["ops"] == 600
            assert report["p99_ms"] >= report["p50_ms"] > 0
            assert report["ops_per_s"] > 0
            await asyncio.sleep(1.0)
            lead = c.leader()
            assert not lead.osdmon.slow_osds, \
                f"false positive: {lead.osdmon.slow_osds}"
            health = lead.healthmon.checks()["checks"]
            assert "OSD_SLOW" not in health
            # inject latency on osd.3's links (both directions, hb
            # included via install_faults) — slow, not dead: delays
            # stay far under the heartbeat grace
            inj = F.FaultInjector()
            c.install_faults(inj)
            inj.install("gray", [
                F.delay("osd.*", "osd.3", 0.05, 0.08),
                F.delay("osd.3", "osd.*", 0.05, 0.08)])
            deadline = asyncio.get_event_loop().time() + 30.0
            while True:
                lead = c.leader()
                if 3 in lead.osdmon.slow_osds:
                    break
                assert asyncio.get_event_loop().time() < deadline, \
                    (f"OSD_SLOW never tripped: scores "
                     f"{lead.osdmon.slow_scores()}")
                await asyncio.sleep(0.2)
            health = lead.healthmon.checks()["checks"]
            assert "OSD_SLOW" in health
            assert "osd.3" in health["OSD_SLOW"]["summary"]
            ret, _, out = await c.client.mon_command(
                {"prefix": "osd slow ls"})
            assert ret == 0
            dump = _json.loads(out)
            assert "3" in dump["slow_osds"]
            assert dump["slow_osds"]["3"]["score"] >= 3.0
            # status carries the score block (prometheus renders it)
            status = await c.client.status()
            assert "3" in status["osdmap"]["slow_osds"]
            # the osd stayed UP the whole time — gray, not dead
            assert status["osdmap"]["num_up_osds"] == 4
            # primary-avoidance hint: affinity dampened while slow
            deadline = asyncio.get_event_loop().time() + 10.0
            while int(lead.osdmon.osdmap.osd_primary_affinity[3]) \
                    != 0:
                assert asyncio.get_event_loop().time() < deadline, \
                    "primary affinity never dampened"
                await asyncio.sleep(0.1)
            # heal: clear the fault, wait for the score to decay
            inj.clear("gray")
            deadline = asyncio.get_event_loop().time() + 40.0
            while True:
                lead = c.leader()
                if 3 not in lead.osdmon.slow_osds:
                    break
                assert asyncio.get_event_loop().time() < deadline, \
                    (f"OSD_SLOW never cleared: "
                     f"{lead.osdmon.slow_scores()}")
                await asyncio.sleep(0.2)
            assert "OSD_SLOW" not in \
                lead.healthmon.checks()["checks"]
            deadline = asyncio.get_event_loop().time() + 10.0
            from ceph_tpu.osd.osdmap import DEFAULT_PRIMARY_AFFINITY
            while int(lead.osdmon.osdmap.osd_primary_affinity[3]) \
                    != DEFAULT_PRIMARY_AFFINITY:
                assert asyncio.get_event_loop().time() < deadline, \
                    "primary affinity never restored on heal"
                await asyncio.sleep(0.1)
        finally:
            await c.stop()
    run(go())


# -- the load harness (tier-1 smoke rides the slow-osd cluster above) ------

@pytest.mark.slow
def test_loadgen_10k_sessions():
    """The full-scale harness: 10k simulated sessions against vstart
    complete with zero errors (the acceptance's scale bar)."""
    async def go():
        c = await Cluster(n_mons=1, n_osds=3, config={
            "osd_client_message_cap": 1024}).start()
        try:
            await c.client.pool_create("load", pg_num=16)
            await c.wait_for_clean(timeout=240)
            t0 = time.perf_counter()
            report = await LoadGen(
                c, "load", sessions=10_000, clients=16,
                ops_per_session=2, write_bytes=128,
                concurrency=256, op_timeout=120.0).run()
            assert report["errors"] == 0, report["error_samples"]
            assert report["ops"] == 20_000
            assert report["sessions"] == 10_000
            print(f"10k-session loadgen: {report} "
                  f"({time.perf_counter() - t0:.1f}s wall)")
        finally:
            await c.stop()
    run(go())
