"""Tier-1 budget guards, enforced mechanically.

The tier-1 run (`pytest -m 'not slow'`, see ROADMAP.md) lives under a
hard wall-clock cap. Two conventions keep it there, and this module
turns both from convention into CI:

1. any test driving a Thrasher storm entry point (`thrash`,
   `backfill_storm`, `overload_storm`) must either carry the `slow`
   marker or pass small LITERAL budgets (a smoke variant) — a deep
   storm slipping into tier-1 blows the cap;
2. every pytest marker used under tests/ must be registered in
   pytest.ini — an unregistered marker (e.g. a typo'd `slowe`)
   silently runs the test in tier-1 instead of excluding it.
"""

import ast
import configparser
import pathlib

TESTS = pathlib.Path(__file__).parent
REPO = TESTS.parent

# storm entry point -> {kwarg: max literal value} a NON-slow (smoke)
# caller may pass; a bigger or non-literal budget requires `slow`
STORM_BUDGETS = {
    "thrash": {"steps": 20},
    "backfill_storm": {"writes": 60, "partitions": 2},
    "overload_storm": {"writers": 4, "prefill": 32, "hold_s": 1.0},
}
BUILTIN_MARKS = {
    "parametrize", "skip", "skipif", "xfail", "usefixtures",
    "filterwarnings",
}


def _mark_names(node) -> set[str]:
    """pytest.mark.<name> attribute chains reachable from ``node``."""
    out = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute) and \
                isinstance(n.value, ast.Attribute) and \
                n.value.attr == "mark" and \
                isinstance(n.value.value, ast.Name) and \
                n.value.value.id == "pytest":
            out.add(n.attr)
    return out


def _storm_calls(fn) -> list[tuple[str, dict]]:
    """(entry point, {kwarg: literal-or-None}) calls inside ``fn``
    (nested async helpers included — ast.walk descends)."""
    calls = []
    for n in ast.walk(fn):
        if isinstance(n, ast.Call) and \
                isinstance(n.func, ast.Attribute) and \
                n.func.attr in STORM_BUDGETS:
            kwargs = {}
            for kw in n.keywords:
                kwargs[kw.arg] = kw.value.value \
                    if isinstance(kw.value, ast.Constant) else None
            calls.append((n.func.attr, kwargs))
    return calls


def _iter_test_functions():
    for path in sorted(TESTS.glob("test_*.py")):
        tree = ast.parse(path.read_text(), filename=str(path))
        module_marks = set()
        for stmt in tree.body:
            if isinstance(stmt, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == "pytestmark"
                    for t in stmt.targets):
                module_marks |= _mark_names(stmt.value)
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)) and \
                    node.name.startswith("test_"):
                marks = set(module_marks)
                for dec in node.decorator_list:
                    marks |= _mark_names(dec)
                yield path, node, marks


def test_storm_tests_are_slow_or_bounded():
    """A storm entry point in a non-slow test must carry small literal
    budgets; anything bigger (or computed) needs @pytest.mark.slow."""
    violations = []
    for path, fn, marks in _iter_test_functions():
        if "slow" in marks:
            continue
        for entry, kwargs in _storm_calls(fn):
            limits = STORM_BUDGETS[entry]
            for arg, cap in limits.items():
                if arg not in kwargs:
                    continue                 # library default: bounded
                val = kwargs[arg]
                if val is None or val > cap:
                    violations.append(
                        f"{path.name}::{fn.name} calls {entry}("
                        f"{arg}={val if val is not None else '<expr>'}"
                        f") above the tier-1 smoke cap {cap} without "
                        f"@pytest.mark.slow")
    assert not violations, "\n".join(violations)


def test_all_markers_registered_in_pytest_ini():
    """Every pytest.mark.<name> used under tests/ must appear in
    pytest.ini's markers section (typos would silently run in
    tier-1)."""
    ini = configparser.ConfigParser()
    ini.read(REPO / "pytest.ini")
    registered = {
        line.strip().split(":", 1)[0].split("(", 1)[0]
        for line in ini["pytest"].get("markers", "").splitlines()
        if line.strip()}
    used = set()
    for path in sorted(TESTS.glob("test_*.py")):
        used |= _mark_names(ast.parse(path.read_text()))
    unregistered = used - registered - BUILTIN_MARKS
    assert not unregistered, (
        f"markers {sorted(unregistered)} used under tests/ but not "
        f"registered in pytest.ini")
