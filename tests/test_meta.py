"""Repo-convention guards, enforced mechanically.

The tier-1 run (`pytest -m 'not slow'`, see ROADMAP.md) lives under a
hard wall-clock cap, and the wire format lives under an
encoding-stability contract. Three conventions keep them, and this
module turns each from convention into CI:

1. any test driving a Thrasher storm entry point (`thrash`,
   `backfill_storm`, `overload_storm`, `mds_storm`) must either carry
   the `slow` marker or pass small LITERAL budgets (a smoke variant)
   — a deep storm slipping into tier-1 blows the cap;
2. every pytest marker used under tests/ must be registered in
   pytest.ini — an unregistered marker (e.g. a typo'd `slowe`)
   silently runs the test in tier-1 instead of excluding it;
3. EVERY Message subclass registered anywhere in the codebase must
   round-trip and match the committed corpus in
   ``tests/golden/messages.json`` — not just the types the struct
   corpus (tests/golden/encoding.json) happened to cover. A new
   message type fails until the corpus is regenerated intentionally:

       python -m tests.test_meta regen-messages
"""

import ast
import configparser
import importlib
import json
import pathlib

import pytest

TESTS = pathlib.Path(__file__).parent
REPO = TESTS.parent
MSG_GOLDEN = TESTS / "golden" / "messages.json"

# storm entry point -> {kwarg: max literal value} a NON-slow (smoke)
# caller may pass; a bigger or non-literal budget requires `slow`
STORM_BUDGETS = {
    "thrash": {"steps": 20},
    "backfill_storm": {"writes": 60, "partitions": 2},
    "overload_storm": {"writers": 4, "prefill": 32, "hold_s": 1.0},
    "mds_storm": {"writes": 24, "kills": 1},
    "elastic_storm": {"writes": 40},
    "qos_storm": {"writes": 30, "hot_parallel": 4},
    # the round-17 tuner acceptance storm: qos_storm's two-tenant
    # shape over two pools — same smoke caps
    "tuner_storm": {"writes": 30, "hot_parallel": 4},
    # the round-16 device-fault storm pays up to three interpret-mode
    # kernel compiles (probe mapper) — keep the IO budgets tiny
    "device_storm": {"ec_writes": 12, "probe_hosts": 4},
    # the 10k-session harness: tier-1 smokes stay <= 200 sessions
    # (LoadGen is a constructor call, matched by Name too)
    "LoadGen": {"sessions": 200},
    # the round-18 worker-process sharded harness: forked workers pay
    # interpreter+jax startup once each, so the smoke budget is ONE
    # worker but session-scale (the sessions run inside the fork,
    # not in the test's own loop)
    "run_sharded": {"sessions": 10000, "workers": 1},
    # the round-18 proc-backend crash storm: every phase SIGKILLs a
    # real process and waits out a supervised respawn (interpreter
    # start ~2-3 s each) — non-slow callers take the defaults
    "proc_storm": {"settle_timeout": 180.0},
    # the round-20 snapshot honesty storm: every write under a snap
    # context pays an OSD-side COW clone, and each snap cut pays a
    # full-image capture read — keep the smoke image small
    "snap_storm": {"writes": 24, "snaps": 3, "image_kb": 32},
}
BUILTIN_MARKS = {
    "parametrize", "skip", "skipif", "xfail", "usefixtures",
    "filterwarnings",
}


def _mark_names(node) -> set[str]:
    """pytest.mark.<name> attribute chains reachable from ``node``."""
    out = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute) and \
                isinstance(n.value, ast.Attribute) and \
                n.value.attr == "mark" and \
                isinstance(n.value.value, ast.Name) and \
                n.value.value.id == "pytest":
            out.add(n.attr)
    return out


def _storm_calls(fn) -> list[tuple[str, dict]]:
    """(entry point, {kwarg: literal-or-None}) calls inside ``fn``
    (nested async helpers included — ast.walk descends)."""
    calls = []
    for n in ast.walk(fn):
        if not isinstance(n, ast.Call):
            continue
        name = None
        if isinstance(n.func, ast.Attribute) and \
                n.func.attr in STORM_BUDGETS:
            name = n.func.attr
        elif isinstance(n.func, ast.Name) and \
                n.func.id in STORM_BUDGETS:
            name = n.func.id          # constructor-style entry points
        if name is None:
            continue
        kwargs = {}
        for kw in n.keywords:
            kwargs[kw.arg] = kw.value.value \
                if isinstance(kw.value, ast.Constant) else None
        calls.append((name, kwargs))
    return calls


def _iter_test_functions():
    for path in sorted(TESTS.glob("test_*.py")):
        tree = ast.parse(path.read_text(), filename=str(path))
        module_marks = set()
        for stmt in tree.body:
            if isinstance(stmt, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == "pytestmark"
                    for t in stmt.targets):
                module_marks |= _mark_names(stmt.value)
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)) and \
                    node.name.startswith("test_"):
                marks = set(module_marks)
                for dec in node.decorator_list:
                    marks |= _mark_names(dec)
                yield path, node, marks


def test_storm_tests_are_slow_or_bounded():
    """A storm entry point in a non-slow test must carry small literal
    budgets; anything bigger (or computed) needs @pytest.mark.slow."""
    violations = []
    for path, fn, marks in _iter_test_functions():
        if "slow" in marks:
            continue
        for entry, kwargs in _storm_calls(fn):
            limits = STORM_BUDGETS[entry]
            for arg, cap in limits.items():
                if arg not in kwargs:
                    continue                 # library default: bounded
                val = kwargs[arg]
                if val is None or val > cap:
                    violations.append(
                        f"{path.name}::{fn.name} calls {entry}("
                        f"{arg}={val if val is not None else '<expr>'}"
                        f") above the tier-1 smoke cap {cap} without "
                        f"@pytest.mark.slow")
    assert not violations, "\n".join(violations)


def test_all_markers_registered_in_pytest_ini():
    """Every pytest.mark.<name> used under tests/ must appear in
    pytest.ini's markers section (typos would silently run in
    tier-1)."""
    ini = configparser.ConfigParser()
    ini.read(REPO / "pytest.ini")
    registered = {
        line.strip().split(":", 1)[0].split("(", 1)[0]
        for line in ini["pytest"].get("markers", "").splitlines()
        if line.strip()}
    used = set()
    for path in sorted(TESTS.glob("test_*.py")):
        used |= _mark_names(ast.parse(path.read_text()))
    unregistered = used - registered - BUILTIN_MARKS
    assert not unregistered, (
        f"markers {sorted(unregistered)} used under tests/ but not "
        f"registered in pytest.ini")


# -- message-corpus guard --------------------------------------------------

def _message_registry():
    """Import every module under ceph_tpu/ that registers messages and
    return the full type registry — discovery is textual (`@register`)
    so a brand-new message module cannot dodge the guard by not being
    imported from the tests."""
    pkg_root = REPO / "ceph_tpu"
    for path in sorted(pkg_root.rglob("*.py")):
        if "@register" not in path.read_text():
            continue
        rel = path.relative_to(REPO).with_suffix("")
        importlib.import_module(".".join(rel.parts))
    from ceph_tpu.msg.message import _REGISTRY
    # only codebase messages: other TEST modules register throwaway
    # types into the same process-wide registry (test_messenger's
    # MPing etc.) and must not leak into the corpus contract
    return {code: cls for code, cls in _REGISTRY.items()
            if cls.__module__.startswith("ceph_tpu.")}


def _sample(codec: str, i: int):
    """Deterministic per-field canonical value (index-seeded so two
    fields of one message differ and byte-swaps are caught)."""
    base, _, rest = codec.partition(":")
    if base in ("u8", "u16", "u32", "u64"):
        return i + 1
    if base in ("s32", "s64"):
        return -(i + 1)
    if base == "f64":
        return i + 0.5
    if base == "bool":
        return i % 2 == 0
    if base == "str":
        return f"s{i}"
    if base in ("blob", "blob_view"):
        # blob_view is wire-identical to blob (round 19's zero-copy
        # ingest changes the DECODE side only), so the golden hex for
        # a field that flips codecs must not move
        return bytes([i % 256, 0x5A])
    if base == "list":
        return [_sample(rest, i), _sample(rest, i + 1)]
    if base == "map":
        k_codec, _, v_codec = rest.partition(":")
        return {_sample(k_codec, i): _sample(v_codec, i + 1)}
    raise ValueError(f"unknown codec {codec!r}")   # pragma: no cover


def _canonical(cls):
    return cls(**{name: _sample(codec, i)
                  for i, (name, codec) in enumerate(cls.FIELDS)})


def _message_corpus() -> dict:
    return {f"{cls.__name__}:{code}": _canonical(cls).encode().hex()
            for code, cls in sorted(_message_registry().items())}


def test_every_registered_message_in_golden_corpus():
    """Every registered Message type round-trips AND matches the
    committed corpus (regenerate intentionally with
    `python -m tests.test_meta regen-messages`)."""
    from ceph_tpu.msg.message import Message
    golden = json.loads(MSG_GOLDEN.read_text())
    current = _message_corpus()
    missing = current.keys() - golden.keys()
    stale = golden.keys() - current.keys()
    assert not missing and not stale, (
        f"message corpus out of date (new: {sorted(missing)}, "
        f"removed: {sorted(stale)}) — regen via "
        f"`python -m tests.test_meta regen-messages`")
    for key, blob_hex in current.items():
        assert blob_hex == golden[key], (
            f"wire encoding of {key} changed — message payloads are "
            f"append-only (zero-filled defaults); regen the corpus "
            f"only for intentional format changes")
        m = Message.decode(bytes.fromhex(blob_hex))
        cls = type(m)
        ref = _canonical(cls)
        for name, _ in cls.FIELDS:
            assert getattr(m, name) == getattr(ref, name), \
                f"{key}.{name} did not round-trip"


def test_pre_trace_blobs_decode_with_zeroed_context():
    """Round 9 appended a 16-byte trace context to every frame; blobs
    encoded BEFORE that (no trailing pair) must still decode, with the
    context zeroed — the wire contract that let the field ride the
    Message base instead of every FIELDS list."""
    from ceph_tpu.msg.message import Message
    for code, cls in sorted(_message_registry().items()):
        m = _canonical(cls)
        blob = m.encode()
        assert blob[-16:] == b"\x00" * 16, \
            f"{cls.__name__}: canonical trace context not zero-filled"
        old = Message.decode(blob[:-16])      # the pre-trace encoding
        assert old.trace_id == 0 and old.parent_span_id == 0
        for name, _ in cls.FIELDS:
            assert getattr(old, name) == getattr(m, name), \
                f"{cls.__name__}.{name} lost decoding a pre-trace blob"
        # and a stamped context round-trips
        m.trace_id, m.parent_span_id = 0x1234, 0x5678
        again = Message.decode(m.encode())
        assert (again.trace_id, again.parent_span_id) == \
            (0x1234, 0x5678)


# -- mgr metric + asok surface guards (round 9: the dump surface is --------
# -- now big enough to rot silently) ---------------------------------------

_CANNED_STATUS = {
    "health": {"status": "HEALTH_OK"},
    "quorum": [0],
    "monmap": {"epoch": 3, "num_mons": 1},
    "auth": {"num_keys": 2},
    "osdmap": {"epoch": 9, "num_osds": 3, "num_up_osds": 3,
               "num_in_osds": 3, "pools": 1, "flags": "noout",
               "num_nearfull_osds": 0, "num_full_osds": 0,
               "osd_utilization": {"0": {"used": 5, "capacity": 10}},
               "pool_quotas": [{"pool": 1, "name": "p",
                                "quota_bytes": 4, "quota_objects": 2,
                                "full": 0}],
               "pending_merges": {"p": {"ready": 1}},
               "slow_osds": {"2": 4.5},
               "degraded_kernel_paths": {"1": 0.5},
               "removed_snaps": 3},
    "pgmap": {"num_pgs": 8, "degraded_pgs": 0, "backfilling_pgs": 0,
              "backfill_progress": {"pushed": 0}, "num_objects": 4,
              "num_bytes": 64, "states": {"active+clean": 8}},
    "fsmap": {"epoch": 2, "states": {"a": "active"},
              "standby_count": 1, "failed": [], "max_mds": 2,
              "actives": {"0": "a"}, "migrations": [],
              "subtrees": {"/": 0, "/d1": 1},
              "rank_ops_rate": {"0": 1.5}, "num_snaps": 2},
    "mgrmap": {"epoch": 4, "active_name": "x", "active_gid": 1,
               "available": True, "standbys": ["y"]},
    "progress": {"events": [{"id": "backfill", "fraction": 0.25,
                             "message": "Backfilling 2 pg(s)"}]},
}

_METRIC_RE = __import__("re").compile(
    r"^([A-Za-z_:][A-Za-z0-9_:]*)(\{[^}]*\})?\s(\S+)$")


def _render_prometheus(reported: bool = False) -> str:
    """PrometheusModule.render against canned cluster state (no live
    cluster needed — render only consumes `get('status')` plus either
    the process perf-counter collection or, with ``reported=True``
    (round 12), a DaemonStateIndex seeded the way daemon MMgrReport
    sessions seed it — so BOTH render paths stay inside the
    exposition-format guards)."""
    import asyncio

    from ceph_tpu.mgr.daemon import Mgr
    from ceph_tpu.mgr.modules import PrometheusModule
    from ceph_tpu.utils.perf_counters import PerfCountersBuilder

    class _StubMgr:
        config: dict = {}
        daemon_state = None
        osd_perf_digest = Mgr.osd_perf_digest

        async def get(self, what):
            assert what == "status"
            return _CANNED_STATUS

        async def monc(self):               # pragma: no cover
            raise AssertionError

    class _StubTuner:
        # the round-17 tuner rows render off the sibling module's
        # live counters — a shaped stand-in keeps the exposition
        # guards over them without a mgr loop
        NAME = "tuner"
        ticks, actions_committed, actions_reverted = 3, 2, 1
        observations = 4

        class _G:
            deferred_total = 1
            streaks = {("gray_osd_responder", "affinity:2", "act"): 2}
        guardrails = _G()

    stub = _StubMgr()
    stub.modules = [_StubTuner()]
    if reported:
        from ceph_tpu.mgr.client import schema_entries
        from ceph_tpu.mgr.daemon_state import DaemonStateIndex
        stub.config = {"mgr_stats_singleton_fallback": False}
        idx = stub.daemon_state = DaemonStateIndex()
        buckets = [0] * 64
        buckets[3], buckets[10] = 5, 2
        for name in ("osd.0", "osd.1"):
            pc = (PerfCountersBuilder(name)
                  .add_u64_counter("ops", "guard fixture")
                  .add_time_avg("commit_latency", "guard fixture")
                  .add_time_avg("apply_latency", "guard fixture")
                  .add_histogram("op_w_latency_hist",
                                 "guard fixture")
                  .create_perf_counters(register=False))
            # the round-13 EC-aggregator family reaches /metrics ONLY
            # through report sessions (register=False per OSD) — seed
            # it so the dedicated ceph_osd_ec_agg_* render path stays
            # inside the exposition-format guards
            agg = (PerfCountersBuilder("osd_ec_agg")
                   .add_u64_counter("batches", "guard fixture")
                   .add_u64_counter("stripes", "guard fixture")
                   .add_time_avg("batch_occupancy", "guard fixture")
                   .create_perf_counters(register=False))
            # the round-19 read-side families reach /metrics the same
            # report-session-only way (decode aggregator + hot-shard
            # residency) — seed both so the dedicated
            # ceph_osd_ec_read_agg_* / ceph_osd_ec_resident_* render
            # paths stay inside the exposition-format guards
            ragg = (PerfCountersBuilder("osd_ec_read_agg")
                    .add_u64_counter("batches", "guard fixture")
                    .add_u64_counter("qos_grants", "guard fixture")
                    .add_time_avg("batch_occupancy", "guard fixture")
                    .create_perf_counters(register=False))
            res = (PerfCountersBuilder("osd_ec_resident")
                   .add_u64_counter("hits", "guard fixture")
                   .add_u64("resident_bytes", "guard fixture")
                   .create_perf_counters(register=False))
            # the round-20 shared-blob clone plane reaches /metrics
            # the same report-session-only way (the family lives on
            # the BlueStore instance, register=False) — seed it so
            # the dedicated ceph_bluestore_sharedblob_* render path
            # stays inside the exposition-format guards
            sbp = (PerfCountersBuilder("bluestore_sharedblob")
                   .add_u64_counter("clones", "guard fixture")
                   .add_u64_counter("cow_released", "guard fixture")
                   .add_u64_counter("aus_freed", "guard fixture")
                   .add_u64("records", "guard fixture")
                   .create_perf_counters(register=False))
            # the round-14 device-runtime families reach /metrics the
            # same report-session-only way (per-daemon `devmon`
            # path-health counters + the process `device_runtime`
            # compile/transfer side) — seed both so the dedicated
            # ceph_device_* render path stays inside the guards
            dd = (PerfCountersBuilder("devmon")
                  .add_u64_counter("path_checks", "guard fixture")
                  .add_u64_counter("path_mismatch", "guard fixture")
                  .add_u64_counter("launches_pallas", "guard fixture")
                  .add_u64_counter("launches_xla", "guard fixture")
                  .create_perf_counters(register=False))
            dp = (PerfCountersBuilder("device_runtime")
                  .add_u64_counter("jit_compiles", "guard fixture")
                  .add_time("jit_compile_seconds", "guard fixture")
                  .add_u64_counter("h2d_bytes", "guard fixture")
                  .create_perf_counters(register=False))
            idx.report(name, 1,
                       schema_entries([pc, agg, ragg, res, sbp, dd,
                                       dp]),
                       1.0, {
                name: {
                    "ops": 7,
                    "commit_latency": {"avgcount": 2, "sum": 0.01},
                    "apply_latency": {"avgcount": 2, "sum": 0.008},
                    "op_w_latency_hist": {
                        "count": 7, "sum": 900.0,
                        "log2_buckets": buckets}},
                "osd_ec_agg": {
                    "batches": 3, "stripes": 96,
                    "batch_occupancy": {"avgcount": 3,
                                        "sum": 96.0}},
                "osd_ec_read_agg": {
                    "batches": 2, "qos_grants": 4,
                    "batch_occupancy": {"avgcount": 2,
                                        "sum": 24.0}},
                "osd_ec_resident": {
                    "hits": 9, "resident_bytes": 8192},
                "bluestore_sharedblob": {
                    "clones": 6, "cow_released": 11,
                    "aus_freed": 5, "records": 2},
                "devmon": {
                    "path_checks": 12, "path_mismatch": 4,
                    "launches_pallas": 8, "launches_xla": 4},
                "device_runtime": {
                    "jit_compiles": 5,
                    "jit_compile_seconds": 1.25,
                    "h2d_bytes": 4096}})
    else:
        # make sure at least one histogram is non-empty so the
        # _bucket rendering path is exercised by the guard
        pc = (PerfCountersBuilder("meta_guard")
              .add_histogram("lat_hist", "guard fixture")
              .create_perf_counters())
        for v in (1, 3, 900, 70000):
            pc.hist_add("lat_hist", v)
    mod = PrometheusModule.__new__(PrometheusModule)
    mod.mgr = stub
    text = asyncio.run(mod.render())
    # round 17: the tuner rows track the stub module's counters
    assert 'ceph_tuner_mode{mode="observe"} 1' in text, text
    assert "ceph_tuner_actions_committed 2" in text, text
    assert "ceph_tuner_actions_reverted 1" in text, text
    assert "ceph_tuner_proposals_deferred 1" in text, text
    assert "ceph_tuner_active_streaks 1" in text, text
    # round 20: the snapshot plane's status-driven rows render on
    # BOTH paths (they only consume the canned status)
    assert "ceph_snap_registered 2" in text, text
    assert "ceph_snap_removed 3" in text, text
    if reported:
        # the canned index must actually drive the render: reported
        # rows + the osd perf digest rows, singleton rows absent
        assert 'ceph_perf{ceph_daemon="osd.0",counter="ops"} 7' \
            in text, text
        assert "ceph_osd_commit_latency_ms{" in text
        assert 'ceph_perf{daemon=' not in text
        # round 13: the aggregator's dedicated rows (counters plain,
        # time-avgs rendered as their long-run mean)
        assert 'ceph_osd_ec_agg_batches{ceph_daemon="osd.0"} 3' \
            in text, text
        assert 'ceph_osd_ec_agg_batch_occupancy' \
            '{ceph_daemon="osd.1"} 32' in text, text
        # round 14: the device-runtime rows render from reported
        # state only (the generic ceph_perf render must NOT double
        # the families' cardinality)
        assert 'ceph_device_path_mismatch_total' \
            '{ceph_daemon="osd.0"} 4' in text, text
        assert 'ceph_device_launches_total{ceph_daemon="osd.1",' \
            'path="pallas"} 8' in text, text
        assert 'ceph_device_jit_compiles_total' \
            '{ceph_daemon="osd.0"} 5' in text, text
        assert 'counter="devmon.' not in text, text
        assert 'counter="device_runtime.' not in text, text
        # round 19: the read-side aggregator + residency rows render
        # from reported state through their dedicated blocks (counters
        # plain, time-avgs as their long-run mean), never doubled via
        # the generic ceph_perf render
        assert 'ceph_osd_ec_read_agg_batches' \
            '{ceph_daemon="osd.0"} 2' in text, text
        assert 'ceph_osd_ec_read_agg_qos_grants' \
            '{ceph_daemon="osd.1"} 4' in text, text
        assert 'ceph_osd_ec_read_agg_batch_occupancy' \
            '{ceph_daemon="osd.0"} 12' in text, text
        assert 'ceph_osd_ec_resident_hits' \
            '{ceph_daemon="osd.1"} 9' in text, text
        assert 'ceph_osd_ec_resident_resident_bytes' \
            '{ceph_daemon="osd.0"} 8192' in text, text
        assert 'counter="osd_ec_read_agg.' not in text, text
        assert 'counter="osd_ec_resident.' not in text, text
        # round 20: the shared-blob clone plane renders through its
        # dedicated block only (never doubled via generic ceph_perf)
        assert 'ceph_bluestore_sharedblob_clones' \
            '{ceph_daemon="osd.0"} 6' in text, text
        assert 'ceph_bluestore_sharedblob_aus_freed' \
            '{ceph_daemon="osd.1"} 5' in text, text
        assert 'ceph_bluestore_sharedblob_records' \
            '{ceph_daemon="osd.0"} 2' in text, text
        assert 'counter="bluestore_sharedblob.' not in text, text
    return text


@pytest.mark.parametrize("reported", [False, True],
                         ids=["singleton", "reported"])
def test_prometheus_metric_names_unique_and_snake_case(reported):
    """Every metric row `mgr/modules.py` renders must have a
    snake_case-valid name, a float-parseable value, and a UNIQUE
    (name, labelset) identity — a duplicated row silently shadows its
    twin in every scrape."""
    text = _render_prometheus(reported)
    seen: dict[tuple, str] = {}
    snake = __import__("re").compile(r"^[a-z][a-z0-9_]*$")
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = _METRIC_RE.match(line)
        assert m, f"unparseable exposition row: {line!r}"
        name, labels, value = m.group(1), m.group(2) or "", m.group(3)
        assert snake.match(name), f"metric name not snake_case: {name}"
        float(value)                        # must parse
        key = (name, labels)
        assert key not in seen, \
            f"duplicate metric row {name}{labels} " \
            f"(first: {seen[key]!r}, again: {line!r})"
        seen[key] = line


@pytest.mark.parametrize("reported", [False, True],
                         ids=["singleton", "reported"])
def test_prometheus_histogram_buckets_monotone(reported):
    """The le-bucketed series must be valid prometheus histograms:
    cumulative counts monotone over increasing le, +Inf == _count."""
    text = _render_prometheus(reported)
    series: dict[str, list[tuple[float, float]]] = {}
    counts: dict[str, float] = {}
    for line in text.splitlines():
        m = _METRIC_RE.match(line)
        if not m:
            continue
        name, labels, value = m.group(1), m.group(2) or "", m.group(3)
        if name == "ceph_perf_hist_bucket":
            le = labels.split('le="')[1].split('"')[0]
            key = labels.split(',le=')[0]
            series.setdefault(key, []).append(
                (float("inf") if le == "+Inf" else float(le),
                 float(value)))
        elif name == "ceph_perf_hist_count":
            counts[labels] = float(value)
    assert series, "no histogram series rendered"
    for key, rows in series.items():
        rows.sort()
        les = [le for le, _ in rows]
        assert les == sorted(set(les)), f"{key}: duplicate le bounds"
        cums = [c for _, c in rows]
        assert cums == sorted(cums), f"{key}: non-monotone buckets"
        assert rows[-1][0] == float("inf"), f"{key}: missing +Inf"
        assert counts.get(key + "}") == rows[-1][1], \
            f"{key}: +Inf bucket != _count"


def _knob_reads(prefixes: tuple) -> dict[str, str]:
    """All config-knob string literals starting with ``prefixes``
    passed to any ``.get(...)`` — or the Mapper's ``._knob(...)``
    live-config accessor — under ceph_tpu/ -> first read site."""
    used: dict[str, str] = {}
    for path in sorted((REPO / "ceph_tpu").rglob("*.py")):
        tree = ast.parse(path.read_text(), filename=str(path))
        for n in ast.walk(tree):
            if isinstance(n, ast.Call) and \
                    isinstance(n.func, ast.Attribute) and \
                    n.func.attr in ("get", "_knob") and n.args and \
                    isinstance(n.args[0], ast.Constant) and \
                    isinstance(n.args[0].value, str) and \
                    n.args[0].value.startswith(prefixes):
                used.setdefault(
                    n.args[0].value,
                    f"{path.relative_to(REPO)}:{n.lineno}")
    return used


def _assert_knobs_registered(prefixes: tuple, what: str) -> None:
    from ceph_tpu.utils.config import OPTIONS
    used = _knob_reads(prefixes)
    assert used, f"no {what} knob reads found (guard went stale)"
    missing = {k: at for k, at in used.items() if k not in OPTIONS}
    assert not missing, (
        f"{what} knobs read but not registered in utils/config.py: "
        f"{missing}")
    for k in used:
        assert OPTIONS[k].default is not None, \
            f"option {k} has no default"


def test_qos_knobs_registered_with_defaults():
    """Every scheduler/QoS/slow-osd knob read anywhere under ceph_tpu/
    (a string literal starting with one of the round-11 prefixes
    passed to a ``.get(...)``) must be a declared Option in
    utils/config.py — an unregistered knob silently falls back to its
    call-site default and drifts from `config show`."""
    _assert_knobs_registered(
        ("osd_qos_", "mon_osd_slow_", "osd_op_queue"), "QoS")


def test_telemetry_knobs_registered_with_defaults():
    """Round 12: every telemetry-plane knob (`mgr_stats_*`,
    `mgr_progress_*`, `mgr_beacon_*`) read anywhere must be a
    registered Option with a default — the report loops read them
    LIVE, so an unregistered knob silently diverges from
    `config show` in every daemon."""
    _assert_knobs_registered(
        ("mgr_stats_", "mgr_progress_", "mgr_beacon_"), "telemetry")


def test_devmon_knobs_registered_with_defaults():
    """Round 14: every device-runtime knob (`devmon_*`,
    `mon_kernel_path_*`) read anywhere must be a registered Option
    with a default — `devmon_expected_engine` is read LIVE per sweep
    check and the mon reads the kernel-path debounce knobs per
    report, so an unregistered knob silently diverges from
    `config show`."""
    _assert_knobs_registered(
        ("devmon_", "mon_kernel_path_"), "device runtime")


def test_crush_engine_knobs_registered_with_defaults():
    """Round 15: every CRUSH-engine knob (`osd_crush_*` — mesh
    provenance lands here) read anywhere must be a registered Option
    with a default — `osd_crush_mesh` is read at OSD boot, so an
    unregistered knob silently diverges from `config show`."""
    _assert_knobs_registered(("osd_crush_",), "CRUSH engine")


def test_kernel_ablate_names_documented():
    """Every CEPH_TPU_KERNEL_ABLATE stage the kernel consults (an
    `"..." in _ABLATE` literal in pallas_mapper.py) must appear in
    the module's documented ABLATE_STAGES set — an undocumented
    stage is an env knob nobody can discover, and a stale entry is a
    knob that silently stopped doing anything."""
    import re
    from ceph_tpu.crush.pallas_mapper import ABLATE_STAGES
    src = (REPO / "ceph_tpu" / "crush" /
           "pallas_mapper.py").read_text()
    used = set(re.findall(r'"([a-z0-9_]+)" in _ABLATE', src))
    assert used, "no _ABLATE reads found (guard went stale)"
    assert used == set(ABLATE_STAGES), (
        f"kernel ablation stages drifted: read {sorted(used)} vs "
        f"documented {sorted(ABLATE_STAGES)}")


def test_resilience_knobs_registered_with_defaults():
    """Round 16: every device-fault resilience knob — the CRUSH
    kernel quarantine/re-probe backoffs (`crush_kernel_reprobe_*`)
    and the EC degrade-ladder bounds (`osd_ec_fallback_*`) — read
    anywhere must be a registered Option with a default. Both planes
    read them LIVE (the Mapper per probe decision, the aggregator per
    degraded batch), so an unregistered knob silently diverges from
    `config show` exactly when an operator is tuning a sick
    cluster."""
    _assert_knobs_registered(
        ("crush_kernel_reprobe_", "osd_ec_fallback_"),
        "device-fault resilience")


def test_tuner_knobs_registered_with_defaults():
    """Round 17: every self-driving-tuner knob (`mgr_tuner_*` policy
    thresholds + guardrails, `mon_tune_*` audit/lease bounds) read
    anywhere must be a registered Option with a default — the tuner
    reads them LIVE every tick (the mode ladder is a runtime flip),
    so an unregistered knob silently diverges from `config show`
    exactly when an operator is reining the loop in."""
    _assert_knobs_registered(("mgr_tuner_", "mon_tune_"), "tuner")


def test_snap_knobs_registered_with_defaults():
    """Round 20: every snapshot-plane knob — the MDS snaprealm gates
    (`mds_snap_*`), the BlueStore shared-blob switch
    (`bluestore_sharedblob_*`), and the OSD snap trimmer's pacing
    (`osd_snap_trim_*`) — read anywhere must be a registered Option
    with a default. The trimmer reads batch/sleep LIVE per
    removed-snaps drain and the store reads the sharedblob switch per
    clone, so an unregistered knob silently diverges from
    `config show` exactly when an operator is pacing a trim storm."""
    _assert_knobs_registered(
        ("mds_snap_", "bluestore_sharedblob_", "osd_snap_trim_"),
        "snapshot")


def test_snap_cli_verbs_cap_classes():
    """Round 20: `fs snap ls` is pinned in the read-only cap class
    (an `allow r` mon cap may list snapshots); `fs snap create` and
    `fs snap rm` mutate the registry + the pool removed_snaps queue
    and must NOT be — they stay behind `mon w`."""
    from ceph_tpu.mon.auth_monitor import READONLY_COMMANDS
    assert "fs snap ls" in READONLY_COMMANDS
    assert "fs snap create" not in READONLY_COMMANDS
    assert "fs snap rm" not in READONLY_COMMANDS


def test_proc_and_config_knobs_registered_with_defaults():
    """Round 18: every proc-backend supervisor knob (`proc_*` —
    restart backoff, stop timeout) and central-config knob
    (`mon_config_*`) read anywhere must be a registered Option with a
    default. The supervisor reads them LIVE per respawn decision and
    the ConfigMonitor per `config set`, so an unregistered knob
    silently diverges from `config show` in both backends."""
    _assert_knobs_registered(
        ("proc_", "mon_config_"), "proc backend / central config")


def test_fault_kinds_documented():
    """Every fault kind the injector can build (`faults._BUILDERS`)
    must appear as a backticked table row in sim/README.md — an
    undocumented kind is an asok `fault install` verb nobody can
    discover, and a stale row documents a kind `rule_from_dict`
    would reject."""
    import re
    from ceph_tpu.sim.faults import _BUILDERS
    readme = (REPO / "ceph_tpu" / "sim" / "README.md").read_text()
    rows = set(re.findall(r"^\|\s*`([a-z_]+)`", readme,
                          flags=re.MULTILINE))
    assert rows, "no fault-kind table rows found in sim/README.md"
    assert rows == set(_BUILDERS), (
        f"fault-kind registry drifted: documented {sorted(rows)} vs "
        f"buildable {sorted(_BUILDERS)}")


def test_ec_agg_knobs_registered_with_defaults():
    """Round 13: every EC-aggregator knob (`osd_ec_agg*`) read
    anywhere must be a registered Option with a default — the
    aggregator reads them LIVE per encode, so an unregistered knob
    silently diverges from `config show`. (The companion
    `osd_qos_cost_per_io_bytes` rides the QoS-prefix guard above.)"""
    _assert_knobs_registered(("osd_ec_agg",), "EC aggregator")


def test_ec_read_agg_knobs_registered_with_defaults():
    """Round 19: every read-side data-plane knob — the decode/repair
    aggregator's (`osd_ec_read_agg*`) and the hot-shard residency
    budget (`osd_ec_resident*`) — read anywhere must be a registered
    Option with a default. The aggregator reads them LIVE per decode
    (the off-flip is a runtime bypass) and the residency cache per
    budget check, so an unregistered knob silently diverges from
    `config show`."""
    _assert_knobs_registered(
        ("osd_ec_read_agg", "osd_ec_resident"),
        "EC read aggregator / residency")


def test_ec_streaming_bench_schema():
    """The round-13 `ec_streaming` bench section at a smoke size:
    JSON-clean, carries every driver-required key (the three measured
    legs + resident reference + the `ec_agg_within_2x` verdict), and
    the verdict is a real bool — schema drift fails here before the
    driver's record goes stale. The within-2x CLAIM itself is pinned
    on TPU only; this guard pins the shape."""
    from ceph_tpu.bench.ec_streaming import ec_streaming_section
    rec = ec_streaming_section(n_ops=4, stripes_per_op=2,
                               chunk_size=128, k=2, m=1, reps=1)
    for key in ("aggregated_GiBs", "per_op_GiBs", "pipeline_GiBs",
                "resident_GiBs"):
        assert isinstance(rec[key], float) and rec[key] > 0, key
    assert isinstance(rec["ec_agg_within_2x"], bool)


def test_ec_daemon_path_bench_schema():
    """The round-19 `ec_daemon_path` bench section at a smoke size:
    JSON-clean, carries every driver-required key (the per-op
    baseline, the aggregated daemon path, the resident reference, and
    the `daemon_within_2x_resident` verdict), the verdict is a real
    bool, and at least one coalesced batch launched. The within-2x
    CLAIM is pinned on TPU only (CPU legs are asyncio-dispatch-bound
    and say so via `cpu_caveat`); this guard pins the shape."""
    from ceph_tpu.bench.ec_daemon_path import ec_daemon_path_section
    rec = ec_daemon_path_section(n_ops=4, stripes_per_op=2,
                                 chunk_size=128, k=2, m=1, reps=1)
    for key in ("per_op_GiBs", "read_agg_GiBs", "resident_GiBs"):
        assert isinstance(rec[key], float) and rec[key] > 0, key
    assert isinstance(rec["daemon_within_2x_resident"], bool)
    assert rec["read_agg_batches"] >= 1
    assert rec["n_ops"] == 4 and rec["k"] == 2 and rec["m"] == 1
    import json
    assert json.loads(json.dumps(rec)) == rec   # JSON-clean


def test_mgr_report_schema_types_cover_perf_counters():
    """Every counter type PerfCounters can register must be a type
    the mgr's DaemonStateIndex accepts (daemon_state.ALLOWED_TYPES)
    — and vice versa. The shipped MMgrReport schema is built straight
    off PerfCounters instances (mgr/client.schema_entries), so a new
    TYPE_* constant added without extending ALLOWED_TYPES would make
    every counter of that type silently vanish from `/metrics`: the
    index drops schema entries naming unknown types by design."""
    from ceph_tpu.mgr import daemon_state
    from ceph_tpu.utils import perf_counters as pcmod
    registered = {v for k, v in vars(pcmod).items()
                  if k.startswith("TYPE_") and isinstance(v, str)}
    assert registered, "no TYPE_* constants found (guard went stale)"
    assert registered == set(daemon_state.ALLOWED_TYPES), (
        f"PerfCounters types {sorted(registered)} != mgr-accepted "
        f"{sorted(daemon_state.ALLOWED_TYPES)} — extend "
        f"daemon_state.ALLOWED_TYPES (and the rate/percentile "
        f"handling) when adding a counter type")
    # the builder surface only ever constructs registered types (an
    # AST check so a new add_* method can't hand out a bare string)
    src = (REPO / "ceph_tpu/utils/perf_counters.py").read_text()
    tree = ast.parse(src)
    builder = next(n for n in ast.walk(tree)
                   if isinstance(n, ast.ClassDef) and
                   n.name == "PerfCountersBuilder")
    type_names = {k for k in vars(pcmod) if k.startswith("TYPE_")}
    for meth in builder.body:
        if not (isinstance(meth, ast.FunctionDef) and
                meth.name.startswith("add_")):
            continue
        ctor_types = {
            n.args[0].id for n in ast.walk(meth)
            if isinstance(n, ast.Call) and
            isinstance(n.func, ast.Name) and
            n.func.id == "_Counter" and n.args and
            isinstance(n.args[0], ast.Name)}
        assert ctor_types and ctor_types <= type_names, (
            f"PerfCountersBuilder.{meth.name} constructs a counter "
            f"whose type is not a TYPE_* constant: {ctor_types}")


def test_every_asok_command_has_docstring():
    """Every admin-socket verb registered anywhere in the codebase
    must carry a non-empty description (the runtime check in
    AdminSocket.register enforces it live; this guard catches it at
    review time, including never-executed registration paths)."""
    violations = []
    for path in sorted((REPO / "ceph_tpu").rglob("*.py")):
        tree = ast.parse(path.read_text(), filename=str(path))
        for n in ast.walk(tree):
            if not (isinstance(n, ast.Call) and
                    isinstance(n.func, ast.Attribute) and
                    n.func.attr == "register" and n.args and
                    isinstance(n.args[0], ast.Constant) and
                    isinstance(n.args[0].value, str)):
                continue               # message @register etc. differ
            desc = None
            if len(n.args) >= 3:
                desc = n.args[2]
            for kw in n.keywords:
                if kw.arg == "desc":
                    desc = kw.value
            ok = desc is not None and (
                not isinstance(desc, ast.Constant) or
                (isinstance(desc.value, str) and desc.value.strip()))
            if not ok:
                violations.append(
                    f"{path.relative_to(REPO)}:{n.lineno} asok command "
                    f"{n.args[0].value!r} registered without a "
                    f"description")
    assert not violations, "\n".join(violations)


# -- pod-scale bench record guards (round 10) ------------------------------

def test_crush_multichip_bench_schema():
    """The crush_multichip bench section must report a MEASURED wall —
    `measured: true`, an explicit `n_devices`, and `seconds_100M` (NOT
    the `_est` suffix the single-chip rows carry: that suffix marks a
    linearity extrapolation, which is exactly what the pod row exists
    to retire). Runs the real section function on the 8-virtual-device
    CPU mesh at a smoke size, so schema drift fails here before the
    driver's record goes stale."""
    from ceph_tpu.bench.crush_sweep import canonical_map
    from ceph_tpu.bench.multichip import measured_sweep
    from ceph_tpu.crush.mapper import Mapper
    from ceph_tpu.parallel import local_mesh

    n = 1 << 12
    rec = measured_sweep(local_mesh(),
                         Mapper(canonical_map(64), block=1 << 10),
                         n, 3, reps=1)
    assert rec["measured"] is True
    assert rec["n_devices"] == 8
    assert "seconds_100M" in rec and rec["seconds_100M"] > 0
    assert "seconds_100M_est" not in rec
    assert rec["extrapolated"] is True      # smoke size < 100M says so
    assert rec["path"].endswith("+sharded")
    assert rec["placements"] == 3 * n
    assert json.loads(json.dumps(rec)) == rec   # JSON-clean


def test_multichip_records_schema_roundtrip():
    """Every committed MULTICHIP_r*.json must parse, carry the driver
    schema, and survive a JSON round-trip — the r06 record additionally
    ships the measured crush_multichip row in its tail, so a schema
    break here would silently orphan the pod-scale evidence."""
    recs = sorted(REPO.glob("MULTICHIP_r*.json"))
    assert recs, "no MULTICHIP records committed"
    for p in recs:
        rec = json.loads(p.read_text())
        assert {"n_devices", "rc", "ok", "skipped",
                "tail"} <= rec.keys(), p.name
        assert isinstance(rec["n_devices"], int), p.name
        assert isinstance(rec["ok"], bool), p.name
        assert isinstance(rec["tail"], str), p.name
        assert json.loads(json.dumps(rec)) == rec, p.name


if __name__ == "__main__":
    import sys
    if len(sys.argv) > 1 and sys.argv[1] == "regen-messages":
        MSG_GOLDEN.write_text(json.dumps(_message_corpus(), indent=1))
        print(f"wrote {MSG_GOLDEN}")
