"""Repo-convention guards, enforced mechanically.

The tier-1 run (`pytest -m 'not slow'`, see ROADMAP.md) lives under a
hard wall-clock cap, and the wire format lives under an
encoding-stability contract. Three conventions keep them, and this
module turns each from convention into CI:

1. any test driving a Thrasher storm entry point (`thrash`,
   `backfill_storm`, `overload_storm`, `mds_storm`) must either carry
   the `slow` marker or pass small LITERAL budgets (a smoke variant)
   — a deep storm slipping into tier-1 blows the cap;
2. every pytest marker used under tests/ must be registered in
   pytest.ini — an unregistered marker (e.g. a typo'd `slowe`)
   silently runs the test in tier-1 instead of excluding it;
3. EVERY Message subclass registered anywhere in the codebase must
   round-trip and match the committed corpus in
   ``tests/golden/messages.json`` — not just the types the struct
   corpus (tests/golden/encoding.json) happened to cover. A new
   message type fails until the corpus is regenerated intentionally:

       python -m tests.test_meta regen-messages
"""

import ast
import configparser
import importlib
import json
import pathlib

TESTS = pathlib.Path(__file__).parent
REPO = TESTS.parent
MSG_GOLDEN = TESTS / "golden" / "messages.json"

# storm entry point -> {kwarg: max literal value} a NON-slow (smoke)
# caller may pass; a bigger or non-literal budget requires `slow`
STORM_BUDGETS = {
    "thrash": {"steps": 20},
    "backfill_storm": {"writes": 60, "partitions": 2},
    "overload_storm": {"writers": 4, "prefill": 32, "hold_s": 1.0},
    "mds_storm": {"writes": 24, "kills": 1},
    "elastic_storm": {"writes": 40},
}
BUILTIN_MARKS = {
    "parametrize", "skip", "skipif", "xfail", "usefixtures",
    "filterwarnings",
}


def _mark_names(node) -> set[str]:
    """pytest.mark.<name> attribute chains reachable from ``node``."""
    out = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute) and \
                isinstance(n.value, ast.Attribute) and \
                n.value.attr == "mark" and \
                isinstance(n.value.value, ast.Name) and \
                n.value.value.id == "pytest":
            out.add(n.attr)
    return out


def _storm_calls(fn) -> list[tuple[str, dict]]:
    """(entry point, {kwarg: literal-or-None}) calls inside ``fn``
    (nested async helpers included — ast.walk descends)."""
    calls = []
    for n in ast.walk(fn):
        if isinstance(n, ast.Call) and \
                isinstance(n.func, ast.Attribute) and \
                n.func.attr in STORM_BUDGETS:
            kwargs = {}
            for kw in n.keywords:
                kwargs[kw.arg] = kw.value.value \
                    if isinstance(kw.value, ast.Constant) else None
            calls.append((n.func.attr, kwargs))
    return calls


def _iter_test_functions():
    for path in sorted(TESTS.glob("test_*.py")):
        tree = ast.parse(path.read_text(), filename=str(path))
        module_marks = set()
        for stmt in tree.body:
            if isinstance(stmt, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == "pytestmark"
                    for t in stmt.targets):
                module_marks |= _mark_names(stmt.value)
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)) and \
                    node.name.startswith("test_"):
                marks = set(module_marks)
                for dec in node.decorator_list:
                    marks |= _mark_names(dec)
                yield path, node, marks


def test_storm_tests_are_slow_or_bounded():
    """A storm entry point in a non-slow test must carry small literal
    budgets; anything bigger (or computed) needs @pytest.mark.slow."""
    violations = []
    for path, fn, marks in _iter_test_functions():
        if "slow" in marks:
            continue
        for entry, kwargs in _storm_calls(fn):
            limits = STORM_BUDGETS[entry]
            for arg, cap in limits.items():
                if arg not in kwargs:
                    continue                 # library default: bounded
                val = kwargs[arg]
                if val is None or val > cap:
                    violations.append(
                        f"{path.name}::{fn.name} calls {entry}("
                        f"{arg}={val if val is not None else '<expr>'}"
                        f") above the tier-1 smoke cap {cap} without "
                        f"@pytest.mark.slow")
    assert not violations, "\n".join(violations)


def test_all_markers_registered_in_pytest_ini():
    """Every pytest.mark.<name> used under tests/ must appear in
    pytest.ini's markers section (typos would silently run in
    tier-1)."""
    ini = configparser.ConfigParser()
    ini.read(REPO / "pytest.ini")
    registered = {
        line.strip().split(":", 1)[0].split("(", 1)[0]
        for line in ini["pytest"].get("markers", "").splitlines()
        if line.strip()}
    used = set()
    for path in sorted(TESTS.glob("test_*.py")):
        used |= _mark_names(ast.parse(path.read_text()))
    unregistered = used - registered - BUILTIN_MARKS
    assert not unregistered, (
        f"markers {sorted(unregistered)} used under tests/ but not "
        f"registered in pytest.ini")


# -- message-corpus guard --------------------------------------------------

def _message_registry():
    """Import every module under ceph_tpu/ that registers messages and
    return the full type registry — discovery is textual (`@register`)
    so a brand-new message module cannot dodge the guard by not being
    imported from the tests."""
    pkg_root = REPO / "ceph_tpu"
    for path in sorted(pkg_root.rglob("*.py")):
        if "@register" not in path.read_text():
            continue
        rel = path.relative_to(REPO).with_suffix("")
        importlib.import_module(".".join(rel.parts))
    from ceph_tpu.msg.message import _REGISTRY
    # only codebase messages: other TEST modules register throwaway
    # types into the same process-wide registry (test_messenger's
    # MPing etc.) and must not leak into the corpus contract
    return {code: cls for code, cls in _REGISTRY.items()
            if cls.__module__.startswith("ceph_tpu.")}


def _sample(codec: str, i: int):
    """Deterministic per-field canonical value (index-seeded so two
    fields of one message differ and byte-swaps are caught)."""
    base, _, rest = codec.partition(":")
    if base in ("u8", "u16", "u32", "u64"):
        return i + 1
    if base in ("s32", "s64"):
        return -(i + 1)
    if base == "f64":
        return i + 0.5
    if base == "bool":
        return i % 2 == 0
    if base == "str":
        return f"s{i}"
    if base == "blob":
        return bytes([i % 256, 0x5A])
    if base == "list":
        return [_sample(rest, i), _sample(rest, i + 1)]
    if base == "map":
        k_codec, _, v_codec = rest.partition(":")
        return {_sample(k_codec, i): _sample(v_codec, i + 1)}
    raise ValueError(f"unknown codec {codec!r}")   # pragma: no cover


def _canonical(cls):
    return cls(**{name: _sample(codec, i)
                  for i, (name, codec) in enumerate(cls.FIELDS)})


def _message_corpus() -> dict:
    return {f"{cls.__name__}:{code}": _canonical(cls).encode().hex()
            for code, cls in sorted(_message_registry().items())}


def test_every_registered_message_in_golden_corpus():
    """Every registered Message type round-trips AND matches the
    committed corpus (regenerate intentionally with
    `python -m tests.test_meta regen-messages`)."""
    from ceph_tpu.msg.message import Message
    golden = json.loads(MSG_GOLDEN.read_text())
    current = _message_corpus()
    missing = current.keys() - golden.keys()
    stale = golden.keys() - current.keys()
    assert not missing and not stale, (
        f"message corpus out of date (new: {sorted(missing)}, "
        f"removed: {sorted(stale)}) — regen via "
        f"`python -m tests.test_meta regen-messages`")
    for key, blob_hex in current.items():
        assert blob_hex == golden[key], (
            f"wire encoding of {key} changed — message payloads are "
            f"append-only (zero-filled defaults); regen the corpus "
            f"only for intentional format changes")
        m = Message.decode(bytes.fromhex(blob_hex))
        cls = type(m)
        ref = _canonical(cls)
        for name, _ in cls.FIELDS:
            assert getattr(m, name) == getattr(ref, name), \
                f"{key}.{name} did not round-trip"


if __name__ == "__main__":
    import sys
    if len(sys.argv) > 1 and sys.argv[1] == "regen-messages":
        MSG_GOLDEN.write_text(json.dumps(_message_corpus(), indent=1))
        print(f"wrote {MSG_GOLDEN}")
