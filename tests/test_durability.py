"""Regression tests for the distributed durability fixes.

Round-3 shipped three acked-write-safety fixes without fault-injected
tests (VERDICT r3 Missing #5); round 4 adds them, plus the round-4
repop-dedup fix (ADVICE r3 medium):

- a replica that commits but whose MOSDRepOpReply is lost must leave
  the client seeing -EAGAIN — including on RESENDS of the same reqid —
  until the repop is known committed (late reply) or a re-peer +
  recovery has made the log durable (ref: PrimaryLogPG::already_complete
  only short-circuits dups of committed repops);
- an EC shard whose apply fails must not count toward the >=k durable
  shard check (ref: ECBackend on_change/commit accounting);
- a late MOSDOpReply from a timed-out objecter attempt must not resolve
  a newer attempt's waiter (ref: MOSDOp::get_retry_attempt).
"""

import asyncio

import pytest

from ceph_tpu.cluster.vstart import Cluster
from ceph_tpu.osd.ec_pg import ECPG
from ceph_tpu.osd.messages import (
    MOSDOpReply, MOSDRepOpReply, OSD_OP_WRITEFULL,
)
from ceph_tpu.rados import ObjectOperationError


def run(coro):
    asyncio.run(coro)


async def _rep_cluster(**cfg):
    config = {"mon_osd_down_out_interval": 2.0,
              "osd_repop_timeout": 0.4}
    config.update(cfg)
    c = await Cluster(n_mons=1, n_osds=3, config=config).start()
    await c.client.pool_create("data", pg_num=4, size=3, min_size=2)
    await c.wait_for_clean(timeout=120)
    return c


async def _locate(c, io, oid: str):
    """Write once so the PG exists, then return (primary_pg, replicas)."""
    await io.write_full(oid, b"seed")
    osdmap = await c.client.monc.wait_for_osdmap()
    seed, primary = c.client.objecter._calc_target(osdmap, io.pool_id, oid)
    posd = next(o for o in c.osds if o.whoami == primary)
    from ceph_tpu.osd.types import pg_t
    pg = posd.pgs[str(pg_t(io.pool_id, seed))]
    replicas = [o for o in pg.acting if o != primary]
    return pg, replicas


def test_repop_timeout_dup_stays_eagain_until_late_reply():
    """Lost MOSDRepOpReply: the op must not be acked (first send OR
    dup resends) until the reply arrives; then the SAME logical op
    succeeds with exactly one log entry (no re-execution).

    Fails on the round-3 code, which recorded result 0 in
    _reqid_results immediately on repop timeout."""
    async def go():
        c = await _rep_cluster()
        try:
            io = await c.client.open_ioctx("data")
            pg, replicas = await _locate(c, io, "victim")
            victim = replicas[0]
            # drop every rep-reply from `victim` at the primary, but
            # remember them for later delivery (reply lost in flight;
            # the replica HAS committed)
            dropped = []
            orig = pg.handle_rep_reply

            def dropping(m):
                if m.from_osd == victim:
                    dropped.append(m)
                    return
                orig(m)
            pg.handle_rep_reply = dropping
            head_before = pg.pg_log.head
            task = asyncio.ensure_future(
                io.write_full("victim", b"payload", timeout=30.0))
            # let the first attempt + at least one dup resend happen
            await asyncio.sleep(3.0)
            assert not task.done(), \
                "op acked while a replica commit was unconfirmed"
            # exactly ONE new log entry despite the resends (dedup)
            new = pg.pg_log.head.v - head_before.v
            assert new == 1, f"expected 1 log entry, got {new}"
            assert any(e[3] for e in pg._repop_waiters.values()), \
                "timed-out repop not tracked"
            # the lost reply finally arrives -> promotion -> the dup
            # in flight completes successfully
            pg.handle_rep_reply = orig
            for m in dropped:
                orig(m)
            await asyncio.wait_for(task, timeout=15.0)
            assert pg.pg_log.head.v - head_before.v == 1
            assert not any(e[3] for e in pg._repop_waiters.values())
            assert await io.read("victim") == b"payload"
        finally:
            await c.stop()
    run(go())


def test_repop_timeout_promoted_after_repeer_recovery():
    """The replica never answers and is killed: once the PG re-peers on
    the surviving set and recovery completes, the pending -EAGAIN is
    promoted and the client's resend succeeds."""
    async def go():
        c = await _rep_cluster()
        try:
            io = await c.client.open_ioctx("data")
            pg, replicas = await _locate(c, io, "victim2")
            victim = replicas[0]
            orig = pg.handle_rep_reply
            pg.handle_rep_reply = lambda m: (
                None if m.from_osd == victim else orig(m))
            task = asyncio.ensure_future(
                io.write_full("victim2", b"payload2", timeout=60.0))
            await asyncio.sleep(2.0)
            assert not task.done()
            pg.handle_rep_reply = orig
            await c.kill_osd(victim)
            await c.wait_for_osd_down(victim, timeout=20)
            # re-peer on 2 live (>= min_size) + recovery -> promote
            await asyncio.wait_for(task, timeout=30.0)
            assert await io.read("victim2") == b"payload2"
        finally:
            await c.stop()
    run(go())


def test_ec_failed_shard_not_counted_as_committed():
    """Every remote EC shard apply fails: committed(=1 local) < k=2 must
    fail the write with -EIO, not ack it. Fails on pre-round-3 code
    (failed acks counted as commits)."""
    async def go():
        from tests.test_ec_cluster import _ec_cluster
        c, io = await _ec_cluster(n_osds=3, k=2, m=1)
        orig = ECPG._apply_sub_write
        try:
            await io.write_full("ok", b"x" * 2048)   # healthy baseline

            def failing(self, m, local=False):
                if not local:
                    return -5                         # injected -EIO
                return orig(self, m, local=local)
            ECPG._apply_sub_write = failing
            with pytest.raises(ObjectOperationError) as ei:
                await io.write_full("doomed", b"y" * 2048, timeout=15.0)
            assert ei.value.errno in (-5, -110)
        finally:
            ECPG._apply_sub_write = orig
            await c.stop()
    run(go())


def test_objecter_stale_attempt_reply_ignored():
    """A late reply carrying an older attempt id must not resolve the
    current attempt's waiter."""
    class _FakeMsgr:
        def add_dispatcher(self, d):
            pass

    class _FakeMonc:
        msgr = _FakeMsgr()

    from ceph_tpu.osdc.objecter import Objecter

    async def go():
        ob = Objecter(_FakeMonc())
        fut = asyncio.get_event_loop().create_future()
        ob._waiters[(7, 1)] = fut                      # current attempt 1
        stale = MOSDOpReply(tid=7, attempt=0, result=0, epoch=1,
                            data=b"old", extra="")
        await ob.ms_dispatch(stale)
        assert not fut.done(), "stale attempt resolved current waiter"
        fresh = MOSDOpReply(tid=7, attempt=1, result=0, epoch=1,
                            data=b"new", extra="")
        await ob.ms_dispatch(fresh)
        assert fut.done() and fut.result().data == b"new"
    run(go())


def test_repop_reply_codec_roundtrip():
    """MOSDOp/MOSDOpReply carry the attempt field on the wire."""
    from ceph_tpu.msg.message import Message
    from ceph_tpu.osd.messages import make_osd_op
    m = make_osd_op(3, 9, 1, 0, "o", [(OSD_OP_WRITEFULL, 0, 4, "", b"abcd")],
                    attempt=2)
    m2 = Message.decode(m.encode())
    assert m2.attempt == 2 and m2.tid == 3
    r = MOSDOpReply(tid=3, attempt=2, result=0, epoch=9, data=b"",
                    extra="")
    r2 = Message.decode(r.encode())
    assert r2.attempt == 2
