"""ObjectStore tier: transactions, stores, crash recovery, fsck.

ref test model: src/test/objectstore/store_test.cc — the same op
sequences run against every store implementation, plus WAL crash
semantics and checksum verification for the durable store.
"""

import os
import zlib

import pytest

from ceph_tpu.os_ import (
    ChecksumError, KVTransaction, MemDB, MemStore, StoreError,
    Transaction, WALDB, WALStore,
)


def stores(tmp_path):
    return [MemStore(), WALStore(str(tmp_path / "w"))]


def test_kv_memdb_batch_and_iter():
    db = MemDB()
    t = db.get_transaction()
    t.set("p", "b", b"2").set("p", "a", b"1").set("q", "x", b"9")
    t.rmkey("p", "missing")
    db.submit_transaction(t)
    assert db.get("p", "a") == b"1"
    assert list(db.get_iterator("p")) == [("a", b"1"), ("b", b"2")]
    t2 = db.get_transaction().rmkeys_by_prefix("p")
    db.submit_transaction(t2)
    assert db.get("p", "a") is None
    assert db.get("q", "x") == b"9"


def test_waldb_durability_and_compaction(tmp_path):
    path = str(tmp_path / "kv")
    db = WALDB(path)
    for i in range(10):
        db.submit_transaction(
            db.get_transaction().set("p", f"k{i}", bytes([i])))
    db.compact()
    db.submit_transaction(db.get_transaction().set("p", "after", b"z"))
    db.close()
    db2 = WALDB(path)
    assert db2.get("p", "k7") == bytes([7])
    assert db2.get("p", "after") == b"z"
    db2.close()


def test_waldb_torn_tail_discarded(tmp_path):
    path = str(tmp_path / "kv")
    db = WALDB(path)
    db.submit_transaction(db.get_transaction().set("p", "good", b"1"))
    db.submit_transaction(db.get_transaction().set("p", "torn", b"2"))
    db.close()
    wal = os.path.join(path, WALDB.WAL)
    sz = os.path.getsize(wal)
    with open(wal, "r+b") as f:      # simulate crash mid-append
        f.truncate(sz - 3)
    db2 = WALDB(path)
    assert db2.get("p", "good") == b"1"
    assert db2.get("p", "torn") is None      # torn record discarded
    # and the tail was reset cleanly: new writes replay fine
    db2.submit_transaction(db2.get_transaction().set("p", "new", b"3"))
    db2.close()
    db3 = WALDB(path)
    assert db3.get("p", "new") == b"3"
    db3.close()


def test_kv_transaction_codec():
    t = KVTransaction()
    t.set("a", "k", b"v").rmkey("b", "x").rmkeys_by_prefix("c")
    t2 = KVTransaction.decode(t.encode())
    assert t2.ops == t.ops


def test_transaction_codec_all_ops():
    t = Transaction()
    t.create_collection("1.0").touch("1.0", "o")
    t.write("1.0", "o", 4, b"abc").zero("1.0", "o", 0, 2)
    t.truncate("1.0", "o", 100)
    t.setattrs("1.0", "o", {"_": b"oi"}).rmattr("1.0", "o", "_")
    t.clone("1.0", "o", "o2").omap_setkeys("1.0", "o", {"k": b"v"})
    t.omap_rmkeys("1.0", "o", ["k"]).omap_clear("1.0", "o")
    t.remove("1.0", "o2").remove_collection("1.0")
    t2 = Transaction.decode(t.encode())
    assert t2.ops == t.ops


@pytest.mark.parametrize("which", ["mem", "wal"])
def test_object_semantics(tmp_path, which):
    st = MemStore() if which == "mem" else WALStore(str(tmp_path / "w"))
    t = Transaction().create_collection("1.0")
    t.write("1.0", "obj", 0, b"hello world")
    t.write("1.0", "obj", 6, b"ceph!")       # overwrite tail
    t.setattrs("1.0", "obj", {"_": b"meta"})
    t.omap_setkeys("1.0", "obj", {"snap": b"1"})
    st.queue_transaction(t)
    assert st.read("1.0", "obj") == b"hello ceph!"
    assert st.read("1.0", "obj", 6, 4) == b"ceph"
    assert st.stat("1.0", "obj") == 11
    assert st.getattrs("1.0", "obj") == {"_": b"meta"}
    assert st.omap_get("1.0", "obj") == {"snap": b"1"}
    # zero extends, truncate shrinks
    st.queue_transaction(Transaction().zero("1.0", "obj", 9, 4))
    assert st.read("1.0", "obj") == b"hello cep\x00\x00\x00\x00"
    st.queue_transaction(Transaction().truncate("1.0", "obj", 5))
    assert st.read("1.0", "obj") == b"hello"
    # clone copies everything
    st.queue_transaction(Transaction().clone("1.0", "obj", "obj2"))
    assert st.read("1.0", "obj2") == b"hello"
    assert st.omap_get("1.0", "obj2") == {"snap": b"1"}
    assert st.list_objects("1.0") == ["obj", "obj2"]
    # remove
    st.queue_transaction(Transaction().remove("1.0", "obj"))
    assert not st.exists("1.0", "obj")
    assert st.exists("1.0", "obj2")
    with pytest.raises(StoreError):
        st.read("1.0", "obj")


def test_missing_collection_raises(tmp_path):
    st = MemStore()
    with pytest.raises(StoreError):
        st.queue_transaction(Transaction().touch("nope", "o"))


def test_walstore_reopen_preserves_state(tmp_path):
    path = str(tmp_path / "w")
    st = WALStore(path)
    t = Transaction().create_collection("2.1")
    t.write("2.1", "a", 0, b"x" * 1000)
    t.omap_setkeys("2.1", "a", {"pglog.1": b"entry"})
    t.create_collection("2.2")
    st.queue_transaction(t)
    st.umount()
    st2 = WALStore(path)
    assert st2.list_collections() == ["2.1", "2.2"]
    assert st2.read("2.1", "a") == b"x" * 1000
    assert st2.omap_get("2.1", "a") == {"pglog.1": b"entry"}
    assert st2.fsck() == []
    st2.umount()


def test_walstore_crash_atomicity(tmp_path):
    """A transaction torn mid-WAL-append is entirely absent on reopen."""
    path = str(tmp_path / "w")
    st = WALStore(path)
    st.queue_transaction(
        Transaction().create_collection("1.0").write("1.0", "a", 0, b"A"))
    st.queue_transaction(
        Transaction().write("1.0", "a", 0, b"B").write("1.0", "b", 0,
                                                       b"new"))
    st.umount()
    wal = os.path.join(path, WALDB.WAL)
    with open(wal, "r+b") as f:
        f.truncate(os.path.getsize(wal) - 2)   # tear the second txn
    st2 = WALStore(path)
    assert st2.read("1.0", "a") == b"A"        # first txn intact
    assert not st2.exists("1.0", "b")          # second fully gone
    assert st2.fsck() == []
    st2.umount()


def test_walstore_checksum_detects_corruption(tmp_path):
    path = str(tmp_path / "w")
    st = WALStore(path)
    st.queue_transaction(
        Transaction().create_collection("1.0").write(
            "1.0", "a", 0, b"payload-payload-payload"))
    # corrupt the in-kv record's data bytes directly (bit rot)
    key = WALStore._okey("1.0", "a")
    rec = bytearray(st.db.get("O", key))
    rec[10] ^= 0xFF
    st.db.submit_transaction(
        st.db.get_transaction().set("O", key, bytes(rec)))
    st.umount()
    st2 = WALStore(path)
    assert any("checksum" in e for e in st2.fsck())
    with pytest.raises(ChecksumError):
        st2.read("1.0", "a")
    st2.umount()


def test_walstore_rmcoll_removes_objects(tmp_path):
    path = str(tmp_path / "w")
    st = WALStore(path)
    st.queue_transaction(
        Transaction().create_collection("1.0")
        .write("1.0", "a", 0, b"1").write("1.0", "b", 0, b"2"))
    st.queue_transaction(Transaction().remove_collection("1.0"))
    st.umount()
    st2 = WALStore(path)
    assert st2.list_collections() == []
    assert list(st2.db.get_iterator("O")) == []
    st2.umount()


def test_transaction_all_or_nothing(tmp_path):
    """A txn that fails mid-way must leave live state untouched
    (ADVICE r2: memory diverged from kv until restart)."""
    for st in (MemStore(), WALStore(str(tmp_path / "w"))):
        st.queue_transaction(
            Transaction().create_collection("1.0").write(
                "1.0", "a", 0, b"before"))
        bad = Transaction().write("1.0", "a", 0, b"after")
        from ceph_tpu.os_.objectstore import OP_RMATTR
        bad.ops.append((OP_RMATTR, "1.0", "missing", "x"))  # will raise
        with pytest.raises(StoreError):
            st.queue_transaction(bad)
        assert st.read("1.0", "a") == b"before"   # first op NOT applied
        # later ops in the txn can satisfy earlier requirements
        ok = Transaction().touch("1.0", "b")
        ok.omap_setkeys("1.0", "b", {"k": b"v"})
        st.queue_transaction(ok)
        assert st.omap_get("1.0", "b") == {"k": b"v"}


def test_walstore_ranged_read_checks_crc(tmp_path):
    """Ranged reads must verify the record checksum too (ADVICE r2)."""
    path = str(tmp_path / "w")
    st = WALStore(path)
    st.queue_transaction(
        Transaction().create_collection("1.0").write(
            "1.0", "a", 0, b"payload-payload-payload"))
    key = WALStore._okey("1.0", "a")
    rec = bytearray(st.db.get("O", key))
    rec[10] ^= 0xFF
    st.db.submit_transaction(
        st.db.get_transaction().set("O", key, bytes(rec)))
    st.umount()
    st2 = WALStore(path)
    with pytest.raises(ChecksumError):
        st2.read("1.0", "a", 2, 4)                # ranged, not full
    st2.umount()


def test_rmcoll_recreate_validates_against_simulated_state(tmp_path):
    """RMCOLL+MKCOLL in one txn leaves the collection EMPTY: a later op
    on a previously-existing object must fail validation up front (not
    mid-apply, which would destroy the collection on a failed txn)."""
    for st in (MemStore(), WALStore(str(tmp_path / "w"))):
        st.queue_transaction(
            Transaction().create_collection("1.0").write(
                "1.0", "a", 0, b"keep me"))
        bad = Transaction()
        bad.remove_collection("1.0")
        bad.create_collection("1.0")
        bad.omap_clear("1.0", "a")          # 'a' gone after reset
        with pytest.raises(StoreError):
            st.queue_transaction(bad)
        # nothing applied: object survives
        assert st.read("1.0", "a") == b"keep me"
