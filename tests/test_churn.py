"""Churn simulator tests (BASELINE config #5): CRUSH's rebalance
optimality properties under OSD add/remove, plus the osdmaptool CLI."""

import numpy as np

from ceph_tpu.bench import osdmaptool
from ceph_tpu.crush.types import ITEM_NONE, WEIGHT_ONE
from ceph_tpu.sim import ChurnEvent, ChurnSim


def make_sim(n_osds=32, pg_num=256, size=3, erasure=False):
    m = osdmaptool.create_simple(n_osds, pg_num, size, erasure)
    return ChurnSim(m, 1)


class TestChurn:
    def test_out_moves_proportional_data(self):
        """Marking one of 32 OSDs out should move roughly the victim's
        share of shards (CRUSH minimal-movement property), not reshuffle
        the cluster."""
        sim = make_sim()
        rep = sim.apply(ChurnEvent("out", 5))
        assert rep.degraded_pgs == 0  # re-replicated immediately
        # victim held ~3*256/32 = 24 shards; movement should be near that
        assert 0 < rep.shards_moved < 3 * 256 * 0.15

    def test_down_then_revive_restores(self):
        sim = make_sim()
        before = sim._up.copy()
        sim.apply(ChurnEvent("down", 9))
        sim.apply(ChurnEvent("out", 9))
        sim.apply(ChurnEvent("in", 9))
        rep = sim.apply(ChurnEvent("up", 9))
        assert rep.degraded_pgs == 0
        assert (sim._up == before).all()  # placement is a pure function

    def test_down_degrades_ec(self):
        sim = make_sim(erasure=True, size=5)
        victim = int(sim._up[0, 0])
        rep = sim.apply(ChurnEvent("down", victim))
        assert rep.degraded_pgs > 0  # holes until marked out
        rep2 = sim.apply(ChurnEvent("out", victim))
        assert rep2.degraded_pgs == 0  # backfill targets found

    def test_add_osd_rebalances_minimally(self):
        sim = make_sim()
        n_shards = 3 * 256
        rep = sim.apply(ChurnEvent("add", 32, WEIGHT_ONE))
        # new osd takes ~1/33 of shards; movement bounded well below that x3
        assert 0 < rep.shards_moved < n_shards * 0.12

    def test_random_thrash_converges(self):
        sim = make_sim()
        rng = np.random.default_rng(7)
        sim.random_thrash(rng, 12)
        # revive everything
        for o in range(sim.map.max_osd):
            sim.map.mark_up(o)
            sim.map.mark_in(o)
        up, _, _, _ = sim.map.map_pool(1)
        assert (up != ITEM_NONE).all()

    def test_summary(self):
        sim = make_sim()
        sim.apply(ChurnEvent("out", 1))
        s = sim.summary()
        assert s["events"] == 1 and s["total_shards_moved"] > 0


class TestOsdmaptoolCLI:
    def test_test_map_pgs(self, capsys):
        rc = osdmaptool.main(["--createsimple", "16", "--pg-num", "128",
                              "--test-map-pgs", "--format", "json"])
        assert rc == 0
        import json
        out = json.loads(capsys.readouterr().out)
        assert out["map_pgs"]["degraded_pgs"] == 0
        assert out["map_pgs"]["avg"] > 0

    def test_churn_cli(self, capsys):
        rc = osdmaptool.main(["--createsimple", "16", "--pg-num", "64",
                              "--churn", "4", "--format", "json"])
        assert rc == 0
        import json
        out = json.loads(capsys.readouterr().out)
        assert out["churn"]["events"] > 0
