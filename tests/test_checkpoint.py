"""Resumable-sweep checkpoint tests (SURVEY.md §5.4; VERDICT missing #9)."""

import numpy as np
import pytest

from ceph_tpu.crush import builder
from ceph_tpu.crush.mapper import Mapper
from ceph_tpu.utils.checkpoint import SweepState, resumable_sweep


def make_map():
    m, root = builder.build_hierarchy(8, 2)
    rid = builder.add_simple_rule(m, root, builder.TYPE_HOST)
    return m, rid


class TestResumableSweep:
    def test_interrupted_resume_matches_oneshot(self, tmp_path):
        m, rid = make_map()
        ck = str(tmp_path / "sweep.json")
        mapper = Mapper(m, block=512)
        # one-shot truth
        c_all, b_all = mapper.sweep(rid, 0, 4096, 3)
        truth = np.asarray(c_all)
        # interrupted run: 2 chunks then 'crash'
        st, done = resumable_sweep(m, rid, 4096, 3, ck, chunk=1024,
                                   mapper=mapper, max_chunks=2)
        assert not done and st.cursor == 2048
        # resume in a fresh call (fresh state loaded from disk)
        st2, done2 = resumable_sweep(m, rid, 4096, 3, ck, chunk=1024,
                                     mapper=mapper)
        assert done2 and st2.cursor == 4096
        assert (st2.counts == truth).all()
        assert st2.bad == int(b_all)

    def test_mismatched_checkpoint_rejected(self, tmp_path):
        m, rid = make_map()
        ck = str(tmp_path / "sweep.json")
        mapper = Mapper(m, block=512)
        resumable_sweep(m, rid, 2048, 3, ck, chunk=1024, mapper=mapper,
                        max_chunks=1)
        # mutate the map: partial counts no longer belong to it
        builder.adjust_item_weight(m, 0, 2 * 0x10000)
        with pytest.raises(ValueError):
            resumable_sweep(m, rid, 2048, 3, ck, chunk=1024)

    def test_state_roundtrip(self, tmp_path):
        st = SweepState(crushmap_text="x", rule=1, num_rep=3,
                        n_total=10, cursor=4, bad=1,
                        counts=np.arange(5, dtype=np.int64))
        p = str(tmp_path / "s.json")
        st.save(p)
        got = SweepState.load(p)
        assert got.cursor == 4 and got.bad == 1
        assert (got.counts == st.counts).all()
        assert SweepState.load(str(tmp_path / "missing.json")) is None
