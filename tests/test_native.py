"""Native (C++) runtime tests: byte-parity between the C++ RS backend and
the JAX plugin (the jerasure<->isa cross-validation pattern, ref:
src/test/erasure-code/TestErasureCodeIsa.cc isa_vandermonde vs jerasure),
plus the dlopen plugin-registry contract."""

import ctypes
import shutil
import subprocess

import numpy as np
import pytest

from ceph_tpu.ec.jax_plugin import ErasureCodeJax
from ceph_tpu.ec.registry import factory


def _native_available() -> bool:
    if shutil.which("make") is None or shutil.which("g++") is None:
        return False
    try:
        from ceph_tpu.interop.native import build_native
        build_native()
        return True
    except RuntimeError:
        return False


pytestmark = pytest.mark.skipif(not _native_available(),
                                reason="native toolchain unavailable")


GEOMETRIES = [(2, 2, "reed_sol_van"), (4, 2, "reed_sol_van"),
              (8, 3, "reed_sol_van"), (8, 3, "cauchy_good"),
              (6, 3, "cauchy_orig"), (10, 4, "reed_sol_van")]


class VT(ctypes.Structure):
    """The native ec_plugin_vtable_t (native/ec/plugin.h) — single
    definition shared by every dlopen-driven test."""
    _fields_ = [
        ("create", ctypes.CFUNCTYPE(ctypes.c_void_p, ctypes.c_char_p)),
        ("destroy", ctypes.CFUNCTYPE(None, ctypes.c_void_p)),
        ("k_of", ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p)),
        ("m_of", ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p)),
        ("encode", ctypes.CFUNCTYPE(
            ctypes.c_int, ctypes.c_void_p, ctypes.c_char_p,
            ctypes.c_char_p, ctypes.c_size_t)),
        ("decode", ctypes.CFUNCTYPE(
            ctypes.c_int, ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int), ctypes.c_int,
            ctypes.POINTER(ctypes.c_int), ctypes.c_int,
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t)),
    ]


def load_registry():
    """ctypes handle to libec_registry.so with the factory prototype."""
    from ceph_tpu.interop.native import native_build_dir
    build = native_build_dir()
    lib = ctypes.CDLL(str(build / "libec_registry.so"),
                      mode=ctypes.RTLD_GLOBAL)
    lib.ec_registry_factory.restype = ctypes.c_void_p
    lib.ec_registry_factory.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_void_p)]
    return lib, build


class TestNativeOracle:
    @pytest.mark.parametrize("k,m,tech", GEOMETRIES)
    def test_coding_matrix_matches_python(self, k, m, tech):
        from ceph_tpu.ec import matrix as rs
        from ceph_tpu.interop.native import ErasureCodeRef
        ref = ErasureCodeRef(f"k={k} m={m} technique={tech}")
        assert (ref.coding_matrix() == rs.coding_matrix(tech, k, m)).all()

    @pytest.mark.parametrize("k,m,tech", GEOMETRIES)
    def test_encode_parity_bytes_match_jax(self, k, m, tech, rng):
        from ceph_tpu.interop.native import ErasureCodeRef
        ref = ErasureCodeRef(f"k={k} m={m} technique={tech}")
        jx = ErasureCodeJax(f"k={k} m={m} technique={tech}")
        data = rng.integers(0, 256, size=(k, 2048), dtype=np.uint8)
        assert (ref.encode_chunks(data) == jx.encode_chunks(data)).all()

    def test_decode_roundtrip_and_cross(self, rng):
        from ceph_tpu.interop.native import ErasureCodeRef
        ref = ErasureCodeRef("k=8 m=3")
        jx = ErasureCodeJax("k=8 m=3")
        data = rng.integers(0, 256, size=(8, 1024), dtype=np.uint8)
        parity = ref.encode_chunks(data)
        full = {i: data[i] for i in range(8)}
        full.update({8 + i: parity[i] for i in range(3)})
        surv = {i: c for i, c in full.items() if i not in (0, 5, 9)}
        got_ref = ref.decode_chunks([0, 5, 9], surv)
        got_jax = jx.decode_chunks([0, 5, 9], surv)
        for i in (0, 5, 9):
            assert (got_ref[i] == full[i]).all()
            assert (got_ref[i] == got_jax[i]).all()

    def test_registry_plugin_ref(self):
        ec = factory("plugin=ref k=4 m=2")
        payload = b"native" * 1000
        enc = ec.encode(range(6), payload)
        del enc[1], enc[4]
        assert ec.decode_concat(enc)[:len(payload)] == payload


class TestDlopenRegistry:
    """The __erasure_code_init dlopen flow, driven exactly as an external
    C consumer would (ref: ErasureCodePluginRegistry::load)."""

    def test_dlopen_factory_and_encode(self):
        lib, build = load_registry()
        vt_ptr = ctypes.c_void_p()
        be = lib.ec_registry_factory(b"rsvan", str(build).encode(),
                                     b"k=4 m=2", ctypes.byref(vt_ptr))
        assert be, "factory returned null"
        assert vt_ptr.value

        vt = ctypes.cast(vt_ptr, ctypes.POINTER(VT)).contents
        assert vt.k_of(be) == 4 and vt.m_of(be) == 2
        data = np.arange(4 * 512, dtype=np.uint8).reshape(4, 512)
        parity = np.zeros((2, 512), dtype=np.uint8)
        rc = vt.encode(be, data.ctypes.data_as(ctypes.c_char_p),
                       parity.ctypes.data_as(ctypes.c_char_p), 512)
        assert rc == 0
        # parity matches the in-process Python/JAX construction
        jx = ErasureCodeJax("k=4 m=2 technique=reed_sol_van")
        assert (parity == jx.encode_chunks(np.ascontiguousarray(data))).all()
        vt.destroy(be)

    def test_unknown_plugin_fails(self):
        lib, build = load_registry()
        vt_ptr = ctypes.c_void_p()
        be = lib.ec_registry_factory(b"nosuch", str(build).encode(),
                                     b"k=4 m=2", ctypes.byref(vt_ptr))
        assert not be


class TestJaxReverseShim:
    """libec_jax.so: the native registry dlopens the shim, the shim
    embeds CPython, and ec_bench drives the flagship JAX plugin through
    the same vtable as any C plugin (SURVEY §7 step 6)."""

    def _build(self):
        from ceph_tpu.interop.native import native_build_dir
        build = native_build_dir()
        if not (build / "libec_jax.so").exists():
            pytest.skip("libec_jax.so not built (no python3-config)")
        return build

    def test_ec_bench_plugin_jax_encode_verify(self):
        build = self._build()
        out = subprocess.run(
            [str(build / "ec_bench"), "--plugin", "jax", "--dir",
             str(build), "--workload", "encode", "--size", "262144",
             "--iterations", "2", "--parameter", "k=4",
             "--parameter", "m=2", "--verify"],
            capture_output=True, text=True, timeout=300)
        assert out.returncode == 0, out.stderr
        assert "verify: ok" in out.stderr
        secs, mbs = out.stdout.split()
        assert float(secs) > 0 and float(mbs) > 0

    def test_ec_bench_plugin_jax_decode_verify(self):
        build = self._build()
        out = subprocess.run(
            [str(build / "ec_bench"), "--plugin", "jax", "--dir",
             str(build), "--workload", "decode", "--size", "262144",
             "--iterations", "1", "--erasures", "2",
             "--parameter", "k=8", "--parameter", "m=3", "--verify"],
            capture_output=True, text=True, timeout=300)
        assert out.returncode == 0, out.stderr
        assert "verify: ok" in out.stderr

    def test_shim_vtable_parity_vs_python(self):
        """Byte parity through the C vtable: load libec_jax.so through
        the native registry in-process (the embedded-interpreter path
        reuses pytest's interpreter via PyGILState), encode through the
        C function pointers, and compare bytes against the in-process
        Python plugin — an actual cross-boundary byte check, not just a
        self-roundtrip."""
        self._build()
        lib, build = load_registry()
        vt_ptr = ctypes.c_void_p()
        be = lib.ec_registry_factory(b"jax", str(build).encode(),
                                     b"k=4 m=2 technique=reed_sol_van",
                                     ctypes.byref(vt_ptr))
        assert be and vt_ptr.value, "jax shim factory failed"

        vt = ctypes.cast(vt_ptr, ctypes.POINTER(VT)).contents
        assert vt.k_of(be) == 4 and vt.m_of(be) == 2
        rng = np.random.default_rng(7)
        data = rng.integers(0, 256, size=(4, 512), dtype=np.uint8)
        parity = np.zeros((2, 512), dtype=np.uint8)
        rc = vt.encode(be, data.ctypes.data_as(ctypes.c_char_p),
                       parity.ctypes.data_as(ctypes.c_char_p), 512)
        assert rc == 0
        jx = ErasureCodeJax("k=4 m=2 technique=reed_sol_van")
        assert (parity == jx.encode_chunks(data)).all()
        vt.destroy(be)


class TestSanitizerTier:
    """ASan build of the native runtime (the reference's sanitizer qa
    tier, scaled to this runtime): instrumented encode + decode +
    dlopen plugin load must run with leak detection on and report
    nothing (ASan exits non-zero on any finding)."""

    def test_asan_encode_decode_verify(self):
        import os
        import pathlib
        native = pathlib.Path(__file__).resolve().parent.parent / "native"
        r = subprocess.run(
            ["make", "-C", str(native), "SANITIZE=address",
             "BUILD=build-asan"],
            capture_output=True, text=True, timeout=300)
        if r.returncode != 0:
            pytest.skip(f"asan build unavailable: {r.stderr[-200:]}")
        build = native / "build-asan"
        for workload, extra in (("encode", []),
                                ("decode", ["--erasures", "2"])):
            out = subprocess.run(
                [str(build / "ec_bench"), "--plugin", "rsvan", "--dir",
                 str(build), "--workload", workload, "--size", "262144",
                 "--iterations", "2", "--parameter", "k=8",
                 "--parameter", "m=3", "--verify"] + extra,
                capture_output=True, text=True, timeout=300,
                env=dict(os.environ, ASAN_OPTIONS="detect_leaks=1"))
            assert out.returncode == 0, (workload, out.stderr[-500:])
            assert "verify: ok" in out.stderr
            assert "AddressSanitizer" not in out.stderr


class TestNativeBench:
    def test_ec_bench_binary(self):
        from ceph_tpu.interop.native import native_build_dir
        build = native_build_dir()
        out = subprocess.run(
            [str(build / "ec_bench"), "--plugin", "rsvan", "--dir",
             str(build), "--workload", "encode", "--size", "1048576",
             "--iterations", "4", "--parameter", "k=4",
             "--parameter", "m=2"],
            capture_output=True, text=True, timeout=120)
        assert out.returncode == 0, out.stderr
        secs, mbs = out.stdout.split()
        assert float(secs) > 0 and float(mbs) > 0
