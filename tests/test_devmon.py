"""Device-runtime observability plane (round 14).

Acceptance surface:

- one COLD ``Mapper`` compile produces exactly one ``jit_compile``
  span (duration inside the client-observed stall) and exactly one
  compile-counter increment — and a warm call produces neither;
- ``Mapper`` path recording is PER CALL (``map_pgs_path`` returns
  this call's engine) with ``last_map_path`` surviving only as a
  best-effort mirror — the single-slot race two interleaved sweeps
  could hit is pinned here;
- a cluster whose daemons are knob-pinned to expect the Pallas engine
  (``devmon_expected_engine=pallas``) while actually serving XLA sees
  the mismatch counter in `/metrics`
  (``ceph_device_path_mismatch_total``, built from REPORTED state),
  trips KERNEL_PATH_DEGRADED after the ``mon_kernel_path_confirm``
  debounce, and clears on heal (knob back to ``auto``);
- a watched daemon task dying with a real exception ships a bounded
  MCrashReport: `ceph crash ls/info` serve it, RECENT_CRASH warns,
  and `ceph crash archive` clears the warning.

Budget discipline: ONE vstart cluster carries every cluster assert
(mismatch counters, /metrics row, health trip + heal, CLI verbs,
crash capture); everything else is unit-level.
"""

import asyncio
import json
import time

import numpy as np
import pytest

from ceph_tpu.crush import builder
from ceph_tpu.crush.builder import TYPE_HOST
from ceph_tpu.crush.mapper import Mapper
from ceph_tpu.utils.devmon import (
    DeviceRuntimeMonitor, devmon, normalize_engine,
)
from ceph_tpu.utils.tracing import Tracer


def run(coro):
    asyncio.run(coro)


def _two_rule_map(n_osds: int = 64):
    """rule 0: replicated chooseleaf firstn (kernel-eligible);
    rule 1: chooseleaf indep (EC shape — NOT kernel-eligible), so the
    two rules resolve to different engines under interpret mode."""
    osds_per_host = 8
    m, root = builder.build_hierarchy(n_osds // osds_per_host,
                                      osds_per_host, n_racks=2)
    builder.add_simple_rule(m, root, TYPE_HOST)
    builder.add_simple_rule(m, root, TYPE_HOST, indep=True)
    return m


# -- units: the monitor itself ----------------------------------------------

def test_normalize_engine():
    assert normalize_engine("pallas") == "pallas"
    assert normalize_engine("pallas-interpret") == "pallas"
    assert normalize_engine("pallas+sharded") == "pallas"
    assert normalize_engine("xla+sharded") == "xla"
    assert normalize_engine("scalar") == "scalar"
    assert normalize_engine(None) == "?"
    assert normalize_engine("florp") == "?"


def test_record_sweep_knob_vs_plan():
    """`devmon_expected_engine` pins the deployment contract (read
    LIVE); 'auto' trusts the plan, so the only mismatch then is a
    degrade relative to the plan's own prediction."""
    cfg = {"devmon_expected_engine": "pallas"}
    dm = DeviceRuntimeMonitor(name="devmon_unit0", register=False,
                              config=cfg)
    # pinned pallas, actually xla: mismatch
    assert dm.record_sweep("xla", "xla") is True
    # pinned pallas, actually the interpreted kernel: NOT a mismatch
    assert dm.record_sweep("pallas-interpret",
                           "pallas-interpret+sharded") is False
    d = dm.perf.dump()
    assert d["path_checks"] == 2 and d["path_mismatch"] == 1
    assert d["launches_xla"] == 1 and d["launches_pallas"] == 1
    assert d["launches_sharded"] == 1
    assert dm.last_mismatch["expected"] == "pallas"
    assert dm.last_mismatch["actual"] == "xla"
    # live flip to auto: plan-trusted, same-engine sweeps are clean
    cfg["devmon_expected_engine"] = "auto"
    assert dm.record_sweep("xla", "xla") is False
    # ... and a mid-run degrade (plan pallas -> actual xla) still trips
    assert dm.record_sweep("pallas", "xla") is True
    assert dm.mismatch_ratio() == pytest.approx(2 / 4)
    hr = dm.health_report()
    assert hr["checks"] == 4 and hr["mismatches"] == 2
    # the merged process side carries compile/transfer keys (all u64)
    for key in ("compiles", "compile_ms", "h2d_bytes", "d2h_bytes"):
        assert isinstance(hr[key], int), key


def test_jit_call_warm_and_failure_unwarm():
    dm = DeviceRuntimeMonitor(name="devmon_unit1", register=False)
    calls = []

    def fn(x):
        calls.append(x)
        if x == "boom":
            raise ValueError("boom")
        return x

    assert dm.jit_call("f", (1,), fn, "a") == "a"
    assert dm.perf.dump()["jit_compiles"] == 1
    # warm: same key, no second compile
    assert dm.jit_call("f", (1,), fn, "b") == "b"
    assert dm.perf.dump()["jit_compiles"] == 1
    # a failed FIRST call un-warms so the retry's compile counts
    with pytest.raises(ValueError):
        dm.jit_call("g", (2,), fn, "boom")
    assert dm.perf.dump()["jit_compiles"] == 1
    assert dm.jit_call("g", (2,), fn, "ok") == "ok"
    assert dm.perf.dump()["jit_compiles"] == 2
    assert dm.functions["f"]["count"] == 1


# -- acceptance: one cold compile -> one span + one counter ------------------

def test_cold_mapper_compile_one_span_one_increment():
    """The acceptance pin: a cold Mapper compile produces exactly ONE
    `jit_compile` span whose duration sits inside the client-observed
    stall, and exactly one compile-counter increment; the warm call
    adds neither."""
    dm = devmon()
    tracer = Tracer("devmon-unit", {"trace_slow_keep_s": 0.0})
    old_tracer = dm.tracer
    dm.attach_tracer(tracer)
    try:
        m = Mapper(_two_rule_map(56), block=1 << 10)
        xs = np.arange(37, dtype=np.uint32)     # odd width: cold key
        before = dm.perf.dump()["jit_compiles"]

        t0 = time.perf_counter()
        out, path = m.map_pgs_path(0, xs, 3)
        stall = time.perf_counter() - t0

        after = dm.perf.dump()["jit_compiles"]
        assert after - before == 1, (before, after)
        spans = [s for s in tracer.dump()["spans"]
                 if s["name"] == "jit_compile"]
        assert len(spans) == 1, spans
        assert 0.0 < spans[0]["duration"] <= stall
        assert spans[0]["tags"]["fn"] == "crush_map_pgs"
        # compile evidence ships monward on the daemon piggyback
        assert tracer.ship_pending() >= 1
        assert out.shape == (37, 3)

        # warm call: no double count, no second span
        m.map_pgs(0, xs, 3)
        assert dm.perf.dump()["jit_compiles"] == after
        assert len([s for s in tracer.dump()["spans"]
                    if s["name"] == "jit_compile"]) == 1
    finally:
        dm.attach_tracer(old_tracer)


# -- the per-call path fix (the last_map_path single-slot race) --------------

def test_map_pgs_path_is_per_call():
    """Two interleaved calls on ONE Mapper that serve different paths
    (the mesh route kicks in per call by batch width) each get THEIR
    OWN path back; the `last_map_path` attribute is last-writer-wins
    — exactly the single-slot race the per-call return exists to fix.
    (Budget note: reuses the cold test's map shape so the rule-VM
    compile is warm; the Pallas-interpret variant of this pin costs
    minutes of interpret-mode compile and is deliberately avoided.)"""
    from ceph_tpu.parallel import local_mesh
    m = Mapper(_two_rule_map(56), block=1 << 10,
               mesh=local_mesh(), mesh_min_batch=64)
    xs_small = np.arange(37, dtype=np.uint32)   # < mesh_min_batch
    xs_big = np.arange(128, dtype=np.uint32)    # >= mesh_min_batch
    out_b, pb = m.map_pgs_path(0, xs_big, 3)
    assert pb == "xla+sharded", pb
    assert out_b.shape == (128, 3)
    out_s, ps = m.map_pgs_path(0, xs_small, 3)
    assert ps == "xla", ps
    # the mirror now shows the LAST call's engine — the singleton
    # slot cannot answer "which path ran MY sweep"...
    assert m.last_map_path == "xla"
    # ...but the per-call value still can
    _, pb2 = m.map_pgs_path(0, xs_big, 3)
    assert pb2 == "xla+sharded"
    assert m.last_map_path == "xla+sharded"
    # sweep_path carries the same per-call contract (small sweep:
    # the plain single-device path)
    counts, bad, sp = m.sweep_path(0, 0, 32, 3)
    assert sp == "xla"
    assert int(np.asarray(counts).sum()) == 32 * 3


def test_kernel_jit_key_carries_variant_tag():
    """Round 15: the compile-warmth key of a kernel-path jit wrapper
    carries the kernel-variant tag, so a `jit_compile` span (its key
    tag is str(key)) distinguishes a fresh candidate-batched-kernel
    compile from a stale plan's re-trace; XLA keys stay variant-free
    (the rule VM did not restructure)."""
    from ceph_tpu.crush import pallas_mapper as pm
    m = Mapper(_two_rule_map(56), block=1 << 10)
    kkey = m._jit_key(0, 3, True, 64)
    assert pm.KERNEL_VARIANT in kkey, kkey
    assert pm.KERNEL_VARIANT not in m._jit_key(0, 3, False, 64)
    # two Mapper incarnations over one map still key apart (the
    # per-incarnation token survives beside the variant tag)
    m2 = Mapper(_two_rule_map(56), block=1 << 10)
    assert m2._jit_key(0, 3, True, 64) != kkey


def test_degraded_mapper_keeps_counting_mismatches():
    """A Mapper whose fused kernel failed mid-run stays pinned to the
    engine it PROMISED ('pallas') under devmon_expected_engine=auto:
    every later sweep keeps counting a mismatch — the baseline must
    not silently re-heal to the fallback engine (the 34x-slower
    silent-degradation case the plane exists to catch)."""
    dm = devmon()
    # reprobe pinned far out: this test is about the PINNED baseline,
    # not the round-16 re-probe cycle (covered below) — a default
    # 0.5s backoff could fire a probe mid-test on a slow host
    m = Mapper(_two_rule_map(56), block=1 << 10,
               config={"crush_kernel_reprobe_base": 3600.0})
    xs = np.arange(37, dtype=np.uint32)     # warm shape (cold test)
    assert m.expected_path(0, 3) == "xla"
    before = dm.perf.dump()["path_mismatch"]
    m.map_pgs(0, xs, 3)                     # healthy: no mismatch
    assert dm.perf.dump()["path_mismatch"] == before
    # simulate the kernel-failure degrade discipline
    m._disable_kernel("unit", RuntimeError("injected"))
    assert m.expected_path(0, 3) == "pallas"
    m.map_pgs(0, xs, 3)
    m.map_pgs(0, xs, 3)
    assert dm.perf.dump()["path_mismatch"] == before + 2
    # hygiene: drop this mapper's quarantine token so later tests see
    # clean gauges (the token table is process-global)
    dm.set_quarantine_state(m._devmon_token, None)


# -- round 16: warm-set eviction, fault injection, kernel quarantine --------

def test_warm_set_evicts_oldest_only(monkeypatch):
    """At _WARM_MAX the warm set evicts the OLDEST key only — the
    pre-round-16 full clear made every concurrently-live jit look
    cold again on its next call, spiking jit_compiles (and minting
    phantom compile spans) across the board."""
    from ceph_tpu.utils import devmon as devmon_mod
    monkeypatch.setattr(devmon_mod, "_WARM_MAX", 3)
    dm = DeviceRuntimeMonitor(name="devmon_unit_warm", register=False)
    for i in range(3):
        dm.jit_call("f", (i,), lambda: i)
    assert dm.perf.dump()["jit_compiles"] == 3
    # 4th distinct key evicts ONLY ("f", (0,))
    dm.jit_call("f", (3,), lambda: 3)
    assert dm.perf.dump()["jit_compiles"] == 4
    # keys 1..3 are still warm: no new compiles
    for i in (1, 2, 3):
        dm.jit_call("f", (i,), lambda: i)
    assert dm.perf.dump()["jit_compiles"] == 4
    # the evicted oldest re-counts (evicting ("f",(1,)) in turn)
    dm.jit_call("f", (0,), lambda: 0)
    assert dm.perf.dump()["jit_compiles"] == 5


def test_device_fault_injection_at_jit_call():
    """The devmon chokepoint honors device FaultRules: jit_fail
    raises before warm bookkeeping (the retry's compile still
    counts), bad_result corrupts the completed array, count bounds a
    rule to its first N firings, and key patterns target by jit-key
    string."""
    from ceph_tpu.sim import faults as F
    from ceph_tpu.utils import devmon as devmon_mod
    inj = F.FaultInjector(seed=3)
    inj.install("dev", [
        F.jit_fail("ec_encode", count=1),
        F.bad_result("crush_map_pgs", key="*'kern'*", count=1),
    ])
    dm = DeviceRuntimeMonitor(name="devmon_unit_fi", register=False)
    devmon_mod.set_fault_injector(inj)
    try:
        # fn-name pattern: only ec_encode fails, and only once
        with pytest.raises(RuntimeError, match="injected device"):
            dm.jit_call("ec_encode", ("xla", 1), lambda: "never")
        assert dm.jit_call("ec_encode", ("xla", 1), lambda: "ok") \
            == "ok"
        # the failed first call un-warmed: the retry counted a compile
        assert dm.perf.dump()["jit_compiles"] == 1
        # key pattern: the xla-keyed call passes clean...
        clean = dm.jit_call("crush_map_pgs", ("xla", 4),
                            lambda: np.arange(6))
        assert np.array_equal(clean, np.arange(6))
        # ...the kern-keyed call is corrupted (one element flipped)
        bad = dm.jit_call("crush_map_pgs", ("kern", "v", 4),
                          lambda: np.arange(6))
        assert bad.shape == (6,) and \
            not np.array_equal(bad, np.arange(6))
        assert int((bad != np.arange(6)).sum()) == 1
        # count exhausted: clean again
        ok = dm.jit_call("crush_map_pgs", ("kern", "v", 4),
                         lambda: np.arange(6))
        assert np.array_equal(ok, np.arange(6))
        assert dm.perf.dump()["faults_injected"] == 2
    finally:
        devmon_mod.set_fault_injector(None)


def _quarantine_mapper(fake_kernel, **knobs):
    """A Mapper whose 'kernel' is a stand-in jax fn — the quarantine
    state machine is exercised without paying interpret-mode compiles
    (the REAL kernel cycle runs in the device_storm acceptance and in
    test_pallas_mapper's interpret suite)."""
    cfg = {"crush_kernel_reprobe_base": 0.0,
           "crush_kernel_reprobe_max": 0.0,
           "crush_kernel_reprobe_disable_after": 3}
    cfg.update(knobs)
    m = Mapper(_two_rule_map(56), block=1 << 10, config=cfg)
    fn = fake_kernel(m)
    # gate on _kernel_mode like the real body: while quarantined
    # (mode None) the serving path must see NO kernel and ride XLA
    m._kernel_body = lambda ruleno, result_max: (
        fn if m._kernel_mode is not None else None)
    m._kernel_mode = "interpret"
    return m


def test_kernel_quarantine_reprobe_cycle():
    """fail -> quarantined (XLA serves the SAME call) -> the due
    probe runs the kernel on a sample, matches the serving path
    bit-exact, and RE-PROMOTES: expected_path returns to pallas, the
    serving output is unchanged, and the devmon records the full
    enter/probe/exit cycle."""
    dm = devmon()
    before = dm.perf.dump()
    # the stand-in kernel IS the serving rule fn: bit-exact trivially
    m = _quarantine_mapper(lambda m: m._rule_fn(0, 3))
    xs = np.arange(37, dtype=np.uint32)
    ref = np.asarray(m.map_pgs(0, xs, 3))

    m._disable_kernel("unit", RuntimeError("injected"))
    info = m.kernel_quarantine_info()
    assert info == {"state": "quarantined", "failures": 1,
                    "next_probe_in_s": 0.0}
    assert m.expected_path(0, 3) == "pallas"   # the promise holds
    # base=0: the next fresh call probes, passes, and re-promotes
    out, path = m.map_pgs_path(0, xs, 3)
    assert m.kernel_quarantine_info() is None
    assert path == "pallas-interpret", path
    assert np.array_equal(np.asarray(out), ref)
    after = dm.perf.dump()
    assert after["quarantine_entries"] - \
        before.get("quarantine_entries", 0) == 1
    assert after["quarantine_exits"] - \
        before.get("quarantine_exits", 0) == 1
    assert after["quarantine_probes"] - \
        before.get("quarantine_probes", 0) == 1
    assert after["quarantine_probe_failures"] == \
        before.get("quarantine_probe_failures", 0)
    # this mapper's enter/exit netted zero on the live gauge
    assert after["quarantined_now"] == before.get("quarantined_now", 0)


def test_kernel_quarantine_permanent_after_disable_after():
    """A kernel that keeps LYING (probe output mismatches the serving
    path) can never re-promote: each probe fails, backoff doubles,
    and after crush_kernel_reprobe_disable_after consecutive failures
    the quarantine goes permanent — no further probes, XLA serves
    forever, the devmon gauge says so."""
    import jax.numpy as jnp
    dm = devmon()
    m = _quarantine_mapper(
        lambda m: (lambda arrays, xs:
                   jnp.full((xs.shape[0], 3), -1, jnp.int32)))
    xs = np.arange(37, dtype=np.uint32)
    # the honest reference comes from the serving XLA path — the
    # stand-in kernel LIES by construction
    m._kernel_mode = None
    ref = np.asarray(m.map_pgs(0, xs, 3))
    m._kernel_mode = "interpret"
    m._disable_kernel("unit", RuntimeError("injected"))
    probes0 = dm.perf.dump()["quarantine_probes"]
    # failures 2 and 3: each call probes, mismatches, re-quarantines
    out, path = m.map_pgs_path(0, xs, 3)
    assert path == "xla" and np.array_equal(np.asarray(out), ref)
    assert m.kernel_quarantine_info()["state"] == "reprobing" or \
        m.kernel_quarantine_info()["failures"] == 2
    m.map_pgs(0, xs, 3)
    info = m.kernel_quarantine_info()
    assert info["state"] == "permanent"
    assert info["failures"] == 3
    assert info["next_probe_in_s"] is None
    # permanent: no more probes, ever
    m.map_pgs(0, xs, 3)
    d = dm.perf.dump()
    assert d["quarantine_probes"] - probes0 == 2
    assert d["quarantine_probe_failures"] >= 2
    assert d["quarantine_permanent_now"] >= 1
    assert m.expected_path(0, 3) == "pallas"   # still the promise
    # hygiene: clear the permanent entry so later tests see clean
    # gauges (the token table is process-global)
    dm.set_quarantine_state(m._devmon_token, None)


def test_pre_append_mpgstats_blobs_decode_zero_filled():
    """MPGStats blobs encoded BEFORE the round-14 append
    (device_health/device_engine) — reconstructed by stripping the
    empty appended containers in front of the trace context — decode
    with the new fields empty (the zero-fill discipline; the round-11
    peer_latency pin's round-14 counterpart)."""
    from ceph_tpu.mon.messages import MPGStats
    from ceph_tpu.msg.message import Message
    m = MPGStats(osd=1, epoch=2, stats={"1.0": b"x"}, slow_ops=3,
                 used_bytes=4, capacity_bytes=5, trace_spans=[b"s"],
                 peer_latency={"3": 1200}, device_health={},
                 device_engine="")
    blob = m.encode()
    assert blob[-16:] == b"\x00" * 16
    # empty map (u32 count) + empty str (u32 len) = 8 bytes
    old = blob[:-24] + blob[-16:]
    m2 = Message.decode(old)
    assert m2.device_health == {} and m2.device_engine == ""
    assert m2.peer_latency == {"3": 1200} and m2.slow_ops == 3
    # and the populated fields round-trip
    m.device_health = {"checks": 5, "mismatches": 2}
    m.device_engine = "tpu"
    again = Message.decode(m.encode())
    assert again.device_health == {"checks": 5, "mismatches": 2}
    assert again.device_engine == "tpu"


def test_cli_device_and_crash_verbs_parse():
    """New CLI verbs parse to their mon prefixes; the read-only ones
    are pinned in the read-only cap class, archive is not."""
    from ceph_tpu.bench.ceph_cli import _parse_command
    from ceph_tpu.mon.auth_monitor import READONLY_COMMANDS
    for words, prefix in [
            (["device-runtime", "status"], "device-runtime status"),
            (["crash", "ls"], "crash ls")]:
        cmd, _ = _parse_command(words)
        assert cmd["prefix"] == prefix
        assert prefix in READONLY_COMMANDS
    cmd, _ = _parse_command(["crash", "info", "x.1"])
    assert cmd == {"prefix": "crash info", "id": "x.1"}
    assert "crash info" in READONLY_COMMANDS
    cmd, _ = _parse_command(["crash", "archive", "x.1"])
    assert cmd["prefix"] == "crash archive"
    assert "crash archive" not in READONLY_COMMANDS   # it mutates


# -- the shared-cluster acceptance run --------------------------------------

DEVMON_CFG = {
    # the deployment contract under test: daemons EXPECT pallas but
    # (CPU test backend) actually serve xla — every sweep mismatches
    "devmon_expected_engine": "pallas",
    "mgr_stats_singleton_fallback": False,
    "mgr_stats_period": 0.2,
    "mon_kernel_path_confirm": 2,
    "mon_kernel_path_degraded_ratio": 0.5,
}


async def _health_checks(c):
    ret, _, out = await c.client.mon_command({"prefix": "health"})
    assert ret == 0
    return json.loads(out)["health"]["checks"]


async def _make_pool(c, name):
    """One pool creation = one new-pool full sweep (a path check) on
    every OSD's tracked mapping table."""
    await c.client.pool_create(name, pg_num=4, size=2)


def test_kernel_path_degraded_and_crash_cluster(tmp_path):
    """The tentpole acceptance run on ONE cluster: knob-forced
    expected-engine mismatch -> per-daemon counters -> /metrics row
    from reported state -> KERNEL_PATH_DEGRADED trips after the
    confirm debounce -> heals on knob flip; then crash capture ->
    RECENT_CRASH -> archive clears."""
    async def go():
        from ceph_tpu.cluster.vstart import Cluster
        from ceph_tpu.mgr.modules import PrometheusModule
        c = await Cluster(
            n_mons=1, n_osds=2, n_mgrs=1,
            config=dict(DEVMON_CFG),
            mgr_modules=[PrometheusModule]).start()
        try:
            await c.client.pool_create("d0", pg_num=4, size=2)
            await c.wait_for_clean(timeout=120)

            # every OSD's first tracked-table build swept pool d0 with
            # expected=pallas, actual=xla -> counted mismatch
            for osd in c.osds:
                d = osd.devmon.perf.dump()
                assert d["path_checks"] >= 1, d
                assert d["path_mismatch"] >= 1, d
                assert d["launches_xla"] >= 1, d

            # keep sweep traffic flowing (one pool per report window)
            # until the mon's per-report delta debounce confirms
            deadline = asyncio.get_event_loop().time() + 60
            i = 0
            while True:
                if "KERNEL_PATH_DEGRADED" in await _health_checks(c):
                    break
                assert asyncio.get_event_loop().time() < deadline, \
                    "KERNEL_PATH_DEGRADED never tripped"
                i += 1
                await _make_pool(c, f"kp-{i}")
                await asyncio.sleep(0.45)

            # the degraded table + CLI view
            ret, _, out = await c.client.mon_command(
                {"prefix": "device-runtime status"})
            assert ret == 0
            drs = json.loads(out)
            assert drs["degraded"], drs
            row = drs["daemons"].get("osd.0")
            assert row is not None, drs
            assert row["engine"] == "cpu"
            assert row["mismatches"] >= 1
            assert row["mismatch_ratio"] > 0.0
            assert row["launches"]["xla"] >= 1

            # /metrics: the mismatch row is built from REPORTED state
            # (singleton fallback disabled), per acceptance
            mgr = c.active_mgr()
            pm = next(m for m in mgr.modules
                      if m.NAME == "prometheus")
            deadline = asyncio.get_event_loop().time() + 30
            while True:
                text = await pm.render()
                rows = {}
                for line in text.splitlines():
                    if line.startswith(
                            "ceph_device_path_mismatch_total{"):
                        lab, val = line.rsplit(" ", 1)
                        rows[lab] = float(val)
                if rows.get('ceph_device_path_mismatch_total'
                            '{ceph_daemon="osd.0"}', 0) > 0:
                    break
                assert asyncio.get_event_loop().time() < deadline, \
                    f"mismatch row never appeared: {rows}"
                await asyncio.sleep(0.2)
            assert 'ceph_device_jit_compiles_total{' in text
            assert 'ceph_device_path_degraded{osd="0"' in text or \
                'ceph_device_path_degraded{osd="1"' in text
            # singleton render's label key never appears
            assert 'ceph_perf{daemon=' not in text

            # -- heal: flip the shared LIVE knob back to auto; clean
            # sweep reports clear the warning after the same confirm
            c.cfg["devmon_expected_engine"] = "auto"
            deadline = asyncio.get_event_loop().time() + 60
            while True:
                if "KERNEL_PATH_DEGRADED" not in \
                        await _health_checks(c):
                    break
                assert asyncio.get_event_loop().time() < deadline, \
                    "KERNEL_PATH_DEGRADED never cleared after heal"
                i += 1
                await _make_pool(c, f"kp-{i}")
                await asyncio.sleep(0.45)

            # the entry/exit pair is a symmetric clog discipline:
            # WRN on confirm, INF through the SAME debounce on heal
            ret, _, out = await c.client.mon_command(
                {"prefix": "log last", "num": 200})
            assert ret == 0
            lines = json.loads(out)["lines"]
            assert any(ln["level"] == "WRN" and
                       "kernel path degraded" in ln["msg"]
                       for ln in lines), lines
            assert any(ln["level"] == "INF" and
                       "kernel path healed" in ln["msg"]
                       for ln in lines), lines

            # -- crash capture on the same cluster --------------------
            from ceph_tpu.utils import crash as crash_mod
            osd = c.osds[0]

            async def _boom():
                raise RuntimeError("synthetic crash (devmon test)")

            crash_mod.watch(asyncio.ensure_future(_boom()),
                            "osd.0", osd.monc, where="unit_probe")
            deadline = asyncio.get_event_loop().time() + 20
            while True:
                ret, _, out = await c.client.mon_command(
                    {"prefix": "crash ls"})
                assert ret == 0
                crashes = json.loads(out)["crashes"]
                if crashes:
                    break
                assert asyncio.get_event_loop().time() < deadline, \
                    "crash report never reached the mon"
                await asyncio.sleep(0.1)
            rep = crashes[-1]
            assert rep["daemon"] == "osd.0"
            assert "synthetic crash" in rep["exception"]
            assert "traceback" not in rep          # ls is the summary
            assert not rep["archived"]
            assert "RECENT_CRASH" in await _health_checks(c)
            # info serves the bounded traceback
            ret, _, out = await c.client.mon_command(
                {"prefix": "crash info", "id": rep["crash_id"]})
            assert ret == 0
            info = json.loads(out)
            assert "RuntimeError" in info["traceback"]
            assert len(info["traceback"]) <= 4000
            # the local ring kept it too (the asok/debug view)
            assert any(r["crash_id"] == rep["crash_id"]
                       for r in crash_mod.recent_crashes())
            # archive acks: the warning clears, the record stays
            ret, rs, _ = await c.client.mon_command(
                {"prefix": "crash archive", "id": rep["crash_id"]})
            assert ret == 0, rs
            assert "RECENT_CRASH" not in await _health_checks(c)
            ret, _, out = await c.client.mon_command(
                {"prefix": "crash ls"})
            assert json.loads(out)["crashes"][-1]["archived"] is True

            # the asok device block serves the daemon+process views
            status = osd.devmon.dump()
            assert status["expected_engine"] == "auto"
            assert status["counters"]["path_mismatch"] >= 1
        finally:
            await c.stop()
    run(go())


def test_device_storm_cluster():
    """The round-16 acceptance run: jit_fail / jit_stall / bad_result
    bursts at the devmon chokepoint under concurrent replicated + EC
    client writes — ZERO client-visible errors, counters prove the
    kernel path was quarantined AND re-promoted (not just degraded),
    a poisoned EC encode is absorbed by the degrade ladder, and every
    acked byte reads back bit-identical on settle."""
    async def go():
        from ceph_tpu.cluster.vstart import Cluster
        from ceph_tpu.sim.thrasher import Thrasher
        c = await Cluster(n_mons=1, n_osds=4,
                          config={"mon_osd_down_out_interval": 2.0}
                          ).start()
        try:
            await c.client.pool_create("rp", pg_num=4, size=2)
            ret, rs, _ = await c.client.mon_command(
                {"prefix": "osd erasure-code-profile set",
                 "name": "kprof",
                 "profile": ["k=2", "m=1", "crush-failure-domain=osd",
                             "stripe_unit=1024"]})
            assert ret == 0, rs
            ret, rs, _ = await c.client.mon_command(
                {"prefix": "osd pool create", "pool": "ecpool",
                 "pg_num": 4, "pool_type": "erasure",
                 "erasure_code_profile": "kprof"})
            assert ret == 0, rs
            await c.wait_for_clean(timeout=240)
            io = await c.client.open_ioctx("rp")
            io_ec = await c.client.open_ioctx("ecpool")

            th = Thrasher(c, seed=16, min_live_osds=4)
            summary = await th.device_storm(io, io_ec, ec_writes=6)

            # zero client-visible errors is asserted INSIDE the storm;
            # the counters prove the full quarantine cycle happened
            assert summary["write_errors"] == 0
            assert summary["ec_writes_acked"] == 6
            assert summary["quarantine_entries"] >= 1
            assert summary["quarantine_exits"] >= 1
            assert summary["probes"] >= 2           # refused + clean
            assert summary["probe_failures"] >= 1   # the bad_result
            assert summary["repromoted_path"] == "pallas-interpret"
            assert summary["ec_degraded_ops"] >= 1  # ladder engaged
            assert summary["faults_injected"] >= 2
            await th.settle_and_verify(io)

            # the quarantine evidence reached the mon's status surface
            ret, _, out = await c.client.mon_command(
                {"prefix": "device-runtime status"})
            assert ret == 0
            drs = json.loads(out)
            row = drs["daemons"].get("osd.0")
            assert row is not None and "quarantine" in row, drs
        finally:
            await c.stop()
    run(go())
