"""Elastic control plane: runtime monmap membership + auth lifecycle.

ref test model: qa/workunits/mon + the MonmapMonitor/AuthMonitor
surfaces — a cluster serving live traffic must grow/shrink its mon
quorum at runtime (`ceph mon add/rm`, re-election through the
committed map), provision/rotate/revoke keys through the AuthMonitor
(revocation FENCES live sessions), and keep a paxos-ordered cluster
log. Round-6 VERDICT items: weak #4 (no runtime monmap change),
missing #3 (no AuthMonitor).
"""

import asyncio
import json

import pytest

from ceph_tpu.cluster.vstart import Cluster
from ceph_tpu.msg import Keyring
from ceph_tpu.rados import Rados
from ceph_tpu.sim.thrasher import Thrasher


def run(coro):
    asyncio.run(coro)


async def _pool_io(c, name="data", pg_num=4, size=2):
    await c.client.pool_create(name, pg_num=pg_num, size=size,
                               min_size=1)
    await c.wait_for_clean(timeout=240)
    return await c.client.open_ioctx(name)


def test_runtime_mon_membership_and_rotation():
    """One cluster, the whole membership lifecycle: mon add -> quorum
    of 3; kill the leader -> re-election among the 3-member map; mon
    rm the corpse -> healthy 2-mon map; then remove the LAST boot mon
    too, fully rotating the set away from the client's boot-time
    address list (the round-6 MonClient bugfix regression). Client
    I/O and commands flow through every transition, and the cluster
    log records the membership events."""
    async def go():
        c = await Cluster(n_mons=2, n_osds=3).start()
        try:
            io = await _pool_io(c)
            boot_mons = set(c.monmap.mons)      # {a, b}
            await io.write_full("before", b"b4")
            # grow to 3 at runtime
            mon = await c.add_mon()
            q = await c.wait_for_quorum(3)
            assert len(q["quorum"]) == 3
            assert q["monmap_epoch"] >= 2
            await io.write_full("with-3-mons", b"3m")
            # kill the leader: survivors re-elect under the 3-map
            killed = await c.kill_mon_leader()
            assert killed is not None
            c.mons.remove(killed)
            q = await c.wait_for_quorum(2, timeout=30)
            assert killed.name not in q["quorum_names"]
            await io.write_full("after-leader-kill", b"ok")
            # heal the map: remove the corpse
            await c.rm_mon(killed.name)
            ret, _, out = await c.client.mon_command(
                {"prefix": "mon dump"})
            assert ret == 0
            dump = json.loads(out)
            assert killed.name not in dump["mons"]
            assert len(dump["mons"]) == 2
            assert dump["epoch"] >= 3
            # health reflects a full quorum again (no MON_DOWN)
            status = await c.client.status()
            assert "MON_DOWN" not in status["health"]["checks"]
            assert status["monmap"]["epoch"] == dump["epoch"]
            # the paxos-ordered cluster log recorded the transitions
            # (clog is fire-and-forget: appended entries may trail a
            # post-membership-change election — poll briefly)
            want = [f"mon.{mon.name} added",
                    f"mon.{killed.name} removed", "booted"]
            deadline = asyncio.get_event_loop().time() + 25.0
            while True:
                ret, _, out = await c.client.mon_command(
                    {"prefix": "log last", "num": 100})
                assert ret == 0
                msgs = [ln["msg"]
                        for ln in json.loads(out)["lines"]]
                if all(any(w in m for m in msgs) for w in want):
                    break
                assert asyncio.get_event_loop().time() < deadline, \
                    f"cluster log missing {want}: {msgs}"
                await asyncio.sleep(0.2)
            # FULL ROTATION: remove the remaining boot mon as well —
            # the surviving set is disjoint from the client's boot
            # address list, so only monmap-following keeps it served
            for name in sorted(boot_mons - {killed.name}):
                await c.rm_mon(name)
            q = await c.wait_for_quorum(1)
            assert q["quorum_names"] == [mon.name]
            assert set(c.client.monc.monmap.mons) == {mon.name}
            await io.write_full("rotated", b"still-served")
            assert await io.read("rotated") == b"still-served"
            # data written across every transition is intact
            for oid, data in [("before", b"b4"), ("with-3-mons", b"3m"),
                              ("after-leader-kill", b"ok"),
                              ("rotated", b"still-served")]:
                assert await io.read(oid) == data

            # -- auth lifecycle, SAME cluster (tier-1 budget: one
            # boot pays for both surfaces) ---------------------------
            # provision
            ret, rs, out = await c.client.mon_command(
                {"prefix": "auth get-or-create",
                 "entity": "client.app",
                 "caps": json.dumps({"osd": "rw"})})
            assert ret == 0, rs
            ent = json.loads(out)
            key = bytes.fromhex(ent["key"])
            assert ent["caps"] == {"osd": "rw"}
            # get-or-create is idempotent: same key back
            ret, _, out = await c.client.mon_command(
                {"prefix": "auth get-or-create",
                 "entity": "client.app"})
            assert json.loads(out)["key"] == ent["key"]
            app = Rados(c.monmap, name="client.app",
                        keyring=Keyring({"client.app": key}))
            await app.connect()
            aio = await app.open_ioctx("data")
            await aio.write_full("app-1", b"provisioned")
            # listed
            ret, _, out = await c.client.mon_command(
                {"prefix": "auth ls"})
            listing = json.loads(out)
            assert "client.app" in listing["keys"]
            # rotate the ADMIN key under its own live session: the
            # session keeps serving; a client pinning the OLD secret
            # can no longer handshake
            old_admin = c.keyring.get("client.admin")
            ret, rs, _ = await c.client.mon_command(
                {"prefix": "auth rotate", "entity": "client.admin"})
            assert ret == 0, rs
            assert c.keyring.get("client.admin") != old_admin
            await io.write_full("after-rotate", b"live")
            stale = Rados(c.monmap, name="client.admin2",
                          keyring=Keyring({"client.admin2": b"x" * 32}))
            with pytest.raises(Exception):
                await asyncio.wait_for(stale.connect(), timeout=3.0)
            await stale.shutdown()
            # revoke client.app: live session fenced, handshake refused
            ret, rs, _ = await c.client.mon_command(
                {"prefix": "auth rm", "entity": "client.app"})
            assert ret == 0, rs
            with pytest.raises(Exception):
                await aio.write_full("app-2", b"nope", timeout=4.0)
            await app.shutdown()
            # surfaced: key count + recent-revocation health
            status = await c.client.status()
            assert status["auth"]["num_keys"] >= 1
            checks = status["health"]["checks"]
            assert "AUTH_KEY_REVOKED" in checks
            assert "client.app" in checks["AUTH_KEY_REVOKED"]["summary"]
            ret, _, out = await c.client.mon_command(
                {"prefix": "auth ls"})
            listing = json.loads(out)
            assert "client.app" not in listing["keys"]
            assert "client.app" in listing["revoked"]
            # acked data written by the revoked client survives
            assert await io.read("app-1") == b"provisioned"
        finally:
            await c.stop()
    run(go())


def test_concurrent_monmap_changes_serialized_with_eagain():
    """ROADMAP elastic follow-up (d): a second `mon add/rm` while one
    membership change is mid-proposal returns -EAGAIN with a clear
    message instead of racing the election. Deterministic: start the
    first command, yield until its proposal lock is held, then issue
    the second inline."""
    async def go():
        c = await Cluster(n_mons=2, n_osds=3).start()
        try:
            lead = c.leader()
            assert lead is not None
            # two prebound joiners (the command requires a live addr)
            from ceph_tpu.mon.monitor import Monitor
            joiners = []
            for name in ("x", "y"):
                ret, rs, _ = await c.client.mon_command(
                    {"prefix": "auth get-or-create",
                     "entity": f"mon.{name}"})
                assert ret == 0, rs
                prov = c.monmap.clone()
                prov.add(name, prov.next_rank(), "127.0.0.1", 0)
                m = Monitor(name, prov, keyring=c.keyring,
                            config=c.cfg)
                addr = await m.msgr.bind()
                prov.mons[name] = (prov.rank_of_name(name),
                                   addr.host, addr.port)
                joiners.append((m, addr))
            t1 = asyncio.ensure_future(lead.handle_command(
                {"prefix": "mon add", "name": "x",
                 "host": joiners[0][1].host,
                 "port": joiners[0][1].port}))
            for _ in range(200):
                if lead.monmapmon._lock.locked():
                    break
                await asyncio.sleep(0)
            assert lead.monmapmon._lock.locked(), \
                "first mon add never reached its proposal"
            ret2, rs2, _ = await lead.handle_command(
                {"prefix": "mon add", "name": "y",
                 "host": joiners[1][1].host,
                 "port": joiners[1][1].port})
            assert ret2 == -11, (ret2, rs2)          # -EAGAIN
            assert "in progress" in rs2, rs2
            ret1, rs1, _ = await t1
            assert ret1 == 0, rs1
            # the refused change retries fine once the first settled
            c.mons.append(joiners[0][0])
            joiners[0][0]._tick_task = asyncio.ensure_future(
                joiners[0][0]._tick_loop())
            await joiners[0][0].elector.start()
            await c.wait_for_quorum(3, timeout=60)
            # mon rm mid-election is also refused: force electing state
            lead2 = c.leader()
            lead2.state = "electing"
            ret3, rs3, _ = await lead2.handle_command(
                {"prefix": "mon rm", "name": "y"})
            lead2.state = "leader"
            assert ret3 == -11 and "re-forming" in rs3, (ret3, rs3)
            await joiners[1][0].msgr.shutdown()
        finally:
            await c.stop()
    run(go())


def test_auth_cap_enforcement_first_slice():
    """ROADMAP elastic follow-up (a), first slice: the mon checks the
    CALLER's stored caps at the wire command entry. `mon r` can read
    but not mutate (-EACCES), `mon rw` can run `mon rm`, key ops need
    `auth *`, and legacy entities with no caps stay unrestricted."""
    async def go():
        c = await Cluster(n_mons=1, n_osds=3).start()
        try:
            io = await _pool_io(c)
            # provision a read-only and a rw entity
            for ent, caps in (("client.ro", {"mon": "allow r"}),
                              ("client.rw", {"mon": "allow rw",
                                             "auth": "allow *"})):
                ret, rs, out = await c.client.mon_command(
                    {"prefix": "auth get-or-create", "entity": ent,
                     "caps": json.dumps(caps)})
                assert ret == 0, rs
            keyfor = {}
            for ent in ("client.ro", "client.rw"):
                ret, _, out = await c.client.mon_command(
                    {"prefix": "auth get", "entity": ent})
                keyfor[ent] = bytes.fromhex(json.loads(out)["key"])
            ro = Rados(c.monmap, name="client.ro",
                       keyring=Keyring({"client.ro": keyfor["client.ro"]}))
            await ro.connect()
            # reads pass for allow r
            ret, rs, out = await ro.mon_command({"prefix": "status"})
            assert ret == 0, rs
            ret, rs, _ = await ro.mon_command(
                {"prefix": "mon dump"})
            assert ret == 0, rs
            # mutations refused: mon membership, pool edits, key ops
            ret, rs, _ = await ro.mon_command(
                {"prefix": "mon add", "name": "z",
                 "host": "127.0.0.1", "port": 1})
            assert ret == -13 and "permission denied" in rs \
                and "mon w" in rs, (ret, rs)
            ret, rs, _ = await ro.mon_command(
                {"prefix": "osd pool set", "pool": "data",
                 "var": "size", "val": "2"})
            assert ret == -13, (ret, rs)
            ret, rs, _ = await ro.mon_command(
                {"prefix": "auth get-or-create",
                 "entity": "client.sneaky"})
            assert ret == -13 and "auth *" in rs, (ret, rs)
            # even auth READS need an auth cap when caps are set
            ret, rs, _ = await ro.mon_command({"prefix": "auth ls"})
            assert ret == -13, (ret, rs)
            await ro.shutdown()
            # the rw entity mutates fine (ENOENT proves it got PAST
            # the cap gate), and auth * licenses key ops
            rw = Rados(c.monmap, name="client.rw",
                       keyring=Keyring({"client.rw": keyfor["client.rw"]}))
            await rw.connect()
            ret, rs, _ = await rw.mon_command(
                {"prefix": "mon rm", "name": "nonexistent"})
            assert ret == -2, (ret, rs)              # past the gate
            ret, rs, _ = await rw.mon_command(
                {"prefix": "auth get-or-create",
                 "entity": "client.minted"})
            assert ret == 0, rs
            await rw.shutdown()
            # legacy: the admin's imported boot key has no caps ->
            # unrestricted (the cluster's own lifecycle stays intact)
            ret, rs, _ = await c.client.mon_command(
                {"prefix": "auth rm", "entity": "client.minted"})
            assert ret == 0, rs
            await io.write_full("after-enforcement", b"ok")
        finally:
            await c.stop()
    run(go())


def test_elastic_storm_smoke():
    """The acceptance storm, smoke-sized: runtime mon add -> leader
    kill -> re-election -> mon rm, key provision/rotate/revoke with
    fencing, and a split-then-merge round-trip — all under concurrent
    client writes, ending settle-and-verify clean."""
    async def go():
        c = await Cluster(n_mons=2, n_osds=3).start()
        try:
            io = await _pool_io(c)
            t = Thrasher(c, seed=7, min_live_osds=3)
            res = await t.elastic_storm(io, writes=24,
                                        phase_timeout=90.0)
            assert set(res["phases"]) == {"mon_cycle", "auth_cycle",
                                          "split_merge"}
            assert res["acked_writes"] > 0
            summary = await t.settle_and_verify(io, timeout=240)
            assert summary["acked_writes"] == res["acked_writes"]
        finally:
            await c.stop()
    run(go())


@pytest.mark.slow
def test_elastic_storm_deep():
    """Deep variant: more writes, repeated split/merge cycling, and a
    second membership cycle."""
    async def go():
        c = await Cluster(n_mons=2, n_osds=4).start()
        try:
            io = await _pool_io(c, pg_num=8, size=3)
            t = Thrasher(c, seed=23, min_live_osds=3)
            res = await t.elastic_storm(io, writes=200,
                                        phase_timeout=120.0)
            assert set(res["phases"]) == {"mon_cycle", "auth_cycle",
                                          "split_merge"}
            # second split/merge cycle under the rotated control plane
            res2 = await t.elastic_storm(io, writes=260,
                                         mon_cycle=False,
                                         auth_cycle=False,
                                         phase_timeout=120.0)
            assert "split_merge" in res2["phases"]
            await t.settle_and_verify(io, timeout=300)
        finally:
            await c.stop()
    run(go())
