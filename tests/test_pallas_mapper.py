"""Fused Pallas CRUSH kernel: bit-exactness vs the scalar spec and the
XLA path (interpret mode on CPU; the same program runs compiled on TPU).

The kernel must agree with mapper_ref on every eligible map — including
engineered draw-tie collisions (the ln-equality repair), reweighted
devices (the compare-list is_out), and collision-heavy small maps where
replica slots contend (the shared candidate table + fallback flagging).
"""

import numpy as np
import pytest

import jax.numpy as jnp


@pytest.fixture(autouse=True)
def _interpret_mode(monkeypatch):
    """Interpret-mode kernel for THESE tests only — restored after
    each one. A module-level os.environ.setdefault here leaked
    interpret mode into the whole pytest process at collection time
    (imports happen before any test runs), silently routing EVERY
    cluster test's CRUSH mapping through the Pallas interpreter —
    ~3x total suite wall time and mass not-clean timeouts on a loaded
    host."""
    monkeypatch.setenv("CEPH_TPU_CRUSH_KERNEL", "interpret")

from ceph_tpu.crush import builder, mapper_ref
from ceph_tpu.crush import pallas_mapper as pm
from ceph_tpu.crush.mapper import Mapper
from ceph_tpu.crush.tensors import pack_map
from ceph_tpu.crush.types import ITEM_NONE, WEIGHT_ONE

N_X = 192


def _assert_kernel_matches_ref(m, rid, numrep, weights=None, xs=None):
    mapper = Mapper(m, np.asarray(weights, dtype=np.int64)
                    if weights is not None else None)
    assert mapper._kernel_mode == "interpret"
    assert mapper._kernel_body(rid, numrep) is not None, \
        "map unexpectedly ineligible for the kernel"
    xs = xs if xs is not None else np.arange(N_X, dtype=np.uint32)
    got = np.asarray(mapper.map_pgs(rid, xs, numrep))
    wl = list(weights) if weights is not None else None
    for i, x in enumerate(xs):
        ref = mapper_ref.do_rule(m, rid, int(x), numrep, weight=wl)
        ref = ref + [ITEM_NONE] * (numrep - len(ref))
        assert list(got[i]) == ref, (int(x), list(got[i]), ref)


def _hier(n_hosts, per_host, n_racks=None):
    m, root = builder.build_hierarchy(
        n_hosts, per_host,
        n_racks=n_racks if n_racks else max(1, n_hosts // 4))
    rid = builder.add_simple_rule(m, root, builder.TYPE_HOST)
    return m, rid


class TestEligibility:
    def test_canonical_map_eligible(self):
        m, rid = _hier(16, 4)
        p = pack_map(m)
        assert pm.build_plan(m, p, rid, None) is not None

    def test_mixed_weights_eligible(self):
        """Round 5: buckets with few distinct weights ride the kernel
        via the weight-class draw (was ineligible through round 4)."""
        m, root = builder.build_flat(
            8, weights=[WEIGHT_ONE] * 7 + [2 * WEIGHT_ONE])
        rid = builder.add_simple_rule(m, root, builder.TYPE_OSD)
        plan = pm.build_plan(m, pack_map(m), rid, None)
        assert plan is not None and plan.kmax == (2,)

    def test_continuous_weights_eligible(self):
        """Round 6 regression: a bucket with MORE than MAX_CLASSES
        distinct weights (the continuous balancer weight-set shape)
        now rides the kernel's per-slot draw instead of gating the
        whole map onto the XLA path (kmax == 0 marks the level)."""
        m, root = builder.build_flat(
            8, weights=[WEIGHT_ONE + i for i in range(8)])
        rid = builder.add_simple_rule(m, root, builder.TYPE_OSD)
        plan = pm.build_plan(m, pack_map(m), rid, None)
        assert plan is not None and plan.kmax == (0,)

    def test_continuous_choose_args_eligible(self):
        """The headline cliff case: a single-position choose_args
        weight-set with every slot perturbed (>4 distinct weights per
        bucket) must yield a kernel plan."""
        from ceph_tpu.crush.types import ChooseArg
        m, rid = _hier(8, 8, n_racks=2)
        rng = np.random.default_rng(11)
        args = {}
        for bid, b in m.buckets.items():
            scale = rng.uniform(0.9, 1.1, size=b.size)
            args[bid] = ChooseArg(weight_set=[[
                max(1, int(w * s))
                for w, s in zip(b.weights, scale)]])
        m.choose_args[0] = args
        plan = pm.build_plan(m, pack_map(m), rid, None,
                             choose_args_key=0)
        assert plan is not None and 0 in plan.kmax

    def test_overweight_class_takes_continuous_draw(self):
        """A weight above the ln-gap license G voids the within-class
        argmax argument — the per-slot draw (which needs no license)
        absorbs it instead of declining the map."""
        from ceph_tpu.crush.ln_table import ln_gap_info
        G, _ = ln_gap_info()
        m, root = builder.build_flat(4, weights=[G + 1] * 4)
        rid = builder.add_simple_rule(m, root, builder.TYPE_OSD)
        plan = pm.build_plan(m, pack_map(m), rid, None)
        assert plan is not None and plan.kmax == (0,)

    def test_huge_weight_ineligible(self):
        """Weights past the two-15-bit-halves table split still
        decline (nothing real gets here: 2^30 is ~16Ki disks)."""
        m, root = builder.build_flat(
            4, weights=[pm.MAX_CONT_WEIGHT + i for i in range(4)])
        rid = builder.add_simple_rule(m, root, builder.TYPE_OSD)
        assert pm.build_plan(m, pack_map(m), rid, None) is None

    def test_wide_continuous_bucket_ineligible(self):
        """A continuous bucket wider than MAX_CONT_SLOTS declines:
        the per-slot ladder unrolls at compile time, so an unbounded
        flat continuous root would trade the old 34x runtime cliff
        for a compile-time one."""
        n = pm.MAX_CONT_SLOTS + 1
        m, root = builder.build_flat(
            n, weights=[WEIGHT_ONE + i for i in range(n)])
        rid = builder.add_simple_rule(m, root, builder.TYPE_OSD)
        assert pm.build_plan(m, pack_map(m), rid, None) is None

    def test_wide_uniform_sibling_of_continuous_ineligible(self):
        """The ladder unrolls over the LEVEL's padded width S (the
        stratum's max bucket size), not each continuous bucket's own
        size: a small continuous host sharing a stratum with a wide
        uniform host must decline, or the compile-time cliff comes
        back through the sibling."""
        from ceph_tpu.crush.types import CrushMap, Tunables
        from ceph_tpu.crush.builder import (
            DEFAULT_TYPE_NAMES, make_bucket)
        wide = pm.MAX_CONT_SLOTS + 8
        m = CrushMap(tunables=Tunables(),
                     type_names=dict(DEFAULT_TYPE_NAMES))
        m.max_devices = 8 + wide
        cont = make_bucket(
            m, builder.TYPE_HOST, list(range(8)),
            [WEIGHT_ONE + 917 * i for i in range(8)], name="h-cont")
        uni = make_bucket(
            m, builder.TYPE_HOST, list(range(8, 8 + wide)),
            [WEIGHT_ONE] * wide, name="h-uni")
        root = make_bucket(m, builder.TYPE_ROOT, [cont, uni],
                           name="root")
        rid = builder.add_simple_rule(m, root, builder.TYPE_HOST)
        assert pm.build_plan(m, pack_map(m), rid, None) is None

    def test_choose_args_single_weight_set_eligible(self):
        from ceph_tpu.crush.types import ChooseArg
        m, rid = _hier(8, 2)
        args = {}
        for bid, b in m.buckets.items():
            args[bid] = ChooseArg(
                weight_set=[[2 * int(w) for w in b.weights]])
        m.choose_args[0] = args
        plan = pm.build_plan(m, pack_map(m), rid, None,
                             choose_args_key=0)
        assert plan is not None

    def test_choose_args_ids_override_ineligible(self):
        from ceph_tpu.crush.types import ChooseArg
        m, rid = _hier(8, 2)
        root = m.rules[rid].steps[0].arg1
        b = m.buckets[root]
        m.choose_args[0] = {root: ChooseArg(
            weight_set=[list(b.weights)],
            ids=[it + 100 for it in b.items])}
        assert pm.build_plan(m, pack_map(m), rid, None,
                             choose_args_key=0) is None

    def test_choose_args_positional_sets_ineligible(self):
        from ceph_tpu.crush.types import ChooseArg
        m, rid = _hier(8, 2)
        root = m.rules[rid].steps[0].arg1
        ws = [int(w) for w in m.buckets[root].weights]
        m.choose_args[0] = {root: ChooseArg(weight_set=[ws, ws])}
        assert pm.build_plan(m, pack_map(m), rid, None,
                             choose_args_key=0) is None

    def test_many_reweights_ineligible(self):
        m, rid = _hier(40, 4)                           # 160 devices
        dw = np.full(160, WEIGHT_ONE, dtype=np.int64)
        dw[:pm.MAX_REWEIGHT + 1] = WEIGHT_ONE // 2
        assert pm.build_plan(m, pack_map(m), rid, dw) is None

    def test_short_weight_vector_ineligible(self):
        """Device ids beyond the reweight vector would dodge the
        compare-list is_out: the kernel must decline."""
        m, rid = _hier(4, 4)
        dw = np.full(8, WEIGHT_ONE, dtype=np.int64)     # ids go to 15
        assert pm.build_plan(m, pack_map(m), rid, dw) is None

    @pytest.mark.slow
    def test_xla_fallback_when_ineligible(self):
        """Ineligible maps silently keep the XLA path through Mapper.
        (>4 distinct weights no longer disqualifies — round 6 — so the
        ineligible shape here is a weight past the table split.)"""
        m, root = builder.build_flat(
            6, weights=[pm.MAX_CONT_WEIGHT + i for i in range(6)])
        rid = builder.add_simple_rule(m, root, builder.TYPE_OSD)
        mapper = Mapper(m)
        assert mapper._kernel_body(rid, 3) is None
        out = np.asarray(mapper.map_pgs(
            0, np.arange(32, dtype=np.uint32), 3))
        for i in range(32):
            ref = mapper_ref.do_rule(m, rid, i, 3)
            assert list(out[i]) == ref + [ITEM_NONE] * (3 - len(ref))


class TestContinuousWeights:
    """Round 6: per-slot continuous draw — ONE tier-1 compile (the
    choose_args map, which exercises the same _choose_level_cont
    layout as plain continuous base weights), the flat variant and
    the deep randomized sweep live under slow (interpret-mode kernel
    compiles cost ~25 s each on the tier-1 CPU run)."""

    @pytest.mark.slow
    def test_flat_continuous_bit_exact(self):
        m, root = builder.build_flat(
            8, weights=[WEIGHT_ONE + 777 * i for i in range(8)])
        rid = builder.add_simple_rule(m, root, builder.TYPE_OSD)
        _assert_kernel_matches_ref(
            m, rid, 3, xs=np.arange(96, dtype=np.uint32))

    @pytest.mark.slow
    def test_single_live_slot_bucket_not_flagged(self):
        """Round-10 two-phase regression (slow: two interpret-mode
        kernel compiles; a flag-RATE pin, not a correctness gate — the
        tier-1 bit-exact suites cover single-slot buckets' results):
        a bucket with a SINGLE live
        slot at a continuous level has no second candidate — that must
        read as trivially unambiguous, not as d2==d1 flagging every
        lane that descends into it to the fallback (the lone-candidate
        k2 used to collapse onto k1)."""
        import numpy as np
        from ceph_tpu.crush.builder import (DEFAULT_TYPE_NAMES,
                                            make_bucket)
        from ceph_tpu.crush.types import CrushMap, Tunables
        m = CrushMap(tunables=Tunables(),
                     type_names=dict(DEFAULT_TYPE_NAMES))
        m.max_devices = 9
        cont = make_bucket(
            m, builder.TYPE_HOST, [0, 1, 2, 3, 4],
            [WEIGHT_ONE + 917 * i for i in range(5)], name="h-cont")
        singles = [make_bucket(m, builder.TYPE_HOST, [5 + i],
                               [WEIGHT_ONE], name=f"h-one{i}")
                   for i in range(4)]
        root = make_bucket(m, builder.TYPE_ROOT, [cont] + singles,
                           name="root")
        rid = builder.add_simple_rule(m, root, builder.TYPE_HOST)
        mapper = Mapper(m)
        plan = mapper._kernel_plan(rid)
        assert plan is not None and 0 in plan.kmax
        xs = jnp.asarray(np.arange(plan.lanes, dtype=np.int32))
        # numrep=1: no slot collisions are possible, so every flag
        # would be an ambiguity flag — ~83% of lanes send at least one
        # of the 3 candidates into a single-disk host, and none may
        # flag for that reason alone
        _, bad = pm._run_kernel(plan, xs, 1, interpret=True)
        assert np.asarray(bad).mean() < 0.02, np.asarray(bad).mean()
        _assert_kernel_matches_ref(m, rid, 2,
                                   xs=np.arange(64, dtype=np.uint32))

    def test_continuous_choose_args_bit_exact(self):
        """Single-position choose_args with EVERY slot perturbed (the
        upstream-balancer weight-set shape) vs the scalar spec.
        Smallest credible multi-level shape: the interpret-mode
        compile scales with the per-slot ladder unroll (S per cont
        level), and this is the one continuous compile tier-1 pays."""
        from ceph_tpu.crush.types import ChooseArg
        m, rid = _hier(4, 5, n_racks=2)
        rng = np.random.default_rng(23)
        args = {}
        for bid, b in m.buckets.items():
            scale = rng.uniform(0.9, 1.1, size=b.size)
            args[bid] = ChooseArg(weight_set=[[
                max(1, int(w * s))
                for w, s in zip(b.weights, scale)]])
        m.choose_args[0] = args
        mapper = Mapper(m, choose_args=0)
        assert mapper._kernel_body(rid, 3) is not None, "ineligible"
        assert 0 in mapper._kernel_plan(rid).kmax, "not continuous"
        xs = np.arange(64, dtype=np.uint32)
        got = np.asarray(mapper.map_pgs(rid, xs, 3))
        for i, x in enumerate(xs):
            ref = mapper_ref.do_rule(m, rid, int(x), 3,
                                     choose_args=args)
            ref = ref + [ITEM_NONE] * (3 - len(ref))
            assert list(got[i]) == ref, (int(x), list(got[i]), ref)


@pytest.mark.slow
class TestBitExact:
    def test_three_level_chooseleaf(self):
        m, rid = _hier(16, 4)
        _assert_kernel_matches_ref(m, rid, 3)

    def test_flat_choose_firstn_osd(self):
        m, root = builder.build_flat(12)
        rid = builder.add_simple_rule(m, root, builder.TYPE_OSD)
        _assert_kernel_matches_ref(m, rid, 3)

    def test_numrep_variants(self):
        m, rid = _hier(16, 4)
        for numrep in (1, 2, 4, 5):
            _assert_kernel_matches_ref(
                m, rid, numrep, xs=np.arange(64, dtype=np.uint32))

    def test_collision_heavy_small_map(self):
        """numrep == n_hosts: every lane contends for every host, the
        candidate scan + fallback must reproduce the scalar walk."""
        m, rid = _hier(4, 2)
        _assert_kernel_matches_ref(m, rid, 4)
        m2, rid2 = _hier(3, 3, n_racks=1)
        _assert_kernel_matches_ref(m2, rid2, 3)

    def test_reweighted_devices(self):
        m, rid = _hier(8, 4)
        w = np.full(32, WEIGHT_ONE, dtype=np.int64)
        w[3] = 0                       # fully out
        w[17] = WEIGHT_ONE // 2        # probabilistic
        w[18] = WEIGHT_ONE // 7
        _assert_kernel_matches_ref(m, rid, 3, weights=w)

    def test_reweight_update_rebuilds_plan(self):
        m, rid = _hier(8, 4)
        mapper = Mapper(m)
        xs = np.arange(64, dtype=np.uint32)
        base = np.asarray(mapper.map_pgs(rid, xs, 3))
        w = np.full(32, WEIGHT_ONE, dtype=np.int64)
        w[5] = 0
        mapper.set_device_weights(w)
        out = np.asarray(mapper.map_pgs(rid, xs, 3))
        assert not np.array_equal(base, out)
        for i, x in enumerate(xs):
            ref = mapper_ref.do_rule(m, rid, int(x), 3,
                                     weight=list(w))
            assert list(out[i]) == ref + [ITEM_NONE] * (3 - len(ref))

    def test_mixed_weight_hierarchy(self):
        """Alternating 1T/2T disks in every host — the production shape
        that cliff-edged off the kernel through round 4. Weight-class
        draw must match the scalar spec bit-exactly."""
        weights = [WEIGHT_ONE if i % 2 else 2 * WEIGHT_ONE
                   for i in range(32)]
        m, root = builder.build_hierarchy(8, 4, n_racks=2,
                                          osd_weights=weights)
        rid = builder.add_simple_rule(m, root, builder.TYPE_HOST)
        _assert_kernel_matches_ref(m, rid, 3)

    def test_mixed_flat_four_classes(self):
        rng = np.random.default_rng(7)
        w = [int(x) for x in rng.choice(
            [WEIGHT_ONE, 2 * WEIGHT_ONE, 3 * WEIGHT_ONE,
             WEIGHT_ONE // 2], size=24)]
        m, root = builder.build_flat(24, weights=w)
        rid = builder.add_simple_rule(m, root, builder.TYPE_OSD)
        _assert_kernel_matches_ref(m, rid, 3)

    def test_mixed_weights_with_reweights(self):
        weights = [WEIGHT_ONE if i % 2 else 2 * WEIGHT_ONE
                   for i in range(32)]
        m, root = builder.build_hierarchy(8, 4, n_racks=2,
                                          osd_weights=weights)
        rid = builder.add_simple_rule(m, root, builder.TYPE_HOST)
        dw = np.full(32, WEIGHT_ONE, dtype=np.int64)
        dw[5] = WEIGHT_ONE // 3
        dw[11] = 0
        _assert_kernel_matches_ref(m, rid, 3, weights=dw)

    def test_zero_weight_slot_never_wins(self):
        """A zero-weight item draws S64_MIN in the scalar spec; the
        class model leaves it classless so it can never win."""
        w = [WEIGHT_ONE, 0, WEIGHT_ONE, 2 * WEIGHT_ONE,
             0, WEIGHT_ONE, 2 * WEIGHT_ONE, WEIGHT_ONE]
        m, root = builder.build_flat(8, weights=w)
        rid = builder.add_simple_rule(m, root, builder.TYPE_OSD)
        mapper = Mapper(m)
        assert mapper._kernel_body(rid, 3) is not None
        got = np.asarray(mapper.map_pgs(
            rid, np.arange(N_X, dtype=np.uint32), 3))
        assert not np.isin(got, [1, 4]).any()
        _assert_kernel_matches_ref(m, rid, 3)

    def test_choose_args_single_weight_set(self):
        """A balancer-style single weight-set map (per-bucket weights
        kept to <= MAX_CLASSES distinct values) rides the kernel and
        matches the scalar spec with the same choose_args."""
        from ceph_tpu.crush.types import ChooseArg
        m, rid = _hier(8, 2)
        args = {}
        scales = (0.9, 0.95, 1.05, 1.1)
        for i, (bid, b) in enumerate(sorted(m.buckets.items())):
            ws = [max(1, int(w * scales[(i + j) % 4]))
                  for j, w in enumerate(b.weights)]
            args[bid] = ChooseArg(weight_set=[ws])
        m.choose_args[0] = args
        mapper = Mapper(m, choose_args=0)
        assert mapper._kernel_body(rid, 3) is not None, "ineligible"
        xs = np.arange(N_X, dtype=np.uint32)
        got = np.asarray(mapper.map_pgs(rid, xs, 3))
        for i, x in enumerate(xs):
            ref = mapper_ref.do_rule(m, rid, int(x), 3,
                                     choose_args=args)
            ref = ref + [ITEM_NONE] * (3 - len(ref))
            assert list(got[i]) == ref, (int(x), list(got[i]), ref)

    def test_forced_ambiguity_takes_fallback(self, monkeypatch):
        """With the class-draw margin blown up to cover everything,
        every lane flags ambiguous and the whole block resolves through
        the XLA fallback — still bit-exact (proves the fallback wiring
        end to end, including the >FB overflow path)."""
        monkeypatch.setattr(pm, "MARGIN_ABS", 1e30)
        weights = [WEIGHT_ONE if i % 2 else 2 * WEIGHT_ONE
                   for i in range(16)]
        m, root = builder.build_hierarchy(4, 4, n_racks=2,
                                          osd_weights=weights)
        rid = builder.add_simple_rule(m, root, builder.TYPE_HOST)
        _assert_kernel_matches_ref(m, rid, 3)

    def test_random_class_mixes(self):
        """Randomized sweep over host counts, class counts and numrep
        against the scalar spec."""
        rng = np.random.default_rng(1234)
        for _ in range(4):
            n_hosts = int(rng.integers(3, 9))
            per = int(rng.integers(2, 5))
            nw = int(rng.integers(1, 5))
            wopts = rng.integers(WEIGHT_ONE // 4, 4 * WEIGHT_ONE,
                                 size=nw)
            weights = [int(wopts[rng.integers(0, nw)])
                       for _ in range(n_hosts * per)]
            m, root = builder.build_hierarchy(
                n_hosts, per, n_racks=max(1, n_hosts // 3),
                osd_weights=weights)
            rid = builder.add_simple_rule(m, root, builder.TYPE_HOST)
            numrep = int(rng.integers(1, 4))
            mapper = Mapper(m)
            if mapper._kernel_body(rid, numrep) is None:
                continue                 # rack level exceeded 4 classes
            xs = np.arange(64, dtype=np.uint32)
            got = np.asarray(mapper.map_pgs(rid, xs, numrep))
            for i, x in enumerate(xs):
                ref = mapper_ref.do_rule(m, rid, int(x), numrep)
                ref = ref + [ITEM_NONE] * (numrep - len(ref))
                assert list(got[i]) == ref, (int(x), list(got[i]), ref)

    def test_random_continuous_sweep(self):
        """Deep randomized sweep: continuous per-item base weights AND
        single-position choose_args weight-sets, hierarchy shapes and
        reweights drawn at random, every lane vs the scalar spec."""
        from ceph_tpu.crush.types import ChooseArg
        rng = np.random.default_rng(4242)
        for trial in range(4):
            n_hosts = int(rng.integers(3, 7))
            per = int(rng.integers(5, 9))       # > MAX_CLASSES slots
            n_dev = n_hosts * per
            weights = [int(rng.integers(WEIGHT_ONE // 4,
                                        4 * WEIGHT_ONE))
                       for _ in range(n_dev)]
            m, root = builder.build_hierarchy(
                n_hosts, per, n_racks=max(1, n_hosts // 3),
                osd_weights=weights)
            rid = builder.add_simple_rule(m, root, builder.TYPE_HOST)
            ca = None
            if trial % 2:
                args = {}
                for bid, b in m.buckets.items():
                    scale = rng.uniform(0.85, 1.15, size=b.size)
                    args[bid] = ChooseArg(weight_set=[[
                        max(1, int(w * s))
                        for w, s in zip(b.weights, scale)]])
                m.choose_args[0] = args
                ca = 0
            dw = np.full(n_dev, WEIGHT_ONE, dtype=np.int64)
            if trial >= 2:
                dw[int(rng.integers(0, n_dev))] = 0
                dw[int(rng.integers(0, n_dev))] = WEIGHT_ONE // 3
            numrep = int(rng.integers(1, 4))
            mapper = Mapper(m, dw, choose_args=ca)
            assert mapper._kernel_body(rid, numrep) is not None, \
                "continuous map unexpectedly ineligible"
            assert 0 in mapper._kernel_plan(rid).kmax
            xs = np.arange(96, dtype=np.uint32)
            got = np.asarray(mapper.map_pgs(rid, xs, numrep))
            cargs = m.choose_args.get(ca) if ca is not None else None
            for i, x in enumerate(xs):
                ref = mapper_ref.do_rule(m, rid, int(x), numrep,
                                         weight=list(dw),
                                         choose_args=cargs)
                ref = ref + [ITEM_NONE] * (numrep - len(ref))
                assert list(got[i]) == ref, \
                    (trial, int(x), list(got[i]), ref)

    def test_continuous_forced_ambiguity_takes_fallback(
            self, monkeypatch):
        """Blown-up margin on the per-slot draw: every lane flags and
        the block resolves through the XLA fallback — still exact."""
        monkeypatch.setattr(pm, "MARGIN_ABS", 1e30)
        m, root = builder.build_flat(
            8, weights=[WEIGHT_ONE + 991 * i for i in range(8)])
        rid = builder.add_simple_rule(m, root, builder.TYPE_OSD)
        _assert_kernel_matches_ref(
            m, rid, 3, xs=np.arange(96, dtype=np.uint32))

    def test_crush_ln_neg_exact(self):
        """The in-kernel crush_ln limb pipeline vs ln_table.crush_ln
        over the full 16-bit domain (interpret mode, batched)."""
        import jax
        import jax.numpy as jnp
        from ceph_tpu.crush.ln_table import crush_ln
        rhlh, ll = pm._ln_plane_tables()
        v = np.arange(0x10000, dtype=np.int64)
        expect = (1 << 48) - crush_ln(v)

        def run(vv):
            return pm._crush_ln_neg(
                jnp.asarray(rhlh), jnp.asarray(ll),
                jnp.asarray(vv, dtype=jnp.int32).reshape(1, -1))

        got_hi, got_lo = jax.jit(run)(v)
        got = (np.asarray(got_hi, dtype=np.int64) << 24) | \
            np.asarray(got_lo, dtype=np.int64)
        mism = np.nonzero(got[0] != expect)[0]
        assert mism.size == 0, (mism[:5], got[0][mism[:5]],
                                expect[mism[:5]])

    def test_engineered_draw_ties(self):
        """Scan wide x ranges on a small bucket so ln-equality adjacent
        pairs (zg) actually occur among the drawn hashes; the winner
        must match the spec's first-index tie rule everywhere."""
        m, root = builder.build_flat(16)
        rid = builder.add_simple_rule(m, root, builder.TYPE_OSD)
        # big uniform stride to diversify hash space coverage
        xs = (np.arange(256, dtype=np.uint32) * 2654435761) & 0x7FFFFFFF
        _assert_kernel_matches_ref(m, rid, 3, xs=xs.astype(np.uint32))

    def test_sweep_counts_match_xla(self, monkeypatch):
        m, rid = _hier(16, 4)
        mk = Mapper(m, block=1 << 14)
        monkeypatch.setenv("CEPH_TPU_CRUSH_KERNEL", "0")
        mx = Mapper(m, block=1 << 14)
        monkeypatch.setenv("CEPH_TPU_CRUSH_KERNEL", "interpret")
        assert mk._kernel_mode == "interpret" and mx._kernel_mode is None
        ck, bk = mk.sweep(rid, 0, 3000, 3)
        cx, bx = mx.sweep(rid, 0, 3000, 3)
        assert np.array_equal(np.asarray(ck), np.asarray(cx))
        assert int(bk) == int(bx)


@pytest.fixture(scope="module")
def batched_plan():
    """ONE shared map+plan for the candidate-batching tests (tier-1
    budget: the plan build is host-side but the canonical-shape
    hierarchy is not free, and the jaxpr pins below only trace — no
    compile — so sharing the plan keeps the whole class cheap)."""
    m, rid = _hier(16, 4)
    plan = pm.build_plan(m, pack_map(m), rid, None)
    assert plan is not None
    return m, rid, plan


def _count_dot_generals(jaxpr) -> int:
    """dot_general eqns in ``jaxpr`` and every nested jaxpr (pjit
    bodies, the pallas_call kernel jaxpr, cond/scan branches)."""

    def _subs(v):
        if isinstance(v, (list, tuple)):
            for x in v:
                yield from _subs(x)
        elif hasattr(v, "jaxpr"):            # ClosedJaxpr
            yield v.jaxpr
        elif hasattr(v, "eqns"):             # Jaxpr
            yield v

    n = 0
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "dot_general":
            n += 1
        for v in eqn.params.values():
            for sub in _subs(v):
                n += _count_dot_generals(sub)
    return n


class TestCandidateBatching:
    """Round 15 level-major descent: the kernel body's MXU traffic is
    O(l_total), independent of how many replica candidates descend —
    pinned structurally by jaxpr inspection (no compile, no run; the
    bit-exactness of the batched math rides the existing suites
    unchanged)."""

    def test_dot_general_count_independent_of_numrep(self,
                                                     batched_plan):
        import jax
        _, _, plan = batched_plan
        counts = {}
        for numrep in (2, 3, 4):
            n_cand = numrep + pm.SPEC_EXTRA
            lanes, fold, groups = pm.kernel_geometry(plan, n_cand)
            # the canonical-shape plan keeps full lanes, so every
            # candidate folds into one group — the pinned regime
            assert fold == n_cand and groups == 1, (fold, groups)
            xs = jnp.zeros(lanes, dtype=jnp.int32)
            jx = jax.make_jaxpr(
                lambda v, nr=numrep: pm._run_kernel(
                    plan, v, nr, interpret=True))(xs)
            counts[numrep] = _count_dot_generals(jx.jaxpr)
        assert len(set(counts.values())) == 1, counts
        # the O(l_total) structural pin: ONE fetch matmul per level
        # with P > 1 (level 0 is the hoisted P == 1 broadcast) plus
        # ONE zg tie matmul per uniform choose — nothing scales with
        # numrep
        l_total = plan.l_main + plan.l_leaf
        expect = sum(1 for _, p in plan.sizes if p > 1) + l_total
        assert counts[2] == expect, (counts, expect)

    def test_kernel_geometry_contract(self, batched_plan):
        import types
        _, _, plan = batched_plan
        for n_cand in (3, 5, 8, 11):
            lanes, fold, groups = pm.kernel_geometry(plan, n_cand)
            assert lanes >= pm.MIN_LANES
            assert lanes & (lanes - 1) == 0          # power of two
            assert lanes <= plan.lanes               # PG cell cap
            # the folded working set never exceeds the RAW VMEM
            # budget, and the groups cover every candidate exactly
            assert fold * lanes <= plan.vmem_lanes
            assert fold * (groups - 1) < n_cand <= fold * groups
            # the load-bearing guarantee: per-PG level passes
            # (groups/lanes) never exceed the candidate-major
            # baseline's (n_cand/plan.lanes) — a fold carved out of
            # the PG width alone would violate this
            assert groups * plan.lanes <= n_cand * lanes, \
                (n_cand, lanes, fold, groups)
        # a plan with zero VMEM headroom past MIN_LANES degenerates
        # to candidate-major geometry (fold 1, one group per
        # candidate) — eligibility never shrinks
        narrow = types.SimpleNamespace(lanes=pm.MIN_LANES,
                                       vmem_lanes=pm.MIN_LANES)
        assert pm.kernel_geometry(narrow, 5) == (pm.MIN_LANES, 1, 5)
        # headroom-rich plan: full fold at the unchanged cell width
        rich = types.SimpleNamespace(lanes=1024, vmem_lanes=8192)
        assert pm.kernel_geometry(rich, 5) == (1024, 5, 1)
        # the 10k-OSD bench shape (vmem ~3.4x the cap): the search
        # must prefer fold 3 at full width (2 groups/1024 PGs) over
        # the naive full fold at a narrowed cell (1 group/512 PGs =
        # same passes, narrower cells) and over fold 1 (5 groups)
        bench = types.SimpleNamespace(lanes=1024, vmem_lanes=3503)
        assert pm.kernel_geometry(bench, 5) == (1024, 3, 2)

    def test_plan_info_through_mapper(self):
        """Mapper.kernel_plan_info: the bench-row facts — plan build
        only, no kernel compile (the body closure is built lazily and
        never traced here)."""
        m, rid = _hier(8, 4)
        mapper = Mapper(m)
        info = mapper.kernel_plan_info(rid, 3)
        assert info is not None
        plan = mapper._kernel_plan(rid)
        _, fold, groups = pm.kernel_geometry(plan, 3 + pm.SPEC_EXTRA)
        assert info["candidate_batched"] == (fold > 1)
        assert info["fetches_per_sweep"] == \
            groups * (plan.l_main + plan.l_leaf)
        assert info["candidate_fold"] == fold
        # the XLA path has no plan to describe
        mi, root = builder.build_flat(
            4, weights=[pm.MAX_CONT_WEIGHT + i for i in range(4)])
        ri = builder.add_simple_rule(mi, root, builder.TYPE_OSD)
        assert Mapper(mi).kernel_plan_info(ri, 3) is None


class TestKernelInternals:
    def test_hash_bit_exact(self):
        from ceph_tpu.crush import hash as H
        rng = np.random.default_rng(0)
        a = rng.integers(0, 1 << 32, 256, dtype=np.uint32)
        b = rng.integers(0, 1 << 32, 256, dtype=np.uint32)
        c = rng.integers(0, 1 << 32, 256, dtype=np.uint32)
        want = H.hash32_3(a, b, c).astype(np.int64)
        got = np.asarray(pm._hash3(
            jnp.asarray(a.astype(np.int32)).reshape(2, -1),
            jnp.asarray(b.astype(np.int32)).reshape(2, -1),
            jnp.asarray(c.astype(np.int32)).reshape(2, -1))
        ).reshape(-1).astype(np.uint32).astype(np.int64)
        assert np.array_equal(want, got)
        want2 = H.hash32_2(a, b).astype(np.int64)
        got2 = np.asarray(pm._hash2(
            jnp.asarray(a.astype(np.int32)).reshape(2, -1),
            jnp.asarray(b.astype(np.int32)).reshape(2, -1))
        ).reshape(-1).astype(np.uint32).astype(np.int64)
        assert np.array_equal(want2, got2)

    def test_approx_z_error_bound(self):
        """The two-phase phase-1 scorer's PROVEN envelope: the claimed
        ERR_Z bound on |_approx_z(u) - (2^48 - crush_ln(u))/2^44| must
        hold over the ENTIRE 16-bit hash domain — this is the fact that
        licenses flagging (not recomputing) third-slot candidates. The
        assert keeps real safety headroom (measured max ~4.43e-5,
        dominated by crush_ln's index2 staircase, vs ERR_Z = 1e-4) so a
        platform fma/assoc wobble cannot silently eat the margin."""
        import jax
        from ceph_tpu.crush.ln_table import crush_ln
        u = np.arange(0x10000, dtype=np.int64)
        z_exact = ((1 << 48) - crush_ln(u)).astype(np.float64) / 2.0**44
        got = np.asarray(jax.jit(pm._approx_z)(
            jnp.asarray(u, dtype=jnp.int32).reshape(4, -1)))
        err = np.abs(got.reshape(-1).astype(np.float64) - z_exact)
        assert err.max() <= pm.ERR_Z * 0.6, \
            (err.max(), int(err.argmax()))

    def test_zg_flag_table(self):
        from ceph_tpu.crush.ln_table import ln_gap_info
        _, zg = ln_gap_info()
        m, rid = _hier(4, 2)
        plan = pm.build_plan(m, pack_map(m), rid, None)
        idx = np.where(zg)[0][:8].astype(np.int32)

        class _R:
            def __init__(self, a):
                self.a = a

            def __getitem__(self, k):
                return self.a

        zgt = jnp.asarray(plan.zg2dT)
        for v in idx:
            f = np.asarray(pm._zg_flag(
                _R(zgt), jnp.full((1, 8), int(v) + 1, jnp.int32)))
            assert f[0, 0] == 1, v
            f2 = np.asarray(pm._zg_flag(
                _R(zgt), jnp.full((1, 8), int(v), jnp.int32)))
            # zg[v-1] is almost never also set (pairs are isolated)
            assert f2[0, 0] == int(zg[v - 1]), v


class TestVmemPlanning:
    """The scoped-VMEM model (round 5): the driver's libtpu enforces a
    16 MiB kernel-vmem stack; a flat 10k-OSD map's root level allocated
    121.47 MB at 1024 lanes and killed the round-4 bench. build_plan
    must narrow lanes for mid-size levels and decline outright when
    even MIN_LANES cannot fit."""

    def test_flat_huge_root_declines(self):
        m, root = builder.build_flat(4096)
        rid = builder.add_simple_rule(m, root, builder.TYPE_OSD)
        assert pm.build_plan(m, pack_map(m), rid, None) is None

    @pytest.mark.slow
    def test_mid_map_narrows_lanes(self):
        m, root = builder.build_flat(640)
        rid = builder.add_simple_rule(m, root, builder.TYPE_OSD)
        plan = pm.build_plan(m, pack_map(m), rid, None)
        assert plan is not None
        assert pm.MIN_LANES <= plan.lanes < pm.LANES
        assert plan.lanes & (plan.lanes - 1) == 0     # power of two
        # the model must match what it claims to bound
        per_lane = max(4 * (pm._LIVE_TEMPS * S + 2 * (2 * S + 1) + P)
                       for S, P in plan.sizes)
        assert per_lane * plan.lanes <= pm.VMEM_BUDGET
        # and the narrowed kernel still answers bit-exactly
        _assert_kernel_matches_ref(m, rid, 3)

    def test_canonical_map_keeps_full_lanes(self):
        m, rid = _hier(640, 16, n_racks=20)
        plan = pm.build_plan(m, pack_map(m), rid, None)
        assert plan is not None and plan.lanes == pm.LANES


class TestRuntimeFallback:
    @pytest.mark.slow
    def test_kernel_failure_degrades_to_xla(self, monkeypatch):
        """A kernel that explodes at run time (e.g. a libtpu with a
        tighter VMEM limit than the model assumes) must degrade to the
        XLA path with the right answer — round 4's driver bench died
        exactly here."""
        m, rid = _hier(8, 4)
        mapper = Mapper(m)
        assert mapper._kernel_mode == "interpret"
        assert mapper._kernel_body(rid, 3) is not None

        def boom(*a, **k):
            raise RuntimeError("scoped vmem limit exceeded (simulated)")

        monkeypatch.setattr(pm, "_run_kernel", boom)
        xs = np.arange(64, dtype=np.uint32)
        got = np.asarray(mapper.map_pgs(rid, xs, 3))
        assert mapper._kernel_mode is None            # permanently off
        for i, x in enumerate(xs):
            ref = mapper_ref.do_rule(m, rid, int(x), 3)
            ref = ref + [ITEM_NONE] * (3 - len(ref))
            assert list(got[i]) == ref
        # sweep after the failure also runs (XLA path)
        counts, bad = mapper.sweep(rid, 0, 64, 3)
        assert int(np.asarray(counts).sum()) == 3 * 64
