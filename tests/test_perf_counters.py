"""Perf-counter wiring tests (VERDICT round-1 Weak #7: the counters must
have real call sites; ref: src/common/perf_counters.h +
perf_counters_collection.h, `ceph daemon ... perf dump`)."""

import json
import pytest

import numpy as np

from ceph_tpu.utils.perf_counters import (PerfCountersBuilder,
                                          PerfCountersCollection)


class TestCollection:
    def test_builder_registers_and_dump_aggregates(self):
        pc = (PerfCountersBuilder("t_unit")
              .add_u64_counter("ops")
              .add_time("secs")
              .create_perf_counters())
        pc.inc("ops", 3)
        pc.tinc("secs", 0.5)
        dump = PerfCountersCollection.instance().dump()
        assert dump["t_unit"]["ops"] == 3
        assert dump["t_unit"]["secs"] == 0.5
        json.loads(PerfCountersCollection.instance().dump_json())


class TestWiredCallSites:
    @pytest.mark.slow
    def test_crush_tester_counts(self):
        from ceph_tpu.crush import builder
        from ceph_tpu.crush.tester import CrushTester
        m, root = builder.build_flat(8)
        rid = builder.add_simple_rule(m, root, builder.TYPE_OSD)
        t = CrushTester(m)
        before = t.perf.dump()["mappings"]
        t.test(rid, 3, 0, 63)
        after = t.perf.dump()
        assert after["mappings"] == before + 64
        assert after["map_seconds"] > 0

    def test_ec_backend_counts(self):
        from ceph_tpu.ec import factory
        from ceph_tpu.osd.ec_backend import ECBackendLite
        be = ECBackendLite(factory("plugin=jax k=2 m=1"), chunk_size=128,
                           name="t_ecb")
        be.write("o", 100, b"abc")               # partial => RMW
        d = be.perf.dump()
        assert d["write_bytes"] == 3
        assert d["rmw_stripes"] == 1
        assert d["encode_stripes"] >= 1
        be.lose_shard(0, "o")
        be.recover("o")
        assert be.perf.dump()["recover_chunks"] >= 1

    def test_bench_perf_dump_flag(self, capsys):
        from ceph_tpu.bench import ec_benchmark
        ec_benchmark.main(["--size", "4096", "--iterations", "1",
                           "--parameter", "k=2", "--parameter", "m=1",
                           "--perf-dump"])
        out = capsys.readouterr().out
        payload = out[out.index("{"):]
        dump = json.loads(payload)
        assert dump["ec_bench"]["encode_bytes"] > 0
        assert dump["ec_bench"]["encode_ops"] > 0


class TestMapperLifecycleCounters:
    """Mapper pack/compile/reweight traffic is observable (VERDICT r3
    ask #10: balancer iterations and skip_is_out flips were invisible)."""

    def test_pack_map_and_reweight_counters(self):
        import numpy as np
        from ceph_tpu.crush import builder
        from ceph_tpu.crush.builder import TYPE_HOST
        from ceph_tpu.crush.mapper import PERF, Mapper
        from ceph_tpu.crush.types import WEIGHT_ONE

        before = PERF.dump()
        m, root = builder.build_hierarchy(4, 4)
        builder.add_simple_rule(m, root, TYPE_HOST)
        mapper = Mapper(m)
        mapper.map_pgs(0, np.arange(64, dtype=np.uint32), 3)
        mid = PERF.dump()
        assert mid["packs"] == before["packs"] + 1
        assert mid["pack_seconds"] > before["pack_seconds"]
        assert mid["pgs_mapped"] == before["pgs_mapped"] + 64
        # reweight without a skip_is_out flip: no recompile counted
        w = np.full(16, WEIGHT_ONE, dtype=np.int64)
        mapper.set_device_weights(w)
        after_same = PERF.dump()
        assert after_same["reweights"] == mid["reweights"] + 1
        assert after_same["reweight_recompiles"] == mid["reweight_recompiles"]
        # flip skip_is_out: exactly one recompile event recorded
        w2 = w.copy()
        w2[3] = WEIGHT_ONE // 2
        mapper.set_device_weights(w2)
        flipped = PERF.dump()
        assert flipped["reweight_recompiles"] == \
            after_same["reweight_recompiles"] + 1

    @pytest.mark.slow
    def test_sweep_counters(self):
        import numpy as np
        from ceph_tpu.crush import builder
        from ceph_tpu.crush.builder import TYPE_HOST
        from ceph_tpu.crush.mapper import PERF, Mapper

        m, root = builder.build_hierarchy(4, 4)
        builder.add_simple_rule(m, root, TYPE_HOST)
        mapper = Mapper(m)
        before = PERF.dump()
        mapper.sweep(0, 0, 256, 3)
        after = PERF.dump()
        assert after["pgs_mapped"] == before["pgs_mapped"] + 256
        assert after["sweep_blocks"] >= before["sweep_blocks"] + 1
