"""Proc backend: supervised multi-process cluster + wire-delivered
live config.

Every proc-backend phase shares ONE spawned cluster (spawn-to-healthy
costs seconds of real process startup; respawning per-test would blow
the tier-1 budget), sequenced inside a single event loop because the
supervisor's watcher tasks belong to it:

  1. live `ceph config set` lands TYPED inside every remote OSD
     process without a restart; `config rm` restores the default
  2. per-entity beats per-type beats global across real processes
  3. proc_storm: SIGKILL an OSD, the lead mon and the active mgr under
     continuing writer load (zero errors, bit-identical reads,
     supervisor restarts observed, mgr telemetry re-populates), plus
     the SIGSTOP/SIGCONT gray pass (OSD_SLOW trips, then heals)

ref: src/test/test_c2c.cc has no analog — this is qa/tasks/thrashosds
semantics pointed at real PIDs.
"""

import asyncio

import pytest

from ceph_tpu.cluster.vstart import Cluster
from ceph_tpu.sim.thrasher import Thrasher


def run(coro):
    return asyncio.run(coro)


async def _wait(pred, timeout=30.0):
    t0 = asyncio.get_event_loop().time()
    while True:
        if await pred():
            return
        if asyncio.get_event_loop().time() - t0 > timeout:
            raise TimeoutError
        await asyncio.sleep(0.25)


async def _osd_cfg(c, osd_id: int, name: str):
    out = await c.daemon_command(f"osd.{osd_id}", "config show")
    return out.get(name)


def test_proc_cluster_storm_and_live_config():
    async def go():
        # grace must exceed the OSD_SLOW confirm window: a SIGSTOPped
        # OSD that gets marked DOWN first never shows as slow
        c = Cluster(n_mons=3, n_osds=3, n_mgrs=2,
                    mgr_modules=["prometheus"],
                    config={"osd_heartbeat_grace": 10.0},
                    backend="proc")
        assert c.backend == "proc"
        await c.start()
        try:
            assert c.spawn_to_healthy_s is not None
            await c.client.pool_create("t", pg_num=16, size=3)
            io = await c.client.open_ioctx("t")

            # -- 1: live config lands typed, no restart ----------------
            pids = {n: ch.pid for n, ch in c.children.items()
                    if n.startswith("osd.")}
            await c.config_set("osd", "osd_max_backfills", "7")

            async def landed():
                for i in range(3):
                    if await _osd_cfg(c, i, "osd_max_backfills") != 7:
                        return False
                return True
            await _wait(landed)
            assert pids == {n: ch.pid for n, ch in c.children.items()
                            if n.startswith("osd.")}, \
                "config delivery must not restart daemons"

            # -- 2: most-specific wins across process boundaries -------
            await c.config_set("osd.0", "osd_max_backfills", "3")

            async def split():
                return (await _osd_cfg(c, 0, "osd_max_backfills") == 3
                        and await _osd_cfg(
                            c, 1, "osd_max_backfills") == 7)
            await _wait(split)

            # -- rm restores the boot-time value (key absent) ----------
            await c.config_rm("osd.0", "osd_max_backfills")
            await c.config_rm("osd", "osd_max_backfills")

            async def restored():
                for i in range(3):
                    v = await _osd_cfg(c, i, "osd_max_backfills")
                    if v not in (None, 1):
                        return False
                return True
            await _wait(restored)

            # -- 3: the storm (SIGKILLs + SIGSTOP gray pass) -----------
            th = Thrasher(c, seed=7, write_timeout=30.0)
            summary = await th.proc_storm(io, settle_timeout=180.0,
                                          gray=True)
            assert summary["acked_writes"] > 0
            assert summary["failed_writes"] == 0
            assert sum(summary["restarts"].values()) >= 2
            assert summary["mgr_failover"] is not None
        finally:
            await c.stop()
    run(go())


def test_live_config_set_inproc():
    """The SAME wire-delivered config path, in-process backend: set a
    registered knob centrally, every OSD's runtime layer follows typed
    with no restart; rm restores the default."""
    async def go():
        c = Cluster(n_mons=1, n_osds=2)
        await c.start()
        try:
            ret, rs, _ = await c.client.mon_command(
                {"prefix": "config set", "who": "osd",
                 "name": "osd_max_backfills", "value": "5"})
            assert ret == 0, rs

            async def landed():
                return all(o.config.get("osd_max_backfills") == 5
                           for o in c.osds)
            await _wait(landed, timeout=15.0)
            ret, _, out = await c.client.mon_command(
                {"prefix": "config get", "who": "osd",
                 "name": "osd_max_backfills"})
            assert ret == 0 and out == b"5"
            ret, rs, _ = await c.client.mon_command(
                {"prefix": "config rm", "who": "osd",
                 "name": "osd_max_backfills"})
            assert ret == 0, rs

            async def restored():
                return all(o.config.get("osd_max_backfills") in (None, 1)
                           for o in c.osds)
            await _wait(restored, timeout=15.0)
            # a bogus value for a registered option is refused upfront
            ret, rs, _ = await c.client.mon_command(
                {"prefix": "config set", "who": "osd",
                 "name": "osd_max_backfills", "value": "not-an-int"})
            assert ret == -22
        finally:
            await c.stop()
    run(go())
