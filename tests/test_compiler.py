"""CrushCompiler tests (ref: src/test/crush golden-map fixtures):
compile a hand-written crushtool-format map, round-trip through
decompile, map PGs through compiled rules, device-class shadows."""

import numpy as np
import pytest

from ceph_tpu.crush.compiler import (
    CompileError, class_shadow, compile_crushmap, decompile_crushmap,
)
from ceph_tpu.crush.mapper import Mapper
from ceph_tpu.crush.tester import CrushTester
from ceph_tpu.crush.types import (
    ALG_STRAW2, ITEM_NONE, OP_CHOOSELEAF_FIRSTN, OP_SET_CHOOSE_TRIES,
    OP_TAKE, WEIGHT_ONE,
)

MAP_TEXT = """\
# begin crush map
tunable choose_local_tries 0
tunable choose_local_fallback_tries 0
tunable choose_total_tries 50
tunable chooseleaf_descend_once 1
tunable chooseleaf_vary_r 1
tunable chooseleaf_stable 1

# devices
device 0 osd.0 class hdd
device 1 osd.1 class ssd
device 2 osd.2 class hdd
device 3 osd.3 class ssd
device 4 osd.4 class hdd
device 5 osd.5 class ssd

# types
type 0 osd
type 1 host
type 10 root

# buckets
host host0 {
	id -1
	alg straw2
	hash 0	# rjenkins1
	item osd.0 weight 1.000
	item osd.1 weight 1.000
}
host host1 {
	id -2
	alg straw2
	hash 0
	item osd.2 weight 1.000
	item osd.3 weight 2.000
}
host host2 {
	id -3
	alg straw2
	hash 0
	item osd.4 weight 1.000
	item osd.5 weight 1.000
}
root default {
	id -4
	alg straw2
	hash 0
	item host0 weight 2.000
	item host1 weight 3.000
	item host2 weight 2.000
}

# rules
rule replicated_rule {
	id 0
	type replicated
	step take default
	step chooseleaf firstn 0 type host
	step emit
}
rule ssd_rule {
	id 1
	type replicated
	step set_choose_tries 100
	step take default class ssd
	step chooseleaf firstn 0 type host
	step emit
}

# end crush map
"""


class TestCompile:
    def setup_method(self):
        self.map = compile_crushmap(MAP_TEXT)

    def test_structure(self):
        m = self.map
        assert m.max_devices == 6
        assert m.tunables.choose_total_tries == 50
        assert m.bucket_names[-4] == "default"
        assert m.buckets[-4].items == [-1, -2, -3]
        assert m.buckets[-2].weights == [WEIGHT_ONE, 2 * WEIGHT_ONE]
        assert m.device_classes[1] == "ssd"
        assert m.type_names[10] == "root"

    def test_rules(self):
        r0 = self.map.rules[0]
        assert r0.steps[0].op == OP_TAKE and r0.steps[0].arg1 == -4
        assert r0.steps[1].op == OP_CHOOSELEAF_FIRSTN
        assert r0.steps[1].arg2 == 1  # type host
        r1 = self.map.rules[1]
        assert r1.steps[0].op == OP_SET_CHOOSE_TRIES
        assert r1.steps[0].arg1 == 100

    def test_class_shadow(self):
        m = self.map
        take = m.rules[1].steps[1]
        shadow = m.buckets[take.arg1]
        assert m.bucket_names[take.arg1] == "default~ssd"
        # shadow hosts contain only ssd devices
        for child in shadow.items:
            child_b = m.buckets[child]
            for dev in child_b.items:
                assert m.device_classes[dev] == "ssd"

    def test_mapping_runs(self):
        mapper = Mapper(self.map)
        out = np.asarray(mapper.map_pgs(0, np.arange(256), 3))
        assert (out != ITEM_NONE).all()
        hosts = {0: 0, 1: 0, 2: 1, 3: 1, 4: 2, 5: 2}
        for row in out:
            assert len({hosts[int(d)] for d in row}) == 3

    def test_ssd_rule_only_ssd(self):
        mapper = Mapper(self.map)
        out = np.asarray(mapper.map_pgs(1, np.arange(256), 3))
        valid = out[out != ITEM_NONE]
        assert set(np.unique(valid)) <= {1, 3, 5}

    def test_roundtrip(self):
        text = decompile_crushmap(self.map)
        m2 = compile_crushmap(text)
        assert m2.max_devices == self.map.max_devices
        # rules and placement identical
        mapper1 = Mapper(self.map)
        mapper2 = Mapper(m2)
        xs = np.arange(128)
        for rule in (0, 1):
            a = np.asarray(mapper1.map_pgs(rule, xs, 3))
            b = np.asarray(mapper2.map_pgs(rule, xs, 3))
            assert (a == b).all(), f"rule {rule} diverged after round-trip"

    @pytest.mark.slow
    def test_tester_integration(self):
        tester = CrushTester(self.map)
        res = tester.test(0, 3, 0, 255)
        assert res.bad_mappings == 0

    def test_errors(self):
        with pytest.raises(CompileError):
            compile_crushmap("devicex 0 osd.0\n")
        with pytest.raises(CompileError):
            compile_crushmap("rule r {\n step take nonexistent\n}\n")
        with pytest.raises(CompileError):
            compile_crushmap(
                "type 0 osd\nhost h {\n alg nosuch\n}\n")


class TestChooseArgsGrammar:
    def test_choose_args_roundtrip(self):
        """choose_args blocks survive decompile -> compile (VERDICT #6;
        ref: CrushCompiler parse/decompile of choose_args)."""
        from ceph_tpu.crush import builder
        from ceph_tpu.crush.compiler import (compile_crushmap,
                                             decompile_crushmap)
        from ceph_tpu.crush.types import ChooseArg, WEIGHT_ONE

        m, root = builder.build_hierarchy(4, 2)
        m.choose_args[2] = {root: ChooseArg(
            weight_set=[[WEIGHT_ONE, 2 * WEIGHT_ONE, WEIGHT_ONE,
                         WEIGHT_ONE], [3 * WEIGHT_ONE] * 4],
            ids=[100, 101, 102, 103])}
        text = decompile_crushmap(m)
        m2 = compile_crushmap(text)
        assert 2 in m2.choose_args
        args = list(m2.choose_args[2].values())[0]
        assert args.weight_set == m.choose_args[2][root].weight_set
        assert args.ids == m.choose_args[2][root].ids
        # decompiling the reparsed map is a fixpoint
        assert decompile_crushmap(m2) == decompile_crushmap(
            compile_crushmap(decompile_crushmap(m2)))
