"""PG merging: pg_num decrease on POPULATED pools (inverse of split).

ref test model: PG::merge_from + the pg_num_pending two-phase decrease
— phase 1 commits pg_num_pending and folds pgp_num (sources migrate
onto their stable-mod parents through normal peering), phase 2 commits
the decrease once every source PG is clean, co-located, and QUIESCED
(MOSDPGReadyToMerge barrier); OSDs then fold source collections + logs
into the parents deterministically. Round-6 VERDICT missing #4: the
autoscaler could only scale up, so an over-split pool could never
shrink.

The data-safety invariant pinned here: writes landing in a source PG
during the quiesce window are either PARKED (backoff until the client
retargets the merged parent) or land in the merged parent — never
dropped; every acked byte reads back bit-identical after the fold.
"""

import asyncio
import json

import pytest

from ceph_tpu.cluster.vstart import Cluster
from ceph_tpu.mgr.modules import PGAutoscalerModule


def run(coro):
    asyncio.run(coro)


async def _pool_nums(c, name="data"):
    _, _, out = await c.client.mon_command({"prefix": "osd dump"})
    p = next(x for x in json.loads(out)["pools"] if x["name"] == name)
    return p["pg_num"], p["pgp_num"], p["pg_num_pending"]


async def _wait_merged(c, want_pg, name="data", timeout=90.0):
    deadline = asyncio.get_event_loop().time() + timeout
    while True:
        pg, pgp, pending = await _pool_nums(c, name)
        if pg == want_pg and not pending:
            return
        assert asyncio.get_event_loop().time() < deadline, \
            f"merge to {want_pg} never committed " \
            f"(pg_num={pg} pgp_num={pgp} pending={pending})"
        await asyncio.sleep(0.2)


@pytest.mark.slow
def test_split_then_merge_roundtrip_bit_identical():
    """The acceptance round-trip: populate, split 4->8, migrate
    (pgp_num ramp), merge back to 4 — with a writer RACING the whole
    merge window. Every acked write (pre-merge and racing) must read
    back bit-identical, and the source collections must be gone.

    ``slow``: the tier-1 cap is nearly full — the elastic_storm smoke
    already exercises split-then-merge-bit-identical under load in
    tier-1; this variant adds the collection-teardown, guard-rail and
    racing-quiesce assertions."""
    async def go():
        c = await Cluster(n_mons=1, n_osds=3).start()
        try:
            await c.client.pool_create("data", pg_num=4, size=2,
                                       min_size=1)
            await c.wait_for_clean(timeout=240)
            io = await c.client.open_ioctx("data")
            acked = {f"obj-{i:03d}": bytes([i % 251]) * (32 + i)
                     for i in range(32)}
            for oid, data in acked.items():
                await io.write_full(oid, data)
            # split in place, then migrate the children
            for var, val in (("pg_num", "8"), ("pgp_num", "8")):
                ret, rs, _ = await c.client.mon_command(
                    {"prefix": "osd pool set", "pool": "data",
                     "var": var, "val": val})
                assert ret == 0, rs
                await c.wait_for_clean(timeout=240)

            # racing writer across the merge window: acked-or-parked,
            # never dropped
            stop = asyncio.Event()

            async def racer():
                i = 0
                while not stop.is_set():
                    oid = f"race-{i:04d}"
                    data = bytes([i % 256]) * 48
                    try:
                        await io.write_full(oid, data, timeout=30.0)
                        acked[oid] = data
                    except Exception:
                        pass          # unacked: free to be dropped
                    i += 1
                    await asyncio.sleep(0.02)
            racing = asyncio.ensure_future(racer())
            ret, rs, _ = await c.client.mon_command(
                {"prefix": "osd pool set", "pool": "data",
                 "var": "pg_num", "val": "4"})
            assert ret == 0, rs
            # two-phase: pending set first, commit after quiesce
            _pg, pgp, pending = await _pool_nums(c)
            assert pgp == 4        # pgp folded with the pending commit
            if pending:
                # guard rail: a pool mid-merge refuses further pg_num
                # edits until the decrease commits
                ret, rs, _ = await c.client.mon_command(
                    {"prefix": "osd pool set", "pool": "data",
                     "var": "pg_num", "val": "16"})
                assert ret == -22 and "in flight" in rs
            await _wait_merged(c, 4)
            # a few post-merge racing writes, then stop
            await asyncio.sleep(0.3)
            stop.set()
            await racing
            await c.wait_for_clean(timeout=240)
            # every acked byte bit-identical through the fold
            for oid, data in acked.items():
                assert await io.read(oid) == data, oid
            # source PGs are GONE: no collection with seed >= 4
            for o in c.osds:
                for cid in o.store.list_collections():
                    if cid.startswith(f"{io.pool_id}."):
                        assert int(cid.split(".")[1], 16) < 4, \
                            f"leftover source collection {cid}"
            # writes through the merged map keep flowing
            await io.write_full("post-merge", b"fresh")
            assert await io.read("post-merge") == b"fresh"
            # guard rails (same cluster, mon-side only — no waits):
            # EC pools refuse merges; pg_num < 1 refused
            await c.client.pool_create("ec", pg_num=4,
                                       pool_type="erasure")
            ret, rs, _ = await c.client.mon_command(
                {"prefix": "osd pool set", "pool": "ec",
                 "var": "pg_num", "val": "2"})
            # round 7: EC merge refusal is a self-explanatory
            # -EOPNOTSUPP naming the replicated-only limitation
            assert ret == -95, (ret, rs)
            assert "erasure-coded" in rs and "replicated" in rs \
                and "EOPNOTSUPP" in rs, rs
            ret, rs, _ = await c.client.mon_command(
                {"prefix": "osd pool set", "pool": "data",
                 "var": "pg_num", "val": "0"})
            assert ret == -22
        finally:
            await c.stop()
    run(go())


def test_autoscaler_bidirectional_shrink_and_seed_reproduction():
    """Two halves on one cluster:

    1. seed reproduction — with ``mon_allow_pg_merge=false`` (the
       pre-round-6 behavior) the autoscaler keeps PROPOSING but the
       mon rejects every decrease, so an over-split pool can never
       shrink (and the direct command returns -EINVAL);
    2. flipping the knob on, the same autoscaler proposes AND executes
       the pg_num decrease through the merge barrier: the over-split
       pool lands at the recommendation with data intact."""
    async def go():
        cfg = {"mon_target_pg_per_osd": 2,
               "mgr_pg_autoscaler_interval": 0.25,
               "mon_allow_pg_merge": False}
        c = await Cluster(n_mons=1, n_osds=3, config=cfg,
                          mgr_modules=[PGAutoscalerModule]).start()
        try:
            # 8 PGs vs a recommendation of 2 (target 2/osd * 3 osds /
            # size 3 / 1 pool): over-split past the 4x threshold
            await c.client.pool_create("data", pg_num=8, size=3,
                                       min_size=2)
            await c.wait_for_clean(timeout=240)
            io = await c.client.open_ioctx("data")
            for i in range(12):
                await io.write_full(f"o-{i:03d}", bytes([i]) * 64)
            # seed reproduction: merges disabled -> the pool CANNOT
            # shrink (direct command rejected; autoscaler ticks
            # propose in vain)
            ret, rs, _ = await c.client.mon_command(
                {"prefix": "osd pool set", "pool": "data",
                 "var": "pg_num", "val": "2"})
            assert ret == -22 and "merge" in rs
            await asyncio.sleep(0.8)          # a few autoscaler ticks
            pg, _pgp, pending = await _pool_nums(c)
            assert pg == 8 and pending == 0, \
                "pool shrank with mon_allow_pg_merge=false"
            # enable merges: the SAME autoscaler now shrinks the pool
            c.cfg["mon_allow_pg_merge"] = True
            deadline = asyncio.get_event_loop().time() + 120
            while True:
                pg, _pgp, pending = await _pool_nums(c)
                if pg == 2 and not pending:
                    break
                assert asyncio.get_event_loop().time() < deadline, \
                    f"autoscaler never shrank the pool (pg_num={pg} " \
                    f"pending={pending})"
                await asyncio.sleep(0.3)
            await c.wait_for_clean(timeout=240)
            for i in range(12):
                assert await io.read(f"o-{i:03d}") == bytes([i]) * 64
            # the merge rode the cluster log
            ret, _, out = await c.client.mon_command(
                {"prefix": "log last", "num": 100})
            msgs = [ln["msg"] for ln in json.loads(out)["lines"]]
            assert any("merge started" in m for m in msgs)
            assert any("merged down to 2" in m for m in msgs)
        finally:
            await c.stop()
    run(go())


@pytest.mark.slow
def test_merge_survives_osd_down_during_fold():
    """An OSD that is DOWN while the merge commits must fold its
    stale source collections at boot (the down-during-merge case) and
    converge clean with every acked write intact."""
    async def go():
        c = await Cluster(n_mons=1, n_osds=4).start()
        try:
            await c.client.pool_create("data", pg_num=8, size=2,
                                       min_size=1)
            await c.wait_for_clean(timeout=240)
            io = await c.client.open_ioctx("data")
            acked = {f"obj-{i:03d}": bytes([i % 251]) * (48 + i)
                     for i in range(48)}
            for oid, data in acked.items():
                await io.write_full(oid, data)
            victim = 3
            await c.kill_osd(victim)
            await c.wait_for_osd_down(victim, timeout=60)
            ret, rs, _ = await c.client.mon_command(
                {"prefix": "osd pool set", "pool": "data",
                 "var": "pg_num", "val": "4"})
            assert ret == 0, rs
            await _wait_merged(c, 4, timeout=180)
            await c.revive_osd(victim)
            await c.wait_for_clean(timeout=300)
            for oid, data in acked.items():
                assert await io.read(oid) == data, oid
            for o in c.osds:
                for cid in o.store.list_collections():
                    if cid.startswith(f"{io.pool_id}."):
                        assert int(cid.split(".")[1], 16) < 4, \
                            f"leftover source collection {cid} on " \
                            f"osd.{o.whoami}"
        finally:
            await c.stop()
    run(go())
