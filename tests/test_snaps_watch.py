"""rados watch/notify + self-managed snapshots + RBD snaps/clones
through the live cluster.

ref test model: qa/workunits/rados/test_librados (watch_notify cases)
and qa/workunits/rbd (snap create/rollback/clone import-export cases) —
the round-2/3 verdicts' largest librados/librbd functional gaps
(VERDICT r3 Missing #6).
"""

import asyncio

import pytest

from ceph_tpu.cluster.vstart import Cluster
from ceph_tpu.rados import ObjectOperationError
from ceph_tpu.rbd import RBD


def run(coro):
    asyncio.run(coro)


async def _cluster(pgs=4):
    c = await Cluster(n_mons=1, n_osds=3).start()
    await c.client.pool_create("p", pg_num=pgs, size=3, min_size=2)
    await c.wait_for_clean(timeout=120)
    io = await c.client.open_ioctx("p")
    return c, io


def test_watch_notify_roundtrip():
    async def go():
        c, io = await _cluster()
        try:
            await io.write_full("obj", b"watched")
            got = []
            cookie = await io.watch(
                "obj", lambda nid, payload: got.append((nid, payload)))
            res = await io.notify("obj", b"hello-watchers")
            assert got and got[0][1] == b"hello-watchers"
            assert res["acks"] and not res["timeouts"]
            # a second client notifies; our watcher still fires
            got.clear()
            res = await io.notify("obj", b"again")
            assert got[0][1] == b"again"
            await io.unwatch("obj", cookie)
            res = await io.notify("obj", b"after-unwatch")
            assert not res["acks"]
            assert not got[1:]
        finally:
            await c.stop()
    run(go())


def test_selfmanaged_snap_cow_and_reads():
    """Write v1, snap, write v2: reads at the snap see v1 (the OSD's
    clone-on-write), head sees v2; objects created after the snap read
    -ENOENT at it; snaptrim drops the clone."""
    async def go():
        c, io = await _cluster()
        try:
            await io.write_full("a", b"version-1")
            sid = await io.selfmanaged_snap_create()
            io.set_snap_context(sid, [sid])
            await io.write_full("a", b"version-2!")
            await io.write_full("born-later", b"new")
            assert await io.read("a") == b"version-2!"
            assert await io.read("a", snap_id=sid) == b"version-1"
            assert await io.stat("a", snap_id=sid) == 9
            with pytest.raises(ObjectOperationError):
                await io.read("born-later", snap_id=sid)
            # unmodified-since-snap objects serve the head at the snap
            await io.write_full("quiet", b"still")   # after snap: -2
            # second snap: multiple clones resolve correctly
            sid2 = await io.selfmanaged_snap_create()
            io.set_snap_context(sid2, [sid2, sid])
            await io.write_full("a", b"version-3!!")
            assert await io.read("a", snap_id=sid) == b"version-1"
            assert await io.read("a", snap_id=sid2) == b"version-2!"
            assert await io.read("a") == b"version-3!!"
            # delete preserves snaps
            await io.remove("a")
            with pytest.raises(ObjectOperationError):
                await io.read("a")
            assert await io.read("a", snap_id=sid2) == b"version-2!"
            # clones never leak into listings
            names = await io.list_objects()
            assert not [n for n in names if n.startswith("_snapclone.")]
            # trim both snaps: clones disappear
            await io.snap_trim("a", sid)
            await io.snap_trim("a", sid2)
            with pytest.raises(ObjectOperationError):
                await io.read("a", snap_id=sid2)
        finally:
            await c.stop()
    run(go())


def test_snap_clone_survives_osd_failure():
    """Clone objects ride pg-log recovery like any object: kill an OSD
    after COW, write more, revive — snap reads still correct."""
    async def go():
        c = await Cluster(n_mons=1, n_osds=3,
                          config={"mon_osd_down_out_interval": 2.0}).start()
        await c.client.pool_create("p", pg_num=4, size=3, min_size=2)
        await c.wait_for_clean(timeout=120)
        io = await c.client.open_ioctx("p")
        try:
            await io.write_full("x", b"epoch-one")
            sid = await io.selfmanaged_snap_create()
            io.set_snap_context(sid, [sid])
            await io.write_full("x", b"epoch-two")     # COW happens here
            await c.kill_osd(2)
            await c.wait_for_osd_down(2, timeout=20)
            await io.write_full("x", b"epoch-three")
            await c.revive_osd(2)
            await c.wait_for_clean(timeout=120)
            assert await io.read("x") == b"epoch-three"
            assert await io.read("x", snap_id=sid) == b"epoch-one"
        finally:
            await c.stop()
    run(go())


def test_rbd_snapshots_rollback_and_clone():
    async def go():
        c, io = await _cluster()
        try:
            rbd = RBD(io)
            await rbd.create("img", size=1 << 20, order=16)  # 64K objs
            img = await rbd.open("img")
            await img.write(0, b"A" * 100_000)
            await img.snap_create("s1")
            await img.write(50_000, b"B" * 100_000)
            # read through a snapshot view
            snap_view = await rbd.open("img", snapshot="s1")
            got = await snap_view.read(0, 150_000)
            assert got[:100_000] == b"A" * 100_000
            assert got[100_000:150_000] == b"\x00" * 50_000
            head = await img.read(0, 150_000)
            assert head[:50_000] == b"A" * 50_000
            assert head[50_000:150_000] == b"B" * 100_000
            with pytest.raises(ObjectOperationError):
                await snap_view.write(0, b"nope")
            # snapshot listing + image remove refusal
            snaps = await img.snap_list()
            assert [s["name"] for s in snaps] == ["s1"]
            with pytest.raises(ObjectOperationError):
                await rbd.remove("img")
            # clone from a protected snap, with copy-up on write
            await img.snap_protect("s1")
            await rbd.clone("img", "s1", "child")
            child = await rbd.open("child")
            cg = await child.read(0, 150_000)
            assert cg[:100_000] == b"A" * 100_000     # parent fallthrough
            await child.write(10, b"C" * 5)
            cg = await child.read(0, 100)
            assert cg[:10] == b"A" * 10 and cg[10:15] == b"C" * 5
            # parent head unchanged by child write
            head2 = await img.read(0, 100)
            assert head2 == head[:100]
            # unprotect refused while the child exists
            with pytest.raises(ObjectOperationError):
                img2 = await rbd.open("img")
                await img2.snap_unprotect("s1")
            # rollback restores s1 state on the parent head
            await img.snap_rollback("s1")
            rb = await img.read(0, 150_000)
            assert rb[:100_000] == b"A" * 100_000
            assert rb[100_000:] == b"\x00" * 50_000
        finally:
            await c.stop()
    run(go())
