"""ECBackend-lite tests: stripe math, RMW partial writes, recovery via
minimum_to_decode, scrub localization, and churn-sim hole recovery
(VERDICT round-1 item #4; ref: src/osd/ECUtil.h, ECCommon.h, ECBackend.cc)."""

import numpy as np
import pytest

from ceph_tpu.ec import factory
from ceph_tpu.osd.ec_backend import ECBackendLite, ShardMissing
from ceph_tpu.osd.ecutil import StripeInfo


class TestStripeInfo:
    def test_bounds(self):
        si = StripeInfo(k=4, chunk_size=256)   # stripe width 1024
        assert si.stripe_width == 1024
        assert si.logical_to_prev_stripe_offset(1023) == 0
        assert si.logical_to_prev_stripe_offset(1024) == 1024
        assert si.logical_to_next_stripe_offset(1) == 1024
        assert si.logical_to_next_stripe_offset(1024) == 1024
        assert si.offset_len_to_stripe_bounds(100, 2000) == (0, 3072)
        assert si.stripe_range(1024, 1024) == (1, 1)
        assert si.stripe_range(1000, 100) == (0, 2)

    def test_chunk_offsets(self):
        si = StripeInfo(k=4, chunk_size=256)
        assert si.aligned_logical_offset_to_chunk_offset(2048) == 512
        assert si.chunk_aligned_logical_offset(512) == 2048
        assert si.logical_to_stripe_chunk(0) == (0, 0, 0)
        assert si.logical_to_stripe_chunk(256) == (0, 1, 0)
        assert si.logical_to_stripe_chunk(1024 + 300) == (1, 1, 44)
        assert si.object_stripes(0) == 0
        assert si.object_stripes(1) == 1
        assert si.object_stripes(1025) == 2


def make_backend(k=4, m=2, chunk=256, plugin="jax"):
    ec = factory(f"plugin={plugin} technique=reed_sol_van k={k} m={m}")
    return ECBackendLite(ec, chunk_size=chunk, name=f"test_{k}_{m}_{chunk}")


class TestRmwWrites:
    def test_aligned_roundtrip(self):
        be = make_backend()
        data = bytes(range(256)) * 16          # 4 stripes exactly
        be.write("obj", 0, data)
        assert be.read("obj", 0, len(data)) == data

    def test_unaligned_offsets_match_model(self):
        """Random writes at unaligned offsets: backend == bytearray model."""
        be = make_backend()
        rng = np.random.default_rng(5)
        model = bytearray(16 << 10)
        high = 0
        for _ in range(25):
            off = int(rng.integers(0, 12 << 10))
            ln = int(rng.integers(1, 3 << 10))
            payload = rng.integers(0, 256, ln, dtype=np.uint8).tobytes()
            be.write("obj", off, payload)
            model[off:off + ln] = payload
            high = max(high, off + ln)
            assert be.read("obj", 0, high) == bytes(model[:high])
        # every shard consistent after arbitrary RMW history
        assert be.scrub("obj") == []

    def test_rmw_counts_partial_stripes(self):
        be = make_backend()
        be.write("obj", 0, b"x" * 1024)         # aligned: no RMW
        assert be.perf.dump()["rmw_stripes"] == 0
        be.write("obj", 100, b"y" * 10)         # partial: RMW
        assert be.perf.dump()["rmw_stripes"] == 1
        want = b"x" * 100 + b"y" * 10 + b"x" * 914
        assert be.read("obj", 0, 1024) == want

    def test_sparse_write_zero_fills(self):
        be = make_backend()
        be.write("obj", 3000, b"tail")
        assert be.read("obj", 0, 3000) == b"\0" * 3000
        assert be.read("obj", 3000, 4) == b"tail"


class TestRecovery:
    @pytest.mark.parametrize("lost", [[0], [5], [1, 4], [2, 3]])
    def test_recover_lost_shards(self, lost):
        be = make_backend()
        rng = np.random.default_rng(7)
        data = rng.integers(0, 256, 5000, dtype=np.uint8).tobytes()
        be.write("obj", 0, data)
        for s in lost:
            be.lose_shard(s, "obj")
        assert be.missing_shards("obj") == set(lost)
        plan_lost, to_read = be.recovery_plan("obj")
        assert plan_lost == set(lost)
        assert to_read <= set(range(6)) - set(lost)
        assert len(to_read) <= 4                # MDS: k reads suffice
        recovered = be.recover("obj")
        assert recovered == set(lost)
        assert be.missing_shards("obj") == set()
        assert be.read("obj", 0, len(data)) == data
        assert be.scrub("obj") == []

    def test_data_read_blocked_until_recovered(self):
        be = make_backend()
        be.write("obj", 0, b"a" * 4096)
        be.lose_shard(1, "obj")
        with pytest.raises(ShardMissing):
            be.read("obj", 0, 4096)
        be.recover("obj")
        assert be.read("obj", 0, 4096) == b"a" * 4096

    def test_recover_all_multiple_objects(self):
        be = make_backend()
        payloads = {}
        rng = np.random.default_rng(9)
        for i in range(4):
            payloads[f"o{i}"] = rng.integers(0, 256, 2048,
                                             dtype=np.uint8).tobytes()
            be.write(f"o{i}", 0, payloads[f"o{i}"])
        be.lose_shard(2)                        # whole-shard loss (OSD died)
        fixed = be.recover_all()
        assert set(fixed) == {f"o{i}" for i in range(4)}
        for oid, want in payloads.items():
            assert be.read(oid, 0, len(want)) == want

    def test_lrc_recovery_reads_fewer_than_k(self):
        """LRC local repair: single lost shard needs only its layer."""
        ec = factory("plugin=lrc k=4 m=2 l=3")
        be = ECBackendLite(ec, chunk_size=128, name="test_lrc")
        rng = np.random.default_rng(3)
        data = rng.integers(0, 256, 4096, dtype=np.uint8).tobytes()
        be.write("obj", 0, data)
        be.lose_shard(0, "obj")
        _, to_read = be.recovery_plan("obj")
        assert len(to_read) < ec.get_data_chunk_count() + \
            ec.get_coding_chunk_count() - 1   # strictly local, not global
        be.recover("obj")
        assert be.read("obj", 0, len(data)) == data


class TestScrub:
    def test_detects_and_localizes_corruption(self):
        be = make_backend()
        rng = np.random.default_rng(11)
        be.write("obj", 0, rng.integers(0, 256, 4096,
                                        dtype=np.uint8).tobytes())
        assert be.scrub("obj") == []
        be.shards[3]["obj"][1, 7] ^= 0xFF       # silent single-shard flip
        assert be.scrub("obj") == [3]
        # parity shard corruption localizes too
        be.shards[3]["obj"][1, 7] ^= 0xFF       # restore
        be.shards[5]["obj"][0, 0] ^= 1
        assert be.scrub("obj") == [5]


class TestChurnRecovery:
    def test_churn_holes_recovered_by_decode(self):
        """The round-1 churn sim only *reported* EC holes; holes must now
        be repaired by decode: when an OSD dies, each degraded PG's
        object recovers its lost shard and the data survives."""
        from ceph_tpu.bench import osdmaptool
        from ceph_tpu.sim import ChurnEvent, ChurnSim

        m = osdmaptool.create_simple(12, 16, 5, erasure=True)  # k=3 m=2
        sim = ChurnSim(m, 1)
        rng = np.random.default_rng(13)
        # one object per PG, stored in a per-PG EC backend keyed by shard
        backends = {}
        payloads = {}
        for pg in range(16):
            be = make_backend(k=3, m=2, chunk=128)
            data = rng.integers(0, 256, 1536, dtype=np.uint8).tobytes()
            be.write(f"pg{pg}", 0, data)
            backends[pg] = be
            payloads[pg] = data
        victim = int(sim._up[0, 0])
        up_before = sim._up.copy()
        sim.apply(ChurnEvent("down", victim))
        # shard s of pg is lost iff the victim held slot s before
        for pg in range(16):
            for slot in range(5):
                if up_before[pg, slot] == victim:
                    backends[pg].lose_shard(slot, f"pg{pg}")
        recovered = 0
        for pg in range(16):
            fixed = backends[pg].recover(f"pg{pg}")
            recovered += len(fixed)
            assert backends[pg].read(f"pg{pg}", 0, 1536) == payloads[pg]
            assert backends[pg].scrub(f"pg{pg}") == []
        assert recovered > 0                    # the victim held shards
