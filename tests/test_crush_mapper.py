"""CRUSH mapper tests.

Two tiers, mirroring the reference's crush test strategy
(ref: src/test/crush/TestCrushWrapper.cc + crushtool --test fixtures):
1. semantic assertions on the scalar spec (distinct failure domains,
   weight proportionality, reweight-out behavior);
2. exact cross-validation of the vectorized JAX mapper against the scalar
   spec over a matrix of map shapes, algorithms and rules, including
   randomized maps.
"""

import numpy as np
import pytest

from ceph_tpu.crush import builder, mapper_ref
from ceph_tpu.crush.mapper import Mapper
from ceph_tpu.crush.types import (
    ALG_LIST, ALG_STRAW2, ALG_UNIFORM, ITEM_NONE, WEIGHT_ONE,
    OP_CHOOSE_FIRSTN, OP_CHOOSE_INDEP, OP_CHOOSELEAF_FIRSTN, RuleStep,
    Tunables,
)

N_X = 256  # xs per config; full sweeps ran during bring-up


def assert_match(m, rid, numrep, xs=None, weights=None):
    xs = xs if xs is not None else np.arange(N_X, dtype=np.uint32)
    mapper = Mapper(m, np.asarray(weights, dtype=np.int64)
                    if weights is not None else None)
    got = np.asarray(mapper.map_pgs(rid, xs, numrep))
    wl = list(weights) if weights is not None else None
    for i, x in enumerate(xs):
        ref = mapper_ref.do_rule(m, rid, int(x), numrep, weight=wl)
        ref = ref + [ITEM_NONE] * (numrep - len(ref))
        assert list(got[i]) == ref, (int(x), list(got[i]), ref)


class TestScalarSemantics:
    def test_firstn_distinct_and_complete(self):
        m, root = builder.build_flat(10)
        rid = builder.add_simple_rule(m, root, builder.TYPE_OSD)
        for x in range(300):
            out = mapper_ref.do_rule(m, rid, x, 3)
            assert len(out) == 3 and len(set(out)) == 3

    def test_chooseleaf_distinct_hosts(self):
        m, root = builder.build_hierarchy(6, 4)
        rid = builder.add_simple_rule(m, root, builder.TYPE_HOST)
        for x in range(300):
            out = mapper_ref.do_rule(m, rid, x, 3)
            hosts = {o // 4 for o in out}
            assert len(hosts) == 3

    def test_indep_positions_and_domains(self):
        m, root = builder.build_hierarchy(8, 2)
        rid = builder.add_simple_rule(m, root, builder.TYPE_HOST, indep=True)
        for x in range(200):
            out = mapper_ref.do_rule(m, rid, x, 6)
            assert len(out) == 6
            real = [o for o in out if o != ITEM_NONE]
            assert len({o // 2 for o in real}) == len(real)

    def test_reweight_zero_excludes(self):
        m, root = builder.build_flat(5)
        rid = builder.add_simple_rule(m, root, builder.TYPE_OSD)
        w = [0x10000, 0, 0x10000, 0x10000, 0x10000]
        for x in range(200):
            assert 1 not in mapper_ref.do_rule(m, rid, x, 3, weight=w)

    def test_mapping_stability_under_weight_change(self):
        """CRUSH's core promise: adjusting one item's weight only moves
        data to/from that item (statistically)."""
        m1, root1 = builder.build_flat(8)
        r1 = builder.add_simple_rule(m1, root1, builder.TYPE_OSD)
        w2 = [WEIGHT_ONE] * 8
        w2[3] = WEIGHT_ONE // 2
        m2, root2 = builder.build_flat(8, weights=w2)
        r2 = builder.add_simple_rule(m2, root2, builder.TYPE_OSD)
        moved_not_involving_3 = 0
        total_moved = 0
        for x in range(500):
            a = mapper_ref.do_rule(m1, r1, x, 1)[0]
            b = mapper_ref.do_rule(m2, r2, x, 1)[0]
            if a != b:
                total_moved += 1
                if a != 3 and b != 3:
                    moved_not_involving_3 += 1
        assert total_moved > 0
        assert moved_not_involving_3 == 0

    def test_legacy_tunables_run(self):
        """The scalar spec also executes legacy tunables (retries>0)."""
        m, root = builder.build_hierarchy(4, 3, tunables=Tunables.legacy())
        rid = builder.add_simple_rule(m, root, builder.TYPE_HOST)
        out = mapper_ref.do_rule(m, rid, 42, 3)
        assert len(out) == 3


class TestJaxMatchesScalar:
    def test_flat_straw2(self):
        m, root = builder.build_flat(10)
        rid = builder.add_simple_rule(m, root, builder.TYPE_OSD)
        assert_match(m, rid, 3)

    def test_flat_list(self):
        m, root = builder.build_flat(7, alg=ALG_LIST)
        rid = builder.add_simple_rule(m, root, builder.TYPE_OSD)
        assert_match(m, rid, 3)

    def test_hierarchy_chooseleaf_firstn(self):
        m, root = builder.build_hierarchy(6, 4)
        rid = builder.add_simple_rule(m, root, builder.TYPE_HOST)
        assert_match(m, rid, 3)

    def test_hierarchy_chooseleaf_indep(self):
        m, root = builder.build_hierarchy(6, 4)
        rid = builder.add_simple_rule(m, root, builder.TYPE_HOST, indep=True)
        assert_match(m, rid, 5)

    @pytest.mark.slow
    def test_uniform_buckets(self):
        m, root = builder.build_hierarchy(5, 4, alg=ALG_UNIFORM)
        rid = builder.add_simple_rule(m, root, builder.TYPE_HOST)
        assert_match(m, rid, 3)
        rid2 = builder.add_simple_rule(m, root, builder.TYPE_HOST, indep=True)
        assert_match(m, rid2, 4)

    @pytest.mark.slow
    def test_three_level_multistep(self):
        m, root = builder.build_hierarchy(8, 2, n_racks=4)
        rid = builder.add_multistep_rule(m, root, [
            RuleStep(OP_CHOOSE_FIRSTN, 2, builder.TYPE_RACK),
            RuleStep(OP_CHOOSELEAF_FIRSTN, 2, builder.TYPE_HOST)])
        assert_match(m, rid, 4)

    @pytest.mark.slow
    def test_choose_indep_direct_osd(self):
        m, root = builder.build_hierarchy(6, 3)
        rid = builder.add_multistep_rule(
            m, root, [RuleStep(OP_CHOOSE_INDEP, 0, 0)], indep=True)
        assert_match(m, rid, 4)

    @pytest.mark.slow
    def test_failure_holes(self):
        """More shards than failure domains: indep emits NONE holes,
        firstn underfills — both must match the spec exactly."""
        m, root = builder.build_hierarchy(4, 2)
        ri = builder.add_simple_rule(m, root, builder.TYPE_HOST, indep=True)
        assert_match(m, ri, 5)
        rf = builder.add_simple_rule(m, root, builder.TYPE_HOST)
        assert_match(m, rf, 5)

    def test_weights_and_reweights(self):
        m, root = builder.build_flat(
            6, weights=[2 * WEIGHT_ONE, WEIGHT_ONE, WEIGHT_ONE, 0,
                        WEIGHT_ONE, WEIGHT_ONE // 2])
        rid = builder.add_simple_rule(m, root, builder.TYPE_OSD)
        assert_match(m, rid, 3,
                     weights=[0x10000, 0x8000, 0x10000, 0x10000, 0, 0x4000])

    @pytest.mark.slow
    def test_out_of_range_device_rejected_both_paths(self):
        """A device id beyond the reweight vector is out (ref: mapper.c
        is_out item >= weight_max) — and BOTH compiled variants
        (skip_is_out True/False) must agree with the scalar spec, so a
        reweight flip cannot change placement of out-of-range ids
        (ADVICE r3 low #3)."""
        m, root = builder.build_flat(8)
        rid = builder.add_simple_rule(m, root, builder.TYPE_OSD)
        # 5-entry reweight vector: devices 5..7 are out-of-range
        full = [0x10000] * 5                    # skip_is_out compiles True
        assert_match(m, rid, 3, weights=full)
        mixed = [0x10000, 0x8000, 0x10000, 0x10000, 0x10000]  # general path
        assert_match(m, rid, 3, weights=mixed)

    def test_zero_weight_subtree(self):
        m, root = builder.build_hierarchy(
            4, 3, osd_weights=[0, 0, 0] + [WEIGHT_ONE] * 9)
        rid = builder.add_simple_rule(m, root, builder.TYPE_HOST)
        assert_match(m, rid, 3)

    @pytest.mark.slow
    def test_randomized_maps(self, rng):
        """Fuzz: random hierarchy shapes, algs, weights, rule kinds."""
        for trial in range(4):
            n_hosts = int(rng.integers(3, 9))
            per = int(rng.integers(1, 5))
            alg = [ALG_STRAW2, ALG_UNIFORM, ALG_LIST][trial % 3]
            weights = [int(w) for w in rng.integers(
                0, 4 * WEIGHT_ONE, size=n_hosts * per)]
            if alg == ALG_UNIFORM:
                weights = [WEIGHT_ONE] * (n_hosts * per)
            m, root = builder.build_hierarchy(n_hosts, per, alg=alg,
                                              osd_weights=weights)
            indep = bool(trial % 2)
            rid = builder.add_simple_rule(m, root, builder.TYPE_HOST,
                                          indep=indep)
            numrep = int(rng.integers(2, min(n_hosts, 6) + 1))
            xs = rng.integers(0, 2 ** 32, size=128, dtype=np.uint32)
            assert_match(m, rid, numrep, xs=xs)

    def test_device_weight_update_no_recompile(self):
        m, root = builder.build_flat(6)
        rid = builder.add_simple_rule(m, root, builder.TYPE_OSD)
        mapper = Mapper(m)
        xs = np.arange(64, dtype=np.uint32)
        a = np.asarray(mapper.map_pgs(rid, xs, 2))
        w = np.full(6, WEIGHT_ONE, dtype=np.int64)
        w[0] = 0
        mapper.set_device_weights(w)
        b = np.asarray(mapper.map_pgs(rid, xs, 2))
        assert not np.array_equal(a, b)
        assert 0 not in b

    def test_legacy_tunables_fall_back_to_scalar(self):
        """stable=0 / local-retries maps route through the scalar spec
        transparently (round 1 raised NotImplementedError)."""
        m, root = builder.build_flat(6, tunables=Tunables.legacy())
        rid = builder.add_simple_rule(m, root, builder.TYPE_OSD)
        mapper = Mapper(m)
        assert mapper._scalar_reason
        xs = np.arange(64, dtype=np.uint32)
        got = np.asarray(mapper.map_pgs(rid, xs, 3))
        for i, x in enumerate(xs):
            ref = mapper_ref.do_rule(m, rid, int(x), 3)
            ref = ref + [ITEM_NONE] * (3 - len(ref))
            assert list(got[i]) == ref
        counts, bad = mapper.sweep(rid, 0, 64, 3)
        assert np.asarray(counts).sum() == (got != ITEM_NONE).sum()

    @pytest.mark.slow
    def test_straw_v1_matches_scalar(self):
        from ceph_tpu.crush.types import ALG_STRAW
        rng = np.random.default_rng(3)
        weights = [int(w) for w in rng.integers(
            1, 4 * WEIGHT_ONE, size=12)]
        m, root = builder.build_hierarchy(4, 3, alg=ALG_STRAW,
                                          osd_weights=weights)
        rid = builder.add_simple_rule(m, root, builder.TYPE_HOST)
        assert_match(m, rid, 3)

    @pytest.mark.slow
    def test_tree_matches_scalar(self):
        from ceph_tpu.crush.types import ALG_TREE
        rng = np.random.default_rng(4)
        weights = [int(w) for w in rng.integers(
            1, 4 * WEIGHT_ONE, size=10)]  # 5 hosts x 2: non-pow2 sizes
        m, root = builder.build_hierarchy(5, 2, alg=ALG_TREE,
                                          osd_weights=weights)
        rid = builder.add_simple_rule(m, root, builder.TYPE_HOST)
        assert_match(m, rid, 3)

    def test_straw_tree_distribution_weight_proportional(self):
        """Statistical: straw/tree selection tracks weights (the property
        the algorithms exist for), single-level argmax."""
        from ceph_tpu.crush.types import ALG_STRAW, ALG_TREE
        for alg in (ALG_STRAW, ALG_TREE):
            weights = [WEIGHT_ONE, 2 * WEIGHT_ONE, WEIGHT_ONE,
                       4 * WEIGHT_ONE]
            m, root = builder.build_flat(4, alg=alg, weights=weights)
            rid = builder.add_simple_rule(m, root, builder.TYPE_OSD)
            mapper = Mapper(m)
            xs = np.arange(8000, dtype=np.uint32)
            got = np.asarray(mapper.map_pgs(rid, xs, 1))[:, 0]
            counts = np.bincount(got, minlength=4).astype(float)
            frac = counts / counts.sum()
            want = np.asarray(weights, dtype=float)
            want /= want.sum()
            assert np.abs(frac - want).max() < 0.04, (alg, frac, want)


class TestChooseArgs:
    def _map_with_args(self, positions=1):
        from ceph_tpu.crush.types import ChooseArg
        m, root = builder.build_flat(6)
        rid = builder.add_simple_rule(m, root, builder.TYPE_OSD)
        ws = [[WEIGHT_ONE, WEIGHT_ONE, 3 * WEIGHT_ONE, WEIGHT_ONE,
               0, WEIGHT_ONE][:6] for _ in range(positions)]
        if positions > 1:
            ws[1] = [2 * WEIGHT_ONE] * 6
        m.choose_args[0] = {root: ChooseArg(weight_set=ws)}
        return m, rid, root

    def test_weight_set_changes_placement_and_matches_scalar(self):
        m, rid, root = self._map_with_args()
        xs = np.arange(256, dtype=np.uint32)
        plain = np.asarray(Mapper(m).map_pgs(rid, xs, 2))
        witharg = np.asarray(Mapper(m, choose_args=0).map_pgs(rid, xs, 2))
        assert not np.array_equal(plain, witharg)
        assert 4 not in witharg            # zero weight in the weight-set
        cargs = m.choose_args[0]
        for i, x in enumerate(xs):
            ref = mapper_ref.do_rule(m, rid, int(x), 2, choose_args=cargs)
            assert list(witharg[i]) == ref

    def test_multi_position_weight_set(self):
        m, rid, root = self._map_with_args(positions=2)
        xs = np.arange(128, dtype=np.uint32)
        got = np.asarray(Mapper(m, choose_args=0).map_pgs(rid, xs, 2))
        cargs = m.choose_args[0]
        for i, x in enumerate(xs):
            ref = mapper_ref.do_rule(m, rid, int(x), 2, choose_args=cargs)
            assert list(got[i]) == ref

    def test_ids_override_changes_hash(self):
        from ceph_tpu.crush.types import ChooseArg
        m, root = builder.build_flat(4)
        rid = builder.add_simple_rule(m, root, builder.TYPE_OSD)
        m.choose_args[0] = {root: ChooseArg(ids=[100, 101, 102, 103])}
        xs = np.arange(256, dtype=np.uint32)
        plain = np.asarray(Mapper(m).map_pgs(rid, xs, 1))
        withids = np.asarray(Mapper(m, choose_args=0).map_pgs(rid, xs, 1))
        assert not np.array_equal(plain, withids)
        cargs = m.choose_args[0]
        for i, x in enumerate(xs):
            ref = mapper_ref.do_rule(m, rid, int(x), 1, choose_args=cargs)
            assert list(withids[i]) == ref


class TestDerivedStateInvalidation:
    def test_straw_weight_adjust_recomputes(self):
        """Mutating a straw bucket's weight must recompute straws (ref:
        crush_bucket_adjust_item_weight recalculation)."""
        from ceph_tpu.crush.types import ALG_STRAW
        m, root = builder.build_flat(4, alg=ALG_STRAW)
        rid = builder.add_simple_rule(m, root, builder.TYPE_OSD)
        before = list(m.buckets[root].straws)
        builder.adjust_item_weight(m, 0, 8 * WEIGHT_ONE)
        after = list(m.buckets[root].straws)
        assert before != after
        assert_match(m, rid, 2)   # vectorized still matches the spec

    @pytest.mark.slow
    def test_tree_insert_adds_leaf(self):
        from ceph_tpu.crush.types import ALG_TREE
        m, root = builder.build_flat(4, alg=ALG_TREE)
        rid = builder.add_simple_rule(m, root, builder.TYPE_OSD)
        m.max_devices = 5
        builder.insert_item(m, 4, WEIGHT_ONE, root)
        assert len(m.buckets[root].node_weights) >= 10
        mapper = Mapper(m)
        xs = np.arange(4096, dtype=np.uint32)
        got = np.asarray(mapper.map_pgs(rid, xs, 1))[:, 0]
        assert (got == 4).any()   # new item reachable
        assert_match(m, rid, 2)


class TestUniformFastPath:
    """The round-3 uniform-weight straw2 shortcut (argmax over raw
    hashes + ln-equality tie repair) must be bit-exact vs the scalar
    spec, including at engineered draw-tie collisions."""

    def test_ln_gap_info_invariants(self):
        from ceph_tpu.crush.ln_table import crush_ln, ln_gap_info
        G, zg = ln_gap_info()
        t = crush_ln(np.arange(0x10000, dtype=np.int64))
        d = np.diff(t)
        assert G == int(d[d > 0].min()) > 0
        assert np.array_equal(zg[:-1], d == 0)
        assert not zg[-1]
        # classes are adjacent pairs only
        runs = np.diff(np.where(d == 0)[0])
        assert not (runs == 1).any()

    def test_zg_tie_collision_matches_scalar(self):
        """x values engineered so two bucket items hash into one
        ln-equality pair with the LOWER value at an EARLIER index: a
        naive hash argmax would pick the wrong item; the scalar picks
        the first index of the draw-tie class."""
        m, root = builder.build_flat(8)           # uniform weights
        rid = builder.add_simple_rule(m, root, builder.TYPE_OSD)
        mapper = Mapper(m)
        assert mapper._all_uniform
        xs = np.array([10232, 11311, 24792], dtype=np.uint32)
        got = np.asarray(mapper.map_pgs(rid, xs, 1))
        for i, x in enumerate(xs):
            ref = mapper_ref.do_rule(m, rid, int(x), 1)
            assert got[i, 0] == ref[0], (x, got[i, 0], ref)

    def test_uniform_flag_gating(self):
        from ceph_tpu.crush.ln_table import ln_gap_info
        G, _ = ln_gap_info()
        m, root = builder.build_flat(4)
        mapper = Mapper(m)
        assert mapper._all_uniform and mapper._skip_is_out
        # non-uniform weights -> general path
        m2, root2 = builder.build_flat(4)
        m2.buckets[root2].weights[0] = 3 * WEIGHT_ONE
        mp2 = Mapper(m2)
        assert not mp2._all_uniform
        # huge uniform weight above the ln-gap bound -> general path
        m3, root3 = builder.build_flat(4)
        for i in range(4):
            m3.buckets[root3].weights[i] = G + 1
        assert not Mapper(m3)._all_uniform
        # reweighted device -> is_out compiled back in
        w = np.full(4, WEIGHT_ONE, dtype=np.int64)
        w[1] = WEIGHT_ONE // 2
        mapper.set_device_weights(w)
        assert not mapper._skip_is_out

    def test_uniform_vs_scalar_randomized(self, rng):
        """Hierarchy of uniform-weight buckets: fast path everywhere,
        must match the scalar spec over a random x sample."""
        m, root = builder.build_hierarchy(8, 4, n_racks=2)
        rid = builder.add_simple_rule(m, root, builder.TYPE_HOST)
        mapper = Mapper(m)
        assert mapper._all_uniform
        xs = rng.integers(0, 1 << 30, 256).astype(np.uint32)
        got = np.asarray(mapper.map_pgs(rid, xs, 3))
        for i, x in enumerate(xs):
            ref = mapper_ref.do_rule(m, rid, int(x), 3)
            ref = ref + [ITEM_NONE] * (3 - len(ref))
            assert list(got[i]) == ref, (x,)
