"""Multi-controller (multi-host) SPMD over the DCN boundary.

Two coordinated worker PROCESSES (4 virtual CPU devices each) form a
global 8-device mesh whose host axis is the process boundary — the
testable stand-in for a TPU pod's DCN (SURVEY.md §5.8: the reference's
NCCL/MPI multi-host backend seat). Each worker runs the sharded EC and
CRUSH pipelines over the global mesh and asserts them bit-equal to
local single-process computation; the test asserts both workers agree.
"""

import json
import os
import socket
import subprocess
import sys

def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_dcn_mesh():
    port = _free_port()
    coord = f"127.0.0.1:{port}"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "ceph_tpu.parallel.multihost",
             "--coordinator", coord, "--num-processes", "2",
             "--process-id", str(i)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env)
        for i in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=540)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        assert p.returncode == 0, err.decode()[-2000:]
        outs.append(json.loads(out.decode().strip().splitlines()[-1]))
    a, b = outs
    assert a["ok"] and b["ok"]
    assert a["processes"] == b["processes"] == 2
    assert a["global_devices"] == b["global_devices"] == 8
    # both controllers computed the SAME replicated results
    assert a["ec_checksum"] == b["ec_checksum"]
    assert a["crush_placements"] == b["crush_placements"]
