"""Golden fixtures for GF(2^8) / Reed-Solomon, via an independent oracle.

The in-test GF implementation below uses Russian-peasant (shift-and-xor)
multiplication over polynomial 0x11d and Gaussian elimination over plain
Python ints — no log/antilog tables, no numpy vectorization — so it shares
no code or construction style with ceph_tpu/gf (which builds log tables and
bit-matrices). A transposition bug in one would not replicate in the other.

Also pins hex constants that are external mathematical facts:
- The GF(2^8)/0x11d antilog chain: g=2 powers 2,4,8,...,0x1d wrap.
- jerasure's reed_sol_van construction: rows i of the m x k coding matrix
  are vandermonde-derived (ref: jerasure reed_sol_vandermonde_coding_matrix,
  consumed by src/erasure-code/jerasure/ErasureCodeJerasure.cc).
"""

import numpy as np
import pytest


# ---------------------------------------------------------------------------
# Independent GF(2^8) arithmetic (0x11d), shift-and-xor only
# ---------------------------------------------------------------------------

def gmul(a: int, b: int) -> int:
    p = 0
    for _ in range(8):
        if b & 1:
            p ^= a
        b >>= 1
        hi = a & 0x80
        a = (a << 1) & 0xFF
        if hi:
            a ^= 0x1D          # x^8 = x^4+x^3+x^2+1 (0x11d reduced)
    return p


def gpow(a: int, n: int) -> int:
    r = 1
    while n:
        if n & 1:
            r = gmul(r, a)
        a = gmul(a, a)
        n >>= 1
    return r


def ginv(a: int) -> int:
    assert a != 0
    return gpow(a, 254)        # a^(2^8-2)


class TestGfPrimitive:
    def test_antilog_chain_constants(self):
        # powers of the generator 2: external facts of GF(2^8)/0x11d
        want = [1, 2, 4, 8, 16, 32, 64, 128, 0x1D, 0x3A, 0x74, 0xE8,
                0xCD, 0x87, 0x13, 0x26]
        v = 1
        for i, w in enumerate(want):
            assert v == w, i
            v = gmul(v, 2)

    def test_mul_table_matches_repo(self):
        from ceph_tpu.gf.tables import mul_table
        t = mul_table()
        rng = np.random.default_rng(3)
        for _ in range(500):
            a, b = int(rng.integers(256)), int(rng.integers(256))
            assert int(t[a, b]) == gmul(a, b), (a, b)

    def test_inverse_matches_repo(self):
        from ceph_tpu.gf import tables
        for a in range(1, 256):
            assert gmul(a, ginv(a)) == 1


# ---------------------------------------------------------------------------
# Independent RS-Vandermonde construction + parity golden check
# ---------------------------------------------------------------------------

def vandermonde_rs_matrix(k: int, m: int) -> list[list[int]]:
    """Plank's reed_sol_van construction (the one jerasure ships):
    EXTENDED Vandermonde — row 0 = e_0, rows 1..k+m-2 = [i^j], last row =
    e_{k-1} — column-eliminated to [I; C], then row k scaled (via column
    scaling) to all ones and later rows scaled so column 0 is one.
    Plain-int arithmetic, coded independently of ceph_tpu/ec/matrix.py.
    (ref: jerasure reed_sol.c reed_sol_big_vandermonde_distribution_matrix)
    """
    rows = k + m
    vdm = [[0] * k for _ in range(rows)]
    vdm[0][0] = 1
    vdm[rows - 1][k - 1] = 1
    for i in range(1, rows - 1):
        acc = 1
        for j in range(k):
            vdm[i][j] = acc
            acc = gmul(acc, i)
    # column-eliminate the top k x k block to identity, diagonal order
    for i in range(1, k):
        if vdm[i][i] == 0:
            for j in range(i + 1, rows):
                if vdm[j][i]:
                    vdm[i], vdm[j] = vdm[j], vdm[i]
                    break
        piv = ginv(vdm[i][i])
        for r in range(rows):
            vdm[r][i] = gmul(vdm[r][i], piv)
        for j in range(k):
            e = vdm[i][j]
            if j != i and e:
                for r in range(rows):
                    vdm[r][j] ^= gmul(e, vdm[r][i])
    if rows > k:
        # scale columns so row k is all ones (only rows >= k are affected
        # below the identity block)
        for j in range(k):
            e = vdm[k][j]
            inv = ginv(e)
            for r in range(k, rows):
                vdm[r][j] = gmul(vdm[r][j], inv)
        # scale each later row so its first element is one
        for i in range(k + 1, rows):
            inv = ginv(vdm[i][0])
            vdm[i] = [gmul(v, inv) for v in vdm[i]]
    return [row[:] for row in vdm[k:]]


def encode_scalar(matrix, data):
    """(m x k) GF matrix times (k, C) bytes, shift-and-xor only."""
    m, k = len(matrix), len(matrix[0])
    C = len(data[0])
    out = [[0] * C for _ in range(m)]
    for i in range(m):
        for j in range(k):
            coef = matrix[i][j]
            if coef == 0:
                continue
            row = data[j]
            orow = out[i]
            for c in range(C):
                orow[c] ^= gmul(coef, row[c])
    return out


class TestRsGolden:
    @pytest.mark.parametrize("k,m", [(4, 2), (8, 3)])
    def test_coding_matrix_matches_independent(self, k, m):
        from ceph_tpu.ec.matrix import coding_matrix
        got = coding_matrix("reed_sol_van", k, m)
        want = vandermonde_rs_matrix(k, m)
        assert got.shape == (m, k)
        for i in range(m):
            for j in range(k):
                assert int(got[i, j]) == want[i][j], (i, j)

    @pytest.mark.parametrize("k,m", [(4, 2), (8, 3)])
    def test_parity_bytes_match_independent(self, k, m):
        """encode() through the full plugin path must produce byte-exactly
        the parity the independent scalar oracle computes."""
        from ceph_tpu.ec import factory
        ec = factory(f"plugin=jax technique=reed_sol_van k={k} m={m}")
        rng = np.random.default_rng(11)
        C = 256
        payload = rng.integers(0, 256, size=k * C, dtype=np.uint8).tobytes()
        enc = ec.encode(range(k + m), payload)
        data_rows = [list(payload[j * C:(j + 1) * C]) for j in range(k)]
        want_parity = encode_scalar(vandermonde_rs_matrix(k, m), data_rows)
        for i in range(m):
            assert list(enc[k + i]) == want_parity[i], f"parity row {i}"

    def test_first_parity_row_is_xor(self):
        """Vandermonde row 0 is all-ones: parity chunk 0 == XOR of data
        chunks — an external structural fact of reed_sol_van."""
        from ceph_tpu.ec import factory
        ec = factory("plugin=jax technique=reed_sol_van k=5 m=2")
        rng = np.random.default_rng(12)
        data = rng.integers(0, 256, size=(5, 128), dtype=np.uint8)
        parity = np.asarray(ec.encode_chunks(data))
        assert (parity[0] == np.bitwise_xor.reduce(data, axis=0)).all()
