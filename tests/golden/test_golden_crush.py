"""Golden fixtures for CRUSH primitives, pinned to external constants.

Round 1's weakness (VERDICT Missing #6): every oracle in the repo was
written by the same author from the same knowledge, so a shared
misremembering would pass silently. This file pins what CAN be pinned
without the (empty) reference mount:

1. crush_ln table anchors: remembered upstream __RH_LH_tbl constants,
   stated as hex literals here, NOT derived from repo code
   (ref: src/crush/crush_ln_table.h).
2. An INDEPENDENT scalar rjenkins1 implementation written in plain Python
   ints with explicit masking — structurally different from
   ceph_tpu/crush/hash.py's array code — cross-checked on many inputs.
3. An independent crush_ln reimplementation in plain Python ints
   (different normalization loop), cross-checked over the full domain.

ref: src/crush/hash.c crush_hash32_rjenkins1_3; src/crush/mapper.c crush_ln.
"""

import numpy as np
import pytest

M32 = 0xFFFFFFFF


# ---------------------------------------------------------------------------
# 1. Table anchors (hex literals, not computed by repo code)
# ---------------------------------------------------------------------------

class TestLnTableAnchors:
    def test_rh_lh_first_pairs(self):
        from ceph_tpu.crush.ln_table import rh_lh_tables
        rh, lh = rh_lh_tables()
        # index1=256: RH = 2^56/256 = 2^48 exactly, LH = log2(1) = 0
        assert int(rh[0]) == 0x1000000000000
        assert int(lh[0]) == 0x0
        # index1=258 (remembered upstream constants)
        assert int(rh[1]) == 0x0000FE03F80FE040
        assert int(lh[1]) == 0x000002DFCA16DDE1
        # index1=512: RH = 2^56/512 = 2^47, LH = 2^48*log2(2) = 2^48
        assert int(rh[-1]) == 1 << 47
        assert int(lh[-1]) == 1 << 48

    def test_ll_endpoints(self):
        from ceph_tpu.crush.ln_table import ll_table
        ll = ll_table()
        assert int(ll[0]) == 0
        # LL[k] = round(2^48*log2(1+k/2^15)) is monotone increasing
        assert (np.diff(ll.astype(np.int64)) > 0).all()

    def test_crush_ln_endpoints_and_monotone(self):
        from ceph_tpu.crush.ln_table import crush_ln
        v = crush_ln(np.array([0, 0xFFFF], dtype=np.int64))
        assert int(v[0]) == 0                  # log2(1) = 0
        assert int(v[1]) == 1 << 48            # log2(2^16) * 2^44
        allv = crush_ln(np.arange(0x10000, dtype=np.int64))
        assert (np.diff(allv.astype(np.int64)) >= 0).all()


# ---------------------------------------------------------------------------
# 2. Independent rjenkins1 (plain-int style, explicit masks)
# ---------------------------------------------------------------------------

def _mix_scalar(a, b, c):
    """Jenkins 96-bit mix, straight from the hash.c operation list, in
    Python ints (independent of the repo's array implementation)."""
    a = (a - b) & M32; a = (a - c) & M32; a = a ^ (c >> 13)
    b = (b - c) & M32; b = (b - a) & M32; b = (b ^ (a << 8)) & M32
    c = (c - a) & M32; c = (c - b) & M32; c = c ^ (b >> 13)
    a = (a - b) & M32; a = (a - c) & M32; a = a ^ (c >> 12)
    b = (b - c) & M32; b = (b - a) & M32; b = (b ^ (a << 16)) & M32
    c = (c - a) & M32; c = (c - b) & M32; c = c ^ (b >> 5)
    a = (a - b) & M32; a = (a - c) & M32; a = a ^ (c >> 3)
    b = (b - c) & M32; b = (b - a) & M32; b = (b ^ (a << 10)) & M32
    c = (c - a) & M32; c = (c - b) & M32; c = c ^ (b >> 15)
    return a, b, c


def rjenkins1_2(a, b):
    h = 1315423911 ^ a ^ b
    x, y = 231232, 1232
    a, b, h = _mix_scalar(a, b, h)
    x, a, h = _mix_scalar(x, a, h)
    b, y, h = _mix_scalar(b, y, h)
    return h


def rjenkins1_3(a, b, c):
    h = 1315423911 ^ a ^ b ^ c
    x, y = 231232, 1232
    a, b, h = _mix_scalar(a, b, h)
    c, x, h = _mix_scalar(c, x, h)
    y, a, h = _mix_scalar(y, a, h)
    b, x, h = _mix_scalar(b, x, h)
    y, c, h = _mix_scalar(y, c, h)
    return h


class TestRjenkinsCross:
    def test_hash32_2_matches_independent(self):
        from ceph_tpu.crush.hash import hash32_2
        rng = np.random.default_rng(7)
        xs = rng.integers(0, 2**32, size=200, dtype=np.uint32)
        ys = rng.integers(0, 2**32, size=200, dtype=np.uint32)
        got = hash32_2(xs, ys)
        for i in range(200):
            assert int(got[i]) == rjenkins1_2(int(xs[i]), int(ys[i]))

    def test_hash32_3_matches_independent(self):
        from ceph_tpu.crush.hash import hash32_3
        rng = np.random.default_rng(8)
        xs = rng.integers(0, 2**32, size=200, dtype=np.uint32)
        ys = rng.integers(0, 2**32, size=200, dtype=np.uint32)
        zs = rng.integers(0, 2**32, size=200, dtype=np.uint32)
        got = hash32_3(xs, ys, zs)
        for i in range(200):
            assert int(got[i]) == rjenkins1_3(int(xs[i]), int(ys[i]),
                                              int(zs[i]))


# ---------------------------------------------------------------------------
# 3. Independent crush_ln (different normalization: bit_length())
# ---------------------------------------------------------------------------

def crush_ln_scalar(xin: int) -> int:
    """Plain-int crush_ln using Python's int.bit_length for the
    normalization (the repo versions use an unrolled binary search)."""
    from ceph_tpu.crush.ln_table import ll_table, rh_lh_tables
    rh, lh = rh_lh_tables()
    ll = ll_table()
    x = xin + 1
    bits = x.bit_length()
    shift = max(0, 16 - bits)
    x <<= shift
    iexpon = 15 - shift
    index1 = (x >> 8) << 1
    j = (index1 - 256) >> 1
    RH = int(rh[j])
    LH = int(lh[j])
    xl64 = (x * RH) >> 48
    index2 = xl64 & 0xFF
    LL = int(ll[index2])
    return (iexpon << 44) + ((LH + LL) >> 4)


class TestCrushLnCross:
    def test_full_domain(self):
        from ceph_tpu.crush.ln_table import crush_ln
        allv = crush_ln(np.arange(0x10000, dtype=np.int64)).astype(np.int64)
        for x in range(0, 0x10000, 97):          # stride keeps it quick
            assert int(allv[x]) == crush_ln_scalar(x), hex(x)
        for x in (0, 1, 0x7FFF, 0x8000, 0xFFFE, 0xFFFF):
            assert int(allv[x]) == crush_ln_scalar(x), hex(x)

    def test_against_float_log2(self):
        """The fixed-point result must track 2^44*log2(x+1) within the
        documented quantization (~2^-15 in log2 units)."""
        from ceph_tpu.crush.ln_table import crush_ln
        xs = np.arange(1, 0x10000, dtype=np.int64)
        got = crush_ln(xs).astype(np.float64)
        want = 2.0**44 * np.log2(xs.astype(np.float64) + 1)
        assert np.abs(got - want).max() <= 2.0**44 * 2.0**-14
