"""mgr modules, scrub, and offline tools.

ref test models: src/pybind/mgr tests (balancer/autoscaler),
qa/standalone/scrub/, and ceph-objectstore-tool workunits.
"""

import asyncio
import json
import os

import pytest

from ceph_tpu.cluster.vstart import Cluster
from ceph_tpu.mgr import BalancerModule, PGAutoscalerModule, \
    PrometheusModule
from ceph_tpu.os_.objectstore import Transaction, WALStore


def run(coro):
    asyncio.run(coro)


# -- scrub -----------------------------------------------------------------

def test_scrub_clean_and_detects_corruption():
    async def go():
        c = await Cluster(n_mons=1, n_osds=3).start()
        try:
            await c.client.pool_create("s", pg_num=4, size=3)
            await c.wait_for_clean(timeout=90)
            io = await c.client.open_ioctx("s")
            for i in range(6):
                await io.write_full(f"o{i}", bytes([i]) * 256)
            # clean scrub: zero errors on every primary
            total_objs = 0
            for o in c.osds:
                for pg in o.pgs.values():
                    if pg.is_primary():
                        rep = await pg.scrubber.scrub()
                        assert rep["errors"] == [], rep
                        total_objs += rep["objects"]
            assert total_objs == 6
            # corrupt one replica copy behind the cluster's back
            victim_pg = None
            for o in c.osds:
                for pg in o.pgs.values():
                    if not pg.is_primary() and \
                            "o1" in o.store.list_objects(pg.cid):
                        victim_pg = (o, pg)
                        break
                if victim_pg:
                    break
            assert victim_pg is not None
            o, pg = victim_pg
            o.store.queue_transaction(
                Transaction().write(pg.cid, "o1", 0, b"CORRUPT"))
            # the primary's scrub must flag the digest mismatch
            primary_osd = next(x for x in c.osds
                               if x.whoami == pg.primary)
            prim_pg = primary_osd.pgs[pg.cid]
            rep = await prim_pg.scrubber.scrub()
            assert any("o1" in e and "mismatch" in e
                       for e in rep["errors"]), rep
            assert prim_pg.stats()["scrub_errors"] >= 1
        finally:
            await c.stop()
    run(go())


def test_ec_deep_scrub_detects_parity_corruption():
    async def go():
        c = await Cluster(n_mons=1, n_osds=3).start()
        try:
            ret, rs, _ = await c.client.mon_command(
                {"prefix": "osd erasure-code-profile set",
                 "name": "p21",
                 "profile": ["k=2", "m=1", "crush-failure-domain=osd",
                             "stripe_unit=512"]})
            assert ret == 0, rs
            await c.client.pool_create("e", pg_num=2,
                                       pool_type="erasure",
                                       erasure_code_profile="p21")
            await c.wait_for_clean(timeout=90)
            io = await c.client.open_ioctx("e")
            await io.write_full("obj", os.urandom(3000))
            # find the PARITY shard holder (acting position k = 2)
            prim_pg = next(pg for o in c.osds
                           for pg in o.pgs.values()
                           if pg.is_primary() and
                           "obj" in o.store.list_objects(pg.cid))
            rep = await prim_pg.scrubber.scrub(deep=True)
            assert rep["errors"] == [], rep
            parity_osd_id = prim_pg.acting[2]
            parity_osd = next(o for o in c.osds
                              if o.whoami == parity_osd_id)
            parity_osd.store.queue_transaction(
                Transaction().write(prim_pg.cid, "obj", 10, b"XXXX"))
            rep = await prim_pg.scrubber.scrub(deep=True)
            assert any("parity" in e or "mismatch" in e
                       for e in rep["errors"]), rep
        finally:
            await c.stop()
    run(go())


# -- mgr modules -----------------------------------------------------------

def test_mgr_balancer_and_prometheus():
    async def go():
        c = await Cluster(
            n_mons=1, n_osds=4,
            mgr_modules=[BalancerModule, PrometheusModule],
            config={"upmap_max_deviation": 1,
                    "mgr_balancer_interval": 0.5,
                    "mgr_prometheus_interval": 0.3}).start()
        try:
            await c.client.pool_create("b", pg_num=32, size=3)
            await c.wait_for_clean(timeout=120)
            io = await c.client.open_ioctx("b")
            await io.write_full("x", b"1")
            # balancer: run one explicit optimize round; any upmaps it
            # generated must be accepted by the mon and visible in the
            # map
            bal = next(m for m in c.mgr.modules
                       if isinstance(m, BalancerModule))
            applied = await bal.optimize()
            ret, _, out = await c.client.mon_command(
                {"prefix": "osd dump"})
            dump = json.loads(out)
            assert len(dump["pg_upmap_items"]) >= applied * 0 + \
                (1 if applied else 0)
            # prometheus: scrape the real HTTP endpoint
            prom = next(m for m in c.mgr.modules
                        if isinstance(m, PrometheusModule))
            deadline = asyncio.get_event_loop().time() + 15
            while prom.port is None:
                assert asyncio.get_event_loop().time() < deadline
                await asyncio.sleep(0.1)
            await asyncio.sleep(0.5)      # one render tick
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", prom.port)
            writer.write(b"GET /metrics HTTP/1.1\r\n"
                         b"Host: localhost\r\n\r\n")
            await writer.drain()
            body = await asyncio.wait_for(reader.read(65536),
                                          timeout=5.0)
            writer.close()
            text = body.decode()
            assert "ceph_osd_up 4" in text
            assert "ceph_health_status" in text
            assert "ceph_pg_total" in text
        finally:
            await c.stop()
    run(go())


def test_mgr_pg_autoscaler_grows_empty_pool():
    async def go():
        c = await Cluster(
            n_mons=1, n_osds=3,
            mgr_modules=[PGAutoscalerModule],
            config={"mgr_pg_autoscaler_interval": 0.3,
                    "mon_target_pg_per_osd": 32,
                    "autoscaler_max_pg_num": 16}).start()
        try:
            await c.client.pool_create("tiny", pg_num=1, size=3)
            deadline = asyncio.get_event_loop().time() + 30
            while True:
                ret, _, out = await c.client.mon_command(
                    {"prefix": "osd pool ls"})
                pool = next(p for p in json.loads(out)
                            if p["name"] == "tiny")
                if pool["pg_num"] > 1:
                    break
                assert asyncio.get_event_loop().time() < deadline, \
                    "autoscaler never grew the pool"
                await asyncio.sleep(0.2)
            assert pool["pg_num"] in (8, 16)     # pow2 target
        finally:
            await c.stop()
    run(go())


# -- objectstore tool ------------------------------------------------------

def test_objectstore_tool_roundtrip(tmp_path, capsys):
    from ceph_tpu.bench import objectstore_tool as ot
    src = str(tmp_path / "osd0")
    st = WALStore(src)
    t = Transaction().create_collection("1.0")
    t.write("1.0", "a", 0, b"alpha")
    t.setattrs("1.0", "a", {"_v": b"\x01"})
    t.omap_setkeys("1.0", "a", {"k": b"v"})
    t.create_collection("1.1")
    t.write("1.1", "b", 0, b"beta")
    st.queue_transaction(t)
    st.umount()
    assert ot.main(["--data-path", src, "--op", "list-pgs"]) == 0
    assert set(capsys.readouterr().out.split()) == {"1.0", "1.1"}
    assert ot.main(["--data-path", src, "--op", "list",
                    "--pgid", "1.0"]) == 0
    assert json.loads(capsys.readouterr().out.splitlines()[0]) == \
        ["1.0", "a"]
    exp = str(tmp_path / "pg.exp")
    assert ot.main(["--data-path", src, "--op", "export",
                    "--pgid", "1.0", "--file", exp]) == 0
    capsys.readouterr()
    # import into a fresh store (PG migration surgery)
    dst = str(tmp_path / "osd1")
    WALStore(dst).umount()
    assert ot.main(["--data-path", dst, "--op", "import",
                    "--file", exp]) == 0
    capsys.readouterr()
    st2 = WALStore(dst)
    assert st2.read("1.0", "a") == b"alpha"
    assert st2.getattrs("1.0", "a") == {"_v": b"\x01"}
    assert st2.omap_get("1.0", "a") == {"k": b"v"}
    assert st2.fsck() == []
    st2.umount()
    assert ot.main(["--data-path", src, "--op", "info",
                    "--pgid", "1.0", "--object", "a"]) == 0
    info = json.loads(capsys.readouterr().out)
    assert info["size"] == 5 and info["omap_keys"] == ["k"]
    assert ot.main(["--data-path", src, "--op", "remove",
                    "--pgid", "1.1"]) == 0
    capsys.readouterr()
    assert ot.main(["--data-path", src, "--op", "fsck"]) == 0
