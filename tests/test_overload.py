"""End-to-end overload protection: fullness gating, pool quotas,
cluster flags, client backoff.

ref test model: qa/standalone/osd/full-ratios + osd-markdown +
qa/tasks thrashing with pool quotas — the admission-control tier.
The three fullness lines of defense (mon ratios -> pool quota -> OSD
failsafe), the osdmap service flags, MOSDBackoff flow control, the
mark-me-down fast path and failure-report hygiene are each pinned by
a fast test; the full overload storm (FULL trip under concurrent
writers, park-don't-error, drain to clean) runs as a tier-1 smoke
plus a `slow` deep variant.
"""

import asyncio
import time

import pytest

from ceph_tpu.cluster.vstart import Cluster
from ceph_tpu.mon.messages import MOSDFailure
from ceph_tpu.rados import ObjectOperationError
from ceph_tpu.sim.thrasher import Thrasher
from ceph_tpu.utils.throttle import MessageThrottle


def run(coro):
    asyncio.run(coro)


# -- units -----------------------------------------------------------------

def test_message_throttle_caps_and_fifo():
    async def go():
        th = MessageThrottle(max_ops=2, max_bytes=100)
        await th.acquire(10)
        await th.acquire(10)
        order = []

        async def waiter(tag, nbytes):
            await th.acquire(nbytes)
            order.append(tag)
        w1 = asyncio.ensure_future(waiter("a", 10))
        w2 = asyncio.ensure_future(waiter("b", 10))
        await asyncio.sleep(0)
        assert not order                   # both blocked at the cap
        assert th.saturated
        th.release(10)
        await asyncio.gather(w1, asyncio.sleep(0.01))
        assert order == ["a"]              # FIFO
        th.release(10)
        await w2
        assert order == ["a", "b"]
        assert th.peak_ops == 2 and th.waited == 2
        # byte budget: a single over-budget op still admits alone
        th2 = MessageThrottle(max_ops=0, max_bytes=50)
        await th2.acquire(500)
        th2.release(500)
    run(go())


def test_flag_machinery_unit():
    from ceph_tpu.osd.osdmap import (
        FLAG_FULL, FLAG_NAMES, FLAG_NOOUT, flag_names,
    )
    from ceph_tpu.osd.types import (
        FLAG_POOL_FULL, FLAG_POOL_FULL_QUOTA, PGPool,
    )
    assert flag_names(FLAG_FULL | FLAG_NOOUT) == "full,noout"
    assert set(FLAG_NAMES) == {"pauserd", "pausewr", "full", "noout",
                               "nodown", "noup", "noin"}
    p = PGPool(id=1, name="q")
    assert not p.is_full()
    p.flags |= FLAG_POOL_FULL_QUOTA
    assert p.is_full()
    p.flags = FLAG_POOL_FULL
    assert p.is_full()


# -- cluster: flags + quotas ----------------------------------------------

async def _wait_flags(c, want: str, present: bool = True,
                      timeout: float = 15.0):
    """Until `want` is (not) in the status flag string AND the client's
    own map agrees — the gates run against the CLIENT's map."""
    deadline = asyncio.get_event_loop().time() + timeout
    while True:
        status = await c.client.status()
        flags = status["osdmap"].get("flags", "").split(",")
        lead = c.leader()
        epoch = lead.osdmon.osdmap.epoch if lead else 0
        cm = c.client.monc.osdmap
        if (want in flags) == present and cm is not None and \
                cm.epoch >= epoch:
            return
        if asyncio.get_event_loop().time() > deadline:
            raise TimeoutError(f"flags={flags} want {want} "
                               f"present={present}")
        await c.client.monc.subscribe(
            "osdmap", (cm.epoch + 1) if cm else 0)
        await asyncio.sleep(0.1)


def test_flags_park_writes_and_full_try():
    """pausewr parks writes (reads flow); FULL parks writes or fails
    them fast -ENOSPC under FULL_TRY; clearing the flag resumes the
    parked op with no data loss."""
    async def go():
        c = await Cluster(n_mons=1, n_osds=3).start()
        try:
            await c.client.pool_create("ov", pg_num=4)
            await c.wait_for_clean(timeout=120)
            io = await c.client.open_ioctx("ov")
            await io.write_full("a", b"base")
            ret, rs, _ = await c.client.mon_command(
                {"prefix": "osd set", "key": "pausewr"})
            assert ret == 0, rs
            await _wait_flags(c, "pausewr")
            parked = asyncio.ensure_future(
                io.write_full("a", b"paused-write", timeout=30.0))
            await asyncio.sleep(0.6)
            assert not parked.done()            # parked, not failed
            assert await io.read("a") == b"base"   # reads still flow
            ret, _, _ = await c.client.mon_command(
                {"prefix": "osd unset", "key": "pausewr"})
            assert ret == 0
            await asyncio.wait_for(parked, timeout=15.0)
            assert await io.read("a") == b"paused-write"
            # unknown flag is rejected
            ret, _, _ = await c.client.mon_command(
                {"prefix": "osd set", "key": "bogus"})
            assert ret == -22

            # manual FULL: FULL_TRY fails fast, plain write parks
            ret, _, _ = await c.client.mon_command(
                {"prefix": "osd set", "key": "full"})
            assert ret == 0
            await _wait_flags(c, "full")
            with pytest.raises(ObjectOperationError) as ei:
                await io.write_full("b", b"x", full_try=True)
            assert ei.value.errno == -28            # -ENOSPC
            status = await c.client.status()
            assert "OSDMAP_FLAGS" in status["health"]["checks"]
            parked = asyncio.ensure_future(
                io.write_full("b", b"eventually", timeout=30.0))
            await asyncio.sleep(0.5)
            assert not parked.done()
            ret, _, _ = await c.client.mon_command(
                {"prefix": "osd unset", "key": "full"})
            assert ret == 0
            await asyncio.wait_for(parked, timeout=15.0)
            assert await io.read("b") == b"eventually"
        finally:
            await c.stop()
    run(go())


def test_pool_quota_objects_and_bytes():
    """set-quota enforcement: past max_objects the mon flags the pool
    full-quota — writes -EDQUOT under FULL_TRY, park otherwise, and
    resume when the quota is raised; byte quotas trip the same way."""
    async def go():
        c = await Cluster(n_mons=1, n_osds=3).start()
        try:
            await c.client.pool_create("q", pg_num=4)
            await c.wait_for_clean(timeout=120)
            io = await c.client.open_ioctx("q")
            ret, rs, _ = await c.client.mon_command(
                {"prefix": "osd pool set-quota", "pool": "q",
                 "field": "max_objects", "val": "4"})
            assert ret == 0, rs
            for i in range(5):
                await io.write_full(f"q-{i}", b"z" * 64)
            # the fullness sweep needs a stats report to see 5 >= 4
            deadline = asyncio.get_event_loop().time() + 15.0
            while True:
                status = await c.client.status()
                pq = {p["name"]: p for p in
                      status["osdmap"].get("pool_quotas", [])}
                if pq.get("q", {}).get("full"):
                    break
                assert asyncio.get_event_loop().time() < deadline, \
                    f"pool never flagged full: {pq}"
                await asyncio.sleep(0.1)
            assert "POOL_QUOTA_FULL" in \
                (await c.client.status())["health"]["checks"]
            # client map must carry the flagged pool before the gates act
            lead_epoch = c.leader().osdmon.osdmap.epoch
            await c.client.monc.wait_for_osdmap(min_epoch=lead_epoch)
            with pytest.raises(ObjectOperationError) as ei:
                await io.write_full("q-over", b"x", full_try=True)
            assert ei.value.errno == -122           # -EDQUOT
            parked = asyncio.ensure_future(
                io.write_full("q-parked", b"later", timeout=30.0))
            await asyncio.sleep(0.5)
            assert not parked.done()
            # raising the quota resumes the parked write
            ret, _, _ = await c.client.mon_command(
                {"prefix": "osd pool set-quota", "pool": "q",
                 "field": "max_objects", "val": "0"})
            assert ret == 0
            await asyncio.wait_for(parked, timeout=15.0)
            assert await io.read("q-parked") == b"later"
            # byte quota trips too
            ret, _, _ = await c.client.mon_command(
                {"prefix": "osd pool set-quota", "pool": "q",
                 "field": "max_bytes", "val": "1"})
            assert ret == 0
            deadline = asyncio.get_event_loop().time() + 15.0
            while True:
                status = await c.client.status()
                pq = {p["name"]: p for p in
                      status["osdmap"].get("pool_quotas", [])}
                if pq.get("q", {}).get("full"):
                    break
                assert asyncio.get_event_loop().time() < deadline
                await asyncio.sleep(0.1)
            lead_epoch = c.leader().osdmon.osdmap.epoch
            await c.client.monc.wait_for_osdmap(min_epoch=lead_epoch)
            with pytest.raises(ObjectOperationError) as ei:
                await io.write_full("q-bytes", b"x", full_try=True)
            assert ei.value.errno == -122
        finally:
            await c.stop()
    run(go())


# -- cluster: OSD failsafe -------------------------------------------------

def test_failsafe_rejects_stale_map_write():
    """A write carrying a pre-FULL osdmap against a >=97%-full OSD is
    rejected -ENOSPC by the OSD's LOCAL failsafe, never partially
    applied. Mon ratios are pushed out of reach so the FULL flag
    never enters the client's map — the map is 'stale' by
    construction."""
    async def go():
        cfg = {"mon_osd_full_ratio": 9.9,
               "mon_osd_nearfull_ratio": 9.8}
        c = await Cluster(n_mons=1, n_osds=3, config=cfg).start()
        try:
            await c.client.pool_create("fs", pg_num=4)
            await c.wait_for_clean(timeout=120)
            io = await c.client.open_ioctx("fs")
            await io.write_full("fill", b"f" * 65536)
            # shrink capacity to ~ the bytes already stored: every OSD
            # is instantly past osd_failsafe_full_ratio (0.97)
            used = max(o.store_used_bytes() for o in c.osds)
            c.cfg["osd_capacity_bytes"] = used
            await asyncio.sleep(0.7)        # used-bytes cache expiry
            from ceph_tpu.osd.osdmap import FLAG_FULL
            cm = c.client.monc.osdmap
            assert cm is not None and not cm.flags & FLAG_FULL, \
                "client map must stay pre-FULL for this test"
            with pytest.raises(ObjectOperationError) as ei:
                await io.write_full("reject-me", b"x" * 1024,
                                    full_try=True)
            assert ei.value.errno == -28
            # never partially applied: the object does not exist
            with pytest.raises(ObjectOperationError) as ei:
                await io.read("reject-me")
            assert ei.value.errno == -2
            # reads still served at failsafe
            assert await io.read("fill", length=4) == b"ffff"
        finally:
            c.cfg["osd_capacity_bytes"] = 0
            await c.stop()
    run(go())


# -- cluster: noout + graceful mark-me-down --------------------------------

def test_noout_and_mark_me_down():
    """`osd set noout` + OSD stop: the OSD is marked down (fast, via
    MOSDMarkMeDown — no heartbeat-grace burn) but never auto-marked
    out; `unset noout` resumes the down-out tick."""
    async def go():
        cfg = {"mon_osd_down_out_interval": 1.0}
        c = await Cluster(n_mons=1, n_osds=3, config=cfg).start()
        try:
            await c.client.pool_create("no", pg_num=2, size=2,
                                       min_size=1)
            await c.wait_for_clean(timeout=120)
            ret, _, _ = await c.client.mon_command(
                {"prefix": "osd set", "key": "noout"})
            assert ret == 0
            lead = c.leader()
            t0 = asyncio.get_event_loop().time()
            await c.osds[2].stop(mark_down=True)     # graceful
            # the strong property: the down COMMITTED before stop()
            # returned (the crash path can never do this — it only
            # stops answering heartbeats and burns the grace period)
            assert not bool(lead.osdmon.osdmap.is_up(2)), \
                "graceful stop did not commit down before exit"
            took = asyncio.get_event_loop().time() - t0
            assert took < 3.0, f"mark-me-down too slow ({took:.2f}s)"
            # noout: down for > down_out_interval yet still in
            await asyncio.sleep(2.2)
            assert lead.osdmon.osdmap.osd_weight[2] > 0, \
                "osd auto-outed despite noout"
            ret, _, _ = await c.client.mon_command(
                {"prefix": "osd unset", "key": "noout"})
            assert ret == 0
            deadline = asyncio.get_event_loop().time() + 10.0
            while lead.osdmon.osdmap.osd_weight[2] > 0:
                assert asyncio.get_event_loop().time() < deadline, \
                    "down-out tick did not resume after unset noout"
                await asyncio.sleep(0.1)
        finally:
            await c.stop()
    run(go())


# -- cluster: backoff ------------------------------------------------------

def test_backoff_released_on_pg_activation():
    """An op hitting a not-active primary gets MOSDBackoff BLOCK (the
    objecter parks — no timeout churn); when the PG activates the
    UNBLOCK releases the op, which then completes for real."""
    async def go():
        c = await Cluster(n_mons=1, n_osds=3).start()
        try:
            await c.client.pool_create("bo", pg_num=4)
            await c.wait_for_clean(timeout=120)
            io = await c.client.open_ioctx("bo")
            await io.write_full("bo-obj", b"v1")
            objecter = c.client.objecter
            osdmap = await c.client.monc.wait_for_osdmap()
            seed, primary = objecter._calc_target(
                osdmap, io.pool_id, "bo-obj")
            pg = c.osds[primary].pgs[f"{io.pool_id}.{seed:x}"]
            # freeze the PG mid-peering (a legit intermediate state:
            # ops arriving now must be backed off, not queued forever)
            pg.state = "peering"
            parked = asyncio.ensure_future(
                io.write_full("bo-obj", b"v2", timeout=30.0))
            deadline = asyncio.get_event_loop().time() + 5.0
            while not pg.backoffs:
                assert asyncio.get_event_loop().time() < deadline, \
                    "primary never asserted a backoff"
                await asyncio.sleep(0.05)
            await asyncio.sleep(0.3)
            assert not parked.done()        # parked client-side
            assert objecter._backoffs, "objecter did not record BLOCK"
            # drive the REAL activation path: re-advance triggers
            # peering which releases backoffs on completion
            pg.advance(pg.up, pg.acting, pg.primary, pg.epoch)
            await asyncio.wait_for(parked, timeout=15.0)
            assert not pg.backoffs, "backoffs survived activation"
            assert await io.read("bo-obj") == b"v2"
        finally:
            await c.stop()
    run(go())


# -- cluster: failure-report hygiene ---------------------------------------

def test_reporter_expiry_and_still_alive_cancel():
    """Two stale accusations minutes apart must not sum to a markdown
    (reporter lifetime expiry on tick), and a still-alive cancel
    removes its reporter immediately."""
    async def go():
        cfg = {"mon_osd_min_down_reporters": 2}
        c = await Cluster(n_mons=1, n_osds=3, config=cfg).start()
        try:
            lead = c.leader()
            mon = lead.osdmon

            def accuse(reporter):
                return mon.handle(MOSDFailure(
                    target=2, failed_for=5,
                    epoch=mon.osdmap.epoch, reporter=reporter))

            await accuse("osd.0")
            assert bool(mon.osdmap.is_up(2))      # 1 of 2 reporters
            # age the first report past the lifetime; the tick expires it
            mon.failure_reporters[2]["osd.0"] = \
                time.time() - mon.reporter_lifetime - 1
            await mon.tick()
            assert 2 not in mon.failure_reporters
            # the second, later accusation is now FIRST of two again
            await accuse("osd.1")
            assert bool(mon.osdmap.is_up(2)), \
                "stale + fresh accusation wrongly marked osd down"
            # still-alive cancel withdraws a live accusation
            await mon.handle(MOSDFailure(
                target=2, failed_for=0, epoch=mon.osdmap.epoch,
                reporter="osd.1", alive=1))
            assert 2 not in mon.failure_reporters
            # two live reporters within lifetime DO mark it down
            await accuse("osd.0")
            await accuse("osd.1")
            assert not bool(mon.osdmap.is_up(2))
        finally:
            await c.stop()
    run(go())


# -- the overload storm ----------------------------------------------------

def test_overload_storm_smoke():
    """Thrasher.overload_storm: shrink capacity until FULL trips under
    concurrent writers; writers park (zero errors), capacity restore
    drains every parked write, and the cluster converges clean with
    all acked data readable."""
    async def go():
        c = await Cluster(n_mons=1, n_osds=3).start()
        try:
            await c.client.pool_create("storm", pg_num=4)
            await c.wait_for_clean(timeout=120)
            io = await c.client.open_ioctx("storm")
            th = Thrasher(c, seed=11, min_live_osds=3)
            res = await th.overload_storm(io, writers=3,
                                          write_bytes=1024,
                                          prefill=16, hold_s=0.6)
            assert res["errors"] == 0
            summary = await th.settle_and_verify(io, timeout=120)
            assert summary["acked_writes"] == res["acked_writes"]
        finally:
            await c.stop()
    run(go())


@pytest.mark.slow
def test_overload_storm_deep(tmp_path):
    """Deep variant on durable BlueStore-backed stores: bigger writer
    pool, longer FULL dwell, full fsck via settle_and_verify."""
    from ceph_tpu.os_.bluestore import BlueStore

    async def go():
        stores = [BlueStore(str(tmp_path / f"osd{i}"))
                  for i in range(3)]
        c = await Cluster(n_mons=1, n_osds=3, stores=stores).start()
        try:
            await c.client.pool_create("storm", pg_num=8)
            await c.wait_for_clean(timeout=240)
            io = await c.client.open_ioctx("storm")
            th = Thrasher(c, seed=4242, min_live_osds=3)
            res = await th.overload_storm(io, writers=6,
                                          write_bytes=4096,
                                          prefill=64, hold_s=2.0,
                                          full_timeout=60.0,
                                          drain_timeout=120.0)
            assert res["errors"] == 0
            summary = await th.settle_and_verify(io, timeout=300)
            assert summary["acked_writes"] == res["acked_writes"]
        finally:
            await c.stop()
    run(go())
