"""HA metadata plane: MDSMonitor + FSMap failover acceptance.

The pinned invariant (ISSUE 5): ``kill -9`` of the active MDS under
concurrent client metadata I/O -> a standby reaches ``active``, the
client reconnects and replays caps, no acked mutation is lost, and the
fenced old incarnation's late journal write is rejected (blocklist).
Plus the session-survival regression pair (a filesystem without a
standby IS an outage — the pre-subsystem behavior), standby-replay,
and the observability surface (health checks, `fs status`, REST, the
prometheus ``ceph_mds_state`` gauge).

ref test model: qa/tasks/cephfs/test_failover.py + mds_thrash.
"""

import asyncio
import json

import pytest

from ceph_tpu.cephfs.client import CephFSClient
from ceph_tpu.cephfs.mds import MDS_PERF
from ceph_tpu.cluster.vstart import Cluster
from ceph_tpu.sim.thrasher import Thrasher

# fast failover pacing for tests: detection <= ~2s, ladder < 1.5s.
# (The mon's tick-stall guard keeps a blocked event loop — e.g. a jit
# compile — from tripping this grace spuriously.)
FAST_CFG = {
    "mds_beacon_interval": 0.2,
    "mds_beacon_grace": 2.0,
    "mds_reconnect_timeout": 1.0,
    "mds_replay_interval": 0.1,
}


def run(coro):
    asyncio.run(coro)


async def _status(c) -> dict:
    ret, _, out = await c.client.mon_command({"prefix": "status"})
    assert ret == 0
    return json.loads(out)


async def _wait_health(c, check: str, timeout: float = 15.0) -> dict:
    deadline = asyncio.get_event_loop().time() + timeout
    while True:
        st = await _status(c)
        if check in st["health"]["checks"]:
            return st
        assert asyncio.get_event_loop().time() < deadline, \
            (check, st["health"])
        await asyncio.sleep(0.2)


def test_mds_failover_storm_acceptance():
    """The acceptance pin: kill -9 the active under concurrent
    metadata I/O; takeover + cap replay + zero acked-op loss + the
    fenced zombie's late journal write bounces (all asserted inside
    ``Thrasher.mds_storm``), and a cap HELD OPEN across the failover
    stays valid and writable against the successor."""
    async def go():
        c = await Cluster(n_mons=1, n_osds=3, config=FAST_CFG).start()
        try:
            await c.start_fs(n_mds=2)
            monmap = c.client.monc.monmap
            cl1 = await CephFSClient.create(monmap, None, "cephfs",
                                            keyring=c.keyring)
            cl2 = await CephFSClient.create(monmap, None, "cephfs",
                                            keyring=c.keyring)
            held = await cl1.open_file("/held.txt", "w")
            await held.write(b"pre-failover")
            t0 = MDS_PERF.dump().get("takeovers", 0)
            th = Thrasher(c, seed=11)
            res = await th.mds_storm([cl1, cl2], writes=10,
                                     files_before_kill=2)
            assert res["errors"] == 0 and res["acked_writes"] >= 10
            assert MDS_PERF.dump().get("takeovers", 0) > t0
            # the held FW cap was replayed, not re-acquired: the handle
            # never went invalid and still licenses writes
            assert held.valid
            await held.write(b"post-failover")
            assert await cl2.read_file("/held.txt") == b"post-failover"
            # the storm consumed the standby: fs status shows an
            # active with zero standbys + the health warn
            st = await _wait_health(c, "MDS_INSUFFICIENT_STANDBY")
            assert st["fsmap"]["active"] is not None
            assert st["fsmap"]["standby_count"] == 0
            ret, _, out = await c.client.mon_command(
                {"prefix": "fs status"})
            assert ret == 0
            dump = json.loads(out)
            assert dump["ranks"][0]["state"] == "active"
            assert dump["last_failure_osd_epoch"] > 0
            assert dump["stopped_gids"]           # zombie tombstoned
            await cl1.unmount()
            await cl2.unmount()
        finally:
            await c.stop()
    run(go())


def test_mds_single_daemon_outage_and_session_survival_pair():
    """The regression pair. Without a standby the subsystem can only
    declare the outage (MDS_ALL_DOWN) — and a client that does NOT
    follow the fsmap (pinned to the dead incarnation's address, the
    pre-subsystem behavior) loses its session outright. The
    fsmap-following client's session + completed-request table survive
    a FULL restart: a fresh incarnation under the same name loads the
    session table, accepts the reconnect, and serves."""
    async def go():
        from ceph_tpu.mgr import PrometheusModule, RestModule
        c = await Cluster(n_mons=1, n_osds=3, config=FAST_CFG,
                          mgr_modules=[RestModule,
                                       PrometheusModule]).start()
        try:
            await c.start_fs(n_mds=1)
            monmap = c.client.monc.monmap
            ha = await CephFSClient.create(monmap, None, "cephfs",
                                           keyring=c.keyring)
            active = next(m for m in c.mdss if not m._stopping)
            pinned = await CephFSClient.create(monmap, active.addr,
                                               "cephfs",
                                               keyring=c.keyring)
            await ha.write_file("/ha.txt", b"ha")
            await pinned.write_file("/pinned.txt", b"pinned")
            await c.kill_mds(active.name)
            # no standby: rank 0 failed, filesystem offline — ERR check
            st = await _wait_health(c, "MDS_ALL_DOWN")
            assert st["fsmap"]["failed"] == [0]
            # revive under the same name: NEW incarnation (fresh gid +
            # identity — the old one's blocklist must not fence it)
            await c.revive_mds(active.name)
            await c.wait_for_mds_active(timeout=30)
            # fsmap follower: session survived the full restart
            await ha.write_file("/ha2.txt", b"recovered")
            assert await ha.read_file("/ha2.txt") == b"recovered"
            # pinned client: address dead, session gone — the seed's
            # behavior this subsystem exists to fix
            with pytest.raises(Exception):
                await pinned._request("stat", "/", timeout=2.0)
            # observability: REST endpoint + ceph_mds_state gauge
            for _ in range(100):
                if c.mgr.modules[0].port:
                    break
                await asyncio.sleep(0.1)
            body = await _http_get(c.mgr.modules[0].port, "/health")
            assert json.loads(body)["status"] in ("HEALTH_OK",
                                                  "HEALTH_WARN")
            body = await _http_get(c.mgr.modules[0].port, "/status")
            assert "fsmap" in json.loads(body)
            for _ in range(100):
                if c.mgr.modules[1].port:
                    break
                await asyncio.sleep(0.1)
            # the exporter serves a per-tick snapshot: poll until it
            # catches up with the post-revive active state
            deadline = asyncio.get_event_loop().time() + 15.0
            while True:
                metrics = await _http_get(c.mgr.modules[1].port,
                                          "/metrics")
                if "ceph_mds_state{" in metrics and \
                        'state="active"' in metrics:
                    break
                assert asyncio.get_event_loop().time() < deadline, \
                    metrics[:2000]
                await asyncio.sleep(0.5)
            await ha.unmount()
            await pinned.msgr.shutdown()
            if pinned._own_rados is not None:
                await pinned._own_rados.shutdown()
        finally:
            await c.stop()
    run(go())


async def _http_get(port: int, path: str) -> str:
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    writer.write(f"GET {path} HTTP/1.0\r\n\r\n".encode())
    await writer.drain()
    raw = await asyncio.wait_for(reader.read(), timeout=5.0)
    writer.close()
    head, _, body = raw.partition(b"\r\n\r\n")
    assert b"200" in head.split(b"\r\n", 1)[0] or path == "/metrics", \
        head
    return body.decode()


@pytest.mark.slow
def test_mds_standby_replay_takeover():
    """standby_replay: the warm follower tails the journal + session
    table continuously and is preferred at failover. (`slow` to hold
    the 870s tier-1 budget — the ISSUE's budget rule for storm-depth
    variants; the deep storm below also runs standby_replay.)"""
    async def go():
        cfg = dict(FAST_CFG, mds_standby_replay=True)
        c = await Cluster(n_mons=1, n_osds=3, config=cfg).start()
        try:
            await c.start_fs(n_mds=2)
            monmap = c.client.monc.monmap
            cl = await CephFSClient.create(monmap, None, "cephfs",
                                           keyring=c.keyring)
            # the tick promotes the idle standby to standby_replay
            for _ in range(100):
                st = await _status(c)
                if "standby_replay" in st["fsmap"]["states"].values():
                    break
                await asyncio.sleep(0.1)
            states = st["fsmap"]["states"]
            assert "standby_replay" in states.values(), states
            follower = next(m for m in c.mdss
                            if states.get(m.name) == "standby_replay")
            await cl.write_file("/warm.txt", b"tailed")
            p0 = MDS_PERF.dump().get("standby_replay_polls", 0)
            await asyncio.sleep(0.5)
            assert MDS_PERF.dump().get("standby_replay_polls", 0) > p0
            # the follower's tail saw the journal advance
            assert follower._journal_seq > 0
            victim = c.mds_active_name()
            await c.kill_mds(victim)
            newa = await c.wait_for_mds_active(not_name=victim,
                                               timeout=30)
            assert newa == follower.name     # warm standby preferred
            assert await cl.read_file("/warm.txt") == b"tailed"
            await cl.write_file("/after.txt", b"ok")
            await cl.unmount()
        finally:
            await c.stop()
    run(go())


def test_fs_cli_parses():
    from ceph_tpu.bench.ceph_cli import parse_command
    assert parse_command(["fs", "status"])[0] == {"prefix": "fs status"}
    assert parse_command(["fs", "dump"])[0] == {"prefix": "fs dump"}
    assert parse_command(["mds", "fail", "a"])[0] == \
        {"prefix": "mds fail", "who": "a"}


@pytest.mark.slow
def test_mds_storm_deep():
    """Deep variant: three daemons, two consecutive kill -9 failovers
    under sustained multi-client I/O, standby_replay enabled, then an
    operator-driven `mds fail` on top."""
    async def go():
        cfg = dict(FAST_CFG, mds_standby_replay=True)
        c = await Cluster(n_mons=1, n_osds=3, config=cfg).start()
        try:
            await c.start_fs(n_mds=3)
            monmap = c.client.monc.monmap
            clients = [await CephFSClient.create(monmap, None,
                                                 "cephfs",
                                                 keyring=c.keyring)
                       for _ in range(3)]
            th = Thrasher(c, seed=23)
            res = await th.mds_storm(clients, writes=40,
                                     files_before_kill=4, kills=2)
            assert res["errors"] == 0
            assert res["acked_writes"] >= 3 * 40
            # operator failover of the last active: revive a standby
            # first so the rank can move
            await c.revive_mds("d")
            last = c.mds_active_name()
            ret, rs, _ = await c.client.mon_command(
                {"prefix": "mds fail", "who": last})
            assert ret == 0, rs
            newa = await c.wait_for_mds_active(not_name=last,
                                               timeout=30)
            assert newa != last
            # everything written through both failovers still reads
            await clients[0].write_file("/final.txt", b"done")
            assert await clients[1].read_file("/final.txt") == b"done"
            for cl in clients:
                await cl.unmount()
        finally:
            await c.stop()
    run(go())
