// GF(2^8) arithmetic over the 0x11d polynomial — the native runtime's
// counterpart of ceph_tpu/gf/tables.py (ref: jerasure/gf-complete's w=8
// tables; reimplemented from the algebra, not the code).
#pragma once

#include <cstddef>
#include <cstdint>
#include <vector>

namespace ceph_tpu {

class GF256 {
 public:
  static const GF256& instance();

  uint8_t mul(uint8_t a, uint8_t b) const {
    if (a == 0 || b == 0) return 0;
    return exp_[log_[a] + log_[b]];
  }
  uint8_t inv(uint8_t a) const;  // a != 0
  uint8_t div(uint8_t a, uint8_t b) const { return mul(a, inv(b)); }

  // dst[0..len) ^= c * src[0..len)  — the region kernel
  // (ref: isa-l ec_encode_data inner loop; plain table walk here).
  void mul_region_xor(uint8_t c, const uint8_t* src, uint8_t* dst,
                      size_t len) const;

 private:
  GF256();
  uint8_t exp_[512];
  uint8_t log_[256];
};

// (rows x cols) @ (cols x len) over GF(2^8): out = mat * data.
void gf_matmul(const uint8_t* mat, int rows, int cols,
               const uint8_t* const* data, uint8_t* const* out, size_t len);

// In-place inversion of an n x n GF matrix; returns false if singular.
bool gf_matinv(std::vector<uint8_t>& m, int n);

}  // namespace ceph_tpu
