#include "rs_matrix.h"

#include <stdexcept>

#include "gf256.h"

namespace ceph_tpu {

namespace {

// Extended Vandermonde (rows x cols), ref construction mirrored from
// ceph_tpu/ec/matrix.py extended_vandermonde.
std::vector<uint8_t> extended_vandermonde(int rows, int cols) {
  const GF256& gf = GF256::instance();
  if (rows > 257) throw std::runtime_error("k+m must be <= 257 at w=8");
  std::vector<uint8_t> v(rows * cols, 0);
  v[0] = 1;
  v[(rows - 1) * cols + (cols - 1)] = 1;
  for (int i = 1; i < rows - 1; ++i) {
    uint8_t acc = 1;
    for (int j = 0; j < cols; ++j) {
      v[i * cols + j] = acc;
      acc = gf.mul(acc, static_cast<uint8_t>(i));
    }
  }
  return v;
}

// Column elimination to identity top block; mirrors matrix.py
// _systematize step-for-step (same pivot/scaling order => same bytes).
std::vector<uint8_t> systematize(std::vector<uint8_t> dist, int rows,
                                 int cols) {
  const GF256& gf = GF256::instance();
  auto at = [&](int r, int c) -> uint8_t& { return dist[r * cols + c]; };
  for (int i = 1; i < cols; ++i) {
    if (at(i, i) == 0) {
      int found = -1;
      for (int j = i + 1; j < rows; ++j)
        if (at(j, i)) { found = j; break; }
      if (found < 0) throw std::runtime_error("singular construction");
      for (int c = 0; c < cols; ++c)
        std::swap(at(i, c), at(found, c));
    }
    if (at(i, i) != 1) {
      uint8_t inv = gf.inv(at(i, i));
      for (int r = 0; r < rows; ++r) at(r, i) = gf.mul(at(r, i), inv);
    }
    for (int j = 0; j < cols; ++j) {
      uint8_t e = at(i, j);
      if (j != i && e) {
        for (int r = 0; r < rows; ++r)
          at(r, j) ^= gf.mul(e, at(r, i));
      }
    }
  }
  if (rows > cols) {
    for (int j = 0; j < cols; ++j) {
      uint8_t e = at(cols, j);
      if (e == 0) throw std::runtime_error("singular construction");
      if (e != 1) {
        uint8_t inv = gf.inv(e);
        for (int r = cols; r < rows; ++r) at(r, j) = gf.mul(at(r, j), inv);
      }
    }
    for (int i = cols + 1; i < rows; ++i) {
      uint8_t e = at(i, 0);
      if (e == 0) throw std::runtime_error("singular construction");
      if (e != 1) {
        uint8_t inv = gf.inv(e);
        for (int j = 0; j < cols; ++j) at(i, j) = gf.mul(at(i, j), inv);
      }
    }
  }
  return dist;
}

}  // namespace

std::vector<uint8_t> coding_matrix(const std::string& technique, int k,
                                   int m) {
  const GF256& gf = GF256::instance();
  if (k < 1 || m < 1) throw std::runtime_error("invalid k/m");
  if (technique == "reed_sol_van") {
    auto dist = systematize(extended_vandermonde(k + m, k), k + m, k);
    return std::vector<uint8_t>(dist.begin() + k * k, dist.end());
  }
  if (technique == "cauchy_orig" || technique == "cauchy_good" ||
      technique == "cauchy") {
    if (k + m > 256) throw std::runtime_error("k+m must be <= 256");
    std::vector<uint8_t> c(m * k);
    for (int i = 0; i < m; ++i)
      for (int j = 0; j < k; ++j)
        c[i * k + j] = gf.inv(static_cast<uint8_t>(i ^ (m + j)));
    if (technique != "cauchy_orig") {
      for (int j = 0; j < k; ++j) {
        uint8_t e = c[j];
        if (e != 1) {
          uint8_t inv = gf.inv(e);
          for (int i = 0; i < m; ++i) c[i * k + j] = gf.mul(c[i * k + j], inv);
        }
      }
    }
    return c;
  }
  throw std::runtime_error("unknown technique " + technique);
}

std::vector<uint8_t> decode_matrix(const std::string& technique, int k,
                                   int m, const std::vector<int>& avail,
                                   const std::vector<int>& want) {
  const GF256& gf = GF256::instance();
  if (static_cast<int>(avail.size()) < k)
    throw std::runtime_error("need k chunks to decode");
  for (int id : avail)
    if (id < 0 || id >= k + m)
      throw std::runtime_error("available chunk id out of range");
  for (int id : want)
    if (id < 0 || id >= k + m)
      throw std::runtime_error("wanted chunk id out of range");
  auto coding = coding_matrix(technique, k, m);
  auto grow = [&](int r, int j) -> uint8_t {  // generator row r, col j
    if (r < k) return r == j ? 1 : 0;
    return coding[(r - k) * k + j];
  };
  std::vector<uint8_t> sub(k * k);
  for (int i = 0; i < k; ++i)
    for (int j = 0; j < k; ++j) sub[i * k + j] = grow(avail[i], j);
  if (!gf_matinv(sub, k)) throw std::runtime_error("singular submatrix");
  const int w = static_cast<int>(want.size());
  const int a = static_cast<int>(avail.size());
  std::vector<uint8_t> d(w * a, 0);
  for (int i = 0; i < w; ++i)
    for (int j = 0; j < k; ++j) {
      uint8_t acc = 0;
      for (int x = 0; x < k; ++x)
        acc ^= gf.mul(grow(want[i], x), sub[x * k + j]);
      d[i * a + j] = acc;
    }
  return d;
}

}  // namespace ceph_tpu
