// libec_ref: the native CPU Reed-Solomon backend behind a C ABI.
//
// Role (two hats):
//  1. independent correctness oracle for the JAX plugin — same matrix
//     constructions, different implementation, byte-compared in tests
//     (the jerasure<->isa cross-check pattern);
//  2. the measured CPU baseline the benchmark compares the TPU against
//     (ref: src/erasure-code/isa/ErasureCodeIsa.cc role).
//
// ABI: plain C, consumed via ctypes from ceph_tpu/interop/native.py.

#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <vector>

#include "gf256.h"
#include "rs_matrix.h"

using ceph_tpu::coding_matrix;
using ceph_tpu::decode_matrix;
using ceph_tpu::gf_matmul;

namespace {

struct Handle {
  int k = 0;
  int m = 0;
  std::string technique;
  std::vector<uint8_t> coding;  // (m x k)
  // decode-matrix cache keyed by (avail, want) — the table-cache role
  // (ref: src/erasure-code/isa/ErasureCodeIsaTableCache.cc).
  std::map<std::pair<std::vector<int>, std::vector<int>>,
           std::vector<uint8_t>>
      dcache;
  std::mutex mu;
};

}  // namespace

extern "C" {

// Returns an opaque handle or null on error.
void* ec_ref_init(int k, int m, const char* technique) {
  try {
    auto* h = new Handle;
    h->k = k;
    h->m = m;
    h->technique = technique ? technique : "reed_sol_van";
    h->coding = coding_matrix(h->technique, k, m);
    return h;
  } catch (...) {
    return nullptr;
  }
}

void ec_ref_free(void* handle) { delete static_cast<Handle*>(handle); }

// data: k contiguous chunks of chunk_size bytes (data[i] = base+i*size);
// parity out: m contiguous chunks. Returns 0 on success.
int ec_ref_encode(void* handle, const uint8_t* data, uint8_t* parity,
                  size_t chunk_size) {
  auto* h = static_cast<Handle*>(handle);
  if (!h) return -1;
  std::vector<const uint8_t*> in(h->k);
  std::vector<uint8_t*> out(h->m);
  for (int i = 0; i < h->k; ++i) in[i] = data + i * chunk_size;
  for (int i = 0; i < h->m; ++i) out[i] = parity + i * chunk_size;
  gf_matmul(h->coding.data(), h->m, h->k, in.data(), out.data(),
            chunk_size);
  return 0;
}

// avail/want: chunk-id arrays; chunks: n_avail contiguous input chunks in
// avail order; out: n_want contiguous chunks. Returns 0 on success.
int ec_ref_decode(void* handle, const int* avail, int n_avail,
                  const int* want, int n_want, const uint8_t* chunks,
                  uint8_t* out, size_t chunk_size) {
  auto* h = static_cast<Handle*>(handle);
  if (!h || n_avail < h->k) return -1;
  std::vector<int> av(avail, avail + n_avail);
  std::vector<int> wa(want, want + n_want);
  try {
    std::vector<uint8_t>* d;
    {
      std::lock_guard<std::mutex> lock(h->mu);
      auto key = std::make_pair(av, wa);
      auto it = h->dcache.find(key);
      if (it == h->dcache.end())
        it = h->dcache
                 .emplace(key, decode_matrix(h->technique, h->k, h->m, av,
                                             wa))
                 .first;
      d = &it->second;
    }
    std::vector<const uint8_t*> in(n_avail);
    std::vector<uint8_t*> ou(n_want);
    for (int i = 0; i < n_avail; ++i) in[i] = chunks + i * chunk_size;
    for (int i = 0; i < n_want; ++i) ou[i] = out + i * chunk_size;
    gf_matmul(d->data(), n_want, n_avail, in.data(), ou.data(),
              chunk_size);
    return 0;
  } catch (...) {
    return -2;
  }
}

// Expose the coding matrix for cross-language construction checks.
int ec_ref_coding_matrix(void* handle, uint8_t* out) {
  auto* h = static_cast<Handle*>(handle);
  if (!h) return -1;
  std::memcpy(out, h->coding.data(), h->coding.size());
  return 0;
}

}  // extern "C"
