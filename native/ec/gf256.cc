#include "gf256.h"

#include <cstring>

namespace ceph_tpu {

static constexpr int kPoly = 0x11d;  // x^8+x^4+x^3+x^2+1, generator 2

const GF256& GF256::instance() {
  static GF256 gf;
  return gf;
}

GF256::GF256() {
  int x = 1;
  for (int i = 0; i < 255; ++i) {
    exp_[i] = static_cast<uint8_t>(x);
    log_[x] = static_cast<uint8_t>(i);
    x <<= 1;
    if (x & 0x100) x ^= kPoly;
  }
  for (int i = 255; i < 512; ++i) exp_[i] = exp_[i - 255];
  log_[0] = 0;  // never read for zero operands
}

uint8_t GF256::inv(uint8_t a) const {
  return exp_[255 - log_[a]];
}

void GF256::mul_region_xor(uint8_t c, const uint8_t* src, uint8_t* dst,
                           size_t len) const {
  if (c == 0) return;
  if (c == 1) {
    for (size_t i = 0; i < len; ++i) dst[i] ^= src[i];
    return;
  }
  // Per-coefficient 256-entry product table, then one pass: the scalar
  // version of the PSHUFB nibble trick (two gathers beats recomputing
  // log/exp per byte ~3x).
  uint8_t table[256];
  table[0] = 0;
  const int lc = log_[c];
  for (int v = 1; v < 256; ++v)
    table[v] = exp_[lc + log_[v]];
  for (size_t i = 0; i < len; ++i) dst[i] ^= table[src[i]];
}

void gf_matmul(const uint8_t* mat, int rows, int cols,
               const uint8_t* const* data, uint8_t* const* out, size_t len) {
  const GF256& gf = GF256::instance();
  for (int r = 0; r < rows; ++r) {
    std::memset(out[r], 0, len);
    for (int c = 0; c < cols; ++c)
      gf.mul_region_xor(mat[r * cols + c], data[c], out[r], len);
  }
}

bool gf_matinv(std::vector<uint8_t>& m, int n) {
  const GF256& gf = GF256::instance();
  std::vector<uint8_t> inv(n * n, 0);
  for (int i = 0; i < n; ++i) inv[i * n + i] = 1;
  for (int col = 0; col < n; ++col) {
    int piv = -1;
    for (int r = col; r < n; ++r)
      if (m[r * n + col]) { piv = r; break; }
    if (piv < 0) return false;
    if (piv != col) {
      for (int j = 0; j < n; ++j) {
        std::swap(m[piv * n + j], m[col * n + j]);
        std::swap(inv[piv * n + j], inv[col * n + j]);
      }
    }
    uint8_t d = gf.inv(m[col * n + col]);
    for (int j = 0; j < n; ++j) {
      m[col * n + j] = gf.mul(m[col * n + j], d);
      inv[col * n + j] = gf.mul(inv[col * n + j], d);
    }
    for (int r = 0; r < n; ++r) {
      if (r == col) continue;
      uint8_t f = m[r * n + col];
      if (!f) continue;
      for (int j = 0; j < n; ++j) {
        m[r * n + j] ^= gf.mul(f, m[col * n + j]);
        inv[r * n + j] ^= gf.mul(f, inv[col * n + j]);
      }
    }
  }
  m = inv;
  return true;
}

}  // namespace ceph_tpu
