#include "plugin.h"

#include <dlfcn.h>

#include <map>
#include <mutex>
#include <set>
#include <string>

namespace {

struct State {
  std::mutex mu;
  std::map<std::string, const ec_plugin_vtable_t*> plugins;
  std::map<std::string, void*> handles;  // dlopen handles, kept for life
  std::string last_err;
};

State& state() {
  static State s;
  return s;
}

}  // namespace

extern "C" int ec_plugin_register(const char* name,
                                  const ec_plugin_vtable_t* vt) {
  auto& s = state();
  // mu already held during load(); direct registration (tests, builtins)
  // races are the caller's problem, as in the reference singleton.
  if (s.plugins.count(name)) return -1;
  s.plugins[name] = vt;
  return 0;
}

namespace ceph_tpu {

PluginRegistry& PluginRegistry::instance() {
  static PluginRegistry r;
  return r;
}

int PluginRegistry::add(const char* name, const ec_plugin_vtable_t* vt) {
  return ec_plugin_register(name, vt);
}

ec_backend_t* PluginRegistry::factory(const char* name,
                                      const char* directory,
                                      const char* profile,
                                      const ec_plugin_vtable_t** vt_out,
                                      const char** err) {
  auto& s = state();
  std::unique_lock<std::mutex> lock(s.mu);
  auto it = s.plugins.find(name);
  if (it == s.plugins.end()) {
    // ref: ErasureCodePluginRegistry::load — dlopen + __erasure_code_init
    std::string path = std::string(directory ? directory : ".") +
                       "/libec_" + name + ".so";
    void* h = dlopen(path.c_str(), RTLD_NOW | RTLD_GLOBAL);
    if (!h) {
      s.last_err = dlerror();
      if (err) *err = s.last_err.c_str();
      return nullptr;
    }
    auto init = reinterpret_cast<ec_plugin_init_fn>(
        dlsym(h, "__erasure_code_init"));
    if (!init) {
      s.last_err = path + ": no __erasure_code_init";
      if (err) *err = s.last_err.c_str();
      dlclose(h);
      return nullptr;
    }
    std::set<std::string> before;
    for (const auto& kv : s.plugins) before.insert(kv.first);
    int rc = init(name);
    if (rc != 0 || !s.plugins.count(name)) {
      s.last_err = path + ": __erasure_code_init failed";
      if (err) *err = s.last_err.c_str();
      // Drop anything the failed init registered before unloading, so no
      // vtable pointer into the closed .so survives in the registry.
      for (auto it2 = s.plugins.begin(); it2 != s.plugins.end();) {
        if (!before.count(it2->first)) it2 = s.plugins.erase(it2);
        else ++it2;
      }
      dlclose(h);
      return nullptr;
    }
    s.handles[name] = h;
    it = s.plugins.find(name);
  }
  const ec_plugin_vtable_t* vt = it->second;
  ec_backend_t* b = vt->create(profile);
  if (!b) {
    s.last_err = std::string(name) + ": bad profile: " + profile;
    if (err) *err = s.last_err.c_str();
    return nullptr;
  }
  lock.unlock();
  if (vt_out) *vt_out = vt;
  return b;
}

}  // namespace ceph_tpu

// C shims for ctypes / external callers.
extern "C" {

void* ec_registry_factory(const char* name, const char* directory,
                          const char* profile, const void** vt_out) {
  const char* err = nullptr;
  return ceph_tpu::PluginRegistry::instance().factory(
      name, directory, profile,
      reinterpret_cast<const ec_plugin_vtable_t**>(vt_out), &err);
}

}  // extern "C"
