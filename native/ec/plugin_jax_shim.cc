// libec_jax.so — the reverse shim: a dlopen-able native EC plugin that
// embeds CPython and forwards the vtable to the Python/JAX backend
// (ceph_tpu.interop.ec_shim). Lets the native harness (ec_bench, or any
// consumer of the __erasure_code_init contract) drive the flagship TPU
// plugin exactly like a C plugin.
//
// ref: the role of src/erasure-code/ErasureCodePlugin.cc
// __erasure_code_init; SURVEY.md §7 step 6 (the "reverse shim" build
// plan step).
//
// Interpreter lifecycle: initialized lazily on the first create();
// never finalized (plugin .so lifetime == process lifetime, like the
// reference's load-once registry). If the host process already runs
// Python (e.g. a ctypes consumer inside pytest), the existing
// interpreter is reused via PyGILState.

#include <Python.h>

#include <dlfcn.h>

#include <cstdlib>
#include <string>

#include "plugin.h"

namespace {

struct JaxBackend {
  PyObject* handle;  // the Python ErasureCodeInterface instance
  int k, m;
};

PyObject* g_mod = nullptr;  // ceph_tpu.interop.ec_shim, kept for life
bool g_we_initialized = false;  // did WE start the interpreter?

bool ensure_interp() {
  if (Py_IsInitialized()) return true;
  Py_InitializeEx(0);
  if (!Py_IsInitialized()) return false;
  g_we_initialized = true;
  // Release the GIL the init left us holding so every entry point can
  // use the uniform PyGILState_Ensure/Release pairing.
  PyEval_SaveThread();
  return true;
}

std::string repo_root() {
  // <repo>/native/build/libec_jax.so -> <repo>. dli_fname can be
  // RELATIVE (it echoes the dlopen argument), so resolve it first.
  Dl_info info;
  if (dladdr(reinterpret_cast<void*>(&ensure_interp), &info) &&
      info.dli_fname) {
    char abs[4096];
    if (realpath(info.dli_fname, abs)) {
      std::string p = abs;
      for (int i = 0; i < 3; ++i) {
        auto cut = p.rfind('/');
        if (cut == std::string::npos) return ".";
        p.erase(cut);
      }
      if (!p.empty()) return p;
    }
  }
  return ".";
}

// GIL must be held.
PyObject* shim_module() {
  if (g_mod) return g_mod;
  // Bootstrap import paths: the embedded interpreter resolves its
  // prefix from libpython, not from any active virtualenv, so (a) the
  // repo root (for ceph_tpu) and (b) $VIRTUAL_ENV's site-packages (for
  // jax/numpy when ec_bench runs inside a venv) must be added by hand.
  std::string root = repo_root();
  std::string esc;  // escape for a double-quoted Python literal
  for (char c : root) {
    if (c == '\\' || c == '"') esc += '\\';
    esc += c;
  }
  std::string boot =
      "import os, site, sys\n" +
      // The platform pin in ec_shim must only fire for an interpreter
      // WE embedded, never for a host Python that loaded us in-process.
      std::string(g_we_initialized
                      ? "os.environ['CEPH_TPU_EMBEDDED_SHIM'] = '1'\n"
                      : "") +
      "sys.path.insert(0, os.path.abspath(" +
      std::string("\"") + esc + "\"))\n" +
      "venv = os.environ.get('VIRTUAL_ENV')\n"
      "if venv:\n"
      "    d = os.path.join(venv, 'lib',\n"
      "                     'python%d.%d' % sys.version_info[:2],\n"
      "                     'site-packages')\n"
      "    if os.path.isdir(d):\n"
      "        site.addsitedir(d)\n";
  if (PyRun_SimpleString(boot.c_str()) != 0) PyErr_Print();
  g_mod = PyImport_ImportModule("ceph_tpu.interop.ec_shim");
  if (!g_mod) PyErr_Print();
  return g_mod;
}

ec_backend_t* jax_create(const char* profile) {
  if (!ensure_interp()) return nullptr;
  PyGILState_STATE g = PyGILState_Ensure();
  JaxBackend* be = nullptr;
  PyObject* mod = shim_module();
  if (mod) {
    PyObject* h =
        PyObject_CallMethod(mod, "create", "s", profile ? profile : "");
    if (h) {
      PyObject* kk = PyObject_GetAttrString(h, "k");
      PyObject* mm = PyObject_GetAttrString(h, "m");
      if (kk && mm) {
        be = new JaxBackend{h, static_cast<int>(PyLong_AsLong(kk)),
                            static_cast<int>(PyLong_AsLong(mm))};
      } else {
        Py_DECREF(h);
      }
      Py_XDECREF(kk);
      Py_XDECREF(mm);
    } else {
      PyErr_Print();
    }
  }
  PyGILState_Release(g);
  return reinterpret_cast<ec_backend_t*>(be);
}

void jax_destroy(ec_backend_t* b) {
  auto* be = reinterpret_cast<JaxBackend*>(b);
  if (!be) return;
  PyGILState_STATE g = PyGILState_Ensure();
  Py_XDECREF(be->handle);
  PyGILState_Release(g);
  delete be;
}

int jax_k(ec_backend_t* b) { return reinterpret_cast<JaxBackend*>(b)->k; }
int jax_m(ec_backend_t* b) { return reinterpret_cast<JaxBackend*>(b)->m; }

int jax_encode(ec_backend_t* b, const uint8_t* data, uint8_t* parity,
               size_t chunk) {
  auto* be = reinterpret_cast<JaxBackend*>(b);
  PyGILState_STATE g = PyGILState_Ensure();
  int rc = -1;
  PyObject* mod = shim_module();
  PyObject* dmv = PyMemoryView_FromMemory(
      reinterpret_cast<char*>(const_cast<uint8_t*>(data)),
      static_cast<Py_ssize_t>(be->k * chunk), PyBUF_READ);
  PyObject* pmv = PyMemoryView_FromMemory(
      reinterpret_cast<char*>(parity),
      static_cast<Py_ssize_t>(be->m * chunk), PyBUF_WRITE);
  if (mod && dmv && pmv) {
    PyObject* r = PyObject_CallMethod(mod, "encode", "OOOn", be->handle,
                                      dmv, pmv,
                                      static_cast<Py_ssize_t>(chunk));
    if (r) {
      rc = static_cast<int>(PyLong_AsLong(r));
      Py_DECREF(r);
    } else {
      PyErr_Print();
    }
  }
  Py_XDECREF(dmv);
  Py_XDECREF(pmv);
  PyGILState_Release(g);
  return rc;
}

int jax_decode(ec_backend_t* b, const int* avail, int n_avail,
               const int* want, int n_want, const uint8_t* chunks,
               uint8_t* out, size_t chunk) {
  auto* be = reinterpret_cast<JaxBackend*>(b);
  PyGILState_STATE g = PyGILState_Ensure();
  int rc = -1;
  PyObject* mod = shim_module();
  PyObject* al = PyList_New(n_avail);
  PyObject* wl = PyList_New(n_want);
  for (int i = 0; al && i < n_avail; ++i)
    PyList_SET_ITEM(al, i, PyLong_FromLong(avail[i]));
  for (int i = 0; wl && i < n_want; ++i)
    PyList_SET_ITEM(wl, i, PyLong_FromLong(want[i]));
  PyObject* cmv = PyMemoryView_FromMemory(
      reinterpret_cast<char*>(const_cast<uint8_t*>(chunks)),
      static_cast<Py_ssize_t>(static_cast<size_t>(n_avail) * chunk),
      PyBUF_READ);
  PyObject* omv = PyMemoryView_FromMemory(
      reinterpret_cast<char*>(out),
      static_cast<Py_ssize_t>(static_cast<size_t>(n_want) * chunk),
      PyBUF_WRITE);
  if (mod && al && wl && cmv && omv) {
    PyObject* r = PyObject_CallMethod(mod, "decode", "OOOOOn", be->handle,
                                      al, wl, cmv, omv,
                                      static_cast<Py_ssize_t>(chunk));
    if (r) {
      rc = static_cast<int>(PyLong_AsLong(r));
      Py_DECREF(r);
    } else {
      PyErr_Print();
    }
  }
  Py_XDECREF(al);
  Py_XDECREF(wl);
  Py_XDECREF(cmv);
  Py_XDECREF(omv);
  PyGILState_Release(g);
  return rc;
}

const ec_plugin_vtable_t kVtable = {jax_create, jax_destroy, jax_k,
                                    jax_m,      jax_encode,  jax_decode};

}  // namespace

extern "C" int __erasure_code_init(const char* plugin_name) {
  return ec_plugin_register(plugin_name ? plugin_name : "jax", &kVtable);
}
