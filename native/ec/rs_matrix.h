// Reed-Solomon coding-matrix constructions — must be coefficient-exact
// with ceph_tpu/ec/matrix.py (the JAX plugin) so the two backends produce
// identical parity bytes (the jerasure<->isa cross-check pattern,
// ref: src/test/erasure-code TestErasureCodeIsa vs Jerasure).
#pragma once

#include <cstdint>
#include <string>
#include <vector>

namespace ceph_tpu {

// (m x k) coding matrix; technique in {reed_sol_van, cauchy_orig,
// cauchy_good, cauchy}. Throws std::runtime_error on bad input.
std::vector<uint8_t> coding_matrix(const std::string& technique, int k,
                                   int m);

// Rows reconstructing `want` chunk ids from `avail` ids (>= k of them);
// (want.size() x avail.size()), columns past k zero.
std::vector<uint8_t> decode_matrix(const std::string& technique, int k,
                                   int m, const std::vector<int>& avail,
                                   const std::vector<int>& want);

}  // namespace ceph_tpu
