// libec_rsvan: sample dlopen-able plugin wrapping the native RS backend.
// Demonstrates the full registry contract (ref: the jerasure plugin's
// ErasureCodePluginJerasure.cc __erasure_code_init).

#include <cstdlib>
#include <cstring>
#include <string>

#include "plugin.h"

// from ec_ref.cc (linked into this .so as well)
extern "C" {
void* ec_ref_init(int k, int m, const char* technique);
void ec_ref_free(void* handle);
int ec_ref_encode(void* handle, const uint8_t* data, uint8_t* parity,
                  size_t chunk_size);
int ec_ref_decode(void* handle, const int* avail, int n_avail,
                  const int* want, int n_want, const uint8_t* chunks,
                  uint8_t* out, size_t chunk_size);
}

namespace {

struct Backend {
  void* h;
  int k, m;
};

// Find "key=" at a token boundary (start or after whitespace/comma) so
// "pack=9" never matches key "k". Returns npos or the value offset.
size_t find_value(const std::string& p, const char* key) {
  std::string needle = std::string(key) + "=";
  size_t pos = 0;
  while ((pos = p.find(needle, pos)) != std::string::npos) {
    if (pos == 0 || p[pos - 1] == ' ' || p[pos - 1] == '\t' ||
        p[pos - 1] == ',')
      return pos + needle.size();
    pos += needle.size();
  }
  return std::string::npos;
}

int parse_int(const char* profile, const char* key, int dflt) {
  std::string p(profile ? profile : "");
  auto pos = find_value(p, key);
  if (pos == std::string::npos) return dflt;
  return std::atoi(p.c_str() + pos);
}

std::string parse_str(const char* profile, const char* key,
                      const char* dflt) {
  std::string p(profile ? profile : "");
  auto pos = find_value(p, key);
  if (pos == std::string::npos) return dflt;
  auto end = p.find_first_of(" \t,", pos);
  return p.substr(pos, end == std::string::npos ? std::string::npos
                                                : end - pos);
}

ec_backend_t* create(const char* profile) {
  int k = parse_int(profile, "k", 4);
  int m = parse_int(profile, "m", 2);
  std::string tech = parse_str(profile, "technique", "reed_sol_van");
  void* h = ec_ref_init(k, m, tech.c_str());
  if (!h) return nullptr;
  auto* b = new Backend{h, k, m};
  return reinterpret_cast<ec_backend_t*>(b);
}

void destroy(ec_backend_t* be) {
  auto* b = reinterpret_cast<Backend*>(be);
  ec_ref_free(b->h);
  delete b;
}

int k_of(ec_backend_t* be) { return reinterpret_cast<Backend*>(be)->k; }
int m_of(ec_backend_t* be) { return reinterpret_cast<Backend*>(be)->m; }

int encode(ec_backend_t* be, const uint8_t* data, uint8_t* parity,
           size_t chunk) {
  auto* b = reinterpret_cast<Backend*>(be);
  return ec_ref_encode(b->h, data, parity, chunk);
}

int decode(ec_backend_t* be, const int* avail, int n_avail, const int* want,
           int n_want, const uint8_t* chunks, uint8_t* out, size_t chunk) {
  auto* b = reinterpret_cast<Backend*>(be);
  return ec_ref_decode(b->h, avail, n_avail, want, n_want, chunks, out,
                       chunk);
}

const ec_plugin_vtable_t kVtable = {create, destroy, k_of, m_of, encode,
                                    decode};

}  // namespace

extern "C" int __erasure_code_init(const char* plugin_name) {
  return ec_plugin_register(plugin_name, &kVtable);
}
