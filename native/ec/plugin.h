// The native erasure-code plugin contract.
//
// ref: src/erasure-code/ErasureCodePlugin.h — same mechanics with a C
// vtable instead of a C++ interface: a plugin shared object exports
// __erasure_code_init(), which registers a named vtable; the registry
// dlopens libec_<name>.so on demand and instantiates backends from
// profiles.
#pragma once

#include <cstddef>
#include <cstdint>

extern "C" {

typedef struct ec_backend ec_backend_t;  // opaque per-profile instance

typedef struct {
  // profile: "k=8 m=3 technique=reed_sol_van"; null on failure.
  ec_backend_t* (*create)(const char* profile);
  void (*destroy)(ec_backend_t*);
  int (*k_of)(ec_backend_t*);
  int (*m_of)(ec_backend_t*);
  // k contiguous data chunks -> m contiguous parity chunks; 0 = ok.
  int (*encode)(ec_backend_t*, const uint8_t* data, uint8_t* parity,
                size_t chunk_size);
  int (*decode)(ec_backend_t*, const int* avail, int n_avail,
                const int* want, int n_want, const uint8_t* chunks,
                uint8_t* out, size_t chunk_size);
} ec_plugin_vtable_t;

// Called by plugins from __erasure_code_init; 0 = ok, -1 = duplicate.
int ec_plugin_register(const char* name, const ec_plugin_vtable_t* vt);

// Entry point every plugin .so must export
// (ref: ErasureCodePlugin.cc __erasure_code_init contract).
typedef int (*ec_plugin_init_fn)(const char* plugin_name);

}  // extern "C"

#ifdef __cplusplus
namespace ceph_tpu {

// ref: ErasureCodePluginRegistry (singleton, load-once, factory).
class PluginRegistry {
 public:
  static PluginRegistry& instance();

  // dlopen "<dir>/libec_<name>.so" if not yet registered; then create a
  // backend from the profile. Returns nullptr + sets err on failure.
  ec_backend_t* factory(const char* name, const char* directory,
                        const char* profile, const ec_plugin_vtable_t** vt,
                        const char** err);

  int add(const char* name, const ec_plugin_vtable_t* vt);

 private:
  PluginRegistry() = default;
};

}  // namespace ceph_tpu
#endif
