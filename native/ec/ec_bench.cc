// Native benchmark binary — the C++ twin of ceph_erasure_code_benchmark
// (ref: src/test/erasure-code/ceph_erasure_code_benchmark.cc). Produces
// the measured CPU baseline the TPU numbers are compared against.
//
//   ec_bench --plugin rsvan --dir build --workload encode \
//            --size 4194304 --iterations 64 --parameter k=8 --parameter m=3

#include <chrono>
#include <cstdio>
#include <cstring>
#include <random>
#include <string>
#include <vector>

#include "plugin.h"

extern "C" void* ec_registry_factory(const char*, const char*, const char*,
                                     const void**);

int main(int argc, char** argv) {
  std::string plugin = "rsvan", dir = ".", workload = "encode";
  std::string profile;
  size_t size = 1 << 20;
  int iterations = 1, erasures = 1;
  bool verify = false;
  for (int i = 1; i < argc; ++i) {
    std::string a = argv[i];
    auto next = [&]() -> const char* {
      return (i + 1 < argc) ? argv[++i] : "";
    };
    if (a == "--plugin" || a == "-p") plugin = next();
    else if (a == "--dir") dir = next();
    else if (a == "--workload" || a == "-w") workload = next();
    else if (a == "--size" || a == "-s") size = std::stoul(next());
    else if (a == "--iterations" || a == "-i") iterations = std::stoi(next());
    else if (a == "--erasures" || a == "-e") erasures = std::stoi(next());
    else if (a == "--verify") verify = true;
    else if (a == "--parameter" || a == "-P") {
      if (!profile.empty()) profile += " ";
      profile += next();
    } else {
      std::fprintf(stderr, "unknown arg %s\n", a.c_str());
      return 2;
    }
  }
  const ec_plugin_vtable_t* vt = nullptr;
  const void* vtp = nullptr;
  auto* be = static_cast<ec_backend_t*>(
      ec_registry_factory(plugin.c_str(), dir.c_str(), profile.c_str(),
                          &vtp));
  vt = static_cast<const ec_plugin_vtable_t*>(vtp);
  if (!be || !vt) {
    std::fprintf(stderr, "plugin %s load failed\n", plugin.c_str());
    return 1;
  }
  int k = vt->k_of(be), m = vt->m_of(be);
  if (erasures < 1 || erasures > m) {
    std::fprintf(stderr, "--erasures must be in [1, m=%d]\n", m);
    return 2;
  }
  size_t chunk = (size + k - 1) / k;
  chunk = (chunk + 127) / 128 * 128;  // same alignment as the JAX side
  std::vector<uint8_t> data(static_cast<size_t>(k) * chunk);
  std::vector<uint8_t> parity(static_cast<size_t>(m) * chunk);
  std::mt19937 rng(0);
  for (auto& b : data) b = static_cast<uint8_t>(rng());

  // Erase the first `erasures` chunks; assemble the k survivor chunks
  // (data then parity order) into `in` — shared by the decode workload
  // and --verify so the two paths can never disagree on layout.
  auto make_decode_set = [&](std::vector<int>& want, std::vector<int>& avail,
                             std::vector<uint8_t>& in) {
    want.clear();
    avail.clear();
    for (int i = 0; i < erasures; ++i) want.push_back(i);
    for (int i = erasures; i < k + m && (int)avail.size() < k; ++i)
      avail.push_back(i);
    if ((int)avail.size() != k) {  // unreachable given erasures <= m,
      std::fprintf(stderr,        // but never index past avail below
                   "only %zu survivors for k=%d (erasures=%d, m=%d)\n",
                   avail.size(), k, erasures, m);
      std::exit(2);
    }
    in.assign(static_cast<size_t>(k) * chunk, 0);
    for (int i = 0; i < k; ++i) {
      const uint8_t* src = avail[i] < k
          ? data.data() + static_cast<size_t>(avail[i]) * chunk
          : parity.data() + static_cast<size_t>(avail[i] - k) * chunk;
      std::memcpy(in.data() + static_cast<size_t>(i) * chunk, src, chunk);
    }
  };

  double elapsed = 0;
  if (workload == "encode") {
    auto t0 = std::chrono::steady_clock::now();
    for (int it = 0; it < iterations; ++it)
      vt->encode(be, data.data(), parity.data(), chunk);
    elapsed = std::chrono::duration<double>(
                  std::chrono::steady_clock::now() - t0)
                  .count();
  } else {
    vt->encode(be, data.data(), parity.data(), chunk);
    std::vector<int> want, avail;
    std::vector<uint8_t> in;
    make_decode_set(want, avail, in);
    std::vector<uint8_t> out(static_cast<size_t>(want.size()) * chunk);
    auto t0 = std::chrono::steady_clock::now();
    for (int it = 0; it < iterations; ++it)
      vt->decode(be, avail.data(), k, want.data(),
                 static_cast<int>(want.size()), in.data(), out.data(),
                 chunk);
    elapsed = std::chrono::duration<double>(
                  std::chrono::steady_clock::now() - t0)
                  .count();
  }
  if (verify) {
    // Erase the first `erasures` data chunks, decode through the
    // plugin, memcmp against the originals — a plugin-level roundtrip
    // check usable from the shell (the jax shim's smoke test).
    vt->encode(be, data.data(), parity.data(), chunk);
    std::vector<int> want, avail;
    std::vector<uint8_t> in;
    make_decode_set(want, avail, in);
    std::vector<uint8_t> out(static_cast<size_t>(want.size()) * chunk);
    int rc = vt->decode(be, avail.data(), k, want.data(),
                        static_cast<int>(want.size()), in.data(),
                        out.data(), chunk);
    bool ok = rc == 0;
    for (size_t i = 0; ok && i < want.size(); ++i) {
      // want ids >= k are parity chunks (reachable when erasures > k,
      // i.e. m > k geometries) — compare against the right buffer.
      const uint8_t* expect = want[i] < k
          ? data.data() + static_cast<size_t>(want[i]) * chunk
          : parity.data() + static_cast<size_t>(want[i] - k) * chunk;
      ok = std::memcmp(out.data() + i * chunk, expect, chunk) == 0;
    }
    std::fprintf(stderr, "verify: %s\n", ok ? "ok" : "FAIL");
    if (!ok) {
      vt->destroy(be);
      return 3;
    }
  }
  double total = static_cast<double>(iterations) * k * chunk;
  // reference output format: seconds <tab> MB/s
  std::printf("%.6f\t%.2f\n", elapsed, total / elapsed / 1e6);
  vt->destroy(be);
  return 0;
}
