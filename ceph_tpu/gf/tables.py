"""GF(2^8) table construction and numpy oracle.

Field: GF(2^8) with primitive polynomial x^8+x^4+x^3+x^2+1 = 0x11d, the
polynomial used by jerasure/gf-complete at w=8 and by ISA-L — matching it is
required for parity-bit compatibility with the reference plugins
(ref: src/erasure-code/jerasure vendored gf-complete gf_w8.c).

Everything here is host-side numpy: table/matrix construction is tiny and
happens once per profile; the per-byte hot loops live in ``ops.py`` (JAX).
"""

from __future__ import annotations

import functools

import numpy as np

GF_POLY = 0x11D  # primitive polynomial, w=8
GF_ORDER = 256


@functools.lru_cache(maxsize=None)
def _log_exp_tables() -> tuple[np.ndarray, np.ndarray]:
    """(log, exp) tables for generator alpha=2 under GF_POLY."""
    exp = np.zeros(512, dtype=np.int32)
    log = np.zeros(256, dtype=np.int32)
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & 0x100:
            x ^= GF_POLY
    exp[255:510] = exp[0:255]  # wraparound so exp[log a + log b] needs no mod
    log[0] = -1  # sentinel; 0 has no log
    log.flags.writeable = False
    exp.flags.writeable = False
    return log, exp


def gf_mul(a: int, b: int) -> int:
    log, exp = _log_exp_tables()
    if a == 0 or b == 0:
        return 0
    return int(exp[log[a] + log[b]])


def gf_pow(a: int, n: int) -> int:
    log, exp = _log_exp_tables()
    if a == 0:
        return 0 if n else 1
    return int(exp[(log[a] * n) % 255])


def gf_inv(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("gf_inv(0)")
    log, exp = _log_exp_tables()
    return int(exp[255 - log[a]])


def gf_div(a: int, b: int) -> int:
    if b == 0:
        raise ZeroDivisionError("gf_div by 0")
    if a == 0:
        return 0
    log, exp = _log_exp_tables()
    return int(exp[(log[a] - log[b]) % 255])


@functools.lru_cache(maxsize=None)
def mul_table() -> np.ndarray:
    """Full 256x256 product table, uint8."""
    log, exp = _log_exp_tables()
    a = np.arange(256)
    la = log[a]
    t = exp[(la[:, None] + la[None, :])]
    t[0, :] = 0
    t[:, 0] = 0
    t = t.astype(np.uint8)
    t.flags.writeable = False  # cached: mutation would corrupt all GF math
    return t


def gf_mul_np(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Elementwise GF product of uint8 arrays (broadcasting)."""
    return mul_table()[np.asarray(a, dtype=np.uint8),
                       np.asarray(b, dtype=np.uint8)]


def gf_matmul_np(m: np.ndarray, x: np.ndarray) -> np.ndarray:
    """GF(2^8) matrix product: (r,k) @ (k,...) -> (r,...), XOR-accumulated.

    The numpy oracle for both JAX kernels; also used for the tiny per-profile
    matrix algebra (decode-matrix construction).
    """
    m = np.asarray(m, dtype=np.uint8)
    x = np.asarray(x, dtype=np.uint8)
    midx = (slice(None), slice(None)) + (None,) * (x.ndim - 1)
    prod = mul_table()[m[midx], x[None]]
    return np.bitwise_xor.reduce(prod, axis=1)


def gf_matinv_np(m: np.ndarray) -> np.ndarray:
    """Invert a square GF(2^8) matrix by Gauss-Jordan.

    Used to build decode matrices from the surviving rows of the generator
    (ref: src/erasure-code/jerasure jerasure_invert_matrix).
    Raises ValueError if singular.
    """
    m = np.array(m, dtype=np.uint8)
    n = m.shape[0]
    if m.shape != (n, n):
        raise ValueError("square matrix required")
    aug = np.concatenate([m, np.eye(n, dtype=np.uint8)], axis=1)
    for col in range(n):
        pivot = None
        for row in range(col, n):
            if aug[row, col]:
                pivot = row
                break
        if pivot is None:
            raise ValueError("singular GF matrix")
        if pivot != col:
            aug[[col, pivot]] = aug[[pivot, col]]
        inv = gf_inv(int(aug[col, col]))
        aug[col] = gf_mul_np(aug[col], inv)
        for row in range(n):
            if row != col and aug[row, col]:
                aug[row] ^= gf_mul_np(aug[row, col], aug[col])
    return aug[:, n:]


# ---------------------------------------------------------------------------
# Bit-matrix decomposition (the MXU formulation)
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _coeff_bitmatrices() -> np.ndarray:
    """(256, 8, 8) uint8: bitmatrix of every coefficient.

    For y = c*x with x = sum_j x_j alpha^j (LSB-first bits), column j of M_c
    is bits(c * alpha^j):  y_i = XOR_j M_c[i, j] * x_j.
    Same role as jerasure_matrix_to_bitmatrix at w=8 (ref:
    src/erasure-code/jerasure vendored jerasure.c), derived directly from
    field linearity rather than translated.
    """
    out = np.zeros((256, 8, 8), dtype=np.uint8)
    for c in range(256):
        for j in range(8):
            col = gf_mul(c, 1 << j)
            for i in range(8):
                out[c, i, j] = (col >> i) & 1
    out.flags.writeable = False  # cached: see mul_table
    return out


def coeff_bitmatrix(c: int) -> np.ndarray:
    """8x8 0/1 matrix of multiply-by-c."""
    return _coeff_bitmatrices()[c]


def expand_bitmatrix(coding: np.ndarray) -> np.ndarray:
    """Expand an (m, k) GF coding matrix to its (8m, 8k) 0/1 bit-matrix."""
    coding = np.asarray(coding, dtype=np.uint8)
    m, k = coding.shape
    bm = _coeff_bitmatrices()[coding]          # (m, k, 8, 8)
    return bm.transpose(0, 2, 1, 3).reshape(8 * m, 8 * k)


# ---------------------------------------------------------------------------
# Nibble product tables (the VPU/LUT formulation)
# ---------------------------------------------------------------------------

def nibble_tables(coding: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Per-coefficient 16-entry product tables (lo, hi), each (m, k, 16).

    lo[c][n] = c*n,  hi[c][n] = c*(n<<4):  c*x = lo[x & 15] ^ hi[x >> 4].
    The ISA-L vpshufb formulation (ref: src/isa-l gf_vect_mul SIMD kernels),
    expressed as gather tables.
    """
    coding = np.asarray(coding, dtype=np.uint8)
    n = np.arange(16, dtype=np.uint8)
    lo = gf_mul_np(coding[..., None], n)
    hi = gf_mul_np(coding[..., None], (n << 4).astype(np.uint8))
    return lo, hi
