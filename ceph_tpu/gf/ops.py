"""JAX kernels for GF(2^8) linear algebra over byte streams.

These are the hot loops of the erasure-code path — the TPU-native replacement
for ISA-L's ``ec_encode_data`` AVX kernels and jerasure's region ops
(ref: src/erasure-code/isa/ErasureCodeIsa.cc isa_encode;
src/erasure-code/jerasure/ErasureCodeJerasure.cc jerasure_encode).

Layouts: byte payloads are (k, L) uint8 — k chunks of L bytes, L = lane
dimension (chunk bytes, possibly batch*chunk flattened). All kernels are pure
and jit/vmap/shard_map-safe; matrix/table operands are small per-profile
constants built host-side in ``tables.py``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ceph_tpu.gf import tables


def xor_reduce(x: jax.Array, axis: int) -> jax.Array:
    """XOR-accumulate along an axis (GF(2^8) addition)."""
    return jax.lax.reduce(x, np.array(0, dtype=x.dtype),
                          jax.lax.bitwise_xor, (axis,))


def unpack_bits(data: jax.Array) -> jax.Array:
    """(k, L) uint8 -> (8k, L) int8 bit-planes, LSB-first within each byte.

    Row ordering matches tables.expand_bitmatrix: chunk i's bits occupy rows
    [8i, 8i+8), bit j (value 2^j) at row 8i+j.
    """
    k, L = data.shape
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = (data[:, None, :] >> shifts[None, :, None]) & jnp.uint8(1)
    return bits.reshape(8 * k, L).astype(jnp.int8)


def pack_bits(bits: jax.Array) -> jax.Array:
    """(8m, L) 0/1 -> (m, L) uint8, inverse of unpack_bits."""
    m8, L = bits.shape
    m = m8 // 8
    b = bits.reshape(m, 8, L).astype(jnp.uint8)
    weights = (jnp.uint8(1) << jnp.arange(8, dtype=jnp.uint8))
    return jnp.sum(b * weights[None, :, None], axis=1, dtype=jnp.uint8)


def gf_matmul_bitplanes(bitmatrix: jax.Array, data: jax.Array) -> jax.Array:
    """GF(2^8) coding-matrix product via the MXU.

    bitmatrix: (8m, 8k) 0/1 int8 (tables.expand_bitmatrix of the GF matrix).
    data:      (k, L) uint8.
    returns    (m, L) uint8 — XOR-accumulated GF products.

    GF(2^8) multiply-accumulate is GF(2)-linear, so the whole coding matrix is
    one binary matmul: int8 x int8 -> int32 accumulate on the systolic array,
    XOR realized as the low bit of the integer sum.
    """
    bits = unpack_bits(data)                                # (8k, L) int8
    acc = jax.lax.dot_general(
        bitmatrix.astype(jnp.int8), bits,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)                   # (8m, L)
    return pack_bits(acc & 1)


def gf_matmul_lut(lo: jax.Array, hi: jax.Array, data: jax.Array) -> jax.Array:
    """GF(2^8) coding-matrix product via nibble product tables (VPU path).

    lo, hi: (m, k, 16) uint8 from tables.nibble_tables.
    data:   (k, L) uint8.
    returns (m, L) uint8.
    """
    low = (data & 15).astype(jnp.int32)                     # (k, L)
    high = (data >> 4).astype(jnp.int32)
    prod = (jnp.take_along_axis(lo, low[None], axis=2) ^
            jnp.take_along_axis(hi, high[None], axis=2))    # (m, k, L)
    return xor_reduce(prod, axis=1)


def gf_matmul_bytes(matrix: jax.Array, data: jax.Array) -> jax.Array:
    """Reference JAX path: full 256x256 product table gathers.

    matrix: (m, k) uint8 GF coefficients; data: (k, L) uint8.
    Slow (64 KiB gather per element) — used for testing/validation only.
    """
    table = jnp.asarray(tables.mul_table().reshape(-1))
    idx = matrix[:, :, None].astype(jnp.int32) * 256 + data[None].astype(jnp.int32)
    return xor_reduce(jnp.take(table, idx), axis=1)


def gf2_matmul_bytes(bm: jax.Array, planes: jax.Array) -> jax.Array:
    """GF(2) combine of byte rows on the MXU: out[i] = XOR_{j: bm[i,j]=1}
    planes[j], for planes (R_in, L) uint8 -> (R_out, L) uint8.

    The packet-granularity bitmatrix product of jerasure's array codes
    (liberation/blaum_roth/liber8tion): each byte's 8 bits ride as
    parallel lanes; the contraction is over packet rows only, realized as
    one int8 matmul with a mod-2 epilogue.
    """
    r_in, L = planes.shape
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = ((planes[:, None, :] >> shifts[None, :, None]) &
            jnp.uint8(1)).astype(jnp.int8)          # (R_in, 8, L)
    acc = jax.lax.dot_general(
        bm.astype(jnp.int8), bits.reshape(r_in, 8 * L),
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)           # (R_out, 8L)
    b = (acc & 1).astype(jnp.uint8).reshape(-1, 8, L)
    weights = (jnp.uint8(1) << shifts)
    return jnp.sum(b * weights[None, :, None], axis=1, dtype=jnp.uint8)


@functools.partial(jax.jit, static_argnames=("w",))
def bitmatrix_encode_stripes(bm: jax.Array, data: jax.Array,
                             w: int) -> jax.Array:
    """Batched packet-plane encode: data (B, k, C) with C % w == 0 ->
    (B, rows_out/w, C). Each chunk is w packets of C/w bytes (jerasure's
    word/packet layout); drive d's packets are bitmatrix rows
    [d*w, (d+1)*w)."""
    B, k, C = data.shape
    ps = C // w
    planes = data.reshape(B, k * w, ps)             # (B, kw, ps)
    flat = jnp.transpose(planes, (1, 0, 2)).reshape(k * w, B * ps)
    out = gf2_matmul_bytes(bm, flat)                # (mw, B*ps)
    mw = out.shape[0]
    m = mw // w
    out = jnp.transpose(out.reshape(mw, B, ps), (1, 0, 2))
    return out.reshape(B, m, C)


@functools.partial(jax.jit, static_argnames=("backend",))
def encode_stripes(bitmatrix: jax.Array, lo: jax.Array, hi: jax.Array,
                   data: jax.Array, backend: str = "bitmatmul") -> jax.Array:
    """Batched stripe encode: data (batch, k, C) uint8 -> (batch, m, C).

    The stripe batch is the data-parallel axis (SURVEY.md §2.5): every stripe
    is independent, so batching — not tensor-splitting the tiny coding matrix
    — is how this fills the MXU.
    """
    b, k, C = data.shape
    flat = jnp.transpose(data, (1, 0, 2)).reshape(k, b * C)
    if backend == "bitmatmul":
        out = gf_matmul_bitplanes(bitmatrix, flat)
    elif backend == "lut":
        out = gf_matmul_lut(lo, hi, flat)
    else:
        raise ValueError(f"unknown gf backend {backend!r}")
    m = out.shape[0]
    return jnp.transpose(out.reshape(m, b, C), (1, 0, 2))
