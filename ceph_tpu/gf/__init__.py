"""GF(2^8) arithmetic as TPU-friendly linear algebra.

The whole erasure-code stack reduces to GF(2^8) matrix-vector products over
byte streams (ref: src/erasure-code/jerasure vendored gf-complete; the ISA-L
plugin's ec_encode_data hot loop). Two TPU formulations:

- **bitmatmul (MXU)**: multiplication by a constant c in GF(2^8) is linear
  over GF(2), so c is an 8x8 bit-matrix and an (m x k) GF coding matrix
  expands to an (8m x 8k) 0/1 matrix B.  RS encode of k chunks becomes
  ``pack_bits((B @ unpack_bits(data)) mod 2)`` — an int8 matmul landing on
  the systolic array, XOR-accumulate realized as int32 accumulate + mod 2.

- **lut (VPU)**: the ISA-L PSHUFB trick — split each byte into nibbles and
  look each up in per-coefficient 16-entry product tables, XOR the halves
  (ref: src/isa-l ec_encode_data vpshufb kernels). On TPU this is gathers +
  elementwise XOR on the vector unit; no matmul involved.

Both are bit-exact against the pure-numpy oracle in ``tables.py``.
"""

from ceph_tpu.gf.tables import (
    GF_POLY,
    gf_mul,
    gf_div,
    gf_inv,
    gf_pow,
    gf_mul_np,
    gf_matmul_np,
    gf_matinv_np,
    coeff_bitmatrix,
    expand_bitmatrix,
    nibble_tables,
)
from ceph_tpu.gf.ops import (
    unpack_bits,
    pack_bits,
    gf_matmul_bitplanes,
    gf_matmul_lut,
    gf_matmul_bytes,
)
