"""Pallas TPU kernels for the GF(2^8) bit-plane encode.

The XLA `bitmatmul` path (gf.ops.gf_matmul_bitplanes) materializes the
(8k, L) int8 bit-plane expansion in HBM — 8x the payload in traffic —
before the MXU contraction, which caps encode throughput far below the
payload roofline. These kernels fuse unpack -> int8 matmul -> mod-2 ->
pack inside one VMEM tile, so HBM sees only the payload
(read k + write m chunks ≈ 1 + m/k bytes moved per byte encoded).

Design notes (round 4, all measured on a v5e with the interleaved
median-of-paired-slopes protocol; round-3 numbers in parentheses):

- **Mod-2 absorb — the `& 1` before the matmul is unnecessary.** The
  MXU only needs operands CONGRUENT to the bit mod 2: feeding the
  whole shifted byte `(data >> b)` wrapped to int8 keeps parity intact
  (the int8 wrap changes the value by a multiple of 256 — even; the
  int32 accumulator is exact at |acc| <= 8k * 128; the epilogue's
  `acc & 1` kills all junk). One full VPU pass gone.
- **Per-plane constant shifts** replace round 3's
  `concatenate([data]*8)` + broadcasted-iota variable shift: 8 (or 16,
  see below) immediate-shift ops on (k, T) int32, each cast straight
  to int8 — no (8k, T) int32 intermediate, no iota. (Shifting in the
  int8/uint8 domain does not lower in Mosaic — measured, compile
  error — so the shifts stay in native 32-bit lanes.)
- **Block-diagonal r=2 contraction.** The k=8 coding matmul is
  (24, 64) — it uses 9% of the 128x128 systolic array and streams one
  column per cycle anyway. Splitting the tile into two lane-halves and
  stacking their planes gives a (48, 128) @ (128, T/2) product: the
  full contraction depth at half the column count. Applied whenever
  2*8k <= 128.
- **Aligned pack rows.** The mod-2 + byte-pack epilogue is one bf16
  MXU matmul (weights 2^b <= 128 and pbits {0,1} are bf16-exact; the
  f32 accumulator is exact <= 255). The two half-results ride rows
  [0, m) and [8, 8+m) of a 16-row output so both final stores are
  sublane-tile-aligned — Mosaic crashes on an int8 lane-concat whose
  operand carries a vpad sublane offset (measured: the naive
  (3, h)+(3, h) concat), and rejoining the int32 acc halves instead
  costs a 3 MiB VMEM copy per tile (~0.35 ms/step at the bench shape).
- Stage attribution at the bench shape (64 x 8 x 512 KiB, 2.4 ms/step
  full): unpack shifts ~0.76 ms, main matmul ~0.66 ms, epilogue
  ~0.35 ms, HBM floor 0.43 ms — the stages mostly serialize, so the
  formulation is VPU/MXU-issue-bound, not bandwidth-bound. int4
  operands compile but run SLOWER (extra `& 1` + casts outweigh the
  MXU rate); int32 operands don't lower.
- Net: ~103 GiB/s encode at k=8,m=3 on 256 MiB steps (round 3:
  ~79 GiB/s same protocol; round-3's published 88 was a luckier
  platform window — see BASELINE.md).
- The batched entry point takes (B, k, C) stripes directly with a
  (B, C/tile) grid so callers never pay the (B,k,C) -> (k, B*C)
  transpose the XLA path needs. Both grid dims are `parallel`
  (independent output tiles).

The plan (permuted bitmatrix + block-diag operand + pack weights) is
built eagerly on the host (make_plan) because the permutation needs
concrete values; the jitted entry then treats the plan arrays as
ordinary operands.

ref: the role of ISA-L's ec_encode_data AVX512 kernels
(src/erasure-code/isa); the bit-plane formulation is SURVEY.md §7
step 1's MXU mapping.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    HAVE_PALLAS = True
except ImportError:                                   # pragma: no cover
    HAVE_PALLAS = False

# Minimum lane-tile bytes per grid step (and the alignment callers must
# provide). encode_batch_planned picks the largest tile in
# [TILE_L, TILE_MAX] that divides C and keeps the VMEM working set in
# budget — measured on v5e: 128 KiB tiles beat 32 KiB by ~5% and
# 512 KiB exceeds the 16 MiB scoped-VMEM limit at k=8.
TILE_L = 1 << 15
TILE_MAX = 1 << 17
# k * tile cap keeping the scoped-VMEM allocation under the compiler's
# 16 MiB limit (k=8 at 128 KiB tiles measured as the edge's safe side).
_KTILE_CAP = 1 << 20


class EncodePlan(NamedTuple):
    bm_bitmajor: jax.Array   # (8m, 8k) int8, cols permuted to b*k+i
    bm_op: jax.Array         # (r*8m, r*8k) int8 block-diag MXU operand
    packw: jax.Array         # (r*OFF, r*8m) bf16 aligned pack weights


def _pick_tile(k: int, C: int) -> int:
    t = TILE_MAX
    while t > TILE_L:
        if C % t == 0 and k * t <= _KTILE_CAP:
            return t
        t //= 2
    # TILE_L is the floor regardless of k: pallas_ok() gates on it and
    # the pre-cap code ran every k at this tile size
    return TILE_L if C % TILE_L == 0 else 0


def make_plan(bitmatrix: np.ndarray) -> EncodePlan:
    """Host-side constants for one coding bitmatrix (chunk-major rows
    8j+b / cols 8i+b', as produced by tables.expand_bitmatrix)."""
    bm = np.asarray(bitmatrix, dtype=np.int8)
    m8, k8 = bm.shape
    k, m = k8 // 8, m8 // 8
    bm_bitmajor = np.zeros_like(bm)
    for b in range(8):
        bm_bitmajor[:, b * k:(b + 1) * k] = bm[:, b::8]
    r = 2 if 2 * k8 <= 128 else 1
    bm_op = np.zeros((r * m8, r * k8), dtype=np.int8)
    for j in range(r):
        bm_op[j * m8:(j + 1) * m8, j * k8:(j + 1) * k8] = bm_bitmajor
    # Byte pack as one bf16 matmul: out[j] = sum_b (1<<b) * paritybit
    # [8j+b]; per lane-half j its m output rows start at j*OFF so every
    # final store slice is 8-sublane aligned.
    off = 8 * ((m + 7) // 8)
    pw = np.zeros((r * off, r * m8), dtype=np.float32)
    for j in range(r):
        for jj in range(m):
            for b in range(8):
                pw[j * off + jj, j * m8 + 8 * jj + b] = float(1 << b)
    return EncodePlan(jnp.asarray(bm_bitmajor),
                      jnp.asarray(bm_op),
                      jnp.asarray(pw).astype(jnp.bfloat16))


def _make_kernel(k: int, m: int, r: int, off: int):
    def kernel(bm_ref, pw_ref, data_ref, out_ref):
        data = data_ref[0].astype(jnp.int32)          # (k, T)
        T = data.shape[1]
        h = T // r
        if r == 2:
            halves = (data[:, :h], data[:, h:])
        else:
            halves = (data,)
        # constant-shift planes, no & 1 (mod-2 absorb: the int8 wrap of
        # data>>b differs from bit b by an even number; acc & 1 below
        # recovers the parity exactly — |acc| <= 8k*128 is int32-exact)
        planes = [(d >> b).astype(jnp.int8)
                  for d in halves for b in range(8)]
        bits = jnp.concatenate(planes, axis=0)        # (r*8k, h) int8
        acc = jax.lax.dot_general(
            bm_ref[...], bits, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)         # (r*8m, h)
        pbits = (acc & 1).astype(jnp.bfloat16)
        out = jax.lax.dot_general(
            pw_ref[...], pbits, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)       # (r*off, h)
        outi = out.astype(jnp.int32).astype(jnp.uint8)
        if r == 2:
            out_ref[0, :, 0:h] = outi[0:m]
            out_ref[0, :, h:2 * h] = outi[off:off + m]
        else:
            out_ref[0] = outi[0:m]
    return kernel


@functools.partial(jax.jit, static_argnames=("interpret",))
def encode_batch_planned(plan: EncodePlan, data: jax.Array,
                         interpret: bool = False) -> jax.Array:
    """plan x (B, k, C) uint8 -> (B, m, C) uint8 parity.

    C must be a multiple of TILE_L (use pallas_ok; callers fall back to
    the XLA kernel otherwise)."""
    m8, k8 = plan.bm_bitmajor.shape
    B, k, C = data.shape
    assert k8 == 8 * k, (plan.bm_bitmajor.shape, data.shape)
    m = m8 // 8
    r = plan.bm_op.shape[1] // k8
    off = plan.packw.shape[0] // r
    tile = _pick_tile(k, C)
    assert tile, f"C={C} not a multiple of TILE_L={TILE_L}"
    grid = (B, C // tile)
    params = {}
    if not interpret:
        # Output tiles are fully independent: both grid dims parallel
        # lets Mosaic overlap/pipeline across stripes and lane tiles.
        params["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel"))
    return pl.pallas_call(
        _make_kernel(k, m, r, off),
        grid=grid,
        in_specs=[
            pl.BlockSpec(plan.bm_op.shape, lambda b, i: (0, 0)),
            pl.BlockSpec(plan.packw.shape, lambda b, i: (0, 0)),
            pl.BlockSpec((1, k, tile), lambda b, i: (b, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, m, tile), lambda b, i: (b, 0, i)),
        out_shape=jax.ShapeDtypeStruct((B, m, C), jnp.uint8),
        interpret=interpret,
        **params,
    )(plan.bm_op, plan.packw, data)


def gf_encode_batch_pallas(bitmatrix, data: jax.Array,
                           interpret: bool = False) -> jax.Array:
    """Eager convenience wrapper: chunk-major bitmatrix (host value) x
    (B, k, C) -> (B, m, C). Not callable under jit (plan needs values)."""
    return encode_batch_planned(make_plan(np.asarray(bitmatrix)), data,
                                interpret=interpret)


def gf_matmul_bitplanes_pallas(bitmatrix, data: jax.Array,
                               interpret: bool = False) -> jax.Array:
    """2-D wrapper: (8m, 8k) bitmatrix x (k, L) uint8 -> (m, L) uint8."""
    out = gf_encode_batch_pallas(bitmatrix, data[None], interpret=interpret)
    return out[0]


def pallas_ok(C: int) -> bool:
    """Fast-path eligibility for this lane/chunk length."""
    return HAVE_PALLAS and C % TILE_L == 0 and C > 0
