"""Pallas TPU kernels for the GF(2^8) bit-plane encode.

The XLA `bitmatmul` path (gf.ops.gf_matmul_bitplanes) materializes the
(8k, L) int8 bit-plane expansion in HBM — 8x the payload in traffic —
before the MXU contraction, which caps encode throughput far below the
payload roofline. These kernels fuse unpack -> int8 matmul -> mod-2 ->
pack inside one VMEM tile, so HBM sees only the payload
(read k + write m chunks ≈ 1 + m/k bytes moved per byte encoded).

Design notes (measured on a v5e, round 3):

- The VPU bit-unpack, not the MXU matmul, is the bottleneck, so the
  kernel avoids every Mosaic relayout it can:
  * unpack is a `concatenate([data]*8)` (sublane copy, no interleave)
    with a per-row shift from a broadcasted iota — NOT a
    (k, 8, T) -> (8k, T) reshape, which lowers to an expensive bit
    interleaving relayout. The coding bitmatrix columns are permuted
    host-side to the matching bit-major order (see make_plan).
  * the mod-2 + byte-pack epilogue runs on the MXU as a second small
    matmul against constant weight matrices (1<<b), instead of a VPU
    multiply-reduce over a reshaped (m, 8, T) view.
- Together these took the measured rate from ~55 GiB/s (XLA bitmatmul,
  transpose included) to ~80-95 GiB/s at k=8,m=3 on 256 MiB steps.
- The batched entry point takes (B, k, C) stripes directly with a
  (B, C/TILE) grid so callers never pay the (B,k,C) -> (k, B*C)
  transpose the XLA path needs.

The plan (permuted bitmatrix + pack weights) is built eagerly on the
host (make_plan) because the permutation needs concrete values; the
jitted entry then treats the plan arrays as ordinary operands.

ref: the role of ISA-L's ec_encode_data AVX512 kernels
(src/erasure-code/isa); the bit-plane formulation is SURVEY.md §7
step 1's MXU mapping.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    HAVE_PALLAS = True
except ImportError:                                   # pragma: no cover
    HAVE_PALLAS = False

# Lane-tile bytes per grid step. Working set per step is
# ~(k + 8k*4 + 8k + m*4 + m) * TILE_L bytes; 32 KiB keeps it ~10 MiB at
# k=8 — small enough to double-buffer comfortably in a 128 MiB VMEM.
# Measured: 32 KiB beats both 16 KiB and 64 KiB tiles on v5e.
TILE_L = 1 << 15


class EncodePlan(NamedTuple):
    bm_bitmajor: jax.Array   # (8m, 8k) int8, cols permuted to b*k+i
    pack_lo: jax.Array       # (m, 8m) int8, weights 1..64
    pack_hi: jax.Array       # (m, 8m) int8, bit-7 selector


def make_plan(bitmatrix: np.ndarray) -> EncodePlan:
    """Host-side constants for one coding bitmatrix (chunk-major rows
    8j+b / cols 8i+b', as produced by tables.expand_bitmatrix)."""
    bm = np.asarray(bitmatrix, dtype=np.int8)
    m8, k8 = bm.shape
    k, m = k8 // 8, m8 // 8
    bm_bitmajor = np.zeros_like(bm)
    for b in range(8):
        bm_bitmajor[:, b * k:(b + 1) * k] = bm[:, b::8]
    # Byte pack as matmul: out[j] = sum_b (1<<b) * paritybit[8j+b].
    # int8 weights cap at 64, so bit 7 rides a second 0/1 matrix.
    lo = np.zeros((m, m8), dtype=np.int8)
    hi = np.zeros((m, m8), dtype=np.int8)
    for j in range(m):
        for b in range(7):
            lo[j, 8 * j + b] = 1 << b
        hi[j, 8 * j + 7] = 1
    return EncodePlan(jnp.asarray(bm_bitmajor), jnp.asarray(lo),
                      jnp.asarray(hi))


def _kernel(bm_ref, lo_ref, hi_ref, data_ref, out_ref):
    data = data_ref[0].astype(jnp.int32)              # (k, T)
    k, T = data.shape
    big = jnp.concatenate([data] * 8, axis=0)         # (8k, T) bit-major
    shifts = jax.lax.broadcasted_iota(jnp.int32, (8 * k, T), 0) // k
    bits = ((big >> shifts) & 1).astype(jnp.int8)
    acc = jax.lax.dot_general(
        bm_ref[...], bits, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)             # (8m, T)
    pbits = (acc & 1).astype(jnp.int8)
    lo = jax.lax.dot_general(
        lo_ref[...], pbits, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)             # (m, T)
    hi = jax.lax.dot_general(
        hi_ref[...], pbits, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    out_ref[0] = (lo + (hi << 7)).astype(jnp.uint8)


@functools.partial(jax.jit, static_argnames=("interpret",))
def encode_batch_planned(plan: EncodePlan, data: jax.Array,
                         interpret: bool = False) -> jax.Array:
    """plan x (B, k, C) uint8 -> (B, m, C) uint8 parity.

    C must be a multiple of TILE_L (use pallas_ok; callers fall back to
    the XLA kernel otherwise)."""
    m8, k8 = plan.bm_bitmajor.shape
    B, k, C = data.shape
    assert k8 == 8 * k, (plan.bm_bitmajor.shape, data.shape)
    assert C % TILE_L == 0, f"C={C} not a multiple of TILE_L={TILE_L}"
    m = m8 // 8
    grid = (B, C // TILE_L)
    params = {}
    if not interpret:
        # Stripes are independent: declaring the batch grid dim parallel
        # lets Mosaic overlap/pipeline across stripes (measured ~2.5x vs
        # sequential semantics on the bench's (64, 16) grid).
        params["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"))
    return pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((m8, k8), lambda b, i: (0, 0)),
            pl.BlockSpec((m, m8), lambda b, i: (0, 0)),
            pl.BlockSpec((m, m8), lambda b, i: (0, 0)),
            pl.BlockSpec((1, k, TILE_L), lambda b, i: (b, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, m, TILE_L), lambda b, i: (b, 0, i)),
        out_shape=jax.ShapeDtypeStruct((B, m, C), jnp.uint8),
        interpret=interpret,
        **params,
    )(*plan, data)


def gf_encode_batch_pallas(bitmatrix, data: jax.Array,
                           interpret: bool = False) -> jax.Array:
    """Eager convenience wrapper: chunk-major bitmatrix (host value) x
    (B, k, C) -> (B, m, C). Not callable under jit (plan needs values)."""
    return encode_batch_planned(make_plan(np.asarray(bitmatrix)), data,
                                interpret=interpret)


def gf_matmul_bitplanes_pallas(bitmatrix, data: jax.Array,
                               interpret: bool = False) -> jax.Array:
    """2-D wrapper: (8m, 8k) bitmatrix x (k, L) uint8 -> (m, L) uint8."""
    out = gf_encode_batch_pallas(bitmatrix, data[None], interpret=interpret)
    return out[0]


def pallas_ok(C: int) -> bool:
    """Fast-path eligibility for this lane/chunk length."""
    return HAVE_PALLAS and C % TILE_L == 0 and C > 0
