"""Pallas TPU kernel for the GF(2^8) bit-plane encode.

The XLA `bitmatmul` path (gf.ops.gf_matmul_bitplanes) materializes the
(8k, L) int8 bit-plane expansion in HBM — 8x the payload in traffic —
before the MXU contraction, which caps encode throughput far below the
payload roofline. This kernel fuses unpack -> int8 matmul -> mod-2 ->
pack inside one VMEM tile, so HBM sees only the payload in
(read k + write m chunks ≈ 1 + m/k bytes moved per byte encoded).

ref: the role of ISA-L's ec_encode_data AVX512 kernels
(src/erasure-code/isa); the bit-plane formulation is SURVEY.md §7
step 1's MXU mapping.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    HAVE_PALLAS = True
except ImportError:                                   # pragma: no cover
    HAVE_PALLAS = False

# Lane-tile bytes per grid step. 8k int8 bit-planes of a TILE_L block
# plus the int32 accumulator must fit VMEM comfortably:
# 64 * TILE_L (bits) + 24 * 4 * TILE_L (acc) ≈ 160 * TILE_L.
# TILE_L = 64 KiB -> ~10 MiB VMEM working set on a 128 MiB-VMEM v5e.
TILE_L = 1 << 16


def _encode_kernel(bm_ref, data_ref, out_ref):
    data = data_ref[...]                              # (k, TILE_L) uint8
    k = data.shape[0]
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = ((data[:, None, :] >> shifts[None, :, None]) & jnp.uint8(1))
    bits = bits.reshape(8 * k, data.shape[1]).astype(jnp.int8)
    acc = jax.lax.dot_general(
        bm_ref[...], bits,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)             # (8m, TILE_L)
    m8 = acc.shape[0]
    b = (acc & 1).astype(jnp.uint8).reshape(m8 // 8, 8, -1)
    weights = (jnp.uint8(1) << shifts)
    out_ref[...] = jnp.sum(b * weights[None, :, None], axis=1,
                           dtype=jnp.uint8)


@functools.partial(jax.jit, static_argnames=("interpret",))
def gf_matmul_bitplanes_pallas(bitmatrix: jax.Array, data: jax.Array,
                               interpret: bool = False) -> jax.Array:
    """(8m, 8k) bitmatrix x (k, L) uint8 -> (m, L) uint8 parity.

    L must be a multiple of TILE_L for the tiled fast path; callers
    with smaller/unaligned L fall back to the XLA kernel upstream."""
    m8, k8 = bitmatrix.shape
    k, L = data.shape
    assert k8 == 8 * k, (bitmatrix.shape, data.shape)
    m = m8 // 8
    grid = (L // TILE_L,)
    return pl.pallas_call(
        _encode_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((m8, k8), lambda i: (0, 0)),
            pl.BlockSpec((k, TILE_L), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((m, TILE_L), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((m, L), jnp.uint8),
        interpret=interpret,
    )(bitmatrix, data)


def pallas_ok(L: int) -> bool:
    """Fast-path eligibility for this lane length."""
    return HAVE_PALLAS and L % TILE_L == 0
