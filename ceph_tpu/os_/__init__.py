from ceph_tpu.os_.kv import WALDB, KeyValueDB, KVTransaction, MemDB
from ceph_tpu.os_.objectstore import (
    ChecksumError, MemStore, ObjectStore, StoreError, Transaction,
    WALStore,
)

__all__ = [
    "KeyValueDB", "KVTransaction", "MemDB", "WALDB",
    "ObjectStore", "Transaction", "MemStore", "WALStore",
    "StoreError", "ChecksumError",
]
