"""Block allocator for the BlueStore-style store.

ref: src/os/bluestore/BitmapAllocator (via Allocator.h) — tracks free
space in ALLOCATION UNITS over a flat block device. This one is a
numpy bitmap with a rolling first-fit cursor: allocation takes the
first free AUs at-or-after the cursor (wrapping once), grouped into
contiguous extents — fragmented results are fine, the caller's extent
map absorbs them, exactly like BlueStore's PExtentVector.
"""

from __future__ import annotations

import numpy as np


class AllocatorError(Exception):
    pass


class BitmapAllocator:
    """Free-space bitmap in allocation units."""

    def __init__(self, total_aus: int):
        self.total = int(total_aus)
        self.used = np.zeros(self.total, dtype=bool)
        self._cursor = 0

    @property
    def free_aus(self) -> int:
        return self.total - int(self.used.sum())

    def allocate(self, n: int) -> list[tuple[int, int]]:
        """n AUs as [(start_au, n_aus), ...] extents, or raise ENOSPC.

        First-fit from the rolling cursor (wraps once) — the cursor
        keeps sequential workloads laying data forward instead of
        re-scanning the device head every call."""
        if n <= 0:
            return []
        free_idx = np.flatnonzero(~self.used)
        if free_idx.size < n:
            raise AllocatorError(
                f"ENOSPC: want {n} AUs, have {free_idx.size}")
        at = np.searchsorted(free_idx, self._cursor)
        picked = np.concatenate([free_idx[at:], free_idx[:at]])[:n]
        picked.sort()
        self.used[picked] = True
        self._cursor = int(picked[-1]) + 1
        if self._cursor >= self.total:
            self._cursor = 0
        # group consecutive AUs into extents
        cuts = np.flatnonzero(np.diff(picked) != 1) + 1
        out = []
        for run in np.split(picked, cuts):
            out.append((int(run[0]), int(run.size)))
        return out

    def release(self, extents: list[tuple[int, int]]) -> None:
        for start, cnt in extents:
            if start < 0 or start + cnt > self.total:
                raise AllocatorError(f"free out of range: {start}+{cnt}")
            self.used[start:start + cnt] = False

    def mark_used(self, extents: list[tuple[int, int]]) -> None:
        """Mount-time claim (rebuilding state from the onode extents)."""
        for start, cnt in extents:
            if start < 0 or start + cnt > self.total:
                raise AllocatorError(
                    f"claim out of range: {start}+{cnt}")
            if self.used[start:start + cnt].any():
                raise AllocatorError(
                    f"double allocation at {start}+{cnt}")
            self.used[start:start + cnt] = True
