"""BlueStoreLite: extent-allocated object store over a flat block file.

ref: src/os/bluestore/BlueStore.{h,cc} — the architecture in miniature,
not a translation:

- object DATA lives on a flat ``block`` file carved into ALLOCATION
  UNITS by a bitmap allocator (allocator.py); each onode carries an
  extent map [(logical_off, au, n_aus, crc32)] — BlueStore's
  ExtentMap/PExtentVector role, with csum_type=crc32c per extent.
- object METADATA (onodes: size + extents + xattrs + omap) lives in
  the WALDB key-value store — the RocksDB seat; every ObjectStore
  Transaction commits as ONE atomic kv batch.
- WRITES are copy-on-write at AU granularity: the affected AU range is
  rebuilt into freshly allocated space, the block file is written and
  flushed BEFORE the kv commit points at it, and the old AUs are freed
  after — BlueStore's big-write path, which makes torn block writes
  unreachable (metadata never references half-written space).
- SMALL overwrites that stay inside one already-allocated AU take the
  DEFERRED path instead: the bytes ride inside the kv batch (a "D"
  record) and are applied to the block file after the commit; mount
  replays any "D" records left by a crash (idempotent: whole-AU
  rewrite) — BlueStore's deferred_txn machinery.
- ``fsck`` walks every onode: extents in-bounds, no cross-object
  overlap, per-extent crc verified against the block file, allocator
  bitmap consistent with the union of extents (leak/double-use
  detection) — BlueStore::_fsck's core checks. ``statfs`` reports the
  allocator's view.

- CLONE is O(metadata) via SHARED BLOBS (ref: BlueStore::SharedBlob +
  bluestore_shared_blob_t): each extent carries a blob id (``sb_id``,
  0 = unshared); ``Transaction.clone`` stamps the source's extents
  with fresh sb_ids, bumps a persisted per-AU refcount table (kv
  prefix "B") and copies only the extent-map entries — zero data
  bytes move. Overwrites of a shared extent always take the COW path
  (never deferred-in-place), the punched AUs merely decrement their
  refs, and an AU returns to the allocator only at refcount 0
  (deferred-release discipline). ``fsck`` cross-checks the stored
  refcounts against the union of extent-map references.

Not rebuilt: compression, BlueFS/multi-device tiering, cache
trimming. Collections/omap/attrs reuse the kv directly.
"""

from __future__ import annotations

import os
import zlib

from ceph_tpu.encoding.denc import Decoder, Encoder
from ceph_tpu.os_.allocator import AllocatorError, BitmapAllocator
from ceph_tpu.utils.perf_counters import PerfCountersBuilder
from ceph_tpu.os_.kv import KVTransaction, WALDB
from ceph_tpu.os_.objectstore import (
    OP_CLONE, OP_MKCOLL, OP_OMAP_CLEAR, OP_OMAP_RMKEYS, OP_OMAP_SETKEYS,
    OP_REMOVE, OP_RMATTR, OP_RMCOLL, OP_SETATTRS, OP_TOUCH, OP_TRUNCATE,
    OP_WRITE, OP_ZERO,
    ChecksumError, ObjectStore, StoreError, Transaction,
)


class _Onode:
    __slots__ = ("size", "extents", "attrs", "omap")

    def __init__(self):
        self.size = 0
        # [(loff, au, n_aus, crc32 of the logical bytes, sb_id)]
        # sorted by loff; gaps read as zeros (sparse objects).
        # sb_id 0 = unshared; nonzero names a shared-blob refcount
        # record (this AU range may be referenced by other onodes)
        self.extents: list[list[int]] = []
        self.attrs: dict[str, bytes] = {}
        self.omap: dict[str, bytes] = {}


def _enc_onode(o: _Onode) -> bytes:
    e = Encoder()
    e.u64(o.size)
    e.list(o.extents, lambda e, x:
           e.u64(x[0]).u64(x[1]).u64(x[2]).u32(x[3]).u64(x[4]))
    e.map(o.attrs, lambda e, k: e.string(k), lambda e, v: e.blob(v))
    e.map(o.omap, lambda e, k: e.string(k), lambda e, v: e.blob(v))
    return e.tobytes()


def _dec_onode(data: bytes) -> _Onode:
    d = Decoder(data)
    o = _Onode()
    o.size = d.u64()
    o.extents = d.list(lambda d: [d.u64(), d.u64(), d.u64(), d.u32(),
                                  d.u64()])
    o.attrs = d.map(lambda d: d.string(), lambda d: d.blob())
    o.omap = d.map(lambda d: d.string(), lambda d: d.blob())
    return o


def _enc_shared(refs: dict[int, int]) -> bytes:
    e = Encoder()
    e.map(refs, lambda e, k: e.u64(k), lambda e, v: e.u64(v))
    return e.tobytes()


def _dec_shared(data: bytes) -> dict[int, int]:
    return Decoder(data).map(lambda d: d.u64(), lambda d: d.u64())


class BlueStore(ObjectStore):
    """Extent-allocated durable ObjectStore (see module docstring)."""

    AU = 4096                     # min_alloc_size
    DEFERRED_MAX = 64 << 10       # small-overwrite deferred threshold

    def __init__(self, path: str, size: int = 64 << 20,
                 config: dict | None = None):
        self.path = path
        self.config = config if config is not None else {}
        os.makedirs(path, exist_ok=True)
        self.db = WALDB(os.path.join(path, "db"))
        self.block_path = os.path.join(path, "block")
        sb = self.db.get("S", "super")
        if sb is None:
            self.size = size - size % self.AU
            with open(self.block_path, "wb") as f:
                f.truncate(self.size)
            t = KVTransaction()
            e = Encoder()
            e.u64(self.size).u32(self.AU)
            t.set("S", "super", e.tobytes())
            self.db.submit_transaction(t)
        else:
            d = Decoder(sb)
            self.size = d.u64()
            if d.u32() != self.AU:
                raise StoreError("allocation unit mismatch")
        self._f = open(self.block_path, "r+b")
        self.alloc = BitmapAllocator(self.size // self.AU)
        self.colls: dict[str, set[str]] = {}
        self.onodes: dict[tuple[str, str], _Onode] = {}
        # shared-blob refcount table (ref: bluestore_shared_blob_t):
        # sb_id -> {au: refcount}; persisted under kv prefix "B"
        self.shared: dict[int, dict[int, int]] = {}
        self._next_sb = 1
        self._shared_dirty: set[int] = set()
        # round 20: shared-blob plane observability (register=False —
        # the OSD daemon ships the family through its mgr report
        # session; prometheus renders ceph_bluestore_sharedblob_*)
        self.perf = (
            PerfCountersBuilder("bluestore_sharedblob")
            .add_u64_counter("clones",
                             "O(metadata) shared-blob clones executed")
            .add_u64_counter("cow_released",
                             "shared-AU claims released (refcount "
                             "decrements from COW/punch/remove)")
            .add_u64_counter("aus_freed",
                             "shared AUs freed at refcount 0")
            .add_u64("records", "live shared-blob records (gauge)")
            .create_perf_counters(register=False))
        self._dseq = 0
        # au -> bytes queued for deferred write within the CURRENT
        # transaction (overlay for _read_extent; cleared at commit end)
        self._pending_au: dict[int, bytes] = {}
        # crash-injection hook for the qa tier (the messenger's
        # inject-socket-failures discipline, store-side): raise at the
        # named commit boundary so tests can exercise replay/rollback
        self._fail_point: str | None = None
        self._load()

    def _reset_from_kv(self) -> None:
        self.alloc = BitmapAllocator(self.size // self.AU)
        self.colls = {}
        self.onodes = {}
        self.shared = {}
        self._next_sb = 1
        self._shared_dirty = set()
        self._load()

    # -- mount/load --------------------------------------------------------
    def _load(self) -> None:
        for cid, _ in self.db.get_iterator("L"):
            self.colls[cid] = set()
        for key, rec in self.db.get_iterator("B"):
            self.shared[int(key)] = _dec_shared(rec)
        if self.shared:
            self._next_sb = max(self.shared) + 1
        # a shared AU appears in MULTIPLE onodes' extent maps: claim it
        # once (the allocator's strict double-allocation check still
        # guards unshared extents and shared-vs-unshared collisions)
        shared_claimed: set[int] = set()
        for key, rec in self.db.get_iterator("O"):
            cid, _, oid = key.partition("\x00")
            o = _dec_onode(rec)
            self.onodes[(cid, oid)] = o
            self.colls.setdefault(cid, set()).add(oid)
            for x in o.extents:
                if not x[4]:
                    self.alloc.mark_used([(x[1], x[2])])
                    continue
                for a in range(x[1], x[1] + x[2]):
                    if a not in shared_claimed:
                        self.alloc.mark_used([(a, 1)])
                        shared_claimed.add(a)
        # deferred replay (crash between kv commit and block write):
        # whole-AU rewrites are idempotent, so replay-then-delete is
        # safe regardless of whether the block write had landed
        replayed = KVTransaction()
        n = 0
        for key, rec in sorted(self.db.get_iterator("D")):
            d = Decoder(rec)
            au = d.u64()
            data = d.blob()
            self._f.seek(au * self.AU)
            self._f.write(data)
            replayed.rmkey("D", key)
            n += 1
            self._dseq = max(self._dseq, int(key) + 1)
        if n:
            self._f.flush()
            os.fsync(self._f.fileno())
            self.db.submit_transaction(replayed)

    # -- block I/O helpers -------------------------------------------------
    def _read_extent(self, x) -> bytes:
        loff, au, n_aus, crc, _sb = x
        self._f.seek(au * self.AU)
        raw = self._f.read(n_aus * self.AU)
        if self._pending_au:
            # deferred bytes queued in THIS transaction are not on the
            # block file yet but MUST be visible to later ops of the
            # same transaction (a second small overwrite, a clone, a
            # COW of the same range) — splice the overlay in
            buf = None
            for i in range(n_aus):
                chunk = self._pending_au.get(au + i)
                if chunk is not None:
                    if buf is None:
                        buf = bytearray(raw)
                    buf[i * self.AU:(i + 1) * self.AU] = chunk
            if buf is not None:
                return bytes(buf)
        return raw

    def _read_range(self, o: _Onode, start: int, end: int) -> bytes:
        """Logical bytes [start, end) — gaps as zeros, crc verified."""
        out = bytearray(end - start)
        for x in o.extents:
            loff, au, n_aus, crc, _sb = x
            xlen = n_aus * self.AU
            if loff >= end or loff + xlen <= start:
                continue
            raw = self._read_extent(x)
            if zlib.crc32(raw) != crc:
                raise ChecksumError(
                    f"extent crc mismatch at logical {loff}")
            s = max(start, loff)
            e = min(end, loff + xlen)
            out[s - start:e - start] = raw[s - loff:e - loff]
        return bytes(out)

    def _object_bytes(self, o: _Onode) -> bytes:
        return self._read_range(o, 0, o.size) if o.size else b""

    # -- transaction apply -------------------------------------------------
    def queue_transaction(self, t: Transaction) -> None:
        """All-or-nothing: COW block writes land and flush first, then
        ONE kv batch commits every metadata change + deferred record;
        only after the commit are replaced AUs freed and deferred
        bytes applied in place."""
        import time as _time
        self._validate(t.ops)
        kvt = KVTransaction()
        to_free: list[tuple[int, int]] = []
        deferred: list[tuple[int, bytes]] = []
        dirty: set[tuple[str, str]] = set()
        wrote_block = False
        self.last_txn_phases = {}            # a raised txn reports none
        _t0 = _time.monotonic()
        try:
            for op in t.ops:
                wb = self._apply_op(op, kvt, to_free, deferred, dirty)
                wrote_block = wrote_block or wb
            if self._fail_point == "before_kv_commit":  # crash inject
                raise StoreError("fail point: before_kv_commit")
        except Exception:
            # all-or-nothing: nothing committed to kv, so rebuild the
            # in-memory caches (onodes, collections, allocator) from
            # the committed state — a half-applied op list must not
            # leave RAM diverged from disk
            self._pending_au.clear()
            self._reset_from_kv()
            raise
        for key in dirty:
            o = self.onodes.get(key)
            okey = f"{key[0]}\x00{key[1]}"
            if o is None:
                kvt.rmkey("O", okey)
            else:
                kvt.set("O", okey, _enc_onode(o))
        for sb in self._shared_dirty:
            refs = self.shared.get(sb)
            if refs:
                kvt.set("B", f"{sb:016d}", _enc_shared(refs))
            else:
                # every AU hit refcount 0: the record dies with them
                self.shared.pop(sb, None)
                kvt.rmkey("B", f"{sb:016d}")
        if self._shared_dirty:
            self.perf.set("records", len(self.shared))
        self._shared_dirty = set()
        for au, data in deferred:
            e = Encoder()
            e.u64(au).blob(data)
            kvt.set("D", f"{self._dseq:016d}", e.tobytes())
            self._dseq += 1
        if wrote_block:
            self._f.flush()
            os.fsync(self._f.fileno())       # data durable BEFORE the
        _t1 = _time.monotonic()              # metadata points at it
        try:
            self.db.submit_transaction(kvt)
        except Exception:
            # commit failed: RAM reflects an uncommitted transaction —
            # rebuild from the kv or every later read serves phantoms
            self._pending_au.clear()
            self._reset_from_kv()
            raise
        if self._fail_point == "after_kv_commit":      # crash injection
            # the kv batch committed, so the store is durable — but the
            # deferred block writes and alloc.release below never ran.
            # Same discipline as the other failure paths: rebuild RAM
            # from the committed kv (which replays the D records) so a
            # REUSED instance isn't left with a stale overlay or an
            # allocator that still holds the replaced AUs.
            self._pending_au.clear()
            self._reset_from_kv()
            raise StoreError("fail point: after_kv_commit")
        _t2 = _time.monotonic()
        # phase walls for the tracing layer's objectstore sub-span
        # split (ref: BlueStore's kv_commit vs deferred/aio latency
        # counters): block COW+fsync, then the kv batch, then deferred
        # in-place writes (updated again below once they ran)
        self.last_txn_phases = {"block_write": _t1 - _t0,
                                "kv_commit": _t2 - _t1}
        try:
            self.alloc.release(to_free)
            if deferred:
                drop = KVTransaction()
                for i, (au, data) in enumerate(deferred):
                    self._f.seek(au * self.AU)
                    self._f.write(data)
                    drop.rmkey(
                        "D", f"{self._dseq - len(deferred) + i:016d}")
                self._f.flush()
                os.fsync(self._f.fileno())
                self.db.submit_transaction(drop)
                self.last_txn_phases["deferred_write"] = \
                    _time.monotonic() - _t2
        except Exception:
            # the kv committed, so the store is durable — but RAM and
            # the overlay must not keep stale state (a leaked pending
            # AU would splice old bytes into whatever reuses that AU);
            # reload replays the committed D records
            self._pending_au.clear()
            self._reset_from_kv()
            raise
        finally:
            self._pending_au.clear()

    def _validate(self, ops) -> None:
        """Precondition dry-run (the MemStore discipline): benign
        failures — missing objects or collections — must raise BEFORE
        any mutation, so the common error case never pays the
        full-store reload the mid-apply rollback path costs. A lazy
        DELTA overlay keeps this O(ops), not O(store): committed state
        is consulted read-only, only the transaction's own changes are
        tracked."""
        live: dict[str, bool] = {}        # coll existence overrides
        wiped: set[str] = set()           # colls emptied this txn
        obj: dict[tuple[str, str], bool] = {}   # object overrides

        def coll_ok(cid):
            return live.get(cid, cid in self.colls)

        def obj_ok(cid, oid):
            ov = obj.get((cid, oid))
            if ov is not None:
                return ov
            if cid in wiped:
                return False
            return oid in self.colls.get(cid, ())

        for op in ops:
            code = op[0]
            if code == OP_MKCOLL:
                live[op[1]] = True
                continue
            if code == OP_RMCOLL:
                live[op[1]] = False
                wiped.add(op[1])
                for k in [k for k, v in obj.items() if k[0] == op[1]]:
                    del obj[k]
                continue
            cid, oid = op[1], op[2]
            if not coll_ok(cid):
                raise StoreError(f"no collection {cid}")
            if code in (OP_TOUCH, OP_WRITE, OP_ZERO, OP_TRUNCATE,
                        OP_SETATTRS, OP_OMAP_SETKEYS):
                obj[(cid, oid)] = True
            elif code == OP_CLONE:
                if not obj_ok(cid, oid):
                    raise StoreError(f"no object {cid}/{oid}")
                obj[(cid, op[3])] = True
            elif code == OP_REMOVE:
                obj[(cid, oid)] = False
            elif not obj_ok(cid, oid):    # RMATTR / OMAP_RM* / CLEAR
                raise StoreError(f"no object {cid}/{oid}")

    def _onode(self, cid: str, oid: str, create: bool) -> _Onode:
        if cid not in self.colls:
            raise StoreError(f"no collection {cid}")
        o = self.onodes.get((cid, oid))
        if o is None:
            if not create:
                raise StoreError(f"no object {cid}/{oid}")
            o = _Onode()
            self.onodes[(cid, oid)] = o
            self.colls[cid].add(oid)
        return o

    # -- shared-blob refcounts ---------------------------------------------
    def _release_aus(self, au: int, n_aus: int, sb: int,
                     to_free: list) -> None:
        """Drop one extent's claim on [au, au+n_aus). Unshared AUs go
        straight to ``to_free`` (released after the kv commit); shared
        AUs only decrement their refcount and free at 0 — an AU still
        referenced by a sibling clone never reaches the allocator."""
        if not sb:
            if n_aus:
                to_free.append((au, n_aus))
            return
        refs = self.shared.setdefault(sb, {})
        self.perf.inc("cow_released", n_aus)
        for a in range(au, au + n_aus):
            r = refs.get(a, 1) - 1
            if r > 0:
                refs[a] = r
            else:
                refs.pop(a, None)
                to_free.append((a, 1))
                self.perf.inc("aus_freed")
        self._shared_dirty.add(sb)

    def _release_extent(self, x, to_free: list) -> None:
        self._release_aus(x[1], x[2], x[4], to_free)

    def _rewrite_range(self, o: _Onode, off: int, data: bytes,
                       to_free: list) -> None:
        """COW the AU-aligned range covering [off, off+len(data))."""
        a0 = off // self.AU * self.AU
        a1 = -(-(off + len(data)) // self.AU) * self.AU
        if off == a0 and off + len(data) == a1:
            # full-cover rewrite: no read of the old bytes — which
            # also means a corrupt extent CAN be repaired by
            # overwriting it whole (and no redundant crc work)
            buf = bytearray(data)
        else:
            buf = bytearray(self._read_range(o, a0, a1))
            buf[off - a0:off - a0 + len(data)] = data
        new = self.alloc.allocate((a1 - a0) // self.AU)
        pos = 0
        new_extents = []
        for au, n_aus in new:
            chunk = bytes(buf[pos:pos + n_aus * self.AU])
            self._f.seek(au * self.AU)
            self._f.write(chunk)
            new_extents.append([a0 + pos, au, n_aus, zlib.crc32(chunk),
                                0])
            pos += n_aus * self.AU
        self._replace_extents(o, a0, a1, new_extents, to_free)

    def _replace_extents(self, o: _Onode, a0: int, a1: int,
                         new_extents: list, to_free: list) -> None:
        """Swap the extent-map entries covering AU-aligned [a0, a1).
        A shared extent's punched AUs go through the refcount release
        (this is the COW seam: the new extents are fresh and unshared,
        the old shared AUs live on under their sibling references);
        split survivors keep their sb_id — per-AU refcounts make a
        partial punch naturally correct."""
        kept = []
        for x in o.extents:
            loff, au, n_aus, crc, sb = x
            xlen = n_aus * self.AU
            if loff >= a1 or loff + xlen <= a0:
                kept.append(x)
                continue
            partial = loff < a0 or loff + xlen > a1
            if partial:
                # a split re-stamps sub-extent crcs from the old
                # bytes: VERIFY them first or latent corruption would
                # be laundered into a fresh valid checksum (a fully
                # covered extent is dropped unread, which is also the
                # repair path for corrupt data)
                raw = self._read_extent(x)
                if zlib.crc32(raw) != crc:
                    raise ChecksumError(
                        f"extent crc mismatch at logical {loff} "
                        f"(partial overwrite of a corrupt extent)")
            # extents are AU-aligned and the range is AU-aligned, so
            # partial overlaps split at AU boundaries
            if loff < a0:
                pre = (a0 - loff) // self.AU
                kept.append([loff, au, pre,
                             zlib.crc32(raw[:pre * self.AU]), sb])
                raw = raw[pre * self.AU:]
                au += pre
                n_aus -= pre
                loff = a0
            if loff + n_aus * self.AU > a1:
                post = (loff + n_aus * self.AU - a1) // self.AU
                keep_from = n_aus - post
                kept.append([a1, au + keep_from, post,
                             zlib.crc32(raw[keep_from * self.AU:]), sb])
                n_aus = keep_from
            self._release_aus(au, n_aus, sb, to_free)
        kept.extend(new_extents)
        kept.sort(key=lambda x: x[0])
        o.extents = kept

    def _apply_op(self, op, kvt: KVTransaction, to_free, deferred,
                  dirty) -> bool:
        code = op[0]
        if code == OP_MKCOLL:
            self.colls.setdefault(op[1], set())
            kvt.set("L", op[1], b"1")
            return False
        if code == OP_RMCOLL:
            for oid in list(self.colls.get(op[1], ())):
                self._remove(op[1], oid, to_free, dirty)
            self.colls.pop(op[1], None)
            kvt.rmkey("L", op[1])
            return False
        cid, oid = op[1], op[2]
        wrote = False
        if code == OP_TOUCH:
            self._onode(cid, oid, create=True)
        elif code == OP_ZERO:
            off, ln = op[3], op[4]
            o = self._onode(cid, oid, create=True)
            o.size = max(o.size, off + ln)
            if ln:
                # punch the AU-aligned interior as a HOLE (drop the
                # covered extents — sparse gaps read as zeros), never
                # allocate for it: a zero of a huge range must FREE
                # space, not ENOSPC materializing zero bytes
                h0 = -(-off // self.AU) * self.AU
                h1 = (off + ln) // self.AU * self.AU
                edges = []
                if h1 > h0:
                    self._replace_extents(o, h0, h1, [], to_free)
                    edges = [(off, h0), (h1, off + ln)]
                else:
                    edges = [(off, off + ln)]
                for e0, e1 in edges:
                    if e0 < e1 and any(
                            x[0] < e1 and x[0] + x[2] * self.AU > e0
                            for x in o.extents):
                        # edges are sub-AU and inside an allocated
                        # extent, so _do_write defers them — NO
                        # allocation, keeping zero ENOSPC-free even
                        # on a full store
                        wrote |= self._do_write(
                            o, e0, b"\x00" * (e1 - e0), to_free,
                            deferred)
        elif code == OP_WRITE:
            off, data = op[3], op[4]
            o = self._onode(cid, oid, create=True)
            o.size = max(o.size, off + len(data))
            if data:
                wrote = self._do_write(o, off, data, to_free, deferred)
        elif code == OP_TRUNCATE:
            o = self._onode(cid, oid, create=True)
            new_size = op[3]
            if new_size < o.size:
                lim = -(-new_size // self.AU) * self.AU
                kept = []
                for x in o.extents:
                    loff, au, n_aus, crc, sb = x
                    if loff >= lim:
                        self._release_aus(au, n_aus, sb, to_free)
                    elif loff + n_aus * self.AU > lim:
                        keep = (lim - loff) // self.AU
                        raw = self._read_extent(x)
                        if zlib.crc32(raw) != crc:   # no crc laundering
                            raise ChecksumError(
                                f"extent crc mismatch at {loff}")
                        kept.append([loff, au, keep,
                                     zlib.crc32(raw[:keep * self.AU]),
                                     sb])
                        self._release_aus(au + keep, n_aus - keep, sb,
                                          to_free)
                    else:
                        kept.append(x)
                o.extents = kept
                if new_size % self.AU:
                    # zero the dropped tail INSIDE the last kept AU so
                    # a later size extension reads zeros
                    self._rewrite_range(
                        o, new_size,
                        b"\x00" * (lim - new_size), to_free)
                    wrote = True
            o.size = new_size
        elif code == OP_REMOVE:
            self._remove(cid, oid, to_free, dirty)
            dirty.add((cid, oid))
            return False
        elif code == OP_SETATTRS:
            self._onode(cid, oid, create=True).attrs.update(op[3])
        elif code == OP_RMATTR:
            self._onode(cid, oid, create=False).attrs.pop(op[3], None)
        elif code == OP_CLONE:
            src = self._onode(cid, oid, create=False)
            dst = self._onode(cid, op[3], create=True)
            for x in dst.extents:
                self._release_extent(x, to_free)
            dst.extents = []
            dst.size = 0
            dst.attrs = dict(src.attrs)
            dst.omap = dict(src.omap)
            if self.config.get("bluestore_sharedblob_enabled", True):
                # O(metadata) clone: stamp each source extent with a
                # shared-blob id (first share promotes it, refs=1 per
                # AU for the source's own claim), copy the extent
                # ENTRY to the clone and bump the refs — zero data
                # bytes move. A later overwrite of either side COWs
                # fresh space and decrements (see _replace_extents).
                for x in src.extents:
                    loff, au, n_aus, crc, sb = x
                    if not sb:
                        sb = self._next_sb
                        self._next_sb += 1
                        x[4] = sb
                        self.shared[sb] = {
                            a: 1 for a in range(au, au + n_aus)}
                    refs = self.shared.setdefault(sb, {})
                    for a in range(au, au + n_aus):
                        refs[a] = refs.get(a, 0) + 1
                    self._shared_dirty.add(sb)
                    dst.extents.append([loff, au, n_aus, crc, sb])
                dirty.add((cid, oid))   # src extents got sb stamps
                self.perf.inc("clones")
            else:
                payload = self._object_bytes(src)
                if payload:
                    self._rewrite_range(dst, 0, payload, to_free)
                    wrote = True
            dst.size = src.size
            dirty.add((cid, op[3]))
        elif code == OP_OMAP_SETKEYS:
            self._onode(cid, oid, create=True).omap.update(op[3])
        elif code == OP_OMAP_RMKEYS:
            o = self._onode(cid, oid, create=False)
            for k in op[3]:
                o.omap.pop(k, None)
        elif code == OP_OMAP_CLEAR:
            self._onode(cid, oid, create=False).omap.clear()
        else:
            raise StoreError(f"unknown op {code}")
        dirty.add((cid, oid))
        return wrote

    def _covering_extent(self, o: _Onode, au0: int, au1: int):
        """The single extent covering logical AUs [au0, au1], or None."""
        for x in o.extents:
            loff, au, n_aus = x[0], x[1], x[2]
            first = loff // self.AU
            if first <= au0 and au1 < first + n_aus:
                return x
        return None

    def _do_write(self, o: _Onode, off: int, data: bytes,
                  to_free, deferred) -> bool:
        """Apply one write payload: deferred when it fits inside one
        already-allocated extent (no allocation, bytes ride the kv
        batch), COW otherwise. Returns True when the block file was
        written (caller fsyncs before the commit)."""
        au0 = off // self.AU
        au1 = (off + len(data) - 1) // self.AU
        covered = self._covering_extent(o, au0, au1)
        if covered is None or len(data) > self.DEFERRED_MAX or \
                covered[4]:
            # a SHARED extent can never be patched in place — its
            # bytes are visible through sibling clones' extent maps;
            # the rewrite COWs fresh space and decrements the refs
            self._rewrite_range(o, off, data, to_free)
            return True
        loff, au, n_aus, crc, _sb = covered
        a0 = au0 * self.AU
        a1 = (au1 + 1) * self.AU
        xlen = n_aus * self.AU
        if off == loff and len(data) == xlen:
            # whole-extent overwrite: no read of the old bytes (the
            # corrupt-extent repair path) and the crc is just the data
            raw = bytearray(data)
            covered[3] = zlib.crc32(data)
        else:
            # ONE read+verify of the covering extent serves both the
            # deferred buffer build and the crc re-stamp (reading via
            # _read_range and again in a patch helper doubled the I/O
            # and crc work on the hottest path). Partial overwrites of
            # a corrupt extent refuse: re-stamping would launder the
            # rot into a valid checksum.
            raw = bytearray(self._read_extent(covered))
            if zlib.crc32(bytes(raw)) != crc:
                raise ChecksumError(
                    f"extent crc mismatch at logical {loff} (partial "
                    f"overwrite of a corrupt extent)")
            raw[off - loff:off - loff + len(data)] = data
            covered[3] = zlib.crc32(bytes(raw))
        sub = au + (a0 - loff) // self.AU
        buf = bytes(raw[a0 - loff:a1 - loff])
        deferred.append((sub, buf))
        for i in range((a1 - a0) // self.AU):
            self._pending_au[sub + i] = buf[i * self.AU:
                                            (i + 1) * self.AU]
        return False

    def _remove(self, cid: str, oid: str, to_free, dirty) -> None:
        o = self.onodes.pop((cid, oid), None)
        if o is not None:
            for x in o.extents:
                self._release_extent(x, to_free)
        self.colls.get(cid, set()).discard(oid)
        dirty.add((cid, oid))

    # -- reads -------------------------------------------------------------
    def read(self, cid, oid, offset=0, length=None):
        o = self._onode(cid, oid, create=False)
        end = o.size if length is None else min(offset + length, o.size)
        if offset >= end:
            return b""
        return self._read_range(o, offset, end)

    def stat(self, cid, oid):
        return self._onode(cid, oid, create=False).size

    def exists(self, cid, oid):
        return (cid, oid) in self.onodes

    def getattrs(self, cid, oid):
        return dict(self._onode(cid, oid, create=False).attrs)

    def omap_get(self, cid, oid):
        return dict(self._onode(cid, oid, create=False).omap)

    def list_objects(self, cid):
        return sorted(self.colls.get(cid, ()))

    def list_collections(self):
        return sorted(self.colls)

    def collection_exists(self, cid):
        return cid in self.colls

    # -- admin -------------------------------------------------------------
    def statfs(self) -> dict:
        free = self.alloc.free_aus * self.AU
        return {"total": self.size, "free": free,
                "allocated": self.size - free, "au": self.AU,
                "shared_blobs": len(self.shared),
                "shared_aus": sum(len(r) for r in self.shared.values())}

    def fsck(self) -> list[str]:
        """BlueStore::_fsck's core: extent bounds, cross-object
        overlap, per-extent crc vs the block file, allocator/extent
        bitmap consistency (leaks + double-use) — plus the shared-blob
        cross-check: an AU referenced by more than one extent is legal
        ONLY under one matching sb_id, and every stored refcount must
        equal the actual number of extent-map references (a stored
        count too high is a space leak; too low is a future
        double-free)."""
        import numpy as np
        errors = []
        seen = np.zeros(self.size // self.AU, dtype=bool)
        au_sb: dict[int, int] = {}     # au -> sb_id of first reference
        census: dict[int, dict[int, int]] = {}   # sb -> {au: refs seen}
        for (cid, oid), o in self.onodes.items():
            for x in o.extents:
                loff, au, n_aus, crc, sb = x
                if au < 0 or (au + n_aus) * self.AU > self.size:
                    errors.append(f"{cid}/{oid}: extent out of bounds")
                    continue
                for a in range(au, au + n_aus):
                    if seen[a]:
                        if not sb or au_sb.get(a) != sb:
                            errors.append(f"{cid}/{oid}: extent "
                                          f"overlap at au {a}")
                    else:
                        seen[a] = True
                        au_sb[a] = sb
                    if sb:
                        blob = census.setdefault(sb, {})
                        blob[a] = blob.get(a, 0) + 1
                if zlib.crc32(self._read_extent(x)) != crc:
                    errors.append(
                        f"{cid}/{oid}: crc mismatch at logical {loff}")
        for sb in sorted(set(census) | set(self.shared)):
            want = census.get(sb, {})
            have = self.shared.get(sb, {})
            for a in sorted(set(want) | set(have)):
                if want.get(a, 0) != have.get(a, 0):
                    errors.append(
                        f"shared blob {sb} au {a}: stored refcount "
                        f"{have.get(a, 0)} != {want.get(a, 0)} "
                        f"extent-map references")
        leaked = int((self.alloc.used & ~seen).sum())
        if leaked:
            errors.append(f"allocator leak: {leaked} AUs marked used "
                          f"but referenced by no object")
        missing = int((seen & ~self.alloc.used).sum())
        if missing:
            errors.append(f"allocator corruption: {missing} referenced "
                          f"AUs marked free")
        return errors

    def mount(self) -> None:
        pass

    def umount(self) -> None:
        self._f.flush()
        os.fsync(self._f.fileno())
        self._f.close()
        self.db.close()
