"""KeyValueDB: the kv abstraction under the object store and mon store.

ref: src/kv/KeyValueDB.h (RocksDBStore / MemDB behind one interface) —
prefixed keyspaces, atomic write batches, ordered iteration. Two
implementations: ``MemDB`` (RAM, tests) and ``WALDB`` (append-only
write-ahead log + in-memory table + snapshot compaction: the same
crash-consistency contract BlueStore gets from RocksDB's WAL, sized for
this framework's metadata volumes).
"""

from __future__ import annotations

import os
import struct
import zlib
from typing import Iterator

from ceph_tpu.encoding.denc import Decoder, Encoder


class KVTransaction:
    """Atomic batch (ref: KeyValueDB::Transaction)."""

    def __init__(self) -> None:
        self.ops: list[tuple[str, str, str, bytes | None]] = []

    def set(self, prefix: str, key: str, value: bytes) -> "KVTransaction":
        self.ops.append(("set", prefix, key, bytes(value)))
        return self

    def rmkey(self, prefix: str, key: str) -> "KVTransaction":
        self.ops.append(("rm", prefix, key, None))
        return self

    def rmkeys_by_prefix(self, prefix: str) -> "KVTransaction":
        self.ops.append(("rmprefix", prefix, "", None))
        return self

    def encode(self) -> bytes:
        e = Encoder()
        e.u32(len(self.ops))
        for op, prefix, key, value in self.ops:
            e.string(op).string(prefix).string(key)
            e.optional(value, lambda e, v: e.blob(v))
        return e.tobytes()

    @classmethod
    def decode(cls, data: bytes) -> "KVTransaction":
        d = Decoder(data)
        t = cls()
        for _ in range(d.u32()):
            op, prefix, key = d.string(), d.string(), d.string()
            value = d.optional(lambda d: d.blob())
            t.ops.append((op, prefix, key, value))
        return t


class KeyValueDB:
    """Interface (ref: src/kv/KeyValueDB.h)."""

    def get_transaction(self) -> KVTransaction:
        return KVTransaction()

    def submit_transaction(self, t: KVTransaction) -> None:
        raise NotImplementedError

    def submit_transaction_sync(self, t: KVTransaction) -> None:
        self.submit_transaction(t)

    def get(self, prefix: str, key: str) -> bytes | None:
        raise NotImplementedError

    def get_iterator(self, prefix: str) -> Iterator[tuple[str, bytes]]:
        """Ordered (key, value) pairs under one prefix."""
        raise NotImplementedError

    def close(self) -> None:
        pass


class MemDB(KeyValueDB):
    """ref: src/kv/MemDB — RAM store for tests and MemStore."""

    def __init__(self) -> None:
        self._data: dict[str, dict[str, bytes]] = {}

    def _apply(self, t: KVTransaction) -> None:
        for op, prefix, key, value in t.ops:
            space = self._data.setdefault(prefix, {})
            if op == "set":
                space[key] = value
            elif op == "rm":
                space.pop(key, None)
            elif op == "rmprefix":
                self._data.pop(prefix, None)

    def submit_transaction(self, t: KVTransaction) -> None:
        self._apply(t)

    def get(self, prefix: str, key: str) -> bytes | None:
        return self._data.get(prefix, {}).get(key)

    def get_iterator(self, prefix: str):
        space = self._data.get(prefix, {})
        for k in sorted(space):
            yield k, space[k]


# WAL record framing: u32 len | payload | u32 crc32(payload)
_HDR = struct.Struct("<I")


class WALDB(MemDB):
    """Durable MemDB: every batch is appended to a crc-framed WAL before
    being applied; open() replays the snapshot + WAL, discarding a torn
    tail (the crash-consistency contract of a RocksDB WAL, ref:
    src/kv/RocksDBStore.cc submit_transaction_sync + BlueFS replay).
    """

    SNAPSHOT = "snapshot.kv"
    WAL = "wal.kv"

    def __init__(self, path: str, compact_threshold: int = 64 << 20):
        super().__init__()
        self.path = path
        self.compact_threshold = compact_threshold
        os.makedirs(path, exist_ok=True)
        self._replayed_bytes = 0
        self._load()
        self._wal = open(os.path.join(path, self.WAL), "ab")

    # -- framing -----------------------------------------------------------
    @staticmethod
    def _read_records(path: str) -> tuple[list[bytes], int]:
        """Returns (payloads, clean_bytes); stops at the first torn or
        corrupt record (everything after a crash is discarded)."""
        out: list[bytes] = []
        clean = 0
        if not os.path.exists(path):
            return out, 0
        with open(path, "rb") as f:
            data = f.read()
        off = 0
        while off + 4 <= len(data):
            (ln,) = _HDR.unpack_from(data, off)
            if off + 4 + ln + 4 > len(data):
                break           # torn tail
            payload = data[off + 4:off + 4 + ln]
            (crc,) = _HDR.unpack_from(data, off + 4 + ln)
            if zlib.crc32(payload) != crc:
                break           # corrupt: stop replay here
            out.append(payload)
            off += 8 + ln
            clean = off
        return out, clean

    def _load(self) -> None:
        snap, _ = self._read_records(os.path.join(self.path, self.SNAPSHOT))
        for payload in snap:
            self._apply(KVTransaction.decode(payload))
        wal, clean = self._read_records(os.path.join(self.path, self.WAL))
        for payload in wal:
            self._apply(KVTransaction.decode(payload))
        self._replayed_bytes = clean
        # truncate any torn tail so new appends start at a clean record
        walpath = os.path.join(self.path, self.WAL)
        if os.path.exists(walpath) and \
                os.path.getsize(walpath) > clean:
            with open(walpath, "r+b") as f:
                f.truncate(clean)

    def _append(self, payload: bytes) -> None:
        self._wal.write(_HDR.pack(len(payload)) + payload +
                        _HDR.pack(zlib.crc32(payload)))
        self._wal.flush()
        os.fsync(self._wal.fileno())

    # -- api ---------------------------------------------------------------
    def submit_transaction(self, t: KVTransaction) -> None:
        self._append(t.encode())
        self._apply(t)
        if self._wal.tell() > self.compact_threshold:
            self.compact()

    def compact(self) -> None:
        """Write the whole table as one snapshot batch; reset the WAL
        (ref: RocksDB memtable flush / BlueStore DB compaction)."""
        t = KVTransaction()
        for prefix, space in self._data.items():
            for k, v in space.items():
                t.set(prefix, k, v)
        tmp = os.path.join(self.path, self.SNAPSHOT + ".tmp")
        payload = t.encode()
        with open(tmp, "wb") as f:
            f.write(_HDR.pack(len(payload)) + payload +
                    _HDR.pack(zlib.crc32(payload)))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self.path, self.SNAPSHOT))
        # The rename must be durable BEFORE the WAL is truncated: on
        # power loss an un-fsynced rename can be lost while the
        # truncation survives, dropping every transaction since the
        # previous snapshot. fsync the directory entry first.
        dfd = os.open(self.path, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
        self._wal.close()
        self._wal = open(os.path.join(self.path, self.WAL), "wb")

    def close(self) -> None:
        self._wal.close()
