"""ObjectStore: transactional local object storage.

ref: src/os/ObjectStore.h — collections (one per PG) hold objects with
byte data, xattrs and an omap; all mutations travel in an atomic
``Transaction`` (op list), exactly the unit ReplicatedBackend ships to
replicas and BlueStore commits through its WAL. Reads are synchronous
(ref: ObjectStore::read/stat/omap_get_values).

Implementations: MemStore (RAM, ref src/os/memstore) and WALStore
(kv-backed with checksummed data + crash-consistent WAL + fsck,
the BlueStore seat in this framework).
"""

from __future__ import annotations

import zlib

from ceph_tpu.encoding.denc import Decoder, Encoder
from ceph_tpu.os_.kv import WALDB, KVTransaction

# op codes (ref: ObjectStore::Transaction::Op enum)
OP_TOUCH = 1
OP_WRITE = 2
OP_ZERO = 3
OP_TRUNCATE = 4
OP_REMOVE = 5
OP_SETATTRS = 6
OP_RMATTR = 7
OP_CLONE = 8
OP_MKCOLL = 9
OP_RMCOLL = 10
OP_OMAP_SETKEYS = 11
OP_OMAP_RMKEYS = 12
OP_OMAP_CLEAR = 13


class StoreError(Exception):
    pass


class ChecksumError(StoreError):
    pass


class Transaction:
    """ref: ObjectStore::Transaction — ordered op list, all-or-nothing."""

    def __init__(self) -> None:
        self.ops: list[tuple] = []

    # -- builders ---------------------------------------------------------
    def create_collection(self, cid: str) -> "Transaction":
        self.ops.append((OP_MKCOLL, cid))
        return self

    def remove_collection(self, cid: str) -> "Transaction":
        self.ops.append((OP_RMCOLL, cid))
        return self

    def touch(self, cid: str, oid: str) -> "Transaction":
        self.ops.append((OP_TOUCH, cid, oid))
        return self

    def write(self, cid: str, oid: str, offset: int,
              data: bytes) -> "Transaction":
        self.ops.append((OP_WRITE, cid, oid, offset, bytes(data)))
        return self

    def zero(self, cid: str, oid: str, offset: int,
             length: int) -> "Transaction":
        self.ops.append((OP_ZERO, cid, oid, offset, length))
        return self

    def truncate(self, cid: str, oid: str, size: int) -> "Transaction":
        self.ops.append((OP_TRUNCATE, cid, oid, size))
        return self

    def remove(self, cid: str, oid: str) -> "Transaction":
        self.ops.append((OP_REMOVE, cid, oid))
        return self

    def setattrs(self, cid: str, oid: str,
                 attrs: dict[str, bytes]) -> "Transaction":
        self.ops.append((OP_SETATTRS, cid, oid, dict(attrs)))
        return self

    def rmattr(self, cid: str, oid: str, name: str) -> "Transaction":
        self.ops.append((OP_RMATTR, cid, oid, name))
        return self

    def clone(self, cid: str, oid: str, noid: str) -> "Transaction":
        self.ops.append((OP_CLONE, cid, oid, noid))
        return self

    def omap_setkeys(self, cid: str, oid: str,
                     kv: dict[str, bytes]) -> "Transaction":
        self.ops.append((OP_OMAP_SETKEYS, cid, oid, dict(kv)))
        return self

    def omap_rmkeys(self, cid: str, oid: str, keys: list[str]
                    ) -> "Transaction":
        self.ops.append((OP_OMAP_RMKEYS, cid, oid, list(keys)))
        return self

    def omap_clear(self, cid: str, oid: str) -> "Transaction":
        self.ops.append((OP_OMAP_CLEAR, cid, oid))
        return self

    def empty(self) -> bool:
        return not self.ops

    def append(self, other: "Transaction") -> "Transaction":
        self.ops.extend(other.ops)
        return self

    # -- wire form (shipped in rep ops; ref: Transaction::encode) ---------
    def encode(self) -> bytes:
        e = Encoder()
        e.u32(len(self.ops))
        for op in self.ops:
            code = op[0]
            e.u8(code).string(op[1])                   # cid
            if code in (OP_MKCOLL, OP_RMCOLL):
                continue
            e.string(op[2])                            # oid
            if code == OP_WRITE:
                e.u64(op[3]).blob(op[4])
            elif code == OP_ZERO:
                e.u64(op[3]).u64(op[4])
            elif code == OP_TRUNCATE:
                e.u64(op[3])
            elif code in (OP_SETATTRS, OP_OMAP_SETKEYS):
                e.map(op[3], lambda e, k: e.string(k),
                      lambda e, v: e.blob(v))
            elif code == OP_RMATTR:
                e.string(op[3])
            elif code == OP_CLONE:
                e.string(op[3])
            elif code == OP_OMAP_RMKEYS:
                e.list(op[3], lambda e, k: e.string(k))
        return e.tobytes()

    @classmethod
    def decode(cls, data: bytes) -> "Transaction":
        d = Decoder(data)
        t = cls()
        for _ in range(d.u32()):
            code = d.u8()
            cid = d.string()
            if code in (OP_MKCOLL, OP_RMCOLL):
                t.ops.append((code, cid))
                continue
            oid = d.string()
            if code == OP_WRITE:
                t.ops.append((code, cid, oid, d.u64(), d.blob()))
            elif code == OP_ZERO:
                t.ops.append((code, cid, oid, d.u64(), d.u64()))
            elif code == OP_TRUNCATE:
                t.ops.append((code, cid, oid, d.u64()))
            elif code in (OP_SETATTRS, OP_OMAP_SETKEYS):
                t.ops.append((code, cid, oid, d.map(
                    lambda d: d.string(), lambda d: d.blob())))
            elif code in (OP_RMATTR, OP_CLONE):
                t.ops.append((code, cid, oid, d.string()))
            elif code == OP_OMAP_RMKEYS:
                t.ops.append((code, cid, oid,
                              d.list(lambda d: d.string())))
            else:
                t.ops.append((code, cid, oid))
        return t


class ObjectStore:
    """The interface (ref: src/os/ObjectStore.h)."""

    def queue_transaction(self, t: Transaction) -> None:
        raise NotImplementedError

    # reads
    def read(self, cid: str, oid: str, offset: int = 0,
             length: int | None = None) -> bytes:
        raise NotImplementedError

    def stat(self, cid: str, oid: str) -> int:
        """Returns size; raises StoreError if missing."""
        raise NotImplementedError

    def exists(self, cid: str, oid: str) -> bool:
        try:
            self.stat(cid, oid)
            return True
        except StoreError:
            return False

    def getattrs(self, cid: str, oid: str) -> dict[str, bytes]:
        raise NotImplementedError

    def omap_get(self, cid: str, oid: str) -> dict[str, bytes]:
        raise NotImplementedError

    def list_objects(self, cid: str) -> list[str]:
        raise NotImplementedError

    def list_collections(self) -> list[str]:
        raise NotImplementedError

    def collection_exists(self, cid: str) -> bool:
        return cid in self.list_collections()

    def mount(self) -> None:
        pass

    def umount(self) -> None:
        pass


class _Obj:
    __slots__ = ("data", "attrs", "omap")

    def __init__(self) -> None:
        self.data = bytearray()
        self.attrs: dict[str, bytes] = {}
        self.omap: dict[str, bytes] = {}


class MemStore(ObjectStore):
    """RAM ObjectStore (ref: src/os/memstore/MemStore.{h,cc}) — the
    cluster-free test seam, and the state model WALStore persists."""

    def __init__(self) -> None:
        self.colls: dict[str, dict[str, _Obj]] = {}

    # -- transaction apply -------------------------------------------------
    def _coll(self, cid: str) -> dict[str, _Obj]:
        try:
            return self.colls[cid]
        except KeyError:
            raise StoreError(f"no collection {cid}") from None

    def _obj(self, cid: str, oid: str, create: bool = False) -> _Obj:
        coll = self._coll(cid)
        o = coll.get(oid)
        if o is None:
            if not create:
                raise StoreError(f"no object {cid}/{oid}")
            o = coll[oid] = _Obj()
        return o

    def queue_transaction(self, t: Transaction) -> None:
        # All-or-nothing: validate every op against simulated existence
        # state BEFORE mutating, so a bad op cannot leave memory
        # half-applied while the caller treats the txn as failed
        # (ref: ObjectStore::Transaction atomicity contract).
        self._validate(t.ops)
        for op in t.ops:
            self._apply_op(op)

    # ops whose object lookup auto-creates (mirrors _apply_op)
    _CREATES = frozenset((OP_TOUCH, OP_WRITE, OP_ZERO, OP_TRUNCATE,
                          OP_SETATTRS, OP_OMAP_SETKEYS))
    # ops that raise when the object is missing
    _NEEDS_OBJ = frozenset((OP_RMATTR, OP_OMAP_RMKEYS, OP_OMAP_CLEAR))

    def _validate(self, ops) -> None:
        """Dry-run existence simulation of _apply_op: raises the same
        StoreErrors it would, without touching live state."""
        colls: dict[str, bool] = {}
        objs: dict[tuple[str, str], bool] = {}
        # cids whose contents were dropped by a simulated RMCOLL: object
        # existence under them is decided by the simulation alone, never
        # by live state (an RMCOLL+MKCOLL pair leaves the coll EMPTY).
        reset: set[str] = set()

        def cexists(cid: str) -> bool:
            if cid not in colls:
                colls[cid] = cid in self.colls
            return colls[cid]

        def oexists(cid: str, oid: str) -> bool:
            key = (cid, oid)
            if key not in objs:
                if cid in reset:
                    objs[key] = False
                else:
                    coll = self.colls.get(cid)
                    objs[key] = coll is not None and oid in coll
            return objs[key]

        for op in ops:
            code = op[0]
            if code == OP_MKCOLL:
                colls[op[1]] = True
                continue
            if code == OP_RMCOLL:
                colls[op[1]] = False
                reset.add(op[1])
                for key in [k for k in objs if k[0] == op[1]]:
                    del objs[key]
                continue
            cid, oid = op[1], op[2]
            if not cexists(cid):
                raise StoreError(f"no collection {cid}")
            if code in self._CREATES:
                objs[(cid, oid)] = True
            elif code == OP_CLONE:
                if not oexists(cid, oid):
                    raise StoreError(f"no object {cid}/{oid}")
                objs[(cid, op[3])] = True
            elif code in self._NEEDS_OBJ:
                if not oexists(cid, oid):
                    raise StoreError(f"no object {cid}/{oid}")
            elif code == OP_REMOVE:
                objs[(cid, oid)] = False
            else:
                raise StoreError(f"unknown op {code}")

    def _apply_op(self, op: tuple) -> None:
        code = op[0]
        if code == OP_MKCOLL:
            self.colls.setdefault(op[1], {})
            return
        if code == OP_RMCOLL:
            self.colls.pop(op[1], None)
            return
        cid, oid = op[1], op[2]
        if code == OP_TOUCH:
            self._obj(cid, oid, create=True)
        elif code == OP_WRITE:
            o = self._obj(cid, oid, create=True)
            off, data = op[3], op[4]
            if len(o.data) < off + len(data):
                o.data.extend(b"\x00" * (off + len(data) - len(o.data)))
            o.data[off:off + len(data)] = data
        elif code == OP_ZERO:
            o = self._obj(cid, oid, create=True)
            off, ln = op[3], op[4]
            if len(o.data) < off + ln:
                o.data.extend(b"\x00" * (off + ln - len(o.data)))
            o.data[off:off + ln] = b"\x00" * ln
        elif code == OP_TRUNCATE:
            o = self._obj(cid, oid, create=True)
            size = op[3]
            if size < len(o.data):
                del o.data[size:]
            else:
                o.data.extend(b"\x00" * (size - len(o.data)))
        elif code == OP_REMOVE:
            self._coll(cid).pop(oid, None)
        elif code == OP_SETATTRS:
            self._obj(cid, oid, create=True).attrs.update(op[3])
        elif code == OP_RMATTR:
            self._obj(cid, oid).attrs.pop(op[3], None)
        elif code == OP_CLONE:
            src = self._obj(cid, oid)
            dst = self._obj(cid, op[3], create=True)
            dst.data = bytearray(src.data)
            dst.attrs = dict(src.attrs)
            dst.omap = dict(src.omap)
        elif code == OP_OMAP_SETKEYS:
            self._obj(cid, oid, create=True).omap.update(op[3])
        elif code == OP_OMAP_RMKEYS:
            o = self._obj(cid, oid)
            for k in op[3]:
                o.omap.pop(k, None)
        elif code == OP_OMAP_CLEAR:
            self._obj(cid, oid).omap.clear()
        else:
            raise StoreError(f"unknown op {code}")

    # -- reads -------------------------------------------------------------
    def read(self, cid, oid, offset=0, length=None):
        o = self._obj(cid, oid)
        end = len(o.data) if length is None else offset + length
        return bytes(o.data[offset:end])

    def stat(self, cid, oid):
        return len(self._obj(cid, oid).data)

    def getattrs(self, cid, oid):
        return dict(self._obj(cid, oid).attrs)

    def omap_get(self, cid, oid):
        return dict(self._obj(cid, oid).omap)

    def list_objects(self, cid):
        return sorted(self._coll(cid))

    def list_collections(self):
        return sorted(self.colls)


class WALStore(MemStore):
    """Durable ObjectStore: MemStore semantics + WALDB persistence with
    per-object data checksums and fsck.

    ref: src/os/bluestore/BlueStore.{h,cc} — same contract, small
    machine: each ObjectStore transaction becomes ONE atomic kv batch
    (WALDB's crc-framed WAL gives commit atomicity and torn-tail
    discard, the role RocksDB's WAL plays under BlueStore), each object
    record carries a crc32 over its data verified on read (BlueStore
    csum_type=crc32c), and ``fsck`` revalidates every record
    (ref: BlueStore::_fsck).

    kv layout: prefix "L" = collections, prefix "O" = one record per
    object (data + attrs + omap + crc), key ``cid\\0oid``.
    """

    def __init__(self, path: str, compact_threshold: int = 64 << 20):
        super().__init__()
        self.db = WALDB(path, compact_threshold=compact_threshold)
        # (cid, oid) whose kv record checksum has been verified since
        # its last write — lets ranged reads verify once per version.
        self._verified: set[tuple[str, str]] = set()
        self._load()

    @staticmethod
    def _okey(cid: str, oid: str) -> str:
        return f"{cid}\x00{oid}"

    @staticmethod
    def _encode_obj(o: _Obj) -> bytes:
        e = Encoder()
        e.blob(bytes(o.data))
        e.map(o.attrs, lambda e, k: e.string(k), lambda e, v: e.blob(v))
        e.map(o.omap, lambda e, k: e.string(k), lambda e, v: e.blob(v))
        e.u32(zlib.crc32(bytes(o.data)))
        return e.tobytes()

    @staticmethod
    def _decode_obj(data: bytes) -> tuple[_Obj, bool]:
        d = Decoder(data)
        o = _Obj()
        o.data = bytearray(d.blob())
        o.attrs = d.map(lambda d: d.string(), lambda d: d.blob())
        o.omap = d.map(lambda d: d.string(), lambda d: d.blob())
        ok = d.u32() == zlib.crc32(bytes(o.data))
        return o, ok

    def _load(self) -> None:
        for cid, _ in self.db.get_iterator("L"):
            self.colls[cid] = {}
        for key, rec in self.db.get_iterator("O"):
            cid, _, oid = key.partition("\x00")
            o, _ok = self._decode_obj(rec)   # fsck reports bad crc
            self.colls.setdefault(cid, {})[oid] = o

    def queue_transaction(self, t: Transaction) -> None:
        import time as _time
        # capture pre-state needed for RMCOLL persistence
        removed_coll_objs: dict[str, list[str]] = {}
        for op in t.ops:
            if op[0] == OP_RMCOLL and op[1] in self.colls:
                removed_coll_objs[op[1]] = list(self.colls[op[1]])
        self.last_txn_phases = {}           # a raised txn reports none
        _t0 = _time.monotonic()
        super().queue_transaction(t)        # apply to memory (may raise)
        _t1 = _time.monotonic()
        kt = self.db.get_transaction()
        touched: set[tuple[str, str]] = set()
        for op in t.ops:
            code = op[0]
            if code == OP_MKCOLL:
                kt.set("L", op[1], b"")
            elif code == OP_RMCOLL:
                kt.rmkey("L", op[1])
                for oid in removed_coll_objs.get(op[1], []):
                    kt.rmkey("O", self._okey(op[1], oid))
            else:
                touched.add((op[1], op[2]))
                if code == OP_CLONE:
                    touched.add((op[1], op[3]))
        for cid, oid in sorted(touched):
            coll = self.colls.get(cid)
            o = coll.get(oid) if coll is not None else None
            self._verified.discard((cid, oid))
            if o is None:
                kt.rmkey("O", self._okey(cid, oid))
            else:
                kt.set("O", self._okey(cid, oid), self._encode_obj(o))
        for cid in removed_coll_objs:
            self._verified = {k for k in self._verified if k[0] != cid}
        self.db.submit_transaction(kt)
        # per-phase wall of the LAST transaction, for the tracing
        # layer's objectstore sub-span split (ref: BlueStore's
        # state_kv_queued/state_kv_committing latency counters):
        # "apply" = in-memory state, "wal_kv_commit" = the WAL-backed
        # kv batch (the durability point)
        self.last_txn_phases = {
            "apply": _t1 - _t0,
            "wal_kv_commit": _time.monotonic() - _t1}

    def read(self, cid, oid, offset=0, length=None):
        data = super().read(cid, oid, offset, length)
        # Verify the stored record checksum on EVERY read path, ranged
        # included — but only once per object version: re-decoding the
        # whole record per 4 KiB ranged read would be O(object) each
        # time. The verified set is invalidated on every write to the
        # object (queue_transaction) and repopulated lazily here.
        key = (cid, oid)
        if key not in self._verified:
            rec = self.db.get("O", self._okey(cid, oid))
            if rec is not None:
                _, ok = self._decode_obj(rec)
                if not ok:
                    raise ChecksumError(f"{cid}/{oid} checksum mismatch")
            self._verified.add(key)
        return data

    def fsck(self) -> list[str]:
        """Validate every persisted record (ref: BlueStore::_fsck).
        Returns error strings (empty = clean)."""
        errors = []
        for cid, coll in self.colls.items():
            if self.db.get("L", cid) is None:
                errors.append(f"{cid}: collection missing from kv")
            for oid in coll:
                rec = self.db.get("O", self._okey(cid, oid))
                if rec is None:
                    errors.append(f"{cid}/{oid}: missing record")
                    continue
                _, ok = self._decode_obj(rec)
                if not ok:
                    errors.append(f"{cid}/{oid}: checksum mismatch")
        return errors

    def umount(self) -> None:
        self.db.close()
