"""Native interop: the C++ runtime pieces behind ctypes.

``native.py`` loads libec_ref.so (C ABI RS backend) as plugin ``ref`` —
the measured CPU baseline and an independent correctness oracle for the
JAX backend — and exposes the dlopen plugin-registry flow
(ref: src/erasure-code/ErasureCodePlugin.cc) for tests.
"""

from ceph_tpu.interop.native import (  # noqa: F401
    ErasureCodeRef, build_native, native_build_dir,
)
