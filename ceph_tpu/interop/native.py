"""ctypes bridge to the native EC runtime (native/).

ErasureCodeRef implements the Python ErasureCodeInterface on top of
libec_ref.so — the C++ RS backend whose matrix construction is
coefficient-exact with the JAX plugin. Registered as plugin ``ref``:

    factory("plugin=ref technique=reed_sol_van k=8 m=3")

The shared objects build on demand via ``make -C native`` (g++ is part of
the toolchain; see native/Makefile).
"""

from __future__ import annotations

import ctypes
import functools
import pathlib
import subprocess
from typing import Mapping, Sequence

import numpy as np

from ceph_tpu.ec.interface import ErasureCodeInterface, ErasureCodeProfile

_REPO = pathlib.Path(__file__).resolve().parent.parent.parent
_NATIVE = _REPO / "native"


def native_build_dir() -> pathlib.Path:
    return _NATIVE / "build"


def build_native() -> pathlib.Path:
    """Ensure the native libs are up to date; returns the build dir.

    Always invokes make (it is incremental) so the loaded .so tracks the
    C++ sources. Raises RuntimeError when the toolchain or build fails.
    """
    try:
        subprocess.run(["make", "-C", str(_NATIVE)], check=True,
                       capture_output=True, text=True, timeout=300)
    except FileNotFoundError as e:
        # No toolchain: fall back to a previously built lib if one exists.
        if not (native_build_dir() / "libec_ref.so").exists():
            raise RuntimeError(f"native build failed: {e}") from e
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired) as e:
        out = getattr(e, "stderr", "") or str(e)
        raise RuntimeError(f"native build failed: {out}") from e
    return native_build_dir()


@functools.lru_cache(maxsize=1)
def _lib() -> ctypes.CDLL:
    path = build_native() / "libec_ref.so"
    lib = ctypes.CDLL(str(path))
    lib.ec_ref_init.restype = ctypes.c_void_p
    lib.ec_ref_init.argtypes = [ctypes.c_int, ctypes.c_int,
                                ctypes.c_char_p]
    lib.ec_ref_free.argtypes = [ctypes.c_void_p]
    lib.ec_ref_encode.restype = ctypes.c_int
    lib.ec_ref_encode.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.c_char_p, ctypes.c_size_t]
    lib.ec_ref_decode.restype = ctypes.c_int
    lib.ec_ref_decode.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int), ctypes.c_int,
        ctypes.POINTER(ctypes.c_int), ctypes.c_int, ctypes.c_char_p,
        ctypes.c_char_p, ctypes.c_size_t]
    lib.ec_ref_coding_matrix.restype = ctypes.c_int
    lib.ec_ref_coding_matrix.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    return lib


class ErasureCodeRef(ErasureCodeInterface):
    """plugin=ref — native C++ RS backend (CPU baseline + oracle)."""

    def __init__(self, profile: ErasureCodeProfile | str | None = None):
        super().__init__()
        self.technique = "reed_sol_van"
        self._h = None
        if profile is not None:
            self.init(ErasureCodeProfile.parse(profile))

    def init(self, profile: ErasureCodeProfile) -> None:
        self.profile = profile
        self.k = profile.get_int("k", 2)
        self.m = profile.get_int("m", 2)
        self.technique = profile.get("technique", "reed_sol_van")
        lib = _lib()
        self._h = lib.ec_ref_init(self.k, self.m,
                                  self.technique.encode())
        if not self._h:
            raise ValueError(
                f"ec_ref_init failed: k={self.k} m={self.m} "
                f"technique={self.technique}")

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            try:
                _lib().ec_ref_free(h)
            except Exception:
                pass

    def is_mds(self) -> bool:
        return True

    def coding_matrix(self) -> np.ndarray:
        out = np.zeros((self.m, self.k), dtype=np.uint8)
        rc = _lib().ec_ref_coding_matrix(
            self._h, out.ctypes.data_as(ctypes.c_char_p))
        assert rc == 0
        return out

    def encode_chunks(self, data: np.ndarray) -> np.ndarray:
        data = np.ascontiguousarray(data, dtype=np.uint8)
        k, chunk = data.shape
        assert k == self.k
        parity = np.zeros((self.m, chunk), dtype=np.uint8)
        rc = _lib().ec_ref_encode(
            self._h, data.ctypes.data_as(ctypes.c_char_p),
            parity.ctypes.data_as(ctypes.c_char_p), chunk)
        if rc != 0:
            raise RuntimeError(f"ec_ref_encode rc={rc}")
        return parity

    def decode_chunks(self, want: Sequence[int],
                      chunks: Mapping[int, np.ndarray]) -> dict[int, np.ndarray]:
        avail = sorted(chunks)[:self.k]
        if len(avail) < self.k:
            raise ValueError(f"need {self.k} chunks, have {len(chunks)}")
        chunk = np.asarray(chunks[avail[0]]).shape[0]
        stacked = np.ascontiguousarray(
            np.stack([np.asarray(chunks[i], dtype=np.uint8)
                      for i in avail]))
        want_l = list(want)
        out = np.zeros((len(want_l), chunk), dtype=np.uint8)
        av = (ctypes.c_int * len(avail))(*avail)
        wa = (ctypes.c_int * len(want_l))(*want_l)
        rc = _lib().ec_ref_decode(
            self._h, av, len(avail), wa, len(want_l),
            stacked.ctypes.data_as(ctypes.c_char_p),
            out.ctypes.data_as(ctypes.c_char_p), chunk)
        if rc != 0:
            raise RuntimeError(f"ec_ref_decode rc={rc}")
        return {w: out[i] for i, w in enumerate(want_l)}
