"""Python half of the ``libec_jax.so`` reverse shim.

The forward bridge (``interop.native``) lets Python call the C++ EC
runtime; this module is the opposite direction — the native plugin
registry dlopens ``libec_jax.so`` (built from
``native/ec/plugin_jax_shim.cc``), which embeds a CPython interpreter
and calls these functions, so the native ``ec_bench`` harness can drive
the flagship TPU plugin through the exact ``__erasure_code_init``
contract every other plugin uses (ref: the role of
src/erasure-code/ErasureCodePlugin.cc __erasure_code_init; SURVEY.md §7
step 6).

Buffers cross the boundary as memoryviews over the caller's chunk
arrays — no copies on input; one ndarray assignment on output.

Platform: the embedded interpreter imports this module before touching
jax, and the first thing it does is pin ``jax_platforms`` (default
``cpu``; override with CEPH_TPU_SHIM_PLATFORM=tpu to let the native
harness drive the real chip). Without the pin this sandbox's
sitecustomize would dial the remote-TPU claim from inside ec_bench.
"""

from __future__ import annotations

import os


def _pin_platform() -> None:
    # Only pin when WE are the embedded interpreter (plugin_jax_shim.cc
    # sets the marker just before importing this module, and only when
    # it called Py_Initialize itself). A host Python process that loads
    # the shim in-process keeps its own platform choice.
    if os.environ.get("CEPH_TPU_EMBEDDED_SHIM") != "1":
        return
    import jax
    try:
        jax.config.update(
            "jax_platforms", os.environ.get("CEPH_TPU_SHIM_PLATFORM", "cpu"))
    except Exception:
        pass  # backends already initialized — keep whatever is live


_pin_platform()


def create(profile: str):
    """profile "k=8 m=3 technique=..." -> ErasureCodeInterface instance."""
    from ceph_tpu.ec.registry import factory
    prof = profile.strip() or "k=2 m=2"
    if "plugin=" not in prof:
        prof = "plugin=jax " + prof
    return factory(prof)


def encode(h, data_mv, parity_mv, chunk: int) -> int:
    import numpy as np
    data = np.frombuffer(data_mv, dtype=np.uint8).reshape(h.k, chunk)
    parity = h.encode_chunks(data)
    np.frombuffer(parity_mv, dtype=np.uint8).reshape(h.m, chunk)[:] = parity
    return 0


def decode(h, avail, want, chunks_mv, out_mv, chunk: int) -> int:
    import numpy as np
    chunks = np.frombuffer(chunks_mv, dtype=np.uint8).reshape(
        len(avail), chunk)
    got = h.decode_chunks(list(want),
                          {a: chunks[i] for i, a in enumerate(avail)})
    out = np.frombuffer(out_mv, dtype=np.uint8).reshape(len(want), chunk)
    for i, w in enumerate(want):
        out[i] = got[w]
    return 0
